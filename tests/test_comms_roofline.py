"""Communication & roofline observability (PR 8).

Covers the three tentpole pieces — the HLO collective scan
(monitor/comms.py + the lazy program analyzer), the roofline
classifier (monitor/roofline.py), and the sharding inspector
(distributed/introspect.py + the /roofline + /sharding routes — plus
the satellites: the eager/trace collective byte-count agreement (one
count per op, monitor-internal re-traces suppressed), the hardened
cost_analysis reads, and the fleet histogram-mean divergence wiring.
"""
import json
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import paddle_tpu as pt
from paddle_tpu import monitor
from paddle_tpu.monitor import comms, fleet, mfu as mfu_mod
from paddle_tpu.monitor import programs, roofline, server
from paddle_tpu.distributed import introspect


@pytest.fixture
def mon():
    monitor.reset()
    pt.set_flags({"FLAGS_enable_monitor": True})
    yield
    pt.set_flags({"FLAGS_enable_monitor": False})
    server.stop_server()
    monitor.reset()


def _mesh(shape=(4, 2), axes=("dp", "tp")):
    n = 1
    for d in shape:
        n *= d
    return Mesh(np.array(jax.devices()[:n]).reshape(shape), axes)


def _sharded_program(mesh=None):
    """A jitted program whose GSPMD partitioning inserts collectives,
    plus its sharded input."""
    mesh = mesh or _mesh()
    sh = NamedSharding(mesh, P("dp", "tp"))
    f = jax.jit(lambda x: (x @ x.T).sum(), in_shardings=(sh,))
    x = jax.device_put(jnp.ones((8, 8), jnp.float32), sh)
    return f, x


# ---------------------------------------------------------------------------
# HLO collective scan
# ---------------------------------------------------------------------------

_SYNTH_HLO = """
HloModule synth
ENTRY %main (p0: f32[4,8]) -> f32[4,8] {
  %p0 = f32[4,8]{1,0} parameter(0)
  %all-reduce = f32[4,8]{1,0} all-reduce(f32[4,8]{1,0} %p0), to_apply=%add
  %ag = f32[16,8]{1,0} all-gather(f32[4,8]{1,0} %all-reduce), dimensions={0}
  %rs = f32[2,8]{1,0} reduce-scatter(f32[4,8]{1,0} %all-reduce), to_apply=%add
  %cp = f32[4,8]{1,0} collective-permute(f32[4,8]{1,0} %p0), source_target_pairs={{0,1}}
  %a2a = f32[4,8]{1,0} all-to-all(f32[4,8]{1,0} %p0), dimensions={0}
  %ars = (f32[4,8]{1,0}, f32[4,8]{1,0}) all-reduce-start(f32[4,8]{1,0} %p0), to_apply=%add
  ROOT %ard = f32[4,8]{1,0} all-reduce-done((f32[4,8]{1,0}, f32[4,8]{1,0}) %ars)
}
"""


class TestHloScan:
    def test_counts_and_bytes_by_kind(self):
        got = comms.scan_hlo_collectives(_SYNTH_HLO)
        # sync all-reduce (128B) + async start (tuple halved -> 128B);
        # the -done op never double-counts
        assert got["all_reduce"] == {"count": 2, "bytes": 256}
        assert got["all_gather"] == {"count": 1, "bytes": 512}
        assert got["reduce_scatter"] == {"count": 1, "bytes": 64}
        assert got["collective_permute"] == {"count": 1, "bytes": 128}
        assert got["all_to_all"] == {"count": 1, "bytes": 128}

    def test_no_collectives_empty(self):
        assert comms.scan_hlo_collectives(
            "ENTRY %m { ROOT %d = f32[8,8]{1,0} dot(...) }") == {}

    def test_tpu_tiled_layout_shapes(self):
        # TPU post-optimization HLO carries tiled/memory-space layout
        # annotations with parens INSIDE the braces — the async -start
        # tuples the TPU backend emits by default must still count
        hlo = (
            "%ar-start = (bf16[1024]{0:T(1024)}, bf16[1024]{0:T(1024)})"
            " all-reduce-start(bf16[1024]{0:T(1024)} %p0), to_apply=%a\n"
            "%ar-done = bf16[1024]{0:T(1024)} all-reduce-done("
            "(bf16[1024]{0:T(1024)}, bf16[1024]{0:T(1024)}) %ar-start)\n"
            "%ag = f32[8,128]{1,0:T(8,128)} all-gather("
            "f32[1,128]{1,0:T(8,128)} %p1), dimensions={0}\n")
        got = comms.scan_hlo_collectives(hlo)
        assert got["all_reduce"] == {"count": 1, "bytes": 2048}
        assert got["all_gather"] == {"count": 1, "bytes": 4096}

    def test_shape_bytes(self):
        assert comms.shape_bytes("f32[4,8]{1,0}") == 128
        assert comms.shape_bytes("bf16[2,3]") == 12
        assert comms.shape_bytes("(f32[4], u32[2])") == 24
        assert comms.shape_bytes("f32[]") == 4
        assert comms.shape_bytes("pred[8]") == 8
        assert comms.shape_bytes("mystery[4]") == 0   # unknown dtype

    def test_total_counts(self):
        assert comms.total_counts(None) == (0, 0)
        assert comms.total_counts({}) == (0, 0)
        assert comms.total_counts(
            {"all_reduce": {"count": 2, "bytes": 10},
             "all_gather": {"count": 1, "bytes": 5}}) == (3, 15)

    def test_real_sharded_program_scans_collectives(self, mon):
        f, x = _sharded_program()
        f(x)
        programs.record_jit_call(("scan", 1), "sharded", f, (x,))
        programs.analyze_pending()
        rec = programs.programs_snapshot()[0]
        assert rec["collectives"], rec
        total_ops, total_bytes = comms.total_counts(rec["collectives"])
        assert total_ops > 0 and total_bytes > 0
        assert set(rec["collectives"]) <= set(comms.COLLECTIVE_KINDS)
        g = monitor.snapshot()["gauges"]
        assert g["comm.program.collectives.total"] == total_ops
        assert g["comm.program.bytes.total"] == total_bytes
        assert g["comm.program.last_collectives"] == total_ops

    def test_single_device_program_scans_empty(self, mon):
        f = jax.jit(lambda x: x @ x)
        x = jnp.ones((8, 8), jnp.float32)
        f(x)
        programs.record_jit_call(("scan", 2), "local", f, (x,))
        programs.analyze_pending()
        rec = programs.programs_snapshot()[0]
        # analyzed (not None) but no collectives on one device
        assert rec["collectives"] == {}


# ---------------------------------------------------------------------------
# satellite: eager/trace byte agreement + count-once discipline
# ---------------------------------------------------------------------------

class TestCollectiveByteAudit:
    def test_trace_and_eager_paths_agree_and_count_once(self, mon):
        from jax.experimental.shard_map import shard_map

        from paddle_tpu.distributed import collective as coll
        from paddle_tpu.distributed import comm_ops

        mesh = _mesh((8,), ("x",))
        # per-device block is [1, 4] f32 = 16 bytes
        f = jax.jit(shard_map(
            lambda x: comm_ops.all_reduce(x, "x"), mesh=mesh,
            in_specs=P("x", None), out_specs=P(None, None)))
        x = jnp.ones((8, 4), jnp.float32)

        def deltas():
            c = monitor.snapshot().get("counters", {})
            return (c.get("dist.all_reduce.calls", 0),
                    c.get("dist.all_reduce.bytes", 0),
                    c.get("dist.eager.all_reduce.calls", 0),
                    c.get("dist.eager.all_reduce.bytes", 0))

        assert deltas() == (0, 0, 0, 0)
        f(x)                                   # one trace+compile
        assert deltas() == (1, 16, 0, 0)
        f(x)                                   # cache hit: no retrace
        assert deltas() == (1, 16, 0, 0)

        # the SAME reduction (a 16-byte operand) through the eager
        # host path must count the same bytes, once per call
        t = pt.to_tensor(np.ones((1, 4), np.float32))
        coll.all_reduce(t)
        assert deltas() == (1, 16, 1, 16)

    def test_monitor_internal_retrace_is_suppressed(self, mon):
        from jax.experimental.shard_map import shard_map

        from paddle_tpu.distributed import comm_ops

        mesh = _mesh((8,), ("x",))
        f = jax.jit(shard_map(
            lambda x: comm_ops.all_reduce(x, "x"), mesh=mesh,
            in_specs=P("x", None), out_specs=P(None, None)))
        x = jnp.ones((8, 4), jnp.float32)
        f(x)
        before = monitor.snapshot()["counters"]["dist.all_reduce.calls"]
        # every monitor-internal lowering: the MFU/cost capture, the
        # registry's record-time capture, and the lazy analyzer's AOT
        # compile — none may re-fire the trace-time counters
        mfu_mod.lowered_cost(f, x)
        programs.record_jit_call(("sup", 1), "sup", f, (x,))
        programs.analyze_pending()
        after = monitor.snapshot()["counters"]["dist.all_reduce.calls"]
        assert after == before

    def test_eager_host_exchange_latency_observed(self, mon):
        from paddle_tpu.distributed import collective as coll
        objs = []
        coll.all_gather_object(objs, {"a": 1})
        assert objs == [{"a": 1}]
        coll.barrier()
        h = monitor.snapshot()["histograms"]
        assert h["comm.latency.all_gather_object_ms"]["count"] == 1
        assert h["comm.latency.barrier_ms"]["count"] == 1

    def test_off_path_registers_nothing(self):
        from jax.experimental.shard_map import shard_map

        from paddle_tpu.distributed import collective as coll
        from paddle_tpu.distributed import comm_ops

        monitor.reset()
        pt.set_flags({"FLAGS_enable_monitor": False})
        mesh = _mesh((8,), ("x",))
        f = jax.jit(shard_map(
            lambda x: comm_ops.all_reduce(x, "x"), mesh=mesh,
            in_specs=P("x", None), out_specs=P(None, None)))
        f(jnp.ones((8, 4), jnp.float32))
        objs = []
        coll.all_gather_object(objs, 3)
        coll.barrier()
        introspect.register_sharded_tree("off", {"w": jnp.ones(4)})
        assert monitor.snapshot() == {}
        assert introspect.sharding_snapshot()["trees"] == {}
        assert programs.programs_snapshot() == []


# ---------------------------------------------------------------------------
# satellite: hardened cost_analysis reads
# ---------------------------------------------------------------------------

class _BrokenLower:
    def lower(self, *a, **k):
        raise RuntimeError("backend says no")


class _KeylessCost:
    class _L:
        def cost_analysis(self):
            return {"utilization": 1.0}       # no flops, no bytes

    def lower(self, *a, **k):
        return self._L()


class TestCostAnalysisHardening:
    def test_raising_lower_returns_none_and_counts(self, mon):
        cost = mfu_mod.lowered_cost(_BrokenLower(), 1)
        assert cost == {"flops": None, "bytes_accessed": None}
        assert mfu_mod.lowered_flops(_BrokenLower(), 1) is None
        c = monitor.snapshot()["counters"]
        assert c["monitor.cost_analysis.unavailable"] == 2

    def test_missing_keys_return_none_and_count(self, mon):
        cost = mfu_mod.lowered_cost(_KeylessCost())
        assert cost == {"flops": None, "bytes_accessed": None}
        assert monitor.snapshot()["counters"][
            "monitor.cost_analysis.unavailable"] == 1

    def test_record_jit_call_survives_broken_backend(self, mon):
        rec = programs.record_jit_call(("broken", 1), "b",
                                       _BrokenLower(), (1,))
        # unavailable stays None on the record too — /programs and
        # /roofline never report a fabricated 0.0
        assert rec.flops is None
        assert rec.bytes_accessed is None
        assert programs.has_record(("broken", 1))

    def test_cost_analysis_value_shapes(self):
        assert mfu_mod.cost_analysis_value(None, "flops") is None
        assert mfu_mod.cost_analysis_value({"flops": 8.0}, "flops") == 8.0
        assert mfu_mod.cost_analysis_value({"flops": -1}, "flops") is None
        assert mfu_mod.cost_analysis_value(
            [{"flops": 8.0}, {"x": 1}], "flops") == 8.0
        assert mfu_mod.cost_analysis_value([{"x": 1}], "flops") is None
        # legacy 0.0-defaulting read keeps its shape
        assert mfu_mod.cost_analysis_flops({"bytes": 9}) == 0.0

    def test_answered_zero_is_not_unavailable(self, mon):
        # a pure data-movement program legitimately reports 0 flops:
        # that is an ANSWER, not an unavailable read
        class ZeroCost:
            class _L:
                def cost_analysis(self):
                    return {"flops": 0.0, "bytes accessed": 0.0}

            def lower(self, *a, **k):
                return self._L()

        cost = mfu_mod.lowered_cost(ZeroCost())
        assert cost == {"flops": 0.0, "bytes_accessed": 0.0}
        assert "monitor.cost_analysis.unavailable" not in \
            monitor.snapshot().get("counters", {})

    def test_record_program_flops_accepts_none(self, mon):
        mfu_mod.record_program_flops(None)
        assert "jit.program.flops" not in \
            monitor.snapshot().get("counters", {})

    def test_real_program_reports_bytes_accessed(self, mon):
        f = jax.jit(lambda x: x @ x)
        x = jnp.ones((16, 16), jnp.float32)
        cost = mfu_mod.lowered_cost(f, x)
        assert cost["flops"] and cost["flops"] >= 2 * 16 ** 3
        assert cost["bytes_accessed"] and cost["bytes_accessed"] > 0


# ---------------------------------------------------------------------------
# roofline classification
# ---------------------------------------------------------------------------

class TestRoofline:
    PEAKS = {"peak_flops_per_sec": 1e12,
             "peak_hbm_bytes_per_sec": 1e11,
             "peak_ici_bytes_per_sec": 1e10}

    def test_verdicts(self):
        # AI 100 >> ridge 10 -> compute-bound
        c = roofline.classify(1e9, 1e7, 0, self.PEAKS)
        assert c["verdict"] == "compute-bound"
        assert c["arithmetic_intensity"] == pytest.approx(100.0)
        # AI 1 << ridge 10 -> hbm-bound
        h = roofline.classify(1e7, 1e7, 0, self.PEAKS)
        assert h["verdict"] == "hbm-bound"
        # comm time dominates both
        m = roofline.classify(1e7, 1e7, 1e8, self.PEAKS)
        assert m["verdict"] == "comm-bound"
        assert m["t_comm_s"] == pytest.approx(1e-2)
        assert m["t_modeled_s"] == pytest.approx(1e-2)

    def test_unavailable_inputs_do_not_classify(self):
        assert roofline.classify(None, 1e7, 0, self.PEAKS)["verdict"] \
            is None
        assert roofline.classify(1e7, None, 0, self.PEAKS)["verdict"] \
            is None
        assert roofline.classify(0, 0, 0, self.PEAKS)["verdict"] is None

    def test_answered_zero_flops_classifies(self):
        # a genuine zero-FLOP data-movement program with real byte
        # traffic is trivially memory-bound — an ANSWER, not a gap
        c = roofline.classify(0.0, 1e7, 0, self.PEAKS)
        assert c["verdict"] == "hbm-bound"
        assert c["arithmetic_intensity"] == 0.0

    def test_ridge_point(self):
        assert roofline.ridge_point(1e12, 1e11) == pytest.approx(10.0)
        assert roofline.ridge_point(0, 1e11) is None

    def test_env_overrides(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_PEAK_HBM_GBS", "100")
        monkeypatch.setenv("PADDLE_TPU_PEAK_ICI_GBS", "10")
        assert roofline.peak_hbm_bytes_per_sec() == pytest.approx(1e11)
        assert roofline.peak_ici_bytes_per_sec() == pytest.approx(1e10)
        peaks = roofline.resolve_peaks()
        assert peaks["hbm_source"] == "env"
        assert peaks["ici_source"] == "env"

    def test_generation_table(self):
        class FakeDev:
            device_kind = "TPU v5p"
            platform = "tpu"

        hbm = roofline._resolve_bw("PADDLE_TPU_PEAK_HBM_GBS",
                                   roofline.PEAK_HBM_GBS_TABLE,
                                   1.0, FakeDev())
        assert hbm["source"] == "table"
        assert hbm["generation"] == "v5p"
        assert hbm["bytes_per_sec"] == pytest.approx(2765e9)
        # ONE shared resolver: the FLOPs denominator must match the
        # same generation for the same device
        fl = mfu_mod.resolve_peak("PADDLE_TPU_PEAK_FLOPS",
                                  mfu_mod.PEAK_FLOPS_TABLE, 1.0,
                                  FakeDev())
        assert fl["generation"] == hbm["generation"]
        assert fl["value"] == mfu_mod.PEAK_FLOPS_TABLE["v5p"]
        peaks = roofline.resolve_peaks(FakeDev())
        assert peaks["flops_source"] == "table"
        assert peaks["flops_generation"] == "v5p"

    def test_snapshot_attribution_and_gauges(self, mon):
        f, x = _sharded_program()
        f(x)
        programs.record_jit_call(("rf", 1), "sharded", f, (x,))
        programs.note_hit(("rf", 1))           # 2 invocations
        g = jax.jit(lambda y: y * 2.0)
        y = jnp.ones((4,), jnp.float32)
        g(y)
        programs.record_jit_call(("rf", 2), "tiny", g, (y,))
        rs = roofline.roofline_snapshot(analyze=True)
        by_name = {p["name"]: p for p in rs["programs"]}
        sharded = by_name["sharded"]
        assert sharded["verdict"] in ("compute-bound", "hbm-bound",
                                      "comm-bound")
        assert sharded["invocations"] == 2
        assert sharded["collective_ops"] > 0
        assert sharded["comms_analyzed"]
        shares = [p["share"] for p in rs["programs"] if p["share"]]
        assert sum(shares) == pytest.approx(1.0, abs=0.01)
        assert rs["attribution"]["comm_fraction"] is not None
        assert rs["comm"]["programs_analyzed"] == 2
        assert rs["comm"]["programs_with_collectives"] == 1
        gauges = monitor.snapshot()["gauges"]
        assert gauges["roofline.programs.classified"] == 2
        assert "roofline.comm.modeled_fraction" in gauges

    def test_empty_registry_snapshot(self, mon):
        rs = roofline.roofline_snapshot(analyze=False)
        assert rs["programs"] == []
        assert rs["attribution"]["total_modeled_s"] == 0.0
        assert rs["attribution"]["comm_fraction"] is None


# ---------------------------------------------------------------------------
# sharding inspector
# ---------------------------------------------------------------------------

class TestShardingInspector:
    def test_describe_sharded_and_replicated_leaves(self):
        mesh = _mesh()
        tree = {
            "w": jax.device_put(jnp.ones((8, 16), jnp.float32),
                                NamedSharding(mesh, P("dp", "tp"))),
            "b": jax.device_put(jnp.ones((16,), jnp.float32),
                                NamedSharding(mesh, P())),
        }
        d = introspect.describe_tree(tree)
        by_path = {leaf["path"]: leaf for leaf in d["leaves"]}
        w = by_path["['w']"]
        assert w["spec"] == "PartitionSpec('dp', 'tp')"
        assert w["mesh_axes"] == {"dp": 4, "tp": 2}
        assert w["shard_shape"] == [2, 8]
        assert w["shard_bytes"] == 2 * 8 * 4
        assert w["replication_factor"] == pytest.approx(1.0)
        assert not w["fully_replicated"]
        b = by_path["['b']"]
        assert b["replication_factor"] == pytest.approx(8.0)
        assert b["fully_replicated"]
        assert b["shard_bytes"] == 64
        assert d["num_arrays"] == 2
        assert d["replicated_bytes"] == 64
        # uniform layout: no cross-device imbalance
        assert d["imbalance"]["devices"] == 8
        assert d["imbalance"]["relative_imbalance"] == pytest.approx(
            0.0, abs=1e-6)

    def test_imbalance_detects_single_device_tree(self):
        # unsharded arrays all live on device 0 -> max imbalance
        mesh = _mesh()
        tree = {
            "sharded": jax.device_put(jnp.ones((8, 8), jnp.float32),
                                      NamedSharding(mesh, P("dp"))),
            "host_only": jnp.ones((64,), jnp.float32),
        }
        d = introspect.describe_tree(tree)
        assert d["imbalance"]["relative_imbalance"] > 0

    def test_unsharded_and_non_array_leaves(self):
        d = introspect.describe_tree({"a": np.ones((4,), np.float32),
                                      "s": "not-an-array", "n": 3})
        assert d["num_arrays"] == 1
        leaf = d["leaves"][0]
        assert leaf["num_devices"] == 1
        assert leaf["replication_factor"] == 1.0

    def test_tensor_facade_unwraps(self):
        t = pt.to_tensor(np.ones((2, 3), np.float32))
        d = introspect.describe_tree({"t": t})
        assert d["num_arrays"] == 1
        assert d["leaves"][0]["global_bytes"] == 24

    def test_leaf_bound_truncates(self):
        tree = {f"p{i}": jnp.ones((2,), jnp.float32) for i in range(20)}
        d = introspect.describe_tree(tree, max_leaves=5)
        assert len(d["leaves"]) == 5
        assert d["truncated"]
        assert d["num_arrays"] == 20
        assert d["total_global_bytes"] == 20 * 8

    def test_register_and_snapshot(self, mon):
        mesh = _mesh()
        tree = {"w": jax.device_put(jnp.ones((8, 8), jnp.float32),
                                    NamedSharding(mesh, P("dp", "tp")))}
        introspect.register_sharded_tree("train.params", tree)
        snap = introspect.sharding_snapshot()
        assert "train.params" in snap["trees"]
        assert snap["world"]["devices"] == 8
        # monitor.reset clears the registered trees
        monitor.reset()
        assert introspect.sharding_snapshot()["trees"] == {}

    def test_ensure_tree_only_materializes_when_absent(self, mon):
        calls = []

        def make():
            calls.append(1)
            return {"w": jnp.ones((2,), jnp.float32)}

        assert introspect.ensure_sharded_tree("e.params", make)
        assert not introspect.ensure_sharded_tree("e.params", make)
        assert calls == [1]          # steady state never re-computes

    def test_engine_params_tree_recovers_after_reset(self, mon):
        """monitor.reset() mid-run must not permanently empty the
        /sharding trees view: the next dispatch re-registers the live
        engine's params, like the program registry itself."""
        from paddle_tpu.inference import Request, ServingEngine
        from paddle_tpu.models import llama as L

        cfg = L.llama_tiny(num_hidden_layers=1, vocab_size=64,
                           hidden_size=32, intermediate_size=64,
                           num_attention_heads=2, num_key_value_heads=2,
                           max_position_embeddings=32)
        eng = ServingEngine(L, L.init_params(cfg, jax.random.PRNGKey(0)),
                            cfg, num_slots=1, max_len=16, page_size=8,
                            decode_chunk=2)
        assert any(k.endswith(".params")
                   for k in introspect.sharding_snapshot()["trees"])
        monitor.reset()
        assert introspect.sharding_snapshot()["trees"] == {}
        rng = np.random.default_rng(0)
        eng.run([Request(rid=0, prompt=rng.integers(
            0, cfg.vocab_size, (4,)).astype(np.int32),
            max_new_tokens=2)])
        assert any(k.endswith(".params")
                   for k in introspect.sharding_snapshot()["trees"])

    def test_program_records_carry_arg_sharding(self, mon):
        f, x = _sharded_program()
        f(x)
        programs.record_jit_call(("shard", 1), "sharded", f, (x,))
        snap = introspect.sharding_snapshot()
        assert len(snap["programs"]) == 1
        prog = snap["programs"][0]
        assert prog["name"] == "sharded"
        leaf = prog["sharding"]["leaves"][0]
        assert leaf["spec"] == "PartitionSpec('dp', 'tp')"
        assert leaf["shard_bytes"] == 32


# ---------------------------------------------------------------------------
# operator endpoints + end-to-end acceptance
# ---------------------------------------------------------------------------

def _get_json(url):
    with urllib.request.urlopen(url, timeout=30) as r:
        return r.status, json.load(r)


class TestEndpoints:
    def test_roofline_and_sharding_routes(self, mon):
        srv = server.start_server(port=0)
        f, x = _sharded_program()
        f(x)
        programs.record_jit_call(("ep", 1), "sharded", f, (x,))
        status, rl = _get_json(f"{srv.url}/roofline")
        assert status == 200
        assert rl["programs"][0]["name"] == "sharded"
        assert rl["programs"][0]["verdict"] is not None
        assert rl["programs"][0]["collective_ops"] > 0
        assert rl["peaks"]["ridge_point_flops_per_byte"] > 0
        status, sh = _get_json(f"{srv.url}/sharding")
        assert status == 200
        assert sh["programs"][0]["name"] == "sharded"
        status, root = _get_json(f"{srv.url}/")
        assert "/roofline" in root["routes"]
        assert "/sharding" in root["routes"]

    @pytest.mark.slow
    def test_acceptance_train_step_and_decode_in_roofline(self, mon):
        """A compiled llama train step and a ServingEngine decode
        program both appear in /roofline with nonzero FLOPs, nonzero
        bytes-accessed, a boundedness verdict, and (explicitly
        sharded) nonzero collective counts; /sharding reports per-leaf
        specs + shard bytes for the same programs. Slow lane per the
        tier-1 budget (ISSUE 8): the mesh train step compiles twice
        (once real, once for the lazy AOT analysis, ~15s);
        test_decode_program_classified + test_roofline_and_sharding_
        routes keep the decode-program and sharded-collective pins in
        the fast lane, and scripts/tpu_smoke.py roofline_scrape runs
        the full path end to end."""
        from paddle_tpu.inference import Request, ServingEngine
        from paddle_tpu.models import llama as L

        # smallest config that still exercises the mesh: the /roofline
        # scrape AOT-recompiles the train step for its lazy analysis,
        # so compile weight counts double here
        cfg = L.llama_tiny(num_hidden_layers=1, vocab_size=64,
                           hidden_size=32, intermediate_size=64,
                           num_attention_heads=2, num_key_value_heads=2,
                           max_position_embeddings=64)
        mesh = _mesh((4, 2, 1), ("dp", "fsdp", "tp"))
        with mesh:
            params = L.shard_params(
                L.init_params(cfg, jax.random.PRNGKey(0)), cfg, mesh)
            step = L.make_train_step(cfg, mesh, lr=1e-3, donate=False,
                                     guard=False)
            opt = L.adamw_init(params)
            opt = jax.device_put(
                opt, {"step": NamedSharding(mesh, P()),
                      "m": jax.tree.map(lambda a: a.sharding, params),
                      "v": jax.tree.map(lambda a: a.sharding, params)})
            ids = jax.device_put(
                jnp.zeros((8, 16), jnp.int32),
                NamedSharding(mesh, P(("dp", "fsdp"), None)))
            params, opt, _ = step(params, opt, ids)
            programs.record_jit_call(("acc", "train"),
                                     "llama.train_step", step,
                                     (params, opt, ids))

        eng = ServingEngine(L, L.init_params(cfg, jax.random.PRNGKey(1)),
                            cfg, num_slots=2, max_len=32, page_size=8,
                            decode_chunk=2)
        rng = np.random.default_rng(0)
        eng.run([Request(rid=0, prompt=rng.integers(
            0, cfg.vocab_size, (6,)).astype(np.int32),
            max_new_tokens=4)])

        srv = server.start_server(port=0)
        _, rl = _get_json(f"{srv.url}/roofline")
        by_name = {p["name"]: p for p in rl["programs"]}
        train = by_name["llama.train_step"]
        decode = next(p for n, p in by_name.items()
                      if n.startswith("serving.decode_chunk"))
        for p in (train, decode):
            assert p["flops"] > 0, p
            assert p["bytes_accessed"] > 0, p
            assert p["verdict"] in ("compute-bound", "hbm-bound",
                                    "comm-bound"), p
        # the explicitly-sharded train step crosses the mesh
        assert train["collective_ops"] > 0, train

        _, sh = _get_json(f"{srv.url}/sharding")
        names = [p["name"] for p in sh["programs"]]
        assert "llama.train_step" in names
        assert any(n.startswith("serving.") for n in names)
        train_sh = next(p for p in sh["programs"]
                        if p["name"] == "llama.train_step")
        specs = {leaf["spec"] for leaf in train_sh["sharding"]["leaves"]}
        assert any(s and "PartitionSpec" in s for s in specs)
        assert all(leaf["shard_bytes"] > 0
                   for leaf in train_sh["sharding"]["leaves"])
        # the engine registered its params tree
        assert any(k.endswith(".params") for k in sh["trees"])

    def test_decode_program_classified(self, mon):
        """Fast-lane half of the acceptance pin: a ServingEngine
        decode program lands in the roofline view with measured FLOPs,
        bytes-accessed and a verdict, and the engine's params tree is
        in the sharding view (the mesh-sharded train-step half lives
        in the slow-marked acceptance test + the smoke stage)."""
        from paddle_tpu.inference import Request, ServingEngine
        from paddle_tpu.models import llama as L

        cfg = L.llama_tiny(num_hidden_layers=1, vocab_size=64,
                           hidden_size=32, intermediate_size=64,
                           num_attention_heads=2, num_key_value_heads=2,
                           max_position_embeddings=32)
        eng = ServingEngine(L, L.init_params(cfg, jax.random.PRNGKey(0)),
                            cfg, num_slots=1, max_len=16, page_size=8,
                            decode_chunk=2)
        rng = np.random.default_rng(0)
        eng.run([Request(rid=0, prompt=rng.integers(
            0, cfg.vocab_size, (4,)).astype(np.int32),
            max_new_tokens=3)])
        rs = roofline.roofline_snapshot(analyze=True, max_analyze=8)
        decode = next(p for p in rs["programs"]
                      if p["name"].startswith("serving.decode_chunk"))
        assert decode["flops"] > 0
        assert decode["bytes_accessed"] > 0
        assert decode["verdict"] in ("compute-bound", "hbm-bound",
                                     "comm-bound")
        assert decode["comms_analyzed"]
        snap = introspect.sharding_snapshot()
        assert any(k.endswith(".params") for k in snap["trees"])
        assert any(p["name"].startswith("serving.")
                   for p in snap["programs"])

    def test_flag_off_nothing_served_or_registered(self):
        monitor.reset()
        pt.set_flags({"FLAGS_enable_monitor": False,
                      "FLAGS_enable_monitor_server": False})
        from paddle_tpu.inference import Request, ServingEngine
        from paddle_tpu.models import llama as L

        cfg = L.llama_tiny(num_hidden_layers=1)
        eng = ServingEngine(L, L.init_params(cfg, jax.random.PRNGKey(0)),
                            cfg, num_slots=1, max_len=16, page_size=8)
        rng = np.random.default_rng(0)
        eng.run([Request(rid=0, prompt=rng.integers(
            0, cfg.vocab_size, (4,)).astype(np.int32),
            max_new_tokens=2)])
        assert programs.programs_snapshot() == []
        assert roofline.roofline_snapshot(analyze=False)["programs"] \
            == []
        assert introspect.sharding_snapshot()["trees"] == {}
        assert monitor.snapshot() == {}


# ---------------------------------------------------------------------------
# satellite: fleet wiring
# ---------------------------------------------------------------------------

class TestFleetCommWiring:
    def test_absent_comm_gauges_stay_none(self):
        snaps = [
            {"gauges": {"comm.program.bytes.total": 100}},
            {"gauges": {}},                     # never analyzed
        ]
        agg = fleet.aggregate_hosts(snaps)
        s = agg["scalars"]["comm.program.bytes.total"]
        assert s["hosts"] == [100, None]
        assert s["sum"] == 100                  # not zero-filled

    def test_histogram_host_means_surface_latency_divergence(self):
        # same counts, one rank 10x slower: invisible to the merged
        # sum, line 1 of the divergence report via host means
        snaps = [
            {"histograms": {"comm.latency.all_reduce_ms":
                            {"count": 10, "sum": 10.0,
                             "min": 0.5, "max": 2.0}}},
            {"histograms": {"comm.latency.all_reduce_ms":
                            {"count": 10, "sum": 100.0,
                             "min": 5.0, "max": 20.0}}},
        ]
        agg = fleet.aggregate_hosts(snaps)
        h = agg["histograms"]["comm.latency.all_reduce_ms"]
        assert h["host_means"] == [1.0, 10.0]
        assert h["count"] == 20
        div = fleet.divergence(agg)
        assert div[0]["metric"] == "comm.latency.all_reduce_ms:mean"
        assert div[0]["relative_spread"] == pytest.approx(0.9)

    def test_histogram_absent_on_some_hosts_not_divergent(self):
        snaps = [
            {"histograms": {"h.x": {"count": 2, "sum": 4.0}}},
            {"histograms": {}},
        ]
        agg = fleet.aggregate_hosts(snaps)
        assert agg["histograms"]["h.x"]["host_means"] == [2.0, None]
        # a single present mean cannot diverge
        assert all(d["metric"] != "h.x:mean"
                   for d in fleet.divergence(agg))

    def test_fleet_text_renders_host_means(self):
        payload = {
            "world_size": 2,
            "aggregate": fleet.aggregate_hosts([
                {"histograms": {"h.y": {"count": 1, "sum": 3.0}}},
                {"histograms": {"h.y": {"count": 1, "sum": 5.0}}}]),
        }
        text = fleet.expose_fleet_text(payload)
        assert 'h_y{host="0",agg="mean"} 3' in text
        assert 'h_y{host="1",agg="mean"} 5' in text
