"""Launch CLI + multi-process bring-up tests.

Reference strategy: test/legacy_test/test_dist_base.py:952 — spin up a
local process cluster, run a worker script, assert on its output. Here the
cluster is 2 CPU processes rendezvousing through jax.distributed's
coordination service, driven by the real launch CLI.
"""
import os
import subprocess
import sys

import jax
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "_mp_worker.py")


def _read_worker_logs(log_dir, nprocs):
    """Full content of every workerlog (assert against ALL of it; callers
    truncate only when printing a failure)."""
    logs = ""
    for rank in range(nprocs):
        p = os.path.join(log_dir, f"workerlog.{rank}")
        if os.path.exists(p):
            logs += f"--- rank {rank} ---\n" + open(p).read()
    return logs


class TestLaunchCLI:
    def test_cli_help(self):
        r = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--help"],
            capture_output=True, text=True, timeout=120,
            env=dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO))
        assert r.returncode == 0
        assert "nproc_per_node" in r.stdout

    @pytest.mark.slow  # tier-1 budget (ISSUE 5): heavy 2-process spawn;
    # test_checkpoint_ft keeps a 2-process launch-CLI case in its lane
    def test_two_process_cluster(self, tmp_path):
        """launch CLI spawns 2 processes; they rendezvous, exchange
        objects, barrier, and round-trip a distributed checkpoint."""
        log_dir = str(tmp_path / "logs")
        r = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--nproc_per_node", "2", "--log_dir", log_dir,
             WORKER, str(tmp_path / "ckpt")],
            capture_output=True, text=True, timeout=420,
            env=dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO))
        logs = _read_worker_logs(log_dir, 2)
        assert r.returncode == 0, logs[-6000:]
        assert "MP_OK rank=0" in logs and "MP_OK rank=1" in logs, \
            logs[-6000:]

    @pytest.mark.skipif(
        jax.__version__.startswith("0.4."),
        reason="environment limit: jax 0.4.x CPU backend has no "
               "multi-process compiled collectives (broadcast_one_to_all "
               "in device_put raises 'Multiprocess computations aren't "
               "implemented on the CPU backend'); needs jax >= 0.5 or a "
               "real accelerator")
    @pytest.mark.parametrize("nprocs", [2, 4])
    def test_cross_process_compiled_collective_training(self, tmp_path,
                                                        nprocs):
        """A jitted DP train step whose gradient all-reduce crosses
        process boundaries (reference pattern:
        test_collective_api_base.py:113): N processes x 2 virtual CPU
        devices form one ("dp",) mesh; the worker asserts the compiled
        HLO contains a cross-replica reduction AND that the final
        weights match single-process training exactly."""
        worker = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "_dist_train_worker.py")
        log_dir = str(tmp_path / "logs")
        r = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--nproc_per_node", str(nprocs), "--log_dir", log_dir,
             worker],
            capture_output=True, text=True, timeout=420,
            env=dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO))
        logs = _read_worker_logs(log_dir, nprocs)
        assert r.returncode == 0, logs[-6000:]
        for rank in range(nprocs):
            assert f"DIST_TRAIN_OK rank={rank}" in logs, logs[-6000:]

    def test_failing_worker_fails_fast(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text(
            "import os, sys, time\n"
            "if os.environ['PADDLE_TRAINER_ID'] == '1':\n"
            "    sys.exit(3)\n"
            "time.sleep(60)\n")
        import time
        t0 = time.time()
        # short peer_grace: this worker never touches collectives, so
        # the survivors-abort-typed window is pure wait here (tier-1
        # wall-time budget; the full-grace path is exercised by the
        # slow-lane rank-loss chaos tests)
        r = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--nproc_per_node", "2", "--peer_grace", "0.3", str(bad)],
            capture_output=True, text=True, timeout=120,
            env=dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO))
        assert r.returncode != 0
        assert time.time() - t0 < 55, "watcher did not fail fast"


class TestSpawn:
    @pytest.mark.slow  # tier-1 budget (ISSUE 3): heavy; run in the slow lane
    def test_spawn_runs_workers(self, tmp_path):
        """paddle.distributed.spawn parity — 2 fresh processes, each
        writes a rank file."""
        script = tmp_path / "sp.py"
        script.write_text(f"""
import os
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import sys
sys.path.insert(0, {REPO!r})

def worker(out_dir):
    import jax
    jax.config.update("jax_platforms", "cpu")
    import paddle_tpu.distributed as dist
    dist.init_parallel_env()
    rank = dist.get_rank()
    open(os.path.join(out_dir, f"rank{{rank}}.txt"), "w").write(str(rank))

if __name__ == "__main__":
    import paddle_tpu.distributed as dist
    dist.spawn(worker, args=({str(tmp_path)!r},), nprocs=2)
""")
        r = subprocess.run([sys.executable, str(script)],
                           capture_output=True, text=True, timeout=300,
                           env=dict(os.environ, JAX_PLATFORMS="cpu",
                                    PYTHONPATH=REPO))
        assert r.returncode == 0, r.stderr[-2000:]
        assert (tmp_path / "rank0.txt").exists()
        assert (tmp_path / "rank1.txt").exists()


class TestAutoTunerTrials:
    @pytest.mark.slow  # tier-1 budget (ISSUE 3): heavy; run in the slow lane
    def test_end_to_end_real_trials(self, tmp_path):
        """VERDICT-r4 item 7: the tuner launches REAL trial subprocesses
        (sharded train steps on a virtual mesh), records CSV history,
        and reports a measured best config."""
        import csv
        import json

        out = tmp_path / "at"
        r = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.distributed.auto_tuner",
             "--max-trials", "2", "--devices", "4",
             "--out-dir", str(out)],
            capture_output=True, text=True, timeout=900,
            env=dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO,
                     TUNER_TRIAL_ITERS="1"))
        assert r.returncode == 0, r.stderr[-3000:]
        report = json.loads(r.stdout.strip().splitlines()[-1])
        assert report["trials"] == 2
        assert report["best"]["time"] is not None
        with open(out / "history.csv") as f:
            rows = list(csv.DictReader(f))
        assert len(rows) == 2
        assert all(float(row["time"]) > 0 for row in rows)
        assert (out / "best_cfg.json").exists()


class TestHeartbeatLiveness:
    """Elastic liveness (reference etcd-heartbeat membership,
    fleet/elastic/manager.py:124): a wedged-but-alive worker is detected
    and the job is killed for restart."""

    def _run(self, tmp_path, body, nprocs=2, **flags):
        script = tmp_path / "w.py"
        script.write_text(body)
        cmd = [sys.executable, "-m", "paddle_tpu.distributed.launch",
               "--nproc_per_node", str(nprocs),
               "--log_dir", str(tmp_path / "logs")]
        for k, v in flags.items():
            cmd += [f"--{k}", str(v)]
        cmd.append(str(script))
        import time
        t0 = time.time()
        r = subprocess.run(cmd, capture_output=True, text=True,
                           timeout=180,
                           env=dict(os.environ, JAX_PLATFORMS="cpu",
                                    PYTHONPATH=REPO))
        return r, time.time() - t0

    @pytest.mark.slow  # tier-1 budget (ISSUE 3): heavy; run in the slow lane
    def test_wedged_worker_detected_via_progress_beats(self, tmp_path):
        # rank 1 emits progress beats then wedges (sleeps forever while
        # its auto-beat thread keeps the process looking alive) — only
        # the progress timeout can catch this
        body = (
            "import os, sys, time\n"
            f"sys.path.insert(0, {REPO!r})\n"
            "from paddle_tpu.distributed import heartbeat\n"
            "heartbeat.start()\n"
            "for i in range(3):\n"
            "    heartbeat.beat(step=i)\n"
            "    time.sleep(0.1)\n"
            "if os.environ['PADDLE_TRAINER_ID'] == '1':\n"
            "    time.sleep(300)   # wedged: alive but no progress\n"
            "else:\n"
            "    for i in range(300):\n"
            "        heartbeat.beat(step=i)\n"
            "        time.sleep(0.1)\n")
        r, dt = self._run(tmp_path, body, progress_timeout=5)
        assert r.returncode == 124, (r.returncode, r.stderr[-1500:])
        assert "wedged" in r.stderr
        assert dt < 60, dt

    @pytest.mark.slow  # tier-1 budget (ISSUE 3): heavy; run in the slow lane
    def test_healthy_workers_unaffected(self, tmp_path):
        body = (
            "import os, sys, time\n"
            f"sys.path.insert(0, {REPO!r})\n"
            "from paddle_tpu.distributed import heartbeat\n"
            "heartbeat.start()\n"
            "for i in range(8):\n"
            "    heartbeat.beat(step=i)\n"
            "    time.sleep(0.1)\n")
        # generous grace: the worker pays a cold paddle_tpu import
        # (several seconds on a loaded box) before its first beat
        r, _ = self._run(tmp_path, body, heartbeat_timeout=45,
                         progress_timeout=45)
        assert r.returncode == 0, r.stderr[-1500:]
