"""Rank-loss chaos pins (ISSUE 14, slow lane — subprocess-heavy; the
fast-lane logic pins live in test_collective_faults.py and
test_data_resume.py):

1. kill -9 of one rank mid-``all_gather_object`` surfaces a typed
   ``PeerLostError`` on the survivor that NAMES the dead rank, in wall
   time far under ``PADDLE_TPU_COLL_TIMEOUT_S`` (tombstone fast path),
   and the survivor exits through the coordinated-abort protocol.
2. an elastic run over a crashing-then-clean worker restarts and
   resumes the DataLoader from its committed state with every sample
   index consumed exactly once (no replay, no skip).
"""
import os
import re
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
HERE = os.path.dirname(os.path.abspath(__file__))


def _read_worker_logs(log_dir, nprocs):
    logs = ""
    for rank in range(nprocs):
        p = os.path.join(log_dir, f"workerlog.{rank}")
        if os.path.exists(p):
            logs += f"--- rank {rank} ---\n" + open(p).read()
    return logs


@pytest.mark.slow  # tier-1 budget (ISSUE 14): 2-process launch + jax
# imports; the attribution/tombstone LOGIC pins run fast-lane against a
# FakeKV in test_collective_faults.py
class TestKillMidGather:
    def test_kill9_surfaces_typed_peer_lost_fast(self, tmp_path):
        worker = os.path.join(HERE, "_gather_kill_worker.py")
        log_dir = str(tmp_path / "logs")
        deadline = "45"
        t0 = time.time()
        r = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--nproc_per_node", "2", "--log_dir", log_dir,
             worker, deadline],
            capture_output=True, text=True, timeout=420,
            env=dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO,
                     PADDLE_TPU_COLL_TIMEOUT_S=deadline))
        wall = time.time() - t0
        logs = _read_worker_logs(log_dir, 2)
        assert r.returncode != 0, logs[-6000:]
        assert "WARM_OK rank=0" in logs and "WARM_OK rank=1" in logs, \
            logs[-6000:]
        # the survivor's typed error names the dead rank...
        assert "PEER_LOST rank=0 lost=[1]" in logs, logs[-6000:]
        # ...in wall time far under the deadline (the worker asserts
        # dt < deadline/2 itself; parse and pin harder here)
        m = re.search(r"PEER_LOST rank=0 .* dt=([0-9.]+)s", logs)
        assert m and float(m.group(1)) < 20.0, logs[-6000:]
        # coordinated abort: marker announced + typed abort line
        assert "aborting: PeerLostError" in logs, logs[-6000:]
        assert "UNEXPECTED_SURVIVAL" not in logs, logs[-6000:]
        # nothing waited out the 45s budget end to end
        assert wall < 300, wall


@pytest.mark.slow  # tier-1 budget (ISSUE 14): elastic relaunch = 2 jax
# interpreter spins; the loader-state resume LOGIC pins run fast-lane
# in test_data_resume.py
class TestElasticExactlyOnceResume:
    def test_kill9_mid_epoch_resumes_exactly_once(self, tmp_path):
        from paddle_tpu.distributed.fleet.elastic import \
            AdaptiveElasticManager

        worker = os.path.join(HERE, "_data_resume_worker.py")
        log = str(tmp_path / "samples.log")
        mgr = AdaptiveElasticManager(max_restarts=2, restart_delay=0.1)
        rc = mgr.run_adaptive(
            worker, (log,), nproc_per_node=1,
            ckpt_dir=str(tmp_path / "ckpt"),
            log_dir=str(tmp_path / "logs"),
            extra_env={"JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO,
                       "KILL_AT_BATCH": "7"})
        assert rc == 0, open(log).read() if os.path.exists(log) else rc
        # one restart, attributed as a worker failure (rc=137 crash)
        restarts = [d for _, s, d in mgr.events if s == "restart"]
        assert len(restarts) == 1 and restarts[0]["rc"] == 137

        lines = [ln for ln in open(log).read().splitlines() if ln]
        steps = [int(re.search(r"step=(\d+)", ln).group(1))
                 for ln in lines]
        # every batch step logged exactly once across both runs —
        # no replay (save committed BEFORE the kill), no skip
        assert steps == sorted(steps) == list(range(20)), steps
        per_step = {}
        for ln in lines:
            s = int(re.search(r"step=(\d+)", ln).group(1))
            ids = [int(x) for x in
                   re.search(r"ids=(.*)$", ln).group(1).split()]
            per_step[s] = ids
        epoch0 = [i for s in range(10) for i in per_step[s]]
        epoch1 = [i for s in range(10, 20) for i in per_step[s]]
        assert sorted(epoch0) == list(range(20))
        assert sorted(epoch1) == list(range(20))
        assert epoch0 != epoch1          # epochs reshuffle
        # the kill landed mid-epoch-0: both runs contributed to it
        runs = {ln.split()[0] for ln in lines}
        assert runs == {"run=0", "run=1"}, runs
