"""Tests for the second namespace-completion batch: device, callbacks,
hub, regularizer, tensor/reader aliases, amp.debugging, utils
(unique_name/dlpack/deprecated), incubate fused layers + autograd."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


def t(x, **kw):
    return paddle.to_tensor(x, **kw)


class TestDeviceNamespace:
    def test_queries(self):
        assert "cpu" in paddle.device.get_all_device_type()
        assert paddle.device.cuda.device_count() >= 1
        assert isinstance(paddle.device.get_device(), str)
        assert paddle.device.is_compiled_with_cuda() is False
        assert paddle.device.is_compiled_with_distribute() is True
        assert paddle.device.get_cudnn_version() is None

    def test_streams_events(self):
        s = paddle.device.Stream()
        ev = s.record_event()
        assert ev.query() is True
        with paddle.device.stream_guard(s):
            assert paddle.device.current_stream() is s
        paddle.device.synchronize()
        ev.synchronize()

    def test_cuda_memory_queries(self):
        assert paddle.device.cuda.memory_allocated() >= 0
        props = paddle.device.cuda.get_device_properties()
        assert hasattr(props, "total_memory")

    def test_set_device(self):
        assert paddle.device.set_device("cpu") == "cpu"


class TestCallbacksNamespace:
    def test_reduce_lr_on_plateau(self):
        from paddle_tpu.optimizer import SGD

        lin = nn.Linear(2, 1)
        opt = SGD(learning_rate=1.0, parameters=lin.parameters())
        cb = paddle.callbacks.ReduceLROnPlateau(
            monitor="loss", factor=0.5, patience=1, verbose=0)

        class _M:
            _optimizer = opt
        cb.model = _M()
        cb.on_eval_end({"loss": 1.0})
        cb.on_eval_end({"loss": 1.0})   # no improvement -> wait=1 >= patience
        assert abs(opt.get_lr() - 0.5) < 1e-9

    def test_tracker_callbacks_gated(self):
        v = paddle.callbacks.VisualDL("/tmp/vdl")
        with pytest.raises(RuntimeError):
            v.on_train_batch_end(0, {"loss": 1.0})


class TestHubAndUtils:
    def test_hub_local(self, tmp_path):
        (tmp_path / "hubconf.py").write_text(
            "def tiny_model(scale=1):\n"
            "    'a tiny hub model'\n"
            "    return {'scale': scale}\n")
        assert "tiny_model" in paddle.hub.list(str(tmp_path))
        assert "tiny" in paddle.hub.help(str(tmp_path), "tiny_model")
        assert paddle.hub.load(str(tmp_path), "tiny_model",
                               scale=3) == {"scale": 3}
        with pytest.raises(NotImplementedError):
            paddle.hub.list("owner/repo", source="github")

    def test_unique_name(self):
        from paddle_tpu.utils import unique_name

        with unique_name.guard():
            a = unique_name.generate("fc")
            b = unique_name.generate("fc")
        assert a != b and a.startswith("fc_")

    def test_dlpack_roundtrip(self):
        from paddle_tpu.utils.dlpack import from_dlpack, to_dlpack

        x = t(np.arange(6, dtype="float32").reshape(2, 3))
        cap = to_dlpack(x)
        y = from_dlpack(cap)
        np.testing.assert_allclose(np.asarray(y.numpy()), x.numpy())

    def test_deprecated_and_versions(self):
        from paddle_tpu.utils import deprecated, require_version, try_import

        @deprecated(update_to="new_api", since="0.1")
        def old():
            return 7

        with pytest.warns(DeprecationWarning):
            assert old() == 7
        require_version("0.0.1")
        with pytest.raises(Exception):
            require_version("999.0.0")
        assert try_import("math") is not None
        with pytest.raises(ImportError):
            try_import("definitely_not_a_module_xyz")

    def test_cuda_extension_gated(self):
        from paddle_tpu.utils.cpp_extension import CUDAExtension

        with pytest.raises(NotImplementedError):
            CUDAExtension(sources=["x.cu"])

    def test_onnx_export_dynamic_batch_inputspec(self, tmp_path):
        # None dims used to gate to the StableHLO fallback; they now
        # export as symbolic onnx dims (converter dynamic_axes support)
        import paddle_tpu.jit as jit

        lin = nn.Linear(3, 2)
        lin.eval()
        p = paddle.onnx.export(lin, str(tmp_path / "m.onnx"),
                               input_spec=[jit.InputSpec([None, 3],
                                                         "float32")])
        from paddle_tpu.onnx import onnx_pb2 as P

        with open(p, "rb") as f:
            m = P.ModelProto.FromString(f.read())
        d0 = m.graph.input[0].type.tensor_type.shape.dim[0]
        assert d0.dim_param

    def test_reader_composition(self):
        r = paddle.reader.firstn(
            paddle.reader.shuffle(lambda: iter(range(10)), 5), 4)
        assert len(list(r())) == 4
        m = paddle.reader.map_readers(lambda a, b: a + b,
                                      lambda: iter([1, 2]),
                                      lambda: iter([10, 20]))
        assert list(m()) == [11, 22]


class TestAmpDebugging:
    def test_operator_stats(self, capsys):
        from paddle_tpu.amp import debugging as dbg

        with dbg.collect_operator_stats():
            _ = t([1.0]) + t([2.0])
            _ = t([[1.0, 2.0]]) @ t([[1.0], [2.0]])
        out = capsys.readouterr().out
        assert "op list" in out and "calls:" in out

    def test_check_numerics(self):
        from paddle_tpu.amp import debugging as dbg

        dbg.check_numerics(t([1.0, 2.0]))    # clean passes
        with pytest.raises(RuntimeError, match="nan"):
            dbg.check_numerics(t([float("nan")]))

    def test_tensor_checker_toggle(self):
        from paddle_tpu.amp import debugging as dbg
        from paddle_tpu.core.flags import get_flags

        dbg.enable_tensor_checker(dbg.TensorCheckerConfig(enable=True))
        assert get_flags("FLAGS_check_nan_inf")["FLAGS_check_nan_inf"]
        dbg.disable_tensor_checker()
        assert not get_flags("FLAGS_check_nan_inf")["FLAGS_check_nan_inf"]


class TestIncubateFused:
    def test_fused_linear_matmul_bias(self):
        from paddle_tpu.incubate.nn import FusedLinear
        from paddle_tpu.incubate.nn.functional import fused_matmul_bias

        lin = FusedLinear(4, 3)
        x = t(np.random.default_rng(0).normal(size=(2, 4)).astype("float32"))
        out = lin(x)
        want = x.numpy() @ lin.weight.numpy() + lin.bias.numpy()
        np.testing.assert_allclose(np.asarray(out.numpy()), want, rtol=1e-5)
        out2 = fused_matmul_bias(x, t(lin.weight.numpy()),
                                 t(lin.bias.numpy()))
        np.testing.assert_allclose(np.asarray(out2.numpy()), want,
                                   rtol=1e-5)

    def test_fused_feedforward_and_mha(self):
        from paddle_tpu.incubate.nn import (FusedFeedForward,
                                            FusedMultiHeadAttention,
                                            FusedTransformerEncoderLayer)

        x = t(np.random.default_rng(1).normal(size=(2, 5, 8))
              .astype("float32"))
        ffn = FusedFeedForward(8, 16, dropout_rate=0.0)
        ffn.eval()
        assert ffn(x).shape == [2, 5, 8]
        mha = FusedMultiHeadAttention(8, 2, dropout_rate=0.0,
                                      attn_dropout_rate=0.0)
        mha.eval()
        assert mha(x).shape == [2, 5, 8]
        enc = FusedTransformerEncoderLayer(8, 2, 16, dropout_rate=0.0)
        enc.eval()
        assert enc(x).shape == [2, 5, 8]

    def test_fused_ec_moe(self):
        from paddle_tpu.incubate.nn import FusedEcMoe

        moe = FusedEcMoe(8, 16, num_experts=4)
        x = t(np.random.default_rng(2).normal(size=(2, 3, 8))
              .astype("float32"))
        assert moe(x).shape == [2, 3, 8]

    def test_masked_mha_decode(self):
        from paddle_tpu.incubate.nn.functional import \
            masked_multihead_attention

        b, nh, hd, t_max = 2, 2, 4, 6
        rng = np.random.default_rng(3)
        x = t(rng.normal(size=(b, 3 * nh * hd)).astype("float32"))
        cache = t(np.zeros((2, b, nh, t_max, hd), "float32"))
        out, new_cache = masked_multihead_attention(
            x, cache_kv=cache,
            sequence_lengths=t(np.zeros((b,), "int32")))
        assert out.shape == [b, nh * hd]
        assert new_cache.shape == [2, b, nh, t_max, hd]
        # at step 0 attention sees only the just-written kv -> out == v
        qkv = x.numpy().reshape(b, 3, nh, hd)
        np.testing.assert_allclose(np.asarray(out.numpy()),
                                   qkv[:, 2].reshape(b, -1), rtol=1e-5)

    def test_varlen_memory_efficient(self):
        from paddle_tpu.incubate.nn.functional import \
            variable_length_memory_efficient_attention

        rng = np.random.default_rng(4)
        q = t(rng.normal(size=(1, 2, 4, 8)).astype("float32"))
        k = t(rng.normal(size=(1, 2, 4, 8)).astype("float32"))
        v = t(rng.normal(size=(1, 2, 4, 8)).astype("float32"))
        out = variable_length_memory_efficient_attention(
            q, k, v, t(np.array([4], "int32")), t(np.array([4], "int32")))
        assert out.shape == [1, 2, 4, 8]

    def test_fused_dropout_add_and_bias_ln(self):
        from paddle_tpu.incubate.nn import (
            FusedBiasDropoutResidualLayerNorm, FusedDropoutAdd)

        x = t(np.ones((2, 4), "float32"))
        y = t(np.full((2, 4), 2.0, "float32"))
        fda = FusedDropoutAdd(p=0.0)
        np.testing.assert_allclose(np.asarray(fda(x, y).numpy()),
                                   np.full((2, 4), 3.0))
        ln = FusedBiasDropoutResidualLayerNorm(4, dropout_rate=0.0)
        ln.eval()
        assert ln(x, y).shape == [2, 4]


class TestIncubateAutograd:
    def test_vjp_jvp(self):
        from paddle_tpu.incubate.autograd import jvp, vjp

        def f(x):
            return x * x

        x = t(np.array([2.0, 3.0], "float32"))
        out, grads = vjp(f, x)
        np.testing.assert_allclose(np.asarray(out.numpy()), [4.0, 9.0])
        np.testing.assert_allclose(np.asarray(grads[0].numpy()),
                                   [4.0, 6.0])
        out, tangent = jvp(f, x, t(np.array([1.0, 0.0], "float32")))
        np.testing.assert_allclose(np.asarray(tangent.numpy()), [4.0, 0.0])

    def test_jacobian_hessian_objects(self):
        from paddle_tpu.incubate.autograd import Hessian, Jacobian

        def f(x):
            return (x * x).sum()

        x = t(np.array([1.0, 2.0], "float32"))
        h = Hessian(f, x)
        np.testing.assert_allclose(np.asarray(h[:].numpy()),
                                   2.0 * np.eye(2), rtol=1e-5)

        def g(x):
            return x * 3.0

        j = Jacobian(g, x)
        np.testing.assert_allclose(np.asarray(j[:].numpy()),
                                   3.0 * np.eye(2), rtol=1e-5)

    def test_prim_toggles(self):
        from paddle_tpu.incubate import autograd as ia

        ia.enable_prim()
        ia.disable_prim()


class TestFleetRoleMakers:
    def test_collective_role_maker(self):
        import paddle_tpu.distributed.fleet as fleet

        rm = fleet.PaddleCloudRoleMaker(is_collective=True)
        assert rm._worker_num() >= 1
        assert rm._is_worker()
        util = fleet.UtilBase()
        assert util.get_file_shard(["a", "b", "c"]) == ["a", "b", "c"]
        with pytest.raises(NotImplementedError):
            fleet.MultiSlotDataGenerator()
        with pytest.raises(NotImplementedError):
            fleet.PaddleCloudRoleMaker(is_collective=False)


class TestFusedGradFlow:
    def test_fused_mha_trains_qkv(self):
        """Review regression: the fused MHA block must deliver gradients
        to the qkv projection (it previously severed the tape)."""
        from paddle_tpu.incubate.nn import FusedMultiHeadAttention

        mha = FusedMultiHeadAttention(8, 2, dropout_rate=0.0,
                                      attn_dropout_rate=0.0)
        x = t(np.random.default_rng(5).normal(size=(2, 4, 8))
              .astype("float32"))
        loss = mha(x).sum()
        loss.backward()
        g = np.asarray(mha.qkv_weight.grad.numpy())
        assert np.isfinite(g).all() and np.abs(g).sum() > 0
        assert np.abs(np.asarray(mha.linear_weight.grad.numpy())).sum() > 0

    def test_varlen_padded_rows_zero(self):
        from paddle_tpu.incubate.nn.functional import \
            variable_length_memory_efficient_attention

        rng = np.random.default_rng(6)
        q = t(rng.normal(size=(1, 2, 4, 8)).astype("float32"))
        k = t(rng.normal(size=(1, 2, 4, 8)).astype("float32"))
        v = t(rng.normal(size=(1, 2, 4, 8)).astype("float32"))
        out = variable_length_memory_efficient_attention(
            q, k, v, t(np.array([2], "int32")), t(np.array([2], "int32")))
        arr = np.asarray(out.numpy())
        assert np.isfinite(arr).all()
        np.testing.assert_allclose(arr[:, :, 2:], 0.0)
        # additive mask is honored
        bias = np.zeros((1, 2, 4, 4), "float32")
        bias[..., 0] = -1e9        # forbid key 0
        out_m = variable_length_memory_efficient_attention(
            q, k, v, t(np.array([2], "int32")), t(np.array([2], "int32")),
            mask=t(bias))
        assert not np.allclose(np.asarray(out_m.numpy())[:, :, :2],
                               arr[:, :, :2])

    def test_multi_transformer_decode_with_cache(self):
        from paddle_tpu.incubate.nn.functional import \
            fused_multi_transformer

        rng = np.random.default_rng(7)
        d, nh, hd, t_max = 8, 2, 4, 6

        def mk(*shape):
            return t(rng.normal(size=shape).astype("float32") * 0.1)

        ws = dict(
            ln_scales=[t(np.ones(d, "float32"))],
            ln_biases=[t(np.zeros(d, "float32"))],
            qkv_weights=[mk(3, nh, hd, d)],
            qkv_biases=[t(np.zeros(3 * d, "float32"))],
            linear_weights=[mk(d, d)],
            linear_biases=[t(np.zeros(d, "float32"))],
            ffn_ln_scales=[t(np.ones(d, "float32"))],
            ffn_ln_biases=[t(np.zeros(d, "float32"))],
            ffn1_weights=[mk(d, 16)],
            ffn1_biases=[t(np.zeros(16, "float32"))],
            ffn2_weights=[mk(16, d)],
            ffn2_biases=[t(np.zeros(d, "float32"))],
        )
        x = mk(2, 1, d)
        caches = [t(np.zeros((2, 2, nh, t_max, hd), "float32"))]
        out, new_caches = fused_multi_transformer(
            x, cache_kvs=caches, time_step=t(np.array([0], "int32")),
            **ws)
        assert out.shape == [2, 1, d]
        assert new_caches[0].shape == [2, 2, nh, t_max, hd]
        # the cache now holds this step's k/v at position 0
        assert np.abs(np.asarray(new_caches[0].numpy())[:, :, :, 0]).sum() > 0
        assert np.abs(np.asarray(new_caches[0].numpy())[:, :, :, 1:]).sum() == 0

    def test_masked_mha_rotary(self):
        from paddle_tpu.incubate.nn.functional import \
            masked_multihead_attention

        b, nh, hd, t_max = 1, 1, 4, 4
        rng = np.random.default_rng(8)
        x = t(rng.normal(size=(b, 3 * nh * hd)).astype("float32"))
        cache = t(np.zeros((2, b, nh, t_max, hd), "float32"))
        rot = np.zeros((b, 1, 1, t_max, hd), "float32")
        rot[..., 0::2] = 1.0          # cos=1, sin=0 -> identity rotation
        out_id, _ = masked_multihead_attention(
            x, cache_kv=cache, rotary_tensor=t(rot),
            sequence_lengths=t(np.zeros((b,), "int32")))
        out_none, _ = masked_multihead_attention(
            x, cache_kv=cache,
            sequence_lengths=t(np.zeros((b,), "int32")))
        np.testing.assert_allclose(np.asarray(out_id.numpy()),
                                   np.asarray(out_none.numpy()), rtol=1e-5)
        rot2 = np.zeros_like(rot)
        rot2[..., 1::2] = 1.0         # cos=0, sin=1 -> real rotation
        _, cache_rot = masked_multihead_attention(
            x, cache_kv=cache, rotary_tensor=t(rot2),
            sequence_lengths=t(np.zeros((b,), "int32")))
        _, cache_none = masked_multihead_attention(
            x, cache_kv=cache,
            sequence_lengths=t(np.zeros((b,), "int32")))
        # k is written to the cache rotated: (t1,t2) -> (-t2, t1)
        k_rot = np.asarray(cache_rot.numpy())[0, 0, 0, 0]
        k_raw = np.asarray(cache_none.numpy())[0, 0, 0, 0]
        np.testing.assert_allclose(k_rot[0::2], -k_raw[1::2], rtol=1e-5)
        np.testing.assert_allclose(k_rot[1::2], k_raw[0::2], rtol=1e-5)


class TestReviewRegressions2:
    def test_jacobian_multi_input_block(self):
        from paddle_tpu.incubate.autograd import Hessian, Jacobian

        def f(x, y):
            return (x * x).sum() + 3.0 * (y * y).sum()

        x = t(np.array([1.0, 2.0], "float32"))
        y = t(np.array([3.0], "float32"))
        j = Jacobian(f, [x, y])
        np.testing.assert_allclose(np.asarray(j[:].numpy()),
                                   [[2.0, 4.0, 18.0]], rtol=1e-5)
        h = Hessian(f, [x, y])
        want = np.diag([2.0, 2.0, 6.0])
        np.testing.assert_allclose(np.asarray(h[:].numpy()), want,
                                   rtol=1e-5)

    def test_reduce_lr_cooldown(self):
        from paddle_tpu.optimizer import SGD

        lin = nn.Linear(2, 1)
        opt = SGD(learning_rate=1.0, parameters=lin.parameters())
        cb = paddle.callbacks.ReduceLROnPlateau(
            monitor="loss", factor=0.5, patience=1, cooldown=3, verbose=0)

        class _M:
            _optimizer = opt
        cb.model = _M()
        for _ in range(5):       # plateau through the cooldown window
            cb.on_eval_end({"loss": 1.0})
        # one reduction at step 2, then 3 cooldown evals absorb the rest
        assert abs(opt.get_lr() - 0.5) < 1e-9

    def test_varlen_decode_causal_alignment(self):
        from paddle_tpu.incubate.nn.functional import \
            variable_length_memory_efficient_attention as vl

        rng = np.random.default_rng(9)
        # decode shape: one query over 4 cached keys -> all attendable
        q = t(rng.normal(size=(1, 1, 1, 8)).astype("float32"))
        k = t(rng.normal(size=(1, 1, 4, 8)).astype("float32"))
        v = t(rng.normal(size=(1, 1, 4, 8)).astype("float32"))
        out = vl(q, k, v, t(np.array([1], "int32")),
                 t(np.array([4], "int32")), causal=True)
        # equals full (non-causal) attention for the single last-row query
        want = vl(q, k, v, t(np.array([1], "int32")),
                  t(np.array([4], "int32")), causal=False)
        np.testing.assert_allclose(np.asarray(out.numpy()),
                                   np.asarray(want.numpy()), rtol=1e-5)

    def test_fused_mha_cache_gate(self):
        from paddle_tpu.incubate.nn.functional import \
            fused_multi_head_attention

        with pytest.raises(NotImplementedError, match="cached decode"):
            fused_multi_head_attention(
                t(np.zeros((1, 2, 8), "float32")),
                t(np.zeros((3, 2, 4, 8), "float32")),
                t(np.zeros((8, 8), "float32")),
                cache_kv=t(np.zeros((2, 1, 2, 4, 4), "float32")))

    def test_async_result_timeout_raises(self, tmp_path):
        import threading
        import time as _time

        from paddle_tpu.distributed.checkpoint import AsyncSaveHandle

        box = []
        th = threading.Thread(target=lambda: _time.sleep(1.5))
        th.start()
        h = AsyncSaveHandle(th, box)
        with pytest.raises(TimeoutError):
            h.result(timeout=0.05)
        h.result(timeout=10)     # completes cleanly afterwards
