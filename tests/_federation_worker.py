"""Worker for the 2-process fleet-federation test (run via the launch
CLI, not collected by pytest — the PR 7/8 ``_fleet_agg_worker``
template).

Each rank runs a tiny serving engine as one fleet replica and
publishes telemetry frames over the coordination-service KV transport
ONLY (``dir_path=None`` — no shared filesystem assumed). Rank 1
injects a synthetic fast-burn into its frames; rank 0 builds a
``FleetSLOView`` over the same KV store, federates both replicas, and
serves ``/fleet/serving``. The parent test asserts:

- both ranks published frames (seq advancing);
- rank 0's federated report lists BOTH replicas;
- attribution line 1 is the injected burner (replica1);
- the rank-0 operator-plane scrape of ``/fleet/serving`` carries the
  same verdict.
"""
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax

jax.config.update("jax_platforms", "cpu")

import urllib.request  # noqa: E402

import numpy as np  # noqa: E402

import paddle_tpu as paddle  # noqa: E402
import paddle_tpu.distributed as dist  # noqa: E402
from paddle_tpu.distributed import heartbeat as hb  # noqa: E402
from paddle_tpu.monitor import federation as fed  # noqa: E402
from paddle_tpu.monitor import server  # noqa: E402


def _burning_report():
    """A synthetic fast-burn compliance report (the slo plane's shape)
    rank 1 injects into its frames."""
    return {
        "objectives": {
            "ttft_p99_ms": {"compliance": 0.5, "burn_fast": 40.0,
                            "burn_slow": 30.0, "samples_slow": 64,
                            "samples_fast": 32, "target_ratio": 0.99},
        },
        "alerting": ["ttft_p99_ms"],
    }


def _healthy_report():
    return {
        "objectives": {
            "ttft_p99_ms": {"compliance": 1.0, "burn_fast": 0.0,
                            "burn_slow": 0.0, "samples_slow": 64,
                            "samples_fast": 32, "target_ratio": 0.99},
        },
        "alerting": [],
    }


def main():
    dist.init_parallel_env()
    rank = dist.get_rank()
    paddle.set_flags({"FLAGS_enable_monitor": True})

    from paddle_tpu.inference import Request, ServingEngine
    from paddle_tpu.models import llama as L

    cfg = L.llama_tiny(num_hidden_layers=1)
    params = L.init_params(cfg, jax.random.PRNGKey(0))
    eng = ServingEngine(L, params, cfg, num_slots=2, max_len=16,
                        page_size=4, decode_chunk=2)
    name = f"replica{rank}"
    slo_fn = _burning_report if rank == 1 else _healthy_report
    pub = eng.publish_frames(name, None, min_interval_s=0.0,
                             slo_fn=slo_fn)
    rng = np.random.default_rng(rank)
    eng.run([Request(rid=i,
                     prompt=rng.integers(0, cfg.vocab_size, (4,))
                     .astype(np.int32), max_new_tokens=3)
             for i in range(3)])
    print(f"PUBLISHED rank={rank} name={name} seq={pub.seq}",
          flush=True)
    assert pub.seq >= 2

    # barrier-ish: both ranks must have published before rank 0 reads
    from paddle_tpu.distributed import collective as coll
    coll.barrier(tag="fedpub")

    if rank == 0:
        view = fed.FleetSLOView(None, staleness_s=60.0)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            view.poll(["replica0", "replica1"])
            if len(view.fresh_frames()) == 2:
                break
            time.sleep(0.2)
        rep = view.fleet_report(poll=False)
        print(f"FEDERATED rank=0 "
              f"replicas={','.join(rep['replicas'])}", flush=True)
        att = rep["attribution"]
        print(f"ATTRIBUTION rank=0 line1={att[0]['replica']}",
              flush=True)
        fed.set_active_view(view)
        srv = server.start_server(port=0)
        p = json.load(urllib.request.urlopen(
            f"{srv.url}/fleet/serving", timeout=10))
        ok = (p["source"] == "controller"
              and sorted(p["frames"]) == ["replica0", "replica1"]
              and p["report"]["alerting"] == ["ttft_p99_ms"])
        burner = p["report"]["attribution"][0]["replica"]
        print(f"SCRAPE rank=0 ok={1 if ok else 0} burner={burner}",
              flush=True)
        server.stop_server()
    # keep rank 1 alive until rank 0 finished reading its KV frames
    coll.barrier(tag="feddone")
    # GC leaves the KV clean for whatever runs next in this store
    hb.remove_named(None, name)


if __name__ == "__main__":
    main()
    sys.exit(0)
