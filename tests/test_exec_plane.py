"""Measured performance plane (monitor/exectime.py, profile_capture.py,
timeseries.py, roofline calibration, /profile + /timeseries routes).

The load-bearing contracts:

- **Sampling math**: 1-in-N on cache-HIT dispatches only; rate 0 or
  monitor-off adds ZERO ``block_until_ready`` calls and zero
  registrations (pinned by monkeypatching the sync indirection).
- **Calibration honesty**: ``model_error_ratio`` is measured/modeled
  when both legs exist and None otherwise — never fabricated; the
  worst ratio exports as ``roofline.model.max_error_ratio``.
- **Capture exclusivity**: one ``/profile`` window at a time (409 on
  the second), capture directory bounded (oldest evicted).
- **Drift detection**: recent-median vs trailing-baseline ratio trips
  the gauge + the warn-level /healthz provider (which never fails
  liveness), and the sentinel sees it observe-only.
"""
import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import monitor
from paddle_tpu.monitor import exectime
from paddle_tpu.monitor import profile_capture as pcap
from paddle_tpu.monitor import programs
from paddle_tpu.monitor import roofline
from paddle_tpu.monitor import server
from paddle_tpu.monitor import timeseries
from paddle_tpu.monitor import trace

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def mon():
    """Monitor on, clean state; everything torn down after."""
    monitor.reset()
    server.stop_server()
    pt.set_flags({"FLAGS_enable_monitor": True})
    yield monitor
    server.stop_server()
    server.unregister_health_provider("steptime_drift")
    timeseries._PROVIDER_REGISTERED[0] = False
    exectime.set_sample_rate(None)
    timeseries.set_capacity(None)
    pt.set_flags({"FLAGS_enable_monitor": False,
                  "FLAGS_enable_monitor_server": False})
    monitor.reset()


@pytest.fixture
def count_blocks(monkeypatch):
    """Count the sampler's added device synchronizations."""
    calls = []
    real = exectime._block_until_ready

    def counting(outputs):
        calls.append(1)
        real(outputs)

    monkeypatch.setattr(exectime, "_block_until_ready", counting)
    return calls


def _static_fn():
    import paddle_tpu.jit as jit

    @jit.to_static
    def f(x):
        return x * 2.0 + 1.0
    return f


def _get(url, timeout=30):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


# ---------------------------------------------------------------------------
# sampling
# ---------------------------------------------------------------------------

class TestExecSampling:
    def test_rate_resolution(self, mon, monkeypatch):
        exectime.set_sample_rate(None)
        monkeypatch.delenv("PADDLE_TPU_EXEC_SAMPLE", raising=False)
        assert exectime.sample_rate() == 16           # default
        exectime.set_sample_rate(None)
        monkeypatch.setenv("PADDLE_TPU_EXEC_SAMPLE", "4")
        assert exectime.sample_rate() == 4
        exectime.set_sample_rate(None)
        monkeypatch.setenv("PADDLE_TPU_EXEC_SAMPLE", "garbage")
        assert exectime.sample_rate() == 16           # invalid -> default
        exectime.set_sample_rate(0)
        assert exectime.sample_rate() == 0

    def test_hit_calls_sampled_into_histogram_and_record(self, mon):
        exectime.set_sample_rate(1)
        f = _static_fn()
        x = pt.to_tensor(np.ones((2, 4), "float32"))
        for _ in range(3):
            f(x)                       # 1 miss + 2 hits
        snap = monitor.snapshot()
        h = snap["histograms"]["jit.program.exec_ms"]
        assert h["count"] == 2         # misses are never exec-sampled
        assert snap["counters"]["jit.program.exec.samples"] == 2
        (rec,) = programs.programs_snapshot()
        assert rec["exec_samples"] == 2
        assert rec["exec_mean_ms"] > 0
        assert rec["exec_max_ms"] >= rec["exec_mean_ms"]

    def test_one_in_n(self, mon):
        exectime.set_sample_rate(4)
        f = _static_fn()
        x = pt.to_tensor(np.ones((2, 4), "float32"))
        f(x)                           # miss
        for _ in range(8):             # 8 hits at 1-in-4 -> 2 samples
            f(x)
        assert monitor.snapshot()["counters"][
            "jit.program.exec.samples"] == 2

    def test_rate_zero_adds_zero_syncs(self, mon, count_blocks):
        exectime.set_sample_rate(0)
        f = _static_fn()
        x = pt.to_tensor(np.ones((2, 4), "float32"))
        for _ in range(4):
            f(x)
        assert count_blocks == []
        snap = monitor.snapshot()
        assert "jit.program.exec_ms" not in snap.get("histograms", {})
        assert "jit.program.exec.samples" not in snap.get("counters", {})

    def test_monitor_off_zero_syncs_and_registrations(self, count_blocks):
        monitor.reset()
        pt.set_flags({"FLAGS_enable_monitor": False})
        exectime.set_sample_rate(1)
        try:
            f = _static_fn()
            x = pt.to_tensor(np.ones((2, 4), "float32"))
            for _ in range(4):
                f(x)
            assert count_blocks == []
            assert monitor.snapshot() == {}
            assert programs.programs_snapshot() == []
            assert exectime.maybe_sample(("k",)) is None
        finally:
            exectime.set_sample_rate(None)
            monitor.reset()

    def test_grad_path_hits_sampled(self, mon):
        exectime.set_sample_rate(1)
        import paddle_tpu.jit as jit

        @jit.to_static
        def f(x):
            return (x * x).sum()

        x = pt.to_tensor(np.ones((2, 3), "float32"),
                         stop_gradient=False)
        f(x)                                    # miss
        out = f(x)                              # hit on the grad path
        out.backward()
        assert monitor.snapshot()["counters"][
            "jit.program.exec.samples"] >= 1

    def test_time_call_and_last_sample_feed(self, mon):
        out, ms = exectime.time_call(
            ("t", "k"), lambda a, b: a + b, 1, 2)
        assert out == 3 and ms >= 0
        assert exectime.take_last_sample_ms() == ms
        assert exectime.take_last_sample_ms() is None   # consumed

    def test_reset_clears_sampler_state(self, mon):
        exectime.set_sample_rate(2)
        assert exectime.maybe_sample("k") is None       # count 1 of 2
        monitor.reset()
        # counts cleared: the next call is count 1 again, not a sample
        assert exectime.maybe_sample("k") is None
        assert exectime.maybe_sample("k") is not None


# ---------------------------------------------------------------------------
# program-record staleness (note_hit satellite)
# ---------------------------------------------------------------------------

class TestStaleness:
    def test_last_hit_age(self, mon):
        programs.record_program("k1", "p1", source="test")
        (rec,) = programs.programs_snapshot()
        assert rec["last_hit_age_s"] is None            # never hit
        programs.note_hit("k1")
        (rec,) = programs.programs_snapshot()
        assert rec["last_hit_age_s"] is not None
        assert 0 <= rec["last_hit_age_s"] < 5.0

    def test_note_exec_unknown_key_noop(self, mon):
        programs.note_exec(("nope",), 1.0)              # must not raise


# ---------------------------------------------------------------------------
# roofline calibration
# ---------------------------------------------------------------------------

class TestCalibration:
    def _peaks_env(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_PEAK_FLOPS", "1e9")
        monkeypatch.setenv("PADDLE_TPU_PEAK_HBM_GBS", "1")
        monkeypatch.setenv("PADDLE_TPU_PEAK_ICI_GBS", "1")

    def test_model_error_ratio_measured_vs_modeled(self, mon,
                                                   monkeypatch):
        self._peaks_env(monkeypatch)
        programs.record_program("m1", "measured", source="test",
                                flops=1e6, bytes_accessed=1e6)
        programs.note_exec("m1", 5.0)
        programs.note_exec("m1", 7.0)
        programs.record_program("m2", "unsampled", source="test",
                                flops=1e6, bytes_accessed=1e6)
        rs = roofline.roofline_snapshot(analyze=False)
        by = {p["name"]: p for p in rs["programs"]}
        m = by["measured"]
        # modeled: max(1e6/1e9, 1e6/1e9) = 1 ms; measured mean 6 ms
        assert m["model_error_ratio"] == pytest.approx(6.0, rel=1e-3)
        assert by["unsampled"]["model_error_ratio"] is None
        assert rs["calibration"]["measured_programs"] == 1
        assert rs["calibration"]["max_error_ratio"] == pytest.approx(
            6.0, rel=1e-3)
        g = monitor.snapshot()["gauges"]["roofline.model.max_error_ratio"]
        assert g == pytest.approx(6.0, rel=1e-3)

    def test_unclassified_program_never_gets_ratio(self, mon,
                                                   monkeypatch):
        self._peaks_env(monkeypatch)
        # sampled but cost-analysis unavailable: no modeled time
        programs.record_program("m3", "nocost", source="test",
                                flops=None, bytes_accessed=None)
        programs.note_exec("m3", 5.0)
        rs = roofline.roofline_snapshot(analyze=False)
        (p,) = [q for q in rs["programs"] if q["name"] == "nocost"]
        assert p["verdict"] is None
        assert p["model_error_ratio"] is None
        assert rs["calibration"]["measured_programs"] == 0
        assert rs["calibration"]["max_error_ratio"] is None

    def test_divergence_flag_both_directions(self, mon, monkeypatch):
        self._peaks_env(monkeypatch)
        monkeypatch.setenv("PADDLE_TPU_ROOFLINE_ERROR_MAX", "2")
        for key, name, ms in (("d1", "way_over", 10.0),
                              ("d2", "way_under", 0.1),
                              ("d3", "близко", 1.2)):
            programs.record_program(key, name, source="test",
                                    flops=1e6, bytes_accessed=1e6)
            programs.note_exec(key, ms)
        rs = roofline.roofline_snapshot(analyze=False)
        by = {p["name"]: p for p in rs["programs"]}
        assert by["way_over"]["model_divergent"] is True     # 10x
        assert by["way_under"]["model_divergent"] is True    # 0.1x
        assert by["близко"]["model_divergent"] is False      # 1.2x
        names = {d["name"] for d in rs["calibration"]["divergent"]}
        assert names == {"way_over", "way_under"}

    def test_max_error_ratio_worst_in_either_direction(self, mon,
                                                       monkeypatch):
        # a 0.05x ratio (model 20x overestimates) must outrank a 1.1x
        # in the gauge — raw max() would mask it behind the ratio
        # nearer 1
        self._peaks_env(monkeypatch)
        for key, name, ms in (("w1", "slightly_over", 1.1),
                              ("w2", "far_under", 0.05)):
            programs.record_program(key, name, source="test",
                                    flops=1e6, bytes_accessed=1e6)
            programs.note_exec(key, ms)
        rs = roofline.roofline_snapshot(analyze=False)
        assert rs["calibration"]["max_error_ratio"] == pytest.approx(
            0.05, rel=1e-3)
        g = monitor.snapshot()["gauges"][
            "roofline.model.max_error_ratio"]
        assert g == pytest.approx(0.05, rel=1e-3)

    def test_threshold_env_parsing(self, monkeypatch):
        monkeypatch.delenv("PADDLE_TPU_ROOFLINE_ERROR_MAX",
                           raising=False)
        assert roofline.model_error_threshold() == 4.0
        monkeypatch.setenv("PADDLE_TPU_ROOFLINE_ERROR_MAX", "0.5")
        assert roofline.model_error_threshold() == 4.0   # must be > 1
        monkeypatch.setenv("PADDLE_TPU_ROOFLINE_ERROR_MAX", "junk")
        assert roofline.model_error_threshold() == 4.0


# ---------------------------------------------------------------------------
# timeseries + drift
# ---------------------------------------------------------------------------

class TestTimeseries:
    def test_off_path_records_nothing(self):
        monitor.reset()
        pt.set_flags({"FLAGS_enable_monitor": False})
        timeseries.record_step(total_ms=1.0)
        assert timeseries.rows() == []
        assert monitor.snapshot() == {}

    def test_ring_bounded(self, mon):
        timeseries.set_capacity(16)
        for i in range(40):
            timeseries.record_step(step=i, total_ms=1.0)
        assert len(timeseries.rows()) == 16
        assert timeseries.total_rows() == 40
        assert timeseries.rows()[-1]["step"] == 39

    def test_auto_step_index(self, mon):
        timeseries.record_step(total_ms=1.0)
        timeseries.record_step(total_ms=1.0)
        assert [r["step"] for r in timeseries.rows()] == [1, 2]

    def test_drift_none_until_windows_fill(self, mon):
        for i in range(10):
            timeseries.record_step(total_ms=10.0)
        st = timeseries.drift_status()     # < 2*recent(8) rows
        assert st["ratio"] is None and st["drifting"] is False
        assert "train.step.drift_ratio" not in \
            monitor.snapshot().get("gauges", {})

    def test_drift_trips_on_slowdown(self, mon):
        for i in range(32):
            timeseries.record_step(total_ms=10.0)
        for i in range(8):
            timeseries.record_step(total_ms=30.0)
        st = timeseries.drift_status()
        assert st["ratio"] == pytest.approx(3.0)
        assert st["drifting"] is True
        assert monitor.snapshot()["gauges"][
            "train.step.drift_ratio"] == pytest.approx(3.0)

    def test_steady_run_does_not_drift(self, mon):
        for i in range(48):
            timeseries.record_step(total_ms=10.0 + (i % 3) * 0.1)
        st = timeseries.drift_status()
        assert st["ratio"] == pytest.approx(1.0, abs=0.05)
        assert st["drifting"] is False

    def test_warn_level_healthz_provider_never_fails_liveness(self,
                                                              mon):
        for i in range(32):
            timeseries.record_step(total_ms=10.0)
        for i in range(8):
            timeseries.record_step(total_ms=100.0)    # 10x drift
        ok, payload = server.health()
        assert ok                                     # warn-level
        rep = payload["providers"]["steptime_drift"]
        assert rep["level"] == "warn"
        assert rep["drifting"] is True and rep["ratio"] > 5

    def test_grad_norm_ema_filled_from_gauge(self, mon):
        monitor.set_gauge("train.anomaly.grad_norm_ema", 1.25)
        timeseries.record_step(total_ms=5.0)
        assert timeseries.rows()[-1]["grad_norm_ema"] == 1.25

    def test_flight_record_carries_timeseries(self, mon):
        timeseries.record_step(total_ms=5.0, loss=2.5)
        payload = trace.flight_payload()
        assert payload["timeseries"]["rows"][-1]["loss"] == 2.5
        assert "drift" in payload["timeseries"]

    def test_steptimer_feeds_rows(self, mon):
        st = monitor.StepTimer("t")
        with st.compute():
            time.sleep(0.002)
        st.end_step(useful_tokens=100, loss=3.5)
        (row,) = timeseries.rows()
        assert row["step"] == 1
        assert row["compute_ms"] >= 1.0
        assert row["total_ms"] >= row["compute_ms"]
        assert row["loss"] == 3.5
        assert row["goodput_tokens_per_sec"] > 0

    def test_timeseries_route(self, mon):
        srv = server.start_server(port=0)
        timeseries.record_step(total_ms=7.0)
        status, body = _get(f"{srv.url}/timeseries")
        assert status == 200
        payload = json.loads(body)
        assert payload["rows"][-1]["total_ms"] == 7.0
        assert "drift" in payload and "capacity" in payload


# ---------------------------------------------------------------------------
# sentinel drift visibility (observe-only)
# ---------------------------------------------------------------------------

class TestSentinelDrift:
    def test_loop_feeds_timeseries_and_surfaces_drift(self, mon):
        from paddle_tpu.training.sentinel import (AnomalySentinel,
                                                  SentinelLoop)

        def fake_step(params, opt, batch, cap):
            return params, opt, 0.5, {"finite": True, "grad_norm": 1.0}

        def make_stream():
            return iter([(i,) for i in range(24)])

        loop = SentinelLoop(fake_step, {"w": 0}, {"m": 0}, make_stream,
                            sentinel=AnomalySentinel())
        out = loop.run(24)
        assert out["applied"] == 24
        rows = timeseries.rows()
        assert len(rows) == 24
        assert rows[-1]["total_ms"] is not None
        assert rows[-1]["loss"] == 0.5
        assert rows[-1]["grad_norm_ema"] is not None
        # drift visible on the sentinel (observe-only: all applied)
        assert loop.sentinel.step_time_drift == \
            timeseries.drift_status()["ratio"]
        # and in the health provider payload
        from paddle_tpu.training.sentinel import \
            _sentinel_health_provider
        import weakref
        rep = _sentinel_health_provider(weakref.ref(loop))()
        assert "step_time_drift" in rep


# ---------------------------------------------------------------------------
# profile capture
# ---------------------------------------------------------------------------

@pytest.fixture
def fake_profiler(monkeypatch):
    """Stub jax.profiler start/stop for the capture LOGIC tests.

    The real profiler cannot run in the shared tier-1 process: once
    test_device_plugin registers its fake PJRT plugin (a permanent
    in-process registration), this jaxlib's ``start_trace`` segfaults
    collecting from a plugin with no profiler extension. The stub
    keeps the exclusivity/eviction/route logic honest (it writes a
    marker trace file per capture); the REAL profiler integration is
    pinned by ``test_real_capture_in_subprocess`` (fresh process, no
    plugin) and the ``profile_capture`` tpu_smoke stage."""
    import jax
    state = {"dir": None}

    def start(d, *a, **kw):
        state["dir"] = d

    def stop():
        d = state.pop("dir", None)
        if d:
            sub = os.path.join(d, "plugins", "profile", "stub")
            os.makedirs(sub, exist_ok=True)
            with open(os.path.join(sub, "stub.xplane.pb"), "wb") as f:
                f.write(b"stub-trace")

    monkeypatch.setattr(jax.profiler, "start_trace", start)
    monkeypatch.setattr(jax.profiler, "stop_trace", stop)
    return state


class TestProfileCapture:
    def test_capture_writes_trace_and_evicts(self, mon, tmp_path,
                                             monkeypatch,
                                             fake_profiler):
        base = str(tmp_path / "caps")
        monkeypatch.setenv("PADDLE_TPU_PROFILE_KEEP", "2")
        infos = []
        for _ in range(3):
            infos.append(pcap.capture_sync(0.05, base_dir=base))
            time.sleep(0.01)       # distinct capture-dir microseconds
        assert infos[-1]["files"], infos[-1]
        # bounded: only the newest 2 remain, oldest evicted
        kept = pcap.list_captures(base)
        assert len(kept) == 2
        assert os.path.basename(infos[0]["dir"]) not in kept
        assert os.path.basename(infos[-1]["dir"]) in kept
        assert infos[-1]["evicted"] >= 1
        assert monitor.snapshot()["counters"][
            "monitor.profile.captures"] == 3

    def test_concurrent_capture_raises_busy(self, mon, tmp_path,
                                            fake_profiler):
        base = str(tmp_path / "caps")
        started = threading.Event()
        results = {}

        def long_capture():
            started.set()
            results["first"] = pcap.capture_sync(0.6, base_dir=base)

        t = threading.Thread(target=long_capture)
        t.start()
        started.wait()
        deadline = time.time() + 2
        while not pcap.capturing() and time.time() < deadline:
            time.sleep(0.01)
        with pytest.raises(pcap.CaptureBusy):
            pcap.capture_sync(0.05, base_dir=base)
        t.join()
        assert results["first"]["files"]
        assert not pcap.capturing()

    def test_profile_route_409_and_400(self, mon, tmp_path,
                                       monkeypatch, fake_profiler):
        monkeypatch.setenv("PADDLE_TPU_PROFILE_DIR",
                           str(tmp_path / "caps"))
        srv = server.start_server(port=0)
        results = []

        def hit():
            results.append(_get(f"{srv.url}/profile?seconds=0.5"))

        ts = [threading.Thread(target=hit) for _ in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        codes = sorted(r[0] for r in results)
        assert codes == [200, 409], codes
        ok_body = json.loads([r[1] for r in results
                              if r[0] == 200][0])
        assert ok_body["files"]
        assert monitor.snapshot()["counters"][
            "monitor.profile.busy_rejected"] == 1
        assert _get(f"{srv.url}/profile?seconds=abc")[0] == 400
        assert _get(f"{srv.url}/profile?seconds=0")[0] == 400
        assert _get(f"{srv.url}/profile?seconds=999")[0] == 400

    def test_annotations_null_outside_capture(self):
        a = pcap.annotate("x")
        b = pcap.annotate_step("x", 3)
        with a, b:
            pass                        # null contexts, no jax import
        assert not pcap.capturing()

    def test_bad_seconds_rejected(self):
        with pytest.raises(ValueError):
            pcap.capture_sync(0)
        with pytest.raises(ValueError):
            pcap.capture_sync(-1)

    @pytest.mark.slow
    def test_real_capture_in_subprocess(self, tmp_path):
        """The REAL jax.profiler path — in a fresh process, where no
        fake PJRT plugin (test_device_plugin) can segfault the
        tracer's device collection. Asserts a nonempty xplane landed
        while jnp work ran inside the window.

        Slow lane (tier-1 rebalance): ~26s of fresh-interpreter + jax
        import; the fast lane keeps every capture LOGIC pin (stubbed
        profiler) and scripts/tpu_smoke.py's profile_capture stage
        drives this same real path end to end."""
        code = (
            "import os, sys, threading\n"
            "os.environ.setdefault('JAX_PLATFORMS', 'cpu')\n"
            "import paddle_tpu as pt\n"
            "pt.set_flags({'FLAGS_enable_monitor': True})\n"
            "import jax.numpy as jnp\n"
            "from paddle_tpu.monitor import profile_capture as pcap\n"
            "stop = threading.Event()\n"
            "def work():\n"
            "    while not stop.is_set():\n"
            "        jnp.ones((64, 64)).sum().block_until_ready()\n"
            "        stop.wait(0.02)\n"
            "t = threading.Thread(target=work); t.start()\n"
            "try:\n"
            "    info = pcap.capture_sync(0.3, base_dir=sys.argv[1])\n"
            "finally:\n"
            "    stop.set(); t.join()\n"
            "assert any(f['path'].endswith('.xplane.pb')\n"
            "           and (f['bytes'] or 0) > 0\n"
            "           for f in info['files']), info\n"
            "print('CAPTURE_OK')\n")
        r = subprocess.run(
            [sys.executable, "-c", code, str(tmp_path)],
            capture_output=True, text=True, timeout=300,
            env=dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu"))
        assert r.returncode == 0 and "CAPTURE_OK" in r.stdout, \
            (r.returncode, r.stderr[-2000:])


# ---------------------------------------------------------------------------
# engine integration
# ---------------------------------------------------------------------------

class TestEngineExec:
    def test_serving_programs_sampled(self, mon):
        exectime.set_sample_rate(1)
        import jax
        from paddle_tpu.inference import Request, ServingEngine
        from paddle_tpu.models import llama as L
        cfg = L.llama_tiny(num_hidden_layers=2)
        params = L.init_params(cfg, jax.random.PRNGKey(0))
        eng = ServingEngine(L, params, cfg, num_slots=2, max_len=32,
                            page_size=8, decode_chunk=2)
        rng = np.random.default_rng(0)
        outs = eng.run([Request(
            rid=i, prompt=rng.integers(0, cfg.vocab_size, (6,))
            .astype(np.int32), max_new_tokens=4) for i in range(2)])
        assert sorted(outs) == [0, 1]
        by = {r["name"]: r for r in programs.programs_snapshot()}
        chunk = next(v for k, v in by.items()
                     if k.startswith("serving.decode_chunk"))
        assert chunk["exec_samples"] >= 1
        assert chunk["exec_mean_ms"] > 0
        # repeat dispatches count as hits -> staleness stamped
        assert chunk["hits"] >= 1
        assert chunk["last_hit_age_s"] is not None
        # engine samples must NOT feed the step-timeseries last-sample
        # slot — a decode-chunk sample between two train steps would
        # otherwise be misattributed as that train step's exec time
        assert exectime.take_last_sample_ms() is None


# ---------------------------------------------------------------------------
# bench guard: lower-is-better exec rungs
# ---------------------------------------------------------------------------

def _load_guard():
    import importlib.util
    path = os.path.join(REPO, "scripts", "check_bench_regression.py")
    spec = importlib.util.spec_from_file_location(
        "check_bench_regression_exec", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _bench_blob(value, exec_block=None):
    rec = {"metric": "llama_train_tokens_per_sec_per_chip",
           "value": value, "unit": "tokens/s"}
    if exec_block is not None:
        rec["extra"] = {"metrics": {"exec": exec_block}}
    return {"n": 5, "rc": 0, "tail": json.dumps(rec) + "\n",
            "parsed": rec}


class TestExecBenchGuard:
    def _write(self, root, rnd, blob):
        with open(os.path.join(root, f"BENCH_r{rnd:02d}.json"),
                  "w") as f:
            json.dump(blob, f)

    def test_absence_on_old_files_skipped_not_zero_floored(self,
                                                           tmp_path):
        guard = _load_guard()
        root = str(tmp_path)
        # old rounds predate the exec block entirely
        self._write(root, 1, _bench_blob(1000.0))
        self._write(root, 2, _bench_blob(1010.0))
        self._write(root, 3, _bench_blob(
            1000.0, exec_block={"headline": {"p50_ms": 120.0}}))
        ok, lines = guard.check(root)
        assert ok, "\n".join(lines)     # no prior ceiling -> no guard

    def test_exec_slowdown_beyond_tolerance_fails(self, tmp_path):
        guard = _load_guard()
        root = str(tmp_path)
        self._write(root, 1, _bench_blob(
            1000.0, exec_block={"headline": {"p50_ms": 100.0}}))
        self._write(root, 2, _bench_blob(
            1000.0, exec_block={"headline": {"p50_ms": 130.0}}))
        ok, lines = guard.check(root)
        assert not ok
        assert any("headline_exec_ms_p50" in l and "REGRESSION" in l
                   for l in lines)

    def test_exec_within_tolerance_passes(self, tmp_path):
        guard = _load_guard()
        root = str(tmp_path)
        self._write(root, 1, _bench_blob(
            1000.0, exec_block={"headline": {"p50_ms": 100.0}}))
        self._write(root, 2, _bench_blob(
            1000.0, exec_block={"headline": {"p50_ms": 110.0}}))
        ok, lines = guard.check(root)
        assert ok, "\n".join(lines)

    def test_exec_improvement_passes_and_newest_absence_reported(
            self, tmp_path):
        guard = _load_guard()
        root = str(tmp_path)
        self._write(root, 1, _bench_blob(
            1000.0, exec_block={"headline": {"p50_ms": 100.0}}))
        self._write(root, 2, _bench_blob(
            1000.0, exec_block={"headline": {"p50_ms": 60.0}}))
        ok, _ = guard.check(root)
        assert ok
        # newest run dropped the block: reported, not a failure
        self._write(root, 3, _bench_blob(1000.0))
        ok, lines = guard.check(root)
        assert ok, "\n".join(lines)
        assert any("headline_exec_ms_p50" in l and "absent" in l
                   for l in lines)

    def test_checked_in_trajectory_still_green(self):
        guard = _load_guard()
        ok, lines = guard.check(REPO)
        assert ok, "\n".join(lines)
