"""ONNX export: jaxpr -> ONNX converter (closes the last L8 delta).

Reference: python/paddle/onnx/__init__.py -> paddle2onnx. Validation
strategy (no onnx/onnxruntime in this environment): parse the exported
bytes back through the same protoc-compiled schema and EXECUTE the
graph with the numpy interpreter in tests/_onnx_runner.py — numerical
agreement with the eager model validates node semantics (Einsum
equations, Where ordering, Gather axes), not just structure.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.core import enforce as E
from paddle_tpu.onnx import export, onnx_pb2 as P
from paddle_tpu.onnx.converter import export_layer, to_onnx_model

from _onnx_runner import run, tensor_to_np


def _check(layer, inputs, rtol=1e-5, atol=1e-5):
    layer.eval()
    model = export_layer(layer, inputs)
    # serialize + reparse: what a consumer reads, not in-memory objects
    model = P.ModelProto.FromString(model.SerializeToString())
    got = run(model, inputs)
    want = layer(*[paddle.to_tensor(x) for x in inputs])
    want = want if isinstance(want, (list, tuple)) else [want]
    assert len(got) == len(want)
    for g, w in zip(got, want):
        np.testing.assert_allclose(g, w.numpy(), rtol=rtol, atol=atol)
    return model


class TestOnnxExport:
    def test_mlp_numerics(self):
        net = nn.Sequential(nn.Linear(4, 16), nn.ReLU(),
                            nn.Linear(16, 8), nn.GELU(),
                            nn.Linear(8, 3), nn.Softmax())
        x = np.random.default_rng(0).normal(size=(5, 4)).astype("float32")
        m = _check(net, [x])
        assert m.opset_import[0].version == 17
        assert any(n.op_type == "Einsum" for n in m.graph.node)

    def test_layernorm_and_residual(self):
        class Block(nn.Layer):
            def __init__(self):
                super().__init__()
                self.ln = nn.LayerNorm(8)
                self.fc = nn.Linear(8, 8)

            def forward(self, x):
                return x + self.fc(self.ln(x))

        x = np.random.default_rng(1).normal(size=(3, 8)).astype("float32")
        _check(Block(), [x], rtol=1e-4, atol=1e-5)

    def test_embedding_gather(self):
        emb = nn.Embedding(10, 6)
        ids = np.asarray([[1, 3, 5], [2, 0, 9]], "int32")
        _check(emb, [ids])

    def test_conv_net(self):
        net = nn.Sequential(nn.Conv2D(3, 4, 3, padding=1), nn.ReLU(),
                            nn.Conv2D(4, 2, 3, stride=2))
        x = np.random.default_rng(2).normal(
            size=(1, 3, 8, 8)).astype("float32")
        _check(net, [x], rtol=1e-4, atol=1e-4)

    def test_pooling(self):
        net = nn.Sequential(nn.Conv2D(2, 3, 3, padding=1), nn.ReLU(),
                            nn.MaxPool2D(2, 2),
                            nn.AvgPool2D(2, 2, padding=1))
        x = np.random.default_rng(6).normal(
            size=(1, 2, 8, 8)).astype("float32")
        _check(net, [x], rtol=1e-4, atol=1e-4)

    @pytest.mark.slow  # tier-1 budget (ISSUE 19 rebalance): big structural export; conv_net/pooling
    # cover the same op set at unit scale
    def test_resnet18_exports_structurally(self):
        # full vision flagship: conv/bn-eval/relu/maxpool/residuals/
        # adaptive-avgpool/fc all convert (numeric check skipped: the
        # test interpreter's python-loop conv is too slow at this size)
        from paddle_tpu.vision.models import resnet18

        net = resnet18()
        net.eval()
        m = export_layer(net, [np.zeros((1, 3, 64, 64), "float32")])
        ops = {n.op_type for n in m.graph.node}
        assert {"Conv", "MaxPool", "Einsum"} <= ops, ops
        assert len(m.graph.initializer) > 60
        # reparse: the serialized bytes are schema-valid
        P.ModelProto.FromString(m.SerializeToString())

    def test_attention_block_no_flash(self):
        mha = nn.MultiHeadAttention(16, 4)
        x = np.random.default_rng(3).normal(
            size=(2, 5, 16)).astype("float32")
        _check(mha, [x], rtol=1e-4, atol=1e-4)

    def test_two_inputs_and_comparison_ops(self):
        class F(nn.Layer):
            def forward(self, a, b):
                return paddle.where(a > b, a - b, b * 2.0)

        a = np.random.default_rng(4).normal(size=(4, 4)).astype("float32")
        b = np.random.default_rng(5).normal(size=(4, 4)).astype("float32")
        _check(F(), [a, b])

    def test_params_become_initializers(self):
        lin = nn.Linear(4, 2)
        lin.eval()
        m = export_layer(lin, [np.zeros((1, 4), "float32")])
        inits = {i.name: tensor_to_np(i) for i in m.graph.initializer}
        vals = sorted((v for v in inits.values()), key=lambda v: v.size)
        w = lin.weight.numpy()
        assert any(v.shape == w.shape and np.allclose(v, w)
                   for v in inits.values())
        assert len(m.graph.input) == 1       # params NOT graph inputs
        assert vals

    def test_llama_scan_unroll_numerics(self):
        # flagship export: the scan-over-layers decoder unrolls into
        # plain dataflow; numeric parity vs the eager model validates
        # the unroll's carry threading and per-iteration slicing
        import jax
        from paddle_tpu.models import llama as L

        cfg = L.llama_tiny(num_hidden_layers=2, hidden_size=32,
                           num_attention_heads=2, num_key_value_heads=2,
                           vocab_size=64, remat=False)
        params = L.init_params(cfg, jax.random.PRNGKey(0))
        ids = np.asarray([[1, 5, 9, 3]], "int32")

        def fn(i):
            return L.forward(params, i, cfg)

        m = to_onnx_model(fn, [ids])
        m = P.ModelProto.FromString(m.SerializeToString())
        got = run(m, [ids])[0]
        want = np.asarray(fn(ids), np.float32)
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)

    def test_scan_beyond_unroll_cap_becomes_loop(self):
        # 500 > _MAX_SCAN_UNROLL: converts as one ONNX Loop node whose
        # body subgraph gathers x[i], not 500 unrolled copies
        import jax

        def fn(x):
            c, ys = jax.lax.scan(lambda c, v: (c * 0.99 + v, c.sum()),
                                 x[0], x)
            return c, ys

        x = np.random.default_rng(3).normal(size=(500, 2)).astype(
            "float32")
        m = to_onnx_model(fn, [x])
        assert sum(1 for n in m.graph.node if n.op_type == "Loop") == 1
        assert len(m.graph.node) < 30
        m = P.ModelProto.FromString(m.SerializeToString())
        got = run(m, [x])
        want = fn(x)
        np.testing.assert_allclose(got[0], np.asarray(want[0]),
                                   rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(got[1], np.asarray(want[1]),
                                   rtol=1e-4, atol=1e-4)

    @pytest.mark.slow  # tier-1 budget (ISSUE 19 rebalance): forced-loop arm; scan_beyond_unroll_cap
    # already pins loop lowering, scan_unroll pins llama numerics
    def test_llama_loop_path_numerics(self, monkeypatch):
        # force the flagship scan-over-layers decoder down the Loop path
        # (cap 0) and check parity vs eager — proves real models convert
        # at arbitrary depth, not just toy scans
        import jax
        from paddle_tpu.models import llama as L
        from paddle_tpu.onnx import converter as C

        monkeypatch.setattr(C, "_MAX_SCAN_UNROLL", 0)
        cfg = L.llama_tiny(num_hidden_layers=3, hidden_size=32,
                           num_attention_heads=2, num_key_value_heads=2,
                           vocab_size=64, remat=False)
        params = L.init_params(cfg, jax.random.PRNGKey(0))
        ids = np.asarray([[1, 5, 9, 3]], "int32")

        def fn(i):
            return L.forward(params, i, cfg)

        m = to_onnx_model(fn, [ids])
        assert any(n.op_type == "Loop" for n in m.graph.node)
        m = P.ModelProto.FromString(m.SerializeToString())
        got = run(m, [ids])[0]
        want = np.asarray(fn(ids), np.float32)
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)

    def test_sort_topk_numerics(self):
        class F(nn.Layer):
            def forward(self, x):
                v, i = paddle.topk(x, 3)
                return paddle.sort(x, axis=-1), v

        x = np.random.default_rng(7).normal(size=(4, 8)).astype("float32")
        _check(F(), [x])

    def test_unsupported_primitive_typed_error(self, tmp_path):
        import jax.numpy as jnp

        def fn(x):
            return jnp.argsort(x, axis=-1)

        with pytest.raises(E.UnimplementedError, match="argsort"):
            to_onnx_model(fn, [np.ones((3, 2), "float32")])

    def test_export_api_writes_file(self, tmp_path):
        net = nn.Sequential(nn.Linear(4, 2))
        net.eval()
        p = export(net, str(tmp_path / "m"),
                   input_spec=[np.ones((1, 4), "float32")])
        assert p.endswith(".onnx")
        m = P.ModelProto.FromString(open(p, "rb").read())
        assert m.producer_name == "paddle-tpu"
        assert m.graph.node

    def test_export_api_fallback_saves_stablehlo(self, tmp_path):
        class Sorter(nn.Layer):
            def forward(self, x):
                return paddle.argsort(x, axis=-1)  # multi-operand sort

        with pytest.raises(E.UnimplementedError, match="sort"):
            export(Sorter(), str(tmp_path / "s"),
                   input_spec=[np.ones((3, 2), "float32")])
        assert (tmp_path / "s.pdmodel").exists()   # StableHLO fallback


class TestDynamicDims:
    """Trace-twice shape polymorphism: initializer entries affine in a
    marked dim are rewritten as runtime Shape() computations, so the
    export runs at sizes never traced."""

    def test_flatten_mlp_dynamic_batch(self):
        # Flatten bakes [B, F] into a Reshape target — the classic
        # dynamic-batch breaker. Export at B=2, execute at B=5.
        class F(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(12, 4)

            def forward(self, x):
                return self.fc(paddle.flatten(x, start_axis=1))

        layer = F(); layer.eval()
        x2 = np.random.default_rng(0).normal(size=(2, 3, 4)).astype(
            "float32")
        m = export_layer(layer, [x2], dynamic_axes={0: {0: "batch"}})
        # input dim 0 is symbolic
        d0 = m.graph.input[0].type.tensor_type.shape.dim[0]
        assert d0.dim_param == "batch"
        d0out = m.graph.output[0].type.tensor_type.shape.dim[0]
        assert d0out.dim_param == "batch"
        m = P.ModelProto.FromString(m.SerializeToString())
        x5 = np.random.default_rng(1).normal(size=(5, 3, 4)).astype(
            "float32")
        got = run(m, [x5])[0]
        want = layer(paddle.to_tensor(x5)).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    def test_attention_softmax_dynamic_batch(self):
        # broadcast/reduce/reshape-heavy graph at a never-traced size
        class F(nn.Layer):
            def __init__(self):
                super().__init__()
                self.q = nn.Linear(8, 8)
                self.k = nn.Linear(8, 8)

            def forward(self, x):
                q, k = self.q(x), self.k(x)
                a = paddle.matmul(q, k, transpose_y=True) / 8 ** 0.5
                a = paddle.nn.functional.softmax(a, axis=-1)
                return paddle.matmul(a, x)

        layer = F(); layer.eval()
        x = np.random.default_rng(2).normal(size=(2, 6, 8)).astype(
            "float32")
        m = export_layer(layer, [x], dynamic_axes={0: {0: "b"}})
        m = P.ModelProto.FromString(m.SerializeToString())
        x7 = np.random.default_rng(3).normal(size=(7, 6, 8)).astype(
            "float32")
        got = run(m, [x7])[0]
        want = layer(paddle.to_tensor(x7)).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_structure_dependent_on_dim_raises(self):
        import jax.numpy as jnp

        def fn(x):
            # iota of length B: the baked arange CHANGES SHAPE with the
            # marked dim -> honest typed failure, not a wrong graph
            return jnp.arange(x.shape[0]) + x[:, 0].astype(jnp.int32)

        x = np.zeros((3, 2), "float32")
        with pytest.raises(E.UnimplementedError):
            to_onnx_model(fn, [x], dynamic_axes={0: {0: "batch"}})

    def test_export_api_inputspec_none_dim(self, tmp_path):
        from paddle_tpu.jit.api import InputSpec
        from paddle_tpu.onnx import export

        class F(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(6, 3)

            def forward(self, x):
                return self.fc(paddle.flatten(x, start_axis=1))

        layer = F(); layer.eval()
        p = export(layer, str(tmp_path / "m"),
                   input_spec=[InputSpec([None, 2, 3], "float32")])
        with open(p, "rb") as f:
            m = P.ModelProto.FromString(f.read())
        d0 = m.graph.input[0].type.tensor_type.shape.dim[0]
        assert d0.dim_param
        x = np.random.default_rng(4).normal(size=(9, 2, 3)).astype(
            "float32")
        got = run(m, [x])[0]
        want = layer(paddle.to_tensor(x)).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    def test_two_symbols_attributed_independently(self):
        # the two-point-fit trap: with batch AND seq both dynamic, a
        # seq-derived Reshape entry must NOT be attributed to batch.
        # Exported at (B=2, S=6), executed at (B=4, S=3).
        class F(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(8, 8)

            def forward(self, x):       # [B, S, 8]
                y = self.fc(x)
                # bakes a [B, S*8] Reshape target: entry 0 is affine in
                # batch, entry 1 affine in seq (k=8) — a two-point fit
                # with shared traces would attribute BOTH to batch
                return paddle.flatten(y, start_axis=1)

        layer = F(); layer.eval()
        x = np.random.default_rng(5).normal(size=(2, 6, 8)).astype(
            "float32")
        m = export_layer(layer, [x],
                         dynamic_axes={0: {0: "batch", 1: "seq"}})
        m = P.ModelProto.FromString(m.SerializeToString())
        x2 = np.random.default_rng(6).normal(size=(4, 3, 8)).astype(
            "float32")
        got = run(m, [x2])[0]
        want = layer(paddle.to_tensor(x2)).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    def test_product_of_two_dynamic_dims_raises(self):
        import jax.numpy as jnp

        def fn(x):
            return jnp.reshape(x, (x.shape[0] * x.shape[1],))

        x = np.zeros((2, 6), "float32")
        with pytest.raises(E.UnimplementedError, match="several"):
            to_onnx_model(fn, [x],
                          dynamic_axes={0: {0: "b", 1: "s"}})


class TestLoopBodyNaming:
    def test_repeated_and_passthrough_outvars(self, monkeypatch):
        # body outputs that repeat one var / pass a carry through
        # unchanged must still yield unique, body-produced output names
        import jax
        from paddle_tpu.onnx import converter as C

        monkeypatch.setattr(C, "_MAX_SCAN_UNROLL", 0)

        def fn(x):
            def cell(c, v):
                y = c + v
                return y, y          # carry AND ys are the SAME var
            c, ys = jax.lax.scan(cell, x[0], x)
            return c, ys

        x = np.random.default_rng(8).normal(size=(5, 3)).astype(
            "float32")
        m = to_onnx_model(fn, [x])
        (loop,) = [n for n in m.graph.node if n.op_type == "Loop"]
        (body,) = [a.g for a in loop.attribute if a.name == "body"]
        out_names = [vi.name for vi in body.output]
        assert len(out_names) == len(set(out_names))
        produced = {o for n in body.node for o in n.output}
        assert set(out_names) <= produced
        in_names = {vi.name for vi in body.input}
        assert not (set(out_names) & in_names)
        m = P.ModelProto.FromString(m.SerializeToString())
        got = run(m, [x])
        want = fn(x)
        np.testing.assert_allclose(got[0], np.asarray(want[0]),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(got[1], np.asarray(want[1]),
                                   rtol=1e-5, atol=1e-6)


class TestGeneralGathers:
    def test_take_along_nonzero_axis(self):
        import jax.numpy as jnp

        def fn(x, idx):
            return jnp.take(x, idx, axis=1)

        x = np.random.default_rng(9).normal(size=(3, 7, 4)).astype(
            "float32")
        idx = np.asarray([[2, 0], [5, 1]], "int32")
        m = to_onnx_model(fn, [x, idx])
        assert any(n.op_type == "Gather" for n in m.graph.node)
        m = P.ModelProto.FromString(m.SerializeToString())
        got = run(m, [x, idx])[0]
        np.testing.assert_allclose(got, np.take(x, idx, axis=1))

    def test_take_last_axis(self):
        import jax.numpy as jnp

        def fn(x, idx):
            return jnp.take(x, idx, axis=2)

        x = np.random.default_rng(10).normal(size=(2, 3, 9)).astype(
            "float32")
        idx = np.asarray([4, 8, 0], "int32")
        m = P.ModelProto.FromString(
            to_onnx_model(fn, [x, idx]).SerializeToString())
        got = run(m, [x, idx])[0]
        np.testing.assert_allclose(got, np.take(x, idx, axis=2))

    def test_multi_coordinate_advanced_indexing(self):
        import jax.numpy as jnp

        def fn(x, ij):
            return x[ij[:, 0], ij[:, 1]]

        x = np.random.default_rng(11).normal(size=(5, 6, 3)).astype(
            "float32")
        ij = np.asarray([[0, 2], [4, 5], [3, 0]], "int32")
        m = to_onnx_model(fn, [x, ij])
        assert any(n.op_type == "GatherND" for n in m.graph.node)
        m = P.ModelProto.FromString(m.SerializeToString())
        got = run(m, [x, ij])[0]
        np.testing.assert_allclose(got, x[ij[:, 0], ij[:, 1]])


def _export_and_run(fn, args, rtol=1e-6, **np_kw):
    """Serialize-roundtrip the export, execute it in the numpy
    interpreter, and pin it to eager jax (shared by the control-flow
    and OOB-gather test classes)."""
    m = P.ModelProto.FromString(
        to_onnx_model(fn, args).SerializeToString())
    got = run(m, args)
    want = fn(*args)
    want = [np.asarray(w) for w in
            (want if isinstance(want, (list, tuple)) else [want])]
    for g, w in zip(got, want):
        np.testing.assert_allclose(g, w, rtol=rtol, **np_kw)
    return m


class TestCondExport:
    """lax.cond / lax.switch -> ONNX If: one exported model serves both
    branch outcomes (previously a documented fallback-to-StableHLO)."""

    def _np_run(self, fn, args):
        return _export_and_run(fn, args)

    def test_cond_both_outcomes_one_model(self):
        import jax.numpy as jnp
        from jax import lax

        def fn(x, flag):
            return lax.cond(flag[0] > 0,
                            lambda x: x * 2.0 + 1.0,
                            lambda x: x - 3.0, x)

        x = np.random.default_rng(0).normal(size=(2, 3)).astype("float32")
        m = self._np_run(fn, [x, np.asarray([1], "int32")])
        assert any(n.op_type == "If" for n in m.graph.node)
        self._np_run(fn, [x, np.asarray([-1], "int32")])

    def test_switch_three_branches(self):
        import jax.numpy as jnp
        from jax import lax

        def fn(x, idx):
            return lax.switch(jnp.clip(idx[0], 0, 2),
                              [lambda x: x + 1.0,
                               lambda x: x * 10.0,
                               lambda x: -x], x)

        x = np.random.default_rng(1).normal(size=(4,)).astype("float32")
        for k in (0, 1, 2):
            self._np_run(fn, [x, np.asarray([k], "int32")])

    def test_select_n_integer_cases(self):
        import jax.numpy as jnp
        from jax import lax

        def fn(x, i):
            return lax.select_n(jnp.clip(i[0], 0, 2),
                                x + 1.0, x * 2.0, -x)

        x = np.random.default_rng(3).normal(size=(3,)).astype("float32")
        for k in (0, 1, 2):
            self._np_run(fn, [x, np.asarray([k], "int32")])

    def test_select_n_single_case_degenerate(self):
        # one case: previously emitted NO nodes, leaving the output
        # name dangling (invalid graph)
        import jax.numpy as jnp
        from jax import lax

        def fn(x, i):
            return lax.select_n(jnp.clip(i[0], 0, 0), x * 2.0)

        x = np.random.default_rng(4).normal(size=(3,)).astype("float32")
        self._np_run(fn, [x, np.asarray([5], "int32")])

    def test_cond_multi_operand_multi_output(self):
        from jax import lax

        def fn(x, y, flag):
            return lax.cond(flag[0] > 0,
                            lambda x, y: (x + y, x @ y.T),
                            lambda x, y: (x - y, y @ x.T), x, y)

        rng = np.random.default_rng(2)
        x = rng.normal(size=(2, 3)).astype("float32")
        y = rng.normal(size=(2, 3)).astype("float32")
        for f in (1, 0):
            self._np_run(fn, [x, y, np.asarray([f], "int32")])


class TestWhileExport:
    """lax.while_loop -> condition-driven ONNX Loop (the last
    control-flow primitive; jax's check-before-first-iteration maps by
    evaluating the condition on the init carry in the outer graph)."""

    def _np_run(self, fn, args):
        return _export_and_run(fn, args)

    def test_data_dependent_trip_count(self):
        from jax import lax

        def fn(x):
            # double until >= 100: trip count depends on the input value
            return lax.while_loop(lambda c: c < 100.0,
                                  lambda c: c * 2.0, x[0])

        m = self._np_run(fn, [np.asarray([3.0], "float32")])
        assert any(n.op_type == "Loop" for n in m.graph.node)
        self._np_run(fn, [np.asarray([1.5], "float32")])

    def test_zero_iterations_returns_init(self):
        from jax import lax

        def fn(x):
            return lax.while_loop(lambda c: c < 0.0,
                                  lambda c: c - 1.0, x[0])

        self._np_run(fn, [np.asarray([7.0], "float32")])

    def test_tuple_carry_and_consts(self):
        import jax.numpy as jnp
        from jax import lax

        def fn(x, step):
            def body(c):
                i, acc = c
                return i + 1, acc + step[0] * i.astype(jnp.float32)

            i, acc = lax.while_loop(lambda c: c[0] < 5,
                                    body, (jnp.int32(0), x[0]))
            return acc

        self._np_run(fn, [np.asarray([0.5], "float32"),
                          np.asarray([2.0], "float32")])


class TestGatherOutOfBounds:
    """jax's FILL_OR_DROP/CLIP gather modes must survive export: ONNX
    Gather* wraps negatives python-style and rejects true OOB, so the
    converter emits an explicit clip + fill guard (advisor finding —
    previously the raw index was exported and runtime inputs outside
    [0, N) silently diverged or crashed)."""

    def _np_run(self, fn, args):
        return _export_and_run(fn, args, equal_nan=True)

    def test_take_fill_mode_oob_nan(self):
        import jax.numpy as jnp

        def fn(x, idx):
            return jnp.take(x, idx, axis=1)   # FILL_OR_DROP -> NaN

        x = np.random.default_rng(0).normal(size=(2, 5, 3)).astype(
            "float32")
        idx = np.asarray([[0, 7], [4, 12]], "int32")   # 7, 12 OOB
        self._np_run(fn, [x, idx])

    def test_take_int_fill_is_intmin(self):
        import jax.numpy as jnp

        def fn(x, idx):
            return jnp.take(x, idx, axis=0)

        x = np.arange(12, dtype="int32").reshape(4, 3)
        idx = np.asarray([1, 9], "int32")
        self._np_run(fn, [x, idx])

    def test_bool_take_oob_fills_true(self):
        import jax.numpy as jnp

        # jax fills OOB bool gathers with True (lax/slicing.py)
        def fn(x, idx):
            return jnp.take(x, idx, axis=0)

        x = np.asarray([False, False, False])
        idx = np.asarray([0, 7], "int32")
        self._np_run(fn, [x, idx])

    def test_take_clip_mode(self):
        import jax.numpy as jnp

        def fn(x, idx):
            return jnp.take(x, idx, axis=0, mode="clip")

        x = np.random.default_rng(1).normal(size=(4, 3)).astype("float32")
        idx = np.asarray([0, 11], "int32")
        self._np_run(fn, [x, idx])

    def test_take_along_axis_oob_nan(self):
        import jax.numpy as jnp

        def fn(x, idx):
            return jnp.take_along_axis(x, idx, axis=1)

        x = np.random.default_rng(2).normal(size=(3, 4)).astype("float32")
        idx = np.asarray([[0, 9], [1, 1], [3, 0]], "int32")
        self._np_run(fn, [x, idx])

    def test_in_bounds_exports_stay_lean(self):
        import jax.numpy as jnp

        # advanced indexing promises in-bounds: no Where/Clip guard
        def fn(x, ij):
            return x[ij[:, 0], ij[:, 1]]

        x = np.random.default_rng(3).normal(size=(5, 6)).astype("float32")
        ij = np.asarray([[0, 2], [4, 5]], "int32")
        m = to_onnx_model(fn, [x, ij])
        # no OOB guard on the PROMISE_IN_BOUNDS gather (jax's own
        # negative-index wrap legitimately emits Where via select_n, so
        # assert on the guard's Clip/Min-Max pair instead)
        assert not any(n.op_type == "Clip" for n in m.graph.node)
        assert not any(n.output[0].startswith("idxclip")
                       for n in m.graph.node)
