"""Exactly-once data resume (ISSUE 14): loader-owned RNG + state_dict
fast-forward, the PackingCollator carry-over buffer, and the
SentinelLoop / hapi checkpoint integration — all fast-lane and
in-process (state round-trips through a real committed checkpoint; the
kill -9 flavor rides tests/test_rank_loss_chaos.py in the slow lane).
"""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.io import DataLoader
from paddle_tpu.io.dataset import Dataset
from paddle_tpu.io.packing import PackingCollator, pack_documents
from paddle_tpu.testing import faults


class IdentDS(Dataset):
    def __init__(self, n):
        self.n = n

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        return np.asarray([i], np.int64)


def _ids(batches):
    return [int(x) for b in batches
            for x in np.asarray(b.numpy()).ravel()]


class TestLoaderOwnedSeeds:
    def test_identical_seeds_identical_streams_despite_ambient_rng(self):
        """The ISSUE 14 satellite pin: per-epoch seeds derive from the
        loader-owned (seed, epoch) root, never from global np.random
        inside __iter__ — so ambient RNG use cannot skew two
        identically-seeded loaders apart."""
        np.random.seed(0)
        a = DataLoader(IdentDS(24), batch_size=4, shuffle=True, seed=11)
        np.random.seed(31337)
        np.random.random(123)       # heavy ambient use between loaders
        b = DataLoader(IdentDS(24), batch_size=4, shuffle=True, seed=11)
        ea1 = _ids(list(a))
        np.random.random(7)         # ...and between epochs
        ea2 = _ids(list(a))
        eb1 = _ids(list(b))
        eb2 = _ids(list(b))
        assert ea1 == eb1 and ea2 == eb2
        assert ea1 != ea2                        # epochs still reshuffle
        assert sorted(ea1) == list(range(24))

    def test_worker_base_seed_derivation_is_ambient_free(self):
        a = DataLoader(IdentDS(8), batch_size=2, seed=5)
        b = DataLoader(IdentDS(8), batch_size=2, seed=5)
        a._epoch = b._epoch = 0
        np.random.seed(1)
        sa = a._epoch_base_seed()
        np.random.seed(2)
        sb = b._epoch_base_seed()
        assert sa == sb
        b._epoch = 1
        assert b._epoch_base_seed() != sa        # epochs get own streams

    def test_seedless_loader_root_follows_paddle_seed(self):
        # a seed= loader ignores ambient RNG entirely; a seedLESS one
        # keeps the historical contract: paddle.seed controls shuffle
        # order (the root comes from the framework generator, once)
        pt.seed(77)
        a = DataLoader(IdentDS(12), batch_size=3, shuffle=True)
        e1a = _ids(list(a))              # root drawn HERE, once
        pt.seed(77)
        b = DataLoader(IdentDS(12), batch_size=3, shuffle=True)
        e1b = _ids(list(b))
        assert e1a == e1b                # paddle.seed reproducible
        # root drawn once: later epochs ignore ambient reseeding
        pt.seed(0)
        np.random.seed(0)
        e2a = _ids(list(a))
        pt.seed(12345)
        np.random.seed(12345)
        e2b = _ids(list(b))
        assert e2a == e2b


class TestStateDictResume:
    def test_mid_epoch_resume_is_exactly_once(self):
        c = DataLoader(IdentDS(20), batch_size=3, shuffle=True, seed=7)
        _ = list(c)                              # epoch 0
        it = iter(c)
        pre = _ids([next(it), next(it)])         # 2 batches of epoch 1
        state = c.state_dict()
        assert state["epoch"] == 1 and state["cursor"] == 2

        fresh = DataLoader(IdentDS(20), batch_size=3, shuffle=True,
                           seed=7)
        fresh.set_state_dict(state)
        post = _ids(list(fresh))
        assert sorted(pre + post) == list(range(20))   # no dup, no skip
        # and the stream is bit-identical to an uninterrupted run
        ref = DataLoader(IdentDS(20), batch_size=3, shuffle=True, seed=7)
        _ = list(ref)
        assert pre + post == _ids(list(ref))
        # the epoch after the resumed epoch also matches
        assert _ids(list(fresh)) == _ids(list(ref))

    def test_resume_state_round_trips_through_checkpoint(self, tmp_path):
        from paddle_tpu.distributed.checkpoint import CheckpointManager
        c = DataLoader(IdentDS(12), batch_size=2, shuffle=True, seed=3)
        it = iter(c)
        pre = _ids([next(it), next(it), next(it)])
        mgr = CheckpointManager(str(tmp_path / "root"))
        mgr.save(1, {"data": dict(c.state_dict()), "step": 1},
                 blocking=True)

        tgt = {"data": dict(DataLoader(IdentDS(12), batch_size=2,
                                       shuffle=True,
                                       seed=3).state_dict()),
               "step": 0}
        mgr2 = CheckpointManager(str(tmp_path / "root"))
        assert mgr2.restore_latest(tgt) == 1
        fresh = DataLoader(IdentDS(12), batch_size=2, shuffle=True,
                           seed=3)
        fresh.set_state_dict(tgt["data"])
        post = _ids(list(fresh))
        assert sorted(pre + post) == list(range(12))

    def test_fast_forward_metric_and_no_dataset_access(self):
        from paddle_tpu import monitor

        class CountingDS(IdentDS):
            def __init__(self, n):
                super().__init__(n)
                self.fetches = []

            def __getitem__(self, i):
                self.fetches.append(i)
                return super().__getitem__(i)

        ds = CountingDS(20)
        c = DataLoader(ds, batch_size=4, seed=1)
        it = iter(c)
        next(it), next(it)
        state = c.state_dict()

        ds2 = CountingDS(20)
        fresh = DataLoader(ds2, batch_size=4, seed=1)
        fresh.set_state_dict(state)
        monitor.reset()
        pt.set_flags({"FLAGS_enable_monitor": True})
        try:
            post = _ids(list(fresh))
        finally:
            pt.set_flags({"FLAGS_enable_monitor": False})
        # the fast-forward consumed INDICES, not samples
        assert sorted(ds2.fetches) == list(range(8, 20))
        assert sorted(post) == list(range(8, 20))
        snap = monitor.snapshot()
        assert snap["counters"][
            "data.resume.fast_forward_batches"] == 2
        monitor.reset()

    def test_dataloader_batch_fault_point(self):
        c = DataLoader(IdentDS(8), batch_size=2, seed=1)
        with faults.injected("dataloader.batch", action="raise", nth=2):
            it = iter(c)
            next(it)
            with pytest.raises(faults.FaultInjected):
                next(it)

    def test_state_dict_is_json_safe(self):
        import json
        c = DataLoader(IdentDS(8), batch_size=2, seed=1,
                       collate_fn=PackingCollator(8, max_rows=1,
                                                  carry_over=True))
        json.dumps(c.state_dict())   # must not raise


class TestPackingCarryOver:
    def _docs(self, lens, base=0):
        out = []
        off = base
        for ln in lens:
            out.append(np.arange(off, off + ln, dtype=np.int32))
            off += ln
        return out

    def test_overflow_carries_in_arrival_order(self):
        col = PackingCollator(8, max_rows=1, carry_over=True)
        b1 = col(self._docs([5, 4, 3]))          # row: [5,3]; carry [4]
        assert b1["ids"].shape[0] == 1
        assert col.state_dict()["carry"] != []
        b2 = col(self._docs([2], base=100))      # carry leads the pack
        ids2 = b2["ids"][b2["segment_ids"] >= 0]
        assert ids2[0] == 5                      # carried chunk first

    def test_every_token_packs_exactly_once(self):
        rng = np.random.default_rng(0)
        lens = [int(x) for x in rng.integers(1, 10, 40)]
        docs = self._docs(lens)
        all_tokens = np.concatenate(docs)
        col = PackingCollator(16, max_rows=2, carry_over=True)
        got = []
        for i in range(0, len(docs), 8):
            packed = col(docs[i:i + 8])
            got.append(packed["ids"][packed["segment_ids"] >= 0])
        while True:
            tail = col.flush()
            if tail is None:
                break
            got.append(tail["ids"][tail["segment_ids"] >= 0])
        got = np.concatenate(got)
        assert sorted(got.tolist()) == sorted(all_tokens.tolist())
        assert len(got) == len(all_tokens)       # exactly once

    def test_state_round_trip_resumes_carry_bit_exact(self):
        docs1 = self._docs([5, 4, 4])
        docs2 = self._docs([3, 6], base=50)
        a = PackingCollator(8, max_rows=1, carry_over=True)
        a(docs1)
        state = a.state_dict()
        import json
        state = json.loads(json.dumps(state))    # checkpoint transport
        b = PackingCollator(8, max_rows=1, carry_over=True)
        b.set_state_dict(state)
        out_a = a(docs2)
        out_b = b(docs2)
        np.testing.assert_array_equal(out_a["ids"], out_b["ids"])
        np.testing.assert_array_equal(out_a["segment_ids"],
                                      out_b["segment_ids"])
        assert a.state_dict() == b.state_dict()

    def test_stateless_collator_still_raises_on_overflow(self):
        from paddle_tpu.core import enforce as E
        col = PackingCollator(8, max_rows=1)
        with pytest.raises(E.ResourceExhaustedError):
            col(self._docs([5, 4, 4]))

    def test_carry_requires_max_rows(self):
        from paddle_tpu.core import enforce as E
        with pytest.raises(E.InvalidArgumentError):
            PackingCollator(8, carry_over=True)

    def test_collect_overflow_function_contract(self):
        packed, overflow = pack_documents(
            self._docs([5, 4, 4]), 8, max_rows=1, collect_overflow=True)
        assert packed["ids"].shape[0] == 1
        # the 4-token chunk overflowed AND the later 4-token chunk
        # (which would fit the open row) stays behind it — arrival
        # order is preserved across batches
        assert [len(c) for c in overflow] == [4, 4]
        assert overflow[0][0] == 5


class TestSentinelLoopDataResume:
    def _toy(self):
        import jax.numpy as jnp

        def step_fn(params, opt, batch, cap):
            ids = jnp.asarray(np.asarray(batch.numpy()), jnp.float32)
            loss = jnp.mean(ids)
            return (params + 1, opt,
                    loss, {"finite": jnp.asarray(True),
                           "grad_norm": jnp.asarray(1.0)})
        return step_fn

    def test_loader_state_rides_checkpoints_and_restores(self, tmp_path):
        import jax.numpy as jnp

        from paddle_tpu.distributed.checkpoint import CheckpointManager
        from paddle_tpu.training.sentinel import SentinelLoop

        loader = DataLoader(IdentDS(24), batch_size=2, shuffle=True,
                            seed=9)
        mgr = CheckpointManager(str(tmp_path / "root"), keep_last_n=3,
                                save_interval_steps=1)
        loop = SentinelLoop(self._toy(), jnp.zeros(()), jnp.zeros(()),
                            dataloader=loader, manager=mgr)
        loop.run(5)                      # 5 batches consumed, each saved
        mgr.wait()
        assert loop.step == 5

        # "restarted worker": fresh loader + loop, one-call resume
        loader2 = DataLoader(IdentDS(24), batch_size=2, shuffle=True,
                             seed=9)
        mgr2 = CheckpointManager(str(tmp_path / "root"), keep_last_n=3,
                                 save_interval_steps=1)
        loop2 = SentinelLoop(self._toy(), jnp.zeros(()), jnp.zeros(()),
                             dataloader=loader2, manager=mgr2)
        assert loop2.restore_latest() == 5
        assert loader2._resume_skip == 5

        seen = []
        orig = loader2.collate_fn

        def spy(batch):
            out = orig(batch)
            seen.extend(int(x) for x in
                        np.asarray(out.numpy()).ravel())
            return out

        loader2.collate_fn = spy
        loop2.run(12)                    # finish the epoch
        mgr2.wait()
        # exactly-once: the resumed stream built only the unseen tail,
        # and together with a reference run covers the epoch once
        ref = DataLoader(IdentDS(24), batch_size=2, shuffle=True, seed=9)
        full = _ids(list(ref))
        assert seen == full[10:24]

    def test_emergency_save_provider_pins_offer_time_cursor(self,
                                                            tmp_path):
        # review fix: the save provider is materialized LATE by a
        # SIGTERM emergency save — mid-next-batch, when the live loader
        # cursor is one ahead of the offered step. The provider must
        # carry the OFFER-time cursor or the resumed loader skips a
        # batch (silent sample loss on exactly the preemption path).
        import jax.numpy as jnp

        from paddle_tpu.training.sentinel import SentinelLoop

        loader = DataLoader(IdentDS(24), batch_size=2, shuffle=True,
                            seed=4)
        loop = SentinelLoop(self._toy(), jnp.zeros(()), jnp.zeros(()),
                            dataloader=loader)
        loop.run(3)                          # step == cursor == 3
        provider = loop._state_provider()    # offered at step 3
        next(iter(loader))                   # SIGTERM lands mid-batch 4
        state = provider()                   # emergency materialization
        assert state["step"] == 3
        assert state["data"]["cursor"] == 3, state["data"]

    def test_legacy_make_stream_signature_still_works(self):
        import jax.numpy as jnp

        from paddle_tpu.training.sentinel import SentinelLoop

        def make_stream():
            return (pt.to_tensor(np.asarray([[i]], np.float32))
                    for i in range(6))

        loop = SentinelLoop(self._toy(), jnp.zeros(()), jnp.zeros(()),
                            make_stream)
        out = loop.run(4)
        assert out["steps"] == 4 and out["applied"] == 4


class TestHapiCheckpointLoaderRegistration:
    def test_fit_registers_and_checkpoint_carries_data_state(
            self, tmp_path):
        import paddle_tpu.nn as nn
        from paddle_tpu.hapi.callbacks import FaultTolerantCheckpoint
        from paddle_tpu.hapi.model import Model
        from paddle_tpu.io.dataset import TensorDataset

        x = np.random.default_rng(0).normal(size=(16, 4)).astype(
            np.float32)
        y = (x @ np.ones((4, 1), np.float32)).astype(np.float32)
        ds = TensorDataset([pt.to_tensor(x), pt.to_tensor(y)])
        net = nn.Linear(4, 1)
        model = Model(net)
        opt = pt.optimizer.SGD(learning_rate=0.01,
                               parameters=net.parameters())
        model.prepare(opt, pt.nn.MSELoss())
        cb = FaultTolerantCheckpoint(str(tmp_path / "ckpt"),
                                     save_interval_steps=1,
                                     async_save=False)
        model.fit(ds, batch_size=4, epochs=1, verbose=0, callbacks=[cb])
        assert cb._loader is not None      # fit registered its loader

        # a later fit restores and re-seats the loader from `data`
        cb2 = FaultTolerantCheckpoint(str(tmp_path / "ckpt"),
                                      save_interval_steps=1,
                                      async_save=False)
        model2 = Model(nn.Linear(4, 1))
        opt2 = pt.optimizer.SGD(learning_rate=0.01,
                                parameters=model2.network.parameters())
        model2.prepare(opt2, pt.nn.MSELoss())
        model2.fit(ds, batch_size=4, epochs=1, verbose=0,
                   callbacks=[cb2])
        assert cb2.restored_step == 4
