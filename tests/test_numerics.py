"""Numerics plane (ISSUE 11): in-graph per-layer tensor statistics in
the guarded train steps (``training/guards.py`` ``grad_numerics``),
the host-side plane (``monitor/numerics.py``: timeseries, worst-layer
attribution, quantization SQNR audit, KV-page absmax), the sentinel's
observe-only worst-layer attribution, the engine's per-chunk KV
sampling seam, the ``/numerics`` route + flight-record block, the
off-flag byte-identical pins, and the int8 dequant cast-ordering
bugfix."""
import importlib
import json
import math
import urllib.request
import weakref

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import monitor
from paddle_tpu.models import llama as L
from paddle_tpu.models import moe as M
from paddle_tpu.monitor import numerics as NM
from paddle_tpu.testing import faults
from paddle_tpu.training import guards as G
from paddle_tpu.training import sentinel as S

FA = importlib.import_module("paddle_tpu.kernels.flash_attention")

B, T, V = 2, 16, 64
INF_CAP = jnp.asarray(np.inf, jnp.float32)


@pytest.fixture(autouse=True)
def _clean():
    yield
    faults.clear()
    pt.set_flags({"FLAGS_enable_sentinel": False,
                  "FLAGS_enable_numerics": False,
                  "FLAGS_enable_monitor": False,
                  "FLAGS_enable_monitor_server": False})
    NM.set_kv_sample_rate(None)
    from paddle_tpu.monitor import exectime
    exectime.set_sample_rate(None)
    from paddle_tpu.monitor import server as _srv
    _srv.stop_server()
    monitor.reset()


def _batch(i, vocab=V):
    r = np.random.RandomState(1000 + i)
    ids = r.randint(0, vocab, size=(B, T + 1)).astype(np.int32)
    return ids[:, :-1], ids[:, 1:]


def _llama():
    cfg = L.llama_tiny(vocab_size=V)
    params = L.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params, L.adamw_init(params)


def _np_stats(g, reduce_axes):
    """Pure-numpy reference of guards.tensor_stats."""
    xf = np.asarray(g, np.float32)
    fi = np.finfo(np.dtype(np.asarray(g).dtype)) \
        if np.issubdtype(np.asarray(g).dtype, np.floating) else None
    over_t = fi.max / 2.0 if fi is not None else np.inf
    under_t = fi.tiny if fi is not None else 0.0
    ax = reduce_axes
    n = np.prod([xf.shape[a] for a in ax]) if ax else 1.0
    if ax is None:
        n, ax = xf.size, tuple(range(xf.ndim))
    absx = np.abs(xf)
    return {
        "absmax": absx.max(axis=ax),
        "rms": np.sqrt((xf * xf).sum(axis=ax) / n),
        "mean": xf.sum(axis=ax) / n,
        "zero_frac": (xf == 0).sum(axis=ax) / n,
        "overflow_frac": (absx > over_t).sum(axis=ax) / n,
        "underflow_frac": ((absx < under_t) & (xf != 0)).sum(axis=ax) / n,
        "gnorm_sq": (xf * xf).sum(axis=ax),
    }


# ---------------------------------------------------------------------------
# in-graph stats: parity, agreement, dtype boundaries
# ---------------------------------------------------------------------------

class TestInGraphStats:
    def test_stats_parity_vs_numpy_reference(self):
        """The guarded+numerics step's stats block equals a pure-numpy
        recomputation from the same gradients."""
        cfg, params, opt = _llama()
        step = L.make_train_step(cfg, guard=True, numerics=True,
                                 donate=False)
        batch = _batch(0)
        _, _, _, h = step(params, opt, batch, INF_CAP)
        _, grads = jax.value_and_grad(
            lambda p: L.loss_fn(p, batch, cfg))(params)
        nm = h["numerics"]
        for name, g in grads["layers"].items():
            want = _np_stats(np.asarray(g),
                             tuple(range(1, np.asarray(g).ndim)))
            for stat in G.NUMERIC_STATS:
                np.testing.assert_allclose(
                    np.asarray(nm["layers"][name][stat]), want[stat],
                    rtol=2e-4, atol=1e-7, err_msg=f"{name}.{stat}")
        for name in ("embed", "ln_f", "lm_head"):
            want = _np_stats(np.asarray(grads[name]), None)
            for stat in G.NUMERIC_STATS:
                np.testing.assert_allclose(
                    np.asarray(nm["tensors"][name][stat]), want[stat],
                    rtol=2e-4, atol=1e-7, err_msg=f"{name}.{stat}")

    @pytest.mark.slow  # tier-1 budget (ISSUE 19 rebalance): arm-invariance re-check of the numpy
    # parity pin above; the flash-arm parity suite covers arms
    def test_stats_parity_holds_on_both_attention_arms(self):
        """Kernel-interpret and jnp-fallback attention produce the same
        numerics block (within float tolerance) for the same packed
        batch — the stats are attention-impl-independent."""
        from paddle_tpu.io import packing as PK
        from paddle_tpu.nn.functional import attention as att
        cfg, params, opt = _llama()
        step = L.make_train_step(cfg, guard=True, numerics=True,
                                 donate=False)
        rng = np.random.default_rng(5)
        docs = [rng.integers(0, V, (ln,)).astype(np.int32)
                for ln in (40, 24)]
        pb = PK.packed_train_batch(PK.pack_documents(docs, 64))
        prev = att._SEGMENT_IMPL
        blocks = []
        try:
            for impl in (None,                    # jnp fallback
                         lambda *a, **kw: FA.flash_attention_segments(
                             *a, **kw, interpret=True)):
                att.register_segment_impl(impl)
                _, _, _, h = step(params, opt, pb, INF_CAP)
                blocks.append(jax.tree.map(np.asarray, h["numerics"]))
        finally:
            att.register_segment_impl(prev)
        for a, b in zip(jax.tree.leaves(blocks[0]),
                        jax.tree.leaves(blocks[1])):
            np.testing.assert_allclose(a, b, rtol=5e-3, atol=1e-6)

    def test_per_layer_sums_agree_with_global_norm(self):
        """sqrt(sum of every gnorm_sq entry) == the guarded step's
        grad_norm — the breakdown tiles the global norm exactly."""
        cfg, params, opt = _llama()
        step = L.make_train_step(cfg, guard=True, numerics=True,
                                 donate=False)
        _, _, _, h = step(params, opt, _batch(1), INF_CAP)
        nm = h["numerics"]
        tot = sum(float(np.sum(np.asarray(s["gnorm_sq"])))
                  for s in nm["layers"].values())
        tot += sum(float(np.asarray(s["gnorm_sq"]))
                   for s in nm["tensors"].values())
        np.testing.assert_allclose(math.sqrt(tot),
                                   float(h["grad_norm"]), rtol=1e-5)

    @pytest.mark.slow  # tier-1 budget (ISSUE 19 rebalance): same stats contract as the llama
    # numpy-parity pin above, re-run on the MoE family
    def test_moe_family_same_contract(self):
        cfg = M.moe_tiny(vocab_size=V)
        params = M.init_params(cfg, jax.random.PRNGKey(0))
        opt = M.adamw_init(params)
        step = M.make_train_step(cfg, guard=True, numerics=True,
                                 donate=False)
        _, _, _, h = step(params, opt, _batch(0), INF_CAP)
        nm = h["numerics"]
        assert "router" in nm["layers"] and "e_gate" in nm["layers"]
        tot = sum(float(np.sum(np.asarray(s["gnorm_sq"])))
                  for s in nm["layers"].values())
        tot += sum(float(np.asarray(s["gnorm_sq"]))
                   for s in nm["tensors"].values())
        np.testing.assert_allclose(math.sqrt(tot),
                                   float(h["grad_norm"]), rtol=1e-5)

    def test_overflow_underflow_fraction_at_dtype_boundaries(self):
        """Crafted fp16 values straddling the dtype range: 3/8 within
        2x of finfo.max (overflow band: |x| > 32752), 1/8 nonzero
        below finfo.tiny (underflow band: 0 < |x| < 6.1e-5), 2/8
        exact zeros. fp16 keeps the bands far from f32's own
        subnormal range, so the f32 accumulation of the stats sees
        them exactly (bf16 subnormals can flush on XLA:CPU)."""
        fi = jnp.finfo(jnp.float16)
        arr = jnp.asarray(
            [float(fi.max) * 0.9, 4e4, -5e4,       # over max/2
             1.0, -0.5,                            # normal
             1e-5,                                 # below tiny, nonzero
             0.0, 0.0], jnp.float16)
        st = jax.tree.map(float, G.tensor_stats(arr))
        assert st["overflow_frac"] == pytest.approx(3 / 8)
        assert st["underflow_frac"] == pytest.approx(1 / 8)
        assert st["zero_frac"] == pytest.approx(2 / 8)
        assert st["absmax"] == pytest.approx(float(
            jnp.asarray(float(fi.max) * 0.9, jnp.float16)), rel=1e-6)

    def test_exactly_at_thresholds_not_counted(self):
        """The bands are strict: |x| == max/2 is not overflow, a
        normal at exactly finfo.tiny is not underflow."""
        fi = jnp.finfo(jnp.float32)
        arr = jnp.asarray([float(fi.max) / 2.0, float(fi.tiny)],
                          jnp.float32)
        st = jax.tree.map(float, G.tensor_stats(arr))
        assert st["overflow_frac"] == 0.0
        assert st["underflow_frac"] == 0.0

    def test_integer_tensor_has_no_float_range(self):
        st = jax.tree.map(float, G.tensor_stats(
            jnp.asarray([0, 5, -3], jnp.int32)))
        assert st["overflow_frac"] == 0.0
        assert st["underflow_frac"] == 0.0
        assert st["zero_frac"] == pytest.approx(1 / 3)

    def test_per_layer_rows_keep_axis_zero(self):
        x = jnp.asarray(np.arange(12, dtype=np.float32).reshape(3, 4))
        st = G.tensor_stats(x, reduce_axes=(1,))
        assert np.asarray(st["absmax"]).shape == (3,)
        np.testing.assert_allclose(np.asarray(st["mean"]),
                                   np.arange(12).reshape(3, 4)
                                   .mean(axis=1))


# ---------------------------------------------------------------------------
# off-flag pins: byte-identical program, zero registrations
# ---------------------------------------------------------------------------

class TestOffFlagPins:
    def test_numerics_off_guarded_health_is_two_keys(self):
        """FLAGS_enable_numerics unset -> the guarded step is exactly
        the pre-numerics 4-in/4-out program: health holds only
        finite + grad_norm."""
        cfg, params, opt = _llama()
        pt.set_flags({"FLAGS_enable_sentinel": True})
        step = L.make_train_step(cfg, donate=False)
        out = step(params, opt, _batch(0), INF_CAP)
        assert len(out) == 4 and sorted(out[3]) == ["finite",
                                                    "grad_norm"]

    def test_guard_off_stays_3_in_3_out_even_with_numerics_flag(self):
        """Numerics is a guarded-step feature: with the sentinel off,
        the numerics flag must not change the step's arity."""
        cfg, params, opt = _llama()
        pt.set_flags({"FLAGS_enable_numerics": True})
        step = L.make_train_step(cfg, donate=False)
        out = step(params, opt, _batch(0))
        assert len(out) == 3
        with pytest.raises(TypeError):
            step(params, opt, _batch(0), INF_CAP)
        # explicit numerics=True without guard: same pin
        step2 = L.make_train_step(cfg, donate=False, guard=False,
                                  numerics=True)
        assert len(step2(params, opt, _batch(0))) == 3

    def test_flag_resolves_numerics_on_guarded_step(self):
        cfg, params, opt = _llama()
        pt.set_flags({"FLAGS_enable_sentinel": True,
                      "FLAGS_enable_numerics": True})
        step = L.make_train_step(cfg, donate=False)
        out = step(params, opt, _batch(0), INF_CAP)
        assert "numerics" in out[3]

    def test_zero_registrations_without_numerics(self):
        """Monitor on, numerics flag off: a guarded step + an engine
        run with KV sampling disabled register nothing under
        numerics.*."""
        pt.set_flags({"FLAGS_enable_monitor": True})
        monitor.reset()
        NM.set_kv_sample_rate(0)
        cfg, params, opt = _llama()
        step = L.make_train_step(cfg, guard=True, donate=False)
        step(params, opt, _batch(0), INF_CAP)
        snap = monitor.snapshot()
        names = (list(snap.get("counters", {}))
                 + list(snap.get("gauges", {}))
                 + list(snap.get("histograms", {})))
        assert not [n for n in names if n.startswith("numerics.")]

    def test_record_paths_noop_when_monitor_off(self):
        assert not monitor.enabled()
        cfg, params, opt = _llama()
        step = L.make_train_step(cfg, guard=True, numerics=True,
                                 donate=False)
        _, _, _, h = step(params, opt, _batch(0), INF_CAP)
        assert NM.record_step_stats(h["numerics"]) is None
        NM.record_kv_absmax(np.ones((2, 4), np.float32))
        # the audit still RETURNS its report (explicit analysis), but
        # persists nothing off-flag
        rep = NM.audit_quantized_tree(params,
                                      L.quantize_weights(params))
        assert rep["tensors"] and NM.last_audit() is None
        snap = NM.numerics_snapshot()
        assert snap["total_steps"] == 0
        assert snap["kv"]["samples"] == 0
        assert snap["quant"] is None
        assert monitor.snapshot() == {}

    def test_guarded_update_math_unchanged_by_numerics(self):
        """The numerics block is pure observation: params/opt/loss of
        the numerics step equal the plain guarded step's exactly."""
        cfg, params, opt = _llama()
        a = L.make_train_step(cfg, guard=True, donate=False)
        b = L.make_train_step(cfg, guard=True, numerics=True,
                              donate=False)
        pa, oa, la, _ = a(params, opt, _batch(0), INF_CAP)
        pb, ob, lb, _ = b(params, opt, _batch(0), INF_CAP)
        assert float(la) == float(lb)
        for x, y in zip(jax.tree.leaves(pa), jax.tree.leaves(pb)):
            assert np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(jax.tree.leaves(oa), jax.tree.leaves(ob)):
            assert np.array_equal(np.asarray(x), np.asarray(y))


# ---------------------------------------------------------------------------
# GSPMD/donation invariance
# ---------------------------------------------------------------------------

class TestMeshInvariance:
    def test_mesh_guarded_numerics_step_runs_with_donation(self):
        """The numerics aux outputs are replicated scalars/[L] rows —
        the sharding prefix must compose with the llama mesh path's
        explicit out_shardings and donation."""
        from jax.sharding import Mesh
        cfg, params, opt = _llama()
        devs = np.array(jax.devices()[:4]).reshape(1, 2, 2)
        mesh = Mesh(devs, ("dp", "fsdp", "tp"))
        step = L.make_train_step(cfg, mesh=mesh, guard=True,
                                 numerics=True)
        sharded = L.shard_params(params, cfg, mesh)
        oshard = jax.tree.map(lambda p: p, L.adamw_init(sharded))
        with mesh:
            p2, o2, loss, h = step(sharded, oshard, _batch(0), INF_CAP)
        assert np.isfinite(float(loss))
        nm = h["numerics"]
        assert np.asarray(nm["layers"]["wq"]["gnorm_sq"]).shape == \
            (cfg.num_hidden_layers,)
        tot = sum(float(np.sum(np.asarray(s["gnorm_sq"])))
                  for s in nm["layers"].values())
        tot += sum(float(np.asarray(s["gnorm_sq"]))
                   for s in nm["tensors"].values())
        np.testing.assert_allclose(math.sqrt(tot),
                                   float(h["grad_norm"]), rtol=1e-5)


# ---------------------------------------------------------------------------
# host plane: timeseries, movers, worst layer
# ---------------------------------------------------------------------------

def _fake_stats(layer_gnorms, leaf="wq"):
    """Minimal stats tree: one stacked leaf with given per-layer
    squared norms (other stats filled consistently)."""
    g = np.asarray(layer_gnorms, np.float32)
    z = np.zeros_like(g)
    return {"layers": {leaf: {
        "absmax": np.sqrt(g), "rms": np.sqrt(g), "mean": z,
        "zero_frac": z, "overflow_frac": z, "underflow_frac": z,
        "gnorm_sq": g}}, "tensors": {}}


class TestNumericsPlane:
    def setup_method(self, _):
        pt.set_flags({"FLAGS_enable_monitor": True})
        monitor.reset()

    def test_worst_layer_names_the_spiking_layer(self):
        wl = NM.record_step_stats(_fake_stats([1.0, 100.0, 4.0]))
        assert wl["name"] == "wq" or wl["name"] == "layers.wq[1]"
        assert wl == NM.worst_layer()
        assert wl["name"] == "layers.wq[1]"
        assert wl["grad_norm"] == pytest.approx(10.0)
        assert wl["finite"]

    def test_nonfinite_layer_outranks_any_finite_norm(self):
        wl = NM.record_step_stats(
            _fake_stats([1e30, float("nan"), 2.0]))
        assert wl["name"] == "layers.wq[1]"
        assert not wl["finite"]
        g = monitor.snapshot()["gauges"]
        assert g["numerics.worst.gnorm"] == -1.0

    def test_ring_is_bounded_with_lifetime_evidence(self):
        cap = NM.numerics_snapshot()["capacity"]
        for i in range(cap + 5):
            NM.record_step_stats(_fake_stats([1.0, 2.0]), step=i)
        snap = NM.numerics_snapshot()
        assert len(snap["rows"]) == cap
        assert snap["total_steps"] == cap + 5
        # n selects the LAST n rows; n=0 means none (the bench
        # condensation), not the whole ring
        assert len(NM.numerics_snapshot(n=3)["rows"]) == 3
        assert NM.numerics_snapshot(n=0)["rows"] == []

    def test_top_movers_rank_by_either_direction(self):
        """A 10x collapse must rank above a 2x growth (max(r, 1/r))."""
        for _ in range(20):     # settle the EMAs
            NM.record_step_stats(_fake_stats([4.0, 4.0]))
        NM.record_step_stats(_fake_stats([4.0 * 0.01, 4.0 * 4.0]))
        movers = NM.top_movers()
        assert movers[0]["name"] == "layers.wq[0]"
        assert movers[0]["ratio"] < 1.0

    def test_gauges_and_counters_emitted(self):
        NM.record_step_stats(_fake_stats([1.0, 9.0]))
        snap = monitor.snapshot()
        assert snap["counters"]["numerics.steps"] == 1
        assert snap["gauges"]["numerics.tensors.tracked"] == 2
        assert snap["gauges"]["numerics.worst.gnorm"] == \
            pytest.approx(3.0)


# ---------------------------------------------------------------------------
# quantization audit: SQNR math + cast-ordering fix
# ---------------------------------------------------------------------------

class TestQuantAudit:
    def test_sqnr_math_vs_numpy(self):
        rng = np.random.default_rng(0)
        ref = rng.normal(size=(64, 32)).astype(np.float32)
        noisy = ref + rng.normal(size=ref.shape).astype(np.float32) * 1e-3
        r64, n64 = ref.astype(np.float64), noisy.astype(np.float64)
        want = 10 * np.log10((r64 ** 2).sum()
                             / ((r64 - n64) ** 2).sum())
        assert NM.sqnr_db(ref, noisy) == pytest.approx(float(want),
                                                       rel=1e-9)
        assert NM.sqnr_db(ref, ref) == float("inf")
        assert NM.sqnr_db(np.zeros(4), np.ones(4)) == float("-inf")

    def test_audit_int8_tree_finite_nonzero_sqnr(self):
        pt.set_flags({"FLAGS_enable_monitor": True})
        monitor.reset()
        cfg, params, _ = _llama()
        qp = L.quantize_weights(params)
        report = NM.audit_quantized_tree(params, qp,
                                         serving_dtype=jnp.bfloat16)
        assert report["tensors"], "audit found no quantized leaves"
        for path, ent in report["tensors"].items():
            assert math.isfinite(ent["sqnr_db"]) and \
                ent["sqnr_db"] > 20.0, (path, ent)
            assert ent["max_abs_err"] > 0
            assert math.isfinite(ent["sqnr_served_db"]), (path, ent)
        assert report["min_sqnr_db"] is not None
        assert math.isfinite(report["min_sqnr_db"])
        assert NM.last_audit() is report

    def test_audit_moe_tree_covers_expert_grids(self):
        cfg = M.moe_tiny(vocab_size=V)
        params = M.init_params(cfg, jax.random.PRNGKey(0))
        qp = M.quantize_weights(params)
        report = NM.audit_quantized_tree(params, qp)
        assert "layers.e_gate" in report["tensors"]
        assert report["tensors"]["layers.e_gate"]["sqnr_db"] > 20.0

    def test_wrong_axis_scale_collapses_sqnr(self):
        """The auditor is the wrong-axis tripwire: pairing a correctly
        quantized int8 grid with a scale reduced over the WRONG axis
        collapses SQNR from >30 dB to nonsense."""
        rng = np.random.default_rng(1)
        w = jnp.asarray(rng.normal(size=(256, 128)).astype(np.float32)
                        * 0.05)
        good = L.quant_int8(w, in_axis=0)       # s [128], per out-chan
        right = NM.sqnr_db(np.asarray(w),
                           NM.dequant_ref(good["q"], good["s"]))
        wrong_s = np.abs(np.asarray(w)).max(axis=1) / 127.0  # [256]
        wrong = NM.sqnr_db(np.asarray(w),
                           NM.dequant_ref(good["q"], wrong_s))
        assert right > 30.0
        assert wrong < right - 15.0     # >15 dB collapse trips review

    def test_dequant_ref_rejects_unmatchable_scale(self):
        with pytest.raises(ValueError):
            NM.dequant_ref(np.zeros((4, 6), np.int8),
                           np.zeros((5,), np.float32))

    def test_mm_dequant_cast_ordering_fixed(self):
        """The serving seams dequantize in f32 with ONE cast to the
        activation dtype: the seam's bf16 output must bit-match the
        f32-multiply reference, and its SQNR must be >= the old
        double-rounded ordering's (the fixed regression)."""
        rng = np.random.default_rng(2)
        w = jnp.asarray(rng.normal(size=(64, 48)).astype(np.float32)
                        * 0.1)
        q = L.quant_int8(w, in_axis=0)
        x = jnp.eye(64, dtype=jnp.bfloat16)   # identity: _mm == deq(w)
        seam = np.asarray(L._mm(x, q), np.float32)
        want = np.asarray(
            (q["q"].astype(jnp.float32) * q["s"][None, :])
            .astype(jnp.bfloat16) @ jnp.eye(48, dtype=jnp.bfloat16),
            np.float32)
        np.testing.assert_array_equal(seam, want)
        old = np.asarray(
            (q["q"].astype(jnp.bfloat16)
             * q["s"][None, :].astype(jnp.bfloat16)), np.float32)
        ref = np.asarray(w)
        assert NM.sqnr_db(ref, seam) >= NM.sqnr_db(ref, old)

    def test_weight_only_linear_bf16_cast_ordering_fixed(self):
        """nn.quant.weight_only_linear shares the fixed ordering: with
        bf16 activations the dequantized weight it matmuls against is
        the f32 product cast ONCE (int8 and int4 paths)."""
        from paddle_tpu.nn.quant import (weight_only_linear,
                                         weight_quantize)
        from paddle_tpu.core.tensor import to_tensor
        rng = np.random.default_rng(3)
        w = rng.normal(size=(64, 48)).astype(np.float32) * 0.1
        x16 = np.eye(64, dtype=np.float32)
        for algo, dt in (("weight_only_int8", "int8"),
                         ("weight_only_int4", "int4")):
            q, s = weight_quantize(to_tensor(w), algo=algo)
            out = weight_only_linear(
                to_tensor(jnp.asarray(x16, jnp.bfloat16)), q,
                weight_scale=s, weight_dtype=dt)
            got = np.asarray(out.numpy(), np.float32)
            # reference: unpack+dequant in f32, one cast to bf16
            from paddle_tpu.nn.quant import weight_dequantize
            wd = np.asarray(weight_dequantize(
                q, s, algo=algo, out_dtype="float32").numpy())
            want = np.asarray(
                jnp.asarray(x16, jnp.bfloat16)
                @ jnp.asarray(wd, jnp.float32).astype(jnp.bfloat16),
                np.float32)
            np.testing.assert_allclose(got, want, rtol=1e-2,
                                       atol=1e-3, err_msg=algo)
            # int8 ~43 dB, int4 ~19 dB on this matrix — both far from
            # the wrong-axis collapse regime
            assert NM.sqnr_db(w, np.asarray(
                jnp.asarray(wd, jnp.bfloat16), np.float32)) > 15.0

    @pytest.mark.slow  # tier-1 budget (ISSUE 19 rebalance): weight-only decode parity duplicated by
    # the test_models TestWeightOnlyDecode generate/beam pins
    def test_int8_decode_parity_bf16_quantized_tree(self):
        """The fixed dequant ordering flows through generate: the int8
        tree still decodes (finite logits, valid tokens) and the f32
        tree's greedy tokens are unchanged by quantization-at-bf16
        beyond the documented tolerance path (token validity only —
        exact parity vs bf16 lives in test_paged.py's engine matrix)."""
        cfg, params, _ = _llama()
        qp = L.quantize_weights(params)
        ids = jnp.asarray(_batch(0)[0][:, :8])
        toks = np.asarray(L.generate(qp, ids, cfg, max_new_tokens=4))
        assert toks.shape == (B, 4)
        assert (toks >= 0).all() and (toks < V).all()


# ---------------------------------------------------------------------------
# KV-page absmax sampling (engine seam)
# ---------------------------------------------------------------------------

def _run_engine(n_requests=3, max_new=6):
    from paddle_tpu.inference import Request, ServingEngine
    rng = np.random.default_rng(0)
    cfg = L.llama_tiny(num_hidden_layers=2)
    params = L.init_params(cfg, jax.random.PRNGKey(0))
    eng = ServingEngine(L, params, cfg, num_slots=2, max_len=32,
                        page_size=8, decode_chunk=2)
    outs = eng.run([Request(
        rid=i, prompt=rng.integers(0, cfg.vocab_size, (6,))
        .astype(np.int32), max_new_tokens=max_new)
        for i in range(n_requests)])
    assert len(outs) == n_requests
    return eng


class TestKVPageSampling:
    def test_sampling_zero_extra_syncs(self, monkeypatch):
        """KV sampling at rate 1 adds ZERO block_until_ready calls:
        the per-chunk token download is the only synchronization (the
        PR 9 pattern, pinned via the exectime indirection)."""
        from paddle_tpu.monitor import exectime
        pt.set_flags({"FLAGS_enable_monitor": True})
        monitor.reset()
        exectime.set_sample_rate(0)     # isolate the KV seam
        NM.set_kv_sample_rate(1)
        calls = []
        monkeypatch.setattr(exectime, "_block_until_ready",
                            lambda out: calls.append(out))
        eng = _run_engine()
        snap = NM.kv_snapshot()
        assert snap["samples"] > 0 and snap["pages"] > 0
        assert calls == []

    def test_rate_zero_disables_sampling(self):
        pt.set_flags({"FLAGS_enable_monitor": True})
        monitor.reset()
        NM.set_kv_sample_rate(0)
        _run_engine()
        assert NM.kv_snapshot()["samples"] == 0

    def test_monitor_off_no_sampling_work(self):
        NM.set_kv_sample_rate(1)
        eng = _run_engine()
        assert NM.kv_snapshot()["samples"] == 0
        assert eng._kv_absmax_fn is None     # never even built

    def test_free_pages_excluded_and_values_plausible(self):
        """Sampled absmax values come from live pages only: all finite
        and positive (free pages are zeros the filter drops)."""
        pt.set_flags({"FLAGS_enable_monitor": True})
        monitor.reset()
        NM.set_kv_sample_rate(1)
        _run_engine()
        snap = NM.kv_snapshot()
        assert snap["min"] is not None and snap["min"] > 0
        assert snap["max"] >= snap["min"]
        assert snap["recent"][0]["p50"] <= snap["recent"][0]["p95"]
        g = monitor.snapshot()["gauges"]
        assert g["numerics.kv.absmax.max"] == pytest.approx(
            snap["max"], rel=1e-6)

    def test_one_in_n_rate(self):
        pt.set_flags({"FLAGS_enable_monitor": True})
        monitor.reset()
        NM.set_kv_sample_rate(3)
        eng = _run_engine(n_requests=4, max_new=12)
        chunks = eng.stats.decode_steps // eng.decode_chunk
        samples = NM.kv_snapshot()["samples"]
        # every 3rd chunk (some chunks may be turbo-length; bound, not
        # exact): at least one sample, never more than chunks/3 + 1
        assert 1 <= samples <= chunks // 3 + 1


# ---------------------------------------------------------------------------
# sentinel attribution (observe-only)
# ---------------------------------------------------------------------------

class TestSentinelAttribution:
    def test_corrupt_batch_names_worst_layer_in_health_report(self):
        """The acceptance path: a spike injected via the corrupt fault
        action surfaces the worst layer in the sentinel health report;
        the verdict ladder is untouched (one SKIP, training
        continues)."""
        pt.set_flags({"FLAGS_enable_monitor": True})
        monitor.reset()
        cfg, params, opt = _llama()
        step = L.make_train_step(cfg, guard=True, numerics=True,
                                 donate=False)

        def make_stream():
            return (_batch(i) for i in range(8))

        loop = S.SentinelLoop(step, params, opt, make_stream,
                              sentinel=S.AnomalySentinel(
                                  S.SentinelConfig(agree=False)))
        faults.inject("train.batch", action="corrupt", nth=3)
        out = loop.run(8)
        assert out["skipped"] == 1 and out["applied"] == 7
        # frozen at the anomaly: healthy steps after the skip refresh
        # the latest view but not the last-anomaly attribution
        wl = loop.sentinel.worst_layer_at_anomaly
        assert wl is not None and not wl["finite"]
        assert loop.sentinel.worst_layer["finite"]   # latest step OK
        report = S._sentinel_health_provider(weakref.ref(loop))()
        assert report["worst_layer_last_anomaly"] == wl["name"]
        assert report["worst_layer"] == \
            loop.sentinel.worst_layer["name"]
        # the plane recorded every step; the skip instant names a layer
        ev = [e for e in monitor.trace.events()
              if e["name"] == "anomaly.skip"]
        assert ev and ev[-1]["args"]["worst_layer"] == wl["name"]

    @pytest.mark.slow  # tier-1 budget (ISSUE 20 rebalance): healthy-path arm;
    # corrupt_batch_names_worst_layer_in_health_report keeps attribution fast
    def test_healthy_steps_keep_finite_attribution(self):
        pt.set_flags({"FLAGS_enable_monitor": True})
        monitor.reset()
        cfg, params, opt = _llama()
        step = L.make_train_step(cfg, guard=True, numerics=True,
                                 donate=False)
        loop = S.SentinelLoop(step, params, opt,
                              lambda: (_batch(i) for i in range(3)),
                              sentinel=S.AnomalySentinel(
                                  S.SentinelConfig(agree=False)))
        out = loop.run(3)
        assert out["applied"] == 3
        wl = loop.sentinel.worst_layer
        assert wl is not None and wl["finite"]
        report = S._sentinel_health_provider(weakref.ref(loop))()
        assert report["worst_layer_grad_norm"] == pytest.approx(
            wl["grad_norm"])
        assert NM.numerics_snapshot()["total_steps"] == 3

    @pytest.mark.slow  # tier-1 budget (ISSUE 19 rebalance): verdict invariance duplicated by the
    # sentinel guarded-step suite; corrupt-batch attribution stays
    def test_verdicts_identical_with_and_without_numerics(self):
        """Observe-only: the same poisoned stream produces the same
        skip/apply accounting whether or not numerics is on."""
        cfg, params, opt = _llama()
        outs = {}
        for numerics in (False, True):
            pt.set_flags({"FLAGS_enable_monitor": numerics})
            monitor.reset()
            step = L.make_train_step(cfg, guard=True, numerics=numerics,
                                     donate=False)
            loop = S.SentinelLoop(step, params, opt,
                                  lambda: (_batch(i) for i in range(6)),
                                  sentinel=S.AnomalySentinel(
                                      S.SentinelConfig(agree=False)))
            faults.inject("train.batch", action="corrupt", nth=2)
            outs[numerics] = loop.run(6)
            faults.clear()
        assert outs[False]["skipped"] == outs[True]["skipped"] == 1
        assert outs[False]["applied"] == outs[True]["applied"]


# ---------------------------------------------------------------------------
# /numerics route + flight record
# ---------------------------------------------------------------------------

class TestRouteAndFlight:
    def test_numerics_route_serves_stats_and_audit(self):
        from paddle_tpu.monitor import server as srv
        pt.set_flags({"FLAGS_enable_monitor": True})
        monitor.reset()
        NM.record_step_stats(_fake_stats([1.0, 25.0]))
        cfg, params, _ = _llama()
        NM.audit_quantized_tree(params, L.quantize_weights(params),
                                serving_dtype=jnp.bfloat16)
        s = srv.start_server()
        try:
            p = json.load(urllib.request.urlopen(
                f"{s.url}/numerics", timeout=10))
        finally:
            srv.stop_server()
        assert p["worst_layer"]["name"] == "layers.wq[1]"
        assert p["tensors"]["layers.wq[0]"]["gnorm"] == \
            pytest.approx(1.0)
        assert p["quant"]["min_sqnr_db"] > 0
        assert "layers.wq" in p["quant"]["tensors"]
        # strict JSON: the payload round-trips with no NaN tokens
        assert json.loads(json.dumps(p, allow_nan=False)) == p

    def test_route_listed_at_root(self):
        from paddle_tpu.monitor import server as srv
        s = srv.start_server()
        try:
            p = json.load(urllib.request.urlopen(f"{s.url}/",
                                                 timeout=10))
        finally:
            srv.stop_server()
        assert "/numerics" in p["routes"]

    def test_flight_record_carries_numerics_block(self):
        from paddle_tpu.monitor import trace as T
        pt.set_flags({"FLAGS_enable_monitor": True})
        monitor.reset()
        NM.record_step_stats(_fake_stats([float("nan"), 2.0]))
        fp = T.flight_payload()
        assert fp["numerics"]["total_steps"] == 1
        assert fp["numerics"]["worst_layer"]["name"] == "layers.wq[0]"
        # non-finite floats serialize as null, never NaN tokens
        json.dumps(fp["numerics"], allow_nan=False)

    def test_snapshot_sanitizes_nonfinite(self):
        pt.set_flags({"FLAGS_enable_monitor": True})
        monitor.reset()
        NM.record_step_stats(_fake_stats([float("nan")]))
        snap = NM.numerics_snapshot()
        assert snap["worst_layer"]["grad_norm"] is None
        assert snap["worst_layer"]["finite"] is False
        assert snap["rows"][0]["gnorm"]["layers.wq[0]"] is None


# ---------------------------------------------------------------------------
# overhead measurement harness
# ---------------------------------------------------------------------------

def measure_numerics_overhead(iters=20, windows=6):
    """Median per-window overhead of the in-graph numerics block:
    interleaved ON/OFF windows of the same guarded PACKED train step
    at the bench training_packed rung's CPU shape (llama_tiny, the
    shared heavy-tailed trace) — the acceptance measurement. Returns
    (median_pct, per-pair pcts). Measured on this container:
    0.59% median across 9x30-step window pairs (CHANGES.md)."""
    import time
    from paddle_tpu.io import packing as PK
    cfg = L.llama_tiny(num_hidden_layers=2)
    params = L.init_params(cfg, jax.random.PRNGKey(0))
    opt = L.adamw_init(params)
    lens = PK.heavy_tailed_lengths(128, 24, seed=7)
    rng = np.random.default_rng(7)
    docs = [rng.integers(0, cfg.vocab_size, (ln,)).astype(np.int32)
            for ln in lens]
    packed = PK.pack_documents(docs, 128)
    batch = tuple(jnp.asarray(a) for a in
                  (packed["ids"], packed["labels"],
                   packed["segment_ids"], packed["positions"]))
    off = L.make_train_step(cfg, guard=True, numerics=False,
                            donate=False)
    on = L.make_train_step(cfg, guard=True, numerics=True,
                           donate=False)

    def window(step):
        t0 = time.perf_counter()
        for _ in range(iters):
            out = step(params, opt, batch, INF_CAP)
        jax.block_until_ready(out)
        return time.perf_counter() - t0

    window(off), window(on)                      # compile + warm
    pcts = []
    for _ in range(windows):
        t_off = window(off)
        t_on = window(on)
        pcts.append((t_on - t_off) / t_off * 100.0)
    pcts.sort()
    mid = len(pcts) // 2
    med = pcts[mid] if len(pcts) % 2 else (pcts[mid - 1]
                                           + pcts[mid]) / 2
    return med, pcts


@pytest.mark.slow
def test_numerics_overhead_harness():
    """The in-graph stats are fused reductions over grads the step
    already holds: median overhead across interleaved ON/OFF windows
    stays small. The tier-1 bound is loose (shared 2-core container
    swings +/-10% window to window); the <2% acceptance number is the
    9x30-window median recorded in CHANGES.md."""
    med, pcts = measure_numerics_overhead()
    assert med < 10.0, (med, pcts)
