"""Distributed core tests on the 8-virtual-device CPU mesh.

Mirrors the reference test strategy (SURVEY.md §4): mesh/SPMD tests run
single-process multi-device; numeric parity against local math like
test_collective_api_base.py does.
"""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as pt
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import comm_ops
from paddle_tpu.distributed.process_mesh import placements_to_spec


def make_mesh(*shape, names=None):
    return dist.ProcessMesh(
        np.arange(int(np.prod(shape))).reshape(shape), names)


class TestProcessMesh:
    def test_basic(self):
        mesh = make_mesh(2, 4, names=["dp", "mp"])
        assert mesh.shape == [2, 4]
        assert mesh.dim_names == ["dp", "mp"]
        assert mesh.process_ids == list(range(8))
        assert mesh.get_dim_size("mp") == 4
        assert mesh.size == 8

    def test_jax_mesh(self):
        mesh = make_mesh(2, 4, names=["dp", "mp"])
        jm = mesh.jax_mesh()
        assert jm.axis_names == ("dp", "mp")
        assert jm.devices.shape == (2, 4)

    def test_get_mesh_with_dim(self):
        mesh = make_mesh(2, 4, names=["dp", "mp"])
        sub = mesh.get_mesh_with_dim("mp")
        assert sub.dim_names == ["mp", "dp"]
        assert sub.shape == [4, 2]
        sliced = mesh.get_mesh_with_dim("mp", 0)
        assert sliced.shape == [2]

    def test_placements_to_spec(self):
        from jax.sharding import PartitionSpec as P
        assert placements_to_spec(
            [dist.Shard(0), dist.Replicate()], ["a", "b"]) == P("a")
        assert placements_to_spec(
            [dist.Replicate(), dist.Shard(1)], ["a", "b"]) == P(None, "b")
        assert placements_to_spec(
            [dist.Shard(1), dist.Shard(1)], ["a", "b"]) == P(None, ("a", "b"))
        assert placements_to_spec(
            [dist.Replicate(), dist.Replicate()], ["a", "b"]) == P()


class TestShardTensor:
    def test_shard_and_value(self):
        mesh = make_mesh(2, 4, names=["dp", "mp"])
        x = pt.arange(32, dtype="float32").reshape([8, 4])
        dx = dist.shard_tensor(x, mesh, [dist.Shard(0), dist.Replicate()])
        assert dx.placements[0] == dist.Shard(0)
        assert dx.process_mesh is mesh
        np.testing.assert_allclose(dx.numpy(), x.numpy())
        # Physically sharded: each dp shard holds 4 rows.
        shard_shapes = {s.data.shape for s in dx._data.addressable_shards}
        assert shard_shapes == {(4, 4)}

    def test_reshard(self):
        mesh = make_mesh(2, 4, names=["dp", "mp"])
        x = pt.ones([8, 8])
        dx = dist.shard_tensor(x, mesh, [dist.Shard(0), dist.Replicate()])
        dy = dist.reshard(dx, mesh, [dist.Replicate(), dist.Shard(1)])
        assert dy.placements == [dist.Replicate(), dist.Shard(1)]
        np.testing.assert_allclose(dy.numpy(), np.ones((8, 8)))

    def test_partial_stores_replicated(self):
        mesh = make_mesh(8, names=["dp"])
        x = pt.ones([4, 4])
        dx = dist.shard_tensor(x, mesh, [dist.Partial()])
        assert dx.placements[0].is_partial()
        np.testing.assert_allclose(dx.numpy(), np.ones((4, 4)))

    def test_unshard(self):
        mesh = make_mesh(8, names=["x"])
        t = dist.shard_tensor(pt.arange(16, dtype="float32"), mesh,
                              [dist.Shard(0)])
        u = dist.unshard_dtensor(t)
        np.testing.assert_allclose(u.numpy(), np.arange(16, dtype=np.float32))

    def test_dtensor_from_fn(self):
        mesh = make_mesh(8, names=["x"])
        t = dist.dtensor_from_fn(pt.ones, mesh, [dist.Shard(0)], [16, 2])
        assert t.shape == [16, 2]
        np.testing.assert_allclose(t.numpy(), np.ones((16, 2)))

    def test_sharded_math_matches_local(self):
        """Global-semantics check: math on sharded tensors == local math."""
        mesh = make_mesh(2, 4, names=["dp", "mp"])
        xn = np.random.randn(8, 16).astype(np.float32)
        wn = np.random.randn(16, 12).astype(np.float32)
        dx = dist.shard_tensor(pt.to_tensor(xn), mesh,
                               [dist.Shard(0), dist.Replicate()])
        dw = dist.shard_tensor(pt.to_tensor(wn), mesh,
                               [dist.Replicate(), dist.Shard(1)])
        out = pt.matmul(dx, dw)
        np.testing.assert_allclose(out.numpy(), xn @ wn, rtol=2e-5, atol=2e-5)


class TestShardLayer:
    def test_default_replicate(self):
        mesh = make_mesh(8, names=["dp"])
        layer = pt.nn.Linear(4, 4)
        dist.shard_layer(layer, mesh)
        assert layer.weight.process_mesh == mesh

    def test_custom_shard_fn(self):
        mesh = make_mesh(2, 4, names=["dp", "mp"])

        def shard_fn(name, sublayer, m):
            for pname, p in list(sublayer._parameters.items()):
                if p is None or p.ndim != 2:
                    continue
                t = dist.shard_tensor(p, m, [dist.Replicate(), dist.Shard(1)])
                new_p = type(p)(t._data, name=p.name)
                new_p._placements = t._placements
                new_p._process_mesh = t._process_mesh
                sublayer._parameters[pname] = new_p

        layer = pt.nn.Linear(8, 8)
        dist.shard_layer(layer, mesh, shard_fn)
        # weight got resharded by the fn
        assert layer.weight.shape == [8, 8]


class TestShardOptimizer:
    def test_stage1_shards_moments(self):
        mesh = make_mesh(8, names=["dp"])
        dist.set_mesh(mesh)
        try:
            layer = pt.nn.Linear(16, 16)
            dist.shard_layer(layer, mesh)
            opt = pt.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=layer.parameters())
            opt = dist.shard_optimizer(opt, dist.ShardingStage1("dp", mesh))
            x = pt.ones([4, 16])
            loss = layer(x).sum()
            loss.backward()
            opt.step()
            # Moment accumulators exist and are sharded on dim 0 over dp.
            accs = list(opt._inner._accumulators.values())
            assert accs, "optimizer states missing"
            m1 = accs[0]["moment1"]
            shard_shapes = {s.data.shape for s in m1.addressable_shards}
            assert shard_shapes == {(2, 16)}
        finally:
            dist.set_mesh(None)

    def test_stage3_shards_params(self):
        mesh = make_mesh(8, names=["dp"])
        layer = pt.nn.Linear(16, 4)
        opt = pt.optimizer.SGD(learning_rate=0.1,
                               parameters=layer.parameters())
        opt = dist.shard_optimizer(opt, dist.ShardingStage3("dp", mesh))
        x = pt.ones([2, 16])
        layer(x).sum().backward()
        opt.step()
        w = layer.weight
        shard_shapes = {s.data.shape for s in w._data.addressable_shards}
        assert shard_shapes == {(2, 4)}


class TestCollectiveAPI:
    def test_groups(self):
        g = dist.new_group([0, 1, 2, 3])
        assert g.nranks == 4
        assert dist.get_group(g.id) is g
        assert g.get_group_rank(2) == 2
        dist.destroy_process_group()

    def test_world_size_one_semantics(self):
        t = pt.ones([4])
        out = dist.all_reduce(t)
        np.testing.assert_allclose(out.numpy(), np.ones(4))
        lst = []
        dist.all_gather(lst, t)
        assert len(lst) == 1
        objs = []
        dist.all_gather_object(objs, {"a": 1})
        assert objs == [{"a": 1}]
        dist.barrier()

    def test_reduce_op(self):
        assert dist.ReduceOp.SUM == 0
        assert dist.ReduceOp.AVG == 4


class TestCommOps:
    """The compiled collective path (the real TPU backend) via shard_map."""

    def test_psum_all_gather_reduce_scatter(self):
        from jax.sharding import Mesh, PartitionSpec as P
        from paddle_tpu.core.jax_compat import shard_map
        mesh = Mesh(np.array(jax.devices()[:8]), ("x",))
        data = np.arange(32, dtype=np.float32).reshape(8, 4)

        @jax.jit
        def run(x):
            def f(xs):
                s = comm_ops.all_reduce(xs, "x")          # psum
                g = comm_ops.all_gather(xs, "x", gather_dim=0)
                rs = comm_ops.reduce_scatter(g, "x", scatter_dim=0)
                return s, g, rs
            return shard_map(f, mesh=mesh, in_specs=P("x", None),
                             out_specs=(P(), P(None, None), P("x", None)),
                             check_vma=False)(x)

        s, g, rs = run(data)
        np.testing.assert_allclose(np.asarray(s), data.sum(0, keepdims=True))
        np.testing.assert_allclose(np.asarray(g), data)
        # Each device holds the full gathered copy, so psum_scatter sums 8
        # identical contributions into each scattered block.
        np.testing.assert_allclose(np.asarray(rs), 8 * data)

    def test_ppermute_ring(self):
        from jax.sharding import Mesh, PartitionSpec as P
        from paddle_tpu.core.jax_compat import shard_map
        mesh = Mesh(np.array(jax.devices()[:8]), ("x",))
        data = np.arange(8, dtype=np.float32).reshape(8, 1)
        perm = [(i, (i + 1) % 8) for i in range(8)]

        @jax.jit
        def run(x):
            def f(xs):
                return comm_ops.p2p_permute(xs, "x", perm)
            return shard_map(f, mesh=mesh, in_specs=P("x", None),
                             out_specs=P("x", None))(x)

        out = np.asarray(run(data)).flatten()
        np.testing.assert_allclose(out, np.roll(np.arange(8.0), 1))

    def test_broadcast_axis(self):
        from jax.sharding import Mesh, PartitionSpec as P
        from paddle_tpu.core.jax_compat import shard_map
        mesh = Mesh(np.array(jax.devices()[:8]), ("x",))
        data = np.arange(8, dtype=np.float32).reshape(8, 1)

        @jax.jit
        def run(x):
            def f(xs):
                return comm_ops.broadcast(xs, "x", src=3)
            return shard_map(f, mesh=mesh, in_specs=P("x", None),
                             out_specs=P("x", None))(x)

        out = np.asarray(run(data)).flatten()
        np.testing.assert_allclose(out, np.full(8, 3.0))

    def test_all_to_all(self):
        from jax.sharding import Mesh, PartitionSpec as P
        from paddle_tpu.core.jax_compat import shard_map
        mesh = Mesh(np.array(jax.devices()[:8]), ("x",))
        data = np.arange(64, dtype=np.float32).reshape(8, 8)

        @jax.jit
        def run(x):
            def f(xs):
                return comm_ops.all_to_all(xs, "x", split_dim=1, concat_dim=0)
            return shard_map(f, mesh=mesh, in_specs=P("x", None),
                             out_specs=P(None, "x"))(x)

        out = np.asarray(run(data))
        # Row-sharded in, split on dim1 / concat on dim0, column-sharded out:
        # device j ends with column j — reassembly is the identity.
        np.testing.assert_allclose(out, data)


class TestFleet:
    def test_init_topology(self):
        import paddle_tpu.distributed.fleet as fleet
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 4,
                                   "pp_degree": 1}
        hcg = fleet.init(is_collective=True, strategy=strategy)
        try:
            assert hcg.get_data_parallel_world_size() == 2
            assert hcg.get_model_parallel_world_size() == 4
            assert hcg.get_parallel_mode() == "tensor_parallel"
            assert hcg.mesh.size == 8
            assert "mp" in hcg.mesh.dim_names
            assert hcg.get_data_parallel_group().nranks == 2
        finally:
            dist.set_mesh(None)
            fleet.fleet._hcg = None

    def test_topology_queries(self):
        topo = fleet_topo = __import__(
            "paddle_tpu.distributed.fleet.topology",
            fromlist=["CommunicateTopology"]).CommunicateTopology(
                dims=[2, 1, 1, 1, 4])
        assert topo.world_size() == 8
        assert topo.get_rank(data=1, pipe=0, sharding=0, sep=0, model=2) == 6
        assert topo.get_coord(6) == (1, 0, 0, 0, 2)
        assert topo.get_comm_list("model")[0] == [0, 1, 2, 3]
        assert topo.get_axis_list("data", 0) == [0, 1, 2, 3]

    def test_mp_layers(self):
        import paddle_tpu.distributed.fleet as fleet
        mesh = make_mesh(2, 4, names=["dp", "mp"])
        dist.set_mesh(mesh)
        try:
            col = fleet.ColumnParallelLinear(16, 32, gather_output=False,
                                             mesh=mesh)
            row = fleet.RowParallelLinear(32, 16, input_is_parallel=True,
                                          mesh=mesh)
            emb = fleet.VocabParallelEmbedding(64, 16, mesh=mesh)
            ids = pt.to_tensor(np.random.randint(0, 64, (2, 8)))
            h = emb(ids)
            assert h.shape == [2, 8, 16]
            y = col(h)
            assert y.shape == [2, 8, 32]
            # weight physically column-sharded over mp (4 ways on dim 1)
            wshapes = {s.data.shape for s in col.weight._data.addressable_shards}
            assert wshapes == {(16, 8)}
            z = row(y)
            assert z.shape == [2, 8, 16]
            # numeric parity with unsharded math
            ref = h.numpy() @ col.weight.numpy() + col.bias.numpy()
            np.testing.assert_allclose(y.numpy(), ref, rtol=2e-5, atol=2e-5)
            # ParallelCrossEntropy smoke
            ce = fleet.ParallelCrossEntropy()
            logits = pt.to_tensor(
                np.random.randn(4, 64).astype(np.float32), stop_gradient=False)
            labels = pt.to_tensor(np.random.randint(0, 64, (4, 1)))
            loss = ce(logits, labels)
            assert loss.shape == [4, 1]
        finally:
            dist.set_mesh(None)


class TestDataParallel:
    def test_wrap_and_run(self):
        mesh = make_mesh(8, names=["dp"])
        dist.set_mesh(mesh)
        try:
            layer = pt.nn.Linear(4, 4)
            dp = dist.DataParallel(layer)
            x = pt.ones([8, 4])
            y = dp(x)
            assert y.shape == [8, 4]
            with dp.no_sync():
                y2 = dp(x)
            np.testing.assert_allclose(y.numpy(), y2.numpy())
            assert layer.weight.process_mesh == mesh
        finally:
            dist.set_mesh(None)


class TestDistributedCheckpoint:
    def test_save_load_reshard(self, tmp_path):
        mesh = make_mesh(2, 4, names=["dp", "mp"])
        w = dist.shard_tensor(
            pt.to_tensor(np.arange(64, dtype=np.float32).reshape(8, 8)),
            mesh, [dist.Shard(0), dist.Replicate()])
        b = pt.ones([8])
        sd = {"w": w, "b": b, "step": 3}
        dist.save_state_dict(sd, str(tmp_path))

        # Load into a DIFFERENTLY sharded target (reshard-on-load).
        w2 = dist.shard_tensor(pt.zeros([8, 8]), mesh,
                               [dist.Replicate(), dist.Shard(1)])
        b2 = pt.zeros([8])
        sd2 = {"w": w2, "b": b2, "step": 0}
        dist.load_state_dict(sd2, str(tmp_path))
        np.testing.assert_allclose(w2.numpy(),
                                   np.arange(64).reshape(8, 8))
        np.testing.assert_allclose(b2.numpy(), np.ones(8))
        assert sd2["step"] == 3
        # target sharding preserved
        shapes = {s.data.shape for s in w2._data.addressable_shards}
        assert shapes == {(8, 2)}


class TestEnv:
    def test_env_defaults(self):
        assert dist.get_rank() == 0
        assert dist.get_world_size() == 1
        penv = dist.ParallelEnv()
        assert penv.rank == 0
        assert penv.nranks == 1
        dist.init_parallel_env()
        assert dist.is_initialized()


class TestNoSyncAccumulation:
    def test_no_sync_accumulation_parity(self):
        """Grad accumulation under no_sync == one big batch (the contract
        documented in DataParallel.no_sync)."""
        mesh = make_mesh(8, names=["dp"])
        dist.set_mesh(mesh)
        try:
            pt.seed(21)
            layer = pt.nn.Linear(16, 4)
            model = dist.DataParallel(layer)
            xin = np.random.default_rng(1).normal(
                size=(8, 16)).astype("float32")

            # accumulate two half-batches under no_sync, sync on the last
            with model.no_sync():
                ((model(pt.to_tensor(xin[:4])) ** 2).mean() / 2).backward()
            ((model(pt.to_tensor(xin[4:])) ** 2).mean() / 2).backward()
            acc = layer.weight.grad.numpy().copy()
            layer.weight.clear_grad()
            layer.bias.clear_grad()

            ((model(pt.to_tensor(xin)) ** 2).mean()).backward()
            full = layer.weight.grad.numpy()
            np.testing.assert_allclose(acc, full, rtol=1e-4, atol=1e-6)
        finally:
            dist.set_mesh(None)


class TestAsyncCheckpoint:
    def test_async_save_roundtrip(self, tmp_path):
        import numpy as np

        import paddle_tpu as paddle
        from paddle_tpu.distributed import checkpoint as ckpt

        sd = {"w": paddle.to_tensor(np.arange(12, dtype="float32")
                                    .reshape(3, 4)),
              "step": 7}
        handle = ckpt.async_save_state_dict(sd, str(tmp_path / "ck"))
        # caller may mutate immediately after return
        sd["w"].set_value(np.zeros((3, 4), "float32"))
        handle.result(timeout=60)
        assert handle.done()
        target = {"w": paddle.to_tensor(np.zeros((3, 4), "float32")),
                  "step": 0}
        ckpt.load_state_dict(target, str(tmp_path / "ck"))
        np.testing.assert_allclose(
            np.asarray(target["w"].numpy()),
            np.arange(12, dtype="float32").reshape(3, 4))


class TestCrossAxisGradClip:
    def test_global_norm_clip_sharded_vs_local(self):
        """VERDICT r2 gap: cross-mesh-axis clip discipline. The global
        grad norm computed over SHARDED parameters (fsdp+tp placements)
        must match the single-device computation, and the clipped update
        must be identical."""
        import numpy as np

        import paddle_tpu as paddle
        import paddle_tpu.distributed as dist
        import paddle_tpu.nn as nn
        from paddle_tpu.optimizer import SGD, ClipGradByGlobalNorm

        rng = np.random.default_rng(0)
        w = rng.normal(size=(8, 8)).astype("float32") * 3.0
        x = rng.normal(size=(4, 8)).astype("float32")

        def build(shard):
            lin = nn.Linear(8, 8)
            lin.weight.set_value(w)
            if shard:
                mesh = dist.ProcessMesh(
                    np.arange(8).reshape(2, 2, 2),
                    dim_names=["dp", "fsdp", "tp"])
                # weight sharded across BOTH fsdp and tp axes
                lin.weight = dist.shard_tensor(
                    lin.weight, mesh,
                    [dist.Replicate(), dist.Shard(0), dist.Shard(1)])
            opt = SGD(learning_rate=0.1, parameters=lin.parameters(),
                      grad_clip=ClipGradByGlobalNorm(1.0))
            return lin, opt

        results = []
        for shard in (False, True):
            lin, opt = build(shard)
            loss = (lin(paddle.to_tensor(x)) ** 2).sum()
            loss.backward()
            # the raw grad norm is far above the clip threshold
            gn = float(np.linalg.norm(
                np.asarray(lin.weight.grad.numpy())))
            assert gn > 1.0
            opt.step()
            results.append(np.asarray(lin.weight.numpy()))
        np.testing.assert_allclose(results[0], results[1], rtol=1e-5,
                                   atol=1e-6)
        # and the post-clip update magnitude reflects clip_norm=1.0:
        # ||delta|| = lr * ||clipped grad|| = 0.1 * ~1.0 (bias included)
        delta = np.linalg.norm(results[0] - w)
        assert delta < 0.1 + 1e-3
