"""Auto-parallel Engine: cost-model-driven plans (VERDICT-r4 item 8).

Reference: auto_parallel/static/engine.py:63 + static/cost/ — the Engine
plans the distributed layout instead of making the user pick. Here the
planner reuses the auto-tuner's candidate/prune/cost machinery and the
plan materialises as a ('dp','fsdp','tp') Mesh.
"""
import math

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.core import enforce as E
from paddle_tpu.distributed.engine import (Engine, ParallelPlan,
                                           plan_parallel)

NORTH_STAR = dict(num_params=8e9, num_layers=32, hidden_size=4096,
                  seq_length=2048, dtype="bfloat16")


class TestPlanner:
    def test_hybrid_plan_when_naive_dp_cannot_fit(self):
        # 8B params on 8 x 17.5 GB chips: pure dp needs 128 GB/chip of
        # param+grad+optimizer state — the planner must find a hybrid
        # (fsdp shards state, tp shards compute) and say why
        plan = plan_parallel(8, NORTH_STAR, global_batch_size=8,
                             hbm_bytes=17.5e9, chips_per_host=2,
                             sharding_stage=3, use_recompute=True)
        dp, sh, mp = plan.mesh_shape
        assert dp * sh * mp == 8
        assert sh > 1 and mp > 1, plan.describe()          # non-trivial
        assert math.isinf(plan.naive_cost)                 # dp-only OOMs
        assert plan.cost < plan.naive_cost
        assert plan.config["estimated_memory_bytes"] <= 17.5e9
        assert plan.candidates_feasible < plan.candidates_considered
        assert "fsdp" in plan.describe()

    def test_naive_dp_chosen_when_it_fits(self):
        # tiny model, huge HBM: nothing beats pure data parallelism in
        # the cost model (mp pays comm, pp pays bubble)
        plan = plan_parallel(8, dict(num_params=1e6, num_layers=4,
                                     hidden_size=64, seq_length=128),
                             global_batch_size=64, hbm_bytes=95e9)
        assert not math.isinf(plan.naive_cost)
        assert plan.cost <= plan.naive_cost
        assert plan.mesh_shape[2] == 1                     # no tp needed

    def test_infeasible_raises_typed(self):
        with pytest.raises(E.ResourceExhaustedError, match="no parallel"):
            plan_parallel(2, NORTH_STAR, hbm_bytes=1e9)

    def test_build_mesh(self):
        plan = plan_parallel(8, NORTH_STAR, global_batch_size=8,
                             hbm_bytes=17.5e9, chips_per_host=2)
        mesh = plan.build_mesh()
        assert mesh.axis_names == ("dp", "fsdp", "tp")
        assert int(np.prod(mesh.devices.shape)) == 8

    def test_dryrun_mesh_comes_from_planner(self):
        import __graft_entry__ as g
        assert g._mesh_shape(8) == (1, 4, 2)


class TestEngine:
    def test_prepare_plans_and_builds_mesh(self):
        eng = Engine()
        plan = eng.prepare(model_cfg=NORTH_STAR, n_devices=8,
                           global_batch_size=8, hbm_bytes=17.5e9,
                           chips_per_host=2)
        assert isinstance(plan, ParallelPlan)
        assert eng.mesh is not None and eng.plan is plan

    def test_fit_evaluate_predict(self):
        rng = np.random.default_rng(0)
        X = rng.normal(size=(16, 4)).astype("float32")
        Y = (X @ rng.normal(size=(4, 1)).astype("float32"))
        model = nn.Linear(4, 1)
        eng = Engine(model=model, loss=nn.MSELoss(),
                     optimizer=optimizer.AdamW(
                         learning_rate=0.05,
                         parameters=model.parameters()))
        data = [(paddle.to_tensor(X[i:i + 4]), paddle.to_tensor(Y[i:i + 4]))
                for i in range(0, 16, 4)]
        losses = eng.fit(data, epochs=30)
        assert losses[-1] < losses[0] * 0.2, (losses[0], losses[-1])
        assert eng.evaluate(data) < losses[0]
        preds = eng.predict([(paddle.to_tensor(X[:4]),)])
        assert tuple(preds[0].shape) == (4, 1)

    def test_fit_requires_optimizer(self):
        model = nn.Linear(2, 1)
        eng = Engine(model=model, loss=nn.MSELoss())
        x = paddle.to_tensor(np.ones((2, 2), "float32"))
        y = paddle.to_tensor(np.ones((2, 1), "float32"))
        with pytest.raises(E.NotFoundError):
            eng.fit([(x, y)], epochs=1)


class TestPipelineMaterialization:
    """pp>1 plans materialise as pipeline runtime configs (ROUND5 gap:
    the planner could CHOOSE pp but nothing turned the choice into a
    runnable schedule)."""

    def _pp_plan(self, pp=4, dp=1, mbs=2, gbs=16):
        cfg = dict(dp_degree=dp, sharding_degree=1, mp_degree=2,
                   pp_degree=pp, micro_batch_size=mbs)
        return ParallelPlan(config=cfg, world=dp * 2 * pp, cost=1.0,
                            naive_cost=math.inf, global_batch_size=gbs)

    def test_pipeline_config_derivation(self):
        pc = self._pp_plan().pipeline_config()
        assert pc.num_stages == 4
        assert pc.num_micro == 8            # 16 / (dp=1 * sh=1 * mbs=2)
        assert pc.micro_batch_size == 2

    def test_pipeline_config_uses_planner_acc_steps(self):
        # a real planner candidate carries acc_steps = gbs/(dp*sh)/mbs;
        # the materialised num_micro must match the costed work exactly
        # (the batch splits over BOTH dp-like axes before micro-batching)
        plan = self._pp_plan()
        plan.config.update(sharding_degree=2, acc_steps=4)
        assert plan.pipeline_config().num_micro == 4

    def test_pipeline_config_sharding_fallback(self):
        plan = self._pp_plan(gbs=16, mbs=2)
        plan.config["sharding_degree"] = 2   # no acc_steps in config
        assert plan.pipeline_config().num_micro == 4   # 16/(1*2*2)

    def test_pp1_has_no_pipeline_config(self):
        plan = self._pp_plan(pp=1)
        plan.config["pp_degree"] = 1
        assert plan.pipeline_config() is None
        with pytest.raises(E.InvalidArgumentError, match="pp=1"):
            plan.build_pipeline_step(lambda p, x: x, lambda y, l: 0.0)

    def test_indivisible_batch_raises(self):
        plan = self._pp_plan(mbs=3, gbs=16)
        with pytest.raises(E.PreconditionNotMetError):
            plan.pipeline_config()

    def test_mesh_gains_pp_axis(self):
        plan = self._pp_plan()
        mesh = plan.build_mesh()
        assert mesh.axis_names == ("dp", "fsdp", "tp", "pp")
        assert mesh.shape["pp"] == 4 and mesh.shape["tp"] == 2

    def test_pp_step_trains_and_matches_sequential_oracle(self):
        # 1x1x2x4 mesh: a 4-stage pipeline (tp axis unused by the stage
        # fn) vs running the same stages sequentially — GPipe semantics
        # must be exact, not approximate
        import jax
        import jax.numpy as jnp

        plan = self._pp_plan(pp=4, dp=1, mbs=2, gbs=16)
        d = 8
        rng = np.random.default_rng(0)
        params = {"w": jnp.asarray(rng.normal(size=(4, d, d)) * 0.3,
                                   jnp.float32)}

        def stage_fn(p, x):
            return jnp.tanh(x @ p["w"])

        def loss_fn(y, l):
            return jnp.mean((y - l) ** 2)

        step, mesh, pc = plan.build_pipeline_step(
            stage_fn, loss_fn, lr=0.05, remat=False)
        x = jnp.asarray(rng.normal(size=(16, d)), jnp.float32)
        lbl = jnp.asarray(rng.normal(size=(16, d)), jnp.float32)

        from paddle_tpu.distributed.pipeline import shard_stage_params
        pparams = shard_stage_params(params, mesh, axis=pc.axis)
        new_params, loss = step(pparams, x, lbl)

        # oracle: sequential stage application per micro-batch
        def oracle_loss(params, x, lbl):
            xs = x.reshape(pc.num_micro, pc.micro_batch_size, d)
            ls = lbl.reshape(pc.num_micro, pc.micro_batch_size, d)
            def per_micro(xm, lm):
                y = xm
                for s in range(4):
                    y = stage_fn({"w": params["w"][s]}, y)
                return loss_fn(y, lm)
            return jnp.mean(jax.vmap(per_micro)(xs, ls))

        want_loss, want_g = jax.value_and_grad(oracle_loss)(params, x, lbl)
        np.testing.assert_allclose(float(loss), float(want_loss),
                                   rtol=1e-5)
        want_w = params["w"] - 0.05 * want_g["w"]
        np.testing.assert_allclose(np.asarray(new_params["w"]),
                                   np.asarray(want_w), rtol=1e-4,
                                   atol=1e-5)
