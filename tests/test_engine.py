"""Auto-parallel Engine: cost-model-driven plans (VERDICT-r4 item 8).

Reference: auto_parallel/static/engine.py:63 + static/cost/ — the Engine
plans the distributed layout instead of making the user pick. Here the
planner reuses the auto-tuner's candidate/prune/cost machinery and the
plan materialises as a ('dp','fsdp','tp') Mesh.
"""
import math

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.core import enforce as E
from paddle_tpu.distributed.engine import (Engine, ParallelPlan,
                                           plan_parallel)

NORTH_STAR = dict(num_params=8e9, num_layers=32, hidden_size=4096,
                  seq_length=2048, dtype="bfloat16")


class TestPlanner:
    def test_hybrid_plan_when_naive_dp_cannot_fit(self):
        # 8B params on 8 x 17.5 GB chips: pure dp needs 128 GB/chip of
        # param+grad+optimizer state — the planner must find a hybrid
        # (fsdp shards state, tp shards compute) and say why
        plan = plan_parallel(8, NORTH_STAR, global_batch_size=8,
                             hbm_bytes=17.5e9, chips_per_host=2,
                             sharding_stage=3, use_recompute=True)
        dp, sh, mp = plan.mesh_shape
        assert dp * sh * mp == 8
        assert sh > 1 and mp > 1, plan.describe()          # non-trivial
        assert math.isinf(plan.naive_cost)                 # dp-only OOMs
        assert plan.cost < plan.naive_cost
        assert plan.config["estimated_memory_bytes"] <= 17.5e9
        assert plan.candidates_feasible < plan.candidates_considered
        assert "fsdp" in plan.describe()

    def test_naive_dp_chosen_when_it_fits(self):
        # tiny model, huge HBM: nothing beats pure data parallelism in
        # the cost model (mp pays comm, pp pays bubble)
        plan = plan_parallel(8, dict(num_params=1e6, num_layers=4,
                                     hidden_size=64, seq_length=128),
                             global_batch_size=64, hbm_bytes=95e9)
        assert not math.isinf(plan.naive_cost)
        assert plan.cost <= plan.naive_cost
        assert plan.mesh_shape[2] == 1                     # no tp needed

    def test_infeasible_raises_typed(self):
        with pytest.raises(E.ResourceExhaustedError, match="no parallel"):
            plan_parallel(2, NORTH_STAR, hbm_bytes=1e9)

    def test_build_mesh(self):
        plan = plan_parallel(8, NORTH_STAR, global_batch_size=8,
                             hbm_bytes=17.5e9, chips_per_host=2)
        mesh = plan.build_mesh()
        assert mesh.axis_names == ("dp", "fsdp", "tp")
        assert int(np.prod(mesh.devices.shape)) == 8

    def test_dryrun_mesh_comes_from_planner(self):
        import __graft_entry__ as g
        assert g._mesh_shape(8) == (1, 4, 2)


class TestEngine:
    def test_prepare_plans_and_builds_mesh(self):
        eng = Engine()
        plan = eng.prepare(model_cfg=NORTH_STAR, n_devices=8,
                           global_batch_size=8, hbm_bytes=17.5e9,
                           chips_per_host=2)
        assert isinstance(plan, ParallelPlan)
        assert eng.mesh is not None and eng.plan is plan

    def test_fit_evaluate_predict(self):
        rng = np.random.default_rng(0)
        X = rng.normal(size=(16, 4)).astype("float32")
        Y = (X @ rng.normal(size=(4, 1)).astype("float32"))
        model = nn.Linear(4, 1)
        eng = Engine(model=model, loss=nn.MSELoss(),
                     optimizer=optimizer.AdamW(
                         learning_rate=0.05,
                         parameters=model.parameters()))
        data = [(paddle.to_tensor(X[i:i + 4]), paddle.to_tensor(Y[i:i + 4]))
                for i in range(0, 16, 4)]
        losses = eng.fit(data, epochs=30)
        assert losses[-1] < losses[0] * 0.2, (losses[0], losses[-1])
        assert eng.evaluate(data) < losses[0]
        preds = eng.predict([(paddle.to_tensor(X[:4]),)])
        assert tuple(preds[0].shape) == (4, 1)

    def test_fit_requires_optimizer(self):
        model = nn.Linear(2, 1)
        eng = Engine(model=model, loss=nn.MSELoss())
        x = paddle.to_tensor(np.ones((2, 2), "float32"))
        y = paddle.to_tensor(np.ones((2, 1), "float32"))
        with pytest.raises(E.NotFoundError):
            eng.fit([(x, y)], epochs=1)


class TestPipelineMaterialization:
    """pp>1 plans materialise as pipeline runtime configs (ROUND5 gap:
    the planner could CHOOSE pp but nothing turned the choice into a
    runnable schedule)."""

    def _pp_plan(self, pp=4, dp=1, mbs=2, gbs=16):
        cfg = dict(dp_degree=dp, sharding_degree=1, mp_degree=2,
                   pp_degree=pp, micro_batch_size=mbs)
        return ParallelPlan(config=cfg, world=dp * 2 * pp, cost=1.0,
                            naive_cost=math.inf, global_batch_size=gbs)

    def test_pipeline_config_derivation(self):
        pc = self._pp_plan().pipeline_config()
        assert pc.num_stages == 4
        assert pc.num_micro == 8            # 16 / (dp=1 * sh=1 * mbs=2)
        assert pc.micro_batch_size == 2

    def test_pipeline_config_uses_planner_acc_steps(self):
        # a real planner candidate carries acc_steps = gbs/(dp*sh)/mbs;
        # the materialised num_micro must match the costed work exactly
        # (the batch splits over BOTH dp-like axes before micro-batching)
        plan = self._pp_plan()
        plan.config.update(sharding_degree=2, acc_steps=4)
        assert plan.pipeline_config().num_micro == 4

    def test_pipeline_config_sharding_fallback(self):
        plan = self._pp_plan(gbs=16, mbs=2)
        plan.config["sharding_degree"] = 2   # no acc_steps in config
        assert plan.pipeline_config().num_micro == 4   # 16/(1*2*2)

    def test_pp1_has_no_pipeline_config(self):
        plan = self._pp_plan(pp=1)
        plan.config["pp_degree"] = 1
        assert plan.pipeline_config() is None
        with pytest.raises(E.InvalidArgumentError, match="pp=1"):
            plan.build_pipeline_step(lambda p, x: x, lambda y, l: 0.0)

    def test_indivisible_batch_raises(self):
        plan = self._pp_plan(mbs=3, gbs=16)
        with pytest.raises(E.PreconditionNotMetError):
            plan.pipeline_config()

    def test_mesh_gains_pp_axis(self):
        plan = self._pp_plan()
        mesh = plan.build_mesh()
        assert mesh.axis_names == ("dp", "fsdp", "tp", "pp")
        assert mesh.shape["pp"] == 4 and mesh.shape["tp"] == 2

    def test_pp_step_trains_and_matches_sequential_oracle(self):
        # 1x1x2x4 mesh: a 4-stage pipeline (tp axis unused by the stage
        # fn) vs running the same stages sequentially — GPipe semantics
        # must be exact, not approximate
        import jax
        import jax.numpy as jnp

        plan = self._pp_plan(pp=4, dp=1, mbs=2, gbs=16)
        d = 8
        rng = np.random.default_rng(0)
        params = {"w": jnp.asarray(rng.normal(size=(4, d, d)) * 0.3,
                                   jnp.float32)}

        def stage_fn(p, x):
            return jnp.tanh(x @ p["w"])

        def loss_fn(y, l):
            return jnp.mean((y - l) ** 2)

        step, mesh, pc = plan.build_pipeline_step(
            stage_fn, loss_fn, lr=0.05, remat=False)
        x = jnp.asarray(rng.normal(size=(16, d)), jnp.float32)
        lbl = jnp.asarray(rng.normal(size=(16, d)), jnp.float32)

        from paddle_tpu.distributed.pipeline import shard_stage_params
        pparams = shard_stage_params(params, mesh, axis=pc.axis)
        new_params, loss = step(pparams, x, lbl)

        # oracle: sequential stage application per micro-batch
        def oracle_loss(params, x, lbl):
            xs = x.reshape(pc.num_micro, pc.micro_batch_size, d)
            ls = lbl.reshape(pc.num_micro, pc.micro_batch_size, d)
            def per_micro(xm, lm):
                y = xm
                for s in range(4):
                    y = stage_fn({"w": params["w"][s]}, y)
                return loss_fn(y, lm)
            return jnp.mean(jax.vmap(per_micro)(xs, ls))

        want_loss, want_g = jax.value_and_grad(oracle_loss)(params, x, lbl)
        np.testing.assert_allclose(float(loss), float(want_loss),
                                   rtol=1e-5)
        want_w = params["w"] - 0.05 * want_g["w"]
        np.testing.assert_allclose(np.asarray(new_params["w"]),
                                   np.asarray(want_w), rtol=1e-4,
                                   atol=1e-5)


# ===========================================================================
# Overload-safe serving (ISSUE 13): priority admission, load shedding,
# deadlines, SLO-aware preemption, drain — the ACTING half of ROADMAP
# item 5. Chaos contract: every submitted request ends in exactly one of
# completed / rejected / expired / shed, with a typed reason, and the
# engine's page allocator comes out clean.
# ===========================================================================

import threading
import time as _time

import jax
import jax.numpy as jnp


def _serving_engine(**kw):
    from paddle_tpu.inference import ServingEngine
    from paddle_tpu.models import llama as L
    cfg = L.llama_tiny()
    params = L.init_params(cfg, jax.random.PRNGKey(3))
    return ServingEngine(L, params, cfg, **kw), cfg, params


def _mk_req(cfg, rid, n=5, new=4, seed=None, **kw):
    from paddle_tpu.inference import Request
    rng = np.random.default_rng(rid if seed is None else seed)
    return Request(rid=rid,
                   prompt=rng.integers(0, cfg.vocab_size, (n,))
                   .astype(np.int32),
                   max_new_tokens=new, **kw)


def _alloc_clean(eng):
    eng.cache.alloc.check_invariants()
    assert eng.cache.alloc.free_pages == eng.cache.num_pages


@pytest.fixture
def mon():
    import paddle_tpu as pt
    from paddle_tpu import monitor
    from paddle_tpu.monitor import slo
    monitor.reset()
    pt.set_flags({"FLAGS_enable_monitor": True})
    yield monitor
    pt.set_flags({"FLAGS_enable_monitor": False})
    slo.set_objectives(ttft_p99_ms=None, tpot_p99_ms=None,
                       e2e_p99_ms=None, availability=None)
    monitor.reset()


@pytest.mark.serving
class TestPriorityAdmission:
    def test_high_priority_jumps_queue(self):
        # 1 slot busy with a long blocker; a later HIGH-priority
        # request must be admitted (and complete) before the earlier
        # low-priority one. outputs is insertion-ordered = completion
        # order.
        eng, cfg, _ = _serving_engine(num_slots=1, max_len=32,
                                      page_size=4, decode_chunk=2,
                                      priority_admission=True)
        eng.submit(_mk_req(cfg, 0, new=12))
        eng.step()                              # blocker occupies slot
        eng.submit(_mk_req(cfg, 1, new=2, priority=0))
        eng.submit(_mk_req(cfg, 2, new=2, priority=3))
        outs = eng.run()
        order = list(outs)
        assert order.index(2) < order.index(1), order
        assert all(o.finish_reason == "completed" for o in outs.values())
        _alloc_clean(eng)

    def test_flags_off_stays_fifo(self):
        # default engine: priority is observe-only, FIFO order holds
        eng, cfg, _ = _serving_engine(num_slots=1, max_len=32,
                                      page_size=4, decode_chunk=2)
        eng.submit(_mk_req(cfg, 0, new=12))
        eng.step()
        eng.submit(_mk_req(cfg, 1, new=2, priority=0))
        eng.submit(_mk_req(cfg, 2, new=2, priority=3))
        outs = eng.run()
        order = list(outs)
        assert order.index(1) < order.index(2), order

    def test_tenant_inflight_cap(self):
        # tenant "a" floods a 2-slot engine; with cap=1 tenant "b"'s
        # later request is co-resident with exactly one "a" request
        eng, cfg, _ = _serving_engine(num_slots=2, max_len=32,
                                      page_size=4, decode_chunk=2,
                                      priority_admission=True,
                                      tenant_inflight_cap=1)
        for i in range(3):
            eng.submit(_mk_req(cfg, i, new=10, tenant="a"))
        eng.submit(_mk_req(cfg, 9, new=10, tenant="b"))
        eng.step()
        tenants = sorted(s.req.tenant for s in eng.slots
                         if s is not None)
        assert tenants == ["a", "b"], tenants
        outs = eng.run()
        assert len(outs) == 4
        assert all(o.finish_reason == "completed" for o in outs.values())

    @pytest.mark.slow  # tier-1 budget (ISSUE 19 rebalance): token identity under policies duplicated
    # by flags_off_stays_fifo + preemption_tokens_identical pins
    def test_admitted_tokens_byte_identical_under_policies(self):
        # acceptance: with policies ON and the engine overloaded,
        # every ADMITTED request still emits byte-identical tokens to
        # a solo run on a fresh default engine
        eng, cfg, params = _serving_engine(
            num_slots=2, max_len=16, page_size=4, decode_chunk=2,
            num_pages=5, priority_admission=True, max_queue=4,
            slo_preemption=True)
        reqs = [_mk_req(cfg, i, n=4 + (i % 3), new=3 + (i % 4),
                        priority=i % 3) for i in range(6)]
        for r in reqs:
            try:
                eng.submit(r)
            except Exception:
                pass
        outs = eng.run()
        from paddle_tpu.inference import ServingEngine
        from paddle_tpu.models import llama as L
        for o in outs.values():
            if o.finish_reason != "completed":
                continue
            solo = ServingEngine(L, params, cfg, num_slots=1,
                                 max_len=16, page_size=4,
                                 decode_chunk=2)
            want = solo.run([_mk_req(cfg, o.rid,
                                     n=4 + (o.rid % 3),
                                     new=3 + (o.rid % 4))])[o.rid]
            np.testing.assert_array_equal(o.tokens, want.tokens)


@pytest.mark.serving
class TestShedding:
    def test_bounded_queue_sheds_typed_with_retry_hint(self):
        from paddle_tpu.inference import (EngineOverloaded,
                                          RequestRejected)
        eng, cfg, _ = _serving_engine(num_slots=1, max_len=32,
                                      page_size=4, decode_chunk=2,
                                      max_queue=2)
        eng.submit(_mk_req(cfg, 0, new=8))
        eng.step()
        eng.submit(_mk_req(cfg, 1))
        eng.submit(_mk_req(cfg, 2))
        with pytest.raises(EngineOverloaded) as ei:
            eng.submit(_mk_req(cfg, 3))
        assert isinstance(ei.value, RequestRejected)   # typed family
        assert ei.value.retry_after_s >= 1.0
        assert "queue full" in ei.value.reason
        assert eng.stats.shed == 1
        outs = eng.run()                     # queued work unaffected
        assert sorted(outs) == [0, 1, 2]

    def test_high_priority_displaces_lowest(self):
        eng, cfg, _ = _serving_engine(num_slots=1, max_len=32,
                                      page_size=4, decode_chunk=2,
                                      max_queue=2,
                                      priority_admission=True)
        eng.submit(_mk_req(cfg, 0, new=8))
        eng.step()
        eng.submit(_mk_req(cfg, 1, priority=1))
        eng.submit(_mk_req(cfg, 2, priority=0))   # the lowest queued
        eng.submit(_mk_req(cfg, 3, priority=5))   # displaces rid 2
        out2 = eng.outputs[2]
        assert out2.finish_reason == "shed"
        assert out2.retry_after_s is not None and out2.retry_after_s > 0
        assert out2.tokens.size == 0
        outs = eng.run()
        states = {rid: o.finish_reason for rid, o in outs.items()}
        assert states == {0: "completed", 1: "completed",
                          2: "shed", 3: "completed"}
        # no silent loss: every submit is accounted exactly once
        assert eng.stats.completed == 3 and eng.stats.shed == 1

    def test_equal_priority_never_displaced(self):
        from paddle_tpu.inference import EngineOverloaded
        eng, cfg, _ = _serving_engine(num_slots=1, max_len=32,
                                      page_size=4, decode_chunk=2,
                                      max_queue=1,
                                      priority_admission=True)
        eng.submit(_mk_req(cfg, 0, new=8))
        eng.step()
        eng.submit(_mk_req(cfg, 1, priority=2))
        with pytest.raises(EngineOverloaded):
            eng.submit(_mk_req(cfg, 2, priority=2))
        eng.run()

    def test_shed_on_burn_sheds_only_best_effort(self, mon):
        from paddle_tpu.inference import EngineOverloaded
        from paddle_tpu.monitor import slo
        # trip the fast burn: a window of e2e violations
        slo.set_objectives(e2e_p99_ms=1.0)
        for _ in range(40):
            slo.record_request({"tenant": "t", "e2e_ms": 100.0})
        assert slo.burn_alerting(max_age_s=0) is True
        eng, cfg, _ = _serving_engine(num_slots=1, max_len=32,
                                      page_size=4, decode_chunk=2,
                                      shed_on_burn=True)
        with pytest.raises(EngineOverloaded) as ei:
            eng.submit(_mk_req(cfg, 0, priority=0))
        assert "burn" in ei.value.reason
        eng.submit(_mk_req(cfg, 1, priority=1))    # protected class
        outs = eng.run()
        assert outs[1].finish_reason == "completed"
        # the sheds entered the SLO window as shed/rejected
        assert any(r.get("shed") for r in slo.records())

    def test_flags_off_never_sheds(self):
        eng, cfg, _ = _serving_engine(num_slots=1, max_len=32,
                                      page_size=4, decode_chunk=2)
        for i in range(30):
            eng.submit(_mk_req(cfg, i, new=2))
        outs = eng.run()
        assert len(outs) == 30 and eng.stats.shed == 0


@pytest.mark.serving
class TestDeadlines:
    def test_deadline_validation_typed(self):
        from paddle_tpu.inference import RequestRejected
        eng, cfg, _ = _serving_engine(num_slots=1, max_len=32,
                                      page_size=4)
        for bad in (-1.0, 0.0, float("nan"), "soon"):
            with pytest.raises(RequestRejected, match="deadline"):
                eng.submit(_mk_req(cfg, 0, deadline_s=bad))

    def test_expires_in_queue_with_cost(self, mon):
        from paddle_tpu.monitor import slo
        eng, cfg, _ = _serving_engine(num_slots=1, max_len=32,
                                      page_size=4, decode_chunk=2)
        eng.submit(_mk_req(cfg, 0, new=10))
        eng.step()                                  # slot busy
        eng.submit(_mk_req(cfg, 1, new=4, deadline_s=1e-4))
        _time.sleep(0.01)
        outs = eng.run()
        o = outs[1]
        assert o.finish_reason == "expired" and o.tokens.size == 0
        assert eng.stats.expired == 1
        assert o.cost is not None and o.cost.queue_wait_ms > 0
        assert o.cost.e2e_ms is not None
        # the record entered the SLO window, flagged expired (bad for
        # availability, excluded from latency objectives)
        recs = [r for r in slo.records() if r.get("expired")]
        assert len(recs) == 1
        assert mon.snapshot()["counters"].get(
            "serving.requests.expired") == 1

    def test_running_eviction_delivers_partial_tokens(self):
        eng, cfg, _ = _serving_engine(num_slots=1, max_len=64,
                                      page_size=4, decode_chunk=2)
        eng.submit(_mk_req(cfg, 0, new=40, deadline_s=0.05))
        eng.step()                                  # admitted, decoding
        assert eng.slots[0] is not None
        _time.sleep(0.08)
        outs = eng.run()
        o = outs[0]
        assert o.finish_reason == "expired"
        assert 0 < o.tokens.size < 40               # partial delivery
        # token accounting contract holds across expiry
        emitted = sum(len(x.tokens) for x in outs.values())
        assert eng.stats.tokens_generated \
            - eng.stats.tokens_discarded == emitted
        _alloc_clean(eng)

    def test_done_slot_past_deadline_retires_completed(self):
        # a request that FINISHED before its deadline scan must retire
        # with its full output, not be clawed back as expired
        eng, cfg, _ = _serving_engine(num_slots=1, max_len=32,
                                      page_size=4, decode_chunk=4)
        eng.submit(_mk_req(cfg, 0, new=2, deadline_s=0.02))
        eng.step()                    # prefill + chunk: gen hits max
        _time.sleep(0.04)             # deadline passes AFTER done
        outs = eng.run()
        assert outs[0].finish_reason == "completed"
        assert outs[0].tokens.size == 2

    @pytest.mark.slow  # tier-1 budget (ISSUE 19 rebalance): chaos storm; the per-seam deadline tests
    # above pin expiry/retire/eviction behavior fast
    def test_expired_deadline_storm_chaos(self, mon):
        # chaos: a storm of near-instant deadlines mixed with viable
        # work — every request ends in exactly one typed state, the
        # viable work completes, the allocator comes out clean
        eng, cfg, _ = _serving_engine(num_slots=2, max_len=32,
                                      page_size=4, decode_chunk=2,
                                      num_pages=8)
        rids_doomed = list(range(0, 8))
        rids_ok = list(range(100, 104))
        for i in rids_doomed:
            eng.submit(_mk_req(cfg, i, new=6, deadline_s=2e-4))
        for i in rids_ok:
            eng.submit(_mk_req(cfg, i, new=3))
        _time.sleep(0.01)
        outs = eng.run()
        assert sorted(outs) == sorted(rids_doomed + rids_ok)
        states = {rid: o.finish_reason for rid, o in outs.items()}
        assert all(states[i] == "completed" for i in rids_ok), states
        assert sum(1 for i in rids_doomed
                   if states[i] == "expired") >= 6, states
        assert eng.stats.expired + eng.stats.completed == len(outs)
        # costs recorded for every expiry
        for i in rids_doomed:
            if states[i] == "expired":
                assert outs[i].cost is not None
        _alloc_clean(eng)


@pytest.mark.serving
class TestSloPreemption:
    def _overcommit(self, **kw):
        # 2 slots on a 5-page pool: two 5-token prompts (2 pages each)
        # fit, but both growing past 8 KV positions demands a 3rd page
        # each — only one exists, forcing a preemption
        eng, cfg, _ = _serving_engine(num_slots=2, max_len=16,
                                      page_size=4, num_pages=5,
                                      decode_chunk=2, **kw)
        eng.submit(_mk_req(cfg, 0, n=5, new=8, priority=0))  # older, low
        eng.submit(_mk_req(cfg, 1, n=5, new=8, priority=2))  # younger, high
        return eng

    def test_default_evicts_youngest(self):
        eng = self._overcommit()
        outs = eng.run()
        # youngest-first: the younger (high-priority) request is the
        # victim — exactly the inversion the SLO policy fixes
        assert outs[1].preemptions >= 1
        assert outs[0].preemptions == 0
        _alloc_clean(eng)

    def test_slo_preemption_evicts_lowest_priority(self):
        eng = self._overcommit(slo_preemption=True)
        outs = eng.run()
        assert outs[0].preemptions >= 1      # low priority evicted
        assert outs[1].preemptions == 0      # high priority protected
        # both still complete with full outputs
        assert all(o.finish_reason == "completed" and o.tokens.size == 8
                   for o in outs.values())
        _alloc_clean(eng)

    @pytest.mark.slow   # parity duplicate: byte-identity under
    #   policies is already pinned fast-lane by
    #   test_admitted_tokens_byte_identical_under_policies (which
    #   forces preemption churn on the same 5-page pool) and the
    #   test_paged parity matrix
    def test_preemption_tokens_identical_both_policies(self):
        a = self._overcommit().run()
        b = self._overcommit(slo_preemption=True).run()
        for rid in (0, 1):
            np.testing.assert_array_equal(a[rid].tokens, b[rid].tokens)


@pytest.mark.serving
@pytest.mark.chaos
class TestOverloadChaos:
    def test_priority_inversion_probe(self, mon):
        # saturated 2-slot engine, bounded queue: a stream of
        # low-priority work keeps it overloaded; every high-priority
        # request must be admitted (displacing lows as needed) and
        # complete BEFORE the lows that were queued when it arrived,
        # with bounded admission wait in its cost record — while at
        # least some low-priority work is shed
        eng, cfg, _ = _serving_engine(num_slots=2, max_len=32,
                                      page_size=4, decode_chunk=2,
                                      max_queue=3,
                                      priority_admission=True)
        from paddle_tpu.inference import EngineOverloaded
        rid = 0
        shed_low = 0
        high_rids = []
        for wave in range(6):
            for _ in range(3):                       # low-pri flood
                try:
                    eng.submit(_mk_req(cfg, rid, new=4, priority=0,
                                       tenant="low"))
                except EngineOverloaded:
                    shed_low += 1
                rid += 1
            queued_lows = [r.rid for r in eng.queue]
            hi = rid
            rid += 1
            eng.submit(_mk_req(cfg, hi, new=4, priority=5,
                               tenant="high"))
            high_rids.append((hi, queued_lows))
            eng.step()
        outs = eng.run()
        displaced = {r for r, o in outs.items()
                     if o.finish_reason == "shed"}
        for hi, queued_lows in high_rids:
            assert outs[hi].finish_reason == "completed"
            for lo in queued_lows:
                if lo in displaced:
                    continue
                # a low queued when the high arrived can be ADMITTED
                # no earlier than the high (the priority scan picks
                # the high first; the low at best rides the same
                # prefill group) — and it enqueued earlier, so its
                # admission wait is provably >= the high's
                assert outs[hi].cost.queue_wait_ms \
                    <= outs[lo].cost.queue_wait_ms + 1e-6, (hi, lo)
        assert shed_low + len(displaced) >= 1        # lows were shed
        # every high-priority admission wait is recorded — the BOUND
        # is the deterministic pairwise property asserted above
        # (wait(hi) <= wait(any co-queued surviving low)); a global
        # max(hi) <= max(lo) comparison is NOT implied (a high can
        # legitimately wait behind other highs while most lows were
        # shed) and flakes under suite load
        assert all(outs[h].cost.queue_wait_ms >= 0
                   for h, _ in high_rids)

    def test_page_starvation_churn_no_silent_loss(self):
        # synthetic page starvation: a 5-page pool under 6 requests —
        # heavy preemption churn; nothing is lost, everything
        # completes, the allocator comes out clean, and the token
        # contract (generated - discarded == emitted) holds
        eng, cfg, _ = _serving_engine(num_slots=2, max_len=16,
                                      page_size=4, num_pages=5,
                                      decode_chunk=2,
                                      slo_preemption=True)
        reqs = [_mk_req(cfg, i, n=3 + (i % 5), new=2 + (i % 6),
                        priority=i % 2) for i in range(6)]
        outs = eng.run(reqs)
        assert sorted(outs) == list(range(6))
        assert all(o.finish_reason == "completed"
                   for o in outs.values())
        emitted = sum(len(o.tokens) for o in outs.values())
        assert eng.stats.tokens_generated \
            - eng.stats.tokens_discarded == emitted
        _alloc_clean(eng)

    def test_faults_every_mode_fires_repeatedly(self):
        from paddle_tpu.testing import faults
        fired = [0]
        with faults.injected("chaos.tick", action="delay", nth=2,
                             delay_s=0.0, every=True):
            for _ in range(5):
                faults.hit("chaos.tick")
            inj = faults._POINTS["chaos.tick"]
            assert inj.hits == 5 and not inj.fired
        # one-shot default still latches after the Nth
        with faults.injected("chaos.tick", action="raise", nth=2):
            faults.hit("chaos.tick")
            with pytest.raises(faults.FaultInjected):
                faults.hit("chaos.tick")
            faults.hit("chaos.tick")    # latched: no re-fire


@pytest.mark.serving
class TestDrainLifecycle:
    def test_drain_sheds_queue_finishes_live(self):
        from paddle_tpu.inference import EngineOverloaded
        eng, cfg, _ = _serving_engine(num_slots=2, max_len=32,
                                      page_size=4, decode_chunk=2)
        for i in range(5):
            eng.submit(_mk_req(cfg, i, new=6))
        eng.step()                         # 2 admitted, 3 queued
        assert not eng.drain_complete
        eng.begin_drain()
        assert eng.draining
        with pytest.raises(EngineOverloaded) as ei:
            eng.submit(_mk_req(cfg, 99))
        assert "drain" in ei.value.reason
        outs = eng.run()
        states = {rid: o.finish_reason for rid, o in outs.items()}
        assert sorted(outs) == [0, 1, 2, 3, 4]
        completed = [r for r, s in states.items() if s == "completed"]
        shed = [r for r, s in states.items() if s == "shed"]
        assert len(completed) == 2 and len(shed) == 3
        for r in shed:
            assert outs[r].retry_after_s is not None
        assert eng.drain_complete
        assert eng.autoscale_payload()["drain_safe"]
        _alloc_clean(eng)

    def test_drain_keep_queued_finishes_everything(self):
        eng, cfg, _ = _serving_engine(num_slots=2, max_len=32,
                                      page_size=4, decode_chunk=2)
        for i in range(4):
            eng.submit(_mk_req(cfg, i, new=4))
        eng.step()
        eng.begin_drain(shed_queued=False)
        outs = eng.run()
        assert all(o.finish_reason == "completed"
                   for o in outs.values())
        assert len(outs) == 4 and eng.drain_complete

    def test_begin_drain_idempotent(self):
        eng, cfg, _ = _serving_engine(num_slots=1, max_len=32,
                                      page_size=4)
        eng.begin_drain()
        eng.begin_drain()
        assert eng.drain_complete


@pytest.mark.serving
class TestReviewRegressions:
    def test_deadline_overflow_rejected_typed(self):
        # float(10**400) raises OverflowError — must reject typed,
        # not crash the caller (the max_new_tokens precedent)
        from paddle_tpu.inference import RequestRejected
        eng, cfg, _ = _serving_engine(num_slots=1, max_len=32,
                                      page_size=4)
        with pytest.raises(RequestRejected, match="deadline"):
            eng.submit(_mk_req(cfg, 0, deadline_s=10 ** 400))

    def test_drain_safe_counts_done_unretired_slot(self):
        # a finished-but-unretired slot's output only materializes at
        # the next step's retire — drain_safe must hold it resident,
        # or a controller could stop the replica and lose the output
        eng, cfg, _ = _serving_engine(num_slots=1, max_len=32,
                                      page_size=4, decode_chunk=2)
        eng.submit(_mk_req(cfg, 0, new=1))   # done at prefill sampling
        eng.step()
        slot = eng.slots[0]
        assert slot is not None and slot.done     # done, not retired
        assert not eng.autoscale_payload()["drain_safe"]
        assert not eng.drain_complete
        eng.run()
        assert eng.outputs[0].finish_reason == "completed"
        assert eng.autoscale_payload()["drain_safe"]

    def test_tenant_cap_alone_keeps_fifo(self):
        # review fix: the cap without priority admission must enforce
        # the cap but keep STRICT FIFO among eligible requests — a
        # priority>0 request must not jump the queue
        eng, cfg, _ = _serving_engine(num_slots=1, max_len=32,
                                      page_size=4, decode_chunk=2,
                                      tenant_inflight_cap=1)
        assert not eng._priority_admission
        eng.submit(_mk_req(cfg, 0, new=10, tenant="a"))
        eng.step()                               # "a" holds the slot
        eng.submit(_mk_req(cfg, 1, new=2, tenant="b", priority=0))
        eng.submit(_mk_req(cfg, 2, new=2, tenant="b", priority=9))
        eng.submit(_mk_req(cfg, 3, new=2, tenant="a", priority=9))
        eng.step()
        # cap skips tenant "a"'s rid 3 while rid 0 runs; FIFO among
        # eligible picks rid 1 over the higher-priority rid 2
        outs = eng.run()
        order = list(outs)
        assert order.index(1) < order.index(2), order
        assert all(o.finish_reason == "completed"
                   for o in outs.values())

    def test_repeat_drain_never_sheds_preempted_requeue(self):
        # review fix: a preemption re-queue is ADMITTED live work —
        # begin_drain (first call or the controller's per-tick
        # retries) must finish it, not shed it
        eng, cfg, _ = _serving_engine(num_slots=2, max_len=16,
                                      page_size=4, num_pages=5,
                                      decode_chunk=2)
        eng.submit(_mk_req(cfg, 0, n=5, new=8))
        eng.submit(_mk_req(cfg, 1, n=5, new=8))
        # step until page pressure preempts one back onto the queue
        for _ in range(20):
            eng.step()
            if eng.queue:
                break
        assert eng.queue and getattr(
            eng.queue[0], "_preempt_count", 0) > 0
        eng.begin_drain()
        assert eng.queue                  # preempted re-queue survives
        eng.begin_drain()                 # controller-style retry
        assert eng.queue
        outs = eng.run()
        assert all(o.finish_reason == "completed"
                   for o in outs.values()), {
            r: o.finish_reason for r, o in outs.items()}
        assert all(o.tokens.size == 8 for o in outs.values())
        _alloc_clean(eng)

    def test_shed_on_burn_no_feedback_from_own_sheds(self, mon):
        # review fix: sheds are availability-bad records; an
        # availability-only burn (i.e. the gate's own output) must NOT
        # keep the gate armed — only a LATENCY burn sheds
        from paddle_tpu.monitor import slo
        for _ in range(40):
            slo.record_shed("t")          # availability burn only
        assert slo.burn_alerting(max_age_s=0) is True        # full view
        assert slo.burn_alerting(max_age_s=0,
                                 load_only=True) is False    # gate view
        eng, cfg, _ = _serving_engine(num_slots=1, max_len=32,
                                      page_size=4, decode_chunk=2,
                                      shed_on_burn=True)
        eng.submit(_mk_req(cfg, 0, priority=0))   # NOT shed
        outs = eng.run()
        assert outs[0].finish_reason == "completed"

    def test_displacement_never_picks_preempted_requeue(self):
        # review fix: admitted work mid-recompute is exempt from
        # displacement — when only preemption re-queues are queued,
        # the high-priority newcomer is shed instead
        from paddle_tpu.inference import EngineOverloaded
        eng, cfg, _ = _serving_engine(num_slots=2, max_len=16,
                                      page_size=4, num_pages=5,
                                      decode_chunk=2, max_queue=1)
        eng.submit(_mk_req(cfg, 0, n=5, new=8))
        eng.step()                            # admit before the bound
        eng.submit(_mk_req(cfg, 1, n=5, new=8))
        for _ in range(20):                   # force a preemption
            eng.step()
            if eng.queue and getattr(eng.queue[0],
                                     "_preempt_count", 0) > 0:
                break
        assert getattr(eng.queue[0], "_preempt_count", 0) > 0
        with pytest.raises(EngineOverloaded):  # newcomer shed, not
            eng.submit(_mk_req(cfg, 9, n=5, new=2, priority=9))
        outs = eng.run()                       # the admitted victim
        assert outs[0].finish_reason == "completed"
        assert outs[1].finish_reason == "completed"
        assert outs[0].tokens.size == 8 and outs[1].tokens.size == 8

    def test_negative_cap_and_queue_mean_uncapped(self):
        # review fix: -1 follows the "unlimited" convention instead of
        # blocking admission forever (0 >= -1 for every tenant)
        eng, cfg, _ = _serving_engine(num_slots=2, max_len=32,
                                      page_size=4, decode_chunk=2,
                                      tenant_inflight_cap=-1,
                                      max_queue=-5)
        assert eng._tenant_cap == 0 and eng._max_queue == 0
        for i in range(6):
            eng.submit(_mk_req(cfg, i, new=2, tenant="a"))
        outs = eng.run()
        assert len(outs) == 6
        assert all(o.finish_reason == "completed"
                   for o in outs.values())
