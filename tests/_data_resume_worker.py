"""Worker for the elastic exactly-once data-resume pin (run via
AdaptiveElasticManager, NOT collected by pytest). Consumes a seeded
shuffled DataLoader for a fixed number of batches across 2 epochs,
logging every consumed sample index, checkpointing the loader's
{seed, epoch, cursor} state after EVERY batch; on run 0 it kill -9s
itself mid-epoch. The resumed run must consume exactly the unseen
tail — the test stitches the logs and asserts every sample index
trains exactly once per epoch (no replay, no skip)."""
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

from paddle_tpu.distributed.fleet import elastic
from paddle_tpu.io import DataLoader
from paddle_tpu.io.dataset import Dataset

N, BS, EPOCHS = 20, 2, 2
TOTAL = (N // BS) * EPOCHS


class IdentDS(Dataset):
    def __len__(self):
        return N

    def __getitem__(self, i):
        return np.asarray([i], np.int64)


def main():
    log_path = sys.argv[1]
    kill_at = int(os.environ.get("KILL_AT_BATCH", "-1"))
    run = elastic.elastic_run_index()
    loader = DataLoader(IdentDS(), batch_size=BS, shuffle=True, seed=13)
    start, state = elastic.load_state(
        {"data": loader.state_dict(), "step": 0})
    if start:
        loader.set_state_dict(state["data"])
    step = int(start)
    with open(log_path, "a") as log:
        while step < TOTAL:
            advanced = False
            for batch in loader:
                ids = " ".join(str(int(x)) for x in
                               np.asarray(batch.numpy()).ravel())
                log.write(f"run={run} step={step} ids={ids}\n")
                log.flush()
                step += 1
                advanced = True
                elastic.save_state(
                    step, {"data": dict(loader.state_dict()),
                           "step": step}, blocking=True)
                if run == 0 and step == kill_at:
                    os._exit(137)          # simulated node loss
                if step >= TOTAL:
                    break
            if not advanced:
                break                      # defensive: never spin
    print(f"DATA_DONE run={run} steps={step}", flush=True)


if __name__ == "__main__":
    main()
