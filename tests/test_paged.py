"""Paged KV cache + ragged paged attention + continuous-batching engine
(inference/paged.py, inference/engine.py, kernels/paged_attention.py).

The load-bearing contract: the paged decode path must produce EXACTLY
the ring-buffer path's tokens (greedy and fixed-seed sampling, bf16 and
weight-only int8, llama and MoE) while allocating KV at page
granularity — plus allocator refcount invariants (nothing leaks, OOM is
admission refusal, fork is copy-on-write) and scheduler behavior under
a randomized arrival/length trace.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle  # noqa: F401  (backend/platform init)
from paddle_tpu.core import enforce as E
from paddle_tpu.inference import PagedKVCache, Request, ServingEngine
from paddle_tpu.inference.paged import PageAllocator
from paddle_tpu.kernels import paged_attention as PA
from paddle_tpu.models import llama as L
from paddle_tpu.models import moe as M

pytestmark = pytest.mark.serving


def _prompts(rng, vocab, lens):
    return [rng.integers(0, vocab, (n,)).astype(np.int32) for n in lens]


def _ring_generate(family, params, cfg, prompt, n, **kw):
    return np.asarray(family.generate(
        params, jnp.asarray(prompt)[None, :], cfg, max_new_tokens=n,
        **kw))[0]


class TestKernel:
    """ragged_paged_attention (interpret mode) vs the jnp gather ref."""

    def _case(self, dtype, B=3, nh=4, kv=2, hd=64, ps=8, P=16, maxp=4,
              lengths=(13, 0, 25), seed=0):
        rng = np.random.default_rng(seed)
        q = jnp.asarray(rng.normal(size=(B, nh, hd)), dtype)
        kp = jnp.asarray(rng.normal(size=(P, kv, ps, hd)), dtype)
        vp = jnp.asarray(rng.normal(size=(P, kv, ps, hd)), dtype)
        bt = jnp.asarray(rng.permutation(P)[:B * maxp].reshape(B, maxp),
                         jnp.int32)
        ln = jnp.asarray(lengths, jnp.int32)
        return q, kp, vp, bt, ln

    def test_kernel_matches_ref_f32(self):
        q, kp, vp, bt, ln = self._case(jnp.float32)
        got = PA.ragged_paged_attention(q, kp, vp, bt, ln, interpret=True)
        want = PA.paged_attention_ref(q, kp, vp, bt, ln)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    def test_kernel_matches_ref_bf16_gqa(self):
        q, kp, vp, bt, ln = self._case(jnp.bfloat16, nh=8, kv=2, ps=16,
                                       lengths=(31, 7, 64))
        got = PA.ragged_paged_attention(q, kp, vp, bt, ln, interpret=True)
        want = PA.paged_attention_ref(q, kp, vp, bt, ln)
        np.testing.assert_allclose(
            np.asarray(got).astype(np.float32),
            np.asarray(want).astype(np.float32), rtol=2e-2, atol=2e-2)

    def test_empty_sequence_yields_zero_row_not_nan(self):
        q, kp, vp, bt, _ = self._case(jnp.float32)
        ln = jnp.zeros((3,), jnp.int32)
        for fn in (lambda: PA.ragged_paged_attention(
                q, kp, vp, bt, ln, interpret=True),
                lambda: PA.paged_attention_ref(q, kp, vp, bt, ln)):
            out = np.asarray(fn())
            assert np.isfinite(out).all()
            np.testing.assert_array_equal(out, np.zeros_like(out))

    def test_ref_matches_ring_attention_math(self):
        """Paged gather attention == the ring _attn_over_cache on the
        same KV laid out contiguously (pages = consecutive chunks)."""
        rng = np.random.default_rng(3)
        B, nh, kv, hd, ps, maxp = 2, 4, 2, 32, 4, 3
        Mlen = maxp * ps
        q = jnp.asarray(rng.normal(size=(B, 1, nh, hd)), jnp.float32)
        kc = jnp.asarray(rng.normal(size=(B, Mlen, kv, hd)), jnp.float32)
        vc = jnp.asarray(rng.normal(size=(B, Mlen, kv, hd)), jnp.float32)
        pos = 9                                   # ring: 0..pos valid
        ring = L._attn_over_cache(q, kc, vc, jnp.asarray(pos))
        # re-page the same cache: page p of seq b = rows [p*ps, (p+1)*ps)
        kp = jnp.moveaxis(kc.reshape(B * maxp, ps, kv, hd), 2, 1)
        vp = jnp.moveaxis(vc.reshape(B * maxp, ps, kv, hd), 2, 1)
        bt = jnp.arange(B * maxp, dtype=jnp.int32).reshape(B, maxp)
        ln = jnp.full((B,), pos + 1, jnp.int32)
        paged = PA.paged_attention_ref(q[:, 0], kp, vp, bt, ln)
        np.testing.assert_allclose(np.asarray(ring)[:, 0],
                                   np.asarray(paged).reshape(B, -1),
                                   rtol=1e-5, atol=1e-5)

    def test_supported_guard(self):
        q, kp, _, bt, _ = self._case(jnp.float32)
        assert PA.supported(q, kp, bt)
        assert not PA.supported(q.astype(jnp.int8), kp, bt)
        assert not PA.supported(q[:, :3], kp, bt)      # nh % kv != 0


class TestAllocator:
    def test_alloc_advance_free_roundtrip(self):
        a = PageAllocator(num_pages=8, page_size=4, max_pages_per_seq=4)
        pages = a.alloc(0, 10)                         # 3 pages
        assert len(pages) == 3 and a.used_pages == 3
        a.advance(0, 10)
        a.check_invariants()
        a.free(0)
        assert a.used_pages == 0 and a.free_pages == 8
        a.check_invariants()

    def test_oom_returns_none_state_unchanged(self):
        a = PageAllocator(num_pages=2, page_size=4, max_pages_per_seq=4)
        assert a.alloc(0, 8) is not None
        assert a.alloc(1, 4) is None                   # OOM: no pages
        assert 1 not in a._seqs and a.used_pages == 2
        a.advance(0, 8)
        assert a.ensure(0, 12) is None                 # grow OOM
        assert len(a.seq_pages(0)) == 2                # unchanged
        a.check_invariants()

    def test_ensure_grows_only_when_needed(self):
        a = PageAllocator(num_pages=8, page_size=4, max_pages_per_seq=8)
        a.alloc(0, 4)
        a.advance(0, 4)
        new, cow = a.ensure(0, 4)
        assert new == [] and cow == []
        new, cow = a.ensure(0, 5)
        assert len(new) == 1 and cow == []
        a.check_invariants()

    def test_fork_shares_then_copies_on_write(self):
        a = PageAllocator(num_pages=8, page_size=4, max_pages_per_seq=4)
        pages = a.alloc(0, 6)
        a.advance(0, 6)
        assert a.fork(0, 1) == pages
        assert a.used_pages == 2                       # shared, no copies
        a.check_invariants()
        new, cow = a.ensure(1, 7)     # writes into the shared tail page
        assert new == [] and len(cow) == 1
        assert cow[0][0] == pages[1]
        assert a.seq_pages(1)[1] != pages[1]
        assert a.seq_pages(0) == pages                 # src untouched
        a.check_invariants()
        a.free(0)
        a.free(1)
        assert a.used_pages == 0
        a.check_invariants()

    def test_double_alloc_and_overadvance_raise(self):
        a = PageAllocator(num_pages=4, page_size=4, max_pages_per_seq=4)
        a.alloc(0, 4)
        with pytest.raises(E.EnforceError):
            a.alloc(0, 4)
        with pytest.raises(E.EnforceError):
            a.advance(0, 5)                            # past capacity

    def test_pool_cow_copies_device_pages(self):
        cfg = L.llama_tiny()
        c = PagedKVCache(cfg, num_pages=6, page_size=4,
                         max_pages_per_seq=3, dtype=jnp.float32)
        pages = c.alloc.alloc(0, 6)
        c.pool["k"] = c.pool["k"].at[:, pages[1]].set(7.0)
        c.alloc.advance(0, 6)
        c.alloc.fork(0, 1)
        _, cow = c.alloc.ensure(1, 7)
        c.apply_cow(cow)
        dst = c.alloc.seq_pages(1)[1]
        np.testing.assert_array_equal(
            np.asarray(c.pool["k"][:, dst]),
            np.full_like(np.asarray(c.pool["k"][:, dst]), 7.0))


class TestPagedDecodeParity:
    """Identical tokens vs the ring-buffer path (the acceptance bar)."""

    def _run(self, family, cfg, params, lens, new, **req_kw):
        rng = np.random.default_rng(7)
        prompts = _prompts(rng, cfg.vocab_size, lens)
        want = [_ring_generate(family, params, cfg, p, new,
                               **{k: v for k, v in req_kw.items()
                                  if k in ("temperature", "key")})
                for p in prompts]
        eng = ServingEngine(family, params, cfg, num_slots=2,
                            max_len=32, page_size=4, decode_chunk=3)
        outs = eng.run([Request(rid=i, prompt=p, max_new_tokens=new,
                                **req_kw)
                        for i, p in enumerate(prompts)])
        for i, w in enumerate(want):
            np.testing.assert_array_equal(outs[i].tokens, w)
        eng.cache.alloc.check_invariants()
        assert eng.cache.alloc.used_pages == 0         # all retired
        return eng

    @pytest.mark.slow
    def test_llama_greedy_f32(self):
        # tier-1 budget (ISSUE 8): duplicate-dtype parity (~6s) — the
        # bf16 case below keeps the llama engine parity seam in the
        # fast lane at the dtype the engine actually serves
        cfg = L.llama_tiny()
        params = L.init_params(cfg, jax.random.PRNGKey(0))
        self._run(L, cfg, params, (5, 8, 11), 6)

    def test_llama_greedy_bf16(self):
        cfg = L.llama_tiny(dtype=jnp.bfloat16)
        params = L.init_params(cfg, jax.random.PRNGKey(0))
        self._run(L, cfg, params, (5, 9), 5)

    def test_llama_temperature_fixed_seed(self):
        cfg = L.llama_tiny()
        params = L.init_params(cfg, jax.random.PRNGKey(1))
        self._run(L, cfg, params, (8,), 6, temperature=0.8,
                  key=jax.random.PRNGKey(42))

    @pytest.mark.slow  # tier-1 budget (ISSUE 14 rebalance): int8 paged
    # parity duplicates the bf16 paged pin above + the weight-only
    # generate/beam pins in test_models (TestWeightOnlyDecode)
    def test_llama_int8(self):
        cfg = L.llama_tiny()
        qp = L.quantize_weights(L.init_params(cfg, jax.random.PRNGKey(2)))
        self._run(L, cfg, qp, (6, 10), 5)

    @pytest.mark.slow  # tier-1 budget (ISSUE 5): heavy; llama parity
    def test_moe_greedy(self):  # cases keep the engine seam in tier-1
        cfg = M.moe_tiny()
        params = M.init_params(cfg, jax.random.PRNGKey(3))
        self._run(M, cfg, params, (4, 9), 5)

    @pytest.mark.slow  # tier-1 budget (ISSUE 5): heavy; run in slow lane
    def test_moe_int8(self):
        cfg = M.moe_tiny()
        qp = M.quantize_weights(M.init_params(cfg, jax.random.PRNGKey(4)))
        self._run(M, cfg, qp, (7,), 4)

    def test_eos_stops_and_frees(self):
        cfg = L.llama_tiny()
        params = L.init_params(cfg, jax.random.PRNGKey(5))
        rng = np.random.default_rng(9)
        prompt = rng.integers(0, cfg.vocab_size, (6,)).astype(np.int32)
        full = _ring_generate(L, params, cfg, prompt, 8)
        eos = int(full[3])                  # force a stop mid-stream
        eng = ServingEngine(L, params, cfg, num_slots=1, max_len=32,
                            page_size=4, decode_chunk=3)
        outs = eng.run([Request(rid=0, prompt=prompt, max_new_tokens=8,
                                eos_token_id=eos)])
        got = outs[0].tokens
        assert got[-1] == eos and len(got) <= 8
        np.testing.assert_array_equal(got, full[:len(got)])
        assert eng.cache.alloc.used_pages == 0

    def test_decode_through_interpret_kernel_matches_ref(self):
        """The pallas kernel (interpret) slotted into the decode seam
        produces the same tokens as the jnp fallback."""
        from paddle_tpu import kernels as K
        cfg = L.llama_tiny()
        params = L.init_params(cfg, jax.random.PRNGKey(6))
        rng = np.random.default_rng(11)
        prompt = rng.integers(0, cfg.vocab_size, (5,)).astype(np.int32)
        want = _ring_generate(L, params, cfg, prompt, 4)
        orig = K.dispatched_paged_attention
        import paddle_tpu.inference.paged as paged_mod  # noqa: F401

        def interp(q, kp, vp, bt, ln, *, scale=None):
            return PA.ragged_paged_attention(q, kp, vp, bt, ln,
                                             scale=scale, interpret=True)

        K.dispatched_paged_attention = interp
        try:
            eng = ServingEngine(L, params, cfg, num_slots=1, max_len=16,
                                page_size=8, decode_chunk=2)
            outs = eng.run([Request(rid=0, prompt=prompt,
                                    max_new_tokens=4)])
        finally:
            K.dispatched_paged_attention = orig
        np.testing.assert_array_equal(outs[0].tokens, want)


class TestEngineScheduling:
    def test_randomized_arrival_length_trace(self):
        """Poisson-ish arrivals x random prompt/gen lengths through a
        small slot grid: every request completes with exactly its token
        budget, no page leaks, occupancy accounted."""
        cfg = L.llama_tiny()
        params = L.init_params(cfg, jax.random.PRNGKey(8))
        rng = np.random.default_rng(123)
        eng = ServingEngine(L, params, cfg, num_slots=3, max_len=48,
                            page_size=4, decode_chunk=2)
        reqs = [Request(rid=i,
                        prompt=rng.integers(
                            0, cfg.vocab_size,
                            (int(rng.integers(1, 14)),)).astype(np.int32),
                        max_new_tokens=int(rng.integers(1, 9)))
                for i in range(9)]
        pending = list(reqs)
        # staggered arrivals: a couple of requests join per scheduler step
        eng.submit(pending.pop(0))
        busy = True
        while busy or pending:
            for _ in range(int(rng.integers(0, 3))):
                if pending:
                    eng.submit(pending.pop(0))
            busy = eng.step()
        outs = eng.outputs
        assert sorted(outs) == [r.rid for r in reqs]
        for r in reqs:
            assert len(outs[r.rid].tokens) == r.max_new_tokens
            # spot-check correctness on a couple of requests
        for r in reqs[:2]:
            want = _ring_generate(L, params, cfg, r.prompt,
                                  r.max_new_tokens)
            np.testing.assert_array_equal(outs[r.rid].tokens, want)
        eng.cache.alloc.check_invariants()
        assert eng.cache.alloc.used_pages == 0
        s = eng.stats
        assert s.completed == len(reqs)
        assert s.tokens_generated == sum(r.max_new_tokens for r in reqs)
        assert 0.0 < s.occupancy() <= 1.0

    @pytest.mark.slow  # tier-1 budget (ISSUE 19 rebalance): preemption duplicated by the randomized
    # arrival trace above + test_engine's eviction-policy suite
    def test_preemption_under_tiny_pool(self):
        cfg = L.llama_tiny()
        params = L.init_params(cfg, jax.random.PRNGKey(9))
        rng = np.random.default_rng(5)
        eng = ServingEngine(L, params, cfg, num_slots=2, max_len=16,
                            page_size=4, num_pages=5, decode_chunk=2)
        reqs = [Request(rid=i, prompt=rng.integers(
                    0, cfg.vocab_size, (4,)).astype(np.int32),
                        max_new_tokens=8) for i in range(3)]
        outs = eng.run(reqs)
        assert eng.stats.preempted >= 1            # pool forces eviction
        for r in reqs:                             # recompute = exact
            want = _ring_generate(L, params, cfg, r.prompt, 8)
            np.testing.assert_array_equal(outs[r.rid].tokens, want)
        assert eng.cache.alloc.used_pages == 0

    def test_admission_refused_on_oom_idle_engine(self):
        cfg = L.llama_tiny()
        params = L.init_params(cfg, jax.random.PRNGKey(10))
        eng = ServingEngine(L, params, cfg, num_slots=1, max_len=16,
                            page_size=4, num_pages=4)
        # pool holds 4 pages; a 17-token request exceeds max_len
        with pytest.raises(E.EnforceError):
            eng.submit(Request(rid=0,
                               prompt=np.zeros(12, np.int32),
                               max_new_tokens=8))

    def test_watermark_defers_admission(self):
        cfg = L.llama_tiny()
        params = L.init_params(cfg, jax.random.PRNGKey(11))
        rng = np.random.default_rng(6)
        eng = ServingEngine(L, params, cfg, num_slots=2, max_len=16,
                            page_size=4, num_pages=8, watermark=0.5,
                            decode_chunk=2)
        reqs = [Request(rid=i, prompt=rng.integers(
                    0, cfg.vocab_size, (12,)).astype(np.int32),
                        max_new_tokens=4) for i in range(2)]
        for r in reqs:
            eng.submit(r)
        eng.step()
        # each prompt buckets to 4 pages; admitting the second would
        # leave 0 < 4 (= watermark) free pages: deferred
        assert eng.stats.admitted == 1 and len(eng.queue) == 1
        outs = eng.run()
        assert sorted(outs) == [0, 1]
        assert eng.cache.alloc.used_pages == 0

    def test_max_len_auto_page_size(self):
        """page_size=None resolves through the autotune knob (defaults
        off-TPU) and the engine still round-trips."""
        cfg = L.llama_tiny()
        params = L.init_params(cfg, jax.random.PRNGKey(12))
        eng = ServingEngine(L, params, cfg, num_slots=1, max_len=32)
        assert eng.page_size >= 1
        outs = eng.run([Request(rid=0,
                                prompt=np.arange(4, dtype=np.int32),
                                max_new_tokens=3)])
        assert len(outs[0].tokens) == 3


class TestPagedAutotune:
    def test_page_size_sweep_with_injected_measure(self):
        from paddle_tpu.kernels import autotune as at
        cache = at.AutotuneCache(path="/dev/null/never")  # memory-only
        calls = []

        def measure(ps):
            calls.append(ps)
            return {8: 5.0, 16: 1.0, 32: 2.0, 64: 3.0}[ps]

        got = at.paged_page_size(4, 8, 2, 64, 128, jnp.float32,
                                 measure=measure, cache=cache)
        assert got == 16 and len(calls) >= 2
        # second call is a cache hit: no remeasure
        calls.clear()
        got = at.paged_page_size(4, 8, 2, 64, 128, jnp.float32,
                                 measure=measure, cache=cache)
        assert got == 16 and calls == []

    def test_bf16_candidates_respect_sublane(self):
        from paddle_tpu.kernels import autotune as at
        assert all(ps >= 16 for ps in at.paged_candidates(jnp.bfloat16,
                                                          128))
        assert 8 in at.paged_candidates(jnp.float32, 128)
