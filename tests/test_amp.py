"""AMP tests (reference strategy: test/amp/ — dtype routing by op list,
GradScaler dynamics, O2 decorate)."""
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.amp as amp
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt


def t(x, sg=True):
    return paddle.to_tensor(np.asarray(x, dtype=np.float32), stop_gradient=sg)


class TestAutoCast:
    def test_white_op_casts_to_bf16(self):
        a, b = t(np.random.randn(4, 4)), t(np.random.randn(4, 4))
        with amp.auto_cast(dtype="bfloat16"):
            out = paddle.matmul(a, b)
        assert out.dtype == jnp.bfloat16

    def test_black_op_stays_fp32(self):
        x = paddle.to_tensor(np.random.randn(4).astype(np.float32))
        with amp.auto_cast(dtype="bfloat16"):
            out = paddle.exp(x)
        assert out.dtype == jnp.float32

    def test_other_ops_keep_input_dtype(self):
        x = t(np.random.randn(4))
        with amp.auto_cast():
            out = x + x
        assert out.dtype == jnp.float32

    def test_disabled_outside_context(self):
        a, b = t(np.random.randn(2, 2)), t(np.random.randn(2, 2))
        out = paddle.matmul(a, b)
        assert out.dtype == jnp.float32

    def test_custom_lists(self):
        x = t(np.random.randn(4))
        with amp.auto_cast(custom_white_list={"exp"}, dtype="bfloat16"):
            out = paddle.exp(x)
        assert out.dtype == jnp.bfloat16

    def test_nested_restores(self):
        with amp.auto_cast():
            assert amp.is_auto_cast_enabled()
            with amp.auto_cast(enable=False):
                assert not amp.is_auto_cast_enabled()
            assert amp.is_auto_cast_enabled()
        assert not amp.is_auto_cast_enabled()

    def test_linear_under_autocast_trains(self):
        paddle.seed(0)
        net = nn.Linear(8, 4)
        o = opt.Adam(learning_rate=1e-2, parameters=net.parameters())
        x = t(np.random.randn(16, 8))
        y = t(np.random.randn(16, 4))
        first = last = None
        for _ in range(40):
            with amp.auto_cast(dtype="bfloat16"):
                out = net(x)
            loss = paddle.mean((out.astype("float32") - y) ** 2)
            loss.backward()
            o.step()
            o.clear_grad()
            first = first if first is not None else float(loss)
            last = float(loss)
        assert last < first


class TestDecorate:
    def test_o2_casts_params_but_not_norms(self):
        model = nn.Sequential(nn.Linear(4, 8), nn.LayerNorm(8),
                              nn.Linear(8, 2))
        amp.decorate(model, level="O2", dtype="bfloat16")
        assert model[0].weight.dtype == jnp.bfloat16
        assert model[1].weight.dtype == jnp.float32
        assert model[2].weight.dtype == jnp.bfloat16

    def test_o2_sets_multi_precision(self):
        model = nn.Linear(4, 4)
        o = opt.AdamW(parameters=model.parameters())
        amp.decorate(model, o, level="O2")
        assert o._multi_precision


class TestGradScaler:
    def test_scale_multiplies(self):
        s = amp.GradScaler(init_loss_scaling=8.0)
        loss = t(2.0)
        assert float(s.scale(loss)) == 16.0

    def test_unscale_restores_grads(self):
        p = paddle.Parameter(t([1.0, 2.0])._data)
        s = amp.GradScaler(init_loss_scaling=4.0)
        loss = s.scale(paddle.sum(p * 3.0))
        loss.backward()
        np.testing.assert_allclose(p.grad.numpy(), [12.0, 12.0])
        o = opt.SGD(learning_rate=0.0, parameters=[p])
        s.unscale_(o)
        np.testing.assert_allclose(p.grad.numpy(), [3.0, 3.0])

    def test_inf_skips_step_and_decreases_scale(self):
        p = paddle.Parameter(t([1.0])._data)
        o = opt.SGD(learning_rate=1.0, parameters=[p])
        s = amp.GradScaler(init_loss_scaling=64.0, decr_ratio=0.5)
        p.grad = t([float("inf")])
        s.step(o)
        s.update()
        np.testing.assert_allclose(p.numpy(), [1.0])  # step skipped
        assert s.get_loss_scaling() == 32.0

    def test_good_steps_increase_scale(self):
        p = paddle.Parameter(t([1.0])._data)
        o = opt.SGD(learning_rate=0.1, parameters=[p])
        s = amp.GradScaler(init_loss_scaling=2.0, incr_every_n_steps=2,
                           incr_ratio=2.0)
        for _ in range(2):
            loss = s.scale(paddle.sum(p * 1.0))
            loss.backward()
            s.step(o)
            s.update()
            o.clear_grad()
        assert s.get_loss_scaling() == 4.0

    def test_per_optimizer_found_inf(self):
        # One optimizer's inf must not be cleared by another's clean grads.
        p1 = paddle.Parameter(t([1.0])._data)
        p2 = paddle.Parameter(t([1.0])._data)
        o1 = opt.SGD(learning_rate=1.0, parameters=[p1])
        o2 = opt.SGD(learning_rate=1.0, parameters=[p2])
        s = amp.GradScaler(init_loss_scaling=2.0)
        p1.grad = t([float("inf")])
        p2.grad = t([2.0])
        s.unscale_(o1)
        s.unscale_(o2)
        s.step(o1)
        s.step(o2)
        np.testing.assert_allclose(p1.numpy(), [1.0])  # skipped
        np.testing.assert_allclose(p2.numpy(), [0.0])  # 1 - 1*1.0

    def test_double_step_raises(self):
        import pytest
        p = paddle.Parameter(t([1.0])._data)
        o = opt.SGD(learning_rate=0.1, parameters=[p])
        s = amp.GradScaler(init_loss_scaling=2.0)
        p.grad = t([2.0])
        s.step(o)
        with pytest.raises(RuntimeError):
            s.step(o)

    def test_full_fp16_loop(self):
        paddle.seed(0)
        net = nn.Linear(8, 4)
        o = opt.Adam(learning_rate=1e-2, parameters=net.parameters())
        s = amp.GradScaler(init_loss_scaling=1024.0)
        x = t(np.random.randn(8, 8))
        y = t(np.random.randn(8, 4))
        first = last = None
        for _ in range(30):
            with amp.auto_cast(dtype="float16"):
                out = net(x)
            loss = paddle.mean((out.astype("float32") - y) ** 2)
            scaled = s.scale(loss)
            scaled.backward()
            s.step(o)
            s.update()
            o.clear_grad()
            first = first if first is not None else float(loss)
            last = float(loss)
        assert last < first

    def test_state_dict(self):
        s = amp.GradScaler(init_loss_scaling=7.0)
        st = s.state_dict()
        s2 = amp.GradScaler()
        s2.load_state_dict(st)
        assert s2.get_loss_scaling() == 7.0
