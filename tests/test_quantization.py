"""Quantized memory plane (docs/quantization.md): packed int4
weight-only trees (models/llama.py quant_packed/unpack_int4, moe.py)
and int8 KV-cache pages behind FLAGS_serving_kv_quant
(inference/paged.py scale planes, kernels/paged_attention.py quant
arm, inference/engine.py wiring).

The load-bearing contracts: flags-off is byte-identical (plain-array
pools, int8-only default quantize_weights); kv-quant greedy decode
emits the full-precision pools' exact tokens (llama and MoE, jnp
fallback AND interpret kernel); int4 trees clear a pinned SQNR floor;
allocator fork/CoW/free move codes and scale planes in lockstep; the
autotune knob keys quantized and full-precision tunings apart and
warm-starts cold shapes from the nearest tuned neighbor.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core import enforce as E
from paddle_tpu.core import flags as FL
from paddle_tpu.inference import PagedKVCache, Request, ServingEngine
from paddle_tpu.kernels import paged_attention as PA
from paddle_tpu.models import llama as L
from paddle_tpu.models import moe as M
from paddle_tpu.monitor import numerics as NU

pytestmark = pytest.mark.serving

# int4 keeps ~4 bits of mantissa: gaussian weights measure ~18-19 dB
# SQNR at tiny shapes; 12 dB is the refuse-to-serve floor
INT4_SQNR_FLOOR_DB = 12.0


def _prompts(rng, vocab, lens):
    return [rng.integers(0, vocab, (n,)).astype(np.int32) for n in lens]


def _serve(family, cfg, params, lens, new=6, seed=7, **kw):
    rng = np.random.default_rng(seed)
    prompts = _prompts(rng, cfg.vocab_size, lens)
    eng = ServingEngine(family, params, cfg, num_slots=2, max_len=32,
                        page_size=4, decode_chunk=3, **kw)
    outs = eng.run([Request(rid=i, prompt=p, max_new_tokens=new)
                    for i, p in enumerate(prompts)])
    eng.cache.alloc.check_invariants()
    assert eng.cache.alloc.used_pages == 0
    return {i: np.asarray(o.tokens) for i, o in outs.items()}, eng


# ---------------------------------------------------------------------------
# int4 nibble packing
# ---------------------------------------------------------------------------

class TestInt4Packing:
    def test_pack_unpack_roundtrip_matches_codes(self):
        """unpack(pack(codes)) == codes for the full [-8, 7] range on
        both parities of the interleave."""
        rng = np.random.default_rng(0)
        w = jnp.asarray(rng.normal(size=(16, 12)), jnp.float32)
        leaf = L.quant_packed(w, in_axis=0, weight_dtype="int4")
        assert set(leaf) == {"q4", "s"}
        assert leaf["q4"].dtype == jnp.int8
        assert leaf["q4"].shape == (8, 12)          # in_axis halved
        assert leaf["s"].shape == (12,)
        codes = np.asarray(L.unpack_int4(leaf["q4"], 0))
        # reference codes straight from the one-scheme contract
        wf = np.asarray(w, np.float64)
        s = np.abs(wf).max(axis=0) / 7.0
        want = np.clip(np.round(wf / np.maximum(s, 1e-10)), -8, 7)
        np.testing.assert_array_equal(codes, want.astype(np.int8))
        assert codes.min() >= -8 and codes.max() <= 7

    def test_dequant_is_f32_multiply_one_cast(self):
        """Dequantized int4 weights reproduce the quantizer's own
        rounding exactly (no intermediate-dtype double rounding)."""
        rng = np.random.default_rng(1)
        w = jnp.asarray(rng.normal(size=(6, 10)), jnp.float32)
        leaf = L.quant_packed(w, in_axis=0, weight_dtype="int4")
        deq = (L.unpack_int4(leaf["q4"], 0).astype(jnp.float32)
               * leaf["s"][None, :])
        err = np.abs(np.asarray(deq) - np.asarray(w)).max()
        step = float(np.asarray(leaf["s"]).max())
        assert err <= 0.5 * step + 1e-7      # round-to-nearest bound

    def test_odd_contraction_dim_refused(self):
        w = jnp.zeros((7, 4), jnp.float32)
        with pytest.raises(E.EnforceError):
            L.quant_packed(w, in_axis=0, weight_dtype="int4")

    def test_unknown_width_refused(self):
        with pytest.raises(E.UnimplementedError):
            L.quant_packed(jnp.zeros((4, 4)), in_axis=0,
                           weight_dtype="int2")

    def test_int8_arm_is_quant_int8(self):
        w = jnp.asarray(np.random.default_rng(2).normal(size=(8, 8)),
                        jnp.float32)
        a = L.quant_packed(w, in_axis=0)
        b = L.quant_int8(w, in_axis=0)
        np.testing.assert_array_equal(np.asarray(a["q"]),
                                      np.asarray(b["q"]))

    def test_numpy_dequant_ref_matches_jax_unpack(self):
        """monitor/numerics dequant_ref(int4_packed=True) mirrors the
        jax unpack bit-for-bit (both sign-extension tricks agree)."""
        rng = np.random.default_rng(3)
        w = jnp.asarray(rng.normal(size=(3, 8, 6)), jnp.float32)
        leaf = L.quant_packed(w, in_axis=1, weight_dtype="int4")
        want = (L.unpack_int4(leaf["q4"], 1).astype(jnp.float32)
                * leaf["s"][:, None, :])
        got = NU.dequant_ref(np.asarray(leaf["q4"]),
                             np.asarray(leaf["s"]), int4_packed=True)
        np.testing.assert_allclose(got, np.asarray(want), rtol=1e-6,
                                   atol=1e-7)


# ---------------------------------------------------------------------------
# int4 trees: audit floors + serving parity
# ---------------------------------------------------------------------------

class TestInt4Trees:
    def test_llama_audit_clears_sqnr_floor(self):
        cfg = L.llama_tiny()
        params = L.init_params(cfg, jax.random.PRNGKey(2))
        q4 = L.quantize_weights(params, weight_dtype="int4")
        rep = NU.audit_quantized_tree(params, q4)
        assert np.isfinite(rep["int4_min_sqnr_db"])
        assert rep["int4_min_sqnr_db"] >= INT4_SQNR_FLOOR_DB
        assert rep["min_sqnr_db"] >= INT4_SQNR_FLOOR_DB
        assert all(e["bits"] == 4 for e in rep["tensors"].values())

    def test_moe_audit_clears_sqnr_floor(self):
        cfg = M.moe_tiny()
        params = M.init_params(cfg, jax.random.PRNGKey(3))
        q4 = M.quantize_weights(params, weight_dtype="int4")
        rep = NU.audit_quantized_tree(params, q4)
        assert np.isfinite(rep["int4_min_sqnr_db"])
        assert rep["int4_min_sqnr_db"] >= INT4_SQNR_FLOOR_DB

    def test_default_weight_dtype_unchanged_int8(self):
        """Flags-off pin: quantize_weights() still emits {"q","s"}
        int8 leaves — int4 is opt-in by argument only."""
        cfg = L.llama_tiny()
        qp = L.quantize_weights(L.init_params(cfg, jax.random.PRNGKey(0)))
        assert set(qp["layers"]["wq"]) == {"q", "s"}
        assert qp["layers"]["wq"]["q"].dtype == jnp.int8

    def test_llama_int4_ring_vs_paged_parity(self):
        """The int4 tree serves through the SAME engine seam as int8:
        paged tokens == ring-buffer generate tokens."""
        cfg = L.llama_tiny()
        q4 = L.quantize_weights(L.init_params(cfg, jax.random.PRNGKey(2)),
                                weight_dtype="int4")
        rng = np.random.default_rng(7)
        prompts = _prompts(rng, cfg.vocab_size, (6, 10))
        want = [np.asarray(L.generate(q4, jnp.asarray(p)[None, :], cfg,
                                      max_new_tokens=5))[0]
                for p in prompts]
        got, _ = _serve(L, cfg, q4, (6, 10), new=5)
        for i, w in enumerate(want):
            np.testing.assert_array_equal(got[i], w)

    @pytest.mark.slow  # tier-1 budget: llama int4 parity above keeps
    # the int4 engine seam in the fast lane; MoE adds expert matmuls
    def test_moe_int4_ring_vs_paged_parity(self):
        cfg = M.moe_tiny()
        q4 = M.quantize_weights(M.init_params(cfg, jax.random.PRNGKey(3)),
                                weight_dtype="int4")
        rng = np.random.default_rng(7)
        prompts = _prompts(rng, cfg.vocab_size, (5, 8))
        want = [np.asarray(M.generate(q4, jnp.asarray(p)[None, :], cfg,
                                      max_new_tokens=4))[0]
                for p in prompts]
        got, _ = _serve(M, cfg, q4, (5, 8), new=4)
        for i, w in enumerate(want):
            np.testing.assert_array_equal(got[i], w)


# ---------------------------------------------------------------------------
# int8 KV pages: kernel arm
# ---------------------------------------------------------------------------

class TestKVQuantKernel:
    def _case(self, seed=0, B=3, nh=4, kv=2, hd=64, ps=32, P=12, maxp=3,
              lengths=(13, 0, 70)):
        rng = np.random.default_rng(seed)
        q = jnp.asarray(rng.normal(size=(B, nh, hd)), jnp.float32)
        kq = jnp.asarray(rng.integers(-127, 128, (P, kv, ps, hd)),
                         jnp.int8)
        vq = jnp.asarray(rng.integers(-127, 128, (P, kv, ps, hd)),
                         jnp.int8)
        ks = jnp.asarray(rng.uniform(0.004, 0.02, (P, kv)), jnp.float32)
        vs = jnp.asarray(rng.uniform(0.004, 0.02, (P, kv)), jnp.float32)
        bt = jnp.asarray(rng.integers(0, P, (B, maxp)), jnp.int32)
        ln = jnp.asarray(lengths, jnp.int32)
        return q, kq, vq, ks, vs, bt, ln

    def test_quant_kernel_matches_quant_ref(self):
        q, kq, vq, ks, vs, bt, ln = self._case()
        got = PA.ragged_paged_attention(q, kq, vq, bt, ln, k_scales=ks,
                                        v_scales=vs, interpret=True)
        want = PA.paged_attention_ref(q, kq, vq, bt, ln, k_scales=ks,
                                      v_scales=vs)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    def test_quant_ref_matches_dense_dequant(self):
        """Scale folding is exact: attention over int8 codes + scales
        == attention over the densely dequantized pages."""
        q, kq, vq, ks, vs, bt, ln = self._case(seed=1)
        want = PA.paged_attention_ref(
            q, kq.astype(jnp.float32) * ks[:, :, None, None],
            vq.astype(jnp.float32) * vs[:, :, None, None], bt, ln)
        got = PA.paged_attention_ref(q, kq, vq, bt, ln, k_scales=ks,
                                     v_scales=vs)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-6, atol=1e-6)

    def test_supported_quant_guard(self):
        q, kq, vq, ks, vs, bt, ln = self._case()
        assert PA.supported(q, kq, bt, quant=True)
        # int8 pages without the scales arm are a contract breach
        assert not PA.supported(q, kq, bt)
        # quant arm needs the int8 sublane tile (32 rows)
        assert not PA.supported(q, kq[:, :, :16], bt, quant=True)
        # quant arm over non-int8 pages is not a thing
        assert not PA.supported(q, kq.astype(jnp.float32), bt,
                                quant=True)


# ---------------------------------------------------------------------------
# int8 KV pages: allocator + pool plumbing
# ---------------------------------------------------------------------------

class TestKVQuantPool:
    def test_quant_pool_layout(self):
        cfg = L.llama_tiny()
        c = PagedKVCache(cfg, num_pages=6, page_size=4,
                         max_pages_per_seq=3, dtype=jnp.float32,
                         kv_quant=True)
        for leaf in (c.pool["k"], c.pool["v"]):
            assert set(leaf) == {"q", "s"}
            assert leaf["q"].dtype == jnp.int8
            assert leaf["s"].dtype == jnp.float32
            assert leaf["s"].shape == leaf["q"].shape[:3]

    def test_flags_off_pool_is_plain_array(self):
        """Byte-identity pin: flag off, the pool leaves are the same
        plain arrays as before the quantized plane existed (no dict
        wrapper, no scale planes, same dtype/shape)."""
        cfg = L.llama_tiny()
        c = PagedKVCache(cfg, num_pages=6, page_size=4,
                         max_pages_per_seq=3, dtype=jnp.float32)
        assert isinstance(c.pool["k"], jnp.ndarray)
        assert c.pool["k"].dtype == jnp.float32
        assert not c.kv_quant

    def test_cow_copies_codes_and_scales_in_lockstep(self):
        """apply_cow moves the scale row WITH its page — the invariant
        that keeps dequantization correct across forks."""
        cfg = L.llama_tiny()
        c = PagedKVCache(cfg, num_pages=6, page_size=4,
                         max_pages_per_seq=3, dtype=jnp.float32,
                         kv_quant=True)
        pages = c.alloc.alloc(0, 6)
        c.pool["k"]["q"] = c.pool["k"]["q"].at[:, pages[1]].set(7)
        c.pool["k"]["s"] = c.pool["k"]["s"].at[:, pages[1]].set(0.25)
        c.alloc.advance(0, 6)
        c.alloc.fork(0, 1)
        _, cow = c.alloc.ensure(1, 7)
        c.apply_cow(cow)
        c.alloc.check_invariants()
        dst = c.alloc.seq_pages(1)[1]
        assert dst != pages[1]
        np.testing.assert_array_equal(
            np.asarray(c.pool["k"]["q"][:, dst]), 7)
        np.testing.assert_array_equal(
            np.asarray(c.pool["k"]["s"][:, dst]), 0.25)
        c.alloc.free(0)
        c.alloc.free(1)
        assert c.alloc.used_pages == 0
        c.alloc.check_invariants()

    def test_engine_flag_routes_construction(self):
        """ServingEngine(kv_quant=None) resolves FLAGS_serving_kv_quant
        (the _opt pattern every serving flag follows)."""
        cfg = L.llama_tiny()
        params = L.init_params(cfg, jax.random.PRNGKey(0))
        try:
            FL.set_flags({"FLAGS_serving_kv_quant": True})
            eng = ServingEngine(L, params, cfg, num_slots=1, max_len=16,
                                page_size=4)
            assert eng._kv_quant and isinstance(eng.cache.pool["k"], dict)
        finally:
            FL.set_flags({"FLAGS_serving_kv_quant": False})
        eng = ServingEngine(L, params, cfg, num_slots=1, max_len=16,
                            page_size=4)
        assert not eng._kv_quant
        assert isinstance(eng.cache.pool["k"], jnp.ndarray)


# ---------------------------------------------------------------------------
# int8 KV pages: greedy decode parity (the acceptance bar)
# ---------------------------------------------------------------------------

class TestKVQuantDecodeParity:
    """Quantized pools must emit the full-precision pools' exact greedy
    tokens at tiny shapes (weights untouched — only the KV cache drops
    to int8, and the one-scheme scales keep argmax stable)."""

    def test_llama_greedy_fallback(self):
        cfg = L.llama_tiny()
        params = L.init_params(cfg, jax.random.PRNGKey(0))
        want, _ = _serve(L, cfg, params, (5, 9, 12))
        got, eng = _serve(L, cfg, params, (5, 9, 12), kv_quant=True)
        for i in want:
            np.testing.assert_array_equal(got[i], want[i])
        assert isinstance(eng.cache.pool["k"], dict)

    @pytest.mark.slow  # tier-1 budget (ISSUE 20 rebalance): kv-quant family re-run; llama_greedy_fallback
    # keeps the dequant-parity seam fast
    def test_moe_greedy_fallback(self):
        cfg = M.moe_tiny()
        params = M.init_params(cfg, jax.random.PRNGKey(3))
        want, _ = _serve(M, cfg, params, (5, 9))
        got, _ = _serve(M, cfg, params, (5, 9), kv_quant=True)
        for i in want:
            np.testing.assert_array_equal(got[i], want[i])

    @pytest.mark.slow  # tier-1 budget (ISSUE 20 rebalance): interpret-kernel arm; llama_greedy_fallback +
    # the TestKVQuantKernel parity units keep the seam fast
    def test_llama_greedy_interpret_kernel(self):
        """The quant KERNEL (interpret) slotted into the decode seam
        produces the fallback's tokens — both decode arms agree."""
        from paddle_tpu import kernels as K
        cfg = L.llama_tiny()
        params = L.init_params(cfg, jax.random.PRNGKey(6))
        want, _ = _serve(L, cfg, params, (5, 8), new=4)
        orig = K.dispatched_paged_attention

        def interp(q, kp, vp, bt, ln, *, scale=None, k_scales=None,
                   v_scales=None):
            return PA.ragged_paged_attention(
                q, kp, vp, bt, ln, scale=scale, k_scales=k_scales,
                v_scales=v_scales, interpret=True)

        K.dispatched_paged_attention = interp
        try:
            got, _ = _serve(L, cfg, params, (5, 8), new=4, kv_quant=True)
        finally:
            K.dispatched_paged_attention = orig
        for i in want:
            np.testing.assert_array_equal(got[i], want[i])

    def test_prefix_cache_composition(self):
        """Radix prefix cache over int8 pools: forked pages carry their
        scale rows, tokens match the flags-off serve, and the cache
        holds drain."""
        cfg = L.llama_tiny()
        params = L.init_params(cfg, jax.random.PRNGKey(0))
        rng = np.random.default_rng(1)
        pref = rng.integers(0, cfg.vocab_size, (8,)).astype(np.int32)
        prompts = [np.concatenate(
            [pref, rng.integers(0, cfg.vocab_size, (3,)).astype(np.int32)])
            for _ in range(3)]

        def serve(**kw):
            eng = ServingEngine(L, params, cfg, num_slots=2, max_len=32,
                                page_size=4, decode_chunk=3, **kw)
            outs = eng.run([Request(rid=i, prompt=p, max_new_tokens=5)
                            for i, p in enumerate(prompts)])
            eng.cache.alloc.check_invariants()
            return {i: np.asarray(o.tokens) for i, o in outs.items()}, eng

        want, _ = serve()
        got, eng = serve(kv_quant=True, prefix_cache=True)
        for i in want:
            np.testing.assert_array_equal(got[i], want[i])
        # the radix cache held pages across requests (prefill skipped)
        assert eng.stats.prefix_tokens_saved > 0

    @pytest.mark.slow  # tier-1 budget (ISSUE 20 rebalance): composition sweep; prefix_cache_composition +
    # test_prefix_cache's spec greedy-identity pins keep the seam fast
    def test_spec_decode_composition(self):
        """Speculative verify windows rewrite quantized pages in place
        (paged_verify_window's gather/requant path): tokens match the
        flags-off serve exactly."""
        cfg = L.llama_tiny()
        params = L.init_params(cfg, jax.random.PRNGKey(0))
        want, _ = _serve(L, cfg, params, (6, 9), new=8)
        got, _ = _serve(L, cfg, params, (6, 9), new=8, kv_quant=True,
                        spec_decode=True)
        for i in want:
            np.testing.assert_array_equal(got[i], want[i])


# ---------------------------------------------------------------------------
# numerics feeds
# ---------------------------------------------------------------------------

class TestKVQuantNumerics:
    @pytest.fixture(autouse=True)
    def _clean(self):
        yield
        FL.set_flags({"FLAGS_enable_monitor": False,
                      "FLAGS_serving_kv_quant": False})
        NU.set_kv_sample_rate(None)
        from paddle_tpu import monitor
        monitor.reset()
        NU.reset()

    def test_record_and_snapshot(self):
        from paddle_tpu import monitor
        FL.set_flags({"FLAGS_enable_monitor": True})
        monitor.reset()
        NU.reset()
        NU.record_kv_quant(np.full((2, 3), 0.5, np.float32), 0.01)
        snap = NU.kv_quant_snapshot()
        assert snap["samples"] == 1
        assert snap["scale_p99"] == pytest.approx(0.5)
        assert snap["clip_fraction"] == pytest.approx(0.01)
        g = monitor.snapshot()["gauges"]
        assert g["numerics.kv_quant.scale_p99"] == pytest.approx(0.5)
        assert g["numerics.kv_quant.clip_fraction"] == pytest.approx(0.01)
        NU.reset()
        assert NU.kv_quant_snapshot()["samples"] == 0

    def test_engine_sampling_feeds_kv_quant(self):
        """The engine's 1-in-N absmax seam records scale/clip health
        for quantized pools (live pages only, finite, positive)."""
        from paddle_tpu import monitor
        FL.set_flags({"FLAGS_enable_monitor": True})
        monitor.reset()
        NU.reset()
        NU.set_kv_sample_rate(1)
        cfg = L.llama_tiny()
        params = L.init_params(cfg, jax.random.PRNGKey(0))
        _serve(L, cfg, params, (5, 9), kv_quant=True)
        snap = NU.kv_quant_snapshot()
        assert snap["samples"] > 0
        assert snap["scale_p99"] is not None and snap["scale_p99"] > 0
        assert 0.0 <= snap["clip_fraction"] <= 1.0
        # the absmax plane keeps feeding alongside (absmax = |q|*s)
        assert NU.kv_snapshot()["samples"] > 0


# ---------------------------------------------------------------------------
# autotune key space + warm start
# ---------------------------------------------------------------------------

class TestPagedAutotuneKVQuant:
    def test_kv_quant_candidates_floor_32(self):
        from paddle_tpu.kernels import autotune as AT
        assert all(ps % 32 == 0
                   for ps in AT.paged_candidates(jnp.bfloat16, 256,
                                                 kv_quant=True))
        assert 16 in AT.paged_candidates(jnp.bfloat16, 256)

    def test_key_space_no_collision(self, tmp_path):
        """kv_quant entries ride a ':kvq' suffix — a quantized tuning
        never shadows the full-precision pool's entry for the same
        shape."""
        from paddle_tpu.kernels import autotune as AT
        cache = AT.AutotuneCache(str(tmp_path / "at.json"))
        ps_fp = AT.paged_page_size(4, 8, 2, 64, 256, jnp.bfloat16,
                                   measure=lambda ps: float(ps),
                                   cache=cache)
        ps_q = AT.paged_page_size(4, 8, 2, 64, 256, jnp.bfloat16,
                                  measure=lambda ps: 1.0 / ps,
                                  cache=cache, kv_quant=True)
        keys = sorted(cache._mem)
        assert len(keys) == 2 and keys[1].endswith(":kvq")
        assert ps_fp == 16         # cheapest by injected timing (8 < bf16 sublane)
        assert ps_q == 64
        assert ps_q % 32 == 0

    def test_nearest_neighbor_warm_start(self, tmp_path):
        """A cold shape that cannot measure (CPU backend) seeds from
        the closest tuned neighbor in its key family instead of the
        hardcoded default."""
        from paddle_tpu.kernels import autotune as AT
        cache = AT.AutotuneCache(str(tmp_path / "at.json"))
        # tune b4 via injected measure; then ask for b6 with no measure
        AT.paged_page_size(4, 8, 2, 64, 256, jnp.bfloat16,
                           measure=lambda ps: 1.0 / ps, cache=cache)
        got = AT.paged_page_size(6, 8, 2, 64, 256, jnp.bfloat16,
                                 cache=cache)
        key = [k for k in AT._USED if "b6h8" in k and "kvq" not in k][0]
        assert AT._USED[key]["source"].startswith("warm-start:")
        assert got == 64

    def test_warm_start_ignores_other_families_and_errors(self, tmp_path):
        from paddle_tpu.kernels import autotune as AT
        cache = AT.AutotuneCache(str(tmp_path / "at.json"))
        # a kv-quant entry and an error entry must NOT warm-start the
        # full-precision key family
        AT.paged_page_size(4, 8, 2, 64, 256, jnp.bfloat16,
                           measure=lambda ps: 1.0 / ps, cache=cache,
                           kv_quant=True)
        bad_key = [k for k in cache._mem][0].replace(":kvq", "") \
            .replace("b4", "b2")
        cache.put(bad_key, {"page_size": 8, "error": "boom"})
        got = AT.paged_page_size(6, 8, 2, 64, 256, jnp.bfloat16,
                                 cache=cache)
        key = [k for k in AT._USED if "b6h8" in k and "kvq" not in k][0]
        assert AT._USED[key]["source"] == "default-not-tpu"
        assert got == AT.PAGED_DEFAULT_PAGE
