"""io tests (reference strategy: test/legacy_test/test_dataloader_*.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.io import (BatchSampler, ChainDataset, ComposeDataset,
                           ConcatDataset, DataLoader, Dataset,
                           DistributedBatchSampler, IterableDataset,
                           RandomSampler, SequenceSampler, Subset,
                           TensorDataset, WeightedRandomSampler,
                           random_split)


class RangeDataset(Dataset):
    def __init__(self, n):
        self.n = n

    def __getitem__(self, i):
        return np.float32(i), np.int64(i % 3)

    def __len__(self):
        return self.n


class StreamDataset(IterableDataset):
    def __init__(self, n):
        self.n = n

    def __iter__(self):
        for i in range(self.n):
            yield np.float32(i)


class TestDatasets:
    def test_tensor_dataset(self):
        x = paddle.to_tensor(np.arange(12).reshape(6, 2).astype(np.float32))
        y = paddle.to_tensor(np.arange(6))
        ds = TensorDataset([x, y])
        assert len(ds) == 6
        xi, yi = ds[2]
        np.testing.assert_allclose(xi.numpy(), [4, 5])

    def test_concat_and_subset(self):
        d = ConcatDataset([RangeDataset(3), RangeDataset(2)])
        assert len(d) == 5
        assert d[3][0] == 0.0
        s = Subset(RangeDataset(10), [5, 7])
        assert len(s) == 2 and s[1][0] == 7.0

    def test_compose(self):
        d = ComposeDataset([RangeDataset(3), RangeDataset(3)])
        assert len(d[0]) == 4

    def test_random_split(self):
        a, b = random_split(RangeDataset(10), [7, 3])
        assert len(a) == 7 and len(b) == 3
        all_idx = sorted([x[0] for x in a] + [x[0] for x in b])
        assert all_idx == [float(i) for i in range(10)]

    def test_chain(self):
        c = ChainDataset([StreamDataset(2), StreamDataset(3)])
        assert len(list(c)) == 5


class TestSamplers:
    def test_sequence(self):
        assert list(SequenceSampler(RangeDataset(4))) == [0, 1, 2, 3]

    def test_random_is_permutation(self):
        got = sorted(RandomSampler(RangeDataset(10)))
        assert got == list(range(10))

    def test_weighted(self):
        s = WeightedRandomSampler([0.0, 1.0, 0.0], num_samples=20)
        assert all(i == 1 for i in s)

    def test_batch_sampler(self):
        bs = BatchSampler(RangeDataset(10), batch_size=3)
        batches = list(bs)
        assert len(batches) == 4 and len(batches[-1]) == 1
        bs = BatchSampler(RangeDataset(10), batch_size=3, drop_last=True)
        assert len(list(bs)) == 3

    def test_distributed_batch_sampler_partitions(self):
        seen = []
        for rank in range(2):
            s = DistributedBatchSampler(RangeDataset(10), batch_size=2,
                                        num_replicas=2, rank=rank)
            for b in s:
                seen.extend(b)
        assert sorted(seen) == list(range(10))


class TestDataLoader:
    def test_basic_batching(self):
        dl = DataLoader(RangeDataset(10), batch_size=4)
        batches = list(dl)
        assert len(batches) == 3
        x, y = batches[0]
        assert x.shape == [4]
        np.testing.assert_allclose(x.numpy(), [0, 1, 2, 3])

    def test_shuffle_covers_all(self):
        dl = DataLoader(RangeDataset(20), batch_size=5, shuffle=True)
        seen = np.concatenate([b[0].numpy() for b in dl])
        assert sorted(seen.tolist()) == [float(i) for i in range(20)]

    def test_drop_last(self):
        dl = DataLoader(RangeDataset(10), batch_size=4, drop_last=True)
        assert len(list(dl)) == 2

    def test_num_workers_threaded(self):
        dl = DataLoader(RangeDataset(32), batch_size=4, num_workers=2)
        batches = list(dl)
        assert len(batches) == 8
        seen = np.concatenate([b[0].numpy() for b in batches])
        assert sorted(seen.tolist()) == [float(i) for i in range(32)]

    def test_iterable_dataset(self):
        dl = DataLoader(StreamDataset(7), batch_size=3)
        batches = list(dl)
        assert len(batches) == 3
        assert batches[-1].shape == [1]

    def test_dict_collate(self):
        class DictDS(Dataset):
            def __getitem__(self, i):
                return {"a": np.float32(i), "b": np.ones(2, np.float32)}

            def __len__(self):
                return 4
        dl = DataLoader(DictDS(), batch_size=2)
        b = next(iter(dl))
        assert b["a"].shape == [2] and b["b"].shape == [2, 2]

    def test_custom_collate(self):
        dl = DataLoader(RangeDataset(4), batch_size=2,
                        collate_fn=lambda b: len(b))
        assert list(dl) == [2, 2]

    def test_len(self):
        dl = DataLoader(RangeDataset(10), batch_size=3)
        assert len(dl) == 4


class _SquareDataset:
    """Top-level (picklable) dataset for process-worker tests."""

    def __init__(self, n=32):
        self.n = n

    def __getitem__(self, i):
        import numpy as _np
        return (_np.full((3,), i, "float32"), _np.int64(i * i))

    def __len__(self):
        return self.n


class TestProcessWorkers:
    def test_process_mode_matches_sync(self):
        import paddle_tpu.io as io
        ds = _SquareDataset(32)
        sync = list(io.DataLoader(ds, batch_size=4, shuffle=False))
        procs = list(io.DataLoader(ds, batch_size=4, shuffle=False,
                                   num_workers=2, worker_mode="process"))
        assert len(procs) == len(sync) == 8
        for (xs, ys), (xp, yp) in zip(sync, procs):
            np.testing.assert_allclose(np.asarray(xs.numpy()),
                                       np.asarray(xp.numpy()))
            np.testing.assert_allclose(np.asarray(ys.numpy()),
                                       np.asarray(yp.numpy()))

    def test_process_mode_preserves_order(self):
        import paddle_tpu.io as io
        ds = _SquareDataset(40)
        out = list(io.DataLoader(ds, batch_size=5, shuffle=False,
                                 num_workers=3, worker_mode="process"))
        firsts = [int(np.asarray(b[0].numpy())[0, 0]) for b in out]
        assert firsts == [0, 5, 10, 15, 20, 25, 30, 35]

    def test_worker_error_propagates(self):
        import paddle_tpu.io as io

        class Bad(_SquareDataset):
            def __getitem__(self, i):
                if i == 7:
                    raise ValueError("poison sample")
                return super().__getitem__(i)

        dl = io.DataLoader(Bad(16), batch_size=4, shuffle=False,
                           num_workers=2, worker_mode="process")
        with pytest.raises(RuntimeError, match="poison"):
            list(dl)


class TestElastic:
    def test_restarts_until_success(self, tmp_path):
        from paddle_tpu.distributed.fleet import ElasticManager
        calls = []

        def fake_launch(script, script_args, nproc_per_node, **kw):
            calls.append(nproc_per_node)
            return 0 if len(calls) >= 3 else 1

        m = ElasticManager(max_restarts=5, launcher=fake_launch,
                           restart_delay=0.0)
        rc = m.run("train.py", nproc_per_node=4)
        assert rc == 0 and len(calls) == 3 and m.restarts == 2
        assert m.events[-1][1] == "completed"

    def test_budget_exhausted_returns_failure(self):
        from paddle_tpu.distributed.fleet import ElasticManager
        m = ElasticManager(max_restarts=2,
                           launcher=lambda *a, **k: 7, restart_delay=0.0)
        rc = m.run("train.py", nproc_per_node=2)
        assert rc == 7 and m.restarts == 2
        assert m.events[-1][1] == "error"

    def test_scale_in_toward_min(self):
        from paddle_tpu.distributed.fleet import ElasticManager
        sizes = []

        def fake_launch(script, script_args, nproc_per_node, **kw):
            sizes.append(nproc_per_node)
            return 1

        m = ElasticManager(max_restarts=4, min_nproc=2,
                           launcher=fake_launch, restart_delay=0.0)
        m.run("train.py", nproc_per_node=4)
        assert sizes[0] == 4 and sizes[-1] < 4 and min(sizes) >= 2

    def test_real_elastic_restart(self, tmp_path):
        """End-to-end: a worker that fails on the first run and succeeds
        after a marker file exists (the transient-fault pattern)."""
        from paddle_tpu.distributed.fleet import run_elastic
        marker = tmp_path / "ok"
        script = tmp_path / "w.py"
        script.write_text(
            "import os, sys\n"
            f"m = {str(marker)!r}\n"
            "if not os.path.exists(m):\n"
            "    open(m, 'w').write('x')\n"
            "    sys.exit(1)\n"
            "print('ELASTIC_DONE')\n")
        rc = run_elastic(str(script), nproc_per_node=1, max_restarts=2)
        assert rc == 0

    def test_process_mode_user_collate_runs_in_parent(self):
        import paddle_tpu.io as io
        import paddle_tpu as ptm

        def my_collate(samples):
            xs = np.stack([s[0] for s in samples])
            return {"doubled": ptm.to_tensor(xs * 2)}

        dl = io.DataLoader(_SquareDataset(12), batch_size=4, shuffle=False,
                           num_workers=2, worker_mode="process",
                           collate_fn=my_collate)
        out = list(dl)
        assert set(out[0]) == {"doubled"}
        np.testing.assert_allclose(np.asarray(out[0]["doubled"].numpy())[:, 0],
                                   [0, 2, 4, 6])

    def test_process_mode_rejects_iterable(self):
        import paddle_tpu.io as io
        from paddle_tpu.io.dataset import IterableDataset

        class It(IterableDataset):
            def __iter__(self):
                yield np.zeros(2, "float32")

        dl = io.DataLoader(It(), batch_size=2, num_workers=2,
                           worker_mode="process")
        with pytest.raises(ValueError, match="process"):
            iter(dl)

    def test_process_mode_rejects_tensor_samples(self):
        import paddle_tpu.io as io
        import paddle_tpu as ptm

        class TDs(_SquareDataset):
            def __getitem__(self, i):
                return ptm.to_tensor(np.zeros(2, "float32"))

        dl = io.DataLoader(TDs(8), batch_size=2, num_workers=1,
                           worker_mode="process")
        with pytest.raises(RuntimeError, match="numpy"):
            list(dl)

    def test_elastic_budget_resets_per_run(self):
        from paddle_tpu.distributed.fleet import ElasticManager
        seq = iter([1, 1, 0, 1, 1, 0])    # two jobs, 2 retries each
        m = ElasticManager(max_restarts=3,
                           launcher=lambda *a, **k: next(seq),
                           restart_delay=0.0)
        assert m.run("a.py") == 0 and m.restarts == 2
        assert m.run("b.py") == 0 and m.restarts == 2


class TestNativeDataFeed:
    """C++ datafeed core (csrc/datafeed.cc; reference capability:
    fluid/framework/data_feed.cc — batch assembly off the Python
    interpreter)."""

    def test_ordered_batches_match_tensor_dataset(self):
        import numpy as np

        import paddle_tpu as paddle
        from paddle_tpu.io import DataLoader, TensorDataset
        from paddle_tpu.io.native_feed import native_available

        if not native_available():
            import pytest
            pytest.skip("native toolchain unavailable")
        x = np.arange(36, dtype="float32").reshape(9, 4)
        y = np.arange(9, dtype="int64")
        ds = TensorDataset([paddle.to_tensor(x), paddle.to_tensor(y)])
        dl = DataLoader(ds, batch_size=4, worker_mode="native",
                        num_workers=2)
        xs, ys = [], []
        for bx, by in dl:
            xs.append(np.asarray(bx.numpy()))
            ys.append(np.asarray(by.numpy()))
        np.testing.assert_array_equal(np.concatenate(xs), x)
        np.testing.assert_array_equal(np.concatenate(ys), y)
        assert xs[-1].shape[0] == 1   # tail batch kept (drop_last off)

    def test_shuffle_permutation_and_drop_last(self):
        import numpy as np

        import paddle_tpu as paddle
        from paddle_tpu.io import DataLoader, TensorDataset
        from paddle_tpu.io.native_feed import native_available

        if not native_available():
            import pytest
            pytest.skip("native toolchain unavailable")
        y = np.arange(10, dtype="int64")
        ds = TensorDataset([paddle.to_tensor(y)])
        dl = DataLoader(ds, batch_size=4, shuffle=True, drop_last=True,
                        worker_mode="native")
        seen = np.concatenate([np.asarray(b[0].numpy()) for b in dl])
        assert len(seen) == 8             # drop_last
        assert len(set(seen.tolist())) == 8   # a permutation slice

    def test_native_gather_parity_and_speed(self):
        import time

        import numpy as np

        from paddle_tpu.io.native_feed import (native_available,
                                               native_gather)

        if not native_available():
            import pytest
            pytest.skip("native toolchain unavailable")
        rng = np.random.default_rng(0)
        src = rng.normal(size=(20000, 256)).astype("float32")
        idx = rng.integers(0, 20000, 4096).astype(np.uint64)
        got = native_gather(src, idx)
        np.testing.assert_array_equal(got, src[idx])


def test_native_feeder_rejects_bad_epochs():
    # the epochs check fires before the C++ lib is touched — no skip
    from paddle_tpu.io import native_feed as nf
    import numpy as np
    import pytest
    a = np.arange(12, dtype=np.float32).reshape(6, 2)
    with pytest.raises(ValueError, match="epochs"):
        nf.NativeArrayFeeder([a], batch_size=2, epochs=0)


def test_native_gather_bounds_checked():
    from paddle_tpu.io import native_feed as nf
    if not nf.native_available():
        import pytest
        pytest.skip("native datafeed lib unavailable")
    import numpy as np
    import pytest
    a = np.arange(12, dtype=np.float32).reshape(6, 2)
    with pytest.raises(IndexError, match="out of range"):
        nf.native_gather(a, np.array([0, 6], dtype=np.uint64))
