"""io tests (reference strategy: test/legacy_test/test_dataloader_*.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.io import (BatchSampler, ChainDataset, ComposeDataset,
                           ConcatDataset, DataLoader, Dataset,
                           DistributedBatchSampler, IterableDataset,
                           RandomSampler, SequenceSampler, Subset,
                           TensorDataset, WeightedRandomSampler,
                           random_split)


class RangeDataset(Dataset):
    def __init__(self, n):
        self.n = n

    def __getitem__(self, i):
        return np.float32(i), np.int64(i % 3)

    def __len__(self):
        return self.n


class StreamDataset(IterableDataset):
    def __init__(self, n):
        self.n = n

    def __iter__(self):
        for i in range(self.n):
            yield np.float32(i)


class TestDatasets:
    def test_tensor_dataset(self):
        x = paddle.to_tensor(np.arange(12).reshape(6, 2).astype(np.float32))
        y = paddle.to_tensor(np.arange(6))
        ds = TensorDataset([x, y])
        assert len(ds) == 6
        xi, yi = ds[2]
        np.testing.assert_allclose(xi.numpy(), [4, 5])

    def test_concat_and_subset(self):
        d = ConcatDataset([RangeDataset(3), RangeDataset(2)])
        assert len(d) == 5
        assert d[3][0] == 0.0
        s = Subset(RangeDataset(10), [5, 7])
        assert len(s) == 2 and s[1][0] == 7.0

    def test_compose(self):
        d = ComposeDataset([RangeDataset(3), RangeDataset(3)])
        assert len(d[0]) == 4

    def test_random_split(self):
        a, b = random_split(RangeDataset(10), [7, 3])
        assert len(a) == 7 and len(b) == 3
        all_idx = sorted([x[0] for x in a] + [x[0] for x in b])
        assert all_idx == [float(i) for i in range(10)]

    def test_chain(self):
        c = ChainDataset([StreamDataset(2), StreamDataset(3)])
        assert len(list(c)) == 5


class TestSamplers:
    def test_sequence(self):
        assert list(SequenceSampler(RangeDataset(4))) == [0, 1, 2, 3]

    def test_random_is_permutation(self):
        got = sorted(RandomSampler(RangeDataset(10)))
        assert got == list(range(10))

    def test_weighted(self):
        s = WeightedRandomSampler([0.0, 1.0, 0.0], num_samples=20)
        assert all(i == 1 for i in s)

    def test_batch_sampler(self):
        bs = BatchSampler(RangeDataset(10), batch_size=3)
        batches = list(bs)
        assert len(batches) == 4 and len(batches[-1]) == 1
        bs = BatchSampler(RangeDataset(10), batch_size=3, drop_last=True)
        assert len(list(bs)) == 3

    def test_distributed_batch_sampler_partitions(self):
        seen = []
        for rank in range(2):
            s = DistributedBatchSampler(RangeDataset(10), batch_size=2,
                                        num_replicas=2, rank=rank)
            for b in s:
                seen.extend(b)
        assert sorted(seen) == list(range(10))


class TestDataLoader:
    def test_basic_batching(self):
        dl = DataLoader(RangeDataset(10), batch_size=4)
        batches = list(dl)
        assert len(batches) == 3
        x, y = batches[0]
        assert x.shape == [4]
        np.testing.assert_allclose(x.numpy(), [0, 1, 2, 3])

    def test_shuffle_covers_all(self):
        dl = DataLoader(RangeDataset(20), batch_size=5, shuffle=True)
        seen = np.concatenate([b[0].numpy() for b in dl])
        assert sorted(seen.tolist()) == [float(i) for i in range(20)]

    def test_drop_last(self):
        dl = DataLoader(RangeDataset(10), batch_size=4, drop_last=True)
        assert len(list(dl)) == 2

    def test_num_workers_threaded(self):
        dl = DataLoader(RangeDataset(32), batch_size=4, num_workers=2)
        batches = list(dl)
        assert len(batches) == 8
        seen = np.concatenate([b[0].numpy() for b in batches])
        assert sorted(seen.tolist()) == [float(i) for i in range(32)]

    def test_iterable_dataset(self):
        dl = DataLoader(StreamDataset(7), batch_size=3)
        batches = list(dl)
        assert len(batches) == 3
        assert batches[-1].shape == [1]

    def test_dict_collate(self):
        class DictDS(Dataset):
            def __getitem__(self, i):
                return {"a": np.float32(i), "b": np.ones(2, np.float32)}

            def __len__(self):
                return 4
        dl = DataLoader(DictDS(), batch_size=2)
        b = next(iter(dl))
        assert b["a"].shape == [2] and b["b"].shape == [2, 2]

    def test_custom_collate(self):
        dl = DataLoader(RangeDataset(4), batch_size=2,
                        collate_fn=lambda b: len(b))
        assert list(dl) == [2, 2]

    def test_len(self):
        dl = DataLoader(RangeDataset(10), batch_size=3)
        assert len(dl) == 4
