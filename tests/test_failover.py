"""Exactly-once request failover (paddle_tpu/inference/failover.py).

The contract under test, mechanism by mechanism on stubs (no model,
no wall clock — the coordinator and breaker take injected time):

- admission journal: write-through publish on the name-keyed
  heartbeat transport, completion markers at retirement, bounded
  marker window, future-format refusal, honest degradation when the
  transport fails;
- exactly-once dedup: a rid carrying a completion marker in the
  crash-window payload is never re-dispatched;
- stranded-work re-dispatch: backoff scheduling in coordinator-clock
  seconds, bounded attempts ending in a typed terminal shed,
  ``retry_after_s`` hints clamped to the backoff cap, lineage in
  ``recovered_from``;
- poison quarantine: the attempt ladder AND the content-hash set (a
  retry under a fresh rid still hits it);
- circuit breakers: closed -> open on consecutive sheds -> half-open
  after cooldown -> single probe -> closed or reopened.

Plus the real-engine seam: journal round trip through submit/retire,
and the re-submission safety fix (per-run mutable state reset + the
pinned PRNG key making a resubmitted sampled request byte-identical).
"""
import numpy as np
import pytest

from paddle_tpu.distributed import heartbeat as hb
from paddle_tpu.inference import failover as fo


class _Req:
    """Duck-typed request: exactly the attributes the journal reads."""

    def __init__(self, rid, prompt=(1, 2, 3), max_new_tokens=4,
                 temperature=0.0, tenant="t0", priority=0,
                 deadline_s=None, prompt_spec=None, key=None):
        self.rid = rid
        self.prompt = np.asarray(prompt, np.int32)
        self.max_new_tokens = max_new_tokens
        self.temperature = temperature
        self.tenant = tenant
        self.priority = priority
        self.deadline_s = deadline_s
        self.prompt_spec = prompt_spec
        self.key = key


def _journal(tmp_path, replica="r0", **kw):
    return fo.AdmissionJournal(replica, dir_path=str(tmp_path), **kw)


def _coord(tmp_path, **kw):
    kw.setdefault("heartbeat_dir", str(tmp_path))
    return fo.FailoverCoordinator(**kw)


# ---------------------------------------------------------------------------
# admission journal
# ---------------------------------------------------------------------------

class TestAdmissionJournal:
    def test_round_trip_and_completion_marker(self, tmp_path):
        j = _journal(tmp_path)
        j.admit(_Req(7, prompt=(4, 5), max_new_tokens=6,
                     deadline_s=1.5, priority=2,
                     prompt_spec={"seed": 3, "rid": 7,
                                  "prompt_len": 2, "vocab": 32}))
        j.admit(_Req(8))
        payload = fo.read_journal("r0", dir_path=str(tmp_path))
        assert payload["kind"] == fo.JOURNAL_KIND
        assert set(payload["inflight"]) == {"7", "8"}
        rec = payload["inflight"]["7"]
        assert rec["tenant"] == "t0" and rec["priority"] == 2
        assert rec["deadline_s"] == 1.5
        assert rec["prompt_spec"]["seed"] == 3
        assert "prompt" not in rec          # spec replaces inline tokens
        assert rec["idem"] == f"7:{rec['fingerprint']}"
        # rid 8 has no spec: inline tokens journaled instead
        assert payload["inflight"]["8"]["prompt"] == [1, 2, 3]

        j.finish(7, "completed", tokens=6)
        payload = fo.read_journal("r0", dir_path=str(tmp_path))
        assert set(payload["inflight"]) == {"8"}
        marker = payload["completed"]["7"]
        assert marker["state"] == "completed" and marker["tokens"] == 6
        assert marker["idem"] == rec["idem"]

        fo.sweep_journal("r0", dir_path=str(tmp_path))
        assert fo.read_journal("r0", dir_path=str(tmp_path)) is None

    def test_fingerprint_is_content_keyed(self):
        a = fo.request_fingerprint(np.asarray([1, 2], np.int32), 4, 0.0)
        b = fo.request_fingerprint(np.asarray([1, 2], np.int32), 4, 0.0)
        c = fo.request_fingerprint(np.asarray([1, 3], np.int32), 4, 0.0)
        d = fo.request_fingerprint(np.asarray([1, 2], np.int32), 5, 0.0)
        assert a == b
        assert len({a, c, d}) == 3

    def test_completed_window_bounded(self, tmp_path):
        j = _journal(tmp_path, max_completed=3)
        for rid in range(6):
            j.admit(_Req(rid))
            j.finish(rid, "completed", tokens=1)
        assert list(j.completed) == ["3", "4", "5"]

    def test_future_version_refused(self, tmp_path):
        hb.publish_named(fo.journal_name("rz"),
                         {"kind": fo.JOURNAL_KIND, "v": 99,
                          "inflight": {}, "completed": {}},
                         dir_path=str(tmp_path))
        assert fo.read_journal("rz", dir_path=str(tmp_path)) is None

    def test_publish_failure_degrades_not_raises(self, tmp_path,
                                                 monkeypatch):
        j = _journal(tmp_path)

        def boom(*a, **k):
            raise OSError("transport down")

        monkeypatch.setattr(hb, "publish_named", boom)
        j.admit(_Req(1))            # must not raise
        j.finish(1, "completed")
        assert j.publish_failures == 2


# ---------------------------------------------------------------------------
# coordinator: strand / dedup / backoff / quarantine
# ---------------------------------------------------------------------------

class TestCoordinator:
    def test_strand_with_lineage_and_backoff(self, tmp_path):
        j = _journal(tmp_path, "victim")
        j.admit(_Req(3))
        c = _coord(tmp_path)
        assert c.note_replaced("victim", now=10.0) == 1
        assert c.counters["stranded"] == 1
        (rec,) = c.pending
        assert rec["recovered_from"] == ["victim"]
        assert rec["attempts"] == 1
        assert rec["not_before"] == pytest.approx(10.25)  # 0.25 * 2^0
        assert c.due(10.0) == [] and len(c.pending) == 1
        assert [r["rid"] for r in c.due(10.3)] == [3]
        assert c.outstanding() == 0
        # the consumed journal is swept: a second replace finds nothing
        assert c.note_replaced("victim", now=11.0) == 0

    def test_dedup_on_completion_marker(self, tmp_path):
        # crash-window overlap: the payload carries rid 5 in BOTH maps
        # (finished just before the crash, marker published, inflight
        # copy one event stale) — the marker wins, never re-served
        j = _journal(tmp_path, "victim")
        j.admit(_Req(5))
        j.admit(_Req(6))
        payload = fo.read_journal("victim", dir_path=str(tmp_path))
        payload["completed"]["5"] = {"state": "completed", "tokens": 4}
        hb.publish_named(fo.journal_name("victim"), payload,
                         dir_path=str(tmp_path))
        c = _coord(tmp_path)
        assert c.note_replaced("victim", now=0.0) == 1
        assert c.counters["deduped"] == 1
        assert [r["rid"] for r in c.pending] == [6]

    def test_quarantine_ladder_and_hash_set(self, tmp_path):
        c = _coord(tmp_path, quarantine_attempts=2)
        req = _Req(9, prompt=(7, 7, 7))
        _journal(tmp_path, "r0").admit(req)
        assert c.note_replaced("r0", now=0.0) == 1
        (rec,) = c.due(1.0)
        c.redispatched(rec, "r1", 1.0)
        # the survivor dies too, its journal carrying the same record
        j1 = fo.AdmissionJournal("r1", dir_path=str(tmp_path))
        j1.inflight["9"] = dict(rec)
        j1._publish()
        assert c.note_replaced("r1", now=2.0) == 1
        term = c.terminal[9]
        assert term["state"] == "quarantined"
        assert term["recovered_from"] == ["r0", "r1"]
        assert c.counters["quarantined"] == 1
        # content hash is poisoned: the SAME prompt under a fresh rid
        # quarantines immediately, without climbing the ladder
        fresh = _Req(55, prompt=(7, 7, 7))
        _journal(tmp_path, "r2").admit(fresh)
        c.note_replaced("r2", now=3.0)
        assert c.terminal[55]["state"] == "quarantined"
        assert c.counters["quarantined"] == 2

    def test_restrand_after_survivor_death(self, tmp_path):
        # a re-dispatched rid whose survivor dies is re-stranded from
        # the survivor's journal, not skipped as already-known
        c = _coord(tmp_path, quarantine_attempts=5)
        _journal(tmp_path, "r0").admit(_Req(1))
        c.note_replaced("r0", now=0.0)
        (rec,) = c.due(1.0)
        c.redispatched(rec, "r1", 1.0)
        j1 = fo.AdmissionJournal("r1", dir_path=str(tmp_path))
        j1.inflight["1"] = dict(rec)
        j1._publish()
        assert c.note_replaced("r1", now=2.0) == 1
        (again,) = c.pending
        assert again["attempts"] == 2
        assert again["recovered_from"] == ["r0", "r1"]

    def test_requeue_attempt_bound_and_hint_clamp(self, tmp_path):
        c = _coord(tmp_path, max_attempts=3, backoff_cap_s=5.0)
        rec = {"rid": 4, "attempts": 1, "tenant": "t0"}
        c.requeue(dict(rec), 0.0, retry_after_s=60.0)
        (q,) = c.pending
        assert q["not_before"] == pytest.approx(5.0)   # clamped to cap
        c.pending.clear()
        c.requeue(dict(rec, attempts=2), 0.0)          # hits the bound
        assert not c.pending
        assert c.terminal[4]["state"] == "shed"
        assert c.counters["shed"] == 1

    def test_resolve_expired_and_note_result(self, tmp_path):
        c = _coord(tmp_path)
        rec = {"rid": 2, "attempts": 1, "tenant": "t0"}
        c.resolve(dict(rec), "expired")
        assert c.terminal[2]["state"] == "expired"
        assert c.counters["expired"] == 1
        c.redispatched({"rid": 3, "attempts": 1}, "r0", 0.0)
        c.note_result(3, "completed")
        assert c.counters["recovered"] == 1
        c.note_result(3, "completed")            # idempotent
        assert c.counters["recovered"] == 1

    def test_snapshot_shape(self, tmp_path):
        c = _coord(tmp_path)
        c.resolve({"rid": 1, "attempts": 1}, "expired")
        c.admission_result("r0", False, 0.0)
        snap = c.snapshot()
        assert snap["terminal_by_state"] == {"expired": 1}
        assert snap["pending"] == 0
        assert snap["counters"]["expired"] == 1
        assert snap["breakers"]["r0"]["state"] == "closed"


# ---------------------------------------------------------------------------
# circuit breakers
# ---------------------------------------------------------------------------

class TestCircuitBreaker:
    def test_open_halfopen_close_cycle(self):
        b = fo.CircuitBreaker(threshold=3, cooldown_s=2.0)
        for _ in range(2):
            b.record(False, 0.0)
        assert b.state == "closed"
        b.record(True, 0.0)              # success resets the streak
        for _ in range(3):
            b.record(False, 1.0)
        assert b.state == "open" and b.opened_count == 1
        assert not b.allows(2.0)         # still inside the cooldown
        assert b.allows(3.0)             # cooldown elapsed -> half_open
        assert b.state == "half_open"
        b.note_probe()
        assert not b.allows(3.0)         # single probe in flight
        b.record(True, 3.1)
        assert b.state == "closed" and b.closed_count == 1

    def test_probe_failure_reopens(self):
        b = fo.CircuitBreaker(threshold=1, cooldown_s=1.0)
        b.record(False, 0.0)
        assert b.state == "open"
        assert b.allows(1.5)
        b.note_probe()
        b.record(False, 1.5)
        assert b.state == "open" and b.opened_count == 2
        assert not b.allows(2.0)
        assert b.allows(2.5)

    def test_pick_replica_routes_around_open_breaker(self, tmp_path):
        c = _coord(tmp_path, breaker_threshold=2,
                   breaker_cooldown_s=100.0)
        for _ in range(2):
            c.admission_result("r1", False, 0.0)
        assert c.breakers["r1"].state == "open"
        live = ["r0", "r1", "r2"]
        picks = {c.pick_replica(live, rid, now=1.0) for rid in range(6)}
        assert picks == {"r0", "r2"}

    def test_pick_replica_falls_back_when_all_open(self, tmp_path):
        c = _coord(tmp_path, breaker_threshold=1,
                   breaker_cooldown_s=100.0)
        for n in ("r0", "r1"):
            c.admission_result(n, False, 0.0)
        # routing away from everyone is routing to no one: fall back
        assert c.pick_replica(["r0", "r1"], 0, now=1.0) in ("r0", "r1")

    def test_replaced_replica_breaker_dropped(self, tmp_path):
        c = _coord(tmp_path, breaker_threshold=1)
        c.admission_result("victim", False, 0.0)
        assert "victim" in c.breakers
        c.note_replaced("victim", now=1.0)
        assert "victim" not in c.breakers


# ---------------------------------------------------------------------------
# monitor-plane surface
# ---------------------------------------------------------------------------

class TestFederationSurface:
    def test_fleet_serving_snapshot_failover_block(self, tmp_path):
        # the /fleet/serving payload grows a failover block only while
        # a coordinator is registered — absent otherwise, so flags-off
        # payloads are byte-identical
        from paddle_tpu.monitor import federation as fed
        c = _coord(tmp_path)
        c.resolve({"rid": 1, "attempts": 1}, "expired")
        fo.set_active_coordinator(c)
        try:
            snap = fed.fleet_serving_snapshot()
            assert snap["failover"]["terminal_by_state"] == {
                "expired": 1}
        finally:
            fo.set_active_coordinator(None)
        assert "failover" not in fed.fleet_serving_snapshot()

    def test_active_coordinator_is_weakref(self, tmp_path):
        import gc
        c = _coord(tmp_path)
        fo.set_active_coordinator(c)
        assert fo.active_coordinator() is c
        del c
        gc.collect()
        assert fo.active_coordinator() is None
        fo.set_active_coordinator(None)


# ---------------------------------------------------------------------------
# real-engine seam: journal wiring + re-submission safety
# ---------------------------------------------------------------------------

def _mk_engine(**kw):
    import jax
    from paddle_tpu.inference import ServingEngine
    from paddle_tpu.models import llama as L
    cfg = L.llama_tiny(num_hidden_layers=1)
    params = L.init_params(cfg, jax.random.PRNGKey(3))
    kw.setdefault("num_slots", 2)
    kw.setdefault("max_len", 24)
    kw.setdefault("page_size", 4)
    kw.setdefault("decode_chunk", 2)
    return ServingEngine(L, params, cfg, **kw)


def _drain(eng, limit=200):
    for _ in range(limit):
        if not eng.step():
            return
    raise AssertionError("engine did not go idle")


@pytest.mark.serving
class TestEngineJournalSeam:
    def test_submit_journals_and_retire_markers(self, tmp_path):
        from paddle_tpu.inference.engine import Request
        eng = _mk_engine(failover=True)
        assert eng.attach_journal("rA", str(tmp_path)) is not None
        eng.submit(Request(rid=0, prompt=np.arange(1, 5, dtype=np.int32),
                           max_new_tokens=3, tenant="t0"))
        payload = fo.read_journal("rA", dir_path=str(tmp_path))
        assert set(payload["inflight"]) == {"0"}
        _drain(eng)
        payload = fo.read_journal("rA", dir_path=str(tmp_path))
        assert payload["inflight"] == {}
        marker = payload["completed"]["0"]
        assert marker["state"] == "completed"
        assert marker["tokens"] == len(eng.outputs[0].tokens)

    def test_flags_off_attach_is_noop(self, tmp_path):
        eng = _mk_engine()                      # failover defaults off
        assert eng._failover is False
        assert eng.attach_journal("rB", str(tmp_path)) is None
        assert eng._journal is None

    def test_resubmission_resets_state_and_pins_tokens(self, tmp_path):
        # satellite contract: a Request object re-admitted after a
        # strand starts clean (timing/cost/preemption state reset) and
        # — because submit pinned the sampling key on first admission —
        # replays byte-identical tokens on the survivor
        from paddle_tpu.inference.engine import Request
        a = _mk_engine(failover=True)
        a.attach_journal("rA", str(tmp_path))
        req = Request(rid=1, prompt=np.arange(1, 6, dtype=np.int32),
                      max_new_tokens=4, temperature=0.8)
        assert req.key is None
        a.submit(req)
        assert req.key is not None              # pinned at admission
        key0 = np.asarray(req.key).copy()
        _drain(a)
        first = list(a.outputs[1].tokens)
        # simulate the state a monitored/preempted run leaves behind
        # (the timing anchors are only stamped with the monitor on)
        req._t0 = 123.0
        req._t_enqueue = 124.0
        req._cost = object()
        req._t_deadline = 125.0
        req._preempt_count = 2

        b = _mk_engine(failover=True)
        b.submit(req)                           # re-admission resets
        assert req._t0 is None and req._cost is None
        assert req._t_enqueue is None and req._t_deadline is None
        assert req._preempt_count == 0
        np.testing.assert_array_equal(np.asarray(req.key), key0)
        _drain(b)
        assert list(b.outputs[1].tokens) == first
