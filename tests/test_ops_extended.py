"""Extended op-surface parity tests (round-3 breadth: linalg decompositions,
fft, math/manipulation long tail, inplace variants).

Methodology mirrors the reference's OpTest (test/legacy_test/op_test.py:418):
numpy forward reference + analytic-vs-finite-difference grad checks on a
representative differentiable subset + dtype checks.
"""
import numpy as np
import pytest

import paddle_tpu as pt

RNG = np.random.default_rng(11)


def t(a, sg=True):
    return pt.to_tensor(np.asarray(a), stop_gradient=sg)


def rand(*shape, dtype="float32"):
    return RNG.standard_normal(shape).astype(dtype)


# ---------------------------------------------------------------------------
# linalg decompositions
# ---------------------------------------------------------------------------
class TestLinalgDecomp:
    def test_svd_reconstructs(self):
        a = rand(3, 5, 4)
        u, s, vh = pt.linalg.svd(t(a))
        rec = np.asarray(u.numpy()) @ (
            s.numpy()[..., :, None] * np.asarray(vh.numpy()))
        np.testing.assert_allclose(rec, a, atol=1e-4)

    def test_svd_full_matrices(self):
        a = rand(5, 3)
        u, s, vh = pt.linalg.svd(t(a), full_matrices=True)
        assert u.shape == [5, 5] and vh.shape == [3, 3]

    def test_qr(self):
        a = rand(6, 4)
        q, r = pt.linalg.qr(t(a))
        np.testing.assert_allclose(q.numpy() @ r.numpy(), a, atol=1e-4)
        np.testing.assert_allclose(q.numpy().T @ q.numpy(), np.eye(4),
                                   atol=1e-4)
        r_only = pt.linalg.qr(t(a), mode="r")
        np.testing.assert_allclose(np.abs(r_only.numpy()), np.abs(r.numpy()),
                                   atol=1e-4)

    def test_eigh_eigvalsh(self):
        a = rand(4, 4)
        sym = (a + a.T) / 2
        w, v = pt.linalg.eigh(t(sym))
        np.testing.assert_allclose(
            v.numpy() @ np.diag(w.numpy()) @ v.numpy().T, sym, atol=1e-4)
        np.testing.assert_allclose(pt.linalg.eigvalsh(t(sym)).numpy(),
                                   w.numpy(), atol=1e-5)

    def test_eig_eigvals(self):
        a = rand(4, 4)
        w, v = pt.linalg.eig(t(a))
        wv = pt.linalg.eigvals(t(a))
        np.testing.assert_allclose(sorted(np.asarray(w.numpy()).real),
                                   sorted(np.asarray(wv.numpy()).real),
                                   atol=1e-4)

    def test_lu_roundtrip(self):
        a = rand(4, 4) + 4 * np.eye(4, dtype="float32")
        lu_mat, piv = pt.linalg.lu(t(a))
        p, l, u = pt.linalg.lu_unpack(lu_mat, piv)
        np.testing.assert_allclose(p.numpy() @ l.numpy() @ u.numpy(), a,
                                   atol=1e-4)

    def test_householder_product_vs_scipy(self):
        import scipy.linalg as sla
        a = rand(5, 3).astype("float64")
        (qr_raw, tau), _r = sla.qr(a, mode="raw")
        q_expect = sla.qr(a)[0][:, :3]
        q = pt.linalg.householder_product(
            t(np.asarray(qr_raw).astype("float32")),
            t(tau.astype("float32")))
        np.testing.assert_allclose(q.numpy(), q_expect, atol=1e-4)

    def test_lstsq(self):
        a = rand(6, 3)
        b = rand(6, 2)
        sol, res, rank_, sv = pt.linalg.lstsq(t(a), t(b))
        expect, *_ = np.linalg.lstsq(a, b, rcond=None)
        np.testing.assert_allclose(sol.numpy(), expect, atol=1e-4)
        assert int(rank_.numpy()) == 3

    def test_cond_cov_corrcoef(self):
        a = rand(4, 4) + 3 * np.eye(4, dtype="float32")
        np.testing.assert_allclose(pt.linalg.cond(t(a)).numpy(),
                                   np.linalg.cond(a), rtol=1e-3)
        x = rand(3, 20)
        np.testing.assert_allclose(pt.linalg.cov(t(x)).numpy(), np.cov(x),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(pt.linalg.corrcoef(t(x)).numpy(),
                                   np.corrcoef(x), rtol=1e-4, atol=1e-5)

    def test_cdist_dist_mv(self):
        x, y = rand(3, 4), rand(5, 4)
        expect = np.sqrt(((x[:, None, :] - y[None, :, :]) ** 2).sum(-1))
        np.testing.assert_allclose(pt.cdist(t(x), t(y)).numpy(), expect,
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(
            pt.dist(t(x[0]), t(x[1]), p=3).numpy(),
            (np.abs(x[0] - x[1]) ** 3).sum() ** (1 / 3), rtol=1e-4)
        m, v = rand(3, 4), rand(4)
        np.testing.assert_allclose(pt.mv(t(m), t(v)).numpy(), m @ v,
                                   rtol=1e-5)

    def test_svd_grad(self):
        a = rand(4, 3)
        x = t(a, sg=False)
        u, s, vh = pt.linalg.svd(x)
        s.sum().backward()
        eps = 1e-3
        g = np.zeros_like(a)
        for i in range(4):
            for j in range(3):
                ap, am = a.copy(), a.copy()
                ap[i, j] += eps
                am[i, j] -= eps
                g[i, j] = (np.linalg.svd(ap, compute_uv=False).sum()
                           - np.linalg.svd(am, compute_uv=False).sum()) / (2 * eps)
        np.testing.assert_allclose(x.grad.numpy(), g, atol=1e-2)

    def test_lowrank(self):
        a = (rand(8, 3) @ rand(3, 6))
        u, s, v = pt.linalg.svd_lowrank(t(a), q=3)
        rec = u.numpy() @ np.diag(s.numpy()) @ v.numpy().T
        np.testing.assert_allclose(rec, a, atol=1e-3)
        u2, s2, v2 = pt.linalg.pca_lowrank(t(a), q=3)
        assert s2.shape == [3]

    def test_addmm_vander_matrix_transpose(self):
        i, x, y = rand(3, 4), rand(3, 5), rand(5, 4)
        np.testing.assert_allclose(
            pt.addmm(t(i), t(x), t(y), beta=0.5, alpha=2.0).numpy(),
            0.5 * i + 2.0 * (x @ y), rtol=1e-4, atol=1e-5)
        v = rand(4)
        np.testing.assert_allclose(pt.vander(t(v), n=3).numpy(),
                                   np.vander(v, 3), rtol=1e-5)
        m = rand(2, 3, 4)
        np.testing.assert_allclose(pt.matrix_transpose(t(m)).numpy(),
                                   np.swapaxes(m, -1, -2))

    def test_ormqr(self):
        import scipy.linalg as sla
        a = rand(4, 4).astype("float64")
        (qr_raw, tau), _r = sla.qr(a, mode="raw")
        q_full = sla.qr(a)[0]
        other = rand(4, 2)
        out = pt.linalg.ormqr(t(np.asarray(qr_raw).astype("float32")),
                              t(tau.astype("float32")), t(other))
        np.testing.assert_allclose(out.numpy(), q_full @ other, atol=1e-4)
        out_t = pt.linalg.ormqr(t(np.asarray(qr_raw).astype("float32")),
                                t(tau.astype("float32")), t(other),
                                transpose=True)
        np.testing.assert_allclose(out_t.numpy(), q_full.T @ other,
                                   atol=1e-4)


# ---------------------------------------------------------------------------
# fft
# ---------------------------------------------------------------------------
class TestFFT:
    @pytest.mark.parametrize("norm", ["backward", "ortho", "forward"])
    def test_fft_ifft_roundtrip(self, norm):
        x = rand(3, 16)
        f = pt.fft.fft(t(x), norm=norm)
        back = pt.fft.ifft(f, norm=norm)
        np.testing.assert_allclose(np.asarray(back.numpy()).real, x,
                                   atol=1e-4)
        np.testing.assert_allclose(f.numpy(), np.fft.fft(x, norm=norm),
                                   rtol=1e-4, atol=1e-4)

    def test_rfft_irfft(self):
        x = rand(4, 16)
        f = pt.fft.rfft(t(x))
        assert f.shape == [4, 9]
        np.testing.assert_allclose(pt.fft.irfft(f, n=16).numpy(), x,
                                   atol=1e-4)

    def test_fft2_fftn(self):
        x = rand(2, 8, 8)
        np.testing.assert_allclose(pt.fft.fft2(t(x)).numpy(),
                                   np.fft.fft2(x), rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(pt.fft.fftn(t(x)).numpy(),
                                   np.fft.fftn(x), rtol=1e-4, atol=1e-3)

    def test_hfft_ihfft(self):
        x = rand(16)
        np.testing.assert_allclose(pt.fft.hfft(t(x.astype("complex64"))).numpy(),
                                   np.fft.hfft(x), rtol=1e-3, atol=1e-3)
        ih = pt.fft.ihfft(t(x))
        np.testing.assert_allclose(ih.numpy(), np.fft.ihfft(x), rtol=1e-4,
                                   atol=1e-5)

    def test_shift_freq(self):
        x = rand(9)
        np.testing.assert_allclose(pt.fft.fftshift(t(x)).numpy(),
                                   np.fft.fftshift(x))
        np.testing.assert_allclose(pt.fft.ifftshift(t(x)).numpy(),
                                   np.fft.ifftshift(x))
        np.testing.assert_allclose(pt.fft.fftfreq(8, d=0.5).numpy(),
                                   np.fft.fftfreq(8, d=0.5), rtol=1e-6)
        np.testing.assert_allclose(pt.fft.rfftfreq(8, d=0.5).numpy(),
                                   np.fft.rfftfreq(8, d=0.5), rtol=1e-6)

    def test_fft_grad(self):
        x = rand(8)
        xt = t(x, sg=False)
        y = pt.as_real(pt.fft.fft(xt)).sum()
        y.backward()
        assert np.isfinite(xt.grad.numpy()).all()

    def test_stft_istft_roundtrip(self):
        x = rand(2, 256)
        win = np.hanning(64).astype("float32")
        spec = pt.stft(t(x), n_fft=64, hop_length=16, window=t(win))
        assert spec.shape == [2, 33, 17]   # center pads n_fft//2 each side
        rec = pt.istft(spec, n_fft=64, hop_length=16, window=t(win),
                       length=256)
        # overlap-add reconstruction is exact away from the edges
        np.testing.assert_allclose(rec.numpy()[:, 32:-32], x[:, 32:-32],
                                   atol=1e-3)


# ---------------------------------------------------------------------------
# math long tail
# ---------------------------------------------------------------------------
class TestMathExt:
    @pytest.mark.parametrize("name,np_fn,args", [
        ("copysign", np.copysign, 2),
        ("nextafter", np.nextafter, 2),
        ("sinc", np.sinc, 1),
        ("signbit", np.signbit, 1),
        ("neg", lambda x: -x, 1),
    ])
    def test_elementwise_parity(self, name, np_fn, args):
        xs = [rand(3, 4) for _ in range(args)]
        got = getattr(pt, name)(*[t(x) for x in xs]).numpy()
        np.testing.assert_allclose(got, np_fn(*xs), rtol=1e-5, atol=1e-6)

    def test_bessel(self):
        import scipy.special as sp
        x = np.abs(rand(20)) * 3
        for name, ref in [("i0", sp.i0), ("i0e", sp.i0e),
                          ("i1", sp.i1), ("i1e", sp.i1e)]:
            np.testing.assert_allclose(getattr(pt, name)(t(x)).numpy(),
                                       ref(x), rtol=1e-4, atol=1e-5)

    def test_gamma_family(self):
        import scipy.special as sp
        x = np.abs(rand(10)) * 2 + 0.5
        y = np.abs(rand(10)) * 2 + 0.5
        np.testing.assert_allclose(pt.gammaln(t(x)).numpy(), sp.gammaln(x),
                                   rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(pt.gammainc(t(x), t(y)).numpy(),
                                   sp.gammainc(x, y), rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(pt.gammaincc(t(x), t(y)).numpy(),
                                   sp.gammaincc(x, y), rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(pt.multigammaln(t(x), 2).numpy(),
                                   sp.multigammaln(x, 2), rtol=1e-4,
                                   atol=1e-4)

    def test_cumulative(self):
        x = rand(4, 6)
        np.testing.assert_allclose(
            pt.logcumsumexp(t(x), axis=1).numpy(),
            np.logaddexp.accumulate(x, axis=1), rtol=1e-4, atol=1e-5)
        vals, idx = pt.cummax(t(x), axis=1)
        np.testing.assert_allclose(vals.numpy(),
                                   np.maximum.accumulate(x, axis=1))
        picked = np.take_along_axis(x, np.asarray(idx.numpy(), "int64"),
                                    axis=1)
        np.testing.assert_allclose(picked, vals.numpy())
        vals2, idx2 = pt.cummin(t(x), axis=1)
        np.testing.assert_allclose(vals2.numpy(),
                                   np.minimum.accumulate(x, axis=1))

    def test_nan_aggregates(self):
        x = rand(4, 6)
        x[1, 2] = np.nan
        x[3, 0] = np.nan
        np.testing.assert_allclose(pt.nanmedian(t(x), axis=1).numpy(),
                                   np.nanmedian(x, axis=1), rtol=1e-5)
        np.testing.assert_allclose(
            pt.nanquantile(t(x), 0.3, axis=0).numpy(),
            np.nanquantile(x, 0.3, axis=0), rtol=1e-4, atol=1e-5)

    def test_shifts_bucketize(self):
        a = np.array([1, 2, 4, 8], "int32")
        np.testing.assert_array_equal(
            pt.bitwise_left_shift(t(a), t(np.array([1, 1, 2, 2], "int32"))).numpy(),
            np.left_shift(a, [1, 1, 2, 2]))
        np.testing.assert_array_equal(
            pt.bitwise_right_shift(t(a), t(np.array([1, 1, 2, 2], "int32"))).numpy(),
            np.right_shift(a, [1, 1, 2, 2]))
        edges = np.array([0.0, 1.0, 2.0, 3.0], "float32")
        x = np.array([[-0.5, 0.5], [1.5, 2.5]], "float32")
        np.testing.assert_array_equal(
            pt.bucketize(t(x), t(edges)).numpy(),
            np.searchsorted(edges, x, side="left"))

    def test_diff_trapezoid(self):
        x = rand(3, 8)
        np.testing.assert_allclose(pt.diff(t(x), axis=1).numpy(),
                                   np.diff(x, axis=1), rtol=1e-6)
        np.testing.assert_allclose(pt.diff(t(x), n=2, axis=1).numpy(),
                                   np.diff(x, n=2, axis=1), rtol=1e-5,
                                   atol=1e-6)
        y = rand(8)
        import scipy.integrate as si
        np.testing.assert_allclose(
            pt.cumulative_trapezoid(t(y)).numpy(),
            si.cumulative_trapezoid(y), rtol=1e-4, atol=1e-5)

    def test_frexp_remainder(self):
        x = rand(10) * 10
        m, e = pt.frexp(t(x))
        me, ee = np.frexp(x)
        np.testing.assert_allclose(m.numpy(), me, rtol=1e-6)
        np.testing.assert_array_equal(e.numpy(), ee)
        a, b = rand(6) * 5, np.abs(rand(6)) + 0.5
        np.testing.assert_allclose(pt.remainder(t(a), t(b)).numpy(),
                                   np.mod(a, b), rtol=1e-4, atol=1e-5)

    def test_renorm(self):
        x = rand(3, 4, 5)
        out = pt.renorm(t(x), p=2.0, axis=0, max_norm=1.0).numpy()
        for i in range(3):
            assert np.linalg.norm(out[i].ravel()) <= 1.0 + 1e-4

    def test_multiplex_polar(self):
        a, b = rand(4, 3), rand(4, 3)
        idx = np.array([[0], [1], [0], [1]], "int32")
        out = pt.multiplex([t(a), t(b)], t(idx)).numpy()
        expect = np.where(idx == 0, a, b)
        np.testing.assert_allclose(out, expect)
        mag, ang = np.abs(rand(5)), rand(5)
        z = pt.polar(t(mag), t(ang)).numpy()
        np.testing.assert_allclose(z, mag * np.exp(1j * ang), rtol=1e-5,
                                   atol=1e-6)

    def test_reduce_as_take(self):
        x = rand(4, 5)
        tgt = rand(1, 5)
        np.testing.assert_allclose(pt.reduce_as(t(x), t(tgt)).numpy(),
                                   x.sum(0, keepdims=True), rtol=1e-5)
        idx = np.array([0, 3, -1], "int64")
        np.testing.assert_allclose(pt.take(t(x), t(idx)).numpy(),
                                   np.take(x, idx), rtol=1e-6)

    def test_type_predicates(self):
        assert pt.is_floating_point(t(rand(2)))
        assert not pt.is_integer(t(rand(2)))
        assert pt.is_complex(pt.as_complex(t(rand(2, 2))))
        x = np.array([np.inf, -np.inf, 1.0], "float32")
        np.testing.assert_array_equal(pt.isposinf(t(x)).numpy(),
                                      np.isposinf(x))
        np.testing.assert_array_equal(pt.isneginf(t(x)).numpy(),
                                      np.isneginf(x))

    def test_grad_check_math_ext(self):
        """finite-difference grad parity for differentiable new ops."""
        cases = [
            (lambda x: pt.sinc(x), rand(6) + 0.1),
            (lambda x: pt.i0(x), np.abs(rand(6)) + 0.2),
            (lambda x: pt.gammaln(x), np.abs(rand(6)) + 0.7),
            (lambda x: pt.logcumsumexp(x, axis=0), rand(6)),
            (lambda x: pt.renorm(x, 2.0, 0, 1.0), rand(3, 4)),
            (lambda x: pt.diff(x, axis=0), rand(6)),
        ]
        for fn, xn in cases:
            xt = t(xn, sg=False)
            fn(xt).sum().backward()
            g = xt.grad.numpy()
            eps = 1e-3
            fd = np.zeros_like(xn)
            flat, fdf = xn.reshape(-1), fd.reshape(-1)
            for i in range(flat.size):
                o = flat[i]
                flat[i] = o + eps
                fp = float(fn(t(xn.copy().reshape(xn.shape))).sum().numpy())
                flat[i] = o - eps
                fm = float(fn(t(xn.copy().reshape(xn.shape))).sum().numpy())
                flat[i] = o
                fdf[i] = (fp - fm) / (2 * eps)
            np.testing.assert_allclose(g, fd, rtol=5e-2, atol=5e-3)


# ---------------------------------------------------------------------------
# manipulation long tail
# ---------------------------------------------------------------------------
class TestManipulationExt:
    def test_atleast(self):
        assert pt.atleast_1d(t(np.float32(3.0))).shape == [1]
        assert pt.atleast_2d(t(rand(4))).shape == [1, 4]
        assert pt.atleast_3d(t(rand(2, 3))).shape == [2, 3, 1]

    def test_splits(self):
        x = rand(6, 4, 2)
        parts = pt.tensor_split(t(x), 4, axis=0)
        np.testing.assert_allclose(np.concatenate([p.numpy() for p in parts]),
                                   x)
        assert [p.shape[0] for p in parts] == [2, 2, 1, 1]
        v = pt.vsplit(t(x), 2)
        assert v[0].shape == [3, 4, 2]
        h = pt.hsplit(t(x), 2)
        assert h[0].shape == [6, 2, 2]
        d = pt.dsplit(t(x), 2)
        assert d[0].shape == [6, 4, 1]

    def test_scatter_family(self):
        x = rand(4, 5)
        val = rand(5)
        out = pt.select_scatter(t(x), t(val), axis=0, index=2).numpy()
        expect = x.copy()
        expect[2] = val
        np.testing.assert_allclose(out, expect)

        y = rand(2, 5)
        out2 = pt.slice_scatter(t(x), t(y), axes=[0], starts=[1], ends=[3],
                                strides=[1]).numpy()
        expect2 = x.copy()
        expect2[1:3] = y
        np.testing.assert_allclose(out2, expect2)

        d = rand(4)
        out3 = pt.diagonal_scatter(t(x[:, :4]), t(d)).numpy()
        expect3 = x[:, :4].copy()
        np.fill_diagonal(expect3, d)
        np.testing.assert_allclose(out3, expect3)

    def test_index_ops(self):
        x = rand(4, 5)
        out = pt.index_fill(t(x), t(np.array([0, 2], "int64")), 0, 9.0).numpy()
        expect = x.copy()
        expect[[0, 2]] = 9.0
        np.testing.assert_allclose(out, expect)

        idx = np.array([[0, 2], [1, 3]], "int64")
        x2 = rand(2, 5)
        np.testing.assert_allclose(
            pt.index_sample(t(x2), t(idx)).numpy(),
            np.take_along_axis(x2, idx, axis=1))

    def test_masked_scatter(self):
        x = rand(3, 4)
        mask = x > 0
        vals = rand(12)
        out = pt.masked_scatter(t(x), t(mask), t(vals)).numpy()
        expect = x.copy()
        expect[mask] = vals[:mask.sum()]
        np.testing.assert_allclose(out, expect)

    def test_strided_views(self):
        x = rand(4, 6)
        out = pt.as_strided(t(x), [2, 3], [6, 2]).numpy()
        expect = np.lib.stride_tricks.as_strided(
            x, (2, 3), (6 * 4, 2 * 4))
        np.testing.assert_allclose(out, expect)
        np.testing.assert_allclose(pt.view(t(x), [3, 8]).numpy(),
                                   x.reshape(3, 8))
        np.testing.assert_allclose(pt.view_as(t(x), t(rand(24))).numpy(),
                                   x.reshape(-1))
        np.testing.assert_allclose(
            pt.unflatten(t(x), 1, [2, 3]).numpy(), x.reshape(4, 2, 3))
        np.testing.assert_allclose(
            pt.slice(t(x), [0, 1], [1, 2], [3, 5]).numpy(), x[1:3, 2:5])
        np.testing.assert_allclose(
            pt.strided_slice(t(x), [1], [0], [6], [2]).numpy(), x[:, 0:6:2])

    def test_unfold_tensor(self):
        x = rand(8)
        out = pt.unfold(t(x), 0, 4, 2).numpy()
        expect = np.stack([x[0:4], x[2:6], x[4:8]])
        np.testing.assert_allclose(out, expect)

    def test_unique_consecutive(self):
        x = np.array([1, 1, 2, 2, 3, 1, 1], "int32")
        out, inv, cnt = pt.unique_consecutive(t(x), return_inverse=True,
                                              return_counts=True)
        np.testing.assert_array_equal(out.numpy(), [1, 2, 3, 1])
        np.testing.assert_array_equal(cnt.numpy(), [2, 2, 1, 2])

    def test_kthvalue_mode(self):
        x = rand(3, 7)
        v, i = pt.kthvalue(t(x), 3, axis=1)
        np.testing.assert_allclose(v.numpy(), np.sort(x, axis=1)[:, 2],
                                   rtol=1e-6)
        picked = np.take_along_axis(x, np.asarray(i.numpy())[:, None], 1)[:, 0]
        np.testing.assert_allclose(picked, v.numpy(), rtol=1e-6)

        m = np.array([[1, 2, 2, 3], [4, 4, 5, 6]], "float32")
        mv, mi = pt.mode(t(m))
        np.testing.assert_allclose(mv.numpy(), [2.0, 4.0])

    def test_diag_embed(self):
        d = rand(3, 4)
        out = pt.diag_embed(t(d)).numpy()
        assert out.shape == (3, 4, 4)
        for b in range(3):
            np.testing.assert_allclose(np.diag(out[b]), d[b])
        out2 = pt.diag_embed(t(d), offset=1).numpy()
        assert out2.shape == (3, 5, 5)

    def test_broadcast_misc(self):
        a, b = rand(3, 1), rand(1, 4)
        outs = pt.broadcast_tensors([t(a), t(b)])
        assert outs[0].shape == [3, 4] and outs[1].shape == [3, 4]
        assert pt.broadcast_shape([3, 1], [1, 4]) == [3, 4]
        assert not bool(pt.is_empty(t(rand(2))).numpy())

    def test_shard_index(self):
        x = np.array([[1], [6], [12], [19]], "int64")
        out = pt.shard_index(t(x), index_num=20, nshards=2, shard_id=0).numpy()
        np.testing.assert_array_equal(out, [[1], [6], [-1], [-1]])

    def test_top_p_sampling(self):
        logits = np.array([[10.0, 1.0, 0.5, 0.1]], "float32")
        ps = np.array([0.3], "float32")
        vals, ids = pt.top_p_sampling(t(logits), t(ps))
        assert int(ids.numpy()[0, 0]) == 0   # nucleus contains only argmax

    def test_grad_flow_manipulation(self):
        x = t(rand(4, 5), sg=False)
        y = pt.slice_scatter(x, t(rand(2, 5)), axes=[0], starts=[1],
                             ends=[3], strides=[1])
        y.sum().backward()
        g = x.grad.numpy()
        np.testing.assert_allclose(g[0], np.ones(5))
        np.testing.assert_allclose(g[1], np.zeros(5))


# ---------------------------------------------------------------------------
# inplace variants
# ---------------------------------------------------------------------------
class TestInplace:
    def test_basic_inplace(self):
        x = t(np.array([1.0, 4.0, 9.0], "float32"))
        r = x.sqrt_()
        assert r is x
        np.testing.assert_allclose(x.numpy(), [1.0, 2.0, 3.0], rtol=1e-6)

    def test_functional_inplace(self):
        x = t(rand(3, 3))
        orig = x.numpy().copy()
        pt.exp_(x)
        np.testing.assert_allclose(x.numpy(), np.exp(orig), rtol=1e-5)

    def test_inplace_grad_adoption(self):
        x = t(np.array([2.0], "float32"), sg=False)
        y = x * 3.0
        y.tanh_()        # y becomes tanh(3x) but keeps its place in the graph
        y.backward()
        expect = 3.0 * (1 - np.tanh(6.0) ** 2)
        np.testing.assert_allclose(x.grad.numpy(), [expect], rtol=1e-2)

    def test_random_fills(self):
        x = t(np.zeros((100,), "float32"))
        x.uniform_(0.0, 1.0)
        assert 0 <= float(x.numpy().min()) and float(x.numpy().max()) <= 1
        x.normal_(5.0, 0.1)
        assert 4 < float(x.numpy().mean()) < 6
        x.cauchy_()
        assert np.isfinite(x.numpy()).any()
        x.geometric_(0.5)
        assert float(x.numpy().min()) >= 1.0

    def test_cast_transpose_inplace(self):
        x = t(rand(3, 4))
        x.cast_("float16")
        assert "float16" in str(x.dtype)
        x2 = t(rand(3, 4))
        x2.transpose_([1, 0])
        assert x2.shape == [4, 3]
        x3 = t(rand(3, 4))
        x3.t_()
        assert x3.shape == [4, 3]

    def test_create_parameter_tensor(self):
        p = pt.create_parameter([4, 3], "float32")
        assert p.shape == [4, 3] and not p.stop_gradient
        b = pt.create_parameter([3], "float32", is_bias=True)
        np.testing.assert_allclose(b.numpy(), np.zeros(3))
        ct = pt.create_tensor("float32")
        assert ct.numpy().dtype == np.float32


class TestInplaceRegressions:
    def test_index_fill_inplace_grads(self):
        """index_fill_ participates in autograd via node adoption."""
        w = t(np.ones(4, "float32"), sg=False)
        h = w * 2.0
        h.index_fill_(t(np.array([1, 3], "int64")), 0, 0.0)
        h.sum().backward()
        np.testing.assert_allclose(w.grad.numpy(), [2.0, 0.0, 2.0, 0.0])

    def test_inplace_under_no_grad_poisons_graph(self):
        x = t(np.array([2.0], "float32"), sg=False)
        y = x * 3.0
        with pt.no_grad():
            y.scale_(2.0)
        with pytest.raises(RuntimeError, match="in-place"):
            y.backward()

    def test_param_inplace_under_no_grad_ok(self):
        """Leaf (parameter) in-place updates under no_grad stay legal —
        the optimizer pattern."""
        p = t(np.ones(3, "float32"), sg=False)
        with pt.no_grad():
            p.add_(t(np.ones(3, "float32")))
        (p * 2.0).sum().backward()
        np.testing.assert_allclose(p.grad.numpy(), [2.0] * 3)

    def test_ormqr_batched(self):
        import scipy.linalg as sla
        outs, expects = [], []
        raws, taus, others = [], [], []
        for b in range(3):
            a = RNG.standard_normal((4, 4))
            (qr_raw, tau), _r = sla.qr(a, mode="raw")
            q = sla.qr(a)[0]
            o = RNG.standard_normal((4, 2)).astype("float32")
            raws.append(np.asarray(qr_raw).astype("float32"))
            taus.append(tau.astype("float32"))
            others.append(o)
            expects.append(q @ o)
        out = pt.linalg.ormqr(t(np.stack(raws)), t(np.stack(taus)),
                              t(np.stack(others)))
        np.testing.assert_allclose(out.numpy(), np.stack(expects),
                                   atol=1e-4)

    def test_where_inplace_targets_x(self):
        cond = t(np.array([True, False, True]))
        x = t(np.array([1.0, 2.0, 3.0], "float32"))
        y = t(np.array([9.0, 9.0, 9.0], "float32"))
        r = pt.where_(cond, x, y)
        assert r is x
        np.testing.assert_allclose(x.numpy(), [1.0, 9.0, 3.0])
        np.testing.assert_array_equal(np.asarray(cond.numpy()),
                                      [True, False, True])
