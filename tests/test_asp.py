"""incubate.asp (2:4 structured sparsity) — mask math, prune_model,
decorate training guarantee. Reference: python/paddle/incubate/asp/."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.incubate import asp
from paddle_tpu.incubate.asp import utils as au


class TestMaskMath:
    def test_mask_1d_keeps_top_magnitudes(self):
        mat = np.array([[1.0, -5.0, 2.0, 0.5, 9.0, 0.1, -0.2, 3.0]])
        mask = au.get_mask_1d(mat, 2, 4)
        np.testing.assert_array_equal(
            mask, [[0, 1, 1, 0, 1, 0, 0, 1]])
        assert au.check_mask_1d(mat * mask, 2, 4)
        assert not au.check_mask_1d(mat, 2, 4)

    def test_mask_1d_density_exact(self):
        rng = np.random.default_rng(0)
        mat = rng.normal(size=(32, 64)).astype(np.float32)
        mask = au.get_mask_1d(mat, 2, 4)
        assert au.calculate_density(mat * mask) == pytest.approx(0.5)

    def test_valid_2d_pattern_count_2_4(self):
        # combinatorics: 4x4 0/1 matrices with exactly two 1s per row and
        # column = permanent of all-ones config = 90
        assert len(au._valid_2d_patterns(2, 4)) == 90

    def test_mask_2d_best_valid_and_better_than_greedy(self):
        rng = np.random.default_rng(1)
        mat = rng.normal(size=(16, 16)).astype(np.float32)
        best = au.get_mask_2d_best(mat, 2, 4)
        greedy = au.get_mask_2d_greedy(mat, 2, 4)
        assert au.check_mask_2d(mat * best, 2, 4)
        assert au.check_mask_2d(mat * greedy, 2, 4)
        assert np.abs(mat * best).sum() >= np.abs(mat * greedy).sum() - 1e-6

    def test_mask_2d_rejects_1d_violations_pattern(self):
        # a matrix whose 4x4 tile has a column of 4 large values: 2D mask
        # must keep only 2 of them
        mat = np.zeros((4, 4), np.float32)
        mat[:, 0] = [9, 8, 7, 6]
        mask = au.get_mask_2d_best(mat, 2, 4)
        assert mask[:, 0].sum() == 2

    def test_non_divisible_shapes(self):
        rng = np.random.default_rng(2)
        mat = rng.normal(size=(5, 7)).astype(np.float32)
        m1 = au.get_mask_1d(mat, 2, 4)
        assert m1.shape == mat.shape
        m2 = au.get_mask_2d_greedy(mat, 2, 4)
        assert m2.shape == mat.shape

    def test_create_mask_conv_kernel(self):
        rng = np.random.default_rng(3)
        w = rng.normal(size=(8, 4, 3, 3)).astype(np.float32)
        mask = au.create_mask(w, au.MaskAlgo.MASK_1D, 2, 4)
        assert mask.shape == w.shape
        assert au.check_sparsity(w * mask, au.CheckMethod.CHECK_1D, 2, 4)

    def test_n_is_pruned_count(self):
        # reference n:m semantics: n entries PRUNED per group of m, so
        # 1:4 keeps 3 of 4 (density 0.75), not 1 of 4
        rng = np.random.default_rng(4)
        mat = rng.normal(size=(8, 16)).astype(np.float32)
        mask = au.get_mask_1d(mat, 1, 4)
        assert au.calculate_density(mask) == pytest.approx(0.75)
        assert au.check_mask_1d(mat * mask, 1, 4)
        # a reference-valid 1:4 group (3 nonzeros of 4) passes the check
        assert au.check_mask_1d(np.array([[0.0, 1.0, 5.0, 4.0]]), 1, 4)

    def test_conv_grouping_matches_reference_transpose(self):
        # 4D masks group along axis 2 after transpose(0,1,3,2) —
        # reference utils.py:498 create_mask semantics
        w = np.arange(2 * 3 * 4 * 4, dtype=np.float32).reshape(2, 3, 4, 4)
        mask = au.create_mask(w, au.MaskAlgo.MASK_1D, 2, 4)
        ref = au.get_mask_1d(
            w.transpose(0, 1, 3, 2).reshape(-1, 4), 2, 4) \
            .reshape(2, 3, 4, 4).transpose(0, 1, 3, 2)
        np.testing.assert_array_equal(mask, ref)
        with pytest.raises(ValueError, match="dim 1-4"):
            au.create_mask(np.zeros((2, 2, 2, 2, 2), np.float32))

    def test_check_method_mapping(self):
        assert au.CheckMethod.get_checking_method(
            au.MaskAlgo.MASK_1D) == au.CheckMethod.CHECK_1D
        assert au.CheckMethod.get_checking_method(
            au.MaskAlgo.MASK_2D_BEST) == au.CheckMethod.CHECK_2D


class _Net(nn.Layer):
    def __init__(self):
        super().__init__()
        self.linear1 = nn.Linear(16, 32)
        self.linear2 = nn.Linear(32, 8)
        self.norm = nn.LayerNorm(8)

    def forward(self, x):
        return self.norm(self.linear2(self.linear1(x)))


class TestWorkflow:
    def setup_method(self):
        asp.reset_excluded_layers()
        asp._MASK_REFS.clear()

    def test_prune_model_sparsifies_linear_only(self):
        net = _Net()
        masks = asp.prune_model(net, mask_algo="mask_1d")
        assert len(masks) == 2   # both Linears, never the LayerNorm
        for _, p in [("w1", net.linear1.weight), ("w2", net.linear2.weight)]:
            assert au.check_sparsity(p.numpy(), au.CheckMethod.CHECK_1D)
            assert au.calculate_density(p.numpy()) == pytest.approx(0.5)

    def test_excluded_layers_respected(self):
        net = _Net()
        names = [n for n, _ in asp._prunable_params(net)]
        asp.set_excluded_layers([names[0]])
        masks = asp.prune_model(net)
        assert len(masks) == 1
        asp.reset_excluded_layers()
        assert len(asp.prune_model(_Net())) == 2

    def test_decorated_training_keeps_sparsity_and_learns(self):
        rng = np.random.default_rng(0)
        net = _Net()
        opt = asp.decorate(optimizer.AdamW(
            learning_rate=1e-2, parameters=net.parameters()))
        asp.prune_model(net)
        X = paddle.to_tensor(rng.normal(size=(32, 16)).astype("float32"))
        Y = paddle.to_tensor(rng.normal(size=(32, 8)).astype("float32"))
        first = last = None
        for _ in range(15):
            loss = ((net(X) - Y) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            first = first if first is not None else float(loss)
            last = float(loss)
        assert last < first
        # the 2:4 pattern survived every update
        for p in (net.linear1.weight, net.linear2.weight):
            assert au.check_sparsity(p.numpy(), au.CheckMethod.CHECK_1D)
            assert au.calculate_density(p.numpy()) == pytest.approx(0.5)
        # and the UNPRUNED layer trained normally (no accidental masking)
        assert au.calculate_density(net.norm.weight.numpy()) > 0.9

    def test_add_supported_layer(self):
        class Custom(nn.Layer):
            def __init__(self):
                super().__init__()
                self.weight = paddle.core.Parameter(
                    np.random.default_rng(0).normal(size=(8, 8))
                    .astype("float32"))

            def forward(self, x):
                return x @ self.weight

        class Holder(nn.Layer):
            def __init__(self):
                super().__init__()
                self.c = Custom()

            def forward(self, x):
                return self.c(x)

        net = Holder()
        assert not asp.prune_model(net)      # unknown type: untouched
        asp.add_supported_layer(Custom)
        try:
            masks = asp.prune_model(net)
            assert len(masks) == 1
            assert au.check_sparsity(net.c.weight.numpy())
        finally:
            asp._EXTRA_SUPPORTED.discard("Custom")
