"""kernels/autotune.py — block-size autotune cache (the phi
autotune/cache.h analogue). CPU tests use an injected measure fn (timing
interpret-mode pallas would be meaningless); the real measurement path
runs on TPU via scripts/tpu_smoke.py."""
import json

import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.kernels import autotune as at


class TestCandidates:
    def test_default_first_and_legal(self):
        cands = at.flash_candidates(8, 2048, 2048, 128, jnp.bfloat16)
        assert cands[0] == (128, 128)
        assert len(cands) > 1
        for bq, bk in cands:
            assert 2048 % bq == 0 and 2048 % bk == 0
            assert at._vmem_bytes(bq, bk, 128) <= at._VMEM_BUDGET

    def test_short_seq_clamps(self):
        cands = at.flash_candidates(8, 256, 256, 128, jnp.bfloat16)
        assert all(bq <= 256 and bk <= 256 for bq, bk in cands)

    def test_never_empty(self):
        assert at.flash_candidates(8, 8, 8, 64, jnp.float32)


class TestFlashBlocks:
    def _call(self, cache, measure, sq=2048, sk=2048):
        return at.flash_blocks((2, sq, 4, 128), (2, sk, 2, 128),
                               jnp.bfloat16, True,
                               measure=measure, cache=cache)

    def test_measures_once_then_cached(self, tmp_path):
        cache = at.AutotuneCache(str(tmp_path / "c.json"))
        calls = []

        def measure(bq, bk):
            calls.append((bq, bk))
            return 1.0 if (bq, bk) != (256, 128) else 0.5

        assert self._call(cache, measure) == (256, 128)
        n = len(calls)
        assert n >= 2
        assert self._call(cache, measure) == (256, 128)
        assert len(calls) == n   # cache hit, no re-measure

    def test_persists_to_disk(self, tmp_path):
        path = str(tmp_path / "c.json")
        cache = at.AutotuneCache(path)
        self._call(cache, lambda bq, bk: float(bq))   # smallest bq wins
        disk = json.load(open(path))
        (key,) = disk.keys()
        assert key.startswith("flash:")
        assert disk[key]["blocks"] == [128, 128]
        # a brand-new cache instance (fresh process) reads the winner
        cache2 = at.AutotuneCache(path)
        calls = []
        got = self._call(cache2, lambda bq, bk: calls.append(1) or 1.0)
        assert got == (128, 128) and not calls

    def test_failing_candidates_drop_out(self, tmp_path):
        cache = at.AutotuneCache(str(tmp_path / "c.json"))

        def measure(bq, bk):
            if (bq, bk) == (128, 128):
                raise RuntimeError("compile failed")
            return float(bq + bk)

        got = self._call(cache, measure)
        assert got != (128, 128)

    def test_all_fail_caches_default_once(self, tmp_path):
        cache = at.AutotuneCache(str(tmp_path / "c.json"))
        calls = []

        def measure(bq, bk):
            calls.append(1)
            raise RuntimeError("boom")

        assert self._call(cache, measure) == (128, 128)
        n = len(calls)
        # the failed sweep must not repeat: default was cached
        assert self._call(cache, measure) == (128, 128)
        assert len(calls) == n

    def test_cached_mode_never_measures(self, tmp_path, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_AUTOTUNE", "cached")
        path = str(tmp_path / "c.json")
        cache = at.AutotuneCache(path)
        calls = []
        # miss -> defaults, no measurement even with a measure fn given
        got = self._call(cache, lambda bq, bk: calls.append(1) or 1.0)
        assert got == (128, 128) and not calls
        # pre-tuned entry -> honored
        at._USED.clear()
        cache2 = at.AutotuneCache(path)
        self._seed(cache2, (256, 128))
        got = self._call(cache2, lambda bq, bk: calls.append(1) or 1.0)
        assert got == (256, 128) and not calls
        assert any(v["source"] == "cache" for v in at.used_blocks().values())

    def _seed(self, cache, blocks):
        key = ("flash:cpu:bfloat16:b2h4kv2:q2048k2048d128:c1")
        cache.put(key, {"blocks": list(blocks), "us": 1.0, "candidates": 2})

    def test_env_path_resolves_after_construction(self, tmp_path,
                                                  monkeypatch):
        # The module-level cache is built at import time, BEFORE the
        # harness (bench.py) exports PADDLE_TPU_AUTOTUNE_CACHE. The path
        # must resolve lazily or the tuned repo cache is silently
        # ignored (the round-5 on-chip bench ran default blocks this
        # way).
        cache = at.AutotuneCache()          # constructed with no env var
        path = tmp_path / "repo_cache.json"
        path.write_text(json.dumps({
            "flash:cpu:bfloat16:b2h4kv2:q2048k2048d128:c1":
                {"blocks": [512, 256], "us": 1.0, "candidates": 6}}))
        monkeypatch.setenv("PADDLE_TPU_AUTOTUNE_CACHE", str(path))
        monkeypatch.setenv("PADDLE_TPU_AUTOTUNE", "cached")
        got = self._call(cache, None)
        assert got == (512, 256)

    def test_env_path_change_after_load_evicts(self, tmp_path,
                                               monkeypatch):
        # ADVICE r5: the sticky _loaded/_mem kept serving the OLD path's
        # entries after PADDLE_TPU_AUTOTUNE_CACHE moved (and put() wrote
        # their union into the new file). The cache now tracks its
        # resolved path and evicts on change — no _CACHE rebinding
        # workaround needed (tpu_smoke.py relied on one).
        key = "flash:cpu:bfloat16:b2h4kv2:q2048k2048d128:c1"
        p1, p2 = tmp_path / "a.json", tmp_path / "b.json"
        p1.write_text(json.dumps(
            {key: {"blocks": [256, 128], "us": 1.0, "candidates": 2}}))
        p2.write_text(json.dumps(
            {key: {"blocks": [512, 256], "us": 1.0, "candidates": 2}}))
        monkeypatch.setenv("PADDLE_TPU_AUTOTUNE", "cached")
        monkeypatch.setenv("PADDLE_TPU_AUTOTUNE_CACHE", str(p1))
        cache = at.AutotuneCache()
        assert self._call(cache, None) == (256, 128)   # loads p1
        monkeypatch.setenv("PADDLE_TPU_AUTOTUNE_CACHE", str(p2))
        assert self._call(cache, None) == (512, 256)   # evict + reload
        # a put after the switch must not leak p1's entries into p2
        cache.put("k_extra", {"blocks": [128, 128]})
        disk = json.load(open(p2))
        assert disk[key]["blocks"] == [512, 256]
        assert "k_extra" in disk
        assert json.load(open(p1))[key]["blocks"] == [256, 128]

    def test_in_trace_dispatch_never_measures(self, tmp_path, monkeypatch):
        # A dispatch reached while an outer jit trace is active must not
        # attempt measurement (jitted candidates would stage into the
        # trace and the float() sync raises ConcretizationTypeError,
        # which then poisons the persisted cache as a failed sweep).
        import jax

        monkeypatch.setattr(at, "_tuning_backend", lambda: True)
        cache = at.AutotuneCache(str(tmp_path / "c.json"))
        seen = {}

        def probe(x):
            seen["blocks"] = at.flash_blocks(
                (2, 2048, 4, 128), (2, 2048, 2, 128), jnp.bfloat16, True,
                cache=cache)
            seen["chunk"] = at.ce_chunk(512, 64, 1000, jnp.bfloat16,
                                        cache=cache)
            return x

        jax.jit(probe)(jnp.zeros(()))
        assert seen["blocks"] == (128, 128)
        assert seen["chunk"] == 1000   # default clamped to vocab
        used = at.used_blocks()
        assert any(v.get("source") == "default-in-trace"
                   for v in used.values())
        # and nothing was persisted as a failure
        import os
        assert not os.path.exists(str(tmp_path / "c.json"))

    def test_concurrent_put_merges_disk(self, tmp_path):
        path = str(tmp_path / "c.json")
        a = at.AutotuneCache(path)
        b = at.AutotuneCache(path)
        a.put("k1", {"blocks": [128, 128]})
        b.put("k2", {"blocks": [256, 128]})   # b never saw k1 at load time
        disk = json.load(open(path))
        assert set(disk) == {"k1", "k2"}

    def test_disabled_flag_returns_defaults(self, tmp_path, monkeypatch):
        from paddle_tpu.core import flags
        flags.set_flags({"use_autotune": False})
        try:
            calls = []
            got = self._call(at.AutotuneCache(str(tmp_path / "c.json")),
                             lambda bq, bk: calls.append(1) or 1.0)
            assert got == (128, 128) and not calls
        finally:
            flags.set_flags({"use_autotune": True})

    def test_off_tpu_without_injected_measure_returns_defaults(self,
                                                              tmp_path):
        cache = at.AutotuneCache(str(tmp_path / "c.json"))
        got = at.flash_blocks((2, 2048, 4, 128), (2, 2048, 2, 128),
                              jnp.bfloat16, True, cache=cache)
        assert got == (128, 128)


class TestBf16Moments:
    @pytest.mark.slow  # tier-1 budget (ISSUE 3): heavy; run in the slow lane
    def test_bf16_moments_halve_bytes_and_still_train(self):
        import jax

        from paddle_tpu.models import llama as L

        cfg = L.llama_tiny(num_hidden_layers=2, dtype=jnp.bfloat16)
        params = L.init_params(cfg, jax.random.PRNGKey(0))
        opt32 = L.adamw_init(params)
        opt16 = L.adamw_init(params, moment_dtype=jnp.bfloat16)

        def nbytes(tree):
            return sum(x.size * x.dtype.itemsize
                       for x in jax.tree.leaves(tree))

        assert nbytes(opt16["m"]) * 2 == nbytes(opt32["m"])

        step = L.make_train_step(cfg, lr=1e-3)
        ids = jnp.asarray(np.random.default_rng(0).integers(
            0, cfg.vocab_size, (2, 33)), jnp.int32)
        losses = []
        opt = opt16
        for _ in range(5):
            params, opt, loss = step(params, opt, ids)
            losses.append(float(loss))
        assert losses[-1] < losses[0]
        assert jax.tree.leaves(opt["m"])[0].dtype == jnp.bfloat16


class TestRealMeasurePath:
    def test_measure_flash_runs_end_to_end(self):
        # Regression: the package __init__ rebinds ``flash_attention`` to
        # the function, so a lazy ``from . import flash_attention`` inside
        # _measure_flash bound the function and EVERY candidate died on
        # AttributeError — the on-chip sweep silently fell back to the
        # defaults. Run the real measurement body (interpret mode, tiny
        # shape) so an import regression fails loudly on CPU.
        t = at._measure_flash(1, 16, 16, 2, 1, 64, jnp.float32, True,
                              16, 16, interpret=True)
        assert t > 0


class TestErrorEntrySelfHeal:
    def _call(self, cache, measure):
        return at.flash_blocks((2, 2048, 4, 128), (2, 2048, 2, 128),
                               jnp.bfloat16, True,
                               measure=measure, cache=cache)

    def test_error_entry_is_retried_then_pinned(self, tmp_path):
        # process A: all candidates fail (e.g. tunnel died mid-sweep)
        path = str(tmp_path / "c.json")
        at._FAILED_KEYS.clear()
        cache = at.AutotuneCache(path)
        assert self._call(cache, lambda bq, bk: 1 / 0) == (128, 128)
        (entry,) = cache._mem.values()
        assert entry["error"] and entry["failures"] == 1

        # process B (fresh _FAILED_KEYS): the persisted error entry is a
        # MISS — healthy hardware re-sweeps and self-heals the cache
        at._FAILED_KEYS.clear()
        calls = []
        cache_b = at.AutotuneCache(path)
        got = self._call(cache_b, lambda bq, bk: calls.append(1) or
                         (0.5 if (bq, bk) == (256, 128) else 1.0))
        assert calls and got == (256, 128)
        assert not cache_b.get(next(iter(cache_b._mem))).get("error")

    def test_error_entry_pins_after_budget(self, tmp_path):
        path = str(tmp_path / "c.json")
        for _ in range(at.MAX_SWEEP_FAILURES):
            at._FAILED_KEYS.clear()          # simulate a fresh process
            cache = at.AutotuneCache(path)
            assert self._call(cache, lambda bq, bk: 1 / 0) == (128, 128)
        # budget exhausted: later processes use defaults WITHOUT sweeping
        at._FAILED_KEYS.clear()
        calls = []
        cache = at.AutotuneCache(path)
        got = self._call(cache, lambda bq, bk: calls.append(1) or 1.0)
        assert got == (128, 128) and not calls
        at._FAILED_KEYS.clear()


class TestCeChunk:
    def _call(self, cache, measure, n=8192, v=32000):
        return at.ce_chunk(n, 4096, v, jnp.bfloat16,
                           measure=measure, cache=cache)

    def test_candidates_default_first_clamped(self):
        cands = at.ce_candidates(32000)
        assert cands[0] == at.CE_DEFAULT_CHUNK
        assert all(c <= 32000 for c in cands)
        tiny = at.ce_candidates(1000)
        assert tiny == [1000]          # every candidate clamps to V

    def test_measures_best_and_caches(self, tmp_path):
        at._FAILED_KEYS.clear()
        cache = at.AutotuneCache(str(tmp_path / "c.json"))
        calls = []

        def measure(c):
            calls.append(c)
            return 1.0 / c             # bigger chunk = faster here

        got = self._call(cache, measure)
        assert got == 16384 and calls
        n = len(calls)
        assert self._call(cache, measure) == 16384
        assert len(calls) == n         # second call: cache hit
        disk = json.loads((tmp_path / "c.json").read_text())
        (entry,) = disk.values()
        assert entry["chunk"] == 16384 and entry["candidates"] >= 4

    def test_all_fail_pins_default_and_records_error(self, tmp_path):
        at._FAILED_KEYS.clear()
        cache = at.AutotuneCache(str(tmp_path / "c.json"))
        assert self._call(cache, lambda c: 1 / 0) == at.CE_DEFAULT_CHUNK
        (entry,) = cache._mem.values()
        assert entry["error"] and entry["failures"] == 1
        at._FAILED_KEYS.clear()

    def test_cached_mode_never_measures(self, tmp_path, monkeypatch):
        at._FAILED_KEYS.clear()
        monkeypatch.setenv("PADDLE_TPU_AUTOTUNE", "cached")
        cache = at.AutotuneCache(str(tmp_path / "c.json"))
        calls = []
        got = self._call(cache, lambda c: calls.append(c) or 1.0)
        assert got == at.CE_DEFAULT_CHUNK and not calls

    def test_real_measure_body_runs(self):
        # the flash sweep died on a shadowed import nobody executed on
        # CPU; keep the CE measurement body exercised the same way
        t = at._measure_ce(8, 16, 64, jnp.float32, 32)
        assert t > 0

    def test_dispatcher_resolves_chunk(self, tmp_path, monkeypatch):
        # the llama loss path goes through dispatched_fused_ce: a cache
        # hit must reach the kernel as its vocab_chunk
        import numpy as np
        from paddle_tpu import kernels

        monkeypatch.setenv("PADDLE_TPU_AUTOTUNE", "cached")
        monkeypatch.setenv("PADDLE_TPU_AUTOTUNE_CACHE",
                           str(tmp_path / "c.json"))
        at._FAILED_KEYS.clear()
        cache = at.AutotuneCache(str(tmp_path / "c.json"))
        import jax
        key = f"ce:{jax.default_backend()}:float32:n8v64d16"
        cache.put(key, {"chunk": 32, "us": 1.0, "candidates": 2})
        monkeypatch.setattr(at, "_CACHE", at.AutotuneCache(
            str(tmp_path / "c.json")))
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.standard_normal((8, 16)), jnp.float32)
        head = jnp.asarray(rng.standard_normal((64, 16)), jnp.float32)
        labels = jnp.asarray(rng.integers(0, 64, (8,)), jnp.int32)
        kernels.dispatched_fused_ce(x, head, labels)
        assert at.used_blocks()[key] == {"chunk": 32, "source": "cache"}
