"""KV-store heartbeat transport (VERDICT-r4 weak #6): multi-host
liveness without a shared filesystem.

Reference: fleet/elastic/manager.py etcd-lease heartbeats. Here beats
ride the jax.distributed coordination service; staleness is measured
clock-skew-free (value-change age on the watcher's clock) and a rank-0
relay mirrors KV beats into the controller's file dir.
"""
import json
import os
import time

from paddle_tpu.distributed import heartbeat as hb


class FakeKV:
    """Dict-backed stand-in for the coordination-service client."""

    def __init__(self):
        self.d = {}

    def key_value_set(self, k, v, allow_overwrite=False):
        if not allow_overwrite and k in self.d:
            raise RuntimeError(f"key exists: {k}")
        self.d[k] = v

    def key_value_try_get(self, k):
        if k not in self.d:
            raise KeyError(k)
        return self.d[k]


def _publish(kv, kind, rank, payload):
    kv.key_value_set(f"{hb._KV_PREFIX}/{kind}/rank{rank}",
                     json.dumps(payload), allow_overwrite=True)


class TestKVWatcher:
    def test_never_published_grace_then_stale(self):
        w = hb.KVHeartbeatWatcher(FakeKV())
        t0 = time.time()
        assert w.check([0], auto_timeout=10, progress_timeout=0,
                       started_at=t0) == {}
        stale = w.check([0], auto_timeout=0.0001, progress_timeout=0,
                        started_at=t0 - 5)
        assert 0 in stale and "never published" in stale[0]

    def test_value_change_resets_age_regardless_of_timestamps(self):
        # clock-skew-freeness: the payload carries an ANCIENT remote
        # timestamp; freshness still comes from the value changing on
        # the watcher's own clock
        kv = FakeKV()
        w = hb.KVHeartbeatWatcher(kv)
        _publish(kv, "auto", 0, {"t": 0.0, "seq": 1})
        assert w.check([0], auto_timeout=0.2, progress_timeout=0) == {}
        time.sleep(0.3)         # value unchanged -> age grows locally
        stale = w.check([0], auto_timeout=0.2, progress_timeout=0)
        assert 0 in stale and "no liveness beat" in stale[0]
        _publish(kv, "auto", 0, {"t": 0.0, "seq": 2})   # beat again
        assert w.check([0], auto_timeout=0.2, progress_timeout=0) == {}

    def test_wedged_but_alive_detected_via_progress(self):
        kv = FakeKV()
        w = hb.KVHeartbeatWatcher(kv)
        _publish(kv, "auto", 0, {"seq": 1})
        _publish(kv, "progress", 0, {"step": 5, "seq": 1})
        assert w.check([0], auto_timeout=5, progress_timeout=0.2) == {}
        time.sleep(0.3)
        _publish(kv, "auto", 0, {"seq": 2})   # alive but not progressing
        stale = w.check([0], auto_timeout=5, progress_timeout=0.2)
        assert 0 in stale and "no training progress" in stale[0]
        assert w.latest("progress", 0)["step"] == 5

    def test_no_progress_optin_no_wedge_check(self):
        kv = FakeKV()
        w = hb.KVHeartbeatWatcher(kv)
        _publish(kv, "auto", 0, {"seq": 1})
        time.sleep(0.25)
        _publish(kv, "auto", 0, {"seq": 2})
        assert w.check([0], auto_timeout=5, progress_timeout=0.1) == {}


class TestKVRelay:
    def test_relay_mirrors_kv_beats_to_files(self, tmp_path):
        kv = FakeKV()
        _publish(kv, "auto", 0, {"t": 1.0, "seq": 1})
        _publish(kv, "auto", 1, {"t": 1.0, "seq": 1})
        _publish(kv, "progress", 1, {"step": 3, "seq": 1})
        stop = hb.start_kv_relay(str(tmp_path), [0, 1], interval=0.05,
                                 client=kv)
        try:
            deadline = time.time() + 5
            want = {"rank0.alive", "rank1.alive", "rank1.progress"}
            while time.time() < deadline:
                if want <= set(os.listdir(tmp_path)):
                    break
                time.sleep(0.05)
            assert want <= set(os.listdir(tmp_path)), \
                os.listdir(tmp_path)
            # the file watcher sees the mirrored beats as fresh
            assert hb.check_stale(str(tmp_path), [0, 1],
                                  auto_timeout=30,
                                  progress_timeout=0) == {}
            # unchanged KV value must NOT re-touch the file (staleness
            # must survive the relay)
            mt = os.stat(tmp_path / "rank0.alive").st_mtime
            time.sleep(0.2)
            assert os.stat(tmp_path / "rank0.alive").st_mtime == mt
        finally:
            stop.set()

    def test_relay_without_client_returns_none(self, monkeypatch):
        monkeypatch.setattr(hb, "_kv_client", lambda: None)
        assert hb.start_kv_relay("/tmp/nope", [0]) is None


class TestNamedBeats:
    """Name-keyed beats for serving replicas (ISSUE 13): same files,
    same staleness semantics, arbitrary participant names — the
    transport fleet/elastic.py run_serving watches."""

    def test_touch_and_stale(self, tmp_path):
        d = str(tmp_path)
        hb.touch_named(d, "replica0")
        assert hb.stale_names(d, ["replica0"], timeout=5.0) == {}
        time.sleep(0.06)
        stale = hb.stale_names(d, ["replica0"], timeout=0.05)
        assert "replica0" in stale
        assert "no liveness beat" in stale["replica0"]

    def test_never_beat_grace(self, tmp_path):
        d = str(tmp_path)
        t0 = time.time()
        # inside the startup grace: not stale yet
        assert hb.stale_names(d, ["replica1"], timeout=5.0,
                              started_at={"replica1": t0}) == {}
        stale = hb.stale_names(d, ["replica1"], timeout=0.01,
                               started_at={"replica1": t0 - 1.0})
        assert "never emitted" in stale["replica1"]
        # no started_at: a never-beat name is never declared stale
        assert hb.stale_names(d, ["replica1"], timeout=0.01) == {}

    def test_start_named_daemon_beats(self, tmp_path):
        d = str(tmp_path)
        stop = hb.start_named(d, "replica2", interval=0.02)
        try:
            deadline = time.time() + 2
            path = os.path.join(d, "replica2.alive")
            while not os.path.exists(path) and time.time() < deadline:
                time.sleep(0.01)
            assert hb.stale_names(d, ["replica2"], timeout=1.0) == {}
        finally:
            stop.set()

    def test_leftover_file_older_than_spawn_gets_grace(self, tmp_path):
        # review fix: controllers reuse replica names across runs — a
        # beat file left by a previous incarnation must not get a
        # fresh healthy replica declared stale before its startup
        # grace; an mtime older than started_at counts as never-beat
        d = str(tmp_path)
        hb.touch_named(d, "replica0")            # previous incarnation
        time.sleep(0.06)
        t_spawn = time.time()                    # fresh spawn NOW
        stale = hb.stale_names(d, ["replica0"], timeout=0.05,
                               started_at={"replica0": t_spawn})
        assert stale == {}, stale                # grace, not stale
        time.sleep(0.07)                         # grace spent, no beat
        stale = hb.stale_names(d, ["replica0"], timeout=0.05,
                               started_at={"replica0": t_spawn})
        assert "never emitted" in stale["replica0"]
        hb.touch_named(d, "replica0")            # THIS incarnation beats
        assert hb.stale_names(d, ["replica0"], timeout=0.05,
                              started_at={"replica0": t_spawn}) == {}
