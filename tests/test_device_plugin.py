"""Plugin-device registration seam (VERDICT-r4 item 10).

Reference: paddle/phi/backends/custom/custom_device.cc + phi/capi/ —
runtime registration of third-party devices. TPU-native seam: a PJRT
C-API plugin registers as a jax platform; ops reach it through the
jnp/lax lowering with no per-op hook table. The test builds a REAL
plugin .so (tests/_fake_pjrt_plugin.cc, the vendor-artifact shape) that
owns no hardware, so registration succeeds and initialization fails
through the PJRT error protocol instead of crashing.
"""
import os
import shutil
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import device
from paddle_tpu.core import enforce as E

_SRC = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                    "_fake_pjrt_plugin.cc")
_TF_INC = None
for p in sys.path + [os.path.join(sys.prefix, "lib",
                                  f"python{sys.version_info.major}."
                                  f"{sys.version_info.minor}",
                                  "site-packages")]:
    cand = os.path.join(p, "tensorflow", "include")
    if os.path.isdir(os.path.join(cand, "tensorflow", "compiler", "xla",
                                  "pjrt", "c")):
        _TF_INC = cand
        break


@pytest.fixture(scope="module")
def plugin_so(tmp_path_factory):
    if shutil.which("g++") is None or _TF_INC is None:
        pytest.skip("g++ or pjrt_c_api.h unavailable")
    out = tmp_path_factory.mktemp("pjrt") / "libfake_pjrt.so"
    r = subprocess.run(
        ["g++", "-shared", "-fPIC", "-O1", f"-I{_TF_INC}",
         _SRC, "-o", str(out)],
        capture_output=True, text=True, timeout=300)
    if r.returncode != 0:
        pytest.skip(f"stub plugin did not compile: {r.stderr[-800:]}")
    return str(out)


class TestPluginSeam:
    def test_missing_library_raises_typed(self):
        with pytest.raises(E.NotFoundError, match="not found"):
            device.register_pjrt_plugin("my_npu", "/nonexistent/libfoo.so")
        assert "my_npu" not in device.get_all_custom_device_type()

    def test_bad_name_raises_typed(self):
        with pytest.raises(E.InvalidArgumentError, match="identifier"):
            device.register_pjrt_plugin("my npu!", "/tmp/x.so")

    def test_non_plugin_library_rejected(self, tmp_path):
        bogus = tmp_path / "libnotaplugin.so"
        bogus.write_bytes(b"\x7fELF not a real library")
        with pytest.raises(E.ExternalError, match="failed to load"):
            device.register_pjrt_plugin("bogusdev", str(bogus))
        assert "bogusdev" not in device.get_all_custom_device_type()

    def test_register_and_query(self, plugin_so):
        got = device.register_pjrt_plugin("fakedev", plugin_so)
        assert got == plugin_so
        assert "fakedev" in device.get_all_custom_device_type()
        assert device.is_compiled_with_custom_device("fakedev")
        # idempotent: re-registering the same type returns the recorded
        # path without reloading
        assert device.register_pjrt_plugin("fakedev", "/other.so") \
            == plugin_so
        # the stub owns no hardware: Client_Create reports UNIMPLEMENTED
        # through the PJRT error protocol, so the type is registered-
        # but-unavailable and the query must not raise
        assert not any(d.startswith("fakedev:")
                       for d in device.get_available_custom_device())

    def test_compute_unaffected_by_registration(self, plugin_so):
        device.register_pjrt_plugin("fakedev", plugin_so)
        x = paddle.to_tensor(np.arange(6, dtype="float32").reshape(2, 3))
        assert float((x * 2).sum()) == 30.0
