"""Profiler subsystem tests: scheduler states, RecordEvent spans (native
C++ host tracer via cpp_extension, with the Python fallback), chrome-trace
export, op-dispatch instrumentation.

Reference strategy: test/legacy_test/test_profiler.py + the scheduler-state
unit tests in test_newprofiler.py.
"""
import json
import os

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import profiler as prof_mod
from paddle_tpu.profiler import (Profiler, ProfilerState, ProfilerTarget,
                                 RecordEvent, export_chrome_tracing,
                                 load_profiler_result, make_scheduler)


class TestScheduler:
    def test_state_sequence(self):
        sched = make_scheduler(closed=1, ready=1, record=2, repeat=0,
                               skip_first=1)
        states = [sched(i) for i in range(6)]
        assert states == [
            ProfilerState.CLOSED,          # skip_first
            ProfilerState.CLOSED,
            ProfilerState.READY,
            ProfilerState.RECORD,
            ProfilerState.RECORD_AND_RETURN,
            ProfilerState.CLOSED,          # next cycle
        ]

    def test_repeat_limits_cycles(self):
        sched = make_scheduler(closed=0, ready=0, record=1, repeat=2)
        assert sched(0) == ProfilerState.RECORD_AND_RETURN
        assert sched(1) == ProfilerState.RECORD_AND_RETURN
        assert sched(2) == ProfilerState.CLOSED

    def test_validation(self):
        with pytest.raises(ValueError):
            make_scheduler(closed=-1, ready=0, record=1)
        with pytest.raises(ValueError):
            make_scheduler(closed=0, ready=0, record=0)


class TestHostTracer:
    def test_native_extension_builds(self):
        """The C++ host tracer must actually build + load via the
        cpp_extension path (VERDICT r2: prove the extension path works)."""
        rec = prof_mod._get_recorder()
        assert prof_mod._recorder_kind in ("native", "python")
        # the toolchain is baked into this image — require the native path
        assert prof_mod._recorder_kind == "native", (
            "host_tracer.cc failed to build via utils/cpp_extension.load")

    def test_record_event_spans(self):
        rec = prof_mod._get_recorder()
        rec.start()
        with RecordEvent("outer"):
            with RecordEvent("inner"):
                pass
        rec.stop()
        names = [e["name"] for e in rec.events()]
        assert "outer" in names and "inner" in names
        ev = {e["name"]: e for e in rec.events()}
        assert ev["outer"]["end_ns"] >= ev["inner"]["end_ns"]
        assert ev["outer"]["begin_ns"] <= ev["inner"]["begin_ns"]

    def test_export_chrome_json(self, tmp_path):
        rec = prof_mod._get_recorder()
        rec.start()
        with RecordEvent("span_a"):
            pass
        rec.stop()
        path = str(tmp_path / "trace.json")
        rec.export(path, "test_proc")
        data = json.load(open(path))
        assert "traceEvents" in data
        names = [e.get("name") for e in data["traceEvents"]]
        assert "span_a" in names
        span = next(e for e in data["traceEvents"] if e["name"] == "span_a")
        assert span["ph"] == "X" and "dur" in span and "ts" in span


class TestProfiler:
    def test_profile_train_step_exports(self, tmp_path):
        """Profiling a real train step produces a chrome trace containing
        op spans (VERDICT r2 'done' criterion)."""
        import paddle_tpu.nn as nn
        lin = nn.Linear(8, 8)
        x = pt.to_tensor(np.random.randn(4, 8).astype("float32"))

        p = Profiler(targets=[ProfilerTarget.CPU],
                     on_trace_ready=export_chrome_tracing(str(tmp_path)))
        with p:
            for _ in range(3):
                loss = (lin(x) ** 2).mean()
                loss.backward()
        assert p.last_export_path and os.path.exists(p.last_export_path)
        data = load_profiler_result(p.last_export_path)
        names = {e.get("name") for e in data["traceEvents"]}
        # the dispatcher instrumented eager ops
        assert "matmul" in names or "linear" in names
        assert "mean" in names

    def test_scheduler_driven_windows(self, tmp_path):
        exports = []

        def on_ready(prof):
            exports.append(prof.step_num)

        p = Profiler(scheduler=make_scheduler(closed=1, ready=0, record=1),
                     on_trace_ready=on_ready)
        p.start()
        for _ in range(6):
            p.step()
        p.stop()
        assert len(exports) >= 2   # one export per completed record window

    def test_summary(self):
        p = Profiler()
        with p:
            with RecordEvent("my_block"):
                pass
        table = p.summary()
        assert "my_block" in table

    def test_op_hook_removed_after_stop(self):
        from paddle_tpu.ops import _op
        p = Profiler()
        p.start()
        assert _op._PROFILE_HOOK is not None
        p.stop()
        assert _op._PROFILE_HOOK is None


class TestProfilerRegressions:
    def test_repeat_cycles_all_record(self, tmp_path):
        """Recording restarts after each RECORD_AND_RETURN boundary."""
        traces = []

        def on_ready(prof):
            traces.append(len(prof.events()))

        p = Profiler(scheduler=make_scheduler(closed=0, ready=0, record=2,
                                              repeat=3),
                     on_trace_ready=on_ready)
        p.start()
        for _ in range(6):
            with RecordEvent("tick"):
                pass
            p.step()
        p.stop()
        assert len(traces) == 3
        assert all(n > 0 for n in traces), traces

    def test_tuple_scheduler_one_shot(self):
        exports = []
        p = Profiler(scheduler=(2, 4), on_trace_ready=lambda pr:
                     exports.append(pr.step_num))
        p.start()
        for _ in range(12):
            p.step()
        p.stop()
        assert len(exports) == 1


class TestStatistics:
    """profiler/statistics.py: the profiler_statistic.py parity layer."""

    EVENTS = [
        dict(name="a", begin_ns=0, end_ns=100, tid=1),
        dict(name="a", begin_ns=100, end_ns=400, tid=1),
        dict(name="b", begin_ns=0, end_ns=400, tid=2),
    ]

    def test_aggregate_math(self):
        from paddle_tpu.profiler import statistics as S
        stats = S.aggregate(self.EVENTS)
        a = stats["a"]
        assert (a.calls, a.total_ns, a.min_ns, a.max_ns) == (2, 400, 100,
                                                             300)
        assert a.avg_ns == 200
        # observed window = 400ns; both names fill it entirely
        assert a.ratio == 100.0 and stats["b"].ratio == 100.0

    def test_explicit_span_ratio(self):
        from paddle_tpu.profiler import statistics as S
        stats = S.aggregate(self.EVENTS, span_ns=800)
        assert stats["a"].ratio == 50.0

    def test_sort_keys(self):
        from paddle_tpu.profiler import statistics as S
        from paddle_tpu.profiler.statistics import SortedKeys
        evs = self.EVENTS + [dict(name="c", begin_ns=0, end_ns=50, tid=1),
                             dict(name="c", begin_ns=0, end_ns=50, tid=1)]
        stats = S.aggregate(evs)
        by_max = S._sort(list(stats.values()), SortedKeys.CPUMax)
        assert by_max[0].name == "b"            # max 400
        by_min = S._sort(list(stats.values()), SortedKeys.CPUMin)
        assert by_min[0].name == "b"            # min 400, descending
        # GPU aliases sort the same host columns
        assert [s.name for s in S._sort(list(stats.values()),
                                        SortedKeys.GPUTotal)] == \
            [s.name for s in S._sort(list(stats.values()),
                                     SortedKeys.CPUTotal)]

    def test_table_golden_shape(self):
        from paddle_tpu.profiler import statistics as S
        table = S.build_table(S.aggregate(self.EVENTS), time_unit="ns")
        lines = table.splitlines()
        header = lines[1]
        for col in ("Name", "Calls", "Total(ns)", "Avg(ns)", "Max(ns)",
                    "Min(ns)", "Ratio(%)"):
            assert col in header, header
        row_a = next(ln for ln in lines if ln.startswith("a "))
        cells = row_a.split()
        assert cells[1] == "2" and float(cells[2]) == 400.0
        assert float(cells[3]) == 200.0

    def test_thread_sep(self):
        from paddle_tpu.profiler import statistics as S
        out = S.summary_string(self.EVENTS, thread_sep=True)
        assert "Thread 1" in out and "Thread 2" in out

    def test_op_breakdown_machine_readable(self):
        from paddle_tpu.profiler import statistics as S
        bd = S.op_breakdown(self.EVENTS)
        assert bd["a"]["calls"] == 2 and bd["a"]["total_ns"] == 400
        assert bd["b"]["avg_ns"] == 400

    def test_bad_time_unit_raises(self):
        from paddle_tpu.profiler import statistics as S
        with pytest.raises(ValueError):
            S.build_table({}, time_unit="h")


class TestSummaryParity:
    def test_summary_golden_columns(self):
        """Profiler.summary() renders the reference-shaped per-op table:
        calls/total/avg (+max/min/ratio) columns for each span name."""
        p = Profiler()
        with p:
            lin_x = pt.to_tensor(np.random.randn(4, 8).astype("float32"))
            import paddle_tpu.nn as nn
            lin = nn.Linear(8, 8)
            for _ in range(2):
                _ = (lin(lin_x) ** 2).mean()
        table = p.summary(time_unit="us")
        for col in ("Calls", "Total(us)", "Avg(us)", "Max(us)", "Min(us)",
                    "Ratio(%)"):
            assert col in table
        assert "matmul" in table or "linear" in table
        assert "mean" in table

    def test_summary_sorted_by(self):
        from paddle_tpu.profiler import SortedKeys
        p = Profiler()
        with p:
            with prof_mod.RecordEvent("zz_long"):
                import time as _t
                _t.sleep(0.002)
            with prof_mod.RecordEvent("aa_short"):
                pass
        table = p.summary(sorted_by=SortedKeys.CPUTotal)
        assert table.index("zz_long") < table.index("aa_short")


class TestOpCounterUnderProfiler:
    def test_dispatch_under_profiler_increments_counter(self):
        """ISSUE satellite: op dispatch while a profiler is recording
        must ALSO increment the monitor's per-op counter when the flag
        is on (the two seams compose, not shadow)."""
        from paddle_tpu import monitor
        monitor.reset()
        pt.set_flags({"FLAGS_enable_monitor": True})
        try:
            p = Profiler()
            with p:
                x = pt.to_tensor(np.ones((4, 4), "float32"))
                _ = x + x
            snap = monitor.snapshot()
            assert snap["counters"]["op.add.calls"] >= 1
            # and the profiler saw the same span
            assert any(e["name"] == "add" for e in p.events())
        finally:
            pt.set_flags({"FLAGS_enable_monitor": False})
            monitor.reset()
