"""Profiler subsystem tests: scheduler states, RecordEvent spans (native
C++ host tracer via cpp_extension, with the Python fallback), chrome-trace
export, op-dispatch instrumentation.

Reference strategy: test/legacy_test/test_profiler.py + the scheduler-state
unit tests in test_newprofiler.py.
"""
import json
import os

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import profiler as prof_mod
from paddle_tpu.profiler import (Profiler, ProfilerState, ProfilerTarget,
                                 RecordEvent, export_chrome_tracing,
                                 load_profiler_result, make_scheduler)


class TestScheduler:
    def test_state_sequence(self):
        sched = make_scheduler(closed=1, ready=1, record=2, repeat=0,
                               skip_first=1)
        states = [sched(i) for i in range(6)]
        assert states == [
            ProfilerState.CLOSED,          # skip_first
            ProfilerState.CLOSED,
            ProfilerState.READY,
            ProfilerState.RECORD,
            ProfilerState.RECORD_AND_RETURN,
            ProfilerState.CLOSED,          # next cycle
        ]

    def test_repeat_limits_cycles(self):
        sched = make_scheduler(closed=0, ready=0, record=1, repeat=2)
        assert sched(0) == ProfilerState.RECORD_AND_RETURN
        assert sched(1) == ProfilerState.RECORD_AND_RETURN
        assert sched(2) == ProfilerState.CLOSED

    def test_validation(self):
        with pytest.raises(ValueError):
            make_scheduler(closed=-1, ready=0, record=1)
        with pytest.raises(ValueError):
            make_scheduler(closed=0, ready=0, record=0)


class TestHostTracer:
    def test_native_extension_builds(self):
        """The C++ host tracer must actually build + load via the
        cpp_extension path (VERDICT r2: prove the extension path works)."""
        rec = prof_mod._get_recorder()
        assert prof_mod._recorder_kind in ("native", "python")
        # the toolchain is baked into this image — require the native path
        assert prof_mod._recorder_kind == "native", (
            "host_tracer.cc failed to build via utils/cpp_extension.load")

    def test_record_event_spans(self):
        rec = prof_mod._get_recorder()
        rec.start()
        with RecordEvent("outer"):
            with RecordEvent("inner"):
                pass
        rec.stop()
        names = [e["name"] for e in rec.events()]
        assert "outer" in names and "inner" in names
        ev = {e["name"]: e for e in rec.events()}
        assert ev["outer"]["end_ns"] >= ev["inner"]["end_ns"]
        assert ev["outer"]["begin_ns"] <= ev["inner"]["begin_ns"]

    def test_export_chrome_json(self, tmp_path):
        rec = prof_mod._get_recorder()
        rec.start()
        with RecordEvent("span_a"):
            pass
        rec.stop()
        path = str(tmp_path / "trace.json")
        rec.export(path, "test_proc")
        data = json.load(open(path))
        assert "traceEvents" in data
        names = [e.get("name") for e in data["traceEvents"]]
        assert "span_a" in names
        span = next(e for e in data["traceEvents"] if e["name"] == "span_a")
        assert span["ph"] == "X" and "dur" in span and "ts" in span


class TestProfiler:
    def test_profile_train_step_exports(self, tmp_path):
        """Profiling a real train step produces a chrome trace containing
        op spans (VERDICT r2 'done' criterion)."""
        import paddle_tpu.nn as nn
        lin = nn.Linear(8, 8)
        x = pt.to_tensor(np.random.randn(4, 8).astype("float32"))

        p = Profiler(targets=[ProfilerTarget.CPU],
                     on_trace_ready=export_chrome_tracing(str(tmp_path)))
        with p:
            for _ in range(3):
                loss = (lin(x) ** 2).mean()
                loss.backward()
        assert p.last_export_path and os.path.exists(p.last_export_path)
        data = load_profiler_result(p.last_export_path)
        names = {e.get("name") for e in data["traceEvents"]}
        # the dispatcher instrumented eager ops
        assert "matmul" in names or "linear" in names
        assert "mean" in names

    def test_scheduler_driven_windows(self, tmp_path):
        exports = []

        def on_ready(prof):
            exports.append(prof.step_num)

        p = Profiler(scheduler=make_scheduler(closed=1, ready=0, record=1),
                     on_trace_ready=on_ready)
        p.start()
        for _ in range(6):
            p.step()
        p.stop()
        assert len(exports) >= 2   # one export per completed record window

    def test_summary(self):
        p = Profiler()
        with p:
            with RecordEvent("my_block"):
                pass
        table = p.summary()
        assert "my_block" in table

    def test_op_hook_removed_after_stop(self):
        from paddle_tpu.ops import _op
        p = Profiler()
        p.start()
        assert _op._PROFILE_HOOK is not None
        p.stop()
        assert _op._PROFILE_HOOK is None


class TestProfilerRegressions:
    def test_repeat_cycles_all_record(self, tmp_path):
        """Recording restarts after each RECORD_AND_RETURN boundary."""
        traces = []

        def on_ready(prof):
            traces.append(len(prof.events()))

        p = Profiler(scheduler=make_scheduler(closed=0, ready=0, record=2,
                                              repeat=3),
                     on_trace_ready=on_ready)
        p.start()
        for _ in range(6):
            with RecordEvent("tick"):
                pass
            p.step()
        p.stop()
        assert len(traces) == 3
        assert all(n > 0 for n in traces), traces

    def test_tuple_scheduler_one_shot(self):
        exports = []
        p = Profiler(scheduler=(2, 4), on_trace_ready=lambda pr:
                     exports.append(pr.step_num))
        p.start()
        for _ in range(12):
            p.step()
        p.stop()
        assert len(exports) == 1
