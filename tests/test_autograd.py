"""Tape engine tests — numeric parity with finite differences, the same
strategy as the reference's OpTest.check_grad (test/legacy_test/op_test.py)."""
import numpy as np
import pytest

import paddle_tpu as pt


def numeric_grad(f, x, eps=1e-3):
    """Central finite differences of scalar f at numpy x."""
    g = np.zeros_like(x, dtype=np.float64)
    flat = x.reshape(-1)
    gf = g.reshape(-1)
    for i in range(flat.size):
        orig = flat[i]
        flat[i] = orig + eps
        fp = f(x.copy().reshape(x.shape))
        flat[i] = orig - eps
        fm = f(x.copy().reshape(x.shape))
        flat[i] = orig
        gf[i] = (fp - fm) / (2 * eps)
    return g


class TestBackwardBasics:
    def test_simple_chain(self):
        a = pt.to_tensor(2.0, stop_gradient=False)
        b = a * a * a
        b.backward()
        assert abs(a.grad.item() - 12.0) < 1e-5

    def test_grad_accumulation(self):
        a = pt.to_tensor(3.0, stop_gradient=False)
        (a * 2.0).backward()
        (a * 5.0).backward()
        assert abs(a.grad.item() - 7.0) < 1e-5

    def test_clear_grad(self):
        a = pt.to_tensor(3.0, stop_gradient=False)
        (a * 2.0).backward()
        a.clear_grad()
        assert a.grad is None

    def test_diamond(self):
        # y = x*x used twice: dz/dx = 2*(x*x)' contributions
        x = pt.to_tensor(3.0, stop_gradient=False)
        y = x * x
        z = y + y
        z.backward()
        assert abs(x.grad.item() - 12.0) < 1e-5

    def test_stop_gradient_blocks(self):
        x = pt.to_tensor(1.0, stop_gradient=False)
        y = pt.to_tensor(1.0)  # stop_gradient=True
        z = x * y
        z.backward()
        assert y.grad is None
        assert x.grad is not None

    def test_detach_cuts_graph(self):
        x = pt.to_tensor(2.0, stop_gradient=False)
        y = (x * x).detach()
        z = y * x
        z.backward()
        assert abs(x.grad.item() - 4.0) < 1e-5  # only via z=y*x

    def test_backward_nonscalar_requires_grad_tensor(self):
        x = pt.to_tensor([1.0, 2.0], stop_gradient=False)
        y = x * 2.0
        with pytest.raises(RuntimeError):
            y.backward()
        y = x * 2.0
        y.backward(pt.to_tensor([1.0, 1.0]))
        np.testing.assert_allclose(x.grad.numpy(), [2, 2])

    def test_double_backward_without_retain_raises(self):
        x = pt.to_tensor(2.0, stop_gradient=False)
        y = x * x
        y.backward(retain_graph=False)
        with pytest.raises(RuntimeError):
            y.backward()

    def test_retain_graph(self):
        x = pt.to_tensor(2.0, stop_gradient=False)
        y = x * x
        y.backward(retain_graph=True)
        y.backward()
        assert abs(x.grad.item() - 8.0) < 1e-5

    def test_backward_on_error_path(self):
        t = pt.to_tensor(1.0)  # stop_gradient True
        with pytest.raises(RuntimeError):
            t.backward()

    def test_multi_output_op(self):
        x = pt.to_tensor(np.array([3.0, 1.0, 2.0], np.float32), stop_gradient=False)
        vals, idx = pt.topk(x, 2)
        vals.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [1, 0, 1])

    def test_no_grad_context(self):
        x = pt.to_tensor(1.0, stop_gradient=False)
        with pt.no_grad():
            y = x * 2.0
        assert y.stop_gradient
        assert y._grad_node is None

    def test_hooks(self):
        x = pt.to_tensor(1.0, stop_gradient=False)
        seen = []

        def hook(g):
            seen.append(g.item())
            return g * 2.0
        x.register_hook(hook)
        (x * 3.0).backward()
        assert seen == [3.0]
        assert abs(x.grad.item() - 6.0) < 1e-5

    def test_intermediate_hook(self):
        x = pt.to_tensor(2.0, stop_gradient=False)
        y = x * x
        y.register_hook(lambda g: g * 10.0)
        z = y * 3.0
        z.backward()
        # dz/dy=3 -> hook -> 30 -> dy/dx=2x=4 -> 120
        assert abs(x.grad.item() - 120.0) < 1e-4

    def test_retain_grads_intermediate(self):
        x = pt.to_tensor(2.0, stop_gradient=False)
        y = x * x
        y.retain_grads()
        z = y * 3.0
        z.backward()
        assert abs(y.grad.item() - 3.0) < 1e-5


class TestGradAPI:
    def test_grad_basic(self):
        x = pt.to_tensor(2.0, stop_gradient=False)
        y = x * x
        (gx,) = pt.grad(y, x)
        assert abs(gx.item() - 4.0) < 1e-5
        assert x.grad is None  # .grad untouched

    def test_grad_multiple_inputs(self):
        x = pt.to_tensor(2.0, stop_gradient=False)
        w = pt.to_tensor(3.0, stop_gradient=False)
        y = x * w + x
        gx, gw = pt.grad(y, [x, w])
        assert abs(gx.item() - 4.0) < 1e-5
        assert abs(gw.item() - 2.0) < 1e-5


class TestNumericParity:
    @pytest.mark.parametrize("opname,np_f", [
        ("exp", np.exp), ("tanh", np.tanh), ("sqrt", np.sqrt),
        ("log", np.log), ("sigmoid", lambda v: 1 / (1 + np.exp(-v))),
    ])
    def test_unary_grads(self, opname, np_f):
        xv = np.random.rand(3, 4).astype(np.float64) + 0.5
        x = pt.to_tensor(xv.astype(np.float32), stop_gradient=False)
        getattr(pt, opname)(x).sum().backward()

        def f(v):
            return float(np_f(v).sum())
        ng = numeric_grad(f, xv.copy())
        np.testing.assert_allclose(x.grad.numpy(), ng, rtol=1e-2, atol=1e-3)

    def test_matmul_grad(self):
        a = np.random.randn(3, 4).astype(np.float32)
        b = np.random.randn(4, 5).astype(np.float32)
        x = pt.to_tensor(a, stop_gradient=False)
        y = pt.to_tensor(b, stop_gradient=False)
        pt.matmul(x, y).sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), np.ones((3, 5)) @ b.T, rtol=1e-4)
        np.testing.assert_allclose(y.grad.numpy(), a.T @ np.ones((3, 5)), rtol=1e-4)

    def test_reduction_grads(self):
        xv = np.random.randn(4, 5).astype(np.float32)
        x = pt.to_tensor(xv, stop_gradient=False)
        pt.mean(x).backward()
        np.testing.assert_allclose(x.grad.numpy(), np.full((4, 5), 1 / 20), rtol=1e-5)

    def test_getitem_grad(self):
        x = pt.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3),
                         stop_gradient=False)
        x[0].sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [[1, 1, 1], [0, 0, 0]])

    def test_concat_grad(self):
        x = pt.to_tensor([1.0, 2.0], stop_gradient=False)
        y = pt.to_tensor([3.0], stop_gradient=False)
        pt.concat([x, y]).sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [1, 1])
        np.testing.assert_allclose(y.grad.numpy(), [1])

    def test_where_grad(self):
        x = pt.to_tensor([1.0, -1.0], stop_gradient=False)
        cond = pt.to_tensor([True, False])
        y = pt.where(cond, x * 2.0, x * 3.0)
        y.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [2, 3])


class TestPyLayer:
    def test_forward_backward(self):
        import paddle_tpu.autograd as ag

        class CubeLayer(ag.PyLayer):
            @staticmethod
            def forward(ctx, x):
                ctx.save_for_backward(x)
                return x * x * x

            @staticmethod
            def backward(ctx, dy):
                (x,) = ctx.saved_tensor()
                return dy * 3.0 * x * x

        a = pt.to_tensor(2.0, stop_gradient=False)
        y = CubeLayer.apply(a)
        assert abs(y.item() - 8.0) < 1e-6
        y.backward()
        assert abs(a.grad.item() - 12.0) < 1e-5

    def test_multi_input_output(self):
        import paddle_tpu.autograd as ag

        class MulAdd(ag.PyLayer):
            @staticmethod
            def forward(ctx, x, y):
                ctx.save_for_backward(x, y)
                return x * y, x + y

            @staticmethod
            def backward(ctx, dprod, dsum):
                x, y = ctx.saved_tensor()
                return dprod * y + dsum, dprod * x + dsum

        a = pt.to_tensor(3.0, stop_gradient=False)
        b = pt.to_tensor(4.0, stop_gradient=False)
        p, s = MulAdd.apply(a, b)
        (p + 2.0 * s).backward()
        # d/da (ab + 2(a+b)) = b + 2 = 6 ; d/db = a + 2 = 5
        assert abs(a.grad.item() - 6.0) < 1e-5
        assert abs(b.grad.item() - 5.0) < 1e-5

    def test_none_grad_and_nontensor_input(self):
        import paddle_tpu.autograd as ag

        class ScaleFirst(ag.PyLayer):
            @staticmethod
            def forward(ctx, x, y, k):
                return x * k + y * 0.0

            @staticmethod
            def backward(ctx, dy):
                return dy * 5.0, None

        a = pt.to_tensor(1.0, stop_gradient=False)
        b = pt.to_tensor(1.0, stop_gradient=False)
        out = ScaleFirst.apply(a, b, 5.0)
        out.backward()
        assert abs(a.grad.item() - 5.0) < 1e-5
        assert b.grad is None

    def test_backward_arity_mismatch_raises(self):
        import paddle_tpu.autograd as ag

        class Bad(ag.PyLayer):
            @staticmethod
            def forward(ctx, x, y):
                return x + y

            @staticmethod
            def backward(ctx, dy):
                return dy  # only one grad for two tensor inputs

        a = pt.to_tensor(1.0, stop_gradient=False)
        b = pt.to_tensor(1.0, stop_gradient=False)
        out = Bad.apply(a, b)
        with pytest.raises(ValueError):
            out.backward()

    def test_trains_in_layer(self):
        """A PyLayer op inside an nn.Layer trains end-to-end."""
        import paddle_tpu.autograd as ag
        import paddle_tpu.nn as nn

        class SquareFn(ag.PyLayer):
            @staticmethod
            def forward(ctx, x):
                ctx.save_for_backward(x)
                return x * x

            @staticmethod
            def backward(ctx, dy):
                (x,) = ctx.saved_tensor()
                return dy * 2.0 * x

        lin = nn.Linear(4, 4)
        x = pt.to_tensor(np.random.randn(2, 4).astype("float32"))
        y = SquareFn.apply(lin(x)).sum()
        y.backward()
        assert lin.weight.grad is not None
        assert np.isfinite(lin.weight.grad.numpy()).all()


class TestCreateGraph:
    def test_grad_of_grad_matches_jax(self):
        import jax
        import jax.numpy as jnp

        def f(x):
            return jnp.sin(x) * x * x

        x0 = 0.7
        a = pt.to_tensor(x0, stop_gradient=False)
        y = (a * a) * pt.sin(a)
        (g,) = pt.grad(y, a, create_graph=True)
        (gg,) = pt.grad(g, a)
        expect_g = jax.grad(f)(jnp.float32(x0))
        expect_gg = jax.grad(jax.grad(f))(jnp.float32(x0))
        assert abs(g.item() - float(expect_g)) < 1e-5
        assert abs(gg.item() - float(expect_gg)) < 1e-4

    def test_third_order(self):
        import jax
        import jax.numpy as jnp

        a = pt.to_tensor(0.5, stop_gradient=False)
        y = a * a * a * a          # x^4
        (g1,) = pt.grad(y, a, create_graph=True)     # 4x^3
        (g2,) = pt.grad(g1, a, create_graph=True)    # 12x^2
        (g3,) = pt.grad(g2, a)                       # 24x
        assert abs(g1.item() - 4 * 0.5 ** 3) < 1e-5
        assert abs(g2.item() - 12 * 0.5 ** 2) < 1e-5
        assert abs(g3.item() - 24 * 0.5) < 1e-4

    def test_create_graph_multivar(self):
        # grad-of-grad on a 2-var function: f = x^2 * y; d2f/dxdy = 2x
        x = pt.to_tensor(3.0, stop_gradient=False)
        y = pt.to_tensor(5.0, stop_gradient=False)
        f = x * x * y
        (gx,) = pt.grad(f, x, create_graph=True)     # 2xy
        (gxy,) = pt.grad(gx, y)                      # 2x
        assert abs(gxy.item() - 6.0) < 1e-5

    def test_pylayer_create_graph(self):
        import paddle_tpu.autograd as ag

        class Cube(ag.PyLayer):
            @staticmethod
            def forward(ctx, x):
                ctx.save_for_backward(x)
                return x * x * x

            @staticmethod
            def backward(ctx, dy):
                (x,) = ctx.saved_tensor()
                return dy * 3.0 * x * x

        a = pt.to_tensor(2.0, stop_gradient=False)
        y = Cube.apply(a)
        (g,) = pt.grad(y, a, create_graph=True)      # 3x^2 = 12
        (gg,) = pt.grad(g, a)                        # 6x = 12
        assert abs(g.item() - 12.0) < 1e-5
        assert abs(gg.item() - 12.0) < 1e-4


class TestRecompute:
    def test_recompute_matches_plain(self):
        from paddle_tpu.distributed.fleet import recompute
        import paddle_tpu.nn as nn

        lin1 = nn.Linear(8, 8)
        lin2 = nn.Linear(8, 8)

        def block(x):
            return lin2(pt.nn.functional.relu(lin1(x)))

        class Block:
            def parameters(self):
                return list(lin1.parameters()) + list(lin2.parameters())

            def __call__(self, x):
                return block(x)

        xnp = np.random.randn(4, 8).astype("float32")
        x1 = pt.to_tensor(xnp, stop_gradient=False)
        y1 = recompute(Block(), x1).sum()
        y1.backward()
        g_rc = [p.grad.numpy().copy() for p in Block().parameters()]
        gx_rc = x1.grad.numpy().copy()

        for p in Block().parameters():
            p.clear_grad()
        x2 = pt.to_tensor(xnp, stop_gradient=False)
        y2 = block(x2).sum()
        y2.backward()
        g_pl = [p.grad.numpy() for p in Block().parameters()]
        np.testing.assert_allclose(float(y1.numpy()), float(y2.numpy()),
                                   rtol=1e-5)
        np.testing.assert_allclose(gx_rc, x2.grad.numpy(), rtol=1e-5,
                                   atol=1e-6)
        for a, b in zip(g_rc, g_pl):
            np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)

    def test_recompute_sequential(self):
        from paddle_tpu.distributed.fleet import recompute_sequential
        import paddle_tpu.nn as nn

        layers = [nn.Linear(6, 6) for _ in range(4)]
        x = pt.to_tensor(np.random.randn(2, 6).astype("float32"),
                         stop_gradient=False)
        y = recompute_sequential({"segments": 2}, layers, x)
        y.sum().backward()
        assert x.grad is not None
        for lyr in layers:
            assert lyr.weight.grad is not None

    def test_recompute_inside_jit(self):
        """Functional mode: recompute traces jax.checkpoint into the
        program (no tape), grads flow via jax.grad."""
        import jax
        import jax.numpy as jnp
        from paddle_tpu.distributed.fleet import recompute
        from paddle_tpu.core import state

        def f(x):
            with state.functional_mode():
                def fn(t):
                    return t * t * t
                return recompute(fn, pt.Tensor(x))._data.sum()

        g = jax.grad(f)(jnp.arange(4.0))
        np.testing.assert_allclose(np.asarray(g), 3 * np.arange(4.0) ** 2,
                                   rtol=1e-6)

    def test_pylayer_duplicate_input_positional_grads(self):
        """Same Tensor passed twice: each slot's grad accumulates."""
        import paddle_tpu.autograd as ag

        class TwoSlot(ag.PyLayer):
            @staticmethod
            def forward(ctx, x, y):
                return x * 1.0 + y * 2.0

            @staticmethod
            def backward(ctx, dy):
                return dy * 1.0, dy * 2.0

        a = pt.to_tensor(1.0, stop_gradient=False)
        TwoSlot.apply(a, a).backward()
        assert abs(a.grad.item() - 3.0) < 1e-6
