"""Tape engine tests — numeric parity with finite differences, the same
strategy as the reference's OpTest.check_grad (test/legacy_test/op_test.py)."""
import numpy as np
import pytest

import paddle_tpu as pt


def numeric_grad(f, x, eps=1e-3):
    """Central finite differences of scalar f at numpy x."""
    g = np.zeros_like(x, dtype=np.float64)
    flat = x.reshape(-1)
    gf = g.reshape(-1)
    for i in range(flat.size):
        orig = flat[i]
        flat[i] = orig + eps
        fp = f(x.copy().reshape(x.shape))
        flat[i] = orig - eps
        fm = f(x.copy().reshape(x.shape))
        flat[i] = orig
        gf[i] = (fp - fm) / (2 * eps)
    return g


class TestBackwardBasics:
    def test_simple_chain(self):
        a = pt.to_tensor(2.0, stop_gradient=False)
        b = a * a * a
        b.backward()
        assert abs(a.grad.item() - 12.0) < 1e-5

    def test_grad_accumulation(self):
        a = pt.to_tensor(3.0, stop_gradient=False)
        (a * 2.0).backward()
        (a * 5.0).backward()
        assert abs(a.grad.item() - 7.0) < 1e-5

    def test_clear_grad(self):
        a = pt.to_tensor(3.0, stop_gradient=False)
        (a * 2.0).backward()
        a.clear_grad()
        assert a.grad is None

    def test_diamond(self):
        # y = x*x used twice: dz/dx = 2*(x*x)' contributions
        x = pt.to_tensor(3.0, stop_gradient=False)
        y = x * x
        z = y + y
        z.backward()
        assert abs(x.grad.item() - 12.0) < 1e-5

    def test_stop_gradient_blocks(self):
        x = pt.to_tensor(1.0, stop_gradient=False)
        y = pt.to_tensor(1.0)  # stop_gradient=True
        z = x * y
        z.backward()
        assert y.grad is None
        assert x.grad is not None

    def test_detach_cuts_graph(self):
        x = pt.to_tensor(2.0, stop_gradient=False)
        y = (x * x).detach()
        z = y * x
        z.backward()
        assert abs(x.grad.item() - 4.0) < 1e-5  # only via z=y*x

    def test_backward_nonscalar_requires_grad_tensor(self):
        x = pt.to_tensor([1.0, 2.0], stop_gradient=False)
        y = x * 2.0
        with pytest.raises(RuntimeError):
            y.backward()
        y = x * 2.0
        y.backward(pt.to_tensor([1.0, 1.0]))
        np.testing.assert_allclose(x.grad.numpy(), [2, 2])

    def test_double_backward_without_retain_raises(self):
        x = pt.to_tensor(2.0, stop_gradient=False)
        y = x * x
        y.backward(retain_graph=False)
        with pytest.raises(RuntimeError):
            y.backward()

    def test_retain_graph(self):
        x = pt.to_tensor(2.0, stop_gradient=False)
        y = x * x
        y.backward(retain_graph=True)
        y.backward()
        assert abs(x.grad.item() - 8.0) < 1e-5

    def test_backward_on_error_path(self):
        t = pt.to_tensor(1.0)  # stop_gradient True
        with pytest.raises(RuntimeError):
            t.backward()

    def test_multi_output_op(self):
        x = pt.to_tensor(np.array([3.0, 1.0, 2.0], np.float32), stop_gradient=False)
        vals, idx = pt.topk(x, 2)
        vals.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [1, 0, 1])

    def test_no_grad_context(self):
        x = pt.to_tensor(1.0, stop_gradient=False)
        with pt.no_grad():
            y = x * 2.0
        assert y.stop_gradient
        assert y._grad_node is None

    def test_hooks(self):
        x = pt.to_tensor(1.0, stop_gradient=False)
        seen = []

        def hook(g):
            seen.append(g.item())
            return g * 2.0
        x.register_hook(hook)
        (x * 3.0).backward()
        assert seen == [3.0]
        assert abs(x.grad.item() - 6.0) < 1e-5

    def test_intermediate_hook(self):
        x = pt.to_tensor(2.0, stop_gradient=False)
        y = x * x
        y.register_hook(lambda g: g * 10.0)
        z = y * 3.0
        z.backward()
        # dz/dy=3 -> hook -> 30 -> dy/dx=2x=4 -> 120
        assert abs(x.grad.item() - 120.0) < 1e-4

    def test_retain_grads_intermediate(self):
        x = pt.to_tensor(2.0, stop_gradient=False)
        y = x * x
        y.retain_grads()
        z = y * 3.0
        z.backward()
        assert abs(y.grad.item() - 3.0) < 1e-5


class TestGradAPI:
    def test_grad_basic(self):
        x = pt.to_tensor(2.0, stop_gradient=False)
        y = x * x
        (gx,) = pt.grad(y, x)
        assert abs(gx.item() - 4.0) < 1e-5
        assert x.grad is None  # .grad untouched

    def test_grad_multiple_inputs(self):
        x = pt.to_tensor(2.0, stop_gradient=False)
        w = pt.to_tensor(3.0, stop_gradient=False)
        y = x * w + x
        gx, gw = pt.grad(y, [x, w])
        assert abs(gx.item() - 4.0) < 1e-5
        assert abs(gw.item() - 2.0) < 1e-5


class TestNumericParity:
    @pytest.mark.parametrize("opname,np_f", [
        ("exp", np.exp), ("tanh", np.tanh), ("sqrt", np.sqrt),
        ("log", np.log), ("sigmoid", lambda v: 1 / (1 + np.exp(-v))),
    ])
    def test_unary_grads(self, opname, np_f):
        xv = np.random.rand(3, 4).astype(np.float64) + 0.5
        x = pt.to_tensor(xv.astype(np.float32), stop_gradient=False)
        getattr(pt, opname)(x).sum().backward()

        def f(v):
            return float(np_f(v).sum())
        ng = numeric_grad(f, xv.copy())
        np.testing.assert_allclose(x.grad.numpy(), ng, rtol=1e-2, atol=1e-3)

    def test_matmul_grad(self):
        a = np.random.randn(3, 4).astype(np.float32)
        b = np.random.randn(4, 5).astype(np.float32)
        x = pt.to_tensor(a, stop_gradient=False)
        y = pt.to_tensor(b, stop_gradient=False)
        pt.matmul(x, y).sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), np.ones((3, 5)) @ b.T, rtol=1e-4)
        np.testing.assert_allclose(y.grad.numpy(), a.T @ np.ones((3, 5)), rtol=1e-4)

    def test_reduction_grads(self):
        xv = np.random.randn(4, 5).astype(np.float32)
        x = pt.to_tensor(xv, stop_gradient=False)
        pt.mean(x).backward()
        np.testing.assert_allclose(x.grad.numpy(), np.full((4, 5), 1 / 20), rtol=1e-5)

    def test_getitem_grad(self):
        x = pt.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3),
                         stop_gradient=False)
        x[0].sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [[1, 1, 1], [0, 0, 0]])

    def test_concat_grad(self):
        x = pt.to_tensor([1.0, 2.0], stop_gradient=False)
        y = pt.to_tensor([3.0], stop_gradient=False)
        pt.concat([x, y]).sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [1, 1])
        np.testing.assert_allclose(y.grad.numpy(), [1])

    def test_where_grad(self):
        x = pt.to_tensor([1.0, -1.0], stop_gradient=False)
        cond = pt.to_tensor([True, False])
        y = pt.where(cond, x * 2.0, x * 3.0)
        y.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [2, 3])
