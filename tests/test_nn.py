"""nn layer tests — numeric parity against NumPy/JAX references, mirroring
the reference's OpTest strategy (test/legacy_test/op_test.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def t(x, sg=True):
    return paddle.to_tensor(np.asarray(x, dtype=np.float32),
                            stop_gradient=sg)


class TestFunctionalActivations:
    def test_relu(self):
        x = t([[-1.0, 2.0], [3.0, -4.0]])
        np.testing.assert_allclose(F.relu(x).numpy(),
                                   [[0, 2], [3, 0]], rtol=1e-6)

    def test_softmax_rows_sum_to_one(self):
        x = t(np.random.randn(4, 7))
        s = F.softmax(x).numpy()
        np.testing.assert_allclose(s.sum(-1), np.ones(4), rtol=1e-5)

    def test_gelu_matches_scipy_form(self):
        x = np.linspace(-3, 3, 13).astype(np.float32)
        got = F.gelu(t(x)).numpy()
        from math import erf, sqrt
        want = np.array([0.5 * v * (1 + erf(v / sqrt(2))) for v in x],
                        dtype=np.float32)
        np.testing.assert_allclose(got, want, atol=1e-5)

    def test_silu_swish(self):
        x = t(np.random.randn(5))
        np.testing.assert_allclose(F.silu(x).numpy(), F.swish(x).numpy())

    def test_activation_grad(self):
        x = t(np.random.randn(3, 3), sg=False)
        y = paddle.sum(F.relu(x) * 2.0)
        y.backward()
        want = np.where(x.numpy() > 0, 2.0, 0.0)
        np.testing.assert_allclose(x.grad.numpy(), want)


class TestLinearEmbedding:
    def test_linear_matches_numpy(self):
        l = nn.Linear(6, 3)
        x = t(np.random.randn(4, 6))
        want = x.numpy() @ l.weight.numpy() + l.bias.numpy()
        np.testing.assert_allclose(l(x).numpy(), want, rtol=1e-5)

    def test_linear_no_bias(self):
        l = nn.Linear(6, 3, bias_attr=False)
        assert l.bias is None

    def test_embedding_lookup_and_padding(self):
        e = nn.Embedding(10, 4, padding_idx=0)
        ids = paddle.to_tensor(np.array([[1, 0, 3]]))
        out = e(ids)
        assert out.shape == [1, 3, 4]
        np.testing.assert_allclose(out.numpy()[0, 1], np.zeros(4))

    def test_embedding_grad_scatters(self):
        e = nn.Embedding(5, 3)
        ids = paddle.to_tensor(np.array([1, 1, 2]))
        out = paddle.sum(e(ids))
        out.backward()
        g = e.weight.grad.numpy()
        np.testing.assert_allclose(g[1], 2 * np.ones(3))
        np.testing.assert_allclose(g[2], np.ones(3))
        np.testing.assert_allclose(g[0], np.zeros(3))


class TestNorms:
    def test_layer_norm_stats(self):
        ln = nn.LayerNorm(16)
        x = t(np.random.randn(4, 16) * 5 + 3)
        y = ln(x).numpy()
        np.testing.assert_allclose(y.mean(-1), np.zeros(4), atol=1e-5)
        np.testing.assert_allclose(y.std(-1), np.ones(4), atol=1e-2)

    def test_rms_norm(self):
        rn = nn.RMSNorm(8)
        x = t(np.random.randn(2, 8))
        y = rn(x).numpy()
        xn = x.numpy()
        want = xn / np.sqrt((xn ** 2).mean(-1, keepdims=True) + 1e-6)
        np.testing.assert_allclose(y, want, rtol=1e-4)

    def test_batch_norm_train_updates_stats(self):
        bn = nn.BatchNorm1D(4, data_format="NCL")
        x = t(np.random.randn(8, 4, 5) * 2 + 1)
        bn.train()
        y = bn(x)
        # running stats moved toward batch stats
        assert not np.allclose(bn._mean.numpy(), np.zeros(4))
        bn.eval()
        y2 = bn(x)
        assert y2.shape == x.shape

    def test_group_norm(self):
        gn = nn.GroupNorm(2, 4)
        x = t(np.random.randn(2, 4, 3, 3))
        y = gn(x)
        assert y.shape == x.shape


class TestConvPool:
    def test_conv2d_identity_kernel(self):
        conv = nn.Conv2D(1, 1, 3, padding=1, bias_attr=False)
        w = np.zeros((1, 1, 3, 3), np.float32)
        w[0, 0, 1, 1] = 1.0
        conv.weight.set_value(w)
        x = t(np.random.randn(1, 1, 5, 5))
        np.testing.assert_allclose(conv(x).numpy(), x.numpy(), atol=1e-6)

    def test_conv2d_shape_stride(self):
        conv = nn.Conv2D(3, 8, 3, stride=2, padding=1)
        x = t(np.random.randn(2, 3, 8, 8))
        assert conv(x).shape == [2, 8, 4, 4]

    def test_conv2d_groups(self):
        conv = nn.Conv2D(4, 8, 3, groups=2, padding=1)
        x = t(np.random.randn(1, 4, 6, 6))
        assert conv(x).shape == [1, 8, 6, 6]

    def test_conv_transpose_shape(self):
        convt = nn.Conv2DTranspose(3, 6, 4, stride=2, padding=1)
        x = t(np.random.randn(2, 3, 8, 8))
        assert convt(x).shape == [2, 6, 16, 16]

    def test_conv1d(self):
        conv = nn.Conv1D(2, 4, 3, padding=1)
        x = t(np.random.randn(2, 2, 10))
        assert conv(x).shape == [2, 4, 10]

    def test_max_pool(self):
        x = t(np.arange(16).reshape(1, 1, 4, 4))
        y = F.max_pool2d(x, kernel_size=2)
        np.testing.assert_allclose(y.numpy()[0, 0],
                                   [[5, 7], [13, 15]])

    def test_avg_pool(self):
        x = t(np.ones((1, 1, 4, 4)))
        y = F.avg_pool2d(x, kernel_size=2)
        np.testing.assert_allclose(y.numpy(), np.ones((1, 1, 2, 2)))

    def test_avg_pool_inclusive_ceil(self):
        # exclusive=False counts padding cells in the divisor, but never the
        # ceil_mode extension (reference pooling kernel semantics).
        x = t(np.ones((1, 1, 4, 4)))
        y = F.avg_pool2d(x, kernel_size=2, stride=2, padding=1,
                         exclusive=False, ceil_mode=True)
        # corner window: 1 real + 3 pad cells -> 1/4
        assert y.shape == [1, 1, 3, 3]
        np.testing.assert_allclose(y.numpy()[0, 0, 0, 0], 0.25)
        np.testing.assert_allclose(y.numpy()[0, 0, 1, 1], 1.0)

    def test_avg_pool_exclusive_pad(self):
        x = t(np.ones((1, 1, 4, 4)))
        y = F.avg_pool2d(x, kernel_size=2, stride=2, padding=1,
                         exclusive=True, ceil_mode=True)
        np.testing.assert_allclose(y.numpy()[0, 0], np.ones((3, 3)))

    def test_adaptive_avg_pool(self):
        x = t(np.random.randn(2, 3, 8, 8))
        y = F.adaptive_avg_pool2d(x, output_size=1)
        np.testing.assert_allclose(
            y.numpy()[..., 0, 0], x.numpy().mean((-1, -2)), rtol=1e-5)

    def test_conv_grad(self):
        conv = nn.Conv2D(1, 2, 3)
        x = t(np.random.randn(1, 1, 5, 5), sg=False)
        loss = paddle.sum(conv(x) ** 2)
        loss.backward()
        assert conv.weight.grad is not None
        assert x.grad.shape == x.shape


class TestDropout:
    def test_eval_is_identity(self):
        d = nn.Dropout(0.5)
        d.eval()
        x = t(np.random.randn(10, 10))
        np.testing.assert_allclose(d(x).numpy(), x.numpy())

    def test_train_zeroes_and_scales(self):
        paddle.seed(0)
        d = nn.Dropout(0.5)
        x = t(np.ones((100, 100)))
        y = d(x).numpy()
        assert (y == 0).mean() > 0.3
        nz = y[y != 0]
        np.testing.assert_allclose(nz, 2 * np.ones_like(nz))

    def test_dropout2d_channelwise(self):
        paddle.seed(0)
        x = t(np.ones((4, 8, 5, 5)))
        y = F.dropout2d(x, p=0.5, training=True).numpy()
        flat = y.reshape(4, 8, -1)
        for b in range(4):
            for c in range(8):
                ch = flat[b, c]
                assert (ch == 0).all() or (ch == 2).all()


class TestLosses:
    def test_cross_entropy_matches_manual(self):
        logits = np.random.randn(6, 5).astype(np.float32)
        labels = np.array([0, 1, 2, 3, 4, 0])
        got = float(F.cross_entropy(t(logits), paddle.to_tensor(labels)))
        e = np.exp(logits - logits.max(-1, keepdims=True))
        p = e / e.sum(-1, keepdims=True)
        want = -np.log(p[np.arange(6), labels]).mean()
        assert abs(got - want) < 1e-5

    def test_cross_entropy_ignore_index(self):
        logits = np.random.randn(4, 3).astype(np.float32)
        labels = np.array([0, -100, 2, -100])
        got = float(F.cross_entropy(t(logits), paddle.to_tensor(labels)))
        e = np.exp(logits - logits.max(-1, keepdims=True))
        p = e / e.sum(-1, keepdims=True)
        want = -np.log(p[[0, 2], [0, 2]]).mean()
        assert abs(got - want) < 1e-5

    def test_mse(self):
        a, b = np.random.randn(5), np.random.randn(5)
        got = float(F.mse_loss(t(a), t(b)))
        assert abs(got - ((a - b) ** 2).mean()) < 1e-6

    def test_bce_with_logits(self):
        z = np.random.randn(8).astype(np.float32)
        y = (np.random.rand(8) > 0.5).astype(np.float32)
        got = float(F.binary_cross_entropy_with_logits(t(z), t(y)))
        p = 1 / (1 + np.exp(-z))
        want = -(y * np.log(p) + (1 - y) * np.log(1 - p)).mean()
        assert abs(got - want) < 1e-5

    def test_kl_div(self):
        logp = np.log(np.array([[0.2, 0.8]], dtype=np.float32))
        target = np.array([[0.5, 0.5]], dtype=np.float32)
        got = float(F.kl_div(t(logp), t(target), reduction="sum"))
        want = (target * (np.log(target) - logp)).sum()
        assert abs(got - want) < 1e-5

    def test_loss_layers(self):
        ce = nn.CrossEntropyLoss()
        out = ce(t(np.random.randn(3, 4)), paddle.to_tensor([0, 1, 2]))
        assert out.shape == []
        sl = nn.SmoothL1Loss()
        assert sl(t([1.0, 2.0]), t([1.5, 0.0])).shape == []


class TestAttentionTransformer:
    def test_sdpa_matches_manual(self):
        B, S, H, D = 2, 4, 2, 8
        q = np.random.randn(B, S, H, D).astype(np.float32)
        k = np.random.randn(B, S, H, D).astype(np.float32)
        v = np.random.randn(B, S, H, D).astype(np.float32)
        got = F.scaled_dot_product_attention(t(q), t(k), t(v)).numpy()
        # manual
        qt, kt, vt = [a.transpose(0, 2, 1, 3) for a in (q, k, v)]
        logits = qt @ kt.transpose(0, 1, 3, 2) / np.sqrt(D)
        e = np.exp(logits - logits.max(-1, keepdims=True))
        p = e / e.sum(-1, keepdims=True)
        want = (p @ vt).transpose(0, 2, 1, 3)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_causal_masking(self):
        B, S, H, D = 1, 5, 1, 4
        q = np.random.randn(B, S, H, D).astype(np.float32)
        k = np.random.randn(B, S, H, D).astype(np.float32)
        v = np.random.randn(B, S, H, D).astype(np.float32)
        out = F.scaled_dot_product_attention(
            t(q), t(k), t(v), is_causal=True).numpy()
        # first position attends only to itself
        np.testing.assert_allclose(out[0, 0, 0], v[0, 0, 0], rtol=1e-5)

    def test_encoder_layer(self):
        layer = nn.TransformerEncoderLayer(16, 4, 32, dropout=0.0)
        enc = nn.TransformerEncoder(layer, 2)
        x = t(np.random.randn(2, 6, 16))
        assert enc(x).shape == [2, 6, 16]

    def test_full_transformer(self):
        m = nn.Transformer(d_model=16, nhead=2, num_encoder_layers=1,
                           num_decoder_layers=1, dim_feedforward=32,
                           dropout=0.0)
        src = t(np.random.randn(2, 5, 16))
        tgt = t(np.random.randn(2, 3, 16))
        assert m(src, tgt).shape == [2, 3, 16]


class TestRNN:
    def test_lstm_shapes(self):
        lstm = nn.LSTM(4, 8, num_layers=2)
        x = t(np.random.randn(3, 6, 4))
        out, (h, c) = lstm(x)
        assert out.shape == [3, 6, 8]
        assert h.shape == [2, 3, 8]
        assert c.shape == [2, 3, 8]

    def test_gru_bidirect(self):
        gru = nn.GRU(4, 8, direction="bidirect")
        x = t(np.random.randn(3, 6, 4))
        out, h = gru(x)
        assert out.shape == [3, 6, 16]
        assert h.shape == [2, 3, 8]

    def test_lstm_grad(self):
        lstm = nn.LSTM(4, 8)
        x = t(np.random.randn(2, 5, 4), sg=False)
        out, _ = lstm(x)
        paddle.sum(out).backward()
        assert x.grad.shape == x.shape
        assert lstm.weight_ih_l0.grad is not None

    def test_lstm_cell_consistency(self):
        """Fused scan must equal stepwise cell application."""
        paddle.seed(42)
        lstm = nn.LSTM(3, 5)
        cell = nn.LSTMCell(3, 5)
        cell.weight_ih.set_value(lstm.weight_ih_l0.numpy())
        cell.weight_hh.set_value(lstm.weight_hh_l0.numpy())
        cell.bias_ih.set_value(lstm.bias_ih_l0.numpy())
        cell.bias_hh.set_value(lstm.bias_hh_l0.numpy())
        x = t(np.random.randn(2, 4, 3))
        out, _ = lstm(x)
        h = c = paddle.zeros([2, 5])
        ys = []
        state = (h, c)
        for i in range(4):
            y, state = cell(x[:, i], state)
            ys.append(y.numpy())
        np.testing.assert_allclose(out.numpy(),
                                   np.stack(ys, 1), rtol=1e-4, atol=1e-5)


class TestLayerMechanics:
    def test_state_dict_roundtrip(self):
        m1 = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
        m2 = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
        m2.set_state_dict(m1.state_dict())
        x = t(np.random.randn(3, 4))
        np.testing.assert_allclose(m1(x).numpy(), m2(x).numpy())

    def test_named_parameters(self):
        m = nn.Sequential(nn.Linear(2, 2), nn.Linear(2, 2))
        names = dict(m.named_parameters())
        assert "0.weight" in names and "1.bias" in names

    def test_train_eval_propagates(self):
        m = nn.Sequential(nn.Linear(2, 2), nn.Dropout(0.5))
        m.eval()
        assert not m[1].training
        m.train()
        assert m[1].training

    def test_apply_and_sublayers(self):
        m = nn.Sequential(nn.Linear(2, 2), nn.Sequential(nn.Linear(2, 2)))
        count = []
        m.apply(lambda l: count.append(type(l).__name__))
        assert "Linear" in count and len(count) >= 4

    def test_forward_hooks(self):
        l = nn.Linear(2, 2)
        calls = []
        h = l.register_forward_post_hook(
            lambda layer, inp, out: calls.append(1))
        l(t(np.ones((1, 2))))
        assert calls == [1]
        h.remove()
        l(t(np.ones((1, 2))))
        assert calls == [1]

    def test_layer_to_dtype(self):
        import jax.numpy as jnp
        l = nn.Linear(2, 2)
        l.to(dtype="bfloat16")
        assert l.weight.dtype == jnp.bfloat16

    def test_containers(self):
        ll = nn.LayerList([nn.Linear(2, 2) for _ in range(3)])
        assert len(ll) == 3
        ll.append(nn.Linear(2, 2))
        assert len(ll) == 4
        ld = nn.LayerDict({"a": nn.Linear(2, 2)})
        assert "a" in ld

    def test_buffers_in_state_dict(self):
        bn = nn.BatchNorm1D(3, data_format="NCL")
        sd = bn.state_dict()
        assert "_mean" in sd and "_variance" in sd


class TestParityFixes:
    """Regression tests for Paddle-parity parameters that are easy to drop
    silently (found via review): ceil_mode, padding_mode, output_size,
    sequence_length, dropout downscale mode, gumbel sampling."""

    def test_ceil_mode_shapes(self):
        x = t(np.random.randn(1, 1, 6, 6))
        assert F.max_pool2d(x, kernel_size=3, stride=2,
                            ceil_mode=True).shape == [1, 1, 3, 3]
        assert F.max_pool2d(x, kernel_size=3, stride=2).shape == [1, 1, 2, 2]
        ya = F.avg_pool2d(t(np.ones((1, 1, 6, 6))), kernel_size=3, stride=2,
                          ceil_mode=True)
        np.testing.assert_allclose(ya.numpy(), np.ones((1, 1, 3, 3)))

    def test_conv_transpose_output_size(self):
        x = t(np.random.randn(1, 2, 7, 7))
        convt = nn.Conv2DTranspose(2, 3, 3, stride=2, padding=1)
        assert convt(x, output_size=[14, 14]).shape == [1, 3, 14, 14]
        assert convt(x).shape == [1, 3, 13, 13]

    def test_conv_padding_mode_reflect(self):
        c = nn.Conv2D(1, 1, 3, padding=1, padding_mode="reflect",
                      bias_attr=False)
        xi = t(np.random.randn(1, 1, 5, 5))
        want = F.conv2d(F.pad(xi, [1, 1, 1, 1], mode="reflect"), c.weight,
                        stride=1, padding=0).numpy()
        np.testing.assert_allclose(c(xi).numpy(), want, rtol=1e-5)

    def test_dropout_downscale_in_infer(self):
        x = t(np.ones(10))
        y = F.dropout(x, p=0.5, training=False, mode="downscale_in_infer")
        np.testing.assert_allclose(y.numpy(), 0.5 * np.ones(10))

    def test_gumbel_softmax_samples(self):
        paddle.seed(3)
        logits = t(np.zeros((4, 8)))
        g1 = F.gumbel_softmax(logits, hard=True).numpy()
        g2 = F.gumbel_softmax(logits, hard=True).numpy()
        assert not np.allclose(g1, g2)
        np.testing.assert_allclose(g1.sum(-1), np.ones(4))

    def test_lstm_sequence_length(self):
        paddle.seed(4)
        lstm = nn.LSTM(3, 5)
        xfull = np.random.randn(2, 6, 3).astype(np.float32)
        lens = paddle.to_tensor(np.array([4, 6], np.int32))
        out, (h, c) = lstm(t(xfull), sequence_length=lens)
        out_p, (h_p, c_p) = lstm(t(xfull[:, :4]))
        np.testing.assert_allclose(h.numpy()[0, 0], h_p.numpy()[0, 0],
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(out.numpy()[0, 4:], np.zeros((2, 5)),
                                   atol=1e-6)

    def test_gru_bidirect_sequence_length(self):
        paddle.seed(4)
        gru = nn.GRU(3, 4, direction="bidirect")
        xfull = np.random.randn(2, 6, 3).astype(np.float32)
        lens = paddle.to_tensor(np.array([4, 6], np.int32))
        ob, hb = gru(t(xfull), sequence_length=lens)
        ob_p, hb_p = gru(t(xfull[:, :4]))
        np.testing.assert_allclose(ob.numpy()[0, :4], ob_p.numpy()[0],
                                   rtol=1e-5, atol=1e-6)


class TestVarlenAttention:
    def test_unpadded_matches_per_sequence(self):
        """Packed ragged attention == per-sequence dense attention."""
        import paddle_tpu.nn.functional as F
        rng = np.random.default_rng(0)
        lens = [5, 3, 8]
        T, H, D = sum(lens), 2, 16
        q = rng.normal(size=(T, H, D)).astype("float32")
        k = rng.normal(size=(T, H, D)).astype("float32")
        v = rng.normal(size=(T, H, D)).astype("float32")
        cu = np.cumsum([0] + lens).astype("int32")
        out, _ = F.flash_attn_unpadded(
            paddle.to_tensor(q), paddle.to_tensor(k), paddle.to_tensor(v),
            paddle.to_tensor(cu), paddle.to_tensor(cu), causal=True)
        out = np.asarray(out.numpy())
        import jax.numpy as jnp
        for i, L in enumerate(lens):
            lo, hi = cu[i], cu[i + 1]
            ref = F.sdpa_reference(jnp.asarray(q[None, lo:hi]),
                                   jnp.asarray(k[None, lo:hi]),
                                   jnp.asarray(v[None, lo:hi]), causal=True)
            np.testing.assert_allclose(out[lo:hi], np.asarray(ref)[0],
                                       rtol=1e-4, atol=1e-5)

    def test_padding_tokens_zero(self):
        import paddle_tpu.nn.functional as F
        rng = np.random.default_rng(1)
        T, H, D = 8, 1, 8
        q = rng.normal(size=(T, H, D)).astype("float32")
        cu = np.array([0, 5], "int32")   # tokens 5..7 are padding
        out, _ = F.flash_attn_unpadded(
            paddle.to_tensor(q), paddle.to_tensor(q), paddle.to_tensor(q),
            paddle.to_tensor(cu), paddle.to_tensor(cu))
        np.testing.assert_allclose(np.asarray(out.numpy())[5:], 0.0)

    def test_segment_ids(self):
        import paddle_tpu.nn.functional as F
        import jax.numpy as jnp
        seg = F.segment_ids_from_cu_seqlens(jnp.array([0, 2, 5]), 7)
        np.testing.assert_array_equal(np.asarray(seg),
                                      [0, 0, 1, 1, 1, -1, -1])

    def test_varlen_grad_flows(self):
        import paddle_tpu.nn.functional as F
        rng = np.random.default_rng(2)
        q = paddle.to_tensor(rng.normal(size=(6, 1, 8)).astype("float32"),
                             stop_gradient=False)
        cu = paddle.to_tensor(np.array([0, 3, 6], "int32"))
        out, _ = F.flash_attn_unpadded(q, q, q, cu, cu, causal=True)
        out.sum().backward()
        assert np.isfinite(q.grad.numpy()).all()

    def test_varlen_causal_differing_cu_seqlens(self):
        """Causal masking is SEGMENT-LOCAL: q and k prefix sums differ."""
        import jax.numpy as jnp
        import paddle_tpu.nn.functional as F
        rng = np.random.default_rng(4)
        lens_q, lens_k = [2, 2], [3, 3]
        cq = np.cumsum([0] + lens_q).astype("int32")
        ck = np.cumsum([0] + lens_k).astype("int32")
        H, D = 1, 8
        q = rng.normal(size=(sum(lens_q), H, D)).astype("float32")
        k = rng.normal(size=(sum(lens_k), H, D)).astype("float32")
        v = rng.normal(size=(sum(lens_k), H, D)).astype("float32")
        out, _ = F.flash_attn_unpadded(
            paddle.to_tensor(q), paddle.to_tensor(k), paddle.to_tensor(v),
            paddle.to_tensor(cq), paddle.to_tensor(ck), causal=True)
        out = np.asarray(out.numpy())
        assert np.abs(out).max() > 0      # no fully-masked rows
        # per-sequence reference with local causal alignment
        for i in range(2):
            qs = q[cq[i]:cq[i+1]]
            ks = k[ck[i]:ck[i+1]]
            vs = v[ck[i]:ck[i+1]]
            s = np.einsum("qhd,khd->hqk", qs, ks) / np.sqrt(D)
            mask = np.arange(len(qs))[:, None] >= np.arange(len(ks))[None, :]
            s = np.where(mask[None], s, -1e30)
            p = np.exp(s - s.max(-1, keepdims=True))
            p = p / p.sum(-1, keepdims=True)
            ref = np.einsum("hqk,khd->qhd", p, vs)
            np.testing.assert_allclose(out[cq[i]:cq[i+1]], ref,
                                       rtol=1e-4, atol=1e-5)

    def test_varlen_unsupported_options_raise(self):
        import paddle_tpu.nn.functional as F
        q = paddle.to_tensor(np.zeros((4, 1, 8), "float32"))
        cu = paddle.to_tensor(np.array([0, 4], "int32"))
        with pytest.raises(NotImplementedError, match="dropout"):
            F.flash_attn_unpadded(q, q, q, cu, cu, dropout=0.1)
        with pytest.raises(NotImplementedError, match="softmax"):
            F.flash_attn_unpadded(q, q, q, cu, cu, return_softmax=True)


class TestSdpKernelRestore:
    """ADVICE-r4: sdp_kernel(enable_flash=False) must restore the exact
    dispatcher installed on entry, not clobber it with a fresh
    tpu_only=True registration."""

    def test_restores_prior_impl_verbatim(self):
        import paddle_tpu.nn.functional as F
        from paddle_tpu.nn.functional import attention as att

        prev = att._FLASH_IMPL
        try:
            sentinel = lambda *a, **k: None
            att.register_flash_impl(sentinel)
            with F.sdp_kernel(enable_flash=False):
                assert att._FLASH_IMPL is None
            assert att._FLASH_IMPL is sentinel
            # deliberately-unregistered state also survives
            att.register_flash_impl(None)
            with F.sdp_kernel(enable_flash=False):
                pass
            assert att._FLASH_IMPL is None
        finally:
            att.register_flash_impl(prev)
