"""Minimal numpy ONNX interpreter for validating paddle_tpu.onnx
exports end-to-end (no onnx/onnxruntime exists in this environment).
Executes exactly the op subset the converter emits; an unknown op is a
test failure, not a skip."""
import numpy as np

from paddle_tpu.onnx import onnx_pb2 as P

_NP_DTYPE = {
    P.TensorProto.FLOAT: np.float32, P.TensorProto.DOUBLE: np.float64,
    P.TensorProto.FLOAT16: np.float16, P.TensorProto.INT32: np.int32,
    P.TensorProto.INT64: np.int64, P.TensorProto.INT16: np.int16,
    P.TensorProto.INT8: np.int8, P.TensorProto.UINT8: np.uint8,
    P.TensorProto.BOOL: np.bool_,
}


def tensor_to_np(t):
    if t.data_type == P.TensorProto.BFLOAT16:
        import jax.numpy as jnp
        raw = np.frombuffer(t.raw_data, np.uint16).reshape(tuple(t.dims))
        return np.asarray(raw.view(jnp.bfloat16), np.float32)
    dt = _NP_DTYPE[t.data_type]
    if t.raw_data:
        return np.frombuffer(t.raw_data, dt).reshape(tuple(t.dims)).copy()
    if t.float_data:
        return np.asarray(t.float_data, dt).reshape(tuple(t.dims))
    if t.int64_data:
        return np.asarray(t.int64_data, dt).reshape(tuple(t.dims))
    return np.zeros(tuple(t.dims), dt)


def _attrs(node):
    out = {}
    for a in node.attribute:
        if a.type == P.AttributeProto.INT:
            out[a.name] = a.i
        elif a.type == P.AttributeProto.FLOAT:
            out[a.name] = a.f
        elif a.type == P.AttributeProto.STRING:
            out[a.name] = a.s.decode()
        elif a.type == P.AttributeProto.INTS:
            out[a.name] = list(a.ints)
        elif a.type == P.AttributeProto.FLOATS:
            out[a.name] = list(a.floats)
        elif a.type == P.AttributeProto.GRAPH:
            out[a.name] = a.g
    return out


def _conv(x, w, attrs):
    group = attrs.get("group", 1)
    strides = attrs.get("strides", [1, 1])
    dil = attrs.get("dilations", [1, 1])
    pads = attrs.get("pads", [0] * 4)
    nsp = x.ndim - 2
    pad_width = [(0, 0), (0, 0)] + [
        (pads[i], pads[nsp + i]) for i in range(nsp)]
    x = np.pad(x, pad_width)
    N, C = x.shape[:2]
    O, I = w.shape[:2]
    ksp = w.shape[2:]
    out_sp = [
        (x.shape[2 + i] - (dil[i] * (ksp[i] - 1) + 1)) // strides[i] + 1
        for i in range(nsp)]
    out = np.zeros((N, O, *out_sp), np.float32)
    cg, og = C // group, O // group
    for g in range(group):
        for o in range(og):
            for idx in np.ndindex(*out_sp):
                patch = x[:, g * cg:(g + 1) * cg]
                sl = tuple(
                    slice(idx[i] * strides[i],
                          idx[i] * strides[i] + dil[i] * (ksp[i] - 1) + 1,
                          dil[i])
                    for i in range(nsp))
                val = (patch[(slice(None), slice(None)) + sl]
                       * w[g * og + o]).sum(axis=tuple(range(1, 2 + nsp)))
                out[(slice(None), g * og + o) + idx] = val
    return out


def run(model, inputs):
    """Execute the graph; returns list of output arrays."""
    g = model.graph
    env = {}
    for t in g.initializer:
        env[t.name] = tensor_to_np(t)
    names = [vi.name for vi in g.input]
    assert len(names) == len(inputs), (names, len(inputs))
    for n, x in zip(names, inputs):
        env[n] = np.asarray(x)
    _exec_nodes(g, env)
    return [env[vi.name] for vi in g.output]


def _exec_nodes(g, env):
    """Execute g.node into env (which may hold outer-scope tensors —
    ONNX subgraphs read enclosing-graph names)."""
    for node in g.node:
        i = [env[n] for n in node.input]
        a = _attrs(node)
        op = node.op_type
        if op == "Identity":
            r = i[0]
        elif op == "Add":
            r = i[0] + i[1]
        elif op == "Sub":
            r = i[0] - i[1]
        elif op == "Mul":
            r = i[0] * i[1]
        elif op == "Div":
            r = i[0] / i[1]
        elif op == "Max":
            r = np.maximum(i[0], i[1])
        elif op == "Min":
            r = np.minimum(i[0], i[1])
        elif op == "Neg":
            r = -i[0]
        elif op == "Exp":
            r = np.exp(i[0])
        elif op == "Log":
            r = np.log(i[0])
        elif op == "Tanh":
            r = np.tanh(i[0])
        elif op == "Sigmoid":
            r = 1.0 / (1.0 + np.exp(-i[0]))
        elif op == "Sqrt":
            r = np.sqrt(i[0])
        elif op == "Reciprocal":
            r = 1.0 / i[0]
        elif op == "Abs":
            r = np.abs(i[0])
        elif op == "Sign":
            r = np.sign(i[0])
        elif op == "Floor":
            r = np.floor(i[0])
        elif op == "Ceil":
            r = np.ceil(i[0])
        elif op == "Round":
            r = np.round(i[0])
        elif op == "Erf":
            from scipy.special import erf as _erf  # noqa
            r = _erf(i[0]).astype(i[0].dtype)
        elif op == "Pow":
            r = np.power(i[0], i[1]).astype(i[0].dtype)
        elif op == "Not":
            r = ~i[0]
        elif op == "And":
            r = i[0] & i[1]
        elif op == "Or":
            r = i[0] | i[1]
        elif op == "Mod":
            r = np.fmod(i[0], i[1])
        elif op == "Sin":
            r = np.sin(i[0])
        elif op == "Cos":
            r = np.cos(i[0])
        elif op == "Cast":
            r = i[0].astype(_NP_DTYPE[a["to"]] if a["to"] !=
                            P.TensorProto.BFLOAT16 else np.float32)
        elif op == "Reshape":
            r = i[0].reshape(tuple(int(d) for d in i[1]))
        elif op == "Shape":
            r = np.asarray(i[0].shape, np.int64)
        elif op == "Transpose":
            r = np.transpose(i[0], a["perm"])
        elif op == "Expand":
            r = np.broadcast_to(i[0], tuple(int(d) for d in i[1]))
        elif op == "ReduceSum":
            axes = tuple(int(d) for d in i[1])
            r = i[0].sum(axis=axes, keepdims=bool(a.get("keepdims", 1)))
        elif op in ("ReduceMax", "ReduceMin", "ReduceProd"):
            f = {"ReduceMax": np.max, "ReduceMin": np.min,
                 "ReduceProd": np.prod}[op]
            r = f(i[0], axis=tuple(a["axes"]),
                  keepdims=bool(a.get("keepdims", 1)))
        elif op in ("ArgMax", "ArgMin"):
            f = np.argmax if op == "ArgMax" else np.argmin
            r = f(i[0], axis=a["axis"])
            if a.get("keepdims", 1):
                r = np.expand_dims(r, a["axis"])
        elif op == "Concat":
            r = np.concatenate(i, axis=a["axis"])
        elif op == "Slice":
            starts, ends, axes, steps = (i[1], i[2], i[3], i[4])
            idx = [slice(None)] * i[0].ndim
            imax = np.iinfo(np.int64).max
            for s, e, ax, st in zip(starts, ends, axes, steps):
                s, e = int(s), int(e)
                e = None if e >= imax else (None if e <= -imax else e)
                idx[int(ax)] = slice(s, e, int(st))
            r = i[0][tuple(idx)]
        elif op == "Pad":
            pads = [int(d) for d in i[1]]
            n = len(pads) // 2
            r = np.pad(i[0], [(pads[k], pads[n + k]) for k in range(n)],
                       constant_values=i[2] if len(i) > 2 else 0)
        elif op == "Where":
            r = np.where(i[0], i[1], i[2])
        elif op == "Equal":
            r = i[0] == i[1]
        elif op == "Less":
            r = i[0] < i[1]
        elif op == "LessOrEqual":
            r = i[0] <= i[1]
        elif op == "Greater":
            r = i[0] > i[1]
        elif op == "GreaterOrEqual":
            r = i[0] >= i[1]
        elif op == "Einsum":
            r = np.einsum(a["equation"], *i)
        elif op == "Gather":
            r = np.take(i[0], i[1].astype(np.int64), axis=a["axis"])
        elif op == "GatherElements":
            r = np.take_along_axis(i[0], i[1].astype(np.int64),
                                   axis=a["axis"])
        elif op == "GatherND":
            idx = i[1].astype(np.int64)
            r = i[0][tuple(np.moveaxis(idx, -1, 0))]
        elif op == "Conv":
            r = _conv(i[0].astype(np.float32), i[1].astype(np.float32),
                      a)
        elif op in ("MaxPool", "AveragePool"):
            kernel = a["kernel_shape"]
            strides = a.get("strides", [1] * len(kernel))
            pads = a.get("pads", [0] * (2 * len(kernel)))
            nsp = len(kernel)
            fill = (-np.inf if op == "MaxPool" else 0.0)
            x = np.pad(i[0].astype(np.float64),
                       [(0, 0), (0, 0)] + [(pads[k], pads[nsp + k])
                                           for k in range(nsp)],
                       constant_values=fill)
            out_sp = [(x.shape[2 + k] - kernel[k]) // strides[k] + 1
                      for k in range(nsp)]
            r = np.zeros(i[0].shape[:2] + tuple(out_sp))
            for idx in np.ndindex(*out_sp):
                sl = tuple(slice(idx[k] * strides[k],
                                 idx[k] * strides[k] + kernel[k])
                           for k in range(nsp))
                win = x[(slice(None), slice(None)) + sl]
                red = (win.max(axis=tuple(range(2, 2 + nsp)))
                       if op == "MaxPool"
                       else win.mean(axis=tuple(range(2, 2 + nsp))))
                r[(slice(None), slice(None)) + idx] = red
            r = r.astype(i[0].dtype)
        elif op == "Clip":
            r = np.clip(i[0], i[1], i[2])
        elif op == "CumSum":
            r = np.cumsum(i[0], axis=int(i[1]))
        elif op == "TopK":
            k = int(i[1][0])
            axis = a.get("axis", -1)
            order = np.argsort(i[0], axis=axis, kind="stable")
            if a.get("largest", 1):
                order = np.flip(order, axis=axis)
            idx = np.take(order, range(k), axis=axis)
            r = (np.take_along_axis(i[0], idx, axis=axis),
                 idx.astype(np.int64))
        elif op == "If":
            branch = a["then_branch"] if bool(i[0]) else a["else_branch"]
            benv = dict(env)   # subgraphs read enclosing-graph names
            for bt in branch.initializer:
                benv[bt.name] = tensor_to_np(bt)
            _exec_nodes(branch, benv)
            r = tuple(benv[vi.name] for vi in branch.output)
        elif op == "Loop":
            body = a["body"]
            trip, cond = int(i[0]), bool(i[1])
            carries = list(i[2:])
            n_carry = len(carries)
            n_scan = len(node.output) - n_carry
            scans = [[] for _ in range(n_scan)]
            t = 0
            outer = dict(env)   # loop-invariant: outer scope + body inits
            for bt in body.initializer:
                outer[bt.name] = tensor_to_np(bt)
            while t < trip and cond:
                benv = dict(outer)
                bi = body.input
                benv[bi[0].name] = np.asarray(t, np.int64)
                benv[bi[1].name] = np.asarray(cond)
                for vi, c in zip(bi[2:], carries):
                    benv[vi.name] = c
                _exec_nodes(body, benv)
                outs = [benv[vi.name] for vi in body.output]
                cond = bool(outs[0])
                carries = outs[1:1 + n_carry]
                for k, v in enumerate(outs[1 + n_carry:]):
                    scans[k].append(v)
                t += 1
            r = tuple(carries + [np.stack(s, axis=0) for s in scans])
        else:
            raise AssertionError(f"interpreter has no op {op}")
        if not isinstance(r, tuple):
            r = (r,)
        for nm, val in zip(node.output, r):
            env[nm] = np.asarray(val)
