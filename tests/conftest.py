"""Test harness config.

All tests run on CPU with 8 virtual devices so mesh/SPMD tests work without
TPU hardware — the equivalent of the reference's N-local-process distributed
test strategy (SURVEY.md §4: test/legacy_test/test_dist_base.py) realized as
single-process multi-device."""
import os

# Force CPU: the session sitecustomize registers the shared-TPU "axon"
# backend and overrides jax_platforms at interpreter start, so the env var
# alone is not enough — update the config after import. Tests must NOT claim
# the single TPU chip.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = _flags + " --xla_force_host_platform_device_count=8"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _seed():
    import paddle_tpu as pt
    pt.seed(2024)
    np.random.seed(2024)
    # Full-precision matmuls for numeric parity checks (production default is
    # MXU-friendly reduced precision).
    pt.set_flags({"FLAGS_default_matmul_precision": "highest"})
    yield
    pt.set_flags({"FLAGS_default_matmul_precision": "default"})
