"""CAPABILITY_DELTA.md stale-claim self-check (VERDICT-r4 weak #4).

The delta doc is the SURVEY §2.9 official record of deliberate drops.
Round 4 showed it can rot: the elastic row still said heartbeats were
"not built" two commits after distributed/heartbeat.py landed. This
gives the doc the same discipline docs/attr_delta.json already has
(the attr sweep fails on stale entries):

- Any row asserting a feature is NOT built must carry a machine-
  checkable token ``absent:<dotted.path>``. The moment that path starts
  resolving, the test fails, forcing the doc row to be updated in the
  same round the delta closes.
- The bare phrase "not built" (and variants) without a token is itself
  a failure — untagged claims cannot be checked.
"""
import importlib
import re
from pathlib import Path

DOC = Path(__file__).resolve().parents[1] / "docs" / "CAPABILITY_DELTA.md"


def _resolve(dotted):
    """Import the longest importable module prefix, then walk attrs.
    Returns the object or None if any step is missing."""
    parts = dotted.split(".")
    for i in range(len(parts), 0, -1):
        name = ".".join(parts[:i])
        try:
            obj = importlib.import_module(name)
        except ImportError:
            continue
        for attr in parts[i:]:
            if not hasattr(obj, attr):
                return None
            obj = getattr(obj, attr)
        return obj
    return None


def test_absent_tokens_still_absent():
    text = DOC.read_text()
    tokens = re.findall(r"`absent:([A-Za-z_][\w.]*)`", text)
    assert tokens, "delta doc must carry at least one absent: token"
    stale = [t for t in tokens if _resolve(t) is not None]
    assert not stale, (
        f"CAPABILITY_DELTA.md claims these are absent but they resolve: "
        f"{stale}. The feature landed — update the doc row in the same "
        f"round (VERDICT-r4 weak #4 discipline).")


def test_not_built_claims_are_tagged():
    text = DOC.read_text()
    untagged = []
    for n, line in enumerate(text.splitlines(), 1):
        if re.search(r"\bnot built\b|\bnot yet built\b|\bno converter\b",
                     line, re.I) and "absent:" not in line:
            untagged.append(n)
    assert not untagged, (
        f"CAPABILITY_DELTA.md lines {untagged} claim something is not "
        f"built without an `absent:<dotted.path>` token, so the claim "
        f"cannot be machine-checked for staleness. Tag it.")
