"""Examples stay runnable: compile-check all scripts, execute the fast
ones end to end in subprocesses (fresh interpreter, like a user)."""
import os
import py_compile
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EXAMPLES = os.path.join(ROOT, "examples")


def _run(script, extra_env=None, timeout=420):
    env = dict(os.environ)
    env["PYTHONPATH"] = ROOT
    env["JAX_PLATFORMS"] = "cpu"
    env.update(extra_env or {})
    return subprocess.run(
        [sys.executable, os.path.join(EXAMPLES, script)],
        capture_output=True, text=True, env=env, timeout=timeout)


def test_all_examples_compile():
    scripts = [f for f in os.listdir(EXAMPLES) if f.endswith(".py")]
    assert len(scripts) >= 5
    for s in scripts:
        py_compile.compile(os.path.join(EXAMPLES, s), doraise=True)


def test_serve_predictor_example_runs():
    r = _run("serve_predictor.py")
    assert r.returncode == 0, r.stderr[-800:]
    assert "parity with eager: OK" in r.stdout


@pytest.mark.slow  # tier-1 budget (ISSUE 3): heavy; run in the slow lane
def test_ring_attention_example_runs():
    r = _run("long_context_ring_attention.py",
             {"XLA_FLAGS": "--xla_force_host_platform_device_count=8"})
    assert r.returncode == 0, r.stderr[-800:]
    assert "exact parity OK" in r.stdout


@pytest.mark.slow  # tier-1 budget (ISSUE 3): heavy; run in the slow lane
def test_onnx_export_example_runs():
    r = _run("export_onnx.py")
    assert r.returncode == 0, r.stderr[-800:]
    assert "onnx export: OK" in r.stdout


def test_engine_planning_example_runs():
    r = _run("plan_parallel_engine.py")
    assert r.returncode == 0, r.stderr[-800:]
    assert "engine planning: OK" in r.stdout
