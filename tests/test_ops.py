"""Op numeric tests vs NumPy (reference strategy: OpTest,
test/legacy_test/op_test.py:418 — outputs compared against NumPy)."""
import numpy as np
import pytest

import paddle_tpu as pt


def T(a, sg=True):
    return pt.to_tensor(np.asarray(a), stop_gradient=sg)


class TestCreation:
    def test_factories(self):
        np.testing.assert_allclose(pt.zeros([2, 3]).numpy(), np.zeros((2, 3)))
        np.testing.assert_allclose(pt.ones([2]).numpy(), [1, 1])
        np.testing.assert_allclose(pt.full([2], 7.0).numpy(), [7, 7])
        np.testing.assert_allclose(pt.arange(5).numpy(), np.arange(5))
        np.testing.assert_allclose(pt.arange(1, 7, 2).numpy(), [1, 3, 5])
        np.testing.assert_allclose(pt.linspace(0, 1, 5).numpy(), np.linspace(0, 1, 5))
        np.testing.assert_allclose(pt.eye(3).numpy(), np.eye(3))

    def test_like_factories(self):
        x = T(np.ones((2, 2), np.float32))
        assert pt.zeros_like(x).shape == [2, 2]
        np.testing.assert_allclose(pt.full_like(x, 3.0).numpy(), np.full((2, 2), 3))

    def test_tri(self):
        x = T(np.ones((3, 3), np.float32))
        np.testing.assert_allclose(pt.tril(x).numpy(), np.tril(np.ones((3, 3))))
        np.testing.assert_allclose(pt.triu(x, diagonal=1).numpy(),
                                   np.triu(np.ones((3, 3)), 1))

    def test_assign(self):
        out = pt.zeros([2])
        pt.assign(T([5.0, 6.0]), out)
        np.testing.assert_allclose(out.numpy(), [5, 6])


class TestMath:
    def test_elementwise(self):
        a = np.random.randn(3, 4).astype(np.float32)
        x = T(a)
        np.testing.assert_allclose(pt.exp(x).numpy(), np.exp(a), rtol=1e-5)
        np.testing.assert_allclose(pt.abs(x).numpy(), np.abs(a), rtol=1e-6)
        np.testing.assert_allclose(pt.tanh(x).numpy(), np.tanh(a), rtol=1e-4)
        np.testing.assert_allclose(pt.square(x).numpy(), a * a, rtol=1e-6)
        np.testing.assert_allclose(pt.sign(x).numpy(), np.sign(a))

    def test_clip(self):
        x = T([-2.0, 0.5, 3.0])
        np.testing.assert_allclose(pt.clip(x, min=-1, max=1).numpy(), [-1, 0.5, 1])

    def test_cumsum(self):
        x = T(np.arange(6, dtype=np.float32).reshape(2, 3))
        np.testing.assert_allclose(pt.cumsum(x, axis=1).numpy(),
                                   np.cumsum(x.numpy(), axis=1))

    def test_add_n(self):
        xs = [T([1.0]), T([2.0]), T([3.0])]
        np.testing.assert_allclose(pt.add_n(xs).numpy(), [6])

    def test_maximum_minimum(self):
        a, b = T([1.0, 5.0]), T([3.0, 2.0])
        np.testing.assert_allclose(pt.maximum(a, b).numpy(), [3, 5])
        np.testing.assert_allclose(pt.minimum(a, b).numpy(), [1, 2])

    def test_logsumexp(self):
        a = np.random.randn(4).astype(np.float32)
        np.testing.assert_allclose(pt.logsumexp(T(a)).numpy(),
                                   np.log(np.exp(a).sum()), rtol=1e-4)


class TestReduction:
    def test_basic(self):
        a = np.random.randn(3, 4).astype(np.float32)
        x = T(a)
        np.testing.assert_allclose(pt.sum(x).numpy(), a.sum(), rtol=1e-5)
        np.testing.assert_allclose(pt.mean(x, axis=0).numpy(), a.mean(0), rtol=1e-5)
        np.testing.assert_allclose(pt.max(x, axis=1).numpy(), a.max(1), rtol=1e-6)
        np.testing.assert_allclose(pt.min(x).numpy(), a.min(), rtol=1e-6)
        np.testing.assert_allclose(pt.prod(x, axis=0).numpy(), a.prod(0), rtol=1e-4)

    def test_keepdim(self):
        x = T(np.ones((2, 3), np.float32))
        assert pt.sum(x, axis=1, keepdim=True).shape == [2, 1]

    def test_argmax(self):
        a = np.array([[1, 5, 2], [7, 0, 3]], np.float32)
        np.testing.assert_array_equal(pt.argmax(T(a), axis=1).numpy(), [1, 0])

    def test_std_var(self):
        a = np.random.randn(10).astype(np.float32)
        np.testing.assert_allclose(pt.std(T(a)).numpy(), a.std(ddof=1), rtol=1e-4)
        np.testing.assert_allclose(pt.var(T(a), unbiased=False).numpy(),
                                   a.var(), rtol=1e-4)

    def test_any_all(self):
        x = T(np.array([True, False]))
        assert bool(pt.any(x).numpy())
        assert not bool(pt.all(x).numpy())


class TestManipulation:
    def test_reshape_transpose(self):
        a = np.arange(6, dtype=np.float32)
        x = T(a)
        assert pt.reshape(x, shape=[2, 3]).shape == [2, 3]
        y = pt.reshape(x, shape=[2, -1])
        assert y.shape == [2, 3]
        z = pt.transpose(y, perm=[1, 0])
        assert z.shape == [3, 2]

    def test_squeeze_unsqueeze(self):
        x = T(np.zeros((1, 3, 1), np.float32))
        assert pt.squeeze(x).shape == [3]
        assert pt.squeeze(x, axis=0).shape == [3, 1]
        assert pt.unsqueeze(T([1.0, 2.0]), axis=0).shape == [1, 2]
        assert pt.unsqueeze(T([1.0, 2.0]), axis=[0, 2]).shape == [1, 2, 1]

    def test_concat_stack_split(self):
        x, y = T([[1.0, 2]]), T([[3.0, 4]])
        assert pt.concat([x, y], axis=0).shape == [2, 2]
        assert pt.stack([x, y], axis=0).shape == [2, 1, 2]
        parts = pt.split(T(np.arange(10, dtype=np.float32)), 2)
        assert len(parts) == 2 and parts[0].shape == [5]
        parts = pt.split(T(np.arange(10, dtype=np.float32)), [3, -1])
        assert parts[1].shape == [7]

    def test_gather_scatter(self):
        x = T(np.arange(12, dtype=np.float32).reshape(4, 3))
        idx = T(np.array([0, 2]))
        np.testing.assert_allclose(pt.gather(x, idx).numpy(), x.numpy()[[0, 2]])
        upd = T(np.ones((2, 3), np.float32))
        out = pt.scatter(x, idx, upd)
        np.testing.assert_allclose(out.numpy()[0], [1, 1, 1])

    def test_where_masked(self):
        x = T(np.array([1.0, -2.0, 3.0]))
        np.testing.assert_allclose(
            pt.masked_fill(x, T(np.array([True, False, True])), value=0.0).numpy(),
            [0, -2, 0])

    def test_tile_expand(self):
        x = T([[1.0, 2.0]])
        assert pt.tile(x, repeat_times=[2, 2]).shape == [2, 4]
        assert pt.expand(x, shape=[3, 2]).shape == [3, 2]
        assert pt.broadcast_to(x, shape=[3, 2]).shape == [3, 2]

    def test_flip_roll(self):
        x = T(np.arange(4, dtype=np.float32))
        np.testing.assert_allclose(pt.flip(x, axis=0).numpy(), [3, 2, 1, 0])
        np.testing.assert_allclose(pt.roll(x, shifts=1).numpy(), [3, 0, 1, 2])

    def test_pad(self):
        x = T(np.ones((2, 2), np.float32))
        out = pt.pad(x, pad=[1, 1], value=0.0)
        assert out.shape == [2, 4]

    def test_topk_sort(self):
        x = T(np.array([3.0, 1.0, 4.0, 1.0, 5.0]))
        v, i = pt.topk(x, 2)
        np.testing.assert_allclose(v.numpy(), [5, 4])
        np.testing.assert_array_equal(i.numpy(), [4, 2])
        np.testing.assert_allclose(pt.sort(x, descending=True).numpy(),
                                   [5, 4, 3, 1, 1])

    def test_one_hot(self):
        out = pt.one_hot(T(np.array([0, 2])), 3)
        np.testing.assert_allclose(out.numpy(), [[1, 0, 0], [0, 0, 1]])

    def test_unique(self):
        out = pt.unique(T(np.array([3, 1, 2, 1, 3])))
        np.testing.assert_array_equal(out.numpy(), [1, 2, 3])

    def test_take_put_along_axis(self):
        x = T(np.arange(6, dtype=np.float32).reshape(2, 3))
        idx = T(np.array([[0], [2]]))
        np.testing.assert_allclose(
            pt.take_along_axis(x, idx, axis=1).numpy(), [[0], [5]])


class TestLinalg:
    def test_matmul(self):
        a = np.random.randn(2, 3).astype(np.float32)
        b = np.random.randn(3, 4).astype(np.float32)
        np.testing.assert_allclose(pt.matmul(T(a), T(b)).numpy(), a @ b, rtol=1e-4)
        np.testing.assert_allclose(
            pt.matmul(T(a), T(b.T), transpose_y=True).numpy(), a @ b, rtol=1e-4)

    def test_batched_matmul(self):
        a = np.random.randn(5, 2, 3).astype(np.float32)
        b = np.random.randn(5, 3, 4).astype(np.float32)
        np.testing.assert_allclose(pt.bmm(T(a), T(b)).numpy(), a @ b, rtol=1e-4)

    def test_einsum(self):
        a = np.random.randn(2, 3).astype(np.float32)
        b = np.random.randn(3, 4).astype(np.float32)
        np.testing.assert_allclose(pt.einsum("ij,jk->ik", T(a), T(b)).numpy(),
                                   a @ b, rtol=1e-4)

    def test_norm(self):
        a = np.array([3.0, 4.0], np.float32)
        np.testing.assert_allclose(pt.norm(T(a)).numpy(), 5.0, rtol=1e-5)
        m = np.random.randn(3, 3).astype(np.float32)
        np.testing.assert_allclose(pt.norm(T(m), p="fro").numpy(),
                                   np.linalg.norm(m), rtol=1e-5)

    def test_solve_inv(self):
        a = np.array([[2.0, 0], [0, 4.0]], np.float32)
        np.testing.assert_allclose(pt.inv(T(a)).numpy(),
                                   np.linalg.inv(a), rtol=1e-5)
        b = np.array([[2.0], [8.0]], np.float32)
        np.testing.assert_allclose(pt.solve(T(a), T(b)).numpy(),
                                   np.linalg.solve(a, b), rtol=1e-5)

    def test_svd_qr(self):
        m = np.random.randn(4, 3).astype(np.float32)
        u, s, vh = pt.svd(T(m))   # reference convention: x = U diag(S) VH
        rec = u.numpy() @ np.diag(s.numpy()) @ vh.numpy()
        np.testing.assert_allclose(rec, m, rtol=1e-3, atol=1e-4)
        q, r = pt.qr(T(m))
        np.testing.assert_allclose(q.numpy() @ r.numpy(), m, rtol=1e-3, atol=1e-4)


class TestRandom:
    def test_determinism_with_seed(self):
        pt.seed(7)
        a = pt.randn([4]).numpy()
        pt.seed(7)
        b = pt.randn([4]).numpy()
        np.testing.assert_allclose(a, b)

    def test_shapes_ranges(self):
        u = pt.uniform([100], min=0.0, max=1.0)
        assert u.shape == [100]
        assert float(u.numpy().min()) >= 0 and float(u.numpy().max()) <= 1
        r = pt.randint(0, 5, [50])
        assert r.numpy().min() >= 0 and r.numpy().max() < 5
        p = pt.randperm(10).numpy()
        np.testing.assert_array_equal(np.sort(p), np.arange(10))

    def test_rng_scope_purity(self):
        import jax
        from paddle_tpu.framework.random import rng_scope
        with rng_scope(jax.random.PRNGKey(0)):
            a = pt.randn([3]).numpy()
        with rng_scope(jax.random.PRNGKey(0)):
            b = pt.randn([3]).numpy()
        np.testing.assert_allclose(a, b)
