"""paddle.static + paddle.inference tests.

Reference strategy: test/legacy_test/test_executor_* (feed/fetch parity),
test_inference_api.py (predictor IO binding), save in one process and
serve in a *fresh* process (the deploy contract).
"""
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import static


class TestStaticProgram:
    def test_build_inspect_run(self):
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [None, 4], "float32")
            y = pt.exp(x) * 2.0
            z = pt.sum(y, axis=1)
        assert len(main.ops()) >= 2
        assert "exp" in str(main)
        exe = static.Executor()
        xin = np.random.randn(3, 4).astype("float32")
        (zout,) = exe.run(main, feed={"x": xin}, fetch_list=[z])
        np.testing.assert_allclose(zout, (np.exp(xin) * 2).sum(1), rtol=1e-5)

    def test_multiple_feeds_and_fetches(self):
        main = static.Program()
        with static.program_guard(main):
            a = static.data("a", [2, 3], "float32")
            b = static.data("b", [2, 3], "float32")
            s = a + b
            p = a * b
        exe = static.Executor()
        an = np.random.randn(2, 3).astype("float32")
        bn = np.random.randn(2, 3).astype("float32")
        souts = exe.run(main, feed={"a": an, "b": bn}, fetch_list=[s, p])
        np.testing.assert_allclose(souts[0], an + bn, rtol=1e-6)
        np.testing.assert_allclose(souts[1], an * bn, rtol=1e-6)

    def test_layer_params_live(self):
        """Parameters used by a Layer under program_guard are read live at
        each run — an update between runs changes the output without a
        recompile-and-bake."""
        import paddle_tpu.nn as nn
        lin = nn.Linear(4, 2)
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [1, 4], "float32")
            y = lin(x)
        exe = static.Executor()
        xin = np.ones((1, 4), "float32")
        (y1,) = exe.run(main, feed={"x": xin}, fetch_list=[y])
        lin.weight.set_value(pt.to_tensor(lin.weight.numpy() * 2))
        (y2,) = exe.run(main, feed={"x": xin}, fetch_list=[y])
        b = lin.bias.numpy()
        np.testing.assert_allclose(y2 - b, (y1 - b) * 2, rtol=1e-4,
                                   atol=1e-5)

    def test_append_backward_matches_eager(self):
        import paddle_tpu.nn as nn
        lin = nn.Linear(4, 1)
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [8, 4], "float32")
            loss = pt.mean(lin(x) ** 2)
        grads = static.append_backward(loss)
        assert len(grads) == 2   # weight + bias
        exe = static.Executor()
        xin = np.random.randn(8, 4).astype("float32")
        outs = exe.run(main, feed={"x": xin},
                       fetch_list=[loss] + [g for _, g in grads])

        # eager reference
        xe = pt.to_tensor(xin)
        le = pt.mean(lin(xe) ** 2)
        le.backward()
        np.testing.assert_allclose(outs[0], le.numpy(), rtol=1e-5)
        eager = {id(lin.weight): lin.weight.grad.numpy(),
                 id(lin.bias): lin.bias.grad.numpy()}
        for (p, _), got in zip(grads, outs[1:]):
            np.testing.assert_allclose(got, eager[id(p)], rtol=1e-4,
                                       atol=1e-6)

    def test_static_training_loop_converges(self):
        """The build-once/run-many static training workflow (reference:
        Executor-driven fit loops) — manual SGD on fetched grads."""
        import paddle_tpu.nn as nn
        lin = nn.Linear(4, 1)
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [16, 4], "float32")
            t = static.data("t", [16, 1], "float32")
            loss = pt.mean((lin(x) - t) ** 2)
        grads = static.append_backward(loss)
        exe = static.Executor()
        exe.run(static.default_startup_program())

        rng = np.random.default_rng(0)
        xin = rng.normal(size=(16, 4)).astype("float32")
        tgt = (xin @ rng.normal(size=(4, 1)).astype("float32") + 0.3)
        first = None
        for i in range(60):
            outs = exe.run(main, feed={"x": xin, "t": tgt.astype("float32")},
                           fetch_list=[loss] + [g for _, g in grads])
            if first is None:
                first = outs[0]
            for (p, _), g in zip(grads, outs[1:]):
                p.set_value(pt.to_tensor(p.numpy() - 0.1 * g))
        assert outs[0] < 0.05 * first

    def test_enable_disable_static(self):
        assert pt.in_dynamic_mode()
        pt.enable_static()
        assert pt.in_static_mode()
        pt.disable_static()
        assert pt.in_dynamic_mode()

    def test_save_load_inference_model(self, tmp_path):
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [2, 4], "float32")
            y = pt.tanh(x) * 3.0
        exe = static.Executor()
        prefix = str(tmp_path / "m")
        static.save_inference_model(prefix, [x], [y], exe)
        prog, feed_names, fetch_names = static.load_inference_model(
            prefix, exe)
        xin = np.random.randn(2, 4).astype("float32")
        (out,) = prog.run({feed_names[0]: xin})
        np.testing.assert_allclose(out, np.tanh(xin) * 3.0, rtol=1e-5)


class TestPredictor:
    def _save_artifact(self, tmp_path):
        import paddle_tpu.nn as nn
        from paddle_tpu.jit import InputSpec

        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(4, 3)

            def forward(self, x):
                return pt.nn.functional.softmax(self.fc(x), axis=-1)

        net = Net()
        prefix = str(tmp_path / "net")
        pt.jit.save(net, prefix, input_spec=[InputSpec([None, 4],
                                                       "float32")])
        xin = np.random.randn(5, 4).astype("float32")
        expect = net(pt.to_tensor(xin)).numpy()
        return prefix, xin, expect

    def test_predictor_handles(self, tmp_path):
        from paddle_tpu import inference
        prefix, xin, expect = self._save_artifact(tmp_path)
        cfg = inference.Config(prefix)
        pred = inference.create_predictor(cfg)
        names = pred.get_input_names()
        h = pred.get_input_handle(names[0])
        h.copy_from_cpu(xin)
        pred.run()
        out = pred.get_output_handle(pred.get_output_names()[0]).copy_to_cpu()
        np.testing.assert_allclose(out, expect, rtol=1e-4, atol=1e-5)

    def test_predictor_positional_run(self, tmp_path):
        from paddle_tpu import inference
        prefix, xin, expect = self._save_artifact(tmp_path)
        pred = inference.create_predictor(inference.Config(prefix))
        (out,) = pred.run([xin])
        np.testing.assert_allclose(out, expect, rtol=1e-4, atol=1e-5)

    @pytest.mark.slow  # tier-1 budget (ISSUE 3): heavy; run in the slow lane
    def test_fresh_process_serving(self, tmp_path):
        """Save here; serve through the Predictor API in a NEW python
        process (the reference deploy contract: no model class, no saver
        state — just the artifact)."""
        prefix, xin, expect = self._save_artifact(tmp_path)
        np.save(str(tmp_path / "x.npy"), xin)
        script = f"""
import os
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from paddle_tpu import inference
pred = inference.create_predictor(inference.Config({prefix!r}))
x = np.load({str(tmp_path / 'x.npy')!r})
(out,) = pred.run([x])
np.save({str(tmp_path / 'out.npy')!r}, out)
print("SERVED_OK")
"""
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   PYTHONPATH=os.path.dirname(os.path.dirname(
                       os.path.abspath(__file__))))
        r = subprocess.run([sys.executable, "-c", script], env=env,
                           capture_output=True, text=True, timeout=240)
        assert "SERVED_OK" in r.stdout, r.stderr[-2000:]
        out = np.load(str(tmp_path / "out.npy"))
        np.testing.assert_allclose(out, expect, rtol=1e-4, atol=1e-5)

    def test_config_surface(self, tmp_path):
        from paddle_tpu import inference
        prefix, _, _ = self._save_artifact(tmp_path)
        cfg = inference.Config(prefix)
        cfg.disable_gpu()
        cfg.switch_ir_optim(True)
        assert cfg.ir_optim()
        assert prefix in cfg.summary()
        with pytest.raises(FileNotFoundError):
            inference.create_predictor(inference.Config(str(tmp_path / "no")))


class TestReviewRegressions:
    def test_append_backward_sees_frozen_param_updates(self):
        """Frozen params are live grad-op inputs, not baked constants."""
        import paddle_tpu.nn as nn
        l1 = nn.Linear(4, 4)
        l2 = nn.Linear(4, 1)
        for p in l2.parameters():
            p.stop_gradient = True      # freeze l2
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [4, 4], "float32")
            loss = pt.mean(l2(l1(x)) ** 2)
        grads = static.append_backward(loss)
        assert all(id(p) in {id(q) for q in l1.parameters()}
                   for p, _ in grads)
        exe = static.Executor()
        xin = np.random.randn(4, 4).astype("float32")
        g1 = exe.run(main, feed={"x": xin},
                     fetch_list=[g for _, g in grads])
        # change the FROZEN weight; cached grad executable must see it
        l2.weight.set_value(pt.to_tensor(l2.weight.numpy() * 3.0))
        g2 = exe.run(main, feed={"x": xin},
                     fetch_list=[g for _, g in grads])
        assert not np.allclose(g1[0], g2[0])
        # eager check of the post-update grads
        xe = pt.to_tensor(xin)
        le = pt.mean(l2(l1(xe)) ** 2)
        le.backward()
        np.testing.assert_allclose(g2[0], l1.weight.grad.numpy(),
                                   rtol=1e-4, atol=1e-6)

    def test_saved_artifact_is_batch_polymorphic(self, tmp_path):
        """None dims in static.data stay symbolic in the saved artifact."""
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [None, 4], "float32")
            y = pt.tanh(x)
        prefix = str(tmp_path / "poly")
        static.save_inference_model(prefix, [x], [y], static.Executor())
        prog, feed_names, _ = static.load_inference_model(
            prefix, static.Executor())
        for bs in (1, 8):
            xin = np.random.randn(bs, 4).astype("float32")
            (out,) = prog.run({feed_names[0]: xin})
            np.testing.assert_allclose(out, np.tanh(xin), rtol=1e-5)

    def test_symbolic_kwarg_recorded(self):
        """A symbolic tensor passed via keyword records as a program var."""
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [3], "float32")
            m = static.data("m", [3], "bool")
            out = pt.masked_fill(x, m, value=0.0)
            # symbolic kwarg: where(cond, x, y=kw)
            out2 = pt.where(m, x, y=out)
        exe = static.Executor()
        xin = np.array([1.0, -2.0, 3.0], "float32")
        mn = np.array([True, False, True])
        o1, o2 = exe.run(main, feed={"x": xin, "m": mn},
                         fetch_list=[out, out2])
        np.testing.assert_allclose(o1, np.where(mn, 0.0, xin))
        np.testing.assert_allclose(o2, np.where(mn, xin, o1))


class TestPasses:
    def test_dce_removes_unfetched(self):
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [2, 2], "float32")
            y = pt.exp(x)
            dead = pt.tanh(x) * 3.0      # never fetched
            z = y + 1.0
        n_before = len(main.ops())
        removed = static.dead_code_elimination(main, [z._symbolic])
        assert removed >= 2 and len(main.ops()) < n_before
        exe = static.Executor()
        xin = np.random.randn(2, 2).astype("float32")
        (out,) = exe.run(main, feed={"x": xin}, fetch_list=[z])
        np.testing.assert_allclose(out, np.exp(xin) + 1.0, rtol=1e-5)

    def test_build_time_folding_by_construction(self):
        """Ops on concrete values execute at build time — the constant
        subgraph never enters the program (folding by construction)."""
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [2], "float32")
            c = pt.exp(pt.to_tensor(np.ones(2, "float32")))  # eager, folded
            z = x + c
        assert len(main.ops()) == 1          # only the add was recorded
        (out,) = static.Executor().run(main,
                                       feed={"x": np.zeros(2, "float32")},
                                       fetch_list=[z])
        np.testing.assert_allclose(out, np.exp(np.ones(2)), rtol=1e-5)

    def test_constant_folding_freezes_params(self):
        import paddle_tpu.nn as nn
        lin = nn.Linear(2, 2)
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [1, 2], "float32")
            y = lin(x)
        frozen = static.constant_folding(main, freeze_params=True)
        assert frozen >= 2                   # weight + bias baked
        exe = static.Executor()
        xin = np.ones((1, 2), "float32")
        (before,) = exe.run(main, feed={"x": xin}, fetch_list=[y])
        lin.weight.set_value(pt.to_tensor(lin.weight.numpy() * 5))
        (after,) = exe.run(main, feed={"x": xin}, fetch_list=[y])
        np.testing.assert_allclose(before, after)   # frozen: update ignored

    def test_pass_manager(self):
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [2], "float32")
            dead = pt.sin(x)
            z = pt.cos(x)
        pm = static.PassManager(["constant_folding", "dce"])
        stats = pm.run(main, [z._symbolic])
        assert stats["dce"] >= 1
        (out,) = static.Executor().run(main,
                                       feed={"x": np.zeros(2, "float32")},
                                       fetch_list=[z])
        np.testing.assert_allclose(out, np.ones(2), rtol=1e-6)

    def test_pass_manager_options(self):
        import paddle_tpu.nn as nn
        lin = nn.Linear(2, 2)
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [1, 2], "float32")
            y = lin(x)
        stats = static.PassManager(
            [("constant_folding", {"freeze_params": True})]).run(main)
        assert stats["constant_folding"] >= 2
