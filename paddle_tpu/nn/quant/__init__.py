"""paddle.nn.quant parity: weight-only quantization primitives.

Reference capability: python/paddle/nn/quant/quantized_linear.py
(weight_quantize/weight_dequantize/weight_only_linear/llm_int8_linear)
+ quant_layers Stub. TPU-native: per-output-channel absmax int8 — the
int8 weights stream from HBM at half/quarter the bytes and dequantize
into the bf16 matmul (XLA fuses the scale multiply); int4 packs two
nibbles per int8 byte.
"""
from __future__ import annotations

import jax.numpy as jnp

from ...core.tensor import Tensor
from ..layer.base import Layer
from ...ops._op import op_fn, unwrap, wrap
from ...core import enforce as E

__all__ = ["Stub", "weight_quantize", "weight_dequantize",
           "weight_only_linear", "llm_int8_linear"]


class Stub:
    """Quantization insertion point (reference: quant_layers Stub): a
    placeholder a QuantConfig maps to an observer/quanter at
    quantize-time."""

    def __init__(self, observer=None):
        self._observer = observer

    def forward(self, x):
        return x

    __call__ = forward


def weight_quantize(x, algo="weight_only_int8", arch=None, group_size=-1):
    """Quantize a [in, out] weight to int8/int4 per output channel
    (reference: quantized_linear.py weight_quantize). Returns
    (quantized_weight, scale)."""
    w = unwrap(x).astype(jnp.float32)
    if algo not in ("weight_only_int8", "weight_only_int4", "llm.int8"):
        raise E.InvalidArgumentError(f"unsupported algo {algo!r}")
    absmax = jnp.max(jnp.abs(w), axis=0)            # per out-channel
    if algo == "weight_only_int4":
        if w.shape[0] % 2:
            raise E.InvalidArgumentError(
                "weight_only_int4 packs two rows per byte; in_features "
                f"must be even, got {w.shape[0]} — pad the weight first")
        scale = absmax / 7.0
        q = jnp.clip(jnp.round(w / jnp.maximum(scale, 1e-10)), -8, 7) \
            .astype(jnp.int8)
        # pack two int4 per byte along the input dim
        lo = q[0::2] & 0x0F
        hi = (q[1::2] & 0x0F) << 4
        packed = (lo | hi).astype(jnp.int8)
        return wrap(packed), wrap(scale)
    scale = absmax / 127.0
    q = jnp.clip(jnp.round(w / jnp.maximum(scale, 1e-10)), -127, 127) \
        .astype(jnp.int8)
    return wrap(q), wrap(scale)


def weight_dequantize(x, scale, algo="weight_only_int8", out_dtype="float16"):
    """Inverse of weight_quantize (reference: quantized_linear.py
    weight_dequantize)."""
    from ...core.dtype import convert_dtype

    q = unwrap(x)
    s = unwrap(scale).astype(jnp.float32)
    if algo == "weight_only_int4":
        lo = (q << 4).astype(jnp.int8) >> 4     # sign-extend low nibble
        hi = q >> 4                              # arithmetic shift: high
        full = jnp.stack([lo, hi], axis=1).reshape(-1, q.shape[1])
        w = full.astype(jnp.float32) * s[None, :]
    else:
        w = q.astype(jnp.float32) * s[None, :]
    return wrap(w.astype(convert_dtype(out_dtype)))


@op_fn(name="weight_only_linear_op", nondiff_args=(1,))
def _wol_op(x, qweight, scale, bias=None, *, algo, in_features):
    # dequant in f32 with ONE cast to the activation dtype (the
    # models/llama.py _mm ordering): casting the f32 scale to bf16
    # before the multiply double-rounds and degrades SQNR
    s32 = scale.astype(jnp.float32)
    if algo == "weight_only_int4":
        lo = (qweight << 4).astype(jnp.int8) >> 4
        hi = qweight >> 4
        full = jnp.stack([lo, hi], axis=1).reshape(-1, qweight.shape[1])
        w = (full[:in_features].astype(jnp.float32)
             * s32[None, :]).astype(x.dtype)
    else:
        w = (qweight.astype(jnp.float32) * s32[None, :]).astype(x.dtype)
    out = x @ w
    return out + bias if bias is not None else out


def weight_only_linear(x, weight, bias=None, weight_scale=None,
                       weight_dtype="int8", arch=None, group_size=-1):
    """Linear with int8/int4 weights dequantized into the matmul
    (reference: quantized_linear.py weight_only_linear)."""
    algo = "weight_only_int4" if weight_dtype == "int4" \
        else "weight_only_int8"
    in_features = unwrap(x).shape[-1]
    return _wol_op(x, weight, weight_scale, bias, algo=algo,
                   in_features=int(in_features))


def llm_int8_linear(x, weight, bias=None, weight_scale=None,
                    threshold=6.0):
    """LLM.int8()-shaped linear (reference: quantized_linear.py
    llm_int8_linear). The reference decomposes outlier input columns
    onto an fp16 copy of the weight to dodge int8 GEMM saturation; here
    the int8 weight dequantizes into a bf16/f32 MXU matmul, so the
    decomposition collapses algebraically (x_reg@W + x_out@W == x@W) —
    one full-precision-accumulate matmul is the whole kernel.
    ``threshold`` is accepted for signature parity."""
    xa = unwrap(x)
    q = unwrap(weight)
    s = unwrap(weight_scale).astype(jnp.float32)
    w = (q.astype(jnp.float32) * s[None, :]).astype(xa.dtype)
    out = xa @ w
    if bias is not None:
        out = out + unwrap(bias)
    return wrap(out)



# -- functional layers (reference: nn/quant/functional_layers.py) -----------
# Layer-shaped wrappers around tensor ops so a quant config can hook the
# op boundary; forward simply computes the op.

class FloatFunctionalLayer(Layer):
    def __init__(self):
        super().__init__()


def _functional(name, fn):
    class _F(FloatFunctionalLayer):
        def forward(self, *args, **kwargs):
            return fn(*args, **kwargs)
    _F.__name__ = name
    _F.__qualname__ = name
    return _F


def _op(opname):
    from ... import ops as _ops
    return getattr(_ops, opname)


add = _functional("add", lambda x, y, name=None: x + y)
subtract = _functional("subtract", lambda x, y, name=None: x - y)
multiply = _functional("multiply", lambda x, y, name=None: x * y)
divide = _functional("divide", lambda x, y, name=None: x / y)
matmul = _functional(
    "matmul",
    lambda x, y, transpose_x=False, transpose_y=False, name=None:
        _op("matmul")(x, y, transpose_x=transpose_x,
                      transpose_y=transpose_y))
reshape = _functional("reshape",
                      lambda x, shape, name=None: _op("reshape")(x, shape))
transpose = _functional(
    "transpose", lambda x, perm, name=None: _op("transpose")(x, perm))
concat = _functional(
    "concat", lambda x, axis=0, name=None: _op("concat")(x, axis=axis))
flatten = _functional(
    "flatten",
    lambda x, start_axis=0, stop_axis=-1, name=None:
        _op("flatten")(x, start_axis=start_axis, stop_axis=stop_axis))

QuantStub = Stub    # reference nn/quant/stub.py alias


def apply_per_channel_scale(x, scales):
    """Divide activations by per-channel smoothing scales before a
    weight-only matmul (reference: quant op apply_per_channel_scale,
    the SmoothQuant pre-scale)."""
    from ...ops._op import op_fn

    @op_fn(name="apply_per_channel_scale_op")
    def _apply(x, scales):
        return x / scales

    return _apply(x, scales)


from . import qat  # noqa: E402,F401
__all__ += ["FloatFunctionalLayer", "QuantStub", "add", "subtract",
            "multiply", "divide", "matmul", "reshape", "transpose",
            "concat", "flatten", "apply_per_channel_scale", "qat"]
