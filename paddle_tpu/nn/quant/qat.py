"""paddle.nn.quant.qat — QAT layer wrappers (reference:
python/paddle/nn/quant/qat/{conv,linear}.py). The live QAT engine is
paddle_tpu.quantization.qat; these are the layer-level wrappers it
installs, exposed under the reference path."""
from ...quantization.wrapper import ObserveWrapper  # noqa: F401
from ...quantization.qat import QAT  # noqa: F401

__all__ = ["ObserveWrapper", "QAT"]
