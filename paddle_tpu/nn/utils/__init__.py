"""paddle.nn.utils parity: weight/spectral norm reparameterizations,
gradient clipping helpers, parameter flattening.

Reference capability: python/paddle/nn/utils/ (weight_norm_hook.py,
spectral_norm_hook.py, clip_grad_norm_.py, clip_grad_value_.py,
transform_parameters.py). Reparameterizations install a forward pre-hook
that recomputes the weight from (g, v) before every forward — the same
hook discipline as the reference.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ...core.tensor import Parameter, Tensor
from ...core import enforce as E

__all__ = ["weight_norm", "remove_weight_norm", "spectral_norm",
           "clip_grad_norm_", "clip_grad_value_", "parameters_to_vector",
           "vector_to_parameters"]


def _norm_except_dim(w, dim):
    # dim=None: whole-tensor norm (scalar g, reference semantics)
    axes = tuple(range(w.ndim)) if dim is None else \
        tuple(i for i in range(w.ndim) if i != dim)
    return jnp.sqrt(jnp.sum(w * w, axis=axes, keepdims=True))


class _WeightNormHook:
    def __init__(self, name, dim):
        self.name = name
        self.dim = dim

    def compute(self, layer):
        g = getattr(layer, self.name + "_g")
        v = getattr(layer, self.name + "_v")
        # taped computation: ||v|| along all dims except `dim`
        # (dim=None: whole-tensor norm, scalar g)
        axes = tuple(range(len(v.shape))) if self.dim is None else \
            tuple(i for i in range(len(v.shape)) if i != self.dim)
        vn = (v * v).sum(axis=axes, keepdim=True).sqrt()
        return v * (g / vn)

    def __call__(self, layer, inputs):
        w = self.compute(layer)
        setattr(layer, self.name, w)
        return inputs


def weight_norm(layer, name="weight", dim=0):
    """Reparameterize ``layer.<name>`` as g * v/||v|| (reference:
    weight_norm_hook.py). g and v become the trainable parameters; the
    effective weight is recomputed in a forward pre-hook."""
    w = getattr(layer, name)
    warr = w._data
    g0 = _norm_except_dim(warr, dim)
    g = Parameter(g0)
    v = Parameter(warr)
    # replace the original parameter with the pair
    if name in layer._parameters:
        del layer._parameters[name]
    layer.add_parameter(name + "_g", g)
    layer.add_parameter(name + "_v", v)
    hook = _WeightNormHook(name, dim)
    setattr(layer, name, hook.compute(layer))
    handle = layer.register_forward_pre_hook(hook)
    layer._weight_norm_hooks = getattr(layer, "_weight_norm_hooks", {})
    layer._weight_norm_hooks[name] = (hook, handle)
    return layer


def remove_weight_norm(layer, name="weight"):
    """Fold (g, v) back into a plain parameter (reference:
    weight_norm_hook.py remove_weight_norm)."""
    hooks = getattr(layer, "_weight_norm_hooks", {})
    if name not in hooks:
        raise E.InvalidArgumentError(f"no weight_norm hook on parameter {name!r}")
    hook, handle = hooks.pop(name)
    w = hook.compute(layer)
    handle.remove()
    del layer._parameters[name + "_g"]
    del layer._parameters[name + "_v"]
    layer.add_parameter(name, Parameter(w._data))
    return layer


class _SpectralNormHook:
    def __init__(self, name, n_power_iterations, eps, dim):
        self.name = name
        self.n = n_power_iterations
        self.eps = eps
        self.dim = dim

    def compute(self, layer):
        w = getattr(layer, self.name + "_orig")
        u = getattr(layer, self.name + "_u")
        warr = w._data
        if self.dim != 0:
            perm = [self.dim] + [i for i in range(warr.ndim)
                                 if i != self.dim]
            warr = jnp.transpose(warr, perm)
        mat = warr.reshape(warr.shape[0], -1)
        uv = u._data
        # n_power_iterations=0 is legal: sigma from the persisted u with
        # one v solve, no u update
        vv = mat.T @ uv
        vv = vv / (jnp.linalg.norm(vv) + self.eps)
        for _ in range(self.n):
            uv = mat @ vv
            uv = uv / (jnp.linalg.norm(uv) + self.eps)
            vv = mat.T @ uv
            vv = vv / (jnp.linalg.norm(vv) + self.eps)
        u._data = uv                       # persistent power-iter state
        sigma = uv @ mat @ vv
        return w / sigma

    def __call__(self, layer, inputs):
        setattr(layer, self.name, self.compute(layer))
        return inputs


def spectral_norm(layer, name="weight", n_power_iterations=1, eps=1e-12,
                  dim=None):
    """Spectral normalization reparameterization (reference:
    spectral_norm_hook.py): weight / sigma_max, sigma estimated by
    persistent power iteration."""
    w = getattr(layer, name)
    if dim is None:
        dim = 1 if type(layer).__name__.endswith("Transpose") else 0
    warr = w._data
    rows = warr.shape[dim]
    rng = np.random.default_rng(0)
    u = Parameter(jnp.asarray(rng.normal(size=(rows,)), warr.dtype)
                  / np.sqrt(rows))
    u.stop_gradient = True
    if name in layer._parameters:
        del layer._parameters[name]
    layer.add_parameter(name + "_orig", Parameter(warr))
    layer.add_parameter(name + "_u", u)
    hook = _SpectralNormHook(name, n_power_iterations, eps, dim)
    setattr(layer, name, hook.compute(layer))
    layer.register_forward_pre_hook(hook)
    return layer


def clip_grad_norm_(parameters, max_norm, norm_type=2.0,
                    error_if_nonfinite=False):
    """In-place global-norm gradient clip (reference:
    clip_grad_norm_.py). Returns the total norm."""
    params = [parameters] if isinstance(parameters, Tensor) else \
        list(parameters)
    grads = [p.grad._data for p in params if p.grad is not None]
    if not grads:
        return Tensor(jnp.zeros(()))
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack([jnp.max(jnp.abs(g)) for g in grads]))
    else:
        total = jnp.sum(jnp.stack(
            [jnp.sum(jnp.abs(g) ** norm_type) for g in grads])) \
            ** (1.0 / norm_type)
    if error_if_nonfinite and not bool(jnp.isfinite(total)):
        raise E.PreconditionNotMetError(
            f"gradient norm is non-finite ({float(total)}); cannot clip")
    scale = jnp.minimum(max_norm / (total + 1e-6), 1.0)
    for p in params:
        if p.grad is not None:
            p.grad._data = p.grad._data * scale.astype(p.grad._data.dtype)
    return Tensor(total)


def clip_grad_value_(parameters, clip_value):
    """In-place element clip of grads to [-clip_value, clip_value]
    (reference: clip_grad_value_.py)."""
    params = [parameters] if isinstance(parameters, Tensor) else \
        list(parameters)
    for p in params:
        if p.grad is not None:
            p.grad._data = jnp.clip(p.grad._data, -clip_value, clip_value)


def parameters_to_vector(parameters, name=None):
    """Concatenate flattened parameters (reference:
    transform_parameters.py)."""
    params = list(parameters)
    return Tensor(jnp.concatenate([p._data.reshape(-1) for p in params]))


def vector_to_parameters(vec, parameters, name=None):
    """Write a flat vector back into the parameter list."""
    params = list(parameters)
    off = 0
    v = vec._data if isinstance(vec, Tensor) else jnp.asarray(vec)
    for p in params:
        n = int(np.prod(p._data.shape)) if p._data.ndim else 1
        p._data = v[off:off + n].reshape(p._data.shape).astype(p._data.dtype)
        off += n
