"""Activation layers (reference: python/paddle/nn/layer/activation.py)."""
from __future__ import annotations

from .. import functional as F
from .. import initializer as I
from .base import Layer

__all__ = ["CELU", "ELU", "GELU", "GLU", "Hardshrink", "Hardsigmoid",
           "Hardswish", "Hardtanh", "LeakyReLU", "LogSigmoid", "LogSoftmax",
           "Maxout", "Mish", "PReLU", "ReLU", "ReLU6", "RReLU", "SELU",
           "Sigmoid", "Silu", "Softmax", "Softplus", "Softshrink",
           "Softsign", "Swish", "Tanh", "Tanhshrink", "ThresholdedReLU"]


class ReLU(Layer):
    def forward(self, x):
        return F.relu(x)


class ReLU6(Layer):
    def forward(self, x):
        return F.relu6(x)


class ELU(Layer):
    def __init__(self, alpha=1.0, name=None):
        super().__init__()
        self.alpha = alpha

    def forward(self, x):
        return F.elu(x, alpha=self.alpha)


class CELU(Layer):
    def __init__(self, alpha=1.0, name=None):
        super().__init__()
        self.alpha = alpha

    def forward(self, x):
        return F.celu(x, alpha=self.alpha)


class SELU(Layer):
    def __init__(self, scale=1.0507009873554805, alpha=1.6732632423543772,
                 name=None):
        super().__init__()
        self.scale, self.alpha = scale, alpha

    def forward(self, x):
        return F.selu(x, scale=self.scale, alpha=self.alpha)


class GELU(Layer):
    def __init__(self, approximate=False, name=None):
        super().__init__()
        self.approximate = approximate

    def forward(self, x):
        return F.gelu(x, approximate=self.approximate)


class GLU(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.glu(x, axis=self.axis)


class Hardshrink(Layer):
    def __init__(self, threshold=0.5, name=None):
        super().__init__()
        self.threshold = threshold

    def forward(self, x):
        return F.hardshrink(x, threshold=self.threshold)


class Hardsigmoid(Layer):
    def forward(self, x):
        return F.hardsigmoid(x)


class Hardswish(Layer):
    def forward(self, x):
        return F.hardswish(x)


class Hardtanh(Layer):
    def __init__(self, min=-1.0, max=1.0, name=None):
        super().__init__()
        self.min, self.max = min, max

    def forward(self, x):
        return F.hardtanh(x, min=self.min, max=self.max)


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01, name=None):
        super().__init__()
        self.negative_slope = negative_slope

    def forward(self, x):
        return F.leaky_relu(x, negative_slope=self.negative_slope)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        self.data_format = data_format
        self.weight = self.create_parameter(
            (num_parameters,), attr=weight_attr,
            default_initializer=I.Constant(init))

    def forward(self, x):
        return F.prelu(x, self.weight, data_format=self.data_format)


class RReLU(Layer):
    def __init__(self, lower=1.0 / 8.0, upper=1.0 / 3.0, name=None):
        super().__init__()
        self.lower, self.upper = lower, upper

    def forward(self, x):
        from ...framework import random as frandom
        return F.rrelu(x, lower=self.lower, upper=self.upper,
                       training=self.training,
                       key=frandom.next_key() if self.training else None)


class LogSigmoid(Layer):
    def forward(self, x):
        return F.log_sigmoid(x)


class LogSoftmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.log_softmax(x, axis=self.axis)


class Maxout(Layer):
    def __init__(self, groups, axis=1, name=None):
        super().__init__()
        self.groups, self.axis = groups, axis

    def forward(self, x):
        return F.maxout(x, groups=self.groups, axis=self.axis)


class Mish(Layer):
    def forward(self, x):
        return F.mish(x)


class Sigmoid(Layer):
    def forward(self, x):
        return F.sigmoid(x)


class Silu(Layer):
    def forward(self, x):
        return F.silu(x)


class Softmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.softmax(x, axis=self.axis)


class Softplus(Layer):
    def __init__(self, beta=1.0, threshold=20.0, name=None):
        super().__init__()
        self.beta, self.threshold = beta, threshold

    def forward(self, x):
        return F.softplus(x, beta=self.beta, threshold=self.threshold)


class Softshrink(Layer):
    def __init__(self, threshold=0.5, name=None):
        super().__init__()
        self.threshold = threshold

    def forward(self, x):
        return F.softshrink(x, threshold=self.threshold)


class Softsign(Layer):
    def forward(self, x):
        return F.softsign(x)


class Swish(Layer):
    def forward(self, x):
        return F.swish(x)


class Tanh(Layer):
    def forward(self, x):
        return F.tanh(x)


class Tanhshrink(Layer):
    def forward(self, x):
        return F.tanhshrink(x)


class ThresholdedReLU(Layer):
    def __init__(self, threshold=1.0, value=0.0, name=None):
        super().__init__()
        self.threshold, self.value = threshold, value

    def forward(self, x):
        return F.thresholded_relu(x, threshold=self.threshold,
                                  value=self.value)
