"""Layer classes for the functional long tail.

Reference capability: python/paddle/nn/layer/pooling.py (MaxUnPool*,
FractionalMaxPool*), layer/loss.py (CTCLoss:1300-ish, RNNTLoss,
MultiMarginLoss, TripletMarginWithDistanceLoss, HSigmoidLoss),
layer/activation.py (Softmax2D).
"""
from __future__ import annotations

from .. import functional as F
from ..initializer import Uniform
from .base import Layer
from ...core import enforce as E

__all__ = [
    "MaxUnPool1D", "MaxUnPool2D", "MaxUnPool3D",
    "FractionalMaxPool2D", "FractionalMaxPool3D",
    "MultiMarginLoss", "TripletMarginWithDistanceLoss", "HSigmoidLoss",
    "CTCLoss", "RNNTLoss", "Softmax2D",
]


class _MaxUnPool(Layer):
    _nsp = 2
    _fn = None
    _default_df = "NCHW"

    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format=None, output_size=None, name=None):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.data_format = data_format or self._default_df
        self.output_size = output_size

    def forward(self, x, indices):
        return self._fn(x, indices, self.kernel_size, self.stride,
                        self.padding, self.data_format, self.output_size)

    def extra_repr(self):
        return (f"kernel_size={self.kernel_size}, stride={self.stride}, "
                f"padding={self.padding}")


class MaxUnPool1D(_MaxUnPool):
    _default_df = "NCL"
    _fn = staticmethod(F.max_unpool1d)


class MaxUnPool2D(_MaxUnPool):
    _fn = staticmethod(F.max_unpool2d)


class MaxUnPool3D(_MaxUnPool):
    _default_df = "NCDHW"
    _fn = staticmethod(F.max_unpool3d)


class _FractionalMaxPool(Layer):
    _fn = None

    def __init__(self, output_size, kernel_size=None, random_u=None,
                 return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size
        self.kernel_size = kernel_size
        self.random_u = random_u
        self.return_mask = return_mask

    def forward(self, x):
        return self._fn(x, self.output_size, self.kernel_size,
                        self.random_u, self.return_mask)

    def extra_repr(self):
        return f"output_size={self.output_size}"


class FractionalMaxPool2D(_FractionalMaxPool):
    _fn = staticmethod(F.fractional_max_pool2d)


class FractionalMaxPool3D(_FractionalMaxPool):
    _fn = staticmethod(F.fractional_max_pool3d)


class MultiMarginLoss(Layer):
    def __init__(self, p=1, margin=1.0, weight=None, reduction="mean",
                 name=None):
        super().__init__()
        self.p = p
        self.margin = margin
        self.weight = weight
        self.reduction = reduction

    def forward(self, input, label):
        return F.multi_margin_loss(input, label, self.p, self.margin,
                                   self.weight, self.reduction)


class TripletMarginWithDistanceLoss(Layer):
    def __init__(self, distance_function=None, margin=1.0, swap=False,
                 reduction="mean", name=None):
        super().__init__()
        self.distance_function = distance_function
        self.margin = margin
        self.swap = swap
        self.reduction = reduction

    def forward(self, input, positive, negative):
        return F.triplet_margin_with_distance_loss(
            input, positive, negative, self.distance_function, self.margin,
            self.swap, self.reduction)


class HSigmoidLoss(Layer):
    """Hierarchical sigmoid (reference layer/loss.py HSigmoidLoss): owns
    the [num_classes-1, feature_size] internal-node weight table."""

    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False,
                 name=None):
        super().__init__()
        if num_classes < 2:
            raise E.InvalidArgumentError("num_classes must be >= 2")
        self.num_classes = num_classes
        self.is_custom = is_custom
        std = 1.0 / (feature_size ** 0.5)
        rows = num_classes if is_custom else num_classes - 1
        self.weight = self.create_parameter(
            (rows, feature_size), attr=weight_attr,
            default_initializer=Uniform(-std, std))
        self.bias = None if bias_attr is False else self.create_parameter(
            (rows, 1), attr=bias_attr, is_bias=True,
            default_initializer=Uniform(-std, std))

    def forward(self, input, label, path_table=None, path_code=None):
        return F.hsigmoid_loss(input, label, self.num_classes, self.weight,
                               self.bias, path_table, path_code)


class CTCLoss(Layer):
    def __init__(self, blank=0, reduction="mean"):
        super().__init__()
        self.blank = blank
        self.reduction = reduction

    def forward(self, log_probs, labels, input_lengths, label_lengths,
                norm_by_times=False):
        return F.ctc_loss(log_probs, labels, input_lengths, label_lengths,
                          self.blank, self.reduction, norm_by_times)


class RNNTLoss(Layer):
    def __init__(self, blank=0, fastemit_lambda=0.001, reduction="mean",
                 name=None):
        super().__init__()
        self.blank = blank
        self.fastemit_lambda = fastemit_lambda
        self.reduction = reduction

    def forward(self, input, label, input_lengths, label_lengths):
        return F.rnnt_loss(input, label, input_lengths, label_lengths,
                           self.blank, self.fastemit_lambda, self.reduction)


class Softmax2D(Layer):
    """Softmax over the channel axis of NCHW input (reference
    layer/activation.py Softmax2D: softmax at axis=-3)."""

    def forward(self, x):
        if x.ndim not in (3, 4):
            raise E.InvalidArgumentError(
                f"Softmax2D expects 3D/4D input, got {x.ndim}D")
        return F.softmax(x, axis=-3)
