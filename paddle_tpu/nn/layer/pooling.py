"""Pooling layers (reference: python/paddle/nn/layer/pooling.py)."""
from __future__ import annotations

from .. import functional as F
from .base import Layer

__all__ = ["AvgPool1D", "AvgPool2D", "AvgPool3D", "MaxPool1D", "MaxPool2D",
           "MaxPool3D", "AdaptiveAvgPool1D", "AdaptiveAvgPool2D",
           "AdaptiveAvgPool3D", "AdaptiveMaxPool1D", "AdaptiveMaxPool2D",
           "AdaptiveMaxPool3D"]


class _Pool(Layer):
    _fn = None
    _default_df = "NCHW"

    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 exclusive=True, data_format=None, name=None):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.ceil_mode = ceil_mode
        self.exclusive = exclusive
        self.data_format = data_format or self._default_df

    def extra_repr(self):
        return (f"kernel_size={self.kernel_size}, stride={self.stride}, "
                f"padding={self.padding}")


class AvgPool1D(_Pool):
    _default_df = "NCL"

    def forward(self, x):
        return F.avg_pool1d(x, kernel_size=self.kernel_size,
                            stride=self.stride, padding=self.padding,
                            exclusive=self.exclusive,
                            ceil_mode=self.ceil_mode,
                            data_format=self.data_format)


class AvgPool2D(_Pool):
    def forward(self, x):
        return F.avg_pool2d(x, kernel_size=self.kernel_size,
                            stride=self.stride, padding=self.padding,
                            exclusive=self.exclusive,
                            ceil_mode=self.ceil_mode,
                            data_format=self.data_format)


class AvgPool3D(_Pool):
    _default_df = "NCDHW"

    def forward(self, x):
        return F.avg_pool3d(x, kernel_size=self.kernel_size,
                            stride=self.stride, padding=self.padding,
                            exclusive=self.exclusive,
                            ceil_mode=self.ceil_mode,
                            data_format=self.data_format)


class MaxPool1D(_Pool):
    _default_df = "NCL"

    def forward(self, x):
        return F.max_pool1d(x, kernel_size=self.kernel_size,
                            stride=self.stride, padding=self.padding,
                            ceil_mode=self.ceil_mode,
                            data_format=self.data_format)


class MaxPool2D(_Pool):
    def forward(self, x):
        return F.max_pool2d(x, kernel_size=self.kernel_size,
                            stride=self.stride, padding=self.padding,
                            ceil_mode=self.ceil_mode,
                            data_format=self.data_format)


class MaxPool3D(_Pool):
    _default_df = "NCDHW"

    def forward(self, x):
        return F.max_pool3d(x, kernel_size=self.kernel_size,
                            stride=self.stride, padding=self.padding,
                            ceil_mode=self.ceil_mode,
                            data_format=self.data_format)


class _AdaptivePool(Layer):
    _default_df = "NCHW"

    def __init__(self, output_size, data_format=None, return_mask=False,
                 name=None):
        super().__init__()
        self.output_size = output_size
        self.data_format = data_format or self._default_df
        self.return_mask = return_mask


class AdaptiveAvgPool1D(_AdaptivePool):
    _default_df = "NCL"

    def forward(self, x):
        return F.adaptive_avg_pool1d(x, output_size=self.output_size,
                                     data_format=self.data_format)


class AdaptiveAvgPool2D(_AdaptivePool):
    def forward(self, x):
        return F.adaptive_avg_pool2d(x, output_size=self.output_size,
                                     data_format=self.data_format)


class AdaptiveAvgPool3D(_AdaptivePool):
    _default_df = "NCDHW"

    def forward(self, x):
        return F.adaptive_avg_pool3d(x, output_size=self.output_size,
                                     data_format=self.data_format)


class AdaptiveMaxPool1D(_AdaptivePool):
    _default_df = "NCL"

    def forward(self, x):
        return F.adaptive_max_pool1d(x, output_size=self.output_size,
                                     data_format=self.data_format)


class AdaptiveMaxPool2D(_AdaptivePool):
    def forward(self, x):
        return F.adaptive_max_pool2d(x, output_size=self.output_size,
                                     data_format=self.data_format)


class AdaptiveMaxPool3D(_AdaptivePool):
    _default_df = "NCDHW"

    def forward(self, x):
        return F.adaptive_max_pool3d(x, output_size=self.output_size,
                                     data_format=self.data_format)
