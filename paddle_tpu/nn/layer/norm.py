"""Normalization layers.

Reference: python/paddle/nn/layer/norm.py. RMSNorm included as first-class
(TPU transformers default to it; reference ships it as incubate
fused_rms_norm).
"""
from __future__ import annotations

from typing import Optional, Sequence, Union

import jax.numpy as jnp
import numpy as np

from ...core.tensor import Tensor
from .. import functional as F
from .. import initializer as I
from .base import Layer

__all__ = ["LayerNorm", "RMSNorm", "BatchNorm", "BatchNorm1D", "BatchNorm2D",
           "BatchNorm3D", "SyncBatchNorm", "GroupNorm", "InstanceNorm1D",
           "InstanceNorm2D", "InstanceNorm3D", "LocalResponseNorm",
           "SpectralNorm"]


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon: float = 1e-5,
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = (normalized_shape,)
        self.normalized_shape = tuple(normalized_shape)
        self.epsilon = epsilon
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                self.normalized_shape, attr=weight_attr,
                default_initializer=I.Constant(1.0))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                self.normalized_shape, attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.layer_norm(x, self.weight, self.bias,
                            normalized_ndim=len(self.normalized_shape),
                            epsilon=self.epsilon)

    def extra_repr(self):
        return f"normalized_shape={self.normalized_shape}, epsilon={self.epsilon}"


class RMSNorm(Layer):
    def __init__(self, hidden_size: int, epsilon: float = 1e-6,
                 weight_attr=None, name=None):
        super().__init__()
        self.hidden_size = hidden_size
        self.epsilon = epsilon
        self.weight = self.create_parameter(
            (hidden_size,), attr=weight_attr,
            default_initializer=I.Constant(1.0))

    def forward(self, x):
        return F.rms_norm(x, self.weight, epsilon=self.epsilon)

    def extra_repr(self):
        return f"hidden_size={self.hidden_size}, epsilon={self.epsilon}"


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                (num_features,), attr=weight_attr,
                default_initializer=I.Constant(1.0))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                (num_features,), attr=bias_attr, is_bias=True)
        self.register_buffer("_mean", Tensor(jnp.zeros(num_features,
                                                       jnp.float32)))
        self.register_buffer("_variance", Tensor(jnp.ones(num_features,
                                                          jnp.float32)))

    def forward(self, x):
        return F.batch_norm(
            x, self._mean, self._variance, self.weight, self.bias,
            training=self.training, momentum=self._momentum,
            epsilon=self._epsilon, data_format=self._data_format,
            use_global_stats=self._use_global_stats)

    def extra_repr(self):
        return f"num_features={self._num_features}"


class BatchNorm(_BatchNormBase):
    pass


class BatchNorm1D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCL",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, data_format, use_global_stats, name)


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCDHW",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, data_format, use_global_stats, name)


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica BN. Under pjit/shard_map the batch axis is a mesh axis,
    so the mean/var reductions become global automatically (XLA inserts the
    collective) — the layer is identical to BatchNorm on TPU; kept for API
    parity (reference: nn/layer/norm.py SyncBatchNorm).
    """

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        if isinstance(layer, _BatchNormBase) and not isinstance(
                layer, SyncBatchNorm):
            new = SyncBatchNorm(layer._num_features, layer._momentum,
                                layer._epsilon,
                                data_format=layer._data_format)
            if layer.weight is not None:
                new.weight._data = layer.weight._data
            if layer.bias is not None:
                new.bias._data = layer.bias._data
            new._mean._data = layer._mean._data
            new._variance._data = layer._variance._data
            return new
        for name, sub in list(layer._sub_layers.items()):
            layer._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return layer


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._num_groups = num_groups
        self._num_channels = num_channels
        self._epsilon = epsilon
        self._data_format = data_format
        self.weight = None if weight_attr is False else self.create_parameter(
            (num_channels,), attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            (num_channels,), attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.group_norm(x, self.weight, self.bias,
                            num_groups=self._num_groups,
                            epsilon=self._epsilon,
                            data_format=self._data_format)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._epsilon = epsilon
        self._data_format = data_format
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                (num_features,), attr=weight_attr,
                default_initializer=I.Constant(1.0))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                (num_features,), attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, self.weight, self.bias,
                               epsilon=self._epsilon,
                               data_format=self._data_format)


class InstanceNorm1D(_InstanceNormBase):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCL",
                 name=None):
        super().__init__(num_features, epsilon, momentum, weight_attr,
                         bias_attr, data_format, name)


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCDHW",
                 name=None):
        super().__init__(num_features, epsilon, momentum, weight_attr,
                         bias_attr, data_format, name)


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self.a = (size, alpha, beta, k, data_format)

    def forward(self, x):
        size, alpha, beta, k, df = self.a
        return F.local_response_norm(x, size=size, alpha=alpha, beta=beta,
                                     k=k, data_format=df)


class SpectralNorm(Layer):
    """Power-iteration spectral norm of a weight (reference:
    nn/layer/norm.py SpectralNorm)."""

    def __init__(self, weight_shape, dim=0, power_iters=1, epsilon=1e-12,
                 name=None, dtype="float32"):
        super().__init__(dtype=dtype)
        self._dim = dim
        self._power_iters = power_iters
        self._epsilon = epsilon
        h = weight_shape[dim]
        w = int(np.prod(weight_shape)) // h
        self.weight_u = self.create_parameter(
            (h,), default_initializer=I.Normal(0.0, 1.0))
        self.weight_u.stop_gradient = True
        self.weight_v = self.create_parameter(
            (w,), default_initializer=I.Normal(0.0, 1.0))
        self.weight_v.stop_gradient = True

    def forward(self, weight):
        from ... import ops
        w = weight
        dim = self._dim
        if dim != 0:
            w = ops.moveaxis(w, source=dim, destination=0)
        h = w.shape[0]
        mat = ops.reshape(w, shape=[h, -1])
        u, v = self.weight_u, self.weight_v
        for _ in range(self._power_iters):
            # v = W^T u / ||W^T u||; u = W v / ||W v||
            vt = jnp.matmul(mat._data.T, u._data)
            vt = vt / jnp.maximum(jnp.linalg.norm(vt), self._epsilon)
            ut = jnp.matmul(mat._data, vt)
            ut = ut / jnp.maximum(jnp.linalg.norm(ut), self._epsilon)
            u._data, v._data = ut, vt
        sigma = jnp.dot(u._data, jnp.matmul(mat._data, v._data))
        out = mat / Tensor(sigma)
        out = ops.reshape(out, shape=list(w.shape))
        if dim != 0:
            out = ops.moveaxis(out, source=0, destination=dim)
        return out
