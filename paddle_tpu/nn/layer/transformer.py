"""Transformer layers (reference: python/paddle/nn/layer/transformer.py).

MultiHeadAttention routes through nn.functional.scaled_dot_product_attention,
which dispatches to the Pallas flash kernel when available — the reference's
fused-attention choice made at the kernel-dispatch seam instead of in layer
code.
"""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from ...core.tensor import Tensor
from .. import functional as F
from .base import Layer
from .common import Dropout, Linear
from .container import LayerList
from .norm import LayerNorm

__all__ = ["MultiHeadAttention", "TransformerEncoderLayer",
           "TransformerEncoder", "TransformerDecoderLayer",
           "TransformerDecoder", "Transformer"]


def _convert_attention_mask(attn_mask, dtype):
    if attn_mask is None:
        return None
    if attn_mask.dtype == jnp.bool_:
        return attn_mask
    return attn_mask


class MultiHeadAttention(Layer):
    """Reference: transformer.py MultiHeadAttention. Inputs [B, S, D]."""

    Cache = tuple
    StaticCache = tuple

    def __init__(self, embed_dim, num_heads, dropout=0.0, kdim=None,
                 vdim=None, need_weights=False, weight_attr=None,
                 bias_attr=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.dropout = dropout
        self.head_dim = embed_dim // num_heads
        assert self.head_dim * num_heads == embed_dim
        self.need_weights = need_weights
        kdim = kdim or embed_dim
        vdim = vdim or embed_dim
        self.q_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)
        self.k_proj = Linear(kdim, embed_dim, weight_attr, bias_attr)
        self.v_proj = Linear(vdim, embed_dim, weight_attr, bias_attr)
        self.out_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)

    def forward(self, query, key=None, value=None, attn_mask=None,
                cache=None):
        from ... import ops
        key = query if key is None else key
        value = query if value is None else value
        q = self.q_proj(query)
        k = self.k_proj(key)
        v = self.v_proj(value)
        b, sq = q.shape[0], q.shape[1]
        sk = k.shape[1]
        q = ops.reshape(q, shape=[b, sq, self.num_heads, self.head_dim])
        k = ops.reshape(k, shape=[b, sk, self.num_heads, self.head_dim])
        v = ops.reshape(v, shape=[b, sk, self.num_heads, self.head_dim])
        if cache is not None:
            k_cache, v_cache = cache
            k = ops.concat([k_cache, k], axis=1)
            v = ops.concat([v_cache, v], axis=1)
            new_cache = (k, v)
        mask = _convert_attention_mask(attn_mask, q.dtype)
        out = F.scaled_dot_product_attention(
            q, k, v, mask, dropout_p=self.dropout if self.training else 0.0,
            is_causal=False, training=self.training)
        out = ops.reshape(out, shape=[b, sq, self.embed_dim])
        out = self.out_proj(out)
        if cache is not None:
            return out, new_cache
        return out

    def gen_cache(self, key, value=None, type=None):
        from ... import ops
        b = key.shape[0]
        empty = ops.zeros([b, 0, self.num_heads, self.head_dim],
                          dtype="float32")
        return (empty, empty)


class TransformerEncoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None,
                 layer_norm_eps=1e-5):
        super().__init__()
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead, attn_dropout,
                                            weight_attr=weight_attr,
                                            bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr,
                              bias_attr)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr,
                              bias_attr)
        self.norm1 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.norm2 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.dropout_act = Dropout(act_dropout)
        self.activation = getattr(F, activation)

    def forward(self, src, src_mask=None, cache=None):
        residual = src
        x = self.norm1(src) if self.normalize_before else src
        if cache is None:
            x = self.self_attn(x, x, x, src_mask)
        else:
            x, cache = self.self_attn(x, x, x, src_mask, cache)
        x = residual + self.dropout1(x)
        if not self.normalize_before:
            x = self.norm1(x)
        residual = x
        y = self.norm2(x) if self.normalize_before else x
        y = self.linear2(self.dropout_act(self.activation(self.linear1(y))))
        y = residual + self.dropout2(y)
        if not self.normalize_before:
            y = self.norm2(y)
        return y if cache is None else (y, cache)

    def gen_cache(self, src):
        return self.self_attn.gen_cache(src)


class TransformerEncoder(Layer):
    def __init__(self, encoder_layer, num_layers, norm=None):
        super().__init__()
        import copy
        self.layers = LayerList(
            [encoder_layer] +
            [copy.deepcopy(encoder_layer) for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, src, src_mask=None, cache=None):
        out = src
        new_caches = []
        for i, layer in enumerate(self.layers):
            if cache is None:
                out = layer(out, src_mask)
            else:
                out, c = layer(out, src_mask, cache[i])
                new_caches.append(c)
        if self.norm is not None:
            out = self.norm(out)
        return out if cache is None else (out, new_caches)

    def gen_cache(self, src):
        return [layer.gen_cache(src) for layer in self.layers]


class TransformerDecoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None,
                 layer_norm_eps=1e-5):
        super().__init__()
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead, attn_dropout,
                                            weight_attr=weight_attr,
                                            bias_attr=bias_attr)
        self.cross_attn = MultiHeadAttention(d_model, nhead, attn_dropout,
                                             weight_attr=weight_attr,
                                             bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr,
                              bias_attr)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr,
                              bias_attr)
        self.norm1 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.norm2 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.norm3 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.dropout3 = Dropout(dropout)
        self.dropout_act = Dropout(act_dropout)
        self.activation = getattr(F, activation)

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None,
                cache=None):
        residual = tgt
        x = self.norm1(tgt) if self.normalize_before else tgt
        if cache is None:
            x = self.self_attn(x, x, x, tgt_mask)
        else:
            x, self_cache = self.self_attn(x, x, x, tgt_mask, cache[0])
        x = residual + self.dropout1(x)
        if not self.normalize_before:
            x = self.norm1(x)
        residual = x
        y = self.norm2(x) if self.normalize_before else x
        y = self.cross_attn(y, memory, memory, memory_mask)
        y = residual + self.dropout2(y)
        if not self.normalize_before:
            y = self.norm2(y)
        residual = y
        z = self.norm3(y) if self.normalize_before else y
        z = self.linear2(self.dropout_act(self.activation(self.linear1(z))))
        z = residual + self.dropout3(z)
        if not self.normalize_before:
            z = self.norm3(z)
        return z if cache is None else (z, (self_cache,))

    def gen_cache(self, memory):
        return (self.self_attn.gen_cache(memory),)


class TransformerDecoder(Layer):
    def __init__(self, decoder_layer, num_layers, norm=None):
        super().__init__()
        import copy
        self.layers = LayerList(
            [decoder_layer] +
            [copy.deepcopy(decoder_layer) for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None,
                cache=None):
        out = tgt
        new_caches = []
        for i, layer in enumerate(self.layers):
            if cache is None:
                out = layer(out, memory, tgt_mask, memory_mask)
            else:
                out, c = layer(out, memory, tgt_mask, memory_mask, cache[i])
                new_caches.append(c)
        if self.norm is not None:
            out = self.norm(out)
        return out if cache is None else (out, new_caches)

    def gen_cache(self, memory):
        return [layer.gen_cache(memory) for layer in self.layers]


class Transformer(Layer):
    def __init__(self, d_model=512, nhead=8, num_encoder_layers=6,
                 num_decoder_layers=6, dim_feedforward=2048, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None,
                 custom_encoder=None, custom_decoder=None):
        super().__init__()
        if custom_encoder is not None:
            self.encoder = custom_encoder
        else:
            enc_layer = TransformerEncoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before, weight_attr,
                bias_attr)
            enc_norm = LayerNorm(d_model) if normalize_before else None
            self.encoder = TransformerEncoder(enc_layer, num_encoder_layers,
                                              enc_norm)
        if custom_decoder is not None:
            self.decoder = custom_decoder
        else:
            dec_layer = TransformerDecoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before, weight_attr,
                bias_attr)
            dec_norm = LayerNorm(d_model) if normalize_before else None
            self.decoder = TransformerDecoder(dec_layer, num_decoder_layers,
                                              dec_norm)
        self.d_model = d_model
        self.nhead = nhead

    def forward(self, src, tgt, src_mask=None, tgt_mask=None,
                memory_mask=None):
        memory = self.encoder(src, src_mask)
        return self.decoder(tgt, memory, tgt_mask, memory_mask)

    @staticmethod
    def generate_square_subsequent_mask(length):
        import numpy as np
        from ...core.tensor import to_tensor
        m = np.triu(np.full((length, length), -np.inf, dtype=np.float32), k=1)
        return to_tensor(m)
