"""Recurrent layers: SimpleRNN / LSTM / GRU (+ cells).

Reference: python/paddle/nn/layer/rnn.py. TPU-native design: the whole
sequence loop is ONE ``lax.scan`` inside a single registered op, so XLA
compiles a fused loop (no per-timestep Python dispatch) and the tape's
jax.vjp closure differentiates through the scan. Weight layout follows
paddle: weight_ih [G*H, I], weight_hh [G*H, H]; LSTM gate order i,f,g,o;
GRU gate order r,z,c.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ...ops._op import op_fn
from .. import initializer as I
from .base import Layer

__all__ = ["SimpleRNNCell", "LSTMCell", "GRUCell", "SimpleRNN", "LSTM",
           "GRU", "RNN", "BiRNN"]


def _rnn_step(act, x_t, h, w_ih, w_hh, b_ih, b_hh):
    g = x_t @ w_ih.T + h @ w_hh.T
    if b_ih is not None:
        g = g + b_ih
    if b_hh is not None:
        g = g + b_hh
    return act(g)


def _lstm_step(x_t, h, c, w_ih, w_hh, b_ih, b_hh):
    g = x_t @ w_ih.T + h @ w_hh.T
    if b_ih is not None:
        g = g + b_ih
    if b_hh is not None:
        g = g + b_hh
    i, f, gg, o = jnp.split(g, 4, axis=-1)
    i = jax.nn.sigmoid(i)
    f = jax.nn.sigmoid(f)
    gg = jnp.tanh(gg)
    o = jax.nn.sigmoid(o)
    c_new = f * c + i * gg
    h_new = o * jnp.tanh(c_new)
    return h_new, c_new


def _gru_step(x_t, h, w_ih, w_hh, b_ih, b_hh):
    gi = x_t @ w_ih.T
    gh = h @ w_hh.T
    if b_ih is not None:
        gi = gi + b_ih
    if b_hh is not None:
        gh = gh + b_hh
    i_r, i_z, i_c = jnp.split(gi, 3, axis=-1)
    h_r, h_z, h_c = jnp.split(gh, 3, axis=-1)
    r = jax.nn.sigmoid(i_r + h_r)
    z = jax.nn.sigmoid(i_z + h_z)
    c = jnp.tanh(i_c + r * h_c)
    return (1 - z) * c + z * h  # paddle/cudnn convention


@op_fn
def _rnn_scan(x, h0, w_ih, w_hh, b_ih=None, b_hh=None, *, mode: str,
              activation: str = "tanh", reverse: bool = False,
              c0=None, seq_len=None):
    """One direction, one layer. x: [B,T,I]; h0: [B,H]. Returns (out, h[,c]).

    ``seq_len`` [B] masks padded timesteps: the carried state freezes at the
    last valid step (so final h/c match the unpadded run) and padded outputs
    are zero; the reverse direction reverses only the valid region —
    reference semantics of rnn.py with sequence_length.
    """
    act = jnp.tanh if activation == "tanh" else (lambda v: jnp.maximum(v, 0))
    T = x.shape[1]
    if reverse:
        if seq_len is None:
            x = jnp.flip(x, axis=1)
        else:
            # per-batch reversal of the valid prefix: t -> len-1-t for t<len
            tgrid = jnp.arange(T)[None, :]
            idx = jnp.where(tgrid < seq_len[:, None],
                            seq_len[:, None] - 1 - tgrid, tgrid)
            x = jnp.take_along_axis(x, idx[:, :, None], axis=1)
    xs = jnp.swapaxes(x, 0, 1)  # [T,B,I]
    ts = jnp.arange(T)

    def mask_of(t):
        if seq_len is None:
            return None
        return (t < seq_len)[:, None]  # [B,1]

    if mode == "LSTM":
        def step(carry, inp):
            x_t, t = inp
            h, c = carry
            h2, c2 = _lstm_step(x_t, h, c, w_ih, w_hh, b_ih, b_hh)
            m = mask_of(t)
            if m is not None:
                h2 = jnp.where(m, h2, h)
                c2 = jnp.where(m, c2, c)
                y = jnp.where(m, h2, 0.0)
            else:
                y = h2
            return (h2, c2), y
        (hT, cT), ys = lax.scan(step, (h0, c0), (xs, ts))
    else:
        if mode == "GRU":
            def cell(x_t, h):
                return _gru_step(x_t, h, w_ih, w_hh, b_ih, b_hh)
        else:
            def cell(x_t, h):
                return _rnn_step(act, x_t, h, w_ih, w_hh, b_ih, b_hh)

        def step(h, inp):
            x_t, t = inp
            h2 = cell(x_t, h)
            m = mask_of(t)
            if m is not None:
                h2 = jnp.where(m, h2, h)
                y = jnp.where(m, h2, 0.0)
            else:
                y = h2
            return h2, y
        hT, ys = lax.scan(step, h0, (xs, ts))
    ys = jnp.swapaxes(ys, 0, 1)  # [B,T,H]
    if reverse:
        if seq_len is None:
            ys = jnp.flip(ys, axis=1)
        else:
            tgrid = jnp.arange(T)[None, :]
            idx = jnp.where(tgrid < seq_len[:, None],
                            seq_len[:, None] - 1 - tgrid, tgrid)
            ys = jnp.take_along_axis(ys, idx[:, :, None], axis=1)
    if mode == "LSTM":
        return ys, hT, cT
    return ys, hT


class _RNNCellBase(Layer):
    def __init__(self, input_size, hidden_size, n_gates, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        std = 1.0 / (hidden_size ** 0.5)
        u = I.Uniform(-std, std)
        self.weight_ih = self.create_parameter(
            (n_gates * hidden_size, input_size), attr=weight_ih_attr,
            default_initializer=u)
        self.weight_hh = self.create_parameter(
            (n_gates * hidden_size, hidden_size), attr=weight_hh_attr,
            default_initializer=u)
        self.bias_ih = None if bias_ih_attr is False else \
            self.create_parameter((n_gates * hidden_size,),
                                  attr=bias_ih_attr, is_bias=True,
                                  default_initializer=u)
        self.bias_hh = None if bias_hh_attr is False else \
            self.create_parameter((n_gates * hidden_size,),
                                  attr=bias_hh_attr, is_bias=True,
                                  default_initializer=u)

    def _zero_state(self, x, size):
        from ... import ops
        return ops.zeros([x.shape[0], size], dtype="float32")


@op_fn
def _cell_rnn(x, h, w_ih, w_hh, b_ih=None, b_hh=None, *, activation="tanh"):
    act = jnp.tanh if activation == "tanh" else (lambda v: jnp.maximum(v, 0))
    return _rnn_step(act, x, h, w_ih, w_hh, b_ih, b_hh)


@op_fn
def _cell_lstm(x, h, c, w_ih, w_hh, b_ih=None, b_hh=None):
    return _lstm_step(x, h, c, w_ih, w_hh, b_ih, b_hh)


@op_fn
def _cell_gru(x, h, w_ih, w_hh, b_ih=None, b_hh=None):
    return _gru_step(x, h, w_ih, w_hh, b_ih, b_hh)


class SimpleRNNCell(_RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__(input_size, hidden_size, 1, weight_ih_attr,
                         weight_hh_attr, bias_ih_attr, bias_hh_attr)
        self.activation = activation

    def forward(self, inputs, states=None):
        h = states if states is not None else self._zero_state(
            inputs, self.hidden_size)
        out = _cell_rnn(inputs, h, self.weight_ih, self.weight_hh,
                        self.bias_ih, self.bias_hh,
                        activation=self.activation)
        return out, out


class LSTMCell(_RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__(input_size, hidden_size, 4, weight_ih_attr,
                         weight_hh_attr, bias_ih_attr, bias_hh_attr)

    def forward(self, inputs, states=None):
        if states is None:
            h = self._zero_state(inputs, self.hidden_size)
            c = self._zero_state(inputs, self.hidden_size)
        else:
            h, c = states
        h2, c2 = _cell_lstm(inputs, h, c, self.weight_ih, self.weight_hh,
                            self.bias_ih, self.bias_hh)
        return h2, (h2, c2)


class GRUCell(_RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__(input_size, hidden_size, 3, weight_ih_attr,
                         weight_hh_attr, bias_ih_attr, bias_hh_attr)

    def forward(self, inputs, states=None):
        h = states if states is not None else self._zero_state(
            inputs, self.hidden_size)
        h2 = _cell_gru(inputs, h, self.weight_ih, self.weight_hh,
                       self.bias_ih, self.bias_hh)
        return h2, h2


class _RNNBase(Layer):
    MODE = "RNN_TANH"

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        self.activation = activation
        self.bidirect = direction in ("bidirect", "bidirectional")
        n_dir = 2 if self.bidirect else 1
        self.num_directions = n_dir
        n_gates = {"LSTM": 4, "GRU": 3}.get(self.MODE.split("_")[0], 1)
        std = 1.0 / (hidden_size ** 0.5)
        u = I.Uniform(-std, std)
        for layer in range(num_layers):
            for d in range(n_dir):
                in_size = input_size if layer == 0 else hidden_size * n_dir
                sfx = f"_reverse" if d == 1 else ""
                self.add_parameter(
                    f"weight_ih_l{layer}{sfx}",
                    self.create_parameter((n_gates * hidden_size, in_size),
                                          attr=weight_ih_attr,
                                          default_initializer=u))
                self.add_parameter(
                    f"weight_hh_l{layer}{sfx}",
                    self.create_parameter(
                        (n_gates * hidden_size, hidden_size),
                        attr=weight_hh_attr, default_initializer=u))
                self.add_parameter(
                    f"bias_ih_l{layer}{sfx}",
                    self.create_parameter((n_gates * hidden_size,),
                                          attr=bias_ih_attr, is_bias=True,
                                          default_initializer=u))
                self.add_parameter(
                    f"bias_hh_l{layer}{sfx}",
                    self.create_parameter((n_gates * hidden_size,),
                                          attr=bias_hh_attr, is_bias=True,
                                          default_initializer=u))

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ... import ops
        mode = self.MODE.split("_")[0]
        is_lstm = mode == "LSTM"
        x = inputs
        if self.time_major:
            x = ops.transpose(x, perm=[1, 0, 2])
        batch = x.shape[0]
        n_dir = self.num_directions
        total = self.num_layers * n_dir

        if initial_states is None:
            z = ops.zeros([total, batch, self.hidden_size], dtype="float32")
            h0s = [z[i] for i in range(total)]
            c0s = [z[i] for i in range(total)] if is_lstm else None
        else:
            if is_lstm:
                h0, c0 = initial_states
                h0s = [h0[i] for i in range(total)]
                c0s = [c0[i] for i in range(total)]
            else:
                h0 = initial_states
                h0s = [h0[i] for i in range(total)]
                c0s = None

        h_finals, c_finals = [], []
        for layer in range(self.num_layers):
            outs = []
            for d in range(n_dir):
                idx = layer * n_dir + d
                sfx = "_reverse" if d == 1 else ""
                w_ih = getattr(self, f"weight_ih_l{layer}{sfx}")
                w_hh = getattr(self, f"weight_hh_l{layer}{sfx}")
                b_ih = getattr(self, f"bias_ih_l{layer}{sfx}")
                b_hh = getattr(self, f"bias_hh_l{layer}{sfx}")
                slen = sequence_length
                if slen is not None and hasattr(slen, "_data"):
                    slen = slen._data
                if is_lstm:
                    y, hT, cT = _rnn_scan(
                        x, h0s[idx], w_ih, w_hh, b_ih, b_hh, mode="LSTM",
                        reverse=(d == 1), c0=c0s[idx], seq_len=slen)
                    c_finals.append(cT)
                else:
                    y, hT = _rnn_scan(
                        x, h0s[idx], w_ih, w_hh, b_ih, b_hh, mode=mode,
                        activation=self.activation, reverse=(d == 1),
                        seq_len=slen)
                h_finals.append(hT)
                outs.append(y)
            x = outs[0] if n_dir == 1 else ops.concat(outs, axis=-1)
            if self.dropout > 0 and layer < self.num_layers - 1 \
                    and self.training:
                from .. import functional as Fn
                x = Fn.dropout(x, p=self.dropout, training=True)

        out = x
        if self.time_major:
            out = ops.transpose(out, perm=[1, 0, 2])
        h_final = ops.stack(h_finals, axis=0)
        if is_lstm:
            c_final = ops.stack(c_finals, axis=0)
            return out, (h_final, c_final)
        return out, h_final


class SimpleRNN(_RNNBase):
    MODE = "RNN"


class LSTM(_RNNBase):
    MODE = "LSTM"


class GRU(_RNNBase):
    MODE = "GRU"


class RNN(Layer):
    """Wraps a cell into a sequence runner (reference: rnn.py RNN).
    Python loop over time — for odd custom cells; the fused classes above
    are the fast path."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None,
                **kwargs):
        from ... import ops
        x = inputs
        if self.time_major:
            x = ops.transpose(x, perm=[1, 0, 2])
        T = x.shape[1]
        steps = range(T - 1, -1, -1) if self.is_reverse else range(T)
        states = initial_states
        ys = []
        for t in steps:
            y, states = self.cell(x[:, t], states)
            ys.append(y)
        if self.is_reverse:
            ys = ys[::-1]
        out = ops.stack(ys, axis=1)
        if self.time_major:
            out = ops.transpose(out, perm=[1, 0, 2])
        return out, states


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, is_reverse=False, time_major=time_major)
        self.rnn_bw = RNN(cell_bw, is_reverse=True, time_major=time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ... import ops
        st_fw, st_bw = (initial_states if initial_states is not None
                        else (None, None))
        out_fw, s_fw = self.rnn_fw(inputs, st_fw, sequence_length)
        out_bw, s_bw = self.rnn_bw(inputs, st_bw, sequence_length)
        return ops.concat([out_fw, out_bw], axis=-1), (s_fw, s_bw)
