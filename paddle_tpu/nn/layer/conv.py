"""Convolution layers (reference: python/paddle/nn/layer/conv.py)."""
from __future__ import annotations

import numpy as np

from .. import functional as F
from .. import initializer as I
from .base import Layer

__all__ = ["Conv1D", "Conv2D", "Conv3D", "Conv1DTranspose",
           "Conv2DTranspose", "Conv3DTranspose"]


def _ntuple(v, n):
    return (v,) * n if isinstance(v, int) else tuple(v)


class _ConvNd(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, nsp,
                 stride=1, padding=0, dilation=1, groups=1,
                 padding_mode="zeros", weight_attr=None, bias_attr=None,
                 data_format="NCHW", transpose=False, output_padding=0):
        super().__init__()
        self._in_channels = in_channels
        self._out_channels = out_channels
        self._kernel_size = _ntuple(kernel_size, nsp)
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self._groups = groups
        self._padding_mode = padding_mode
        self._data_format = data_format
        self._nsp = nsp
        self._transpose = transpose
        self._output_padding = output_padding
        if transpose:
            w_shape = (in_channels, out_channels // groups) + self._kernel_size
        else:
            w_shape = (out_channels, in_channels // groups) + self._kernel_size
        fan_in = (in_channels // groups) * int(np.prod(self._kernel_size))
        bound = 1.0 / np.sqrt(fan_in)
        self.weight = self.create_parameter(
            w_shape, attr=weight_attr,
            default_initializer=I.KaimingUniform(
                negative_slope=np.sqrt(5.0), nonlinearity="leaky_relu"))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                (out_channels,), attr=bias_attr, is_bias=True,
                default_initializer=I.Uniform(-bound, bound))

    def _prepad(self, x):
        """Non-zero padding modes pre-pad the input (reflect/replicate/
        circular) and run the conv unpadded (reference: conv.py _ConvNd)."""
        if self._padding_mode == "zeros":
            return x, self._padding
        p = self._padding
        if isinstance(p, int):
            spec = [p, p] * self._nsp
        else:
            # conv padding lists are first-spatial-dim-first; F.pad wants
            # last-dim-first pairs, so reverse the per-dim order.
            spec = []
            for v in reversed(list(p)):
                if isinstance(v, (tuple, list)):
                    spec += [v[0], v[1]]
                else:
                    spec += [v, v]
        mode = {"reflect": "reflect", "replicate": "replicate",
                "circular": "circular"}[self._padding_mode]
        x = F.pad(x, spec, mode=mode, data_format=self._data_format)
        return x, 0

    def extra_repr(self):
        return (f"{self._in_channels}, {self._out_channels}, "
                f"kernel_size={self._kernel_size}, stride={self._stride}")


class Conv1D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, 1, stride,
                         padding, dilation, groups, padding_mode, weight_attr,
                         bias_attr, data_format)

    def forward(self, x):
        x, pad = self._prepad(x)
        return F.conv1d(x, self.weight, self.bias, stride=self._stride,
                        padding=pad, dilation=self._dilation,
                        groups=self._groups, data_format=self._data_format)


class Conv2D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, 2, stride,
                         padding, dilation, groups, padding_mode, weight_attr,
                         bias_attr, data_format)

    def forward(self, x):
        x, pad = self._prepad(x)
        return F.conv2d(x, self.weight, self.bias, stride=self._stride,
                        padding=pad, dilation=self._dilation,
                        groups=self._groups, data_format=self._data_format)


class Conv3D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, 3, stride,
                         padding, dilation, groups, padding_mode, weight_attr,
                         bias_attr, data_format)

    def forward(self, x):
        x, pad = self._prepad(x)
        return F.conv3d(x, self.weight, self.bias, stride=self._stride,
                        padding=pad, dilation=self._dilation,
                        groups=self._groups, data_format=self._data_format)


class Conv1DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, 1, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, transpose=True,
                         output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv1d_transpose(
            x, self.weight, self.bias, stride=self._stride,
            padding=self._padding, output_padding=self._output_padding,
            dilation=self._dilation, groups=self._groups,
            output_size=output_size, data_format=self._data_format)


class Conv2DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, 2, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, transpose=True,
                         output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv2d_transpose(
            x, self.weight, self.bias, stride=self._stride,
            padding=self._padding, output_padding=self._output_padding,
            dilation=self._dilation, groups=self._groups,
            output_size=output_size, data_format=self._data_format)


class Conv3DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, 3, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, transpose=True,
                         output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv3d_transpose(
            x, self.weight, self.bias, stride=self._stride,
            padding=self._padding, output_padding=self._output_padding,
            dilation=self._dilation, groups=self._groups,
            output_size=output_size, data_format=self._data_format)
