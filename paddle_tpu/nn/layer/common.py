"""Common layers: Linear, Embedding, Dropout, Flatten, padding, upsample…

Reference: python/paddle/nn/layer/common.py, distance.py.
"""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from ...core.tensor import Tensor
from .. import functional as F
from .. import initializer as I
from .base import Layer

__all__ = ["Identity", "Linear", "Embedding", "Dropout", "Dropout2D",
           "Dropout3D", "AlphaDropout", "Flatten", "Unflatten", "Pad1D",
           "Pad2D", "Pad3D", "ZeroPad2D", "Upsample", "UpsamplingNearest2D",
           "UpsamplingBilinear2D", "PixelShuffle", "PixelUnshuffle",
           "ChannelShuffle", "CosineSimilarity", "PairwiseDistance",
           "Bilinear", "Fold", "Unfold", "LayerNorm", "Linear"]


class Identity(Layer):
    def forward(self, x):
        return x


class Linear(Layer):
    """y = xW + b, weight [in_features, out_features]
    (reference: nn/layer/common.py Linear)."""

    def __init__(self, in_features: int, out_features: int, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.weight = self.create_parameter(
            (in_features, out_features), attr=weight_attr,
            default_initializer=I.XavierUniform())
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                (out_features,), attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)

    def extra_repr(self):
        return (f"in_features={self.in_features}, "
                f"out_features={self.out_features}")


class Embedding(Layer):
    """Reference: nn/layer/common.py Embedding; weight [num, dim]."""

    def __init__(self, num_embeddings: int, embedding_dim: int,
                 padding_idx: Optional[int] = None, sparse: bool = False,
                 weight_attr=None, name=None):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        if padding_idx is not None and padding_idx < 0:
            padding_idx = num_embeddings + padding_idx
        self.padding_idx = padding_idx
        self.sparse = sparse
        self.weight = self.create_parameter(
            (num_embeddings, embedding_dim), attr=weight_attr,
            default_initializer=I.Normal(0.0, 1.0))
        if padding_idx is not None:
            self.weight._data = self.weight._data.at[padding_idx].set(0.0)

    def forward(self, x):
        return F.embedding(x, self.weight, padding_idx=self.padding_idx,
                           sparse=self.sparse)

    def extra_repr(self):
        return f"{self.num_embeddings}, {self.embedding_dim}"


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p, self.axis, self.mode = p, axis, mode

    def forward(self, x):
        return F.dropout(x, p=self.p, axis=self.axis, training=self.training,
                         mode=self.mode)

    def extra_repr(self):
        return f"p={self.p}"


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format="NCHW", name=None):
        super().__init__()
        self.p, self.data_format = p, data_format

    def forward(self, x):
        return F.dropout2d(x, p=self.p, training=self.training,
                           data_format=self.data_format)


class Dropout3D(Layer):
    def __init__(self, p=0.5, data_format="NCDHW", name=None):
        super().__init__()
        self.p, self.data_format = p, data_format

    def forward(self, x):
        return F.dropout3d(x, p=self.p, training=self.training,
                           data_format=self.data_format)


class AlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.alpha_dropout(x, p=self.p, training=self.training)


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis, self.stop_axis = start_axis, stop_axis

    def forward(self, x):
        from ... import ops
        return ops.flatten(x, start_axis=self.start_axis,
                           stop_axis=self.stop_axis)


class Unflatten(Layer):
    def __init__(self, axis, shape, name=None):
        super().__init__()
        self.axis, self.shape = axis, shape

    def forward(self, x):
        from ... import ops
        full = list(x.shape)
        ax = self.axis if self.axis >= 0 else x.ndim + self.axis
        new = full[:ax] + list(self.shape) + full[ax + 1:]
        return ops.reshape(x, shape=new)


class _PadN(Layer):
    def __init__(self, padding, mode="constant", value=0.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self.padding, self.mode, self.value = padding, mode, value
        self.data_format = data_format

    def forward(self, x):
        return F.pad(x, self.padding, mode=self.mode, value=self.value,
                     data_format=self.data_format)


class Pad1D(_PadN):
    def __init__(self, padding, mode="constant", value=0.0,
                 data_format="NCL", name=None):
        super().__init__(padding, mode, value, data_format, name)


class Pad2D(_PadN):
    pass


class Pad3D(_PadN):
    def __init__(self, padding, mode="constant", value=0.0,
                 data_format="NCDHW", name=None):
        super().__init__(padding, mode, value, data_format, name)


class ZeroPad2D(_PadN):
    def __init__(self, padding, data_format="NCHW", name=None):
        super().__init__(padding, "constant", 0.0, data_format, name)


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest",
                 align_corners=False, align_mode=0, data_format="NCHW",
                 name=None):
        super().__init__()
        self.size, self.scale_factor = size, scale_factor
        self.mode, self.align_corners = mode, align_corners
        self.data_format = data_format

    def forward(self, x):
        return F.interpolate(x, size=self.size,
                             scale_factor=self.scale_factor, mode=self.mode,
                             align_corners=self.align_corners,
                             data_format=self.data_format)


class UpsamplingNearest2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW",
                 name=None):
        super().__init__(size, scale_factor, "nearest", False, 0, data_format)


class UpsamplingBilinear2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW",
                 name=None):
        super().__init__(size, scale_factor, "bilinear", True, 0, data_format)


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.upscale_factor, self.data_format = upscale_factor, data_format

    def forward(self, x):
        return F.pixel_shuffle(x, upscale_factor=self.upscale_factor,
                               data_format=self.data_format)


class PixelUnshuffle(Layer):
    def __init__(self, downscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.downscale_factor, self.data_format = downscale_factor, data_format

    def forward(self, x):
        return F.pixel_unshuffle(x, downscale_factor=self.downscale_factor,
                                 data_format=self.data_format)


class ChannelShuffle(Layer):
    def __init__(self, groups, data_format="NCHW", name=None):
        super().__init__()
        self.groups, self.data_format = groups, data_format

    def forward(self, x):
        return F.channel_shuffle(x, groups=self.groups,
                                 data_format=self.data_format)


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self.axis, self.eps = axis, eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, axis=self.axis, eps=self.eps)


class PairwiseDistance(Layer):
    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self.p, self.epsilon, self.keepdim = p, epsilon, keepdim

    def forward(self, x, y):
        from ... import ops
        return ops.norm(x - y + self.epsilon, p=self.p, axis=-1,
                        keepdim=self.keepdim)


class Bilinear(Layer):
    def __init__(self, in1_features, in2_features, out_features,
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            (out_features, in1_features, in2_features), attr=weight_attr)
        self.bias = None if bias_attr is False else self.create_parameter(
            (out_features,), attr=bias_attr, is_bias=True)

    def forward(self, x1, x2):
        return F.bilinear(x1, x2, self.weight, self.bias)


class Fold(Layer):
    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0,
                 dilations=1, name=None):
        super().__init__()
        self.a = (output_sizes, kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        o, k, s, p, d = self.a
        return F.fold(x, output_sizes=o, kernel_sizes=k, strides=s,
                      paddings=p, dilations=d)


class Unfold(Layer):
    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1,
                 name=None):
        super().__init__()
        self.a = (kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        k, s, p, d = self.a
        return F.unfold(x, kernel_sizes=k, strides=s, paddings=p,
                        dilations=d)


# LayerNorm lives logically in norm.py; imported there. Kept out of common.
from .norm import LayerNorm  # noqa: E402  (re-export for paddle parity)
