"""The Layer base class (paddle.nn.Layer parity).

Reference: python/paddle/nn/layer/layers.py (class Layer). TPU-native notes:
parameters are Tensor handles over jax.Arrays; ``state_dict`` yields the
handles so a jitted step can flatten them as a pytree (Layer itself also
registers as a pytree via ``parameters()``/``raw_state``); buffers
(e.g. BN running stats) are non-trainable handles updated by rebind.
"""
from __future__ import annotations

import contextlib
from collections import OrderedDict
from typing import Callable, Iterator, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ...core import dtype as dtypes
from ...core.tensor import Parameter, Tensor
from .. import initializer as init_mod
from ...core import enforce as E

__all__ = ["Layer"]


class HookRemoveHelper:
    def __init__(self, hooks, key):
        self._hooks, self._key = hooks, key

    def remove(self):
        self._hooks.pop(self._key, None)


class Layer:
    """Base class for all network layers."""

    def __init__(self, name_scope: Optional[str] = None, dtype="float32"):
        self._dtype = dtypes.convert_dtype(dtype) if dtype else jnp.float32
        self._parameters: "OrderedDict[str, Parameter]" = OrderedDict()
        self._sub_layers: "OrderedDict[str, Layer]" = OrderedDict()
        self._buffers: "OrderedDict[str, Tensor]" = OrderedDict()
        self._non_persistable_buffer_names = set()
        self.training = True
        self._forward_pre_hooks: "OrderedDict[int, Callable]" = OrderedDict()
        self._forward_post_hooks: "OrderedDict[int, Callable]" = OrderedDict()
        self._hook_id = 0
        self._name_scope = name_scope or type(self).__name__.lower()

    # -- construction helpers ------------------------------------------------
    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None):
        """paddle.nn.Layer.create_parameter parity. ``attr`` may be a
        ParamAttr-like object (initializer/trainable/name), False (no param),
        or an Initializer."""
        if attr is False:
            return None
        dtype = dtypes.convert_dtype(dtype) if dtype else self._dtype
        initializer = None
        trainable = True
        name = None
        if attr is not None:
            if isinstance(attr, init_mod.Initializer):
                initializer = attr
            else:
                initializer = getattr(attr, "initializer", None)
                trainable = getattr(attr, "trainable", True)
                name = getattr(attr, "name", None)
        # Precedence (reference: layers.py create_parameter): explicit
        # attr initializer > global initializer > caller's default >
        # built-in default (zeros for bias, XavierUniform for weights).
        if initializer is None:
            initializer = init_mod.global_initializer(is_bias)
        if initializer is None:
            initializer = default_initializer
        if initializer is None:
            initializer = init_mod.Constant(0.0) if is_bias \
                else init_mod.XavierUniform()
        data = initializer(tuple(int(s) for s in shape), dtype)
        return Parameter(data, name=name, trainable=trainable)

    def add_parameter(self, name: str, parameter: Optional[Parameter]):
        if parameter is None:
            self._parameters[name] = None
        else:
            self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name: str, sublayer: "Layer"):
        self._sub_layers[name] = sublayer
        return sublayer

    def register_buffer(self, name: str, tensor: Optional[Tensor],
                        persistable: bool = True):
        if tensor is not None and not isinstance(tensor, Tensor):
            tensor = Tensor(jnp.asarray(tensor))
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        return tensor

    # -- attribute protocol --------------------------------------------------
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        subs = self.__dict__.get("_sub_layers")
        bufs = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise E.PreconditionNotMetError("call Layer.__init__ before assigning params")
            bufs.pop(name, None) if bufs else None
            params[name] = value
        elif isinstance(value, Layer):
            if subs is None:
                raise E.PreconditionNotMetError("call Layer.__init__ before assigning sublayers")
            subs[name] = value
        elif params is not None and name in params:
            params[name] = value
        elif subs is not None and name in subs:
            subs[name] = value
        elif bufs is not None and name in bufs:
            if value is not None and not isinstance(value, Tensor):
                value = Tensor(jnp.asarray(value))
            bufs[name] = value
        else:
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        # only called when normal lookup fails
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(
            f"'{type(self).__name__}' object has no attribute '{name}'")

    def __delattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def __dir__(self):
        extra = []
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d:
                extra += list(d.keys())
        return list(super().__dir__()) + extra

    # -- traversal -----------------------------------------------------------
    def named_parameters(self, prefix: str = "",
                         include_sublayers: bool = True
                         ) -> Iterator[Tuple[str, Parameter]]:
        seen = set()
        for layer_prefix, layer in self.named_sublayers(
                prefix=prefix, include_self=True):
            if not include_sublayers and layer is not self:
                continue
            for pname, p in layer._parameters.items():
                if p is None or id(p) in seen:
                    continue
                seen.add(id(p))
                full = f"{layer_prefix}.{pname}" if layer_prefix else pname
                yield full, p

    def parameters(self, include_sublayers: bool = True):
        return [p for _, p in self.named_parameters(
            include_sublayers=include_sublayers)]

    def named_sublayers(self, prefix: str = "", include_self: bool = False,
                        layers_set=None) -> Iterator[Tuple[str, "Layer"]]:
        if layers_set is None:
            layers_set = set()
        if id(self) in layers_set:
            return
        layers_set.add(id(self))
        if include_self:
            yield prefix, self
        for name, sub in self._sub_layers.items():
            if sub is None:
                continue
            sub_prefix = f"{prefix}.{name}" if prefix else name
            yield from sub.named_sublayers(prefix=sub_prefix,
                                           include_self=True,
                                           layers_set=layers_set)

    def sublayers(self, include_self: bool = False):
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def children(self):
        return iter([l for l in self._sub_layers.values() if l is not None])

    def named_children(self):
        return iter([(n, l) for n, l in self._sub_layers.items()
                     if l is not None])

    def named_buffers(self, prefix: str = "", include_sublayers: bool = True):
        seen = set()
        for layer_prefix, layer in self.named_sublayers(
                prefix=prefix, include_self=True):
            if not include_sublayers and layer is not self:
                continue
            for bname, b in layer._buffers.items():
                if b is None or id(b) in seen:
                    continue
                seen.add(id(b))
                full = f"{layer_prefix}.{bname}" if layer_prefix else bname
                yield full, b

    def buffers(self, include_sublayers: bool = True):
        return [b for _, b in self.named_buffers(
            include_sublayers=include_sublayers)]

    def apply(self, fn):
        for l in self.sublayers(include_self=True):
            fn(l)
        return self

    # -- train / eval --------------------------------------------------------
    def train(self):
        for l in self.sublayers(include_self=True):
            l.training = True
        return self

    def eval(self):
        for l in self.sublayers(include_self=True):
            l.training = False
        return self

    # -- hooks ---------------------------------------------------------------
    def register_forward_pre_hook(self, hook):
        self._hook_id += 1
        self._forward_pre_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_pre_hooks, self._hook_id)

    def register_forward_post_hook(self, hook):
        self._hook_id += 1
        self._forward_post_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_post_hooks, self._hook_id)

    # -- call ----------------------------------------------------------------
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in self._forward_pre_hooks.values():
            out = hook(self, inputs)
            if out is not None:
                inputs = out if isinstance(out, tuple) else (out,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in self._forward_post_hooks.values():
            out = hook(self, inputs, outputs)
            if out is not None:
                outputs = out
        return outputs

    # -- state dict ----------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers: bool = True,
                   use_hook: bool = True, structured_name_prefix: str = ""):
        dest = destination if destination is not None else OrderedDict()
        for name, p in self.named_parameters(prefix=structured_name_prefix):
            dest[name] = p
        for name, b in self.named_buffers(prefix=structured_name_prefix):
            short = name.rsplit(".", 1)[-1]
            # find owning layer to check persistability
            owner = self
            parts = name.split(".")[:-1]
            try:
                for part in parts:
                    owner = owner._sub_layers[part]
            except Exception:
                owner = None
            if owner is not None and short in owner._non_persistable_buffer_names:
                continue
            dest[name] = b
        return dest

    def to_static_state_dict(self, *a, **k):
        return self.state_dict(*a, **k)

    def set_state_dict(self, state_dict, use_structured_name: bool = True):
        """Load values into existing parameter/buffer handles (rebind)."""
        missing, unexpected = [], []
        own = self.state_dict()
        for name, value in state_dict.items():
            if name not in own:
                unexpected.append(name)
                continue
            target = own[name]
            arr = value._data if isinstance(value, Tensor) else jnp.asarray(
                np.asarray(value))
            if tuple(arr.shape) != tuple(target._data.shape):
                raise E.InvalidArgumentError(
                    f"shape mismatch for {name}: {tuple(arr.shape)} vs "
                    f"{tuple(target._data.shape)}")
            target._data = arr.astype(target._data.dtype)
        for name in own:
            if name not in state_dict:
                missing.append(name)
        return missing, unexpected

    set_dict = set_state_dict
    load_dict = set_state_dict

    # -- dtype / conversion --------------------------------------------------
    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            dtype = dtypes.convert_dtype(dtype)
            for _, p in self.named_parameters():
                if jnp.issubdtype(p._data.dtype, jnp.floating):
                    p._data = p._data.astype(dtype)
            for _, b in self.named_buffers():
                if jnp.issubdtype(b._data.dtype, jnp.floating):
                    b._data = b._data.astype(dtype)
            for l in self.sublayers(include_self=True):
                l._dtype = dtype
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype=jnp.float32)

    def half(self):
        return self.to(dtype=jnp.float16)

    def bfloat16(self):
        return self.to(dtype=jnp.bfloat16)

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()

    def full_name(self):
        return self._name_scope

    def extra_repr(self) -> str:
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, sub in self._sub_layers.items():
            sub_repr = repr(sub).split("\n")
            sub_repr = [sub_repr[0]] + ["  " + ln for ln in sub_repr[1:]]
            lines.append(f"  ({name}): " + "\n".join(sub_repr))
        main = f"{type(self).__name__}({extra}"
        if lines:
            return main + "\n" + "\n".join(lines) + "\n)"
        return main + ")"
