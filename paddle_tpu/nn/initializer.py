"""Weight initializers (paddle.nn.initializer parity).

Reference: python/paddle/nn/initializer/ (constant.py, normal.py, uniform.py,
xavier.py, kaiming.py, assign.py, orthogonal.py, dirac.py). Initializers are
callables ``init(shape, dtype) -> jax.Array`` drawing from the framework RNG;
a Layer calls them through ``create_parameter``.
"""
from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import random as frandom
from ..core import enforce as E

__all__ = [
    "Initializer", "Constant", "Normal", "TruncatedNormal", "Uniform",
    "XavierNormal", "XavierUniform", "KaimingNormal", "KaimingUniform",
    "Assign", "Orthogonal", "Dirac", "calculate_gain", "set_global_initializer",
]

_global_weight_init = None
_global_bias_init = None


def set_global_initializer(weight_init, bias_init=None):
    global _global_weight_init, _global_bias_init
    _global_weight_init = weight_init
    _global_bias_init = bias_init


def global_initializer(is_bias=False):
    return _global_bias_init if is_bias else _global_weight_init


def calculate_gain(nonlinearity: str, param=None) -> float:
    gains = {"sigmoid": 1.0, "linear": 1.0, "conv1d": 1.0, "conv2d": 1.0,
             "conv3d": 1.0, "conv_transpose1d": 1.0, "conv_transpose2d": 1.0,
             "conv_transpose3d": 1.0, "tanh": 5.0 / 3.0,
             "relu": math.sqrt(2.0), "selu": 3.0 / 4.0}
    if nonlinearity == "leaky_relu":
        a = 0.01 if param is None else param
        return math.sqrt(2.0 / (1 + a ** 2))
    return gains.get(nonlinearity, 1.0)


def _fans(shape: Sequence[int]):
    if len(shape) < 1:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        # paddle linear weight [in, out]
        return shape[0], shape[1]
    # conv [out_c, in_c/groups, *k]
    rf = int(np.prod(shape[2:]))
    return shape[1] * rf, shape[0] * rf


class Initializer:
    def __call__(self, shape, dtype=jnp.float32):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value: float = 0.0):
        self.value = value

    def __call__(self, shape, dtype=jnp.float32):
        return jnp.full(shape, self.value, dtype=dtype)


class Normal(Initializer):
    def __init__(self, mean: float = 0.0, std: float = 1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype=jnp.float32):
        k = frandom.next_key()
        return (self.mean + self.std *
                jax.random.normal(k, shape, jnp.float32)).astype(dtype)


class TruncatedNormal(Initializer):
    def __init__(self, mean: float = 0.0, std: float = 1.0, a: float = -2.0,
                 b: float = 2.0):
        self.mean, self.std, self.a, self.b = mean, std, a, b

    def __call__(self, shape, dtype=jnp.float32):
        k = frandom.next_key()
        r = jax.random.truncated_normal(k, self.a, self.b, shape, jnp.float32)
        return (self.mean + self.std * r).astype(dtype)


class Uniform(Initializer):
    def __init__(self, low: float = -1.0, high: float = 1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype=jnp.float32):
        k = frandom.next_key()
        return jax.random.uniform(k, shape, jnp.float32, self.low,
                                  self.high).astype(dtype)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain: float = 1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype=jnp.float32):
        fi, fo = _fans(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        k = frandom.next_key()
        return (std * jax.random.normal(k, shape, jnp.float32)).astype(dtype)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain: float = 1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype=jnp.float32):
        fi, fo = _fans(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        k = frandom.next_key()
        return jax.random.uniform(k, shape, jnp.float32, -limit,
                                  limit).astype(dtype)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope: float = 0.0,
                 nonlinearity: str = "relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype=jnp.float32):
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        std = gain / math.sqrt(fi)
        k = frandom.next_key()
        return (std * jax.random.normal(k, shape, jnp.float32)).astype(dtype)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope: float = 0.0,
                 nonlinearity: str = "relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype=jnp.float32):
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        limit = gain * math.sqrt(3.0 / fi)
        k = frandom.next_key()
        return jax.random.uniform(k, shape, jnp.float32, -limit,
                                  limit).astype(dtype)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype=jnp.float32):
        arr = jnp.asarray(np.asarray(self.value), dtype=dtype)
        if tuple(arr.shape) != tuple(shape):
            arr = arr.reshape(shape)
        return arr


class Orthogonal(Initializer):
    def __init__(self, gain: float = 1.0):
        self.gain = gain

    def __call__(self, shape, dtype=jnp.float32):
        if len(shape) < 2:
            raise E.InvalidArgumentError("Orthogonal init needs >=2 dims")
        rows = shape[0]
        cols = int(np.prod(shape[1:]))
        k = frandom.next_key()
        a = jax.random.normal(k, (max(rows, cols), min(rows, cols)),
                              jnp.float32)
        q, r = jnp.linalg.qr(a)
        q = q * jnp.sign(jnp.diagonal(r))
        if rows < cols:
            q = q.T
        return (self.gain * q[:rows, :cols].reshape(shape)).astype(dtype)


class Dirac(Initializer):
    def __init__(self, groups: int = 1):
        self.groups = groups

    def __call__(self, shape, dtype=jnp.float32):
        # conv weight [out, in, *k]; delta kernel preserving identity
        w = np.zeros(shape, dtype=np.float32)
        out_c, in_c = shape[0], shape[1]
        og = out_c // self.groups
        centers = tuple(s // 2 for s in shape[2:])
        for g in range(self.groups):
            for i in range(min(og, in_c)):
                w[(g * og + i, i) + centers] = 1.0
        return jnp.asarray(w, dtype=dtype)


class Bilinear(Initializer):
    """Bilinear-upsample kernel init for transposed conv weights
    (reference: nn/initializer/Bilinear): weight [C_out, C_in, kH, kW]
    gets the separable triangle filter that linearly interpolates."""

    def __call__(self, shape, dtype=jnp.float32):
        if len(shape) != 4:
            raise E.InvalidArgumentError(
                f"Bilinear expects a 4-D conv weight shape, got {shape}")
        kh, kw = shape[2], shape[3]

        def tri(k):
            f = (k + 1) // 2
            center = f - 1 if k % 2 == 1 else f - 0.5
            return 1 - np.abs(np.arange(k) - center) / f

        kernel = np.outer(tri(kh), tri(kw)).astype(np.float32)
        w = np.zeros(shape, np.float32)
        for o in range(shape[0]):
            for i in range(shape[1]):
                w[o, i] = kernel
        return jnp.asarray(w, dtype=dtype)


__all__.append("Bilinear")


# fluid-era initializer aliases (the reference binds both names;
# nn/initializer/__init__.py imports XavierInitializer etc.)
ConstantInitializer = Constant
NormalInitializer = Normal
TruncatedNormalInitializer = TruncatedNormal
UniformInitializer = Uniform
XavierInitializer = XavierUniform
MSRAInitializer = KaimingUniform
NumpyArrayInitializer = Assign
__all__ += ["ConstantInitializer", "NormalInitializer",
            "TruncatedNormalInitializer", "UniformInitializer",
            "XavierInitializer", "MSRAInitializer",
            "NumpyArrayInitializer"]
