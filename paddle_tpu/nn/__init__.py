"""paddle.nn parity surface (reference: python/paddle/nn/__init__.py):
Layer system, layers, functional, initializers.
"""
from . import functional  # noqa
from . import initializer  # noqa
from .layer import *  # noqa: F401,F403
from .layer.base import Layer  # noqa
from .layer.rnn import _RNNCellBase as RNNCellBase  # noqa
from . import utils  # noqa
# the reference also binds the spectral_norm helper at nn top level
from .utils import spectral_norm  # noqa
from . import quant  # noqa
from .decode import BeamSearchDecoder, Decoder, dynamic_decode  # noqa
from ..optimizer import (ClipGradByGlobalNorm, ClipGradByNorm,  # noqa
                         ClipGradByValue)


class ParamAttr:
    """paddle.ParamAttr parity: bundles initializer/trainable/name
    (+ regularizer, learning_rate consumed by the optimizer)."""

    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, do_model_average=True,
                 need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip
