"""Seq2seq decoding: Decoder base, BeamSearchDecoder, dynamic_decode.

Reference capability: python/paddle/nn/decode.py (Decoder:42,
BeamSearchDecoder:153, dynamic_decode:674 imperative path).

TPU-native design: the decode loop is an eager host loop over jitted cell
steps (the eager imperative path of the reference); every per-step tensor
op is static-shaped [batch*beam, ...] so each step hits the same compiled
program. A fully-fused lax.while_loop variant can wrap a Decoder whose
step is pure, but the API surface here mirrors the reference's imperative
semantics (early exit when all beams finish).
"""
from __future__ import annotations

import collections

import jax
import jax.numpy as jnp
import numpy as np

from ..ops._op import unwrap, wrap
from .functional.extras import gather_tree

__all__ = ["Decoder", "BeamSearchDecoder", "dynamic_decode"]


def _map_structure(fn, *trees):
    """Structure map treating Tensors (and lists used as accumulators) as
    leaves — unlike jax.tree.map, which would descend into the registered
    Tensor pytree."""
    t0 = trees[0]
    if isinstance(t0, tuple) and hasattr(t0, "_fields"):    # namedtuple
        return type(t0)(*(_map_structure(fn, *vals)
                          for vals in zip(*trees)))
    if isinstance(t0, (tuple, list)) and not isinstance(t0, _Acc):
        return type(t0)(_map_structure(fn, *vals) for vals in zip(*trees))
    if isinstance(t0, dict):
        return {k: _map_structure(fn, *(t[k] for t in trees)) for k in t0}
    return fn(*trees)


class _Acc(list):
    """Per-leaf step accumulator (a list subclass the structure mapper
    treats as a leaf — reference decode.py ArrayWrapper)."""


def _flatten_structure(tree):
    if isinstance(tree, (tuple, list)):
        out = []
        for v in tree:
            out.extend(_flatten_structure(v))
        return out
    if isinstance(tree, dict):
        out = []
        for k in tree:
            out.extend(_flatten_structure(tree[k]))
        return out
    return [tree]


class Decoder:
    """Base decoder interface (reference decode.py:42): initialize / step /
    finalize + tracks_own_finished."""

    def initialize(self, inits):
        raise NotImplementedError

    def step(self, time, inputs, states, **kwargs):
        raise NotImplementedError

    def finalize(self, outputs, final_states, sequence_lengths):
        raise NotImplementedError

    @property
    def tracks_own_finished(self):
        return False


class BeamSearchDecoder(Decoder):
    """Beam search over an RNN-style cell (reference decode.py:153).

    cell: callable (inputs [B*W, I], states) -> (cell_out [B*W, H], states)
    embedding_fn: token ids -> embeddings; output_fn: projects cell output
    to vocab logits.
    """

    class OutputWrapper(collections.namedtuple(
            "OutputWrapper", ("scores", "predicted_ids", "parent_ids"))):
        pass

    class StateWrapper(collections.namedtuple(
            "StateWrapper", ("cell_states", "log_probs", "finished",
                             "lengths"))):
        pass

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    # -- shape utilities (reference decode.py:241-327) ----------------------

    @staticmethod
    def tile_beam_merge_with_batch(x, beam_size):
        a = unwrap(x)
        a = jnp.repeat(a[:, None], beam_size, axis=1)
        return wrap(a.reshape((-1,) + a.shape[2:]))

    def _split_batch_beams(self, x):
        a = unwrap(x)
        return wrap(a.reshape((-1, self.beam_size) + a.shape[1:]))

    def _merge_batch_beams(self, x):
        a = unwrap(x)
        return wrap(a.reshape((-1,) + a.shape[2:]))

    def _expand_to_beam_size(self, x):
        a = unwrap(x)
        return wrap(jnp.repeat(a[:, None], self.beam_size, axis=1))

    def _mask_probs(self, probs, finished):
        """Finished beams emit only end_token with prob 1 (reference
        decode.py:329)."""
        noend = jnp.full((probs.shape[-1],), -1e18, probs.dtype)
        noend = noend.at[self.end_token].set(0.0)
        fin = finished.astype(bool)[..., None]
        return jnp.where(fin, noend[None, None, :], probs)

    def _gather(self, x, indices):
        b = indices.shape[0]
        return x[jnp.arange(b)[:, None], indices]

    # -- decoder interface --------------------------------------------------

    def initialize(self, initial_cell_states):
        cell_states = _map_structure(self._expand_to_beam_size,
                                     initial_cell_states)
        batch = unwrap(_flatten_structure(cell_states)[0]).shape[0]
        # cell states run merged [batch*beam, ...] between steps
        cell_states = _map_structure(self._merge_batch_beams, cell_states)
        log_probs = jnp.tile(
            jnp.asarray([0.0] + [-1e18] * (self.beam_size - 1), jnp.float32),
            (batch, 1))
        finished = jnp.zeros((batch, self.beam_size), bool)
        lengths = jnp.zeros((batch, self.beam_size), jax.dtypes.canonicalize_dtype(jnp.int64))
        init_ids = jnp.full((batch, self.beam_size), self.start_token,
                            jax.dtypes.canonicalize_dtype(jnp.int64))
        inputs = wrap(init_ids)
        if self.embedding_fn is not None:
            inputs = self.embedding_fn(inputs)
        states = self.StateWrapper(cell_states, wrap(log_probs),
                                   wrap(finished), wrap(lengths))
        return inputs, states, wrap(finished)

    def _beam_search_step(self, time, logits, next_cell_states, beam_state):
        import jax

        logits = unwrap(logits)                      # [B, W, V]
        step_log_probs = jax.nn.log_softmax(logits, axis=-1)
        step_log_probs = self._mask_probs(step_log_probs,
                                          unwrap(beam_state.finished))
        log_probs = unwrap(beam_state.log_probs)[..., None] + step_log_probs
        vocab = log_probs.shape[-1]
        batch = log_probs.shape[0]
        flat = log_probs.reshape(batch, -1)
        top_scores, top_idx = jax.lax.top_k(flat, self.beam_size)
        parent = (top_idx // vocab).astype(jax.dtypes.canonicalize_dtype(jnp.int64))     # beam index
        token = (top_idx % vocab).astype(jax.dtypes.canonicalize_dtype(jnp.int64))

        prev_fin = self._gather(unwrap(beam_state.finished), parent)
        next_fin = prev_fin | (token == self.end_token)
        next_len = self._gather(unwrap(beam_state.lengths), parent) + \
            (~prev_fin).astype(jax.dtypes.canonicalize_dtype(jnp.int64))

        next_cell_states = _map_structure(
            lambda s: wrap(self._gather(
                unwrap(self._split_batch_beams(s)), parent).reshape(
                    (-1,) + unwrap(s).shape[1:])),
            next_cell_states)
        output = self.OutputWrapper(wrap(top_scores), wrap(token),
                                    wrap(parent))
        state = self.StateWrapper(next_cell_states, wrap(top_scores),
                                  wrap(next_fin), wrap(next_len))
        return output, state

    def step(self, time, inputs, states, **kwargs):
        merged = self._merge_batch_beams(inputs) \
            if unwrap(inputs).ndim > 1 else inputs
        cell_out, next_cell_states = self.cell(merged, states.cell_states,
                                               **kwargs)
        if self.output_fn is not None:
            cell_out = self.output_fn(cell_out)
        logits = self._split_batch_beams(cell_out)
        output, next_states = self._beam_search_step(
            time, logits, next_cell_states, states)
        next_inputs = output.predicted_ids
        if self.embedding_fn is not None:
            next_inputs = self.embedding_fn(next_inputs)
        return output, next_states, next_inputs, next_states.finished

    def finalize(self, outputs, final_states, sequence_lengths):
        # outputs.*: [T, B, W] stacked; backtrace with gather_tree
        preds = gather_tree(outputs.predicted_ids, outputs.parent_ids)
        return self.OutputWrapper(outputs.scores, preds,
                                  outputs.parent_ids), final_states

    @property
    def tracks_own_finished(self):
        return True


def dynamic_decode(decoder, inits=None, max_step_num=None,
                   output_time_major=False, impute_finished=False,
                   is_test=False, return_length=False, **kwargs):
    """Run ``decoder`` until all beams finish or ``max_step_num`` steps
    (reference decode.py:674 imperative semantics)."""
    inputs, states, finished = decoder.initialize(inits)
    step_outputs_acc = None
    time = 0
    seq_len = None
    while True:
        outputs, next_states, inputs, finished = decoder.step(
            time, inputs, states, **kwargs)
        if seq_len is None:
            seq_len = getattr(next_states, "lengths", None)
        else:
            seq_len = getattr(next_states, "lengths", seq_len)
        if step_outputs_acc is None:
            step_outputs_acc = _map_structure(lambda t: _Acc([t]), outputs)
        else:
            _map_structure(lambda acc, t: acc.append(t),
                           step_outputs_acc, outputs)
        states = next_states
        time += 1
        fin = np.asarray(unwrap(finished))
        if fin.all() or (max_step_num is not None and time > max_step_num):
            break
    stacked = _map_structure(
        lambda acc: wrap(jnp.stack([unwrap(t) for t in acc], axis=0)),
        step_outputs_acc)
    final_outputs, final_states = decoder.finalize(stacked, states, seq_len)
    if not output_time_major:
        final_outputs = _map_structure(
            lambda t: wrap(jnp.swapaxes(unwrap(t), 0, 1)), final_outputs)
    if return_length:
        return final_outputs, final_states, seq_len
    return final_outputs, final_states
