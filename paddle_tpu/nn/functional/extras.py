"""Functional long tail: unpooling, fractional pooling, sequence losses
(CTC / RNN-T), hierarchical sigmoid, margin losses, beam-search utilities,
sparse attention, temporal shift.

Reference capability: python/paddle/nn/functional/loss.py (ctc_loss:1835,
rnnt_loss:1983, hsigmoid_loss:886, multi_margin_loss:3902,
triplet_margin_with_distance_loss:3616, margin_cross_entropy:2110),
functional/extension.py (sequence_mask/gather_tree/temporal_shift),
functional/sparse_attention.py, functional/common.py class_center_sample,
phi/kernels/funcs/pooling.h (fractional index math, unpool scatter).

TPU-native design notes:
- CTC and RNN-T are lax.scan dynamic programs in the log semiring; the
  RNN-T inner (label-axis) recurrence is solved in closed form with
  cumlogsumexp, so each scan step is a vectorised row update (no O(U)
  sequential inner loop — the wavefront rides the VPU).
- Fractional pooling boundaries depend only on static shapes and the host
  random u, so patch gathers stay static-shaped for XLA.
- sparse_attention keeps the reference's CSR layout at the API and
  materialises the mask densely — on TPU the dense masked softmax is the
  fast path (MXU) for the sizes this API targets; block-sparse long-context
  runs ride kernels/ring_attention and varlen flash instead.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ...ops._op import op_fn, unwrap, wrap
from ...core import enforce as E

__all__ = [
    "max_unpool1d", "max_unpool2d", "max_unpool3d",
    "fractional_max_pool2d", "fractional_max_pool3d",
    "multi_margin_loss", "triplet_margin_with_distance_loss",
    "hsigmoid_loss", "pairwise_distance", "sequence_mask", "temporal_shift",
    "class_center_sample", "margin_cross_entropy", "gather_tree",
    "sparse_attention", "ctc_loss", "rnnt_loss",
]


def _reduce(loss, reduction):
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


# ---------------------------------------------------------------------------
# unpooling (reference: phi/kernels/funcs/unpooling.h — scatter by mask)
# ---------------------------------------------------------------------------

def _unpool(x, indices, nsp, kernel_size, stride, padding, output_size,
            data_format):
    if data_format not in ("NCL", "NCHW", "NCDHW"):
        raise E.InvalidArgumentError(f"max_unpool: unsupported data_format {data_format}")
    k = (kernel_size,) * nsp if isinstance(kernel_size, int) else tuple(kernel_size)
    s = k if stride is None else (
        (stride,) * nsp if isinstance(stride, int) else tuple(stride))
    p = (padding,) * nsp if isinstance(padding, int) else tuple(padding)
    spatial = x.shape[2:]
    if output_size is None:
        out_sp = tuple((spatial[i] - 1) * s[i] - 2 * p[i] + k[i]
                       for i in range(nsp))
    else:
        out_sp = tuple(output_size[-nsp:])
    n, c = x.shape[:2]
    flat = int(np.prod(out_sp))
    xf = x.reshape(n, c, -1)
    idx = indices.reshape(n, c, -1).astype(jnp.int32)
    out = jnp.zeros((n, c, flat), x.dtype)
    out = out.at[jnp.arange(n)[:, None, None],
                 jnp.arange(c)[None, :, None], idx].set(xf)
    return out.reshape((n, c) + out_sp)


@op_fn(nondiff_args=(1,))
def _unpool_op(x, indices, *, nsp, kernel_size, stride, padding,
               output_size, data_format):
    return _unpool(x, indices, nsp, kernel_size, stride, padding,
                   output_size, data_format)


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
    return _unpool_op(x, indices, nsp=1, kernel_size=kernel_size,
                      stride=stride, padding=padding,
                      output_size=output_size, data_format=data_format)


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
    return _unpool_op(x, indices, nsp=2, kernel_size=kernel_size,
                      stride=stride, padding=padding,
                      output_size=output_size, data_format=data_format)


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
    return _unpool_op(x, indices, nsp=3, kernel_size=kernel_size,
                      stride=stride, padding=padding,
                      output_size=output_size, data_format=data_format)


# ---------------------------------------------------------------------------
# fractional max pooling (reference: pooling.h FractionalStartIndex/EndIndex)
# ---------------------------------------------------------------------------

def _fractional_bounds(inp, out, ksize, u):
    """Host-side window bounds per output index (reference pooling.h:106-139
    math, identically)."""
    alpha = inp / out
    if not ksize:
        base = inp // out
        u_max1 = (base + 2) / alpha - 1
        u_max2 = (inp + 1 - base) / alpha - (out - 1)
        u = u * min(u_max1, u_max2)
    idx = np.arange(out)
    start = ((idx + u) * alpha).astype(np.int64) - int(u * alpha)
    if ksize:
        end = start + ksize
    else:
        end = ((idx + 1 + u) * alpha).astype(np.int64) - int(u * alpha)
    start = np.clip(start, 0, inp - 1)
    end = np.clip(end, 1, inp)
    return start, end


def _fractional_pool(x, nsp, output_size, kernel_size, random_u, return_mask,
                     data_format):
    if data_format not in ("NCHW", "NCDHW"):
        raise E.InvalidArgumentError(f"fractional pool: bad data_format {data_format}")
    spatial = unwrap(x).shape[2:]
    osz = ((output_size,) * nsp if isinstance(output_size, int)
           else tuple(output_size))
    ksz = ((None,) * nsp if kernel_size is None else
           ((kernel_size,) * nsp if isinstance(kernel_size, int)
            else tuple(kernel_size)))
    if random_u is None:
        random_u = float(np.random.default_rng().uniform(0.01, 0.99))
    u = float(random_u)
    starts, lens = [], []
    for d in range(nsp):
        st, en = _fractional_bounds(spatial[d], osz[d], ksz[d], u)
        starts.append(tuple(int(v) for v in st))
        lens.append(tuple(int(v) for v in en - st))
    return _fractional_pool_op(x, nsp=nsp, osz=osz, starts=tuple(starts),
                               lens=tuple(lens), return_mask=return_mask)


@op_fn
def _fractional_pool_op(x, *, nsp, osz, starts, lens, return_mask):
    n, c = x.shape[:2]
    spatial = x.shape[2:]
    wmax = [max(ln) for ln in lens]
    # gather window patches per dim: result [..., o_d, w_d]
    neg = (-jnp.inf if jnp.issubdtype(x.dtype, jnp.floating)
           else jnp.iinfo(x.dtype).min)
    patches = x
    for d in range(nsp):
        ax = 2 + d            # current dim position (before windows appended)
        pos = (jnp.asarray(starts[d])[:, None]
               + jnp.arange(wmax[d])[None, :])           # [o, w]
        valid = jnp.arange(wmax[d])[None, :] < jnp.asarray(lens[d])[:, None]
        pos_c = jnp.clip(pos, 0, spatial[d] - 1)
        patches = jnp.take(patches, pos_c.reshape(-1), axis=ax)
        new_shape = (patches.shape[:ax] + (osz[d], wmax[d])
                     + patches.shape[ax + 1:])
        patches = patches.reshape(new_shape)
        # mask invalid window cells, move window axis to the end
        bshape = [1] * patches.ndim
        bshape[ax], bshape[ax + 1] = osz[d], wmax[d]
        patches = jnp.where(valid.reshape(bshape), patches, neg)
        patches = jnp.moveaxis(patches, ax + 1, -1)
    # patches: [N, C, o1..onsp, w1..wnsp]
    wdims = tuple(range(patches.ndim - nsp, patches.ndim))
    out = jnp.max(patches, axis=wdims)
    if not return_mask:
        return out
    flat_w = patches.reshape(patches.shape[:-nsp] + (-1,))
    am = jnp.argmax(flat_w, axis=-1)                     # [N, C, o1..onsp]
    # decode patch-local argmax into the global flat spatial index
    coords = []
    rem = am
    for d in reversed(range(nsp)):
        coords.insert(0, rem % wmax[d])
        rem = rem // wmax[d]
    flat_idx = jnp.zeros_like(am)
    for d in range(nsp):
        st = jnp.asarray(starts[d])
        shape = [1] * am.ndim
        shape[2 + d] = osz[d]
        gpos = st.reshape(shape) + coords[d]
        flat_idx = flat_idx * spatial[d] + gpos
    return out, flat_idx.astype(jnp.int32)


def fractional_max_pool2d(x, output_size, kernel_size=None, random_u=None,
                          return_mask=False, name=None):
    return _fractional_pool(x, 2, output_size, kernel_size, random_u,
                            return_mask, "NCHW")


def fractional_max_pool3d(x, output_size, kernel_size=None, random_u=None,
                          return_mask=False, name=None):
    return _fractional_pool(x, 3, output_size, kernel_size, random_u,
                            return_mask, "NCDHW")


# ---------------------------------------------------------------------------
# margin losses
# ---------------------------------------------------------------------------

@op_fn(nondiff_args=(1,))
def _multi_margin(input, label, weight=None, *, p=1, margin=1.0,
                  reduction="mean"):
    n, c = input.shape
    target = input[jnp.arange(n), label]                  # [N]
    diff = jnp.maximum(margin - target[:, None] + input, 0.0)
    if p != 1:
        diff = diff ** p
    if weight is not None:
        diff = diff * weight[label][:, None]
    # exclude the true-class term
    diff = diff.at[jnp.arange(n), label].set(0.0)
    return _reduce(jnp.sum(diff, axis=1) / c, reduction)


def multi_margin_loss(input, label, p=1, margin=1.0, weight=None,
                      reduction="mean", name=None):
    return _multi_margin(input, label, weight, p=p, margin=margin,
                         reduction=reduction)


def triplet_margin_with_distance_loss(input, positive, negative,
                                      distance_function=None, margin=1.0,
                                      swap=False, reduction="mean",
                                      name=None):
    from ...ops import maximum, mean, minimum
    from ...ops import sum as t_sum

    dist = distance_function if distance_function is not None \
        else pairwise_distance
    d_pos = dist(input, positive)
    d_neg = dist(input, negative)
    if swap:
        d_neg = minimum(d_neg, dist(positive, negative))
    # taped Tensor arithmetic end to end (a custom distance_function keeps
    # its autograd path)
    loss = maximum(d_pos - d_neg + margin, wrap(jnp.zeros((), jnp.float32)))
    if reduction == "mean":
        return mean(loss)
    if reduction == "sum":
        return t_sum(loss)
    return loss


@op_fn
def _pairwise_distance(x, y, *, p=2.0, epsilon=1e-6, keepdim=False):
    d = x - y + epsilon
    return jnp.linalg.norm(d, ord=p, axis=-1, keepdims=keepdim)


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False, name=None):
    return _pairwise_distance(x, y, p=float(p), epsilon=epsilon,
                              keepdim=keepdim)


# ---------------------------------------------------------------------------
# hierarchical sigmoid (reference: loss.py:886 + phi MatrixBitCodeFunctor)
# ---------------------------------------------------------------------------

@op_fn(nondiff_args=(1,))
def _hsigmoid(input, label, weight, bias=None, path_table=None,
              path_code=None, *, num_classes):
    if path_table is None:
        # default complete binary tree (reference SimpleCode): for class c,
        # code = c + num_classes; internal node at step j is
        # (code >> (L - j)) - 1, branch bit is (code >> (L - 1 - j)) & 1
        code = label + num_classes
        max_len = int(np.ceil(np.log2(num_classes))) + 1
        length = jnp.floor(jnp.log2(code.astype(jnp.float32))).astype(jnp.int32)
        j = jnp.arange(max_len)
        shift_idx = jnp.maximum(length[:, None] - j[None, :], 0)
        shift_bit = jnp.maximum(length[:, None] - 1 - j[None, :], 0)
        node = (code[:, None] >> shift_idx) - 1             # [N, L]
        bit = (code[:, None] >> shift_bit) & 1
        valid = j[None, :] < length[:, None]
    else:
        node = path_table
        bit = path_code
        valid = node >= 0
    node_c = jnp.clip(node, 0, weight.shape[0] - 1)
    w = weight[node_c]                                      # [N, L, D]
    score = jnp.einsum("nd,nld->nl", input, w)
    if bias is not None:
        score = score + bias.reshape(-1)[node_c]
    t = bit.astype(score.dtype)
    # BCE-with-logits per tree edge: softplus(s) - t*s
    per_edge = jnp.where(valid, jax.nn.softplus(score) - t * score, 0.0)
    return jnp.sum(per_edge, axis=1, keepdims=True)


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    return _hsigmoid(input, label, weight, bias, path_table, path_code,
                     num_classes=int(num_classes))


# ---------------------------------------------------------------------------
# sequence utilities
# ---------------------------------------------------------------------------

def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    from ...core.dtype import convert_dtype

    xa = unwrap(x)
    if maxlen is None:
        from ...core import is_tracer
        if is_tracer(xa):
            raise E.InvalidArgumentError(
                "sequence_mask(maxlen=None) must read the max length from "
                "the data, which is impossible under jit/to_static tracing "
                "(data-dependent output shape). Pass an explicit maxlen, "
                "or call it eagerly.")
        maxlen = int(jnp.max(xa))
    mask = jnp.arange(maxlen) < xa[..., None]
    return wrap(mask.astype(convert_dtype(dtype)))


@op_fn
def _temporal_shift(x, *, seg_num, shift_ratio, data_format):
    if data_format == "NHWC":
        x = jnp.transpose(x, (0, 3, 1, 2))
    nt, c, h, w = x.shape
    n = nt // seg_num
    xr = x.reshape(n, seg_num, c, h, w)
    fold = int(c * shift_ratio)
    # slide fold channels backward in time, next fold forward, rest stay
    back = jnp.concatenate([xr[:, 1:, :fold], jnp.zeros_like(xr[:, :1, :fold])],
                           axis=1)
    fwd = jnp.concatenate([jnp.zeros_like(xr[:, :1, fold:2 * fold]),
                           xr[:, :-1, fold:2 * fold]], axis=1)
    out = jnp.concatenate([back, fwd, xr[:, :, 2 * fold:]], axis=2)
    out = out.reshape(nt, c, h, w)
    if data_format == "NHWC":
        out = jnp.transpose(out, (0, 2, 3, 1))
    return out


def temporal_shift(x, seg_num, shift_ratio=0.25, name=None,
                   data_format="NCHW"):
    if data_format not in ("NCHW", "NHWC"):
        raise E.InvalidArgumentError(f"temporal_shift: bad data_format {data_format}")
    return _temporal_shift(x, seg_num=int(seg_num),
                           shift_ratio=float(shift_ratio),
                           data_format=data_format)


def gather_tree(ids, parents):
    """Beam-search backtrace (reference: extension.py:131, phi gather_tree
    kernel). ids/parents: [max_time, batch, beam]."""

    ia = unwrap(ids)
    pa = unwrap(parents)
    t_max, batch, beam = ia.shape
    binit = jnp.broadcast_to(jnp.arange(beam), (batch, beam))

    def step(carry_beam, xs):
        step_ids, step_parents = xs
        out = jnp.take_along_axis(step_ids, carry_beam, axis=1)
        next_beam = jnp.take_along_axis(step_parents, carry_beam, axis=1)
        return next_beam, out

    _, outs = lax.scan(step, binit, (ia[::-1], pa[::-1]))
    return wrap(outs[::-1])


# ---------------------------------------------------------------------------
# class-center sampling + margin softmax (reference: common.py:2104,
# loss.py:2110 — the PartialFC / ArcFace training pair)
# ---------------------------------------------------------------------------

def class_center_sample(label, num_classes, num_samples, group=None):
    """Sample class centers: all positives plus random negatives up to
    ``num_samples``. Eager/host op (the sampled set is data-dependent by
    design; the reference kernel is host-driven too)."""
    la = np.asarray(unwrap(label))
    pos = np.unique(la)
    if len(pos) >= num_samples:
        sampled = pos
    else:
        neg_pool = np.setdiff1d(np.arange(num_classes), pos,
                                assume_unique=True)
        extra = np.random.default_rng().choice(
            neg_pool, size=num_samples - len(pos), replace=False)
        sampled = np.concatenate([pos, np.sort(extra)])
    remap = np.full(num_classes, -1, np.int64)
    remap[sampled] = np.arange(len(sampled))
    return (wrap(jnp.asarray(remap[la])),
            wrap(jnp.asarray(sampled.astype(np.int64))))


@op_fn(nondiff_args=(1,))
def _margin_ce(logits, label, *, margin1, margin2, margin3, scale,
               return_softmax, reduction):
    n = logits.shape[0]
    cos = jnp.clip(logits[jnp.arange(n), label], -1.0, 1.0)
    theta = jnp.arccos(cos)
    target = jnp.cos(margin1 * theta + margin2) - margin3
    mod = logits.at[jnp.arange(n), label].set(target)
    mod = mod * scale
    logp = jax.nn.log_softmax(mod, axis=-1)
    loss = -logp[jnp.arange(n), label][:, None]
    if reduction is not None:
        loss = _reduce(loss, reduction)
    if return_softmax:
        return loss, jnp.exp(logp)
    return loss


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5,
                         margin3=0.0, scale=64.0, group=None,
                         return_softmax=False, reduction="mean"):
    return _margin_ce(logits, label, margin1=float(margin1),
                      margin2=float(margin2), margin3=float(margin3),
                      scale=float(scale), return_softmax=bool(return_softmax),
                      reduction=reduction)


# ---------------------------------------------------------------------------
# sparse attention (reference: functional/sparse_attention.py — CSR layout)
# ---------------------------------------------------------------------------

@op_fn(nondiff_args=(3, 4))
def _sparse_attention(query, key, value, offset, columns,
                      key_padding_mask=None, attn_mask=None):
    b, h, s, d = query.shape
    scores = jnp.einsum("bhqd,bhkd->bhqk", query, key) / jnp.sqrt(
        jnp.asarray(d, query.dtype))
    # CSR (offset [B,H,S+1], columns [B,H,nnz]) -> dense allowed mask
    def one(off, cols):
        rows = jnp.searchsorted(off[1:], jnp.arange(cols.shape[0]),
                                side="right")
        m = jnp.zeros((s, s), bool).at[rows, cols].set(True)
        return m
    mask = jax.vmap(jax.vmap(one))(offset, columns)   # [B,H,S,S]
    neg = jnp.asarray(-1e9, scores.dtype)
    scores = jnp.where(mask, scores, neg)
    if key_padding_mask is not None:
        scores = jnp.where(key_padding_mask[:, None, None, :] != 0,
                           scores, neg)
    if attn_mask is not None:
        scores = jnp.where(attn_mask[None, None] != 0, scores, neg)
    w = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", w, value)


def sparse_attention(query, key, value, sparse_csr_offset,
                     sparse_csr_columns, key_padding_mask=None,
                     attn_mask=None, name=None):
    return _sparse_attention(query, key, value, sparse_csr_offset,
                             sparse_csr_columns, key_padding_mask, attn_mask)


# ---------------------------------------------------------------------------
# CTC loss (reference: loss.py:1835 / warpctc) — log-semiring lax.scan
# ---------------------------------------------------------------------------

@op_fn(nondiff_args=(1, 2, 3))
def _ctc_loss(log_probs, labels, input_lengths, label_lengths, *, blank,
              norm_by_times, reduction):
    lp = jax.nn.log_softmax(log_probs.astype(jnp.float32), axis=-1)
    t_max, n, _ = lp.shape
    s_max = labels.shape[1]
    neg_inf = jnp.asarray(-1e30, jnp.float32)

    # extended sequence with interleaved blanks: z [N, 2S+1]
    ext = jnp.full((n, 2 * s_max + 1), blank, labels.dtype)
    ext = ext.at[:, 1::2].set(labels)
    ez = 2 * s_max + 1
    # allowed skip: z[s] != blank and z[s] != z[s-2]
    zshift = jnp.concatenate([jnp.full((n, 2), blank, labels.dtype),
                              ext[:, :-2]], axis=1)
    can_skip = (ext != blank) & (ext != zshift)

    emit = jnp.take_along_axis(
        lp.transpose(1, 0, 2),                     # [N, T, C]
        jnp.broadcast_to(ext[:, None, :], (n, t_max, ez)), axis=2)

    a0 = jnp.full((n, ez), neg_inf)
    a0 = a0.at[:, 0].set(emit[:, 0, 0])
    a0 = a0.at[:, 1].set(jnp.where(s_max > 0, emit[:, 0, 1], neg_inf))

    def step(alpha, t):
        prev1 = jnp.concatenate([jnp.full((n, 1), neg_inf), alpha[:, :-1]],
                                axis=1)
        prev2 = jnp.concatenate([jnp.full((n, 2), neg_inf), alpha[:, :-2]],
                                axis=1)
        prev2 = jnp.where(can_skip, prev2, neg_inf)
        merged = jnp.logaddexp(jnp.logaddexp(alpha, prev1), prev2)
        new = merged + emit[:, t]
        # freeze rows past their input length
        new = jnp.where((t < input_lengths)[:, None], new, alpha)
        return new, None

    alpha, _ = lax.scan(step, a0, jnp.arange(1, t_max))
    # final: logaddexp of positions 2L and 2L-1
    l2 = 2 * label_lengths
    last = jnp.take_along_axis(alpha, l2[:, None], axis=1)[:, 0]
    last1 = jnp.take_along_axis(alpha, jnp.maximum(l2 - 1, 0)[:, None],
                                axis=1)[:, 0]
    last1 = jnp.where(label_lengths > 0, last1, neg_inf)
    nll = -jnp.logaddexp(last, last1)
    if norm_by_times:
        nll = nll / input_lengths.astype(nll.dtype)
    if reduction == "mean":
        # warpctc convention: per-sample loss / label_length, then mean
        return jnp.mean(nll / jnp.maximum(
            label_lengths.astype(nll.dtype), 1.0))
    if reduction == "sum":
        return jnp.sum(nll)
    return nll


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    return _ctc_loss(log_probs, labels, input_lengths, label_lengths,
                     blank=int(blank), norm_by_times=bool(norm_by_times),
                     reduction=reduction)


# ---------------------------------------------------------------------------
# RNN-T loss (reference: loss.py:1983 / warp-transducer)
# ---------------------------------------------------------------------------

@op_fn(nondiff_args=(1, 2, 3))
def _rnnt_loss(input, label, input_lengths, label_lengths, *, blank,
               reduction):
    lp = jax.nn.log_softmax(input.astype(jnp.float32), axis=-1)
    b, t_max, u1, _ = lp.shape
    u_max = u1 - 1
    neg_inf = jnp.asarray(-1e30, jnp.float32)

    blank_lp = lp[..., blank]                        # [B, T, U+1]
    lab_lp = jnp.take_along_axis(
        lp[:, :, :u_max, :],
        jnp.broadcast_to(label[:, None, :, None].astype(jnp.int32),
                         (b, t_max, u_max, 1)), axis=3)[..., 0]  # [B,T,U]
    # mask label positions beyond the label length
    uvalid = jnp.arange(u_max)[None, :] < label_lengths[:, None]
    lab_lp = jnp.where(uvalid[:, None, :], lab_lp, neg_inf)

    # alpha rows via closed-form inner recurrence:
    # alpha_t[u] = logaddexp(c[u], alpha_t[u-1] + l[u-1])
    #            = L[u] + logcumsumexp(c - L)[u],  L = exclusive cumsum of l
    def row_solve(c, l):
        big_l = jnp.concatenate(
            [jnp.zeros((b, 1), jnp.float32), jnp.cumsum(l, axis=1)], axis=1)
        z = jnp.maximum(c - big_l, -1e30)   # keep -inf arithmetic finite
        return big_l + lax.cumlogsumexp(z, axis=1)

    a0 = row_solve(jnp.concatenate(
        [jnp.zeros((b, 1), jnp.float32),
         jnp.full((b, u_max), neg_inf)], axis=1), lab_lp[:, 0])

    def step(alpha, t):
        c = alpha + blank_lp[:, t - 1]               # emit blank from t-1
        new = row_solve(c, lab_lp[:, t])
        new = jnp.where((t < input_lengths)[:, None], new, alpha)
        return new, None

    alpha, _ = lax.scan(step, a0, jnp.arange(1, t_max))
    # loss = -(alpha[T-1, U] + blank[T-1, U])
    ti = jnp.maximum(input_lengths - 1, 0)
    final_a = jnp.take_along_axis(
        alpha, label_lengths[:, None], axis=1)[:, 0]
    final_b = jnp.take_along_axis(
        jnp.take_along_axis(blank_lp, ti[:, None, None], axis=1)[:, 0],
        label_lengths[:, None], axis=1)[:, 0]
    return _reduce(-(final_a + final_b), reduction)


def rnnt_loss(input, label, input_lengths, label_lengths, blank=0,
              fastemit_lambda=0.001, reduction="mean", name=None):
    """Transducer loss. ``fastemit_lambda`` is accepted for signature
    parity; the FastEmit regularizer reweights gradients inside the
    warp-transducer backward and does not change the NLL value computed
    here (loss-value parity holds at lambda=0 semantics)."""
    return _rnnt_loss(input, label, input_lengths, label_lengths,
                      blank=int(blank), reduction=reduction)
