"""Loss functionals.

Reference surface: python/paddle/nn/functional/loss.py. All pure JAX;
cross_entropy follows paddle semantics (softmax+NLL fused by default,
ignore_index, weight, soft labels, label smoothing).
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ...ops._op import op_fn

__all__ = [
    "cross_entropy", "softmax_with_cross_entropy", "binary_cross_entropy",
    "binary_cross_entropy_with_logits", "mse_loss", "l1_loss", "nll_loss",
    "kl_div", "smooth_l1_loss", "margin_ranking_loss", "hinge_embedding_loss",
    "cosine_embedding_loss", "triplet_margin_loss", "log_loss", "square_error_cost",
    "sigmoid_focal_loss", "dice_loss", "npair_loss", "poisson_nll_loss",
    "multi_label_soft_margin_loss", "soft_margin_loss", "gaussian_nll_loss",
]


def _reduce(loss, reduction):
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


@op_fn(nondiff_args=(1,))
def cross_entropy(input, label, weight=None, *, ignore_index: int = -100,
                  reduction: str = "mean", soft_label: bool = False,
                  axis: int = -1, use_softmax: bool = True,
                  label_smoothing: float = 0.0):
    """paddle.nn.functional.cross_entropy parity
    (reference loss.py cross_entropy)."""
    if use_softmax:
        logp = jax.nn.log_softmax(input.astype(jnp.float32), axis=axis)
    else:
        logp = jnp.log(jnp.maximum(input.astype(jnp.float32), 1e-30))
    nclass = input.shape[axis]

    if soft_label or (hasattr(label, "ndim") and label.ndim == input.ndim
                      and label.shape == input.shape
                      and jnp.issubdtype(label.dtype, jnp.floating)):
        soft = label.astype(jnp.float32)
        if label_smoothing > 0.0:
            soft = (1 - label_smoothing) * soft + label_smoothing / nclass
        loss = -jnp.sum(soft * logp, axis=axis)
        if weight is not None:
            w = jnp.sum(soft * weight.reshape(
                (1,) * (input.ndim - 1) + (-1,)), axis=axis)
            loss = loss * w
            if reduction == "mean":
                return jnp.sum(loss) / jnp.sum(w)
        return _reduce(loss, reduction)

    lbl = label
    if lbl.ndim == input.ndim:  # trailing singleton label dim
        lbl = jnp.squeeze(lbl, axis=axis)
    lbl = lbl.astype(jnp.int32)
    valid = lbl != ignore_index
    safe = jnp.where(valid, lbl, 0)
    picked = jnp.take_along_axis(
        logp, jnp.expand_dims(safe, axis), axis=axis)
    picked = jnp.squeeze(picked, axis=axis)
    if label_smoothing > 0.0:
        smooth_term = jnp.mean(logp, axis=axis)
        picked = (1 - label_smoothing) * picked + label_smoothing * smooth_term
    loss = jnp.where(valid, -picked, 0.0)
    if weight is not None:
        w = jnp.where(valid, jnp.take(weight, safe), 0.0)
        loss = loss * w
        if reduction == "mean":
            return jnp.sum(loss) / jnp.maximum(jnp.sum(w), 1e-12)
    if reduction == "mean":
        denom = jnp.maximum(jnp.sum(valid.astype(jnp.float32)), 1.0)
        return jnp.sum(loss) / denom
    return _reduce(loss, reduction)


@op_fn(nondiff_args=(1,))
def softmax_with_cross_entropy(logits, label, *, soft_label: bool = False,
                               ignore_index: int = -100, axis: int = -1,
                               return_softmax: bool = False):
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=axis)
    if soft_label:
        loss = -jnp.sum(label.astype(jnp.float32) * logp, axis=axis,
                        keepdims=True)
    else:
        lbl = label
        if lbl.ndim == logits.ndim:
            lbl = jnp.squeeze(lbl, axis=axis)
        lbl = lbl.astype(jnp.int32)
        valid = lbl != ignore_index
        safe = jnp.where(valid, lbl, 0)
        picked = jnp.squeeze(jnp.take_along_axis(
            logp, jnp.expand_dims(safe, axis), axis=axis), axis=axis)
        loss = jnp.expand_dims(jnp.where(valid, -picked, 0.0), axis)
    if return_softmax:
        return loss, jnp.exp(logp).astype(logits.dtype)
    return loss


@op_fn(nondiff_args=(1,))
def binary_cross_entropy(input, label, weight=None, *,
                         reduction: str = "mean"):
    x = jnp.clip(input.astype(jnp.float32), 1e-12, 1.0 - 1e-7)
    lbl = label.astype(jnp.float32)
    loss = -(lbl * jnp.log(x) + (1 - lbl) * jnp.log1p(-x))
    if weight is not None:
        loss = loss * weight
    return _reduce(loss, reduction)


@op_fn(nondiff_args=(1,))
def binary_cross_entropy_with_logits(logit, label, weight=None, *,
                                     reduction: str = "mean",
                                     pos_weight=None):
    z = logit.astype(jnp.float32)
    lbl = label.astype(jnp.float32)
    # numerically stable: max(z,0) - z*y + log(1+exp(-|z|))
    loss = jnp.maximum(z, 0) - z * lbl + jnp.log1p(jnp.exp(-jnp.abs(z)))
    if pos_weight is not None:
        log_w = (pos_weight - 1) * lbl + 1
        loss = loss * log_w
    if weight is not None:
        loss = loss * weight
    return _reduce(loss, reduction)


@op_fn(nondiff_args=(1,))
def mse_loss(input, label, *, reduction: str = "mean"):
    return _reduce(jnp.square(input - label), reduction)


@op_fn(nondiff_args=(1,))
def l1_loss(input, label, *, reduction: str = "mean"):
    return _reduce(jnp.abs(input - label), reduction)


@op_fn(nondiff_args=(1,))
def square_error_cost(input, label):
    return jnp.square(input - label)


@op_fn(nondiff_args=(1,))
def log_loss(input, label, *, epsilon: float = 1e-4):
    x = input.astype(jnp.float32)
    lbl = label.astype(jnp.float32)
    return -lbl * jnp.log(x + epsilon) - (1 - lbl) * jnp.log1p(epsilon - x + 1e-30)


@op_fn(nondiff_args=(1,))
def nll_loss(input, label, weight=None, *, ignore_index: int = -100,
             reduction: str = "mean"):
    lbl = label.astype(jnp.int32)
    valid = lbl != ignore_index
    safe = jnp.where(valid, lbl, 0)
    picked = jnp.take_along_axis(input, jnp.expand_dims(safe, 1), axis=1)
    picked = jnp.squeeze(picked, axis=1)
    loss = jnp.where(valid, -picked, 0.0)
    if weight is not None:
        w = jnp.where(valid, jnp.take(weight, safe), 0.0)
        loss = loss * w
        if reduction == "mean":
            return jnp.sum(loss) / jnp.maximum(jnp.sum(w), 1e-12)
    if reduction == "mean":
        denom = jnp.maximum(jnp.sum(valid.astype(jnp.float32)), 1.0)
        return jnp.sum(loss) / denom
    return _reduce(loss, reduction)


@op_fn(nondiff_args=(1,))
def kl_div(input, label, *, reduction: str = "mean", log_target: bool = False):
    lbl = label.astype(jnp.float32)
    if log_target:
        loss = jnp.exp(lbl) * (lbl - input)
    else:
        loss = jnp.where(lbl > 0, lbl * (jnp.log(jnp.maximum(lbl, 1e-30))
                                         - input), 0.0)
    if reduction == "batchmean":
        return jnp.sum(loss) / input.shape[0]
    return _reduce(loss, reduction)


@op_fn(nondiff_args=(1,))
def smooth_l1_loss(input, label, *, reduction: str = "mean",
                   delta: float = 1.0):
    d = jnp.abs(input - label)
    loss = jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta)
    return _reduce(loss, reduction)


@op_fn(nondiff_args=(2,))
def margin_ranking_loss(input, other, label, *, margin: float = 0.0,
                        reduction: str = "mean"):
    loss = jnp.maximum(-label * (input - other) + margin, 0.0)
    return _reduce(loss, reduction)


@op_fn(nondiff_args=(1,))
def hinge_embedding_loss(input, label, *, margin: float = 1.0,
                         reduction: str = "mean"):
    loss = jnp.where(label == 1, input, jnp.maximum(margin - input, 0.0))
    return _reduce(loss, reduction)


@op_fn(nondiff_args=(2,))
def cosine_embedding_loss(input1, input2, label, *, margin: float = 0.0,
                          reduction: str = "mean"):
    cos = jnp.sum(input1 * input2, axis=-1) / jnp.maximum(
        jnp.linalg.norm(input1, axis=-1) * jnp.linalg.norm(input2, axis=-1),
        1e-12)
    loss = jnp.where(label == 1, 1 - cos, jnp.maximum(cos - margin, 0.0))
    return _reduce(loss, reduction)


@op_fn
def triplet_margin_loss(input, positive, negative, *, margin: float = 1.0,
                        p: float = 2.0, epsilon: float = 1e-6,
                        swap: bool = False, reduction: str = "mean"):
    def dist(a, b):
        return jnp.power(jnp.sum(jnp.power(jnp.abs(a - b) + epsilon, p),
                                 axis=-1), 1.0 / p)
    d_pos = dist(input, positive)
    d_neg = dist(input, negative)
    if swap:
        d_neg = jnp.minimum(d_neg, dist(positive, negative))
    return _reduce(jnp.maximum(d_pos - d_neg + margin, 0.0), reduction)


@op_fn(nondiff_args=(1,))
def sigmoid_focal_loss(logit, label, normalizer=None, *, alpha: float = 0.25,
                       gamma: float = 2.0, reduction: str = "sum"):
    z = logit.astype(jnp.float32)
    lbl = label.astype(jnp.float32)
    p = jax.nn.sigmoid(z)
    ce = jnp.maximum(z, 0) - z * lbl + jnp.log1p(jnp.exp(-jnp.abs(z)))
    p_t = p * lbl + (1 - p) * (1 - lbl)
    a_t = alpha * lbl + (1 - alpha) * (1 - lbl)
    loss = a_t * jnp.power(1 - p_t, gamma) * ce
    if normalizer is not None:
        loss = loss / normalizer
    return _reduce(loss, reduction)


@op_fn(nondiff_args=(1,))
def dice_loss(input, label, *, epsilon: float = 1e-5):
    lbl = jax.nn.one_hot(jnp.squeeze(label, -1), input.shape[-1],
                         dtype=input.dtype)
    reduce_axes = tuple(range(1, input.ndim))
    inter = jnp.sum(input * lbl, axis=reduce_axes)
    union = jnp.sum(input, axis=reduce_axes) + jnp.sum(lbl, axis=reduce_axes)
    dice = (2 * inter + epsilon) / (union + epsilon)
    return jnp.mean(1 - dice)


@op_fn(nondiff_args=(2,))
def npair_loss(anchor, positive, labels, *, l2_reg: float = 0.002):
    batch = anchor.shape[0]
    lbl = labels.reshape(-1, 1).astype(jnp.float32)
    same = (lbl == lbl.T).astype(jnp.float32)
    same = same / jnp.sum(same, axis=1, keepdims=True)
    sim = anchor @ positive.T
    logp = jax.nn.log_softmax(sim, axis=1)
    ce = -jnp.sum(same * logp, axis=1)
    l2 = l2_reg * (jnp.sum(anchor * anchor) + jnp.sum(positive * positive)) \
        / (2.0 * batch)
    return jnp.mean(ce) + l2


@op_fn(nondiff_args=(1,))
def poisson_nll_loss(input, label, *, log_input: bool = True,
                     full: bool = False, epsilon: float = 1e-8,
                     reduction: str = "mean"):
    if log_input:
        loss = jnp.exp(input) - label * input
    else:
        loss = input - label * jnp.log(input + epsilon)
    if full:
        stirling = label * jnp.log(label + epsilon) - label + \
            0.5 * jnp.log(2 * jnp.pi * (label + epsilon))
        loss = loss + jnp.where(label > 1, stirling, 0.0)
    return _reduce(loss, reduction)


@op_fn(nondiff_args=(1,))
def multi_label_soft_margin_loss(input, label, weight=None, *,
                                 reduction: str = "mean"):
    loss = -(label * jax.nn.log_sigmoid(input) +
             (1 - label) * jax.nn.log_sigmoid(-input))
    loss = jnp.mean(loss, axis=-1)
    if weight is not None:
        loss = loss * weight
    return _reduce(loss, reduction)


@op_fn(nondiff_args=(1,))
def soft_margin_loss(input, label, *, reduction: str = "mean"):
    loss = jnp.log1p(jnp.exp(-label * input))
    return _reduce(loss, reduction)


@op_fn(nondiff_args=(2,))
def gaussian_nll_loss(input, variance, label, *, full: bool = False,
                      epsilon: float = 1e-6, reduction: str = "mean"):
    var = jnp.maximum(variance, epsilon)
    loss = 0.5 * (jnp.log(var) + jnp.square(input - label) / var)
    if full:
        loss = loss + 0.5 * jnp.log(2 * jnp.pi)
    return _reduce(loss, reduction)
