"""paddle.nn.functional parity surface (flat namespace).

Reference: python/paddle/nn/functional/__init__.py.
"""
from .activation import *  # noqa: F401,F403
from .common import *  # noqa: F401,F403
from .conv import *  # noqa: F401,F403
from .pooling import *  # noqa: F401,F403
from .norm import *  # noqa: F401,F403
from .loss import *  # noqa: F401,F403
from .attention import *  # noqa: F401,F403
from .vision import *  # noqa: F401,F403
from .extras import *  # noqa: F401,F403

from .activation import __all__ as _a
from .common import __all__ as _c
from .conv import __all__ as _cv
from .pooling import __all__ as _p
from .norm import __all__ as _n
from .loss import __all__ as _l
from .attention import __all__ as _at
from .vision import __all__ as _v
from .extras import __all__ as _x

__all__ = list(_a) + list(_c) + list(_cv) + list(_p) + list(_n) + \
    list(_l) + list(_at) + list(_v) + list(_x)


# diag_embed is also exposed here like the reference functional/__init__
from ...ops.manipulation_ext import diag_embed  # noqa: F401


def pdist(x, p=2.0, name=None):
    """Condensed pairwise distance of an [N, D] matrix: the upper
    triangle of cdist(x, x) flattened to [N*(N-1)/2] (reference:
    nn/functional/distance.py pdist)."""
    import jax.numpy as jnp

    from ...ops._op import op_fn

    @op_fn(name="pdist_op")
    def _pdist(x, *, p):
        n = x.shape[0]
        diff = x[:, None, :] - x[None, :, :]
        if p == 2.0:
            d = jnp.sqrt(jnp.maximum(jnp.sum(diff * diff, -1), 1e-24))
        elif p == float("inf"):
            d = jnp.max(jnp.abs(diff), -1)
        elif p == 0:
            d = jnp.sum((diff != 0).astype(x.dtype), -1)
        else:
            d = jnp.sum(jnp.abs(diff) ** p, -1) ** (1.0 / p)
        iu, ju = jnp.triu_indices(n, k=1)
        return d[iu, ju]

    return _pdist(x, p=float(p))


import contextlib as _ctx


@_ctx.contextmanager
def sdp_kernel(enable_math=True, enable_flash=True,
               enable_mem_efficient=True):
    """Scoped attention-backend selection (reference:
    nn/functional/flash_attention.py sdp_kernel — there it toggles the
    cuDNN/flash backends). Here flash means the Pallas kernel: disabling
    it unregisters the flash dispatcher within the scope."""
    from . import attention as _att
    prev = _att._FLASH_IMPL
    prev_seg = _att._SEGMENT_IMPL
    try:
        if not enable_flash:
            # actually remove the flash dispatcher so the scope runs the
            # XLA/math path (register(flash=False) would merely skip
            # re-installing it); the segment kernel is the same Pallas
            # family, so it toggles with it
            _att.register_flash_impl(None)
            _att.register_segment_impl(None)
        yield
    finally:
        # restore whatever was installed on entry verbatim — a
        # tpu_only=False registration (interpret-mode tests) or a
        # deliberately-unregistered state must survive the scope
        if not enable_flash:
            _att.register_flash_impl(prev)
            _att.register_segment_impl(prev_seg)
