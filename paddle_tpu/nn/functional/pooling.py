"""Pooling functionals over ``lax.reduce_window`` (XLA's native windowed
reduction — maps to the TPU vector unit without custom kernels).

Reference surface: python/paddle/nn/functional/pooling.py.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ...ops._op import op_fn
from ...core import enforce as E

__all__ = [
    "avg_pool1d", "avg_pool2d", "avg_pool3d",
    "max_pool1d", "max_pool2d", "max_pool3d",
    "adaptive_avg_pool1d", "adaptive_avg_pool2d", "adaptive_avg_pool3d",
    "adaptive_max_pool1d", "adaptive_max_pool2d", "adaptive_max_pool3d",
]


def _tuplize(v, n):
    return (v,) * n if isinstance(v, int) else tuple(int(x) for x in v)


def _window(nsp, k, s, data_format):
    if data_format.startswith("NC"):
        dims = (1, 1) + k
        strides = (1, 1) + s
    else:
        dims = (1,) + k + (1,)
        strides = (1,) + s + (1,)
    return dims, strides


def _pad_cfg(padding, nsp, data_format, ndim):
    if isinstance(padding, str):
        return padding.upper()
    p = _tuplize(padding, nsp)
    if len(p) == 2 * nsp:
        pairs = [(p[2 * i], p[2 * i + 1]) for i in range(nsp)]
    else:
        pairs = [(x, x) for x in p]
    full = [(0, 0)] * ndim
    if data_format.startswith("NC"):
        for i in range(nsp):
            full[2 + i] = pairs[i]
    else:
        for i in range(nsp):
            full[1 + i] = pairs[i]
    return full


def _pool(x, nsp, kernel, stride, padding, data_format, kind,
          exclusive=True, ceil_mode=False):
    k = _tuplize(kernel, nsp)
    s = _tuplize(stride if stride is not None else kernel, nsp)
    dims, strides = _window(nsp, k, s, data_format)
    pad = _pad_cfg(padding, nsp, data_format, x.ndim)
    if isinstance(pad, str):
        pad_seq = lax.padtype_to_pads(x.shape, dims, strides, pad)
    else:
        pad_seq = list(pad)
    pad_orig = [tuple(p) for p in pad_seq]
    if ceil_mode:
        # Extend the high-side padding so partially-covered windows are
        # emitted: out = ceil((in + pl + pr - k)/s) + 1 (paddle semantics).
        pad_seq = list(pad_seq)
        for ax in range(x.ndim):
            kk, ss = dims[ax], strides[ax]
            if kk == 1 and ss == 1:
                continue
            pl, pr = pad_seq[ax]
            span = x.shape[ax] + pl + pr - kk
            out_ceil = -(-span // ss) + 1
            needed = (out_ceil - 1) * ss + kk - (x.shape[ax] + pl + pr)
            if needed > 0:
                pad_seq[ax] = (pl, pr + needed)
    if kind == "max":
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else \
            jnp.iinfo(x.dtype).min
        return lax.reduce_window(x, init, lax.max, dims, strides, pad_seq)
    # avg. Divisor semantics (reference pooling kernels): exclusive=True
    # counts only real cells; exclusive=False also counts the user's padding
    # cells but never the ceil_mode extension (pool_size is clipped to
    # input+padding before the window is clamped).
    summed = lax.reduce_window(x, 0.0, lax.add, dims, strides, pad_seq)
    if any(p != (0, 0) for p in pad_seq):
        ones = jnp.ones(x.shape, x.dtype)
        if exclusive:
            counts = lax.reduce_window(ones, 0.0, lax.add, dims, strides,
                                       pad_seq)
        else:
            ones = jnp.pad(ones, pad_orig, constant_values=1)
            pad_ext = [(0, full[1] - orig[1])
                       for full, orig in zip(pad_seq, pad_orig)]
            counts = lax.reduce_window(ones, 0.0, lax.add, dims, strides,
                                       pad_ext)
        return summed / counts
    return summed / float(np.prod(k))


@op_fn
def avg_pool1d(x, *, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format="NCL"):
    return _pool(x, 1, kernel_size, stride, padding, data_format, "avg",
                 exclusive, ceil_mode)


@op_fn
def avg_pool2d(x, *, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format="NCHW"):
    return _pool(x, 2, kernel_size, stride, padding, data_format, "avg",
                 exclusive, ceil_mode)


@op_fn
def avg_pool3d(x, *, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format="NCDHW"):
    return _pool(x, 3, kernel_size, stride, padding, data_format, "avg",
                 exclusive, ceil_mode)


def _max_pool_mask(x, nsp, kernel, stride, padding, ceil_mode, data_format):
    """Max pool + argmax mask (flat input-spatial index per N,C — the
    reference return_mask semantics that max_unpool consumes). Patch
    extraction keeps everything static-shaped for XLA."""
    if not data_format.startswith("NC"):
        raise E.InvalidArgumentError(
            f"return_mask requires channel-first layout, got {data_format}")
    k = _tuplize(kernel, nsp)
    s = _tuplize(stride if stride is not None else kernel, nsp)
    pad = _pad_cfg(padding, nsp, data_format, x.ndim)
    dims, strides = _window(nsp, k, s, data_format)
    if isinstance(pad, str):
        pad_seq = lax.padtype_to_pads(x.shape, dims, strides, pad)
    else:
        pad_seq = list(pad)
    if ceil_mode:
        pad_seq = list(pad_seq)
        for ax in range(x.ndim):
            kk, ss = dims[ax], strides[ax]
            if kk == 1 and ss == 1:
                continue
            pl, pr = pad_seq[ax]
            span = x.shape[ax] + pl + pr - kk
            out_ceil = -(-span // ss) + 1
            needed = (out_ceil - 1) * ss + kk - (x.shape[ax] + pl + pr)
            if needed > 0:
                pad_seq[ax] = (pl, pr + needed)
    neg = (-jnp.inf if jnp.issubdtype(x.dtype, jnp.floating)
           else jnp.iinfo(x.dtype).min)
    n, c = x.shape[:2]
    spatial = x.shape[2:]
    out_sp = tuple(
        (spatial[d] + sum(pad_seq[2 + d]) - k[d]) // s[d] + 1
        for d in range(nsp))
    # window gather per spatial dim (exact arithmetic — no conv/matmul
    # precision involved); invalid (padding) cells masked to -inf
    patches = x
    for d in range(nsp):
        ax = 2 + d
        pos = (jnp.arange(out_sp[d])[:, None] * s[d] - pad_seq[ax][0]
               + jnp.arange(k[d])[None, :])             # [o, k]
        valid = (pos >= 0) & (pos < spatial[d])
        pos_c = jnp.clip(pos, 0, spatial[d] - 1)
        patches = jnp.take(patches, pos_c.reshape(-1), axis=ax)
        patches = patches.reshape(patches.shape[:ax] + (out_sp[d], k[d])
                                  + patches.shape[ax + 1:])
        bshape = [1] * patches.ndim
        bshape[ax], bshape[ax + 1] = out_sp[d], k[d]
        patches = jnp.where(valid.reshape(bshape), patches, neg)
        patches = jnp.moveaxis(patches, ax + 1, -1)
    flatp = patches.reshape((n, c) + out_sp + (int(np.prod(k)),))
    out = jnp.max(flatp, axis=-1)
    am = jnp.argmax(flatp, axis=-1)                  # [N, C, *out_sp]
    # decode: window origin + in-window offset -> flat input index
    flat_idx = jnp.zeros_like(am)
    rem = am
    coords = []
    for d in reversed(range(nsp)):
        coords.insert(0, rem % k[d])
        rem = rem // k[d]
    for d in range(nsp):
        shape = [1] * am.ndim
        shape[2 + d] = out_sp[d]
        origin = (jnp.arange(out_sp[d]) * s[d]
                  - pad_seq[2 + d][0]).reshape(shape)
        gpos = jnp.clip(origin + coords[d], 0, spatial[d] - 1)
        flat_idx = flat_idx * spatial[d] + gpos
    return out, flat_idx.astype(jnp.int32)


@op_fn
def _max_pool_mask_op(x, *, nsp, kernel_size, stride, padding, ceil_mode,
                      data_format):
    return _max_pool_mask(x, nsp, kernel_size, stride, padding, ceil_mode,
                          data_format)


def _max_pool(x, nsp, kernel_size, stride, padding, return_mask, ceil_mode,
              data_format):
    if return_mask:
        return _max_pool_mask_op(x, nsp=nsp, kernel_size=kernel_size,
                                 stride=stride, padding=padding,
                                 ceil_mode=ceil_mode,
                                 data_format=data_format)
    return _max_pool_plain(x, nsp=nsp, kernel_size=kernel_size,
                           stride=stride, padding=padding,
                           ceil_mode=ceil_mode, data_format=data_format)


@op_fn
def _max_pool_plain(x, *, nsp, kernel_size, stride, padding, ceil_mode,
                    data_format):
    return _pool(x, nsp, kernel_size, stride, padding, data_format, "max",
                 ceil_mode=ceil_mode)


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCL", name=None):
    return _max_pool(x, 1, kernel_size, stride, padding, return_mask,
                     ceil_mode, data_format)


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    return _max_pool(x, 2, kernel_size, stride, padding, return_mask,
                     ceil_mode, data_format)


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    return _max_pool(x, 3, kernel_size, stride, padding, return_mask,
                     ceil_mode, data_format)


def _adaptive(x, nsp, output_size, data_format, kind):
    if data_format.startswith("NC"):
        spatial = x.shape[2:2 + nsp]
        sp_axes = list(range(2, 2 + nsp))
    else:
        spatial = x.shape[1:1 + nsp]
        sp_axes = list(range(1, 1 + nsp))
    # reference semantics: None entries keep the input extent
    if isinstance(output_size, (tuple, list)):
        output_size = tuple(
            spatial[i] if output_size[i] is None else output_size[i]
            for i in range(nsp))
    out = _tuplize(output_size, nsp)
    # evenly divisible fast path: reshape + reduce (single XLA reduce).
    if all(spatial[i] % out[i] == 0 for i in range(nsp)):
        shape = list(x.shape)
        new_shape = []
        red_axes = []
        j = 0
        for ax in range(x.ndim):
            if ax in sp_axes:
                i = sp_axes.index(ax)
                new_shape += [out[i], spatial[i] // out[i]]
                red_axes.append(len(new_shape) - 1)
            else:
                new_shape.append(shape[ax])
        xr = x.reshape(new_shape)
        if kind == "avg":
            return jnp.mean(xr, axis=tuple(red_axes))
        return jnp.max(xr, axis=tuple(red_axes))
    # general path: per-output-bin start/end (torch/paddle semantics)
    def pool_axis(arr, axis, in_s, out_s):
        starts = [(i * in_s) // out_s for i in range(out_s)]
        ends = [-(-((i + 1) * in_s) // out_s) for i in range(out_s)]
        pieces = []
        for st, en in zip(starts, ends):
            sl = [slice(None)] * arr.ndim
            sl[axis] = slice(st, en)
            seg = arr[tuple(sl)]
            red = jnp.mean if kind == "avg" else jnp.max
            pieces.append(red(seg, axis=axis, keepdims=True))
        return jnp.concatenate(pieces, axis=axis)
    for i, ax in enumerate(sp_axes):
        x = pool_axis(x, ax, spatial[i], out[i])
    return x


@op_fn
def adaptive_avg_pool1d(x, *, output_size, data_format="NCL"):
    return _adaptive(x, 1, output_size, data_format, "avg")


@op_fn
def adaptive_avg_pool2d(x, *, output_size, data_format="NCHW"):
    return _adaptive(x, 2, output_size, data_format, "avg")


@op_fn
def adaptive_avg_pool3d(x, *, output_size, data_format="NCDHW"):
    return _adaptive(x, 3, output_size, data_format, "avg")


@op_fn
def adaptive_max_pool1d(x, *, output_size, data_format="NCL"):
    return _adaptive(x, 1, output_size, data_format, "max")


@op_fn
def adaptive_max_pool2d(x, *, output_size, data_format="NCHW"):
    return _adaptive(x, 2, output_size, data_format, "max")


@op_fn
def adaptive_max_pool3d(x, *, output_size, data_format="NCDHW"):
    return _adaptive(x, 3, output_size, data_format, "max")
