"""Normalization functionals.

Reference surface: python/paddle/nn/functional/norm.py (+ rms_norm from
python/paddle/incubate/nn/functional/fused_rms_norm.py — on TPU the "fused"
variant IS the default: XLA fuses the reduction+scale into one kernel, and a
Pallas kernel (kernels/) can override for long rows).

Design note: batch_norm's running-stat update is a host-side handle rebind
(the Layer owns the stats); the functional is pure and returns the new stats.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ...core.tensor import Tensor
from ...ops._op import op_fn, unwrap, wrap

__all__ = ["normalize", "layer_norm", "rms_norm", "batch_norm",
           "instance_norm", "group_norm", "local_response_norm"]


@op_fn
def normalize(x, *, p: float = 2, axis: int = 1, epsilon: float = 1e-12):
    if p == 2:
        n = jnp.sqrt(jnp.sum(x * x, axis=axis, keepdims=True))
    else:
        n = jnp.sum(jnp.abs(x) ** p, axis=axis, keepdims=True) ** (1.0 / p)
    return x / jnp.maximum(n, epsilon)


@op_fn
def layer_norm(x, weight=None, bias=None, *, normalized_ndim: int = 1,
               epsilon: float = 1e-5):
    """LayerNorm over the trailing ``normalized_ndim`` dims.

    Stats in float32 regardless of input dtype (bf16-safe on TPU).
    """
    axes = tuple(range(x.ndim - normalized_ndim, x.ndim))
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=axes, keepdims=True)
    y = (xf - mean) * jax.lax.rsqrt(var + epsilon)
    y = y.astype(x.dtype)
    if weight is not None:
        y = y * weight
    if bias is not None:
        y = y + bias
    return y


# Kernel seam (same pattern as attention._FLASH_IMPL): paddle_tpu.kernels
# registers the pallas fused rms_norm here; None = plain XLA path.
_FUSED_RMS_IMPL = None


def register_rms_impl(fn):
    global _FUSED_RMS_IMPL
    _FUSED_RMS_IMPL = fn


@op_fn
def rms_norm(x, weight=None, *, epsilon: float = 1e-6, axis: int = -1):
    """RMSNorm (reference: incubate fused_rms_norm). float32 accumulation."""
    if (_FUSED_RMS_IMPL is not None and weight is not None
            and axis in (-1, x.ndim - 1)):
        return _FUSED_RMS_IMPL(x, weight, epsilon)
    xf = x.astype(jnp.float32)
    ms = jnp.mean(jnp.square(xf), axis=axis, keepdims=True)
    y = (xf * jax.lax.rsqrt(ms + epsilon)).astype(x.dtype)
    if weight is not None:
        y = y * weight
    return y


@op_fn
def _batch_norm_train(x, weight, bias, *, epsilon, data_format, ch_axis):
    axes = tuple(i for i in range(x.ndim) if i != ch_axis)
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=axes)
    var = jnp.mean(jnp.square(xf), axis=axes) - jnp.square(mean)
    shape = [1] * x.ndim
    shape[ch_axis] = x.shape[ch_axis]
    y = (xf - mean.reshape(shape)) * jax.lax.rsqrt(
        var.reshape(shape) + epsilon)
    y = y.astype(x.dtype)
    if weight is not None:
        y = y * weight.reshape(shape)
    if bias is not None:
        y = y + bias.reshape(shape)
    return y, mean, var


@op_fn
def _batch_norm_eval(x, running_mean, running_var, weight, bias, *,
                     epsilon, ch_axis):
    shape = [1] * x.ndim
    shape[ch_axis] = x.shape[ch_axis]
    y = (x.astype(jnp.float32) - running_mean.reshape(shape)) * \
        jax.lax.rsqrt(running_var.reshape(shape) + epsilon)
    y = y.astype(x.dtype)
    if weight is not None:
        y = y * weight.reshape(shape)
    if bias is not None:
        y = y + bias.reshape(shape)
    return y


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training: bool = False, momentum: float = 0.9,
               epsilon: float = 1e-5, data_format: str = "NCHW",
               use_global_stats: Optional[bool] = None, name=None):
    """paddle.nn.functional.batch_norm parity.

    In training mode updates ``running_mean/var`` in place (handle rebind)
    with paddle's momentum convention: r = m*r + (1-m)*batch_stat.
    """
    del name
    ch_axis = 1 if data_format.startswith("NC") and unwrap(x).ndim > 1 else \
        unwrap(x).ndim - 1
    if data_format in ("NLC", "NHWC", "NDHWC"):
        ch_axis = unwrap(x).ndim - 1
    use_batch_stats = training and not use_global_stats
    if use_batch_stats:
        y, mean, var = _batch_norm_train(
            x, weight, bias, epsilon=epsilon, data_format=data_format,
            ch_axis=ch_axis)
        if isinstance(running_mean, Tensor):
            n = 1
            for i, s in enumerate(unwrap(x).shape):
                if i != ch_axis:
                    n *= s
            unbiased = unwrap(var) * (n / max(n - 1, 1))
            running_mean._data = (momentum * running_mean._data +
                                  (1 - momentum) * unwrap(mean))
            running_var._data = (momentum * running_var._data +
                                 (1 - momentum) * unbiased)
        return y
    return _batch_norm_eval(x, running_mean, running_var, weight, bias,
                            epsilon=epsilon, ch_axis=ch_axis)


@op_fn
def instance_norm(x, weight=None, bias=None, *, epsilon: float = 1e-5,
                  data_format: str = "NCHW"):
    if data_format.startswith("NC"):
        ch_axis = 1
        axes = tuple(range(2, x.ndim))
    else:
        ch_axis = x.ndim - 1
        axes = tuple(range(1, x.ndim - 1))
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.var(xf, axis=axes, keepdims=True)
    y = ((xf - mean) * jax.lax.rsqrt(var + epsilon)).astype(x.dtype)
    shape = [1] * x.ndim
    shape[ch_axis] = x.shape[ch_axis]
    if weight is not None:
        y = y * weight.reshape(shape)
    if bias is not None:
        y = y + bias.reshape(shape)
    return y


@op_fn
def group_norm(x, weight=None, bias=None, *, num_groups: int,
               epsilon: float = 1e-5, data_format: str = "NCHW"):
    if data_format.startswith("NC"):
        n, c = x.shape[0], x.shape[1]
        spatial = x.shape[2:]
        xg = x.reshape((n, num_groups, c // num_groups) + spatial)
        axes = tuple(range(2, xg.ndim))
        xf = xg.astype(jnp.float32)
        mean = jnp.mean(xf, axis=axes, keepdims=True)
        var = jnp.var(xf, axis=axes, keepdims=True)
        y = ((xf - mean) * jax.lax.rsqrt(var + epsilon)).astype(x.dtype)
        y = y.reshape(x.shape)
        shape = [1, c] + [1] * len(spatial)
    else:
        n, c = x.shape[0], x.shape[-1]
        spatial = x.shape[1:-1]
        xg = x.reshape((n,) + spatial + (num_groups, c // num_groups))
        axes = tuple(range(1, xg.ndim - 2)) + (xg.ndim - 1,)
        xf = xg.astype(jnp.float32)
        mean = jnp.mean(xf, axis=axes, keepdims=True)
        var = jnp.var(xf, axis=axes, keepdims=True)
        y = ((xf - mean) * jax.lax.rsqrt(var + epsilon)).astype(x.dtype)
        y = y.reshape(x.shape)
        shape = [1] * (x.ndim - 1) + [c]
    if weight is not None:
        y = y * weight.reshape(shape)
    if bias is not None:
        y = y + bias.reshape(shape)
    return y


@op_fn
def local_response_norm(x, *, size: int = 5, alpha: float = 1e-4,
                        beta: float = 0.75, k: float = 1.0,
                        data_format: str = "NCHW"):
    ch_axis = 1 if data_format.startswith("NC") else x.ndim - 1
    sq = jnp.square(x)
    sq = jnp.moveaxis(sq, ch_axis, -1)
    pad_l = (size - 1) // 2
    pad_r = size - 1 - pad_l
    padded = jnp.pad(sq, [(0, 0)] * (sq.ndim - 1) + [(pad_l, pad_r)])
    win = jax.lax.reduce_window(
        padded, 0.0, jax.lax.add,
        (1,) * (sq.ndim - 1) + (size,), (1,) * sq.ndim,
        [(0, 0)] * sq.ndim)
    win = jnp.moveaxis(win, -1, ch_axis)
    return x / jnp.power(k + alpha * win, beta)
