"""Activation functionals (paddle.nn.functional.* parity).

Reference surface: python/paddle/nn/functional/activation.py. Each op is a
pure JAX function registered through the op dispatcher (ops/_op.py), so in
eager mode it records a tape node (backward = jax.vjp closure) and under jit
it traces straight into the compiled program. XLA fuses these into the
surrounding matmuls on TPU — no hand-written kernels needed at this level.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...ops._op import op_fn

__all__ = [
    "celu", "elu", "gelu", "glu", "gumbel_softmax", "hardshrink",
    "hardsigmoid", "hardswish", "hardtanh", "leaky_relu", "log_sigmoid",
    "log_softmax", "maxout", "mish", "prelu", "relu", "relu6", "rrelu",
    "selu", "sigmoid", "silu", "softmax", "softplus", "softshrink",
    "softsign", "swish", "tanh", "tanhshrink", "thresholded_relu",
]


@op_fn
def relu(x):
    return jnp.maximum(x, 0)


@op_fn
def relu6(x):
    return jnp.clip(x, 0, 6)


@op_fn
def elu(x, *, alpha: float = 1.0):
    return jnp.where(x > 0, x, alpha * jnp.expm1(x))


@op_fn
def celu(x, *, alpha: float = 1.0):
    return jnp.maximum(x, 0) + jnp.minimum(0, alpha * jnp.expm1(x / alpha))


@op_fn
def selu(x, *, scale: float = 1.0507009873554805,
         alpha: float = 1.6732632423543772):
    return scale * jnp.where(x > 0, x, alpha * jnp.expm1(x))


@op_fn
def gelu(x, *, approximate: bool = False):
    return jax.nn.gelu(x, approximate=approximate)


@op_fn
def leaky_relu(x, *, negative_slope: float = 0.01):
    return jnp.where(x >= 0, x, negative_slope * x)


@op_fn
def prelu(x, weight, *, data_format: str = "NCHW"):
    # weight: scalar [1] or per-channel [C]; broadcast on the channel axis.
    w = weight
    if w.ndim == 1 and w.shape[0] != 1 and x.ndim > 1:
        ch_axis = 1 if data_format.startswith("NC") else x.ndim - 1
        shape = [1] * x.ndim
        shape[ch_axis] = w.shape[0]
        w = w.reshape(shape)
    return jnp.where(x >= 0, x, w * x)


@op_fn
def rrelu(x, *, lower: float = 1.0 / 8.0, upper: float = 1.0 / 3.0,
          training: bool = False, key=None):
    if training and key is not None:
        a = jax.random.uniform(key, x.shape, dtype=x.dtype,
                               minval=lower, maxval=upper)
    else:
        a = (lower + upper) / 2.0
    return jnp.where(x >= 0, x, a * x)


@op_fn(name="f_sigmoid")
def sigmoid(x):
    return jax.nn.sigmoid(x)


@op_fn
def log_sigmoid(x):
    return jax.nn.log_sigmoid(x)


@op_fn(name="f_tanh")
def tanh(x):
    return jnp.tanh(x)


@op_fn
def tanhshrink(x):
    return x - jnp.tanh(x)


@op_fn
def hardshrink(x, *, threshold: float = 0.5):
    return jnp.where(jnp.abs(x) > threshold, x, 0)


@op_fn
def softshrink(x, *, threshold: float = 0.5):
    return jnp.where(x > threshold, x - threshold,
                     jnp.where(x < -threshold, x + threshold, 0))


@op_fn
def hardsigmoid(x, *, slope: float = 1.0 / 6.0, offset: float = 0.5):
    return jnp.clip(slope * x + offset, 0, 1)


@op_fn
def hardswish(x):
    return x * jnp.clip(x + 3, 0, 6) / 6


@op_fn
def hardtanh(x, *, min: float = -1.0, max: float = 1.0):
    return jnp.clip(x, min, max)


@op_fn
def mish(x):
    return x * jnp.tanh(jax.nn.softplus(x))


@op_fn(name="f_softplus")
def softplus(x, *, beta: float = 1.0, threshold: float = 20.0):
    bx = beta * x
    return jnp.where(bx > threshold, x, jax.nn.softplus(bx) / beta)


@op_fn(name="f_softsign")
def softsign(x):
    return x / (1 + jnp.abs(x))


@op_fn
def silu(x):
    return x * jax.nn.sigmoid(x)


@op_fn
def swish(x):
    return x * jax.nn.sigmoid(x)


@op_fn
def thresholded_relu(x, *, threshold: float = 1.0, value: float = 0.0):
    return jnp.where(x > threshold, x, value)


@op_fn
def softmax(x, *, axis: int = -1):
    return jax.nn.softmax(x, axis=axis)


@op_fn
def log_softmax(x, *, axis: int = -1):
    return jax.nn.log_softmax(x, axis=axis)


@op_fn
def glu(x, *, axis: int = -1):
    a, b = jnp.split(x, 2, axis=axis)
    return a * jax.nn.sigmoid(b)


@op_fn
def maxout(x, *, groups: int, axis: int = 1):
    ax = axis if axis >= 0 else x.ndim + axis
    c = x.shape[ax]
    shape = x.shape[:ax] + (c // groups, groups) + x.shape[ax + 1:]
    return jnp.max(x.reshape(shape), axis=ax + 1)


@op_fn(name="gumbel_softmax_p")
def _gumbel_softmax_op(x, *, temperature: float = 1.0, hard: bool = False,
                       axis: int = -1, key=None):
    if key is not None:
        g = -jnp.log(-jnp.log(
            jax.random.uniform(key, x.shape, dtype=x.dtype, minval=1e-20,
                               maxval=1.0) + 1e-20))
        x = x + g
    y = jax.nn.softmax(x / temperature, axis=axis)
    if hard:
        idx = jnp.argmax(y, axis=axis, keepdims=True)
        y_hard = jnp.zeros_like(y)
        y_hard = jnp.put_along_axis(y_hard, idx, 1.0, axis=axis,
                                    inplace=False)
        y = jax.lax.stop_gradient(y_hard - y) + y  # straight-through estimator
    return y


def gumbel_softmax(x, temperature: float = 1.0, hard: bool = False,
                   axis: int = -1, name=None):
    """paddle gumbel_softmax parity — always samples Gumbel noise, drawing
    its key from the framework RNG (same discipline as dropout)."""
    del name
    from ...framework import random as frandom
    return _gumbel_softmax_op(x, temperature=temperature, hard=hard,
                              axis=axis, key=frandom.next_key())


# -- inplace variants (reference: activation.py relu_/elu_/... aliases) -----

def _inplace(fn):
    import functools

    @functools.wraps(fn)
    def wrapper(x, *args, **kwargs):
        from ...ops import _adopt, _snapshot
        return _adopt(x, fn(_snapshot(x), *args, **kwargs))
    wrapper.__name__ = fn.__name__ + "_"
    return wrapper


relu_ = _inplace(relu)
elu_ = _inplace(elu)
tanh_ = _inplace(tanh)
hardtanh_ = _inplace(hardtanh)
leaky_relu_ = _inplace(leaky_relu)
softmax_ = _inplace(softmax)
thresholded_relu_ = _inplace(thresholded_relu)

__all__ += ["relu_", "elu_", "tanh_", "hardtanh_", "leaky_relu_",
            "softmax_", "thresholded_relu_"]
