"""Common functionals: linear, embedding, dropout, pad, interpolate, …

Reference surface: python/paddle/nn/functional/{common,input,vision}.py.
All pure-JAX; dropout draws its key from the framework RNG (eager) or the
enclosing rng_scope (jit), mirroring the reference's seeded dropout kernels.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from ...core.tensor import Tensor
from ...framework import random as frandom
from ...ops._op import op_fn, unwrap, wrap

__all__ = [
    "linear", "embedding", "one_hot", "dropout", "dropout2d", "dropout3d",
    "alpha_dropout", "pad", "zeropad2d", "interpolate", "upsample",
    "pixel_shuffle", "pixel_unshuffle", "channel_shuffle", "unfold", "fold",
    "cosine_similarity", "bilinear", "label_smooth",
]


@op_fn
def linear(x, weight, bias=None):
    """y = x @ W + b. Weight layout [in, out] (paddle convention —
    python/paddle/nn/functional/common.py linear); maps straight onto the MXU.
    """
    y = jnp.matmul(x, weight)
    if bias is not None:
        y = y + bias
    return y


@op_fn(name="embedding", nondiff_args=(0,))
def _embedding_dense(ids, weight, *, padding_idx: Optional[int] = None):
    out = jnp.take(weight, ids, axis=0)
    if padding_idx is not None:
        mask = (ids == padding_idx)[..., None]
        out = jnp.where(mask, 0.0, out)
    return out


def embedding(ids, weight, *, padding_idx: Optional[int] = None,
              sparse: bool = False):
    """``sparse=True`` emits a row-sparse (SelectedRows-equivalent) grad
    for ``weight`` on the eager tape: O(tokens·D) instead of a dense
    [V, D] scatter per step (reference:
    paddle/phi/kernels/cpu/embedding_sparse_grad_kernel.cc). Engages
    only in plain eager mode with a LEAF weight — under jit / static
    capture / segmented capture, or when weight is itself an op output
    (its cotangent would have to enter a jax.vjp), the dense path runs:
    XLA's fused scatter is the right compiled answer there."""
    if sparse and _sparse_grad_applicable(ids, weight):
        return _embedding_sparse_eager(ids, weight, padding_idx)
    return _embedding_dense(ids, weight, padding_idx=padding_idx)


def _sparse_grad_applicable(ids, weight) -> bool:
    from ...amp.auto_cast import _amp as _amp_state
    from ...amp.auto_cast import current_cast_dtype_for
    from ...core import state as _state
    from ...core.tensor import is_tracer
    from ...ops import _op as _opmod
    if not (isinstance(weight, Tensor) and isinstance(ids, Tensor)):
        return False
    if weight.stop_gradient or not _state.grad_enabled():
        return False          # no grad at all — dense path, same result
    if weight._grad_node is not None:
        return False          # non-leaf weight: cotangent feeds a vjp
    if _amp_state.enabled and current_cast_dtype_for("embedding"):
        return False          # AMP-listed: only op_fn has the cast seam
    if _opmod._SEGMENT_PROGRAM is not None:
        return False          # segmented capture records dense ops
    if weight._symbolic is not None or ids._symbolic is not None:
        return False          # static Program build
    if is_tracer(weight._data) or is_tracer(ids._data):
        return False          # inside jit tracing
    return True


def _embedding_sparse_eager(ids_t, weight_t, padding_idx):
    from ...autograd import tape
    from ...core.flags import flag_value
    from ...core.selected_rows import SelectedRows
    from ...ops import _op as _opmod

    ids = ids_t._data
    w = weight_t._data
    pure = _embedding_dense.pure_fn      # one definition of the math
    ph = _opmod._PROFILE_HOOK
    if ph is not None:
        ph[0]("embedding_sparse")
    try:
        out = pure(ids, w, padding_idx=padding_idx)
    finally:
        if ph is not None:
            ph[1]()
    if flag_value("check_nan_inf"):
        _opmod._check_nan_inf("embedding_sparse", out)
    out_t = wrap(out)
    tail = w.shape[1:]
    dense_shape = w.shape

    def vjp_fn(cot):
        flat_ids = ids.reshape(-1).astype(jnp.int32)
        vals = cot.reshape((-1,) + tail)
        if padding_idx is not None:
            vals = jnp.where((flat_ids == padding_idx)[:, None], 0.0, vals)
        return (SelectedRows(flat_ids, vals, dense_shape),)

    node = tape.record_node("embedding_sparse", vjp_fn, [weight_t], [out_t])
    # create_graph / double-backward re-differentiates through the DENSE
    # pure fn (the sparse vjp is a leaf-grad fast path, not new math)
    node.pure_spec = (pure, {"padding_idx": padding_idx}, (1,), {0: ids}, 2)
    return out_t


@op_fn(differentiable=False)
def one_hot(x, *, num_classes: int):
    return jax.nn.one_hot(x, num_classes, dtype=jnp.float32)


def _dropout_impl(x, p, training, mode, key, bcast_dims=None):
    if not training or p == 0.0:
        return x
    keep = 1.0 - p
    shape = list(x.shape)
    if bcast_dims:
        for d in bcast_dims:
            shape[d] = 1
    mask = jax.random.bernoulli(key, keep, tuple(shape))
    if mode == "upscale_in_train":
        return jnp.where(mask, x / keep, 0.0).astype(x.dtype)
    return jnp.where(mask, x, 0.0).astype(x.dtype)  # downscale_in_infer


@op_fn(name="dropout_p")
def _dropout_op(x, *, p, training, mode, key, bcast_dims=None):
    return _dropout_impl(x, p, training, mode, key, bcast_dims)


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train",
            name=None):
    """paddle.nn.functional.dropout parity (upscale_in_train default).
    downscale_in_infer scales by (1-p) at inference instead of upscaling
    at train time (reference: common.py dropout)."""
    del name
    if not training or p == 0.0:
        if p > 0.0 and mode == "downscale_in_infer":
            from ...ops import scale as _scale
            return _scale(x, scale=1.0 - p)
        return x if isinstance(x, Tensor) else wrap(unwrap(x))
    bcast = None
    if axis is not None:
        nd = unwrap(x).ndim
        axes = [axis] if isinstance(axis, int) else list(axis)
        bcast = [d for d in range(nd) if d not in axes]
    return _dropout_op(x, p=float(p), training=True, mode=mode,
                       key=frandom.next_key(), bcast_dims=bcast)


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    """Drops whole channels of NCHW/NHWC feature maps."""
    del name
    if not training or p == 0.0:
        return x if isinstance(x, Tensor) else wrap(unwrap(x))
    bcast = [2, 3] if data_format == "NCHW" else [1, 2]
    return _dropout_op(x, p=float(p), training=True, mode="upscale_in_train",
                       key=frandom.next_key(), bcast_dims=bcast)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    del name
    if not training or p == 0.0:
        return x if isinstance(x, Tensor) else wrap(unwrap(x))
    bcast = [2, 3, 4] if data_format == "NCDHW" else [1, 2, 3]
    return _dropout_op(x, p=float(p), training=True, mode="upscale_in_train",
                       key=frandom.next_key(), bcast_dims=bcast)


@op_fn(name="alpha_dropout_p")
def _alpha_dropout_op(x, *, p, key):
    # SELU-preserving dropout (reference: common.py alpha_dropout).
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale
    keep = 1.0 - p
    a = (keep + alpha_p ** 2 * keep * (1 - keep)) ** -0.5
    b = -a * alpha_p * (1 - keep)
    mask = jax.random.bernoulli(key, keep, x.shape)
    return (a * jnp.where(mask, x, alpha_p) + b).astype(x.dtype)


def alpha_dropout(x, p=0.5, training=True, name=None):
    del name
    if not training or p == 0.0:
        return x if isinstance(x, Tensor) else wrap(unwrap(x))
    return _alpha_dropout_op(x, p=float(p), key=frandom.next_key())


def _norm_pad(pad_spec, ndim, data_format):
    """Convert a paddle pad spec to a jnp.pad config.

    Paddle semantics (python/paddle/nn/functional/common.py pad): an int pads
    every dim; a list of 2*ndim ints is per-dim pairs ordered from the LAST
    dim backwards (torch-style); a shorter list pads the spatial dims of the
    NC*/N*C layout, again last-spatial-dim first.
    """
    if isinstance(pad_spec, int):
        return [(pad_spec, pad_spec)] * ndim
    pad_spec = [int(p) for p in pad_spec]
    out = [(0, 0)] * ndim
    n_pairs = len(pad_spec) // 2
    if n_pairs == ndim:
        dims = list(range(ndim - 1, -1, -1))
    elif data_format.startswith("NC"):
        dims = list(range(ndim - 1, ndim - 1 - n_pairs, -1))
    else:  # channel-last: spatial dims end one before the channel dim
        dims = list(range(ndim - 2, ndim - 2 - n_pairs, -1))
    for i, d in enumerate(dims):
        out[d] = (pad_spec[2 * i], pad_spec[2 * i + 1])
    return out


@op_fn(name="f_pad")
def _pad_op(x, *, pad_cfg, mode, value):
    jmode = {"constant": "constant", "reflect": "reflect",
             "replicate": "edge", "circular": "wrap"}[mode]
    if jmode == "constant":
        return jnp.pad(x, pad_cfg, mode="constant", constant_values=value)
    return jnp.pad(x, pad_cfg, mode=jmode)


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    """paddle.nn.functional.pad parity."""
    del name
    nd = unwrap(x).ndim
    cfg = _norm_pad(unwrap(pad) if isinstance(pad, Tensor) else pad, nd,
                    data_format)
    cfg = [(int(a), int(b)) for a, b in cfg]
    return _pad_op(x, pad_cfg=tuple(cfg), mode=mode, value=value)


def zeropad2d(x, padding, data_format="NCHW", name=None):
    return pad(x, padding, mode="constant", value=0.0,
               data_format=data_format, name=name)


@op_fn
def cosine_similarity(x1, x2, *, axis: int = 1, eps: float = 1e-8):
    dot = jnp.sum(x1 * x2, axis=axis)
    n1 = jnp.linalg.norm(x1, axis=axis)
    n2 = jnp.linalg.norm(x2, axis=axis)
    return dot / jnp.maximum(n1 * n2, eps)


@op_fn
def bilinear(x1, x2, weight, bias=None):
    # weight: [out, in1, in2] (reference: common.py bilinear)
    y = jnp.einsum("bi,oij,bj->bo", x1, weight, x2)
    if bias is not None:
        y = y + bias
    return y


@op_fn
def label_smooth(label, *, epsilon: float = 0.1):
    k = label.shape[-1]
    return (1 - epsilon) * label + epsilon / k


@op_fn
def pixel_shuffle(x, *, upscale_factor: int, data_format: str = "NCHW"):
    r = upscale_factor
    if data_format == "NCHW":
        n, c, h, w = x.shape
        x = x.reshape(n, c // (r * r), r, r, h, w)
        x = x.transpose(0, 1, 4, 2, 5, 3)
        return x.reshape(n, c // (r * r), h * r, w * r)
    n, h, w, c = x.shape
    x = x.reshape(n, h, w, r, r, c // (r * r))
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(n, h * r, w * r, c // (r * r))


@op_fn
def pixel_unshuffle(x, *, downscale_factor: int, data_format: str = "NCHW"):
    r = downscale_factor
    if data_format == "NCHW":
        n, c, h, w = x.shape
        x = x.reshape(n, c, h // r, r, w // r, r)
        x = x.transpose(0, 1, 3, 5, 2, 4)
        return x.reshape(n, c * r * r, h // r, w // r)
    n, h, w, c = x.shape
    x = x.reshape(n, h // r, r, w // r, r, c)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(n, h // r, w // r, c * r * r)


@op_fn
def channel_shuffle(x, *, groups: int, data_format: str = "NCHW"):
    if data_format == "NCHW":
        n, c, h, w = x.shape
        x = x.reshape(n, groups, c // groups, h, w)
        x = x.transpose(0, 2, 1, 3, 4)
        return x.reshape(n, c, h, w)
    n, h, w, c = x.shape
    x = x.reshape(n, h, w, groups, c // groups)
    x = x.transpose(0, 1, 2, 4, 3)
    return x.reshape(n, h, w, c)


def _lerp_axis_aligned(x, axis, out_size):
    """Linear resize of one axis with align_corners=True coordinates:
    src = i * (in-1)/(out-1)."""
    in_size = x.shape[axis]
    if out_size == 1 or in_size == 1:
        idx = jnp.zeros((out_size,), jnp.int32)
        return jnp.take(x, idx, axis=axis)
    src = jnp.arange(out_size, dtype=jnp.float32) * \
        ((in_size - 1) / (out_size - 1))
    lo = jnp.clip(jnp.floor(src).astype(jnp.int32), 0, in_size - 1)
    hi = jnp.clip(lo + 1, 0, in_size - 1)
    frac = (src - lo.astype(jnp.float32))
    shape = [1] * x.ndim
    shape[axis] = out_size
    frac = frac.reshape(shape).astype(x.dtype)
    return jnp.take(x, lo, axis=axis) * (1 - frac) + \
        jnp.take(x, hi, axis=axis) * frac


@op_fn
def interpolate(x, *, size=None, scale_factor=None, mode: str = "nearest",
                align_corners: bool = False, data_format: str = "NCHW"):
    """Resize via jax.image (XLA gather/conv lowering on TPU).

    align_corners=True for the linear family uses the corner-aligned source
    grid (src = i*(in-1)/(out-1)), matching the reference's interpolate;
    'area' mode is bin-averaging (adaptive average pooling semantics).
    """
    channel_last = not data_format.startswith("NC")
    spatial = x.shape[1:-1] if channel_last else x.shape[2:]
    if size is None:
        if isinstance(scale_factor, (int, float)):
            scale_factor = [scale_factor] * len(spatial)
        size = [int(s * f) for s, f in zip(spatial, scale_factor)]
    size = [int(s) for s in (size if isinstance(size, (list, tuple)) else [size])]
    sp_axes = (tuple(range(1, x.ndim - 1)) if channel_last
               else tuple(range(2, x.ndim)))
    if mode == "area":
        from .pooling import _adaptive
        return _adaptive(x, len(size), tuple(size), data_format, "avg")
    linear_family = mode in ("linear", "bilinear", "trilinear")
    if align_corners and linear_family:
        for ax, s in zip(sp_axes, size):
            x = _lerp_axis_aligned(x, ax, s)
        return x
    jmode = {"nearest": "nearest", "bilinear": "linear", "linear": "linear",
             "trilinear": "linear", "bicubic": "cubic"}[mode]
    if channel_last:
        full = (x.shape[0],) + tuple(size) + (x.shape[-1],)
    else:
        full = (x.shape[0], x.shape[1]) + tuple(size)
    return jax.image.resize(x, full, method=jmode)


def upsample(x, size=None, scale_factor=None, mode="nearest",
             align_corners=False, data_format="NCHW", name=None):
    del name
    return interpolate(x, size=size, scale_factor=scale_factor, mode=mode,
                       align_corners=align_corners, data_format=data_format)


@op_fn
def unfold(x, *, kernel_sizes, strides=1, paddings=0, dilations=1):
    """im2col (reference: common.py unfold). x: [N,C,H,W] ->
    [N, C*kh*kw, L]."""
    kh, kw = (kernel_sizes, kernel_sizes) if isinstance(kernel_sizes, int) \
        else kernel_sizes
    sh, sw = (strides, strides) if isinstance(strides, int) else strides
    ph, pw = (paddings, paddings) if isinstance(paddings, int) else paddings[:2]
    dh, dw = (dilations, dilations) if isinstance(dilations, int) else dilations
    n, c, h, w = x.shape
    x = jnp.pad(x, [(0, 0), (0, 0), (ph, ph), (pw, pw)])
    oh = (h + 2 * ph - dh * (kh - 1) - 1) // sh + 1
    ow = (w + 2 * pw - dw * (kw - 1) - 1) // sw + 1
    patches = jax.lax.conv_general_dilated_patches(
        x, (kh, kw), (sh, sw), "VALID", rhs_dilation=(dh, dw),
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    return patches.reshape(n, c * kh * kw, oh * ow)


@op_fn
def fold(x, *, output_sizes, kernel_sizes, strides=1, paddings=0,
         dilations=1):
    """col2im: inverse of unfold (sum overlapping patches)."""
    kh, kw = (kernel_sizes, kernel_sizes) if isinstance(kernel_sizes, int) \
        else kernel_sizes
    sh, sw = (strides, strides) if isinstance(strides, int) else strides
    ph, pw = (paddings, paddings) if isinstance(paddings, int) else paddings[:2]
    dh, dw = (dilations, dilations) if isinstance(dilations, int) else dilations
    oh_img, ow_img = output_sizes
    n, ckk, L = x.shape
    c = ckk // (kh * kw)
    oh = (oh_img + 2 * ph - dh * (kh - 1) - 1) // sh + 1
    ow = (ow_img + 2 * pw - dw * (kw - 1) - 1) // sw + 1
    x = x.reshape(n, c, kh, kw, oh, ow)
    out = jnp.zeros((n, c, oh_img + 2 * ph, ow_img + 2 * pw), x.dtype)
    for i in range(kh):
        for j in range(kw):
            hi = i * dh
            wj = j * dw
            out = out.at[:, :, hi:hi + sh * oh:sh, wj:wj + sw * ow:sw].add(
                x[:, :, i, j])
    return out[:, :, ph:ph + oh_img, pw:pw + ow_img]
