"""Convolution functionals.

Reference surface: python/paddle/nn/functional/conv.py (conv1d/2d/3d and
transpose variants). TPU-native design: one pure function over
``jax.lax.conv_general_dilated`` — XLA lowers it onto the MXU directly, with
layout chosen by dimension_numbers (both NCHW and NHWC supported; NHWC is the
TPU-preferred layout). Weight layout follows paddle: [out_c, in_c/groups, *k].
"""
from __future__ import annotations

from typing import Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax

from ...ops._op import op_fn
from ...core import enforce as E

__all__ = ["conv1d", "conv2d", "conv3d", "conv1d_transpose",
           "conv2d_transpose", "conv3d_transpose"]


def _tuplize(v, n):
    if isinstance(v, int):
        return (v,) * n
    return tuple(int(x) for x in v)


def _dim_numbers(ndim_spatial: int, data_format: str):
    sp = "DHW"[-ndim_spatial:] if ndim_spatial <= 3 else None
    if data_format.startswith("NC"):
        lhs = "NC" + sp
    else:
        lhs = "N" + sp + "C"
    rhs = "OI" + sp
    return (lhs, rhs, lhs)


def _norm_padding(padding, n, data_format):
    """paddle padding: int | list[int] | list[pair] | 'SAME'/'VALID'."""
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, int):
        return [(padding, padding)] * n
    padding = list(padding)
    if len(padding) == n and all(isinstance(p, int) for p in padding):
        return [(p, p) for p in padding]
    if len(padding) == 2 * n:
        return [(padding[2 * i], padding[2 * i + 1]) for i in range(n)]
    # paddle also allows per-dim pairs including batch/channel; strip those
    pairs = [tuple(p) for p in padding]
    if len(pairs) == n + 2:
        if data_format.startswith("NC"):
            pairs = pairs[2:]
        else:
            pairs = pairs[1:-1]
    return pairs


def _conv(x, weight, bias, stride, padding, dilation, groups, data_format,
          nsp):
    dn = lax.conv_dimension_numbers(x.shape, weight.shape,
                                    _dim_numbers(nsp, data_format))
    out = lax.conv_general_dilated(
        x, weight,
        window_strides=_tuplize(stride, nsp),
        padding=_norm_padding(padding, nsp, data_format),
        rhs_dilation=_tuplize(dilation, nsp),
        dimension_numbers=dn,
        feature_group_count=groups,
        preferred_element_type=None)
    if bias is not None:
        if data_format.startswith("NC"):
            out = out + bias.reshape((1, -1) + (1,) * nsp)
        else:
            out = out + bias
    return out


@op_fn
def conv1d(x, weight, bias=None, *, stride=1, padding=0, dilation=1,
           groups: int = 1, data_format: str = "NCL"):
    return _conv(x, weight, bias, stride, padding, dilation, groups,
                 data_format, 1)


@op_fn
def conv2d(x, weight, bias=None, *, stride=1, padding=0, dilation=1,
           groups: int = 1, data_format: str = "NCHW"):
    return _conv(x, weight, bias, stride, padding, dilation, groups,
                 data_format, 2)


@op_fn
def conv3d(x, weight, bias=None, *, stride=1, padding=0, dilation=1,
           groups: int = 1, data_format: str = "NCDHW"):
    return _conv(x, weight, bias, stride, padding, dilation, groups,
                 data_format, 3)


def _conv_transpose(x, weight, bias, stride, padding, output_padding,
                    dilation, groups, data_format, nsp, output_size):
    # weight layout [in_c, out_c/groups, *k] (paddle conv_transpose
    # convention). Implemented as the gradient of conv: lhs-dilated conv.
    stride = _tuplize(stride, nsp)
    dilation = _tuplize(dilation, nsp)
    opad = _tuplize(output_padding or 0, nsp)
    pad_cfg = _norm_padding(padding, nsp, data_format)

    # flip spatial dims and swap I/O: transpose conv = conv with flipped
    # kernel, lhs dilation = stride.
    w = jnp.flip(weight, axis=tuple(range(2, 2 + nsp)))
    if groups > 1:
        ic, ocg = w.shape[0], w.shape[1]
        w = w.reshape((groups, ic // groups, ocg) + w.shape[2:])
        w = jnp.swapaxes(w, 1, 2)
        w = w.reshape((groups * ocg, ic // groups) + w.shape[3:])
    else:
        w = jnp.swapaxes(w, 0, 1)

    k = [dilation[i] * (weight.shape[2 + i] - 1) + 1 for i in range(nsp)]
    if isinstance(pad_cfg, str):
        if pad_cfg == "VALID":
            pad_cfg = [(0, 0)] * nsp
        else:  # SAME
            pad_cfg = [((k[i] - 1) // 2, k[i] // 2) for i in range(nsp)]
    if output_size is not None:
        # Resolve the stride ambiguity: derive output_padding so the result
        # hits the requested spatial size (reference: conv.py
        # conv2d_transpose output_size handling).
        if data_format.startswith("NC"):
            in_sp = x.shape[2:2 + nsp]
        else:
            in_sp = x.shape[1:1 + nsp]
        out_req = _tuplize(output_size, nsp)
        opad = tuple(
            out_req[i] - ((in_sp[i] - 1) * stride[i] - pad_cfg[i][0]
                          - pad_cfg[i][1] + k[i])
            for i in range(nsp))
        if any(o < 0 or o >= stride[i] for i, o in enumerate(opad)):
            raise E.InvalidArgumentError(
                f"output_size {out_req} unreachable with stride {stride}")
    tpad = [(k[i] - 1 - pad_cfg[i][0],
             k[i] - 1 - pad_cfg[i][1] + opad[i]) for i in range(nsp)]

    dn = lax.conv_dimension_numbers(x.shape, w.shape,
                                    _dim_numbers(nsp, data_format))
    out = lax.conv_general_dilated(
        x, w, window_strides=(1,) * nsp, padding=tpad,
        lhs_dilation=stride, rhs_dilation=dilation,
        dimension_numbers=dn, feature_group_count=groups)
    if bias is not None:
        if data_format.startswith("NC"):
            out = out + bias.reshape((1, -1) + (1,) * nsp)
        else:
            out = out + bias
    return out


@op_fn
def conv1d_transpose(x, weight, bias=None, *, stride=1, padding=0,
                     output_padding=0, dilation=1, groups: int = 1,
                     output_size=None, data_format: str = "NCL"):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, data_format, 1, output_size)


@op_fn
def conv2d_transpose(x, weight, bias=None, *, stride=1, padding=0,
                     output_padding=0, dilation=1, groups: int = 1,
                     output_size=None, data_format: str = "NCHW"):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, data_format, 2, output_size)


@op_fn
def conv3d_transpose(x, weight, bias=None, *, stride=1, padding=0,
                     output_padding=0, dilation=1, groups: int = 1,
                     output_size=None, data_format: str = "NCDHW"):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, data_format, 3, output_size)
