"""Vision functional ops: grid_sample / affine_grid.

Reference capability: python/paddle/nn/functional/vision.py (grid_sample
backed by phi grid_sample_kernel, affine_grid). TPU-native: bilinear
sampling is expressed as four gathers + a lerp — XLA lowers the gathers to
vectorized dynamic-slices, and the whole op is differentiable through the
gathers (no custom backward kernel needed).
"""
from __future__ import annotations

import jax.numpy as jnp

from ...ops._op import op_fn
from ...core import enforce as E

__all__ = ["grid_sample", "affine_grid"]


def _unnormalize(coord, size, align_corners):
    if align_corners:
        return (coord + 1.0) / 2.0 * (size - 1)
    return ((coord + 1.0) * size - 1.0) / 2.0


@op_fn(name="grid_sample")
def _grid_sample(x, grid, *, mode="bilinear", padding_mode="zeros",
                 align_corners=True):
    """x [N, C, H, W], grid [N, Hg, Wg, 2] in [-1, 1] -> [N, C, Hg, Wg]."""
    n, c, h, w = x.shape
    gx = _unnormalize(grid[..., 0], w, align_corners)    # [N, Hg, Wg]
    gy = _unnormalize(grid[..., 1], h, align_corners)

    def clip_or_reflect(v, size):
        if padding_mode == "border":
            return jnp.clip(v, 0, size - 1)
        # reflection: reflect about the pixel CENTERS (align_corners=True:
        # [0, size-1]) or the pixel BORDERS (False: [-0.5, size-0.5])
        # — the reference reflect_coordinates semantics
        lo = 0.0 if align_corners else -0.5
        hi = (size - 1.0) if align_corners else (size - 0.5)
        span = hi - lo
        v = jnp.mod(jnp.abs(v - lo), 2 * span)
        v = jnp.where(v >= span, 2 * span - v, v) + lo
        return jnp.clip(v, 0, size - 1)

    if padding_mode != "zeros":   # zeros: raw coords, masked at sample time
        gx = clip_or_reflect(gx, w)
        gy = clip_or_reflect(gy, h)

    if mode == "nearest":
        ix = jnp.round(gx).astype(jnp.int32)
        iy = jnp.round(gy).astype(jnp.int32)
        valid = ((ix >= 0) & (ix < w) & (iy >= 0) & (iy < h))
        ixc = jnp.clip(ix, 0, w - 1)
        iyc = jnp.clip(iy, 0, h - 1)
        batch = jnp.arange(n)[:, None, None]
        out = x[batch, :, iyc, ixc]                     # [N, Hg, Wg, C]
        out = jnp.where(valid[..., None], out, 0.0)
        return jnp.moveaxis(out, -1, 1)

    x0 = jnp.floor(gx)
    y0 = jnp.floor(gy)
    x1, y1 = x0 + 1, y0 + 1
    wx1 = gx - x0
    wy1 = gy - y0
    wx0, wy0 = 1.0 - wx1, 1.0 - wy1

    def sample(ix, iy):
        valid = ((ix >= 0) & (ix < w) & (iy >= 0) & (iy < h))
        ixc = jnp.clip(ix.astype(jnp.int32), 0, w - 1)
        iyc = jnp.clip(iy.astype(jnp.int32), 0, h - 1)
        batch = jnp.arange(n)[:, None, None]
        v = x[batch, :, iyc, ixc]                       # [N, Hg, Wg, C]
        return jnp.where(valid[..., None], v, 0.0)

    out = (sample(x0, y0) * (wx0 * wy0)[..., None]
           + sample(x1, y0) * (wx1 * wy0)[..., None]
           + sample(x0, y1) * (wx0 * wy1)[..., None]
           + sample(x1, y1) * (wx1 * wy1)[..., None])
    return jnp.moveaxis(out, -1, 1)


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    if mode not in ("bilinear", "nearest"):
        raise E.InvalidArgumentError(f"mode must be bilinear/nearest, got {mode!r}")
    if padding_mode not in ("zeros", "border", "reflection"):
        raise E.InvalidArgumentError(f"bad padding_mode {padding_mode!r}")
    return _grid_sample(x, grid, mode=mode, padding_mode=padding_mode,
                        align_corners=align_corners)


@op_fn(name="affine_grid")
def _affine_grid(theta, *, out_shape, align_corners=True):
    """theta [N, 2, 3] -> sampling grid [N, H, W, 2] (reference:
    functional/vision.py affine_grid)."""
    n, _, h, w = out_shape
    if align_corners:
        xs = jnp.linspace(-1.0, 1.0, w)
        ys = jnp.linspace(-1.0, 1.0, h)
    else:
        xs = (jnp.arange(w) * 2 + 1) / w - 1
        ys = (jnp.arange(h) * 2 + 1) / h - 1
    gx, gy = jnp.meshgrid(xs, ys)                       # [H, W]
    ones = jnp.ones_like(gx)
    base = jnp.stack([gx, gy, ones], axis=-1)           # [H, W, 3]
    out = jnp.einsum("hwk,nck->nhwc", base, theta)      # [N, H, W, 2]
    return out


def affine_grid(theta, out_shape, align_corners=True, name=None):
    out_shape = [int(s) for s in out_shape]
    return _affine_grid(theta, out_shape=tuple(out_shape),
                        align_corners=align_corners)
