"""Attention functionals.

Reference surface: python/paddle/nn/functional/flash_attention.py
(flash_attention:147, scaled_dot_product_attention:722). TPU-native design:
one pure attention function with a kernel-dispatch seam — the default is the
XLA softmax-attention (fused well by XLA for moderate seq lens); the Pallas
flash kernel (paddle_tpu/kernels/flash_attention.py) overrides when
available/profitable, mirroring the reference's KernelFactory choice of
flash-attn vs math path.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ...ops._op import op_fn

__all__ = ["scaled_dot_product_attention", "flash_attention",
           "sdpa_reference", "sdpa_raw", "segment_attention_raw",
           "apply_rotary_emb",
           "fused_rotary_position_embedding", "flash_attn_unpadded",
           "segment_ids_from_cu_seqlens", "flash_attn_qkvpacked",
           "flash_attn_varlen_qkvpacked", "flash_attention_with_sparse_mask"]

# Filled by paddle_tpu.kernels at import time with a pallas implementation;
# signature (q, k, v, bias, causal, scale) -> out. None = use XLA path.
_FLASH_IMPL = None

# Segment-masked (sequence-packed) attention dispatcher, installed by
# paddle_tpu.kernels.register alongside the flash impl; signature
# (q, k, v, seg_q, seg_k, pos_q, pos_k, *, causal, scale) -> out.
# None = the pure-jnp reference (identical masking semantics).
_SEGMENT_IMPL = None


def register_flash_impl(fn):
    global _FLASH_IMPL
    _FLASH_IMPL = fn


def register_segment_impl(fn):
    global _SEGMENT_IMPL
    _SEGMENT_IMPL = fn


def segment_attention_raw(q, k, v, seg_q, seg_k, pos_q, pos_k, *,
                          causal=False, scale=None):
    """Raw-array segment-masked attention (kernel seam): the registered
    dispatcher (paddle_tpu.kernels.dispatched_segment_attention — Pallas
    segment kernel on TPU, grouped-GQA jnp reference elsewhere) when
    installed, else the reference directly. Used by sdpa_raw's packed
    path and the varlen functional surface below."""
    if _SEGMENT_IMPL is not None:
        return _SEGMENT_IMPL(q, k, v, seg_q, seg_k, pos_q, pos_k,
                             causal=causal, scale=scale)
    from ...kernels.flash_attention import segment_attention_ref
    return segment_attention_ref(q, k, v, seg_q, seg_k, pos_q, pos_k,
                                 causal=causal, scale=scale)


def sdpa_reference(q, k, v, attn_mask=None, *, causal=False, scale=None,
                   dropout_p=0.0, key=None):
    """Math attention on [B, S, H, D] (paddle layout). float32 softmax."""
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / (d ** 0.5)
    # [B,S,H,D] -> [B,H,S,D]
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    # GQA: broadcast kv heads if fewer than q heads
    if kt.shape[1] != qt.shape[1]:
        rep = qt.shape[1] // kt.shape[1]
        kt = jnp.repeat(kt, rep, axis=1)
        vt = jnp.repeat(vt, rep, axis=1)
    logits = jnp.einsum("bhqd,bhkd->bhqk", qt, kt,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        sq, sk = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), dtype=bool), k=sk - sq)
        logits = jnp.where(mask, logits, -jnp.inf)
    if attn_mask is not None:
        if attn_mask.dtype == jnp.bool_:
            logits = jnp.where(attn_mask, logits, -jnp.inf)
        else:
            logits = logits + attn_mask.astype(logits.dtype)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    if dropout_p > 0.0 and key is not None:
        keep = jax.random.bernoulli(key, 1.0 - dropout_p, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_p), 0.0)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, vt)
    return jnp.swapaxes(out, 1, 2)  # back to [B,S,H,D]


def sdpa_raw(query, key, value, attn_mask=None, *, dropout_p: float = 0.0,
             is_causal: bool = False, rng_key=None, scale=None,
             segment_ids=None, positions=None):
    """Raw-array attention dispatcher (kernel seam): flash kernel when
    registered and applicable, else the XLA math path. Used by both the
    eager op below and the functional model cores (models/llama.py).

    ``segment_ids`` [B, S] selects the sequence-packed path: tokens
    attend only within their own document (-1 = padding -> zero rows),
    with ``is_causal`` evaluated on the segment-local ``positions``
    [B, S] (defaults to the global arange, which equals the
    segment-local order for contiguously packed rows)."""
    if segment_ids is not None:
        if attn_mask is not None or dropout_p != 0.0:
            raise NotImplementedError(
                "sdpa_raw: attn_mask/dropout are not supported together "
                "with segment_ids (the packed mask IS the mask)")
        pos = positions
        if pos is None:
            pos = jnp.broadcast_to(jnp.arange(query.shape[1]),
                                   segment_ids.shape)
        return segment_attention_raw(query, key, value, segment_ids,
                                     segment_ids, pos, pos,
                                     causal=is_causal, scale=scale)
    use_flash = (_FLASH_IMPL is not None and attn_mask is None
                 and dropout_p == 0.0)
    if use_flash:
        return _FLASH_IMPL(query, key, value, causal=is_causal, scale=scale)
    return sdpa_reference(query, key, value, attn_mask, causal=is_causal,
                          scale=scale, dropout_p=dropout_p, key=rng_key)


@op_fn
def _sdpa_op(query, key, value, attn_mask=None, *, dropout_p: float = 0.0,
             is_causal: bool = False, rng_key=None, scale=None):
    return sdpa_raw(query, key, value, attn_mask, dropout_p=dropout_p,
                    is_causal=is_causal, rng_key=rng_key, scale=scale)


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p: float = 0.0,
                                 is_causal: bool = False,
                                 training: bool = True, name=None,
                                 scale=None):
    """paddle scaled_dot_product_attention parity: inputs [B, S, H, D].
    Attention dropout draws its key from the framework RNG (same discipline
    as F.dropout)."""
    del name
    from ...framework import random as frandom
    p = dropout_p if training else 0.0
    rng_key = frandom.next_key() if p > 0.0 else None
    return _sdpa_op(query, key, value, attn_mask, dropout_p=p,
                    is_causal=is_causal, rng_key=rng_key, scale=scale)


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, fixed_seed_offset=None,
                    rng_name="", training=True, name=None):
    """paddle flash_attention parity (flash_attention.py:147):
    returns (out, softmax_lse-or-None)."""
    del fixed_seed_offset, rng_name, name
    out = scaled_dot_product_attention(
        query, key, value, None, dropout_p=dropout if training else 0.0,
        is_causal=causal, training=training)
    return out, None


# -- rotary position embedding (shared raw-array helpers) -------------------
# Single source of the rope math for the eager op, the incubate wrapper, and
# the functional model cores (models/llama.py). Reference surface:
# incubate/nn/functional/fused_rotary_position_embedding.py.

def rope_tables(seq_len: int, head_dim: int, *, theta: float = 10000.0,
                dtype=jnp.float32):
    """cos/sin tables [S, head_dim//2]."""
    inv = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, jnp.float32) / head_dim))
    freqs = jnp.outer(jnp.arange(seq_len, dtype=jnp.float32), inv)
    return jnp.cos(freqs).astype(dtype), jnp.sin(freqs).astype(dtype)


def gather_rope_rows(cos, sin, positions):
    """Gather per-token rope table rows at explicit positions [B, S] —
    the position_ids seam: incremental decoding gathers cache offsets,
    sequence packing gathers segment-LOCAL offsets (every document
    restarts at 0). Returns [B, S, D/2] tables rope_raw consumes."""
    return jnp.take(cos, positions, axis=0), jnp.take(sin, positions, axis=0)


def rope_raw(x, cos, sin, *, neox: bool = True):
    """Apply rope on raw arrays. x: [B, S, H, D]; cos/sin: [S, D/2] or
    (gathered at positions) [B, S, D/2]. ``neox=True`` is the rotate-half
    convention (GPT-NeoX / Llama); False the interleaved-pair convention."""
    c = cos[None, :, None, :] if cos.ndim == 2 else cos[:, :, None, :]
    s = sin[None, :, None, :] if sin.ndim == 2 else sin[:, :, None, :]
    if neox:
        d2 = x.shape[-1] // 2
        x1, x2 = x[..., :d2], x[..., d2:]
        return jnp.concatenate(
            [x1 * c - x2 * s, x2 * c + x1 * s], axis=-1).astype(x.dtype)
    x1 = x[..., 0::2]
    x2 = x[..., 1::2]
    r1 = x1 * c - x2 * s
    r2 = x2 * c + x1 * s
    return jnp.stack([r1, r2], axis=-1).reshape(x.shape).astype(x.dtype)


@op_fn
def apply_rotary_emb(x, cos, sin):
    """Rotary position embedding (rotate-half). x: [B, S, H, D];
    cos/sin: [S, D/2]."""
    return rope_raw(x, cos, sin)


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None,
                                    use_neox_rotary_style=True):
    """paddle.incubate parity wrapper: applies rope to q/k (v passed
    through). sin/cos: [1, S, 1, D] or [S, D/2] tables; ``position_ids``
    [B, S] gathers per-token table rows (incremental decoding)."""
    def table(t):
        a = t._data if hasattr(t, "_data") else jnp.asarray(t)
        if a.ndim == 4:
            a = a[0, :, 0, :]
        if a.shape[-1] == q.shape[-1]:   # full-D table -> half table
            a = a[..., : a.shape[-1] // 2]
        return a

    if cos is None or sin is None:
        cos_t, sin_t = rope_tables(q.shape[1], q.shape[-1])
    else:
        cos_t, sin_t = table(cos), table(sin)
    if position_ids is not None:
        pos = position_ids._data if hasattr(position_ids, "_data") \
            else jnp.asarray(position_ids)
        cos_t = jnp.take(cos_t, pos, axis=0)   # [B, S, D/2]
        sin_t = jnp.take(sin_t, pos, axis=0)

    outs = [_rope_op(q, cos_t, sin_t, neox=use_neox_rotary_style)]
    outs.append(_rope_op(k, cos_t, sin_t, neox=use_neox_rotary_style)
                if k is not None else None)
    outs.append(v)
    return tuple(outs)


@op_fn(name="fused_rope")
def _rope_op(x, c, s, *, neox: bool = True):
    return rope_raw(x, c, s, neox=neox)


# ---------------------------------------------------------------------------
# varlen / unpadded attention (long-context aux, SURVEY §5)
# ---------------------------------------------------------------------------

def segment_ids_from_cu_seqlens(cu_seqlens, total):
    """[0, l1, l1+l2, ...] -> per-token segment ids [total] (tokens past
    the last boundary get a padding segment of -1)."""
    import jax.numpy as jnp
    pos = jnp.arange(total)
    # segment id = number of boundaries <= pos, minus 1
    seg = jnp.searchsorted(cu_seqlens, pos, side="right") - 1
    nseg = cu_seqlens.shape[0] - 1
    return jnp.where(seg < nseg, seg, -1)


def _local_positions(cu_seqlens, seg, total):
    """Per-token offset within its own segment (padding tokens get 0)."""
    import jax.numpy as jnp
    starts = jnp.take(cu_seqlens, jnp.clip(seg, 0, None))
    return jnp.arange(total) - starts


@op_fn(name="flash_attn_varlen")
def _flash_varlen(q, k, v, seg_q, seg_k, pos_q, pos_k, *, causal, scale):
    """Packed ragged attention: q/k/v [T, H, D] with per-token segment
    ids; tokens attend only within their segment (block-diagonal mask),
    optionally causal inside each segment (on the segment-LOCAL
    positions — q and k of the same sequence can sit at different global
    offsets when cu_seqlens_q != cu_seqlens_k).

    Reference capability: nn/functional/flash_attention.py
    flash_attn_unpadded (cu_seqlens varlen kernel). TPU-native: the
    packed layout IS the TPU-friendly form (shapes stay static so jit
    never recompiles across batches of different ragged lengths), and
    the body routes through the segment-attention dispatcher — the
    Pallas segment-masked flash kernel with inter-document block
    skipping on TPU (kernels/flash_attention.py), the grouped-GQA jnp
    reference elsewhere. No [H, T, T] score matrix materialises on the
    kernel path, and GQA no longer repeats k/v."""
    out = segment_attention_raw(
        q[None], k[None], v[None], seg_q[None], seg_k[None],
        pos_q[None], pos_k[None], causal=causal, scale=scale)
    return out[0]


def flash_attn_unpadded(query, key, value, cu_seqlens_q, cu_seqlens_k,
                        max_seqlen_q=None, max_seqlen_k=None, scale=None,
                        dropout=0.0, causal=False, return_softmax=False,
                        name=None):
    """paddle.nn.functional.flash_attn_unpadded parity: packed [T, H, D]
    tensors + cu_seqlens prefix sums -> (out, None)."""
    from ...ops._op import unwrap, wrap
    if dropout:
        raise NotImplementedError(
            "flash_attn_unpadded: attention dropout is not implemented on "
            "the varlen path (pass dropout=0.0)")
    if return_softmax:
        raise NotImplementedError(
            "flash_attn_unpadded: return_softmax=True is not supported "
            "(the packed softmax is never materialized)")
    cq, ck = unwrap(cu_seqlens_q), unwrap(cu_seqlens_k)
    tq = unwrap(query).shape[0]
    tk = unwrap(key).shape[0]
    # A prefix sum reaching PAST the packed tensor would silently
    # mis-segment every sequence after the overflow point (tokens it
    # claims don't exist); cu[-1] < T is the documented trailing-padding
    # convention and stays legal. Checked eagerly only — under a trace
    # the values are abstract and the mask math is still well-defined.
    for name, cu, t in (("cu_seqlens_q", cq, tq), ("cu_seqlens_k", ck, tk)):
        try:
            last = int(cu[-1])
        except (TypeError, jax.errors.ConcretizationTypeError):
            continue   # traced values: mask math stays well-defined
        if last > t:
            from ...core import enforce as E
            raise E.InvalidArgumentError(
                f"flash_attn_unpadded: {name}[-1] == {last} exceeds the "
                f"packed tensor length T == {t}; the prefix sums must "
                f"end at or before the token count (trailing tokens "
                f"past {name}[-1] are treated as padding)")
    seg_q = segment_ids_from_cu_seqlens(cq, tq)
    seg_k = segment_ids_from_cu_seqlens(ck, tk)
    out = _flash_varlen(query, key, value, wrap(seg_q), wrap(seg_k),
                        wrap(_local_positions(cq, seg_q, tq)),
                        wrap(_local_positions(ck, seg_k, tk)),
                        causal=bool(causal), scale=scale)
    return out, None


def flash_attn_qkvpacked(qkv, dropout=0.0, causal=False,
                         return_softmax=False, *, fixed_seed_offset=None,
                         rng_name="", training=True, name=None):
    """paddle flash_attn_qkvpacked parity (flash_attention.py:303):
    qkv [B, S, 3, H, D] -> (out, None)."""
    from ...ops._op import unwrap, wrap
    qkva = unwrap(qkv)
    q, k, v = (wrap(qkva[:, :, i]) for i in range(3))
    return flash_attention(q, k, v, dropout=dropout, causal=causal,
                           return_softmax=return_softmax, training=training)


def flash_attn_varlen_qkvpacked(qkv, cu_seqlens_q, cu_seqlens_k,
                                max_seqlen_q=None, max_seqlen_k=None,
                                scale=None, dropout=0.0, causal=False,
                                return_softmax=False, fixed_seed_offset=None,
                                rng_name="", varlen_padded=True, name=None):
    """paddle flash_attn_varlen_qkvpacked parity (flash_attention.py:594):
    packed qkv [T, 3, H, D] + cu_seqlens -> (out, None)."""
    from ...ops._op import unwrap, wrap
    qkva = unwrap(qkv)
    q, k, v = (wrap(qkva[:, i]) for i in range(3))
    return flash_attn_unpadded(q, k, v, cu_seqlens_q, cu_seqlens_k,
                               max_seqlen_q, max_seqlen_k, scale,
                               dropout=dropout, causal=causal,
                               return_softmax=return_softmax)


def flash_attention_with_sparse_mask(query, key, value,
                                     attn_mask_start_row_indices,
                                     attn_mask_start_row=0, dropout_p=0.0,
                                     is_causal=False, return_softmax=False,
                                     return_softmax_lse=False,
                                     return_seed_offset=False, training=True,
                                     name=None):
    """paddle flash_attention_with_sparse_mask parity
    (flash_attention.py:844). ``attn_mask_start_row_indices`` [B, H, S]
    gives, per key column j, the first query row that may NOT attend to it
    (rows >= start are masked). Composed with the causal mask when
    ``is_causal``; evaluated as a dense masked softmax (MXU path)."""
    from ...ops._op import unwrap, wrap
    if return_softmax or return_softmax_lse or return_seed_offset:
        raise NotImplementedError(
            "flash_attention_with_sparse_mask: softmax/lse/seed returns "
            "are not materialized on this path")
    q = unwrap(query)
    starts = unwrap(attn_mask_start_row_indices)
    sq = q.shape[1]
    rows = jnp.arange(sq)
    allowed = rows[None, None, :, None] < starts[:, :, None, :]  # [B,H,Sq,Sk]
    if is_causal:
        allowed = allowed & (rows[:, None] >= rows[None, :])[None, None]
    mask = wrap(allowed)
    out = scaled_dot_product_attention(
        query, key, value, mask,
        dropout_p=dropout_p if training else 0.0, is_causal=False,
        training=training)
    return out, None
