"""Mixture-of-Experts decoder LM family (DeepSeekMoE / Qwen2-MoE /
ERNIE-4.5-style, the BASELINE.json EP configs).

Reference capability: the PaddleNLP llm/ MoE recipes trained through the
reference's expert-parallel stack (incubate/distributed/models/moe/
moe_layer.py dispatch/combine + gate, fleet expert-parallel groups; the
gate's capacity_factor token dropping lives in
incubate/distributed/models/moe/gate/base_gate.py descendants).
TPU-native design, two dispatch modes:

- "capacity" (single-chip default): GShard capacity-based gather
  dispatch. Token slots scatter into a static [E, C] index grid
  (C = ceil(T*k/E * capacity_factor), lane-aligned), experts run
  batched [E, C, D] matmuls, outputs gather back per (token, k) slot.
  Compute scales with ACTIVE tokens (E*C ~ T*k*factor), not E*T — at
  DeepSeekMoE shapes (E=64, k=6) the dense form burns ~10x the active
  FLOPs. Over-capacity slots drop (token keeps its shared-expert path),
  the reference's capacity_factor semantics.
- "dense" (mesh/EP default): routing becomes two einsums against a
  one-hot combine tensor, so shapes stay static under jit and the expert
  axis shards over the mesh's 'ep' dimension (expert weights are
  [E, ...] arrays with E on 'ep'; XLA turns the dispatch einsum into an
  all-to-all over ICI). Exact (no drops); right when E is small or the
  expert axis is sharded and the einsum IS the a2a.

Fine-grained experts + a shared expert follow the DeepSeekMoE shape;
top-k routing carries the switch-style load-balancing auxiliary loss.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .llama import (_head_logits, _mm, _rms, apply_rope,
                    remat_policy)
from ..core import enforce as E
from ..nn.functional.attention import rope_tables as _rope_tables, sdpa_raw

__all__ = [
    "MoEConfig", "moe_tiny", "deepseek_moe_16b", "qwen2_moe_a14b",
    "ernie_4_5_a3b", "init_params", "forward", "forward_hidden", "loss_fn",
    "param_specs", "make_train_step", "count_params", "adamw_init",
    "moe_capacity", "init_cache", "prefill", "decode_step", "generate",
    "beam_search", "quantize_weights",
]


@dataclasses.dataclass
class MoEConfig:
    vocab_size: int = 32000
    hidden_size: int = 2048
    intermediate_size: int = 1408        # per routed expert
    shared_intermediate_size: int = 2816  # shared-expert MLP width
    num_hidden_layers: int = 24
    num_attention_heads: int = 16
    num_key_value_heads: int = 16
    num_experts: int = 64
    num_experts_per_tok: int = 6
    max_position_embeddings: int = 4096
    rms_norm_eps: float = 1e-6
    rope_theta: float = 10000.0
    router_aux_loss_coef: float = 0.001
    dtype: Any = jnp.bfloat16
    remat: bool = True
    # "full" recomputes everything; "dots" saves matmul outputs (viable
    # with capacity dispatch, where the saved expert activations are
    # C-sized, not T-sized).
    remat_policy: str = "full"
    # None = auto: "capacity" on a single device, "dense" under a mesh
    # (the dense dispatch einsum is what GSPMD lowers to the EP a2a).
    dispatch_mode: Optional[str] = None
    capacity_factor: float = 1.25
    # Blockwise fused CE for the single-device loss (the 102k-vocab
    # logits of the DeepSeekMoE family are ~840M materialized); mesh
    # losses keep the einsum head for vocab-parallel GSPMD sharding.
    fused_ce: bool = True

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads


def moe_tiny(**kw) -> MoEConfig:
    base = dict(vocab_size=256, hidden_size=64, intermediate_size=32,
                shared_intermediate_size=64, num_hidden_layers=2,
                num_attention_heads=4, num_key_value_heads=4,
                num_experts=4, num_experts_per_tok=2,
                max_position_embeddings=128, dtype=jnp.float32,
                remat=False, dispatch_mode="dense")
    base.update(kw)
    return MoEConfig(**base)


def deepseek_moe_16b(**kw) -> MoEConfig:
    """DeepSeekMoE-16B shapes (BASELINE config)."""
    base = dict(vocab_size=102400, hidden_size=2048,
                intermediate_size=1408, shared_intermediate_size=2816,
                num_hidden_layers=28, num_attention_heads=16,
                num_key_value_heads=16, num_experts=64,
                num_experts_per_tok=6, max_position_embeddings=4096)
    base.update(kw)
    return MoEConfig(**base)


def qwen2_moe_a14b(**kw) -> MoEConfig:
    """Qwen2-MoE-A14B shapes (BASELINE config)."""
    base = dict(vocab_size=151936, hidden_size=3584,
                intermediate_size=2560, shared_intermediate_size=20480,
                num_hidden_layers=28, num_attention_heads=28,
                num_key_value_heads=4, num_experts=64,
                num_experts_per_tok=8, max_position_embeddings=32768,
                rope_theta=1000000.0)
    base.update(kw)
    return MoEConfig(**base)


def ernie_4_5_a3b(**kw) -> MoEConfig:
    """ERNIE-4.5-style fine-grained MoE shapes (BASELINE north-star
    config family): many small routed experts + an always-on shared
    expert, GQA attention — same structural recipe this MoE core
    implements for DeepSeekMoE."""
    base = dict(vocab_size=103424, hidden_size=2560,
                intermediate_size=1536, shared_intermediate_size=3072,
                num_hidden_layers=28, num_attention_heads=20,
                num_key_value_heads=4, num_experts=64,
                num_experts_per_tok=6, max_position_embeddings=131072,
                rope_theta=500000.0)
    base.update(kw)
    return MoEConfig(**base)


# ---------------------------------------------------------------------------
# params
# ---------------------------------------------------------------------------

def init_params(config: MoEConfig, key) -> Dict[str, Any]:
    c = config
    hd, nh, nkv = c.head_dim, c.num_attention_heads, c.num_key_value_heads
    L, D, Fe, Fs = (c.num_hidden_layers, c.hidden_size,
                    c.intermediate_size, c.shared_intermediate_size)
    E, V = c.num_experts, c.vocab_size
    ks = jax.random.split(key, 12)

    def nrm(k, shape):
        return (jax.random.normal(k, shape, jnp.float32) * 0.02
                ).astype(c.dtype)

    return {
        "embed": nrm(ks[0], (V, D)),
        "layers": {
            "ln1": jnp.ones((L, D), c.dtype),
            "wq": nrm(ks[1], (L, D, nh * hd)),
            "wk": nrm(ks[2], (L, D, nkv * hd)),
            "wv": nrm(ks[3], (L, D, nkv * hd)),
            "wo": nrm(ks[4], (L, nh * hd, D)),
            "ln2": jnp.ones((L, D), c.dtype),
            # router in float32 (routing logits are precision-sensitive)
            "router": jax.random.normal(ks[5], (L, D, E),
                                        jnp.float32) * 0.02,
            # routed experts: [L, E, ...] with E on the ep mesh axis
            "e_gate": nrm(ks[6], (L, E, D, Fe)),
            "e_up": nrm(ks[7], (L, E, D, Fe)),
            "e_down": nrm(ks[8], (L, E, Fe, D)),
            # shared expert (always on — DeepSeekMoE)
            "s_gate": nrm(ks[9], (L, D, Fs)),
            "s_up": nrm(ks[10], (L, D, Fs)),
            "s_down": nrm(ks[11], (L, Fs, D)),
        },
        "ln_f": jnp.ones((D,), c.dtype),
        "lm_head": nrm(jax.random.fold_in(key, 7), (V, D)),
    }


# ---------------------------------------------------------------------------
# MoE block
# ---------------------------------------------------------------------------

def _edeq(w, dtype):
    """Expert-grid weight for the batched einsums: plain array, or the
    weight-only form {"q": int8 [E, in, out], "s": f32 [E, out]} (or
    its packed-int4 sibling {"q4": int8 [E, in/2, out], "s"})
    dequantized into the einsum (the convert fuses under XLA, so HBM
    reads stay int8/int4 — same seam as llama's _mm, including its
    dequant ordering: f32 multiply, ONE cast, so the f32 scale is
    never double-rounded through bf16)."""
    if isinstance(w, dict):
        from .llama import unpack_int4
        q = unpack_int4(w["q4"], -2) if "q4" in w else w["q"]
        return (q.astype(jnp.float32)
                * w["s"][:, None, :]).astype(dtype)
    return w


def quantize_weights(params, weight_dtype: str = "int8"):
    """Weight-only quantization (int8 or packed int4) of a MoE params
    pytree for serving (see llama.quantize_weights). Attention,
    shared-expert, per-expert grids, and the lm head quantize per
    out-channel; the router stays float32 (routing logits are
    precision-sensitive) and the embedding stays full precision
    (gathered, not matmul'd)."""
    from .llama import quant_packed   # the one scheme definition

    out = {"embed": params["embed"], "ln_f": params["ln_f"],
           "layers": {}}
    for name, w in params["layers"].items():
        if name.startswith("ln") or name == "router":
            out["layers"][name] = w
        elif name.startswith("e_"):            # [L, E, in, out]
            out["layers"][name] = quant_packed(
                w, in_axis=2, weight_dtype=weight_dtype)
        else:                                  # [L, in, out]
            out["layers"][name] = quant_packed(
                w, in_axis=1, weight_dtype=weight_dtype)
    out["lm_head"] = quant_packed(params["lm_head"], in_axis=1,
                                  weight_dtype=weight_dtype)
    return out


def moe_capacity(config: MoEConfig, n_tokens: int) -> int:
    """Per-expert slot count: ceil(T*k/E * factor), lane-aligned (128)."""
    c = config
    even = n_tokens * c.num_experts_per_tok / c.num_experts
    cap = int(even * c.capacity_factor + 0.9999)
    return max(8, min(n_tokens, (cap + 127) // 128 * 128 if cap >= 128
                      else cap))


def _route(x, lp, config: MoEConfig):
    """Shared router head: (topv [T,k] normalized f32, topi [T,k], aux)."""
    c = config
    logits = (x.astype(jnp.float32) @ lp["router"])         # [T, E]
    probs = jax.nn.softmax(logits, axis=-1)
    topv, topi = lax.top_k(probs, c.num_experts_per_tok)    # [T, k]
    topv = topv / jnp.sum(topv, axis=-1, keepdims=True)     # renormalize
    # switch-style load-balance aux loss (reference: moe gate aux):
    # fraction of ROUTED token-slots per expert x mean router prob
    sel = jnp.sum(jax.nn.one_hot(topi, c.num_experts, dtype=jnp.float32),
                  axis=1)                                   # [T, E] 0/1
    me = jnp.mean(probs, axis=0)                            # [E]
    ce = jnp.mean(sel, axis=0)
    aux = c.num_experts * jnp.sum(me * ce)
    return topv, topi, aux


def _expert_ffn(xe, lp):
    """Batched per-expert SwiGLU on [E, C|T, D] slot grids."""
    g = jnp.einsum("ecd,edf->ecf", xe, _edeq(lp["e_gate"], xe.dtype))
    u = jnp.einsum("ecd,edf->ecf", xe, _edeq(lp["e_up"], xe.dtype))
    return jnp.einsum("ecf,efd->ecd", jax.nn.silu(g) * u,
                      _edeq(lp["e_down"], xe.dtype))


def _moe_mlp_capacity(x, lp, config: MoEConfig, T):
    """Capacity gather dispatch (single-chip default): compute scales
    with E*C ~ T*k*capacity_factor instead of E*T."""
    c = config
    E, k = c.num_experts, c.num_experts_per_tok
    C = moe_capacity(c, T)
    topv, topi, aux = _route(x, lp, c)

    # Slot bookkeeping in token-major priority order (GShard): pos[t,k] =
    # how many earlier slots chose the same expert == position in that
    # expert's buffer. Over-capacity slots drop.
    oh = jax.nn.one_hot(topi.reshape(-1), E, dtype=jnp.int32)  # [T*k, E]
    pos = jnp.sum((jnp.cumsum(oh, axis=0) - oh) * oh, axis=-1)  # [T*k]
    expert = topi.reshape(-1)                                   # [T*k]
    keep = pos < C
    dest = expert * C + pos                                     # [T*k]

    # Scatter each kept slot's TOKEN INDEX into the [E*C] grid; empty
    # slots point at the appended zero row of xp (index T).
    idx = jnp.full((E * C,), T, jnp.int32)
    idx = idx.at[jnp.where(keep, dest, E * C)].set(
        jnp.repeat(jnp.arange(T, dtype=jnp.int32), k), mode="drop")
    xp = jnp.concatenate([x, jnp.zeros((1, x.shape[1]), x.dtype)])
    xe = jnp.take(xp, idx, axis=0).reshape(E, C, -1)            # [E, C, D]

    y = _expert_ffn(xe, lp)                                     # [E, C, D]

    # Combine: each (t, k) slot gathers its expert output row, scaled by
    # its (still-normalized) router weight; dropped slots contribute 0.
    yk = jnp.take(y.reshape(E * C, -1), jnp.where(keep, dest, 0), axis=0)
    w = (topv.reshape(-1) * keep).astype(jnp.float32)[:, None]
    routed = jnp.sum((yk.astype(jnp.float32) * w).reshape(T, k, -1),
                     axis=1)
    return routed.astype(x.dtype), aux


def _moe_mlp_dense(x, lp, config: MoEConfig, T, mesh):
    """GShard dense dispatch: combine[t, e] carries top-k router weights;
    expert compute is an einsum over the (sharded) expert axis."""
    c = config
    topv, topi, aux = _route(x, lp, c)
    combine = jnp.zeros((T, c.num_experts), jnp.float32).at[
        jnp.arange(T)[:, None], topi].set(topv)             # [T, E]

    constrain = (lambda a, spec: lax.with_sharding_constraint(
        a, NamedSharding(mesh, spec))) if mesh is not None \
        else (lambda a, spec: a)

    # dispatch with the BINARY routing mask (each selected expert sees the
    # unscaled token), combine with the router weights — gates scale
    # expert OUTPUTS, the DeepSeekMoE/GShard semantics (scaling the input
    # of a nonlinear expert would compute a different function)
    dispatch = (combine > 0).astype(c.dtype)                # [T, E]
    xe = jnp.einsum("td,te->etd", x.astype(c.dtype), dispatch)
    xe = constrain(xe, P("ep", None, None))
    y = constrain(_expert_ffn(xe, lp), P("ep", None, None))
    routed = jnp.einsum("etd,te->td", y.astype(jnp.float32),
                        combine)                            # weighted combine
    return routed.astype(x.dtype), aux


def _moe_mlp(h, lp, config: MoEConfig, mesh):
    """Top-k routed experts + shared expert. Returns (out, aux_loss)."""
    c = config
    B, S, D = h.shape
    T = B * S
    x = h.reshape(T, D)

    mode = c.dispatch_mode or ("dense" if mesh is not None else "capacity")
    if mode not in ("dense", "capacity"):
        raise E.InvalidArgumentError(
            f"dispatch_mode must be 'dense' or 'capacity', got {mode!r}")
    if mode == "capacity":
        routed, aux = _moe_mlp_capacity(x, lp, c, T)
    else:
        routed, aux = _moe_mlp_dense(x, lp, c, T, mesh)

    sg = _mm(x, lp["s_gate"])
    su = _mm(x, lp["s_up"])
    shared = _mm(jax.nn.silu(sg) * su, lp["s_down"])

    return (routed + shared).reshape(B, S, D).astype(h.dtype), aux


def decode_mlp(x, lp, config: MoEConfig):
    """Post-attention half of a decode-path layer (ln2 + routed/shared
    MoE MLP + residual) — the family seam inference/paged.py composes
    with (see llama.decode_mlp). Router aux loss is dropped: serving
    never backprops."""
    h2 = _rms(x, lp["ln2"], config.rms_norm_eps)
    out, _ = _moe_mlp(h2, lp, config, None)
    return x + out


def _head(params, config: MoEConfig):
    """lm-head weight (uniform accessor with llama._head — the MoE
    families never tie embeddings)."""
    return params["lm_head"]


def _block(x, lp, cos, sin, config: MoEConfig, mesh,
           segment_ids=None, positions=None):
    c = config
    B, S, D = x.shape
    nh, nkv, hd = c.num_attention_heads, c.num_key_value_heads, c.head_dim

    h = _rms(x, lp["ln1"], c.rms_norm_eps)
    q = _mm(h, lp["wq"]).reshape(B, S, nh, hd)
    k = _mm(h, lp["wk"]).reshape(B, S, nkv, hd)
    v = _mm(h, lp["wv"]).reshape(B, S, nkv, hd)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)
    a = sdpa_raw(q, k, v, is_causal=True, segment_ids=segment_ids,
                 positions=positions).reshape(B, S, nh * hd)
    x = x + _mm(a, lp["wo"])

    h = _rms(x, lp["ln2"], c.rms_norm_eps)
    moe_out, aux = _moe_mlp(h, lp, c, mesh)
    return x + moe_out, aux


def forward_hidden(params, ids, config: MoEConfig, *,
                   mesh: Optional[Mesh] = None, segment_ids=None,
                   positions=None):
    """(final hidden [B,S,D] post ln_f, summed aux loss).
    ``segment_ids``/``positions`` [B, S] select sequence-packed
    semantics — segment-masked attention and per-document rope
    positions, exactly as in the llama family."""
    c = config
    x = jnp.take(params["embed"], ids, axis=0)
    cos, sin = _rope_tables(ids.shape[1], c.head_dim, theta=c.rope_theta)
    if positions is not None:
        from ..nn.functional.attention import gather_rope_rows
        cos, sin = gather_rope_rows(cos, sin, positions)

    def step(carry, lp):
        y, aux = _block(carry, lp, cos, sin, c, mesh,
                        segment_ids, positions)
        return y, aux

    if c.remat:
        step = jax.checkpoint(step, prevent_cse=False,
                              policy=remat_policy(c.remat_policy))
    x, auxes = lax.scan(step, x, params["layers"])
    return _rms(x, params["ln_f"], c.rms_norm_eps), jnp.sum(auxes)


def forward(params, ids, config: MoEConfig, *,
            mesh: Optional[Mesh] = None, segment_ids=None, positions=None):
    """Returns (logits [B,S,V], aux_loss scalar)."""
    x, aux = forward_hidden(params, ids, config, mesh=mesh,
                            segment_ids=segment_ids, positions=positions)
    logits = _head_logits(x, params["lm_head"])
    return logits, aux


# ---------------------------------------------------------------------------
# KV-cache decoding (serving path for the MoE families; same static
# ring-buffer design as models.llama — see the design note there)
# ---------------------------------------------------------------------------

def init_cache(config: MoEConfig, batch: int, max_len: int, dtype=None):
    """Fresh decode cache (same layout as the llama family's)."""
    from .llama import init_cache as _ic
    return _ic(config, batch, max_len, dtype)   # shared field contract


def prefill(params, ids, config: MoEConfig, cache):
    """Consume the prompt [B, S]: fills cache[:, :, :S] and returns
    (cache', last-position logits [B, V])."""
    from .llama import _qkv_proj
    c = config
    B, S = ids.shape
    E.enforce(S <= cache["k"].shape[2],
              f"prompt length {S} exceeds cache max_len "
              f"{cache['k'].shape[2]}")
    x = jnp.take(params["embed"], ids, axis=0)
    cos, sin = _rope_tables(S, c.head_dim, theta=c.rope_theta)

    def step(carry, lp):
        x = carry
        h = _rms(x, lp["ln1"], c.rms_norm_eps)
        q, k, v = _qkv_proj(h, lp, c)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        a = sdpa_raw(q, k, v, is_causal=True).reshape(B, S, -1)
        x = x + _mm(a, lp["wo"])
        h2 = _rms(x, lp["ln2"], c.rms_norm_eps)
        out, _ = _moe_mlp(h2, lp, c, None)
        return x + out, (k, v)

    x, (ks, vs) = lax.scan(step, x, params["layers"])
    kc = lax.dynamic_update_slice(
        cache["k"], ks.astype(cache["k"].dtype), (0,) * 5)
    vc = lax.dynamic_update_slice(
        cache["v"], vs.astype(cache["v"].dtype), (0,) * 5)
    x = _rms(x, params["ln_f"], c.rms_norm_eps)
    logits = _head_logits(x[:, -1, :], params["lm_head"])
    return {"k": kc, "v": vc, "pos": jnp.asarray(S, jnp.int32)}, logits


def decode_step(params, cache, token, config: MoEConfig):
    """One incremental step: ``token`` [B] sits at position cache['pos'].
    Routing runs per decoded token (T = B), so under
    dispatch_mode="capacity" the grid is [E, C] with C =
    moe_capacity(config, B) — typically DROPLESS at small batch, but not
    guaranteed: a slot overflows whenever more than C of the B tokens
    route one of their top-k picks to the same expert (C ~
    ceil(B*k/E * capacity_factor), so a routing hot spot at large B can
    exceed it; only C >= B makes dropping impossible). An over-capacity
    pick silently falls back to the token's shared-expert path, which
    shifts decode logits relative to training. Use dispatch_mode="dense"
    (exact) when serving large batches with skewed routing. Returns
    (cache', logits [B, V])."""
    from .llama import _attn_over_cache, _qkv_proj
    from ..nn.functional.attention import rope_raw
    c = config
    pos = cache["pos"]
    M = cache["k"].shape[2]
    x = jnp.take(params["embed"], token, axis=0)[:, None, :]   # [B, 1, D]
    cos_t, sin_t = _rope_tables(M, c.head_dim, theta=c.rope_theta)
    cos = lax.dynamic_slice_in_dim(cos_t, pos, 1, 0)
    sin = lax.dynamic_slice_in_dim(sin_t, pos, 1, 0)

    def step(carry, xs):
        x = carry
        lp, kc, vc = xs
        h = _rms(x, lp["ln1"], c.rms_norm_eps)
        q, k, v = _qkv_proj(h, lp, c)
        q = rope_raw(q, cos, sin)
        k = rope_raw(k, cos, sin)
        kc = lax.dynamic_update_slice_in_dim(
            kc, k.astype(kc.dtype), pos, 1)
        vc = lax.dynamic_update_slice_in_dim(
            vc, v.astype(vc.dtype), pos, 1)
        a = _attn_over_cache(q, kc, vc, pos)
        x = x + _mm(a.astype(x.dtype), lp["wo"])
        h2 = _rms(x, lp["ln2"], c.rms_norm_eps)
        out, _ = _moe_mlp(h2, lp, c, None)
        return x + out, (kc, vc)

    x, (kc, vc) = lax.scan(step, x,
                           (params["layers"], cache["k"], cache["v"]))
    x = _rms(x, params["ln_f"], c.rms_norm_eps)
    logits = _head_logits(x[:, 0, :], params["lm_head"])
    return {"k": kc, "v": vc, "pos": pos + 1}, logits


def generate(params, ids, config: MoEConfig, *, max_new_tokens: int,
             max_len: Optional[int] = None, temperature: float = 0.0,
             top_k: Optional[int] = None, top_p: Optional[float] = None,
             eos_token_id: Optional[int] = None, pad_token_id: int = 0,
             key=None):
    """Autoregressive generation for the MoE families (greedy /
    temperature / top-k / top-p / EOS stopping); the shared jit-once
    static loop (llama._generate_over)."""
    from .llama import _generate_over
    return _generate_over(
        init_cache, prefill, decode_step, params, ids, config,
        max_new_tokens=max_new_tokens, max_len=max_len,
        temperature=temperature, top_k=top_k, top_p=top_p,
        eos_token_id=eos_token_id, pad_token_id=pad_token_id, key=key)


def beam_search(params, ids, config: MoEConfig, *, max_new_tokens: int,
                num_beams: int, max_len: Optional[int] = None,
                length_penalty: float = 0.0,
                eos_token_id: Optional[int] = None, pad_token_id: int = 0):
    """Static-shape beam search for the MoE families (shared loop —
    see llama.beam_search)."""
    from .llama import _beam_search_over
    return _beam_search_over(
        init_cache, prefill, decode_step, params, ids, config,
        max_new_tokens=max_new_tokens, num_beams=num_beams,
        max_len=max_len, length_penalty=length_penalty,
        eos_token_id=eos_token_id, pad_token_id=pad_token_id)


def loss_fn(params, batch, config: MoEConfig, *,
            mesh: Optional[Mesh] = None):
    """Causal-LM CE + router aux loss. Accepts every llama
    ``unpack_batch`` form, including sequence-packed
    (inp, labels, segment_ids, positions) rows whose labels carry the
    ignore_index at cross-document / padding positions."""
    from .llama import unpack_batch
    inp, labels, seg, pos = unpack_batch(batch)
    c = config
    if c.fused_ce and mesh is None:
        # Blockwise fused CE: the [B,S,V] logits (~840M f32 at the
        # DeepSeekMoE 102k vocab) never materialize in HBM. Same
        # dispatcher as the llama family (autotuned vocab chunk).
        from ..kernels import dispatched_fused_ce

        x, aux = forward_hidden(params, inp, c, mesh=mesh,
                                segment_ids=seg, positions=pos)
        ce = dispatched_fused_ce(x, params["lm_head"], labels)
        return ce + c.router_aux_loss_coef * aux
    logits, aux = forward(params, inp, c, mesh=mesh, segment_ids=seg,
                          positions=pos)
    # the same ignore_index masking as the fused path (packed batches
    # mark cross-document targets and padding with -100)
    from ..kernels.fused_ce import masked_xent_from_logits
    ce = masked_xent_from_logits(logits, labels)
    return ce + c.router_aux_loss_coef * aux


# ---------------------------------------------------------------------------
# sharding + train step
# ---------------------------------------------------------------------------

def param_specs(config: MoEConfig) -> Dict[str, Any]:
    """Placements over a ('dp','fsdp','ep','tp') mesh: expert weights put
    E on 'ep' (expert parallelism) and the expert FFN dims on 'tp'/'fsdp';
    dense weights follow the Megatron/fsdp layout of the llama family."""
    return {
        "embed": P("tp", "fsdp"),
        "layers": {
            "ln1": P(None, None),
            "wq": P(None, "fsdp", "tp"),
            "wk": P(None, "fsdp", "tp"),
            "wv": P(None, "fsdp", "tp"),
            "wo": P(None, "tp", "fsdp"),
            "ln2": P(None, None),
            "router": P(None, "fsdp", None),
            "e_gate": P(None, "ep", "fsdp", "tp"),
            "e_up": P(None, "ep", "fsdp", "tp"),
            "e_down": P(None, "ep", "tp", "fsdp"),
            "s_gate": P(None, "fsdp", "tp"),
            "s_up": P(None, "fsdp", "tp"),
            "s_down": P(None, "tp", "fsdp"),
        },
        "ln_f": P(None),
        "lm_head": P("tp", "fsdp"),
    }


def count_params(config: MoEConfig) -> int:
    import numpy as np
    c = config
    dummy = jax.eval_shape(lambda: init_params(c, jax.random.key(0)))
    return int(sum(np.prod(l.shape) for l in jax.tree.leaves(dummy)))


def adamw_init(params):
    from .llama import adamw_init as _ai
    return _ai(params)


def make_train_step(config: MoEConfig, mesh: Optional[Mesh] = None, *,
                    lr: float = 1e-4, donate: bool = True,
                    guard: Optional[bool] = None,
                    numerics: Optional[bool] = None):
    """Jitted AdamW train step; with a mesh, params/opt-state placements
    come from param_specs and the batch shards over ('dp','fsdp').
    Buffer donation updates params/opt-state in place — without it the
    step holds BOTH generations of the expert weights, which at MoE
    sizes is the difference between fitting and OOM.

    ``guard`` (default: ``FLAGS_enable_sentinel``) builds the GUARDED
    4-in/4-out step — identical contract to the llama family's (see
    ``llama.make_train_step``): the update gates on
    ``llama.step_health``'s ok flag behind a ``lax.cond``, anomalous
    steps leave params/opt-state byte-identical, and the health aux
    scalars feed ``training.sentinel``. ``numerics`` (default:
    ``FLAGS_enable_numerics``; guarded step only) adds the in-graph
    per-layer grad statistics block — same contract as the llama
    family's."""
    from .llama import _adamw_update, unpack_batch
    from ..training.guards import (gated_update, grad_numerics,
                                   resolve_guard, resolve_numerics,
                                   step_health)
    guard = resolve_guard(guard)
    numerics = guard and resolve_numerics(numerics)

    def grads_of(params, batch):
        return jax.value_and_grad(
            lambda p: loss_fn(p, batch, config, mesh=mesh))(params)

    def update(p, o, g):
        return _adamw_update(p, g, o, lr)

    def step(params, opt_state, batch):
        loss, grads = grads_of(params, batch)
        params, opt_state = update(params, opt_state, grads)
        return params, opt_state, loss

    def guarded_step(params, opt_state, batch, gnorm_cap):
        loss, grads = grads_of(params, batch)
        ok, health = step_health(loss, grads, unpack_batch(batch)[0],
                                 config.vocab_size, gnorm_cap)
        if numerics:
            health["numerics"] = grad_numerics(grads)
        params, opt_state = gated_update(ok, update, params, opt_state,
                                         grads)
        return params, opt_state, loss, health

    dn = (0, 1) if donate else ()
    if mesh is None:
        return jax.jit(guarded_step if guard else step, donate_argnums=dn)

    specs = param_specs(config)
    pshard = jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                          is_leaf=lambda s: isinstance(s, P))
    bshard = NamedSharding(mesh, P(("dp", "fsdp"), None))

    if guard:
        def placed_guarded(params, opt_state, batch, gnorm_cap):
            params = jax.lax.with_sharding_constraint(params, pshard)
            batch = jax.lax.with_sharding_constraint(batch, bshard)
            return guarded_step(params, opt_state, batch, gnorm_cap)

        return jax.jit(placed_guarded, donate_argnums=dn)

    def placed(params, opt_state, batch):
        params = jax.lax.with_sharding_constraint(params, pshard)
        batch = jax.lax.with_sharding_constraint(batch, bshard)
        return step(params, opt_state, batch)

    return jax.jit(placed, donate_argnums=dn)
