"""Llama model family — the flagship decoder LM, TPU-first.

Reference capability: the PaddleNLP llm/ Llama recipe trained through the
reference's hybrid-parallel stack (SURVEY.md §6 north star; reference
components: fleet/layers/mpu/mp_layers.py TP layers,
nn/functional/flash_attention.py, incubate fused_rms_norm / fused rope).

TPU-native design — two coupled implementations of the same math:

1. **Functional core** (`init_params` / `forward` / `loss_fn` /
   `make_train_step`): pure JAX over a parameter pytree. Layers are stacked
   along a leading axis and iterated with ``lax.scan`` (one trace for all
   layers — fast compiles at depth), each step wrapped in ``jax.checkpoint``
   (rematerialisation: trade FLOPs for HBM, the reference's recompute
   pass). Sharding is GSPMD: `param_specs` gives per-leaf PartitionSpecs
   over a ('dp','fsdp','tp') mesh (Megatron TP column/row splits expressed
   as weight placements; ZeRO-3 as fsdp sharding), activations constrained
   with `with_sharding_constraint` (sequence-parallel constraint on the
   residual stream when `sp=True`).

2. **Eager Layer model** (`LlamaForCausalLM`): nn.Layer composition for
   imperative training/fine-tuning parity (`model(ids).backward()`), built
   from the framework's RMSNorm/Linear/Embedding layers and the same
   attention kernel seam (F.scaled_dot_product_attention → flash kernel).
"""
from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.ad_checkpoint import checkpoint_name
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import nn
from ..nn import functional as F
from ..core import enforce as E
from ..training.guards import (gated_update, grad_global_norm,
                               grad_numerics, resolve_guard,
                               resolve_numerics, step_health)
from ..nn.functional.attention import (gather_rope_rows as _gather_rope_rows,
                                       rope_raw, rope_tables as _rope_tables,
                                       sdpa_raw)

__all__ = [
    "LlamaConfig", "llama_tiny", "llama_3_8b",
    "init_params", "forward", "loss_fn", "param_specs", "unpack_batch",
    "make_train_step", "make_forward", "adamw_init", "count_params",
    "grad_global_norm",
    "LlamaForCausalLM",
    "init_cache", "prefill", "decode_step", "generate", "make_sampler",
    "beam_search", "quantize_weights", "quant_int8", "quant_packed",
    "unpack_int4",
]


@dataclasses.dataclass
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 14336
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 8
    max_position_embeddings: int = 8192
    rms_norm_eps: float = 1e-5
    rope_theta: float = 500000.0
    tie_word_embeddings: bool = False
    dtype: Any = jnp.bfloat16       # params/activations dtype (MXU-friendly)
    remat: bool = True              # per-layer rematerialisation
    # remat policy: "full" recomputes everything (min HBM); "dots" saves
    # non-batch matmul outputs (reference recompute's selective checkpointing
    # — fewer recomputed FLOPs, higher MFU, modest extra HBM); "attn"
    # saves only the named attention outputs (2*B*S*D bytes/layer) so the
    # backward never re-runs the flash kernel but everything else still
    # rematerialises — the sweet spot when HBM is tight or the XLA
    # program size under "dots" is a problem (the axon tunnel's remote
    # compile helper rejects the "dots" program at bench shapes).
    remat_policy: str = "dots"
    # Blockwise lm-head cross entropy (kernels/fused_ce.py): the [B,S,V]
    # logits never hit HBM. Engaged on the single-device path; the GSPMD
    # multi-device loss keeps the einsum head (vocab-parallel sharding of
    # the scan-chunked head is not yet wired).
    fused_ce: bool = True
    # None: the vocab-chunk comes from the autotune cache (measured per
    # shape on TPU). An explicit int is respected verbatim — set it to
    # cap loss-path HBM regardless of what tuning found fastest.
    fused_ce_chunk: Optional[int] = None

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads


def llama_tiny(**kw) -> LlamaConfig:
    """Small config for tests/dryruns."""
    base = dict(vocab_size=256, hidden_size=64, intermediate_size=128,
                num_hidden_layers=2, num_attention_heads=4,
                num_key_value_heads=2, max_position_embeddings=128,
                rope_theta=10000.0, dtype=jnp.float32, remat=False)
    base.update(kw)
    return LlamaConfig(**base)


def llama_3_8b(**kw) -> LlamaConfig:
    """Llama-3-8B shapes (the BASELINE.json north-star recipe)."""
    base = dict(vocab_size=128256, hidden_size=4096, intermediate_size=14336,
                num_hidden_layers=32, num_attention_heads=32,
                num_key_value_heads=8, max_position_embeddings=8192,
                rope_theta=500000.0)
    base.update(kw)
    return LlamaConfig(**base)


# ---------------------------------------------------------------------------
# Functional core
# ---------------------------------------------------------------------------

def init_params(config: LlamaConfig, key) -> Dict[str, Any]:
    """Parameter pytree. Per-layer weights are stacked on axis 0 (scan
    layout). Initialisation mirrors the reference Llama recipe:
    normal(0, 0.02) for projections/embeddings, ones for norms."""
    c = config
    hd, nh, nkv = c.head_dim, c.num_attention_heads, c.num_key_value_heads
    L, D, Ff, V = c.num_hidden_layers, c.hidden_size, c.intermediate_size, c.vocab_size
    ks = jax.random.split(key, 8)

    def nrm(k, shape, fan_in):
        std = 0.02
        return (jax.random.normal(k, shape, jnp.float32) * std).astype(c.dtype)

    params = {
        "embed": nrm(ks[0], (V, D), D),
        "layers": {
            "ln1": jnp.ones((L, D), c.dtype),
            "wq": nrm(ks[1], (L, D, nh * hd), D),
            "wk": nrm(ks[2], (L, D, nkv * hd), D),
            "wv": nrm(ks[3], (L, D, nkv * hd), D),
            "wo": nrm(ks[4], (L, nh * hd, D), nh * hd),
            "ln2": jnp.ones((L, D), c.dtype),
            "gate": nrm(ks[5], (L, D, Ff), D),
            "up": nrm(ks[6], (L, D, Ff), D),
            "down": nrm(ks[7], (L, Ff, D), Ff),
        },
        "ln_f": jnp.ones((D,), c.dtype),
    }
    if not c.tie_word_embeddings:
        params["lm_head"] = nrm(jax.random.fold_in(key, 99), (V, D), D)
    return params


def remat_policy(name: str):
    """Resolve a config remat-policy name to a jax.checkpoint policy
    (one definition shared by every model family — llama, moe, ...):
    "full" recomputes everything, "dots" saves non-batch matmul outputs,
    "attn" saves only values tagged checkpoint_name("attn_out")."""
    policies = {
        "dots": jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
        "attn": jax.checkpoint_policies.save_only_these_names("attn_out"),
        "full": None,
    }
    if name not in policies:
        raise E.InvalidArgumentError(
            f"remat_policy must be one of {sorted(policies)}, got {name!r}")
    return policies[name]


def rope_tables(config: LlamaConfig, seq_len: int, dtype=jnp.float32):
    """cos/sin tables [S, head_dim//2] (shared helper, config theta)."""
    return _rope_tables(seq_len, config.head_dim, theta=config.rope_theta,
                        dtype=dtype)


# rotate-half application shared with the eager op (single rope source)
apply_rope = rope_raw


def _rms(x, w, eps):
    xf = x.astype(jnp.float32)
    y = xf * jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return (y * w.astype(jnp.float32)).astype(x.dtype)


def _act_spec(sp: bool):
    # residual stream [B, S, D]: batch over dp+fsdp; seq over tp when
    # sequence-parallel (Megatron-SP: norm/elementwise regions run seq-sharded,
    # GSPMD inserts the allgather/reduce-scatter at the matmul boundaries).
    return P(("dp", "fsdp"), "tp" if sp else None, None)


def _noc(a, spec):
    """No-op sharding constraint (single-device paths)."""
    return a


def _mm(x, w):
    """Matmul against a weight that is either a plain array or a
    weight-only-quantized {"q": int8 [in, out], "s": f32 [out]} dict
    (reference: nn/quant weight_only_linear). The dequant fuses into
    the dot under XLA, so HBM reads stay int8 — on the HBM-bound decode
    path that halves the weight traffic.

    Dequant ordering matters for SQNR: the q*s multiply runs in f32
    with ONE cast to the activation dtype. The old
    ``q.astype(bf16) * s.astype(bf16)`` rounded the f32 scale AND the
    product — double rounding that measurably degraded bf16 SQNR
    (caught by the monitor/numerics.py quantization auditor, pinned
    by tests/test_numerics.py)."""
    if isinstance(w, dict):
        q = unpack_int4(w["q4"], -2) if "q4" in w else w["q"]
        return x @ (q.astype(jnp.float32)
                    * w["s"][None, :]).astype(x.dtype)
    return x @ w


def _head_logits(x2d, head):
    """lm-head logits [.., V] from hidden [.., D]; head is [V, D] (or
    its weight-only form {"q": int8 [V, D], "s": f32 [V]})."""
    if isinstance(head, dict):
        # f32 multiply, one cast — the _mm dequant-ordering contract
        q = unpack_int4(head["q4"], -1) if "q4" in head else head["q"]
        w = (q.astype(jnp.float32)
             * head["s"][:, None]).astype(x2d.dtype)
    else:
        w = head
    return jnp.einsum("...d,vd->...v", x2d, w,
                      preferred_element_type=jnp.float32)


def quantize_weights(params, weight_dtype: str = "int8"):
    """Weight-only quantization of a llama params pytree for serving
    (reference: paddle.nn.quant.weight_quantize applied by the
    inference pipelines). Every matmul weight — per-layer attention and
    MLP matrices and the lm head — becomes {"q": int8, "s": f32
    per-out-channel scale} (``weight_dtype="int8"``) or {"q4": two
    int4 nibbles packed per int8 byte along the contraction dim,
    "s": f32} (``weight_dtype="int4"``); the embedding stays full
    precision (it is gathered, not matmul'd; with tied embeddings it
    therefore also serves the head in full precision). The quantized
    tree drops into forward / prefill / decode_step / generate /
    beam_search unchanged — the dequant seams key off the leaf's dict
    shape, a static pytree property."""
    out = {"embed": params["embed"], "layers": {},
           "ln_f": params["ln_f"]}
    for name, w in params["layers"].items():
        if name.startswith("ln"):
            out["layers"][name] = w
            continue
        out["layers"][name] = quant_packed(w, in_axis=1,
                                           weight_dtype=weight_dtype)
    if "lm_head" in params:
        out["lm_head"] = quant_packed(params["lm_head"], in_axis=1,
                                      weight_dtype=weight_dtype)
    return out


def quant_int8(w, in_axis: int):
    """Per-out-channel absmax int8 quantization of a stacked weight:
    the ONE scheme definition every family's quantize_weights and every
    dequant seam (_mm / _edeq / _head_logits) must agree on for the
    quantized-vs-dequantized bit-exact contract. Reduces |w| over
    ``in_axis`` (the contraction dim); returns {"q": int8, "s": f32
    with the reduced axis dropped}."""
    wf = w.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(wf), axis=in_axis, keepdims=True)
    s = absmax / 127.0
    q = jnp.clip(jnp.round(wf / jnp.maximum(s, 1e-10)),
                 -127, 127).astype(jnp.int8)
    return {"q": q, "s": jnp.squeeze(s, in_axis)}


def quant_packed(w, in_axis: int, weight_dtype: str = "int8"):
    """The family-generic weight-only quantizer: ``quant_int8``
    generalized over the code width under the SAME one-scheme
    per-out-channel absmax contract (reduce |w| over ``in_axis``,
    symmetric scale, round-to-nearest, f32-multiply dequant with ONE
    cast).

    - ``"int8"``: {"q": int8, "s"} — exactly :func:`quant_int8`.
    - ``"int4"``: scale = absmax/7, codes clipped to [-8, 7], then two
      consecutive codes along ``in_axis`` pack into one int8 byte
      (even index -> low nibble, odd -> high nibble — the
      nn/quant weight-only layer's layout): {"q4": int8 with
      ``in_axis`` halved, "s"}. The distinct key name is the STATIC
      marker the dequant seams and the numerics auditor branch on —
      no traced metadata rides the tree."""
    if weight_dtype == "int8":
        return quant_int8(w, in_axis)
    E.enforce_eq(weight_dtype, "int4",
                 "weight-only serving supports int8 and packed int4",
                 error=E.UnimplementedError)
    in_axis = in_axis % w.ndim
    E.enforce(w.shape[in_axis] % 2 == 0,
              f"int4 packing needs an even contraction dim, got "
              f"{w.shape[in_axis]} on axis {in_axis} of {w.shape}")
    wf = w.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(wf), axis=in_axis, keepdims=True)
    s = absmax / 7.0
    q = jnp.clip(jnp.round(wf / jnp.maximum(s, 1e-10)),
                 -8, 7).astype(jnp.int8)
    lo = jax.lax.slice_in_dim(q, 0, None, stride=2, axis=in_axis)
    hi = jax.lax.slice_in_dim(q, 1, None, stride=2, axis=in_axis)
    packed = ((lo & 0x0F) | (hi << 4)).astype(jnp.int8)
    return {"q4": packed, "s": jnp.squeeze(s, in_axis)}


def unpack_int4(q4, in_axis: int):
    """Inverse of :func:`quant_packed`'s int4 nibble pack: sign-extend
    both nibbles of each byte (arithmetic shifts) and re-interleave
    along ``in_axis``, doubling it — int8 codes in [-8, 7], ready for
    the standard f32-multiply dequant. Fuses into the consuming dot
    under XLA, so HBM weight reads stay at 4 bits per value."""
    in_axis = in_axis % q4.ndim
    lo = jnp.left_shift(q4, 4).astype(jnp.int8) >> 4
    hi = q4 >> 4                     # arithmetic: sign-extends
    shape = list(q4.shape)
    shape[in_axis] *= 2
    return jnp.stack([lo, hi], axis=in_axis + 1).reshape(shape)


def _qkv_proj(h, lp, config: LlamaConfig, constrain=_noc):
    """Attention input projections [B,S,D] -> q/k/v head grids (no rope;
    callers position-encode: training uses the full table, decode the
    gathered row at the cache position). Heads shard over tp inside the
    attention region."""
    c = config
    B, S, _ = h.shape
    nh, nkv, hd = c.num_attention_heads, c.num_key_value_heads, c.head_dim
    q = constrain(_mm(h, lp["wq"]).reshape(B, S, nh, hd),
                  P(("dp", "fsdp"), None, "tp", None))
    k = constrain(_mm(h, lp["wk"]).reshape(B, S, nkv, hd),
                  P(("dp", "fsdp"), None, "tp", None))
    v = constrain(_mm(h, lp["wv"]).reshape(B, S, nkv, hd),
                  P(("dp", "fsdp"), None, "tp", None))
    return q, k, v


def _ffn(x, lp, config: LlamaConfig, sp: bool = False, constrain=_noc):
    """Post-attention half of a decoder layer (ln2 + SwiGLU + residual)."""
    c = config
    h = _rms(x, lp["ln2"], c.rms_norm_eps)
    g = constrain(_mm(h, lp["gate"]), P(("dp", "fsdp"), None, "tp"))
    u = constrain(_mm(h, lp["up"]), P(("dp", "fsdp"), None, "tp"))
    return x + constrain(_mm(jax.nn.silu(g) * u, lp["down"]),
                         _act_spec(sp))


def decode_mlp(x, lp, config: LlamaConfig):
    """Post-attention half of a decode-path layer (ln2 + SwiGLU +
    residual). The family seam the paged serving path
    (inference/paged.py) composes with: llama and the MoE family expose
    the same signature, so one paged prefill/decode implementation
    serves every decoder family."""
    return _ffn(x, lp, config)


def _block(x, lp, cos, sin, config: LlamaConfig, sp: bool, mesh,
           segment_ids=None, positions=None):
    """One decoder layer. x: [B, S, D]; lp: this layer's param slice."""
    c = config
    B, S, D = x.shape
    constrain = (lambda a, spec: lax.with_sharding_constraint(
        a, NamedSharding(mesh, spec))) if mesh is not None else _noc

    h = _rms(x, lp["ln1"], c.rms_norm_eps)
    q, k, v = _qkv_proj(h, lp, c, constrain)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)
    a = sdpa_raw(q, k, v, is_causal=True, segment_ids=segment_ids,
                 positions=positions)
    # Named so remat_policy="attn" can pin exactly this value: the one
    # tensor whose recompute (a full flash-attention forward) dominates
    # the backward pass under full remat, at 2*B*S*D bytes per layer.
    a = checkpoint_name(a, "attn_out")
    a = a.reshape(B, S, -1)
    x = x + constrain(_mm(a, lp["wo"]), _act_spec(sp))
    return _ffn(x, lp, c, sp, constrain)


def forward_hidden(params, ids, config: LlamaConfig, *, sp: bool = False,
                   mesh: Optional[Mesh] = None, segment_ids=None,
                   positions=None):
    """Final hidden states [B, S, D] (post ln_f) from token ids [B, S].

    ``segment_ids``/``positions`` [B, S] select sequence-packed
    semantics: rope positions restart per document and attention is
    segment-masked (see nn.functional.attention.sdpa_raw)."""
    c = config
    x = jnp.take(params["embed"], ids, axis=0)
    cos, sin = rope_tables(c, ids.shape[1])
    if positions is not None:
        # segment-local rope rows (sequence packing) via the shared
        # position_ids gather seam
        cos, sin = _gather_rope_rows(cos, sin, positions)

    def step(carry, lp):
        return _block(carry, lp, cos, sin, c, sp, mesh,
                      segment_ids, positions), None

    if c.remat:
        step = jax.checkpoint(step, prevent_cse=False,
                              policy=remat_policy(c.remat_policy))
    x, _ = lax.scan(step, x, params["layers"])
    return _rms(x, params["ln_f"], c.rms_norm_eps)


def _head(params, config: LlamaConfig):
    return params["embed"] if config.tie_word_embeddings \
        else params["lm_head"]


def forward(params, ids, config: LlamaConfig, *, sp: bool = False,
            mesh: Optional[Mesh] = None, segment_ids=None, positions=None):
    """Logits [B, S, V] from token ids [B, S]. Pure; jit/shard-ready."""
    x = forward_hidden(params, ids, config, sp=sp, mesh=mesh,
                       segment_ids=segment_ids, positions=positions)
    # logits in float32 for a stable softmax-xent
    return _head_logits(x, _head(params, config))


# ---------------------------------------------------------------------------
# KV-cache decoding (serving path)
#
# Reference capability: incremental decoding via per-layer K/V caches —
# python/paddle/nn/layer/transformer.py MultiHeadAttention.gen_cache /
# Cache (concat-grown) and the PaddleNLP llm generation loops built on
# it. TPU-native design: a STATIC [L, B, max_len, kv, hd] ring buffer
# written with lax.dynamic_update_slice and masked attention — shapes
# never change across steps, so the whole generate loop jits as one
# program (concat-grown caches would retrace/recompile every token).
# ---------------------------------------------------------------------------

def init_cache(config: LlamaConfig, batch: int, max_len: int, dtype=None):
    """Fresh decode cache for ``batch`` sequences of up to ``max_len``."""
    c = config
    dt = dtype if dtype is not None else c.dtype
    shape = (c.num_hidden_layers, batch, max_len, c.num_key_value_heads,
             c.head_dim)
    return {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt),
            "pos": jnp.zeros((), jnp.int32)}


def _attn_over_cache(q, kc, vc, pos):
    """Single-position attention against the cache. q: [B, 1, nh, hd];
    kc/vc: [B, M, nkv, hd]; positions > pos are masked out."""
    B, M, nkv, hd = kc.shape
    nh = q.shape[2]
    g = nh // nkv
    qf = q.astype(jnp.float32).reshape(B, nkv, g, hd)
    scores = jnp.einsum("bkgd,bmkd->bkgm", qf,
                        kc.astype(jnp.float32)) / math.sqrt(hd)
    mask = (jnp.arange(M) <= pos)[None, None, None, :]
    scores = jnp.where(mask, scores, -jnp.inf)
    p = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bkgm,bmkd->bkgd", p, vc.astype(jnp.float32))
    return out.reshape(B, 1, nh * hd)


def prefill(params, ids, config: LlamaConfig, cache):
    """Consume the prompt [B, S]: fills cache[:, :, :S] and returns
    (cache', last-position logits [B, V])."""
    c = config
    B, S = ids.shape
    E.enforce(S <= cache["k"].shape[2],
              f"prompt length {S} exceeds cache max_len "
              f"{cache['k'].shape[2]}")
    x = jnp.take(params["embed"], ids, axis=0)
    cos, sin = rope_tables(c, S)

    def step(carry, lp):
        x = carry
        h = _rms(x, lp["ln1"], c.rms_norm_eps)
        q, k, v = _qkv_proj(h, lp, c)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        a = sdpa_raw(q, k, v, is_causal=True).reshape(B, S, -1)
        x = x + _mm(a, lp["wo"])
        return _ffn(x, lp, c), (k, v)   # cache post-rope k, raw v

    x, (ks, vs) = lax.scan(step, x, params["layers"])
    kc = lax.dynamic_update_slice(
        cache["k"], ks.astype(cache["k"].dtype), (0,) * 5)
    vc = lax.dynamic_update_slice(
        cache["v"], vs.astype(cache["v"].dtype), (0,) * 5)
    x = _rms(x, params["ln_f"], c.rms_norm_eps)
    logits = _head_logits(x[:, -1, :], _head(params, c))
    return {"k": kc, "v": vc, "pos": jnp.asarray(S, jnp.int32)}, logits


def decode_step(params, cache, token, config: LlamaConfig):
    """One incremental step: ``token`` [B] sits at position cache['pos'].
    Returns (cache', logits [B, V]) for the next position."""
    c = config
    pos = cache["pos"]
    M = cache["k"].shape[2]
    x = jnp.take(params["embed"], token, axis=0)[:, None, :]   # [B, 1, D]
    cos_t, sin_t = rope_tables(c, M)
    cos = lax.dynamic_slice_in_dim(cos_t, pos, 1, 0)           # [1, hd/2]
    sin = lax.dynamic_slice_in_dim(sin_t, pos, 1, 0)

    def step(carry, xs):
        x = carry
        lp, kc, vc = xs
        h = _rms(x, lp["ln1"], c.rms_norm_eps)
        q, k, v = _qkv_proj(h, lp, c)
        q = rope_raw(q, cos, sin)
        k = rope_raw(k, cos, sin)
        kc = lax.dynamic_update_slice_in_dim(
            kc, k.astype(kc.dtype), pos, 1)
        vc = lax.dynamic_update_slice_in_dim(
            vc, v.astype(vc.dtype), pos, 1)
        a = _attn_over_cache(q, kc, vc, pos)
        x = x + _mm(a.astype(x.dtype), lp["wo"])
        return _ffn(x, lp, c), (kc, vc)

    x, (kc, vc) = lax.scan(step, x,
                           (params["layers"], cache["k"], cache["v"]))
    x = _rms(x, params["ln_f"], c.rms_norm_eps)
    logits = _head_logits(x[:, 0, :], _head(params, c))
    return {"k": kc, "v": vc, "pos": pos + 1}, logits


def generate(params, ids, config: LlamaConfig, *, max_new_tokens: int,
             max_len: Optional[int] = None, temperature: float = 0.0,
             top_k: Optional[int] = None, top_p: Optional[float] = None,
             eos_token_id: Optional[int] = None, pad_token_id: int = 0,
             key=None):
    """Autoregressive generation: greedy (temperature 0) or temperature
    sampling with optional top-k / nucleus (top-p) filtering and EOS
    stopping — the reference generation-loop controls (PaddleNLP
    GenerationMixin). ids: [B, S] prompt; returns [B, max_new_tokens];
    with ``eos_token_id`` set, positions after a sequence's EOS hold
    ``pad_token_id`` (the loop itself stays static-shape: finished rows
    keep decoding, their outputs are masked). Jit once, reuse for any
    same-shape prompt."""
    return _generate_over(
        init_cache, prefill, decode_step, params, ids, config,
        max_new_tokens=max_new_tokens, max_len=max_len,
        temperature=temperature, top_k=top_k, top_p=top_p,
        eos_token_id=eos_token_id, pad_token_id=pad_token_id, key=key)


def _generate_over(init_cache_fn, prefill_fn, decode_fn, params, ids,
                   config, *, max_new_tokens: int,
                   max_len: Optional[int] = None, temperature: float = 0.0,
                   top_k: Optional[int] = None, top_p: Optional[float] = None,
                   eos_token_id: Optional[int] = None, pad_token_id: int = 0,
                   key=None):
    """Family-agnostic sampling loop: any model exposing the
    (init_cache, prefill, decode_step) cache contract plugs in (same
    precedent as _beam_search_over — one copy of the EOS/done logic)."""
    c = config
    B, S = ids.shape
    M = max_len if max_len is not None else S + max_new_tokens
    E.enforce(M >= S + max_new_tokens,
              f"max_len {M} < prompt {S} + max_new_tokens "
              f"{max_new_tokens}")
    if max_new_tokens == 0:
        return jnp.zeros((B, 0), jnp.int32)
    cache = init_cache_fn(c, B, M)
    cache, logits = prefill_fn(params, ids, c, cache)
    sample = make_sampler(temperature, top_k=top_k, top_p=top_p)

    def emit(logits, done, k):
        """One sampling step's token + masked output (shared by the
        scan body and the final carried-logits sample)."""
        tok = sample(logits, k)
        if eos_token_id is not None:
            out = jnp.where(done, jnp.asarray(pad_token_id, jnp.int32),
                            tok)
            done = done | (tok == eos_token_id)
        else:
            out = tok
        return tok, out, done

    def body(carry, k):
        cache, logits, done = carry
        tok, out, done = emit(logits, done, k)
        cache, logits = decode_fn(params, cache, tok, c)
        return (cache, logits, done), out

    keys = jax.random.split(
        key if key is not None else jax.random.PRNGKey(0), max_new_tokens)
    # scan only max_new_tokens-1 decode steps: the final token samples
    # from the carried logits — the last decode's logits were computed
    # and discarded before (one whole step of wasted decode per call)
    (cache, logits, done), toks = lax.scan(
        body, (cache, logits, jnp.zeros((B,), bool)), keys[:-1])
    _, last, _ = emit(logits, done, keys[-1])
    toks = jnp.concatenate([toks, last[None]], axis=0)
    return toks.T                                   # [B, max_new_tokens]


def beam_search(params, ids, config: LlamaConfig, *, max_new_tokens: int,
                num_beams: int, max_len: Optional[int] = None,
                length_penalty: float = 0.0,
                eos_token_id: Optional[int] = None, pad_token_id: int = 0):
    """Static-shape beam search (reference capability: PaddleNLP
    GenerationMixin beam decoding). One prefill, then every step runs
    ONE batched decode over [B*K] beam rows, selects the global top-K of
    ``running score + log-softmax`` over [K, V], and reorders the KV
    cache along the beam axis with a gather — shapes never change, so
    the whole search jits once.

    Finished beams (EOS emitted) are frozen: their only continuation is
    ``pad_token_id`` at zero additional score. Final ranking divides
    scores by ``generated_length ** length_penalty`` (0 = pure
    log-prob). Returns (tokens [B, max_new_tokens] of the best beam,
    best scores [B])."""
    return _beam_search_over(
        init_cache, prefill, decode_step, params, ids, config,
        max_new_tokens=max_new_tokens, num_beams=num_beams,
        max_len=max_len, length_penalty=length_penalty,
        eos_token_id=eos_token_id, pad_token_id=pad_token_id)


def _beam_search_over(init_cache_fn, prefill_fn, decode_fn, params, ids,
                      config, *, max_new_tokens: int, num_beams: int,
                      max_len: Optional[int] = None,
                      length_penalty: float = 0.0,
                      eos_token_id: Optional[int] = None,
                      pad_token_id: int = 0):
    """Family-agnostic beam loop: any model exposing the
    (init_cache, prefill, decode_step) cache contract plugs in (the MoE
    family reuses this verbatim)."""
    c = config
    B, S = ids.shape
    K = num_beams
    E.enforce(K >= 1, f"num_beams must be >= 1, got {K}")
    M = max_len if max_len is not None else S + max_new_tokens
    E.enforce(M >= S + max_new_tokens,
              f"max_len {M} < prompt {S} + max_new_tokens "
              f"{max_new_tokens}")

    cache = init_cache_fn(c, B, M)
    cache, logits = prefill_fn(params, ids, c, cache)   # logits [B, V]
    # replicate the prompt cache across beams: [L, B, ...] -> [L, B*K, ...]
    tile = lambda a: jnp.repeat(a, K, axis=1)
    cache = {"k": tile(cache["k"]), "v": tile(cache["v"]),
             "pos": cache["pos"]}
    V = logits.shape[-1]
    logits = jnp.repeat(logits, K, axis=0)              # [B*K, V]
    # beam 0 starts live, the rest at -inf so step 1 picks K distinct
    # tokens from the prompt distribution
    scores = jnp.tile(jnp.asarray([0.0] + [-jnp.inf] * (K - 1)), (B, 1))
    neg = jnp.asarray(-jnp.inf, jnp.float32)
    if max_new_tokens == 0:
        best0 = jnp.argmax(scores, axis=1)
        return (jnp.zeros((B, 0), jnp.int32),
                jnp.take_along_axis(scores, best0[:, None], axis=1)[:, 0])

    def select(logits, scores, done, lengths):
        """One beam-selection step (pure math over the carried logits);
        shared by the scan body and the final no-decode step."""
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        logp = logp.reshape(B, K, V)
        # frozen beams: only pad continues, at zero additional score
        pad_only = jnp.full((V,), -jnp.inf).at[pad_token_id].set(0.0)
        logp = jnp.where(done[:, :, None], pad_only[None, None, :], logp)
        total = scores[:, :, None] + logp               # [B, K, V]
        top, flat = lax.top_k(total.reshape(B, K * V), K)
        beam_idx, tok = flat // V, (flat % V).astype(jnp.int32)  # [B, K]
        done = jnp.take_along_axis(done, beam_idx, axis=1)
        lengths = jnp.take_along_axis(lengths, beam_idx, axis=1)
        lengths = lengths + (~done).astype(jnp.int32)
        # frozen beams continue through their (possibly wrapped) pad
        # score slot internally, but the RECORDED token is the literal
        # pad id (pad_token_id may be negative, e.g. -1)
        tok = jnp.where(done, jnp.asarray(pad_token_id, jnp.int32), tok)
        if eos_token_id is not None:
            done = done | ((tok == eos_token_id) & ~done)
        return top, tok, beam_idx, done, lengths

    def step(carry, _):
        cache, logits, scores, done, lengths = carry
        scores, tok, beam_idx, done, lengths = select(
            logits, scores, done, lengths)
        gather_rows = (jnp.arange(B)[:, None] * K + beam_idx).reshape(-1)
        cache = {"k": jnp.take(cache["k"], gather_rows, axis=1),
                 "v": jnp.take(cache["v"], gather_rows, axis=1),
                 "pos": cache["pos"]}
        cache, logits = decode_fn(params, cache, tok.reshape(-1), c)
        return (cache, logits, scores, done, lengths), (tok, beam_idx)

    done0 = jnp.zeros((B, K), bool)
    len0 = jnp.zeros((B, K), jnp.int32)
    # scan only max_new_tokens-1 decode steps; the final selection runs
    # on the carried logits with no trailing decode (whose logits were
    # previously computed and thrown away) and no cache reorder
    (cache, logits, scores, done, lengths), (toks, bidx) = lax.scan(
        step, (cache, logits, scores, done0, len0), None,
        length=max_new_tokens - 1)
    scores, tok_f, bidx_f, done, lengths = select(
        logits, scores, done, lengths)
    toks = jnp.concatenate([toks, tok_f[None]], axis=0)
    bidx = jnp.concatenate([bidx, bidx_f[None]], axis=0)

    # Reconstruct each surviving beam's token path by walking the
    # recorded (token, parent-beam) choices backwards.
    def back(carry, xs):
        beam = carry                                    # [B, K]
        tok, bi = xs
        t = jnp.take_along_axis(tok, beam, axis=1)
        beam = jnp.take_along_axis(bi, beam, axis=1)
        return beam, t

    init = jnp.tile(jnp.arange(K), (B, 1))
    _, path = lax.scan(back, init, (toks, bidx), reverse=True)
    path = jnp.moveaxis(path, 0, -1)                    # [B, K, T]

    norm = jnp.maximum(lengths, 1).astype(jnp.float32) ** length_penalty
    best = jnp.argmax(scores / norm, axis=1)            # [B]
    best_toks = jnp.take_along_axis(
        path, best[:, None, None], axis=1)[:, 0, :]
    best_scores = jnp.take_along_axis(scores / norm, best[:, None],
                                      axis=1)[:, 0]
    return best_toks, best_scores


def make_sampler(temperature: float = 0.0, *, top_k: Optional[int] = None,
                 top_p: Optional[float] = None):
    """sample(logits [B, V], key) -> [B] int32: greedy at temperature 0,
    else categorical with optional top-k cut and top-p nucleus filtering
    (the reference generation-loop controls). Static-shape — safe inside
    a jitted decode scan. Shared by every model family's generate."""
    if top_p is not None:
        E.enforce(0.0 < top_p <= 1.0,
                  f"top_p must be in (0, 1], got {top_p}")

    def _filter(logits):
        if top_k is not None:
            kth = lax.top_k(logits, min(top_k, logits.shape[-1]))[0][
                ..., -1:]
            logits = jnp.where(logits < kth, -jnp.inf, logits)
        if top_p is not None and top_p < 1.0:
            # drop the tail whose cumulative prob (over descending
            # probs) already exceeded top_p BEFORE this token; the
            # first token always survives
            srt = jnp.sort(logits, axis=-1)[..., ::-1]
            probs = jax.nn.softmax(srt, axis=-1)
            cum = jnp.cumsum(probs, axis=-1) - probs
            cut = jnp.min(jnp.where(cum < top_p, srt, jnp.inf), axis=-1,
                          keepdims=True)
            logits = jnp.where(logits < cut, -jnp.inf, logits)
        return logits

    def sample(logits, k):
        if temperature == 0.0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        # temperature FIRST, then filter: top-p membership is decided on
        # the tempered distribution (the reference semantics; top-k is
        # invariant to the order, nucleus is not)
        return jax.random.categorical(
            k, _filter(logits / temperature), axis=-1).astype(jnp.int32)

    return sample


def unpack_batch(batch):
    """Normalize a train-step batch to (inp, labels, segment_ids,
    positions) — the ONE accepted-forms definition shared by every model
    family's loss_fn:

    - ids [B, S+1] (labels = shifted ids),
    - (inp, labels),
    - (inp, labels, segment_ids, positions)  — sequence-packed rows,
    - {"ids", "labels", "segment_ids", "positions"} — the packing
      collator's output (io/packing.py): labels are already next-token
      targets with cross-document / padding positions at ignore_index.
    """
    if isinstance(batch, dict):
        return (batch["ids"], batch["labels"],
                batch.get("segment_ids"), batch.get("positions"))
    if isinstance(batch, (tuple, list)):
        if len(batch) == 4:
            return batch[0], batch[1], batch[2], batch[3]
        inp, labels = batch
        return inp, labels, None, None
    return batch[:, :-1], batch[:, 1:], None, None


def loss_fn(params, batch, config: LlamaConfig, *, sp: bool = False,
            mesh: Optional[Mesh] = None):
    """Causal-LM cross entropy. batch = (ids [B,S+1]) or (inp, labels)
    or a sequence-packed form (see ``unpack_batch``): packed rows carry
    per-token segment ids / segment-local positions, and the labels set
    cross-document next-token targets to the fused-CE ignore_index so a
    document never predicts the first token of the next one.

    Single-device: blockwise fused CE (kernels/fused_ce.py) — the [B,S,V]
    logits never materialise in HBM (the reference's
    cross_entropy_kernel.cu capability, rebuilt as an online-softmax scan
    over vocab chunks). Multi-device (mesh): einsum logits + stable xent,
    which GSPMD shards vocab-parallel.
    """
    inp, labels, seg, pos = unpack_batch(batch)
    c = config
    if c.fused_ce and mesh is None:
        from ..kernels import dispatched_fused_ce

        x = forward_hidden(params, inp, c, sp=sp, mesh=mesh,
                           segment_ids=seg, positions=pos)
        return dispatched_fused_ce(x, _head(params, c), labels,
                                   vocab_chunk=c.fused_ce_chunk)
    logits = forward(params, inp, c, sp=sp, mesh=mesh, segment_ids=seg,
                     positions=pos)
    # identical ignore_index masking to the fused path (one shared
    # definition — padded labels zero out, mean over valid tokens)
    from ..kernels.fused_ce import masked_xent_from_logits
    return masked_xent_from_logits(logits, labels)


def param_specs(config: LlamaConfig) -> Dict[str, Any]:
    """GSPMD placement of every weight over a ('dp','fsdp','tp') mesh.
    Megatron column-parallel (wq/wk/wv/gate/up: output dim on tp),
    row-parallel (wo/down: input dim on tp), vocab-parallel embedding &
    head; fsdp (ZeRO-3) shards the other matmul dim."""
    specs = {
        "embed": P("tp", "fsdp"),
        "layers": {
            "ln1": P(None, None),
            "wq": P(None, "fsdp", "tp"),
            "wk": P(None, "fsdp", "tp"),
            "wv": P(None, "fsdp", "tp"),
            "wo": P(None, "tp", "fsdp"),
            "ln2": P(None, None),
            "gate": P(None, "fsdp", "tp"),
            "up": P(None, "fsdp", "tp"),
            "down": P(None, "tp", "fsdp"),
        },
        "ln_f": P(None),
    }
    if not config.tie_word_embeddings:
        specs["lm_head"] = P("tp", "fsdp")
    return specs


def count_params(config: LlamaConfig) -> int:
    c = config
    hd = c.head_dim
    per_layer = (c.hidden_size * hd * (c.num_attention_heads +
                                       2 * c.num_key_value_heads)
                 + c.num_attention_heads * hd * c.hidden_size
                 + 3 * c.hidden_size * c.intermediate_size
                 + 2 * c.hidden_size)
    n = c.vocab_size * c.hidden_size + c.num_hidden_layers * per_layer \
        + c.hidden_size
    if not c.tie_word_embeddings:
        n += c.vocab_size * c.hidden_size
    return n


# -- fused AdamW (the functional-path optimizer; mirrors optimizer/adamw) ---

def adamw_init(params, moment_dtype=jnp.float32):
    """Adam state. moment_dtype=jnp.bfloat16 halves optimizer HBM
    (4 bytes/param for m+v instead of 8) at a small quality cost — the
    update math still runs in f32 (_adamw_update casts up), so only the
    stored moments are rounded."""
    return {
        "step": jnp.zeros((), jnp.int32),
        "m": jax.tree.map(lambda p: jnp.zeros_like(p, moment_dtype), params),
        "v": jax.tree.map(lambda p: jnp.zeros_like(p, moment_dtype), params),
    }


def _adamw_update(params, grads, opt_state, lr, *, b1=0.9, b2=0.95,
                  eps=1e-8, wd=0.1):
    step = opt_state["step"] + 1
    t = step.astype(jnp.float32)
    bc1 = 1.0 - b1 ** t
    bc2 = 1.0 - b2 ** t

    def upd(p, g, m, v):
        mdt = m.dtype      # stored moment dtype (f32 or bf16)
        gf = g.astype(jnp.float32)
        m = b1 * m.astype(jnp.float32) + (1 - b1) * gf
        v = b2 * v.astype(jnp.float32) + (1 - b2) * (gf * gf)
        u = (m / bc1) / (jnp.sqrt(v / bc2) + eps)
        newp = p.astype(jnp.float32) - lr * (u + wd * p.astype(jnp.float32))
        return newp.astype(p.dtype), m.astype(mdt), v.astype(mdt)

    flat_p, tdef = jax.tree.flatten(params)
    flat_g = tdef.flatten_up_to(grads)
    flat_m = tdef.flatten_up_to(opt_state["m"])
    flat_v = tdef.flatten_up_to(opt_state["v"])
    out = [upd(p, g, m, v) for p, g, m, v in
           zip(flat_p, flat_g, flat_m, flat_v)]
    newp = tdef.unflatten([o[0] for o in out])
    newm = tdef.unflatten([o[1] for o in out])
    newv = tdef.unflatten([o[2] for o in out])
    return newp, {"step": step, "m": newm, "v": newv}


def make_forward(config: LlamaConfig, mesh: Optional[Mesh] = None):
    """Jitted inference forward. Without a mesh: plain jit (single chip)."""
    if mesh is None:
        return jax.jit(partial(forward, config=config))
    specs = param_specs(config)
    pshard = jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                          is_leaf=lambda x: isinstance(x, P))
    dshard = NamedSharding(mesh, P(("dp", "fsdp"), None))
    return jax.jit(partial(forward, config=config, mesh=mesh),
                   in_shardings=(pshard, dshard),
                   out_shardings=NamedSharding(mesh, P(("dp", "fsdp"), None, "tp")))


def make_train_step(config: LlamaConfig, mesh: Optional[Mesh] = None, *,
                    lr: float = 3e-4, weight_decay: float = 0.1,
                    sp: bool = False, donate: bool = True,
                    guard: Optional[bool] = None,
                    numerics: Optional[bool] = None):
    """Build `(params, opt_state, batch) -> (params, opt_state, loss)`.

    With a mesh (axes 'dp','fsdp','tp'): full GSPMD hybrid parallelism —
    dp/fsdp batch sharding, ZeRO-3 param+opt-state sharding on fsdp,
    Megatron TP on tp, optional sequence parallel. Buffer donation keeps
    params/opt-state in place (no 2x HBM). The batch may be any
    ``unpack_batch`` form — the single batch sharding below is a pytree
    PREFIX, so a packed (inp, labels, segment_ids, positions) tuple (all
    [B, S]) shards each leaf over ('dp','fsdp') without new plumbing.

    ``guard`` (default: ``FLAGS_enable_sentinel``) selects the GUARDED
    step `(params, opt_state, batch, gnorm_cap) -> (params, opt_state,
    loss, health)`: the optimizer update sits behind a ``lax.cond`` on
    :func:`step_health`'s ok flag, so an anomalous batch (non-finite
    loss/grads, out-of-range token ids, grad norm over the host-fed
    ``gnorm_cap`` scalar) leaves params and opt-state byte-identical —
    all-or-nothing ON DEVICE, donation and shardings intact — and
    ``health`` = {"finite", "grad_norm"} feeds the host-side
    ``training.sentinel`` policy engine. Unguarded (the default with
    the flag off), the step is exactly the 3-in/3-out program above:
    zero extra device outputs.

    ``numerics`` (default: ``FLAGS_enable_numerics``; guarded step
    only) adds ``health["numerics"]`` — the in-graph per-layer tensor
    statistics of the gradients (``training.guards.grad_numerics``:
    absmax/rms/mean/zero fraction, overflow/underflow fraction vs
    dtype range, and the per-layer grad-norm breakdown whose squared
    entries sum to ``grad_norm``) as fused reductions in the SAME
    compiled program. Off (the default) the guarded step is
    byte-identical to the pre-numerics program."""
    guard = resolve_guard(guard)
    numerics = guard and resolve_numerics(numerics)

    def grads_of(params, batch):
        return jax.value_and_grad(
            lambda p: loss_fn(p, batch, config, sp=sp, mesh=mesh))(params)

    def update(p, o, g):
        return _adamw_update(p, g, o, lr, wd=weight_decay)

    def step(params, opt_state, batch):
        loss, grads = grads_of(params, batch)
        params, opt_state = update(params, opt_state, grads)
        return params, opt_state, loss

    def guarded_step(params, opt_state, batch, gnorm_cap):
        loss, grads = grads_of(params, batch)
        ok, health = step_health(loss, grads, unpack_batch(batch)[0],
                                 config.vocab_size, gnorm_cap)
        if numerics:
            # fused per-layer reductions over the grads the step already
            # holds — same program, small f32 aux outputs
            health["numerics"] = grad_numerics(grads)
        params, opt_state = gated_update(ok, update, params, opt_state,
                                         grads)
        return params, opt_state, loss, health

    dn = (0, 1) if donate else ()
    if mesh is None:
        return jax.jit(guarded_step if guard else step, donate_argnums=dn)

    specs = param_specs(config)
    pshard = jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                          is_leaf=lambda x: isinstance(x, P))
    oshard = {"step": NamedSharding(mesh, P()), "m": pshard, "v": pshard}
    dshard = NamedSharding(mesh, P(("dp", "fsdp"), None))
    scalar = NamedSharding(mesh, P())
    if guard:
        # the health aux scalars replicate; with numerics on, `scalar`
        # acts as a pytree PREFIX covering the whole stats subtree
        # (every entry is a replicated scalar or [L] row). Without
        # numerics the explicit dict keeps the program byte-identical
        # to the pre-numerics one.
        hshard = scalar if numerics else {"finite": scalar,
                                          "grad_norm": scalar}
        return jax.jit(
            guarded_step,
            in_shardings=(pshard, oshard, dshard, scalar),
            out_shardings=(pshard, oshard, scalar, hshard),
            donate_argnums=dn)
    return jax.jit(step,
                   in_shardings=(pshard, oshard, dshard),
                   out_shardings=(pshard, oshard, scalar),
                   donate_argnums=dn)


def shard_params(params, config: LlamaConfig, mesh: Mesh):
    """Place an (initialised) param pytree onto the mesh per param_specs."""
    specs = param_specs(config)
    return jax.tree.map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, s)),
        params, specs,
        is_leaf=lambda x: isinstance(x, P))


# ---------------------------------------------------------------------------
# Eager Layer model (imperative parity path)
# ---------------------------------------------------------------------------

class LlamaDecoderLayer(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        c = config
        self.config = c
        self.input_layernorm = nn.RMSNorm(c.hidden_size, epsilon=c.rms_norm_eps)
        self.q_proj = nn.Linear(c.hidden_size,
                                c.num_attention_heads * c.head_dim,
                                bias_attr=False)
        self.k_proj = nn.Linear(c.hidden_size,
                                c.num_key_value_heads * c.head_dim,
                                bias_attr=False)
        self.v_proj = nn.Linear(c.hidden_size,
                                c.num_key_value_heads * c.head_dim,
                                bias_attr=False)
        self.o_proj = nn.Linear(c.num_attention_heads * c.head_dim,
                                c.hidden_size, bias_attr=False)
        self.post_attention_layernorm = nn.RMSNorm(c.hidden_size,
                                                   epsilon=c.rms_norm_eps)
        self.gate_proj = nn.Linear(c.hidden_size, c.intermediate_size,
                                   bias_attr=False)
        self.up_proj = nn.Linear(c.hidden_size, c.intermediate_size,
                                 bias_attr=False)
        self.down_proj = nn.Linear(c.intermediate_size, c.hidden_size,
                                   bias_attr=False)

    def forward(self, x, cos, sin):
        from .. import ops
        c = self.config
        b, s = x.shape[0], x.shape[1]
        h = self.input_layernorm(x)
        q = ops.reshape(self.q_proj(h),
                        shape=[b, s, c.num_attention_heads, c.head_dim])
        k = ops.reshape(self.k_proj(h),
                        shape=[b, s, c.num_key_value_heads, c.head_dim])
        v = ops.reshape(self.v_proj(h),
                        shape=[b, s, c.num_key_value_heads, c.head_dim])
        q = F.apply_rotary_emb(q, cos, sin)
        k = F.apply_rotary_emb(k, cos, sin)
        a = F.scaled_dot_product_attention(q, k, v, is_causal=True)
        a = ops.reshape(a, shape=[b, s, c.num_attention_heads * c.head_dim])
        x = x + self.o_proj(a)
        h = self.post_attention_layernorm(x)
        x = x + self.down_proj(F.silu(self.gate_proj(h)) * self.up_proj(h))
        return x


class LlamaForCausalLM(nn.Layer):
    """Imperative Llama (reference surface: PaddleNLP LlamaForCausalLM)."""

    def __init__(self, config: LlamaConfig):
        super().__init__()
        c = config
        self.config = c
        self.embed_tokens = nn.Embedding(c.vocab_size, c.hidden_size)
        self.layers = nn.LayerList(
            [LlamaDecoderLayer(c) for _ in range(c.num_hidden_layers)])
        self.norm = nn.RMSNorm(c.hidden_size, epsilon=c.rms_norm_eps)
        if not c.tie_word_embeddings:
            self.lm_head = nn.Linear(c.hidden_size, c.vocab_size,
                                     bias_attr=False)

    def forward(self, ids):
        from .. import ops
        c = self.config
        x = self.embed_tokens(ids)
        s = ids.shape[1]
        cos, sin = rope_tables(c, s)
        for layer in self.layers:
            x = layer(x, cos, sin)
        x = self.norm(x)
        if c.tie_word_embeddings:
            return ops.matmul(x, ops.transpose(self.embed_tokens.weight,
                                               perm=[1, 0]))
        return self.lm_head(x)

    _LAYER_MAP = (("ln1", "input_layernorm"), ("wq", "q_proj"),
                  ("wk", "k_proj"), ("wv", "v_proj"), ("wo", "o_proj"),
                  ("ln2", "post_attention_layernorm"),
                  ("gate", "gate_proj"), ("up", "up_proj"),
                  ("down", "down_proj"))

    def functional_params(self):
        """This Layer's weights as the functional-core pytree
        (init_params layout) — the bridge onto the jitted train/decode
        paths. Values are snapshots: mutate the Layer, re-export."""
        c = self.config
        layers = {
            fk: jnp.stack([jnp.asarray(getattr(l, attr).weight.numpy())
                           for l in self.layers])
            for fk, attr in self._LAYER_MAP}
        params = {"embed": jnp.asarray(self.embed_tokens.weight.numpy()),
                  "layers": layers,
                  "ln_f": jnp.asarray(self.norm.weight.numpy())}
        if not c.tie_word_embeddings:
            # functional head is [V, D]; nn.Linear stores [D, V]
            params["lm_head"] = jnp.asarray(self.lm_head.weight.numpy()).T
        return params

    def generate(self, ids, max_new_tokens: int, num_beams: int = 1,
                 **kw):
        """Autoregressive generation through the static-cache functional
        path (see module-level ``generate``; ``num_beams > 1`` selects
        beam search, the reference's one-generate-API shape). Accepts
        array or Tensor ids; returns a Tensor [B, max_new_tokens]."""
        from ..core.tensor import to_tensor

        arr = ids.numpy() if hasattr(ids, "numpy") else np.asarray(ids)
        args = (self.functional_params(), jnp.asarray(arr, jnp.int32),
                self.config)
        if num_beams > 1:
            # the GenerationMixin-style surface accepts both kwarg sets;
            # beam search is deterministic, so sampling knobs are
            # silently inapplicable (reference behavior) — drop them
            for k in ("temperature", "top_k", "top_p", "key"):
                kw.pop(k, None)
            toks, _ = beam_search(*args, max_new_tokens=max_new_tokens,
                                  num_beams=num_beams, **kw)
        else:
            kw.pop("length_penalty", None)   # beam-only knob
            toks = generate(*args, max_new_tokens=max_new_tokens, **kw)
        return to_tensor(np.asarray(toks))
