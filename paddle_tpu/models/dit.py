"""DiT — Diffusion Transformer family (the BASELINE.json DiT/SD3 config).

Reference capability: the PaddleMIX DiT/SD3 recipes trained through the
reference stack (conv patchify + adaLN-Zero transformer blocks +
timestep/label conditioning). TPU-native design: same functional-core
pattern as models/llama.py — stacked per-block params under lax.scan with
optional remat, GSPMD param_specs over ('dp','fsdp','tp'); patchify is a
reshape-einsum (not a conv) so the whole model is matmuls on the MXU.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = [
    "DiTConfig", "dit_tiny", "dit_xl_2", "init_params", "forward",
    "loss_fn", "param_specs", "make_train_step", "count_params",
    "adamw_init", "ddim_sample",
]


@dataclasses.dataclass
class DiTConfig:
    image_size: int = 32          # latent spatial size (32 = 256px VAE/8)
    patch_size: int = 2
    in_channels: int = 4
    hidden_size: int = 1152
    num_hidden_layers: int = 28
    num_attention_heads: int = 16
    mlp_ratio: float = 4.0
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16
    remat: bool = True

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads


def dit_tiny(**kw) -> DiTConfig:
    base = dict(image_size=8, patch_size=2, in_channels=4, hidden_size=64,
                num_hidden_layers=2, num_attention_heads=4, num_classes=10,
                dtype=jnp.float32, remat=False)
    base.update(kw)
    return DiTConfig(**base)


def dit_xl_2(**kw) -> DiTConfig:
    """DiT-XL/2 shapes (the headline DiT config)."""
    base = dict(image_size=32, patch_size=2, hidden_size=1152,
                num_hidden_layers=28, num_attention_heads=16)
    base.update(kw)
    return DiTConfig(**base)


# ---------------------------------------------------------------------------
# params
# ---------------------------------------------------------------------------

def init_params(config: DiTConfig, key) -> Dict[str, Any]:
    c = config
    D = c.hidden_size
    L = c.num_hidden_layers
    pdim = c.patch_size * c.patch_size * c.in_channels
    F = int(D * c.mlp_ratio)
    ks = jax.random.split(key, 12)

    def nrm(k, shape, std=0.02):
        return (jax.random.normal(k, shape, jnp.float32) * std
                ).astype(c.dtype)

    return {
        "patch_w": nrm(ks[0], (pdim, D)),
        "patch_b": jnp.zeros((D,), c.dtype),
        "pos": nrm(ks[1], (c.num_patches, D)),
        # timestep MLP (sinusoidal freq embed -> 2-layer MLP)
        "t_w1": nrm(ks[2], (256, D)),
        "t_b1": jnp.zeros((D,), c.dtype),
        "t_w2": nrm(ks[3], (D, D)),
        "t_b2": jnp.zeros((D,), c.dtype),
        # label embedding (+1 row: classifier-free-guidance null class)
        "y_embed": nrm(ks[4], (c.num_classes + 1, D)),
        "blocks": {
            # adaLN-Zero: 6 modulation vectors per block from conditioning;
            # final projection starts at ZERO (identity residual at init)
            "mod_w": jnp.zeros((L, D, 6 * D), c.dtype),
            "mod_b": jnp.zeros((L, 6 * D), c.dtype),
            "qkv_w": nrm(ks[5], (L, D, 3 * D)),
            "qkv_b": jnp.zeros((L, 3 * D), c.dtype),
            "proj_w": nrm(ks[6], (L, D, D)),
            "proj_b": jnp.zeros((L, D), c.dtype),
            "mlp_w1": nrm(ks[7], (L, D, F)),
            "mlp_b1": jnp.zeros((L, F), c.dtype),
            "mlp_w2": nrm(ks[8], (L, F, D)),
            "mlp_b2": jnp.zeros((L, D), c.dtype),
        },
        # final adaLN + zero-init output projection to patch pixels
        "final_mod_w": jnp.zeros((D, 2 * D), c.dtype),
        "final_mod_b": jnp.zeros((2 * D,), c.dtype),
        "final_w": jnp.zeros((D, pdim), c.dtype),
        "final_b": jnp.zeros((pdim,), c.dtype),
    }


# ---------------------------------------------------------------------------
# pieces
# ---------------------------------------------------------------------------

def timestep_embedding(t, dim=256, max_period=10000.0):
    """Sinusoidal timestep features [B, dim] (DiT convention)."""
    half = dim // 2
    freqs = jnp.exp(-math.log(max_period)
                    * jnp.arange(half, dtype=jnp.float32) / half)
    args = t.astype(jnp.float32)[:, None] * freqs[None, :]
    return jnp.concatenate([jnp.cos(args), jnp.sin(args)], axis=-1)


def patchify(x, config: DiTConfig):
    """[B, C, H, W] -> [B, N, p*p*C] (einops-style reshape)."""
    c = config
    B, C, H, W = x.shape
    p = c.patch_size
    x = x.reshape(B, C, H // p, p, W // p, p)
    x = jnp.transpose(x, (0, 2, 4, 3, 5, 1))        # B, h, w, p, p, C
    return x.reshape(B, (H // p) * (W // p), p * p * C)


def unpatchify(x, config: DiTConfig):
    c = config
    B, N, _ = x.shape
    p = c.patch_size
    hw = c.image_size // p
    x = x.reshape(B, hw, hw, p, p, c.in_channels)
    x = jnp.transpose(x, (0, 5, 1, 3, 2, 4))
    return x.reshape(B, c.in_channels, hw * p, hw * p)


def _ln(x):
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    return ((xf - mu) * jax.lax.rsqrt(var + 1e-6)).astype(x.dtype)


def _modulate(x, shift, scale):
    return x * (1 + scale[:, None, :]) + shift[:, None, :]


def _block(x, cond, bp, config: DiTConfig):
    c = config
    B, N, D = x.shape
    nh, hd = c.num_attention_heads, c.head_dim
    mod = jax.nn.silu(cond) @ bp["mod_w"] + bp["mod_b"]     # [B, 6D]
    sh1, sc1, g1, sh2, sc2, g2 = jnp.split(mod, 6, axis=-1)

    h = _modulate(_ln(x), sh1, sc1)
    qkv = h @ bp["qkv_w"] + bp["qkv_b"]
    q, k, v = jnp.split(qkv.reshape(B, N, 3, nh, hd), 3, axis=2)
    q, k, v = q[:, :, 0], k[:, :, 0], v[:, :, 0]            # [B, N, nh, hd]
    from ..nn.functional.attention import sdpa_raw
    a = sdpa_raw(q, k, v, is_causal=False).reshape(B, N, D)
    x = x + g1[:, None, :] * (a @ bp["proj_w"] + bp["proj_b"])

    h = _modulate(_ln(x), sh2, sc2)
    h = jax.nn.gelu(h @ bp["mlp_w1"] + bp["mlp_b1"], approximate=True)
    x = x + g2[:, None, :] * (h @ bp["mlp_w2"] + bp["mlp_b2"])
    return x


def forward(params, x, t, y, config: DiTConfig, *,
            mesh: Optional[Mesh] = None):
    """Noise prediction: x [B,C,H,W] latents, t [B] timesteps, y [B]
    labels -> [B,C,H,W]."""
    c = config
    p = params
    h = patchify(x.astype(c.dtype), c) @ p["patch_w"] + p["patch_b"]
    h = h + p["pos"][None]

    temb = timestep_embedding(t).astype(c.dtype)
    cond = jax.nn.silu(temb @ p["t_w1"] + p["t_b1"]) @ p["t_w2"] + p["t_b2"]
    cond = cond + jnp.take(p["y_embed"], y, axis=0)

    def step(carry, bp):
        return _block(carry, cond, bp, c), None

    step_fn = jax.checkpoint(step, prevent_cse=False) if c.remat else step
    h, _ = lax.scan(step_fn, h, p["blocks"])

    fmod = jax.nn.silu(cond) @ p["final_mod_w"] + p["final_mod_b"]
    fsh, fsc = jnp.split(fmod, 2, axis=-1)
    h = _modulate(_ln(h), fsh, fsc)
    out = h @ p["final_w"] + p["final_b"]
    return unpatchify(out.astype(jnp.float32), c)


def _alpha_bar_table(tmax: int = 1000):
    """cumprod(1 - beta_t) for the linear DDPM schedule (a compile-time
    constant table, indexed by traced t)."""
    betas = jnp.linspace(1e-4, 0.02, tmax)
    return jnp.cumprod(1.0 - betas)


def loss_fn(params, batch, config: DiTConfig, *,
            mesh: Optional[Mesh] = None):
    """DDPM epsilon-prediction MSE: batch = (x0, t, y, noise), t integer
    timesteps in [0, 1000) (the DiT training objective)."""
    x0, t, y, noise = batch
    abar = jnp.take(_alpha_bar_table(), t.astype(jnp.int32)
                    )[:, None, None, None]
    xt = jnp.sqrt(abar) * x0 + jnp.sqrt(1 - abar) * noise
    pred = forward(params, xt, t, y, config, mesh=mesh)
    return jnp.mean((pred - noise) ** 2)


def ddim_sample(params, y, config: DiTConfig, *, steps: int = 50,
                eta: float = 0.0, guidance_scale: float = 1.0,
                key=None, tmax: int = 1000):
    """DDIM sampling loop (reference capability: the diffusion
    schedulers behind the DiT/SD3 pipelines). TPU-native: the full
    reverse trajectory is a lax.scan over a static timestep ladder —
    one compiled program regardless of step count; eta=0 is the
    deterministic DDIM ODE, eta=1 recovers ancestral DDPM noise.
    Classifier-free guidance batches the conditional and null branches
    (label id = config.num_classes) in ONE forward per step.

    y: [B] int labels; returns x0 samples [B, C, H, W] float32.
    """
    c = config
    B = y.shape[0]
    key = key if key is not None else jax.random.PRNGKey(0)
    key, k0 = jax.random.split(key)
    x = jax.random.normal(
        k0, (B, c.in_channels, c.image_size, c.image_size), jnp.float32)

    abar = _alpha_bar_table(tmax)
    # descending ladder t_s -> t_{s-1}, e.g. 999, 979, ..., 19, -1
    ts = jnp.linspace(tmax - 1, 0, steps).astype(jnp.int32)
    ts_prev = jnp.concatenate([ts[1:], jnp.asarray([-1], jnp.int32)])
    noise_keys = jax.random.split(key, steps)

    def eps_fn(x, t):
        tb = jnp.full((B,), t, jnp.int32)
        if guidance_scale == 1.0:
            return forward(params, x, tb, y, c)
        null = jnp.full((B,), c.num_classes, jnp.int32)   # CFG null label
        both = forward(params, jnp.concatenate([x, x]),
                       jnp.concatenate([tb, tb]),
                       jnp.concatenate([y, null]), c)
        e_cond, e_null = jnp.split(both, 2, axis=0)
        return e_null + guidance_scale * (e_cond - e_null)

    def step(x, inputs):
        t, t_prev, nk = inputs
        a_t = abar[t]
        a_prev = jnp.where(t_prev >= 0, abar[jnp.maximum(t_prev, 0)], 1.0)
        eps = eps_fn(x, t)
        x0 = (x - jnp.sqrt(1.0 - a_t) * eps) / jnp.sqrt(a_t)
        sigma = eta * jnp.sqrt((1.0 - a_prev) / (1.0 - a_t)
                               * (1.0 - a_t / a_prev))
        dir_xt = jnp.sqrt(jnp.maximum(1.0 - a_prev - sigma ** 2, 0.0)) \
            * eps
        noise = sigma * jax.random.normal(nk, x.shape, jnp.float32)
        x = jnp.sqrt(a_prev) * x0 + dir_xt + noise
        return x, None

    x, _ = lax.scan(step, x, (ts, ts_prev, noise_keys))
    return x


def param_specs(config: DiTConfig) -> Dict[str, Any]:
    """('dp','fsdp','tp') placements: attention/MLP matmuls column/row
    split on tp, the other dim on fsdp."""
    return {
        "patch_w": P("fsdp", "tp"),
        "patch_b": P(None),
        "pos": P(None, "fsdp"),
        "t_w1": P("fsdp", "tp"), "t_b1": P(None),
        "t_w2": P("fsdp", "tp"), "t_b2": P(None),
        "y_embed": P(None, "fsdp"),
        "blocks": {
            "mod_w": P(None, "fsdp", "tp"), "mod_b": P(None, None),
            "qkv_w": P(None, "fsdp", "tp"), "qkv_b": P(None, None),
            "proj_w": P(None, "tp", "fsdp"), "proj_b": P(None, None),
            "mlp_w1": P(None, "fsdp", "tp"), "mlp_b1": P(None, None),
            "mlp_w2": P(None, "tp", "fsdp"), "mlp_b2": P(None, None),
        },
        "final_mod_w": P("fsdp", "tp"), "final_mod_b": P(None),
        "final_w": P("fsdp", None), "final_b": P(None),
    }


def count_params(config: DiTConfig) -> int:
    import numpy as np
    dummy = jax.eval_shape(lambda: init_params(config, jax.random.key(0)))
    return int(sum(np.prod(l.shape) for l in jax.tree.leaves(dummy)))


def adamw_init(params):
    from .llama import adamw_init as _ai
    return _ai(params)


def make_train_step(config: DiTConfig, mesh: Optional[Mesh] = None, *,
                    lr: float = 1e-4):
    from .llama import _adamw_update

    def step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(
            lambda p: loss_fn(p, batch, config, mesh=mesh))(params)
        params, opt_state = _adamw_update(params, grads, opt_state, lr)
        return params, opt_state, loss

    if mesh is None:
        return jax.jit(step)
    specs = param_specs(config)
    pshard = jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                          is_leaf=lambda s: isinstance(s, P))

    def placed(params, opt_state, batch):
        params = jax.lax.with_sharding_constraint(params, pshard)
        return step(params, opt_state, batch)

    return jax.jit(placed)
