"""Model zoo (reference capability: PaddleNLP/PaddleMIX model recipes
trained through the framework — SURVEY.md §7 phase 8)."""
from . import dit  # noqa: F401
from . import llama  # noqa: F401
from . import moe  # noqa: F401
from . import ocr  # noqa: F401

__all__ = ["llama", "moe", "dit", "ocr"]
