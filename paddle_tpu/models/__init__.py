"""Model zoo (reference capability: PaddleNLP/PaddleMIX model recipes
trained through the framework — SURVEY.md §7 phase 8)."""
from . import llama  # noqa: F401

__all__ = ["llama"]
