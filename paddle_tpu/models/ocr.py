"""PP-OCR-style text recognition model family (conv workload of the
BASELINE config matrix).

Reference capability: the PP-OCRv4 recognition recipe (ecosystem
PaddleOCR; in-tree the reference provides its building blocks — conv/
bn kernels, CTC loss, LSTM). Architecture: a MobileNetV3-ish conv
backbone collapsing height, a BiLSTM sequence neck, and a CTC head —
the classic CRNN/PP-OCR rec pipeline, trained with
paddle.nn.functional.ctc_loss.

TPU-native notes: the backbone is NCHW convs XLA lays out for the MXU;
the recurrent neck is a lax.scan (nn.LSTM); the whole train step jits
into one XLA program (see make_train_step).
"""
from __future__ import annotations

from dataclasses import dataclass

from .. import nn


@dataclass
class OCRRecConfig:
    image_height: int = 32
    in_channels: int = 3
    num_classes: int = 97           # charset + blank (index 0)
    hidden_size: int = 96           # BiLSTM width
    backbone_channels: tuple = (32, 64, 128, 256)
    dtype: str = "float32"


def ocr_rec_tiny(**kw) -> OCRRecConfig:
    base = dict(image_height=16, num_classes=12, hidden_size=16,
                backbone_channels=(8, 12, 16, 24))
    base.update(kw)
    return OCRRecConfig(**base)


def pp_ocrv4_rec(**kw) -> OCRRecConfig:
    """PP-OCRv4 mobile rec shapes."""
    return OCRRecConfig(**kw)


class _ConvBNAct(nn.Sequential):
    def __init__(self, in_c, out_c, stride):
        super().__init__(
            nn.Conv2D(in_c, out_c, 3, stride=stride, padding=1,
                      bias_attr=False),
            nn.BatchNorm2D(out_c),
            nn.Hardswish())


class OCRRecognizer(nn.Layer):
    """[N, C, H, W] image -> [N, W', num_classes] per-timestep logits.

    Strides collapse H to 1 while keeping W resolution (the PP-OCR rec
    backbone discipline: horizontal stride stays 1 after the stem)."""

    def __init__(self, config: OCRRecConfig = None, **kw):
        super().__init__()
        c = config or OCRRecConfig(**kw)
        self.config = c
        chans = c.backbone_channels
        blocks = [_ConvBNAct(c.in_channels, chans[0], stride=2)]
        in_c = chans[0]
        for out_c in chans[1:]:
            # downsample height only: (2, 1) stride keeps sequence length
            blocks.append(_ConvBNAct(in_c, out_c, stride=(2, 1)))
            in_c = out_c
        self.backbone = nn.Sequential(*blocks)
        self.pool = nn.AdaptiveAvgPool2D((1, None))
        self.neck = nn.LSTM(in_c, c.hidden_size, direction="bidirect")
        self.head = nn.Linear(2 * c.hidden_size, c.num_classes)

    def forward(self, x):
        feat = self.backbone(x)                      # [N, C, h', W/2]
        feat = self.pool(feat)                       # [N, C, 1, W/2]
        n, ch, _, wseq = feat.shape
        seq = feat.reshape([n, ch, wseq]).transpose([0, 2, 1])  # [N,T,C]
        out, _ = self.neck(seq)                      # [N, T, 2H]
        return self.head(out)                        # [N, T, classes]


def ctc_greedy_decode(logits, blank: int = 0):
    """Best-path CTC decoding (reference capability: the CTCLabelDecode
    postprocess behind the PP-OCR rec pipelines): argmax per frame,
    collapse repeats, drop blanks. logits: [N, T, C] (Tensor or array).
    Returns (list of per-sample id lists, [N] mean top-prob confidences
    over the kept frames)."""
    import numpy as np

    arr = logits.numpy() if hasattr(logits, "numpy") else np.asarray(logits)
    # softmax over classes for confidences (stable)
    z = arr - arr.max(axis=-1, keepdims=True)
    probs = np.exp(z) / np.exp(z).sum(axis=-1, keepdims=True)
    ids = arr.argmax(axis=-1)                        # [N, T]
    top = probs.max(axis=-1)                         # [N, T]
    texts, confs = [], []
    for n in range(ids.shape[0]):
        keep = np.ones(ids.shape[1], bool)
        keep[1:] = ids[n, 1:] != ids[n, :-1]         # collapse repeats
        keep &= ids[n] != blank                      # drop blanks
        texts.append(ids[n, keep].tolist())
        confs.append(float(top[n, keep].mean()) if keep.any() else 0.0)
    return texts, np.asarray(confs, np.float32)


def ctc_train_step(model: OCRRecognizer, optimizer):
    """Build an eager train-step closure: (images, labels, label_lens) ->
    loss. The CTC loss rides the taped log-semiring scan
    (nn/functional/extras.py ctc_loss)."""
    import numpy as np

    from .. import to_tensor
    from ..nn import functional as F

    def step(images, labels, label_lens):
        logits = model(images)                       # [N, T, C]
        t_len = logits.shape[1]
        n = logits.shape[0]
        log_probs = logits.transpose([1, 0, 2])      # [T, N, C]
        input_lens = to_tensor(np.full((n,), t_len, "int32"))
        loss = F.ctc_loss(log_probs, labels, input_lens, label_lens,
                          blank=0)
        loss.backward()
        optimizer.step()
        optimizer.clear_grad()
        return loss

    return step
