"""paddle.hub parity: load models from a hubconf.py (reference:
python/paddle/hub.py help/list/load).

No network egress here, so only ``source='local'`` works: ``repo_dir``
is a directory containing ``hubconf.py`` whose public callables are the
hub entry points. GitHub sources raise with that explanation.
"""
from __future__ import annotations

import importlib.util
import os
import sys
from .core import enforce as E

__all__ = ["help", "list", "load"]

_HUB_CONF = "hubconf.py"


def _load_hubconf(repo_dir, source):
    if source != "local":
        raise NotImplementedError(
            f"paddle.hub source={source!r} requires network access, "
            "unavailable in this environment; clone the repo and use "
            "source='local'")
    path = os.path.join(repo_dir, _HUB_CONF)
    if not os.path.exists(path):
        raise FileNotFoundError(f"no {_HUB_CONF} in {repo_dir}")
    spec = importlib.util.spec_from_file_location("paddle_tpu_hubconf", path)
    mod = importlib.util.module_from_spec(spec)
    sys.path.insert(0, repo_dir)
    try:
        spec.loader.exec_module(mod)
    finally:
        sys.path.pop(0)
    return mod


def list(repo_dir, source="local", force_reload=False):  # noqa: A001
    mod = _load_hubconf(repo_dir, source)
    return [n for n in dir(mod)
            if callable(getattr(mod, n)) and not n.startswith("_")]


def help(repo_dir, model, source="local", force_reload=False):  # noqa: A001
    mod = _load_hubconf(repo_dir, source)
    fn = getattr(mod, model, None)
    if fn is None:
        raise E.InvalidArgumentError(f"model {model!r} not found in {repo_dir}")
    return fn.__doc__


def load(repo_dir, model, source="local", force_reload=False, **kwargs):
    mod = _load_hubconf(repo_dir, source)
    fn = getattr(mod, model, None)
    if fn is None:
        raise E.InvalidArgumentError(f"model {model!r} not found in {repo_dir}")
    return fn(**kwargs)
