"""Segmented (mixed) capture for to_static graph breaks.

Reference capability: jit/sot — the reference's symbolic opcode
translator splits a function with an untraceable data-dependent Python
branch into compiled subgraphs around an eager island
(jit/sot/translate.py:30), guarded so repeat calls reuse the compiled
pieces. Round-3 shipped whole-call eager fallback; this is the real
thing, redesigned for the TPU stack:

- The function runs once against SYMBOLIC tensors riding the static
  Program recorder (static/ir.py — the same @op_fn seam the Executor
  uses), with deterministic local var names. During this RECORDING call
  the ops replay directly (uncompiled) so Python gets its concrete
  branch values with no compile latency.
- Every point where Python needs a concrete value (``bool(t)``/
  ``float(t)``/``t.item()``/``t.numpy()`` on a traced tensor — exactly
  where jax tracing dies with a ConcretizationTypeError) becomes a
  GUARD: the ops since the previous break form one segment, and the
  concretized value keys the edge to the next segment.
- After the recording, each segment is built as ONE jitted slice whose
  outputs are pruned to what later segments/guards/outputs actually
  read (XLA fuses and DCEs inside the slice). Later calls replay the
  compiled slices down the guard tree — zero re-recording, zero Python
  tracing — and only re-record on an unseen branch outcome. Float
  guards match by exact value (a concretized float may steer Python
  arbitrarily, so value identity is the only sound guard); bool guards
  (``if (x > 0):``) give the classic two-way cache. The tree is capped:
  a pathological continuous guard saturates it and the signature is
  pinned back to plain eager by the api layer (never unbounded memory,
  never perpetual per-call re-recording).

Training mode (grads ON) is served too — the reference's SOT captures
training functions with graph breaks (jit/sot/translate.py:30, the
eval-frame hook serves backward()): the recording pass is identical
(the recorder does not tape), and the grafted compiled path is then
replayed with each slice taped as ONE GradNode whose vjp is a cached
jitted function (``_Slice.call_taped``). ``loss.backward()`` flows
through the chain of slice vjps into the parameters and the inputs,
with zero Python tracing at steady state. The compiled vjp
REMATERIALISES the slice forward inside backward (jax.vjp at backward
time) instead of storing residuals across the host boundary — ~one
extra fused forward per slice per step, the standard TPU memory/FLOPs
trade (same family as jax.checkpoint). create_graph (double backward)
through a segmented call is not supported — the slice nodes carry no
re-differentiable pure spec; use full_graph=True or eager for that.
RNG-consuming ops (dropout) bake their key at recording time, matching
full-graph to_static behaviour: each cached path reuses its recorded
mask sequence.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from ..core.tensor import Tensor, set_symbolic_concretize_hook
from ..static.ir import Program, Var

MAX_PATHS_PER_SIG = 64

# observability (tested; also useful when debugging a capture).
# segments_compiled counts jitted slices BUILT (XLA compiles lazily on
# their first replay); segments_executed counts compiled-slice runs.
STATS = {"segments_compiled": 0, "segments_executed": 0,
         "recordings": 0, "cached_path_hits": 0}


def reset_stats():
    for k in STATS:
        STATS[k] = 0


class SegmentCaptureError(RuntimeError):
    """Recorder/replay-internal failure (NOT an exception raised by the
    user's own function) — the api layer degrades these to eager."""


class _Slice:
    """One compiled segment: replays ``ops`` of a recorded Program.
    Inputs: env arrays it consumes + live params; outputs: the pruned
    set later segments/guards/outputs read."""

    def __init__(self, program, ops, in_names, out_vars):
        self.in_names = in_names
        self.out_names = [v.name for v in out_vars]
        refs = program.param_refs(ops)
        self._refs = refs

        def run(feed_arrays, param_arrays):
            overrides = {id(r.param): a
                         for r, a in zip(refs, param_arrays)}
            return program._replay_env(dict(feed_arrays), out_vars,
                                       overrides, ops=ops)

        self._run = run
        self._jit = jax.jit(run)
        # (diff_idx) -> jitted vjp of run; one entry serves every step of
        # a training loop (jax.jit re-specializes on shape change)
        self._bwd_cache: Dict[tuple, Any] = {}
        STATS["segments_compiled"] += 1

    def __call__(self, env):
        feed = {n: env[n] for n in self.in_names}
        outs = self._jit(feed, [r.param._data for r in self._refs])
        env.update(zip(self.out_names, outs))
        STATS["segments_executed"] += 1

    def call_taped(self, env):
        """Training-mode replay: ``env`` maps names -> Tensors and this
        slice records as ONE GradNode. The vjp is deferred to backward
        and served by a jitted function cached per diff-signature, so a
        steady-state train step pays compiled fwd + compiled bwd per
        slice and no Python tracing."""
        import jax.numpy as jnp

        from ..autograd import tape as _tape

        feed_t = [env[n] for n in self.in_names]
        param_t = [r.param for r in self._refs]
        in_list = feed_t + param_t
        arrays = [t._data for t in in_list]
        nf = len(self.in_names)
        outs = self._jit(dict(zip(self.in_names, arrays[:nf])),
                         arrays[nf:])
        out_tensors = [Tensor(o) for o in outs]
        diff_idx = tuple(
            i for i, t in enumerate(in_list)
            if isinstance(t, Tensor) and not t.stop_gradient
            and jnp.issubdtype(t._data.dtype, jnp.inexact))
        if diff_idx and out_tensors:
            bwd = self._bwd_cache.get(diff_idx)
            if bwd is None:
                in_names, run = self.in_names, self._run

                def bwd_impl(diff_primals, all_arrays, cts):
                    def closed(*d):
                        full = list(all_arrays)
                        for i, a in zip(diff_idx, d):
                            full[i] = a
                        return tuple(run(dict(zip(in_names, full[:nf])),
                                         list(full[nf:])))
                    return jax.vjp(closed, *diff_primals)[1](tuple(cts))

                bwd = jax.jit(bwd_impl)
                self._bwd_cache[diff_idx] = bwd
            diff_primals = tuple(arrays[i] for i in diff_idx)
            all_arrays = tuple(arrays)

            def vjp_fn(cts):
                return bwd(diff_primals, all_arrays,
                           cts if isinstance(cts, tuple) else (cts,))

            node = _tape.record_node(
                "segment_slice", vjp_fn,
                [in_list[i] for i in diff_idx], out_tensors)
            node.multi_out = True      # vjp always takes the full tuple
        env.update(zip(self.out_names, out_tensors))
        STATS["segments_executed"] += 1


class _Node:
    """Guard-tree node: run ``slice``, then either return (leaf,
    out_tree set) or concretize ``guard_name`` and follow the edge
    matching its value."""

    __slots__ = ("slice", "guard_name", "children", "out_tree",
                 "out_entries")

    def __init__(self):
        self.slice: Optional[_Slice] = None
        self.guard_name: Optional[str] = None
        self.children: Dict[Any, _Node] = {}
        self.out_tree = None
        # tagged leaves: ("var", name) reads the env; ("const", v) is a
        # literal output (non-tensor or concrete-tensor leaf)
        self.out_entries: Optional[List[Tuple[str, Any]]] = None


def _guard_value(arr):
    """Hashable guard key for a concretized array (scalars in practice;
    small arrays allowed — bytes of the buffer)."""
    a = np.asarray(arr)
    if a.size == 1:
        return a.reshape(()).item()
    return a.tobytes()


class _SliceSpec:
    __slots__ = ("start", "stop", "guard_name")

    def __init__(self, start, stop, guard_name=None):
        self.start = start
        self.stop = stop
        self.guard_name = guard_name


class _Recorder:
    """One segmented recording of fn(*args): replays ops directly while
    noting segment boundaries; compiled pruned slices are built in
    graft()."""

    def __init__(self, owner, sig):
        self.owner = owner
        self.sig = sig
        self.program = Program(local_names=True)
        self.env: Dict[str, Any] = {}
        self.feed_names: List[str] = []
        self.watermark = 0
        self.path_values: List[Any] = []
        self.specs: List[_SliceSpec] = []

    # -- capture-side ------------------------------------------------------
    def symbolize(self, args, kwargs):
        """EVERY Tensor leaf anywhere in (args, kwargs) — including ones
        nested in lists/dicts — becomes a live feed var (a baked nested
        tensor would make cached replays silently reuse the recording's
        values, since the signature keys on shape/dtype only)."""
        flat, tree = jax.tree_util.tree_flatten(
            (list(args), dict(kwargs)),
            is_leaf=lambda x: isinstance(x, Tensor))
        sym_flat = []
        for i, leaf in enumerate(flat):
            if isinstance(leaf, Tensor):
                name = f"leaf{i}"
                t = self.program.add_feed(name, leaf._data.shape,
                                          leaf._data.dtype)
                self.env[name] = leaf._data
                self.feed_names.append(name)
                sym_flat.append(t)
            else:
                sym_flat.append(leaf)
        sym_args, sym_kw = jax.tree_util.tree_unflatten(tree, sym_flat)
        return sym_args, sym_kw

    def _advance(self, guard_name=None):
        """Close the current segment: replay its ops directly (NOT
        compiled — this is the one-time recording pass) and note the
        boundary."""
        stop = len(self.program.ops())
        ops = self.program.ops()[self.watermark:stop]
        try:
            self.program._replay_env(self.env, [], ops=ops)
        except Exception as e:
            raise SegmentCaptureError(
                f"segment replay failed during recording: "
                f"{type(e).__name__}: {e}") from e
        self.specs.append(_SliceSpec(self.watermark, stop, guard_name))
        self.watermark = stop

    def concretize(self, tensor):
        var = tensor._symbolic
        if var.program is not self.program:
            raise SegmentCaptureError(
                "concretized a symbolic tensor from a different Program "
                "inside segmented capture")
        # EVERY concretization is a guard — its value steers Python
        # control flow, so cached replays must check it (an
        # already-materialized var yields an empty segment).
        self._advance(guard_name=var.name)
        value = self.env[var.name]
        self.path_values.append(_guard_value(value))
        return value

    def finalize(self, out):
        flat, tree = jax.tree_util.tree_flatten(
            out, is_leaf=lambda x: isinstance(x, Tensor))
        entries: List[Tuple[str, Any]] = []
        for leaf in flat:
            if isinstance(leaf, Tensor) and leaf._symbolic is not None:
                entries.append(("var", leaf._symbolic.name))
            elif isinstance(leaf, Tensor):
                entries.append(("const", leaf._data))
            else:
                entries.append(("const", leaf))
        self._advance(guard_name=None)
        self.out_tree = tree
        self.out_entries = entries
        return tree, entries

    # -- tree building -----------------------------------------------------
    def build_nodes(self) -> List[_Node]:
        """Compiled, output-pruned slices for the recorded path. Works
        backward: a slice fetches only the vars some LATER consumer
        (guard, later slice input, final output) reads — the cached
        replay env keeps them, so XLA can fuse/DCE everything else
        inside the slice. The "needed" set accumulates across ALL
        recordings of this signature (owner._needed), so a shared
        prefix slice rebuilt for path B still fetches what path A's
        suffix consumes."""
        all_ops = self.program.ops()
        per = []
        for spec in self.specs:
            ops = all_ops[spec.start:spec.stop]
            defined = {v.name for op in ops for v in op.outputs}
            consumed = {v.name for op in ops for v in op.inputs}
            consumed |= {v.name for op in ops
                         for v in op.kwargs.values()
                         if isinstance(v, Var)}
            per.append((spec, ops, defined, consumed))

        needed = {name for tag, name in self.out_entries if tag == "var"}
        for spec, _ops, _d, consumed in per:
            if spec.guard_name:
                needed.add(spec.guard_name)
            needed |= consumed
        acc = self.owner._needed.setdefault(self.sig, set())
        acc |= needed
        needed = set(acc)
        fetch_sets: List[set] = [set() for _ in per]
        for i in range(len(per) - 1, -1, -1):
            spec, ops, defined, consumed = per[i]
            fetch_sets[i] = defined & needed

        nodes = []
        feed_ok = set(self.feed_names)
        defined_before: set = set()
        for (spec, ops, defined, consumed), fetch in zip(per, fetch_sets):
            in_names = sorted(
                n for n in ((consumed | fetch) - defined)
                if n in feed_ok or n in defined_before)
            blk = self.program.global_block
            out_vars = [blk.vars[n] for n in sorted(fetch)]
            node = _Node()
            node.slice = _Slice(self.program, ops, in_names, out_vars)
            node.guard_name = spec.guard_name
            nodes.append(node)
            defined_before |= defined
        nodes[-1].out_tree = self.out_tree
        nodes[-1].out_entries = self.out_entries
        return nodes

    def graft(self):
        """Build compiled nodes for the recorded path and insert them
        into the owner's guard tree. The freshly built chain REPLACES
        the shared prefix (its fetch sets cover the union of all
        recorded paths' needs); divergent branches hanging off the old
        prefix are re-attached to the new nodes. Returns the chain."""
        nodes = self.build_nodes()
        for i in range(len(nodes) - 1):
            nodes[i].children[self.path_values[i]] = nodes[i + 1]
        old = self.owner.paths.get(self.sig)
        self.owner.paths[self.sig] = nodes[0]
        if old is None:
            return nodes
        node = old
        for i, v in enumerate(self.path_values):
            for val, child in node.children.items():
                if val != v:
                    nodes[i].children[val] = child
            nxt = node.children.get(v)
            if nxt is None:
                return nodes
            node = nxt
        return nodes


def _leaf_value(entry, env):
    tag, v = entry
    if tag == "var":
        val = env[v]
        # taped replays keep Tensors (with their grad graph) in the env
        return val if isinstance(val, Tensor) else Tensor(val)
    return Tensor(v) if isinstance(v, jax.Array) else v


class SegmentedFunction:
    """Callable running ``fn`` as compiled segments around eager
    islands, with a per-signature guard tree."""

    def __init__(self, fn, cache_key_fn):
        self.fn = fn
        self._cache_key = cache_key_fn
        self.paths: Dict[Any, _Node] = {}
        # per-sig union of env names any recorded path consumes (drives
        # cross-path-safe slice output pruning)
        self._needed: Dict[Any, set] = {}

    def __call__(self, args, kwargs):
        sig = self._cache_key(args, kwargs)
        root = self.paths.get(sig)
        if root is not None:
            hit = self._try_cached(root, args, kwargs)
            if hit is not _MISS:
                STATS["cached_path_hits"] += 1
                return hit
        return self._record(sig, args, kwargs)

    # -- cached fast path --------------------------------------------------
    def _feed_env(self, args, kwargs, taped):
        flat, _ = jax.tree_util.tree_flatten(
            (list(args), dict(kwargs)),
            is_leaf=lambda x: isinstance(x, Tensor))
        if taped:
            # keep the Tensor handles: they are the GradNode inputs, so
            # backward() reaches the caller's x.grad / param.grad
            return {f"leaf{i}": leaf for i, leaf in enumerate(flat)
                    if isinstance(leaf, Tensor)}
        return {f"leaf{i}": leaf._data for i, leaf in enumerate(flat)
                if isinstance(leaf, Tensor)}

    def _try_cached(self, node, args, kwargs):
        from ..core import state
        taped = state.grad_enabled()
        env = self._feed_env(args, kwargs, taped)
        try:
            while True:
                if taped:
                    node.slice.call_taped(env)
                else:
                    node.slice(env)
                if node.out_tree is not None:    # leaf
                    leaves = [_leaf_value(e, env)
                              for e in node.out_entries]
                    return jax.tree_util.tree_unflatten(node.out_tree,
                                                        leaves)
                gv = env[node.guard_name]
                v = _guard_value(gv._data if isinstance(gv, Tensor)
                                 else gv)
                child = node.children.get(v)
                if child is None:
                    return _MISS   # unseen branch outcome -> record
                node = child
        except Exception as e:
            raise SegmentCaptureError(
                f"cached segment replay failed: {type(e).__name__}: "
                f"{e}") from e

    # -- recording path ----------------------------------------------------
    def _record(self, sig, args, kwargs):
        from ..core import tensor as _ct
        from ..ops import _op as _opmod

        if self._n_paths(sig) >= MAX_PATHS_PER_SIG:
            # a continuous guard (e.g. ``float(loss)`` differing every
            # call) would otherwise re-record per call forever — strictly
            # slower than plain eager. Raising BEFORE fn runs is safe
            # (no side effects yet); the api layer pins this signature
            # into its eager set.
            raise SegmentCaptureError(
                f"guard tree saturated ({MAX_PATHS_PER_SIG} paths) — a "
                "continuous guard value is defeating the cache; this "
                "signature degrades to eager")
        STATS["recordings"] += 1
        rec = _Recorder(self, sig)
        try:
            sym_args, sym_kw = rec.symbolize(args, kwargs)
        except Exception as e:
            raise SegmentCaptureError(
                f"symbolize failed: {type(e).__name__}: {e}") from e
        prev_hook = _ct._SYMBOLIC_CONCRETIZE
        set_symbolic_concretize_hook(rec.concretize)
        prev_prog = _opmod.set_segment_program(rec.program)
        try:
            # exceptions from the user's own fn propagate as themselves
            # (api must NOT re-run fn for those — side effects)
            out = self.fn(*sym_args, **sym_kw)
        finally:
            set_symbolic_concretize_hook(prev_hook)
            _opmod.set_segment_program(prev_prog)
        try:
            from ..core import state as _state
            tree, entries = rec.finalize(out)
            nodes = rec.graft()
            if _state.grad_enabled():
                # the recording replay does not tape — produce the result
                # by replaying the JUST-RECORDED chain taped, without
                # consulting guards (they were already decided by fn with
                # these very inputs; re-checking them against compiled
                # slice values could miss on a last-ulp fusion difference
                # and would re-run fn, double-executing its side effects)
                env = self._feed_env(args, kwargs, taped=True)
                for node in nodes:
                    node.slice.call_taped(env)
                leaves = [_leaf_value(e, env) for e in entries]
                return jax.tree_util.tree_unflatten(tree, leaves)
            leaves = [_leaf_value(e, rec.env) for e in entries]
            return jax.tree_util.tree_unflatten(tree, leaves)
        except SegmentCaptureError:
            raise
        except Exception as e:
            raise SegmentCaptureError(
                f"finalize failed: {type(e).__name__}: {e}") from e

    def _n_paths(self, sig):
        """Number of complete cached paths (leaves) for a signature."""
        root = self.paths.get(sig)
        if root is None:
            return 0
        count = 0
        stack = [root]
        while stack:
            n = stack.pop()
            if n.out_tree is not None:
                count += 1
            stack.extend(n.children.values())
        return count


_MISS = object()
