"""paddle.jit — the compiled path (to_static / save / load).

Reference: python/paddle/jit/api.py (to_static:136, save, load) and the
dy2st machinery (SURVEY.md §2.3). TPU-native redesign:

- **Capture** is trace-based: the eager Layer/function runs once under
  ``jax.jit`` tracing with parameter/buffer handles temporarily rebound to
  tracers (the Tensor facade is a pytree, so the SAME model code serves both
  modes — no AST transpile or bytecode hook needed; those exist in the
  reference because torch-style mutation can't trace, our ops are pure).
- **Program cache** keyed by input shapes/dtypes/training-flag mirrors the
  reference's _ExecutorCache (base/executor.py:857): new input signature →
  new traced program (the reference's dynamic-shape buckets).
- **Autograd**: a to_static call in training mode is ONE tape node whose
  backward is the compiled vjp of the whole program — the static-graph
  backward of the reference (append_backward) collapses into jax.vjp of the
  jitted function; XLA compiles both passes.
- **Buffers** (BN stats etc.) are threaded as extra outputs and written back
  after each call, keeping in-place semantics without mutation inside jit.
"""
from __future__ import annotations

import functools
import os
import pickle
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import monitor as _monitor
from ..core import jax_compat as _jax_compat  # noqa: F401  (jax.export shim)
from ..core import enforce as E
from ..core import state
from ..core.dtype import convert_dtype
from ..core.tensor import Parameter, Tensor
from ..nn.layer.base import Layer

__all__ = ["to_static", "not_to_static", "InputSpec", "StaticFunction",
           "save", "load", "TranslatedLayer", "enable_to_static"]

_to_static_enabled = True


def enable_to_static(flag: bool):
    global _to_static_enabled
    _to_static_enabled = bool(flag)


class InputSpec:
    """paddle.static.InputSpec parity (shape may contain None: resolved at
    first trace; each distinct concrete signature compiles once).

    DimExpr-lite (reference: paddle/pir/include/dialect/shape/): a dim
    may be a NAME string instead of None — the same name appearing on
    two axes (of one or several inputs) asserts they are equal at every
    call, and ``to_static(constraints=[...])`` can relate names
    arithmetically ("S % 8 == 0"). Named dims also export as SHARED
    symbolic dims in jit.save."""

    def __init__(self, shape, dtype="float32", name=None, stop_gradient=True):
        self.shape = list(shape)
        for d in self.shape:
            if not (d is None or isinstance(d, (int, str))):
                raise E.InvalidArgumentError(
                    f"InputSpec dim must be int, None, or a symbolic "
                    f"name string; got {d!r}")
        self.dtype = convert_dtype(dtype)
        self.name = name
        self.stop_gradient = stop_gradient

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype})"


def _sig_of(x) -> tuple:
    if isinstance(x, Tensor):
        return ("T", tuple(x._data.shape), str(x._data.dtype),
                bool(x.stop_gradient))
    if isinstance(x, (jax.Array, np.ndarray)):
        return ("A", tuple(x.shape), str(x.dtype))
    if isinstance(x, (list, tuple)):
        return ("L", tuple(_sig_of(v) for v in x))
    if isinstance(x, dict):
        return ("D", tuple(sorted((k, _sig_of(v)) for k, v in x.items())))
    return ("P", repr(x))


class _Program:
    """One traced+compiled specialization (reference: a PIR Program +
    PirInterpreter instance in the _ExecutorCache)."""

    def __init__(self, jitted, out_tree_store):
        self.jitted = jitted
        self.out_tree_store = out_tree_store


class _GraphBreak(Exception):
    """Raised at trace time when the user function branches on a tensor
    value; full_graph=False converts it into an eager fallback (the
    reference's SOT graph-break semantics)."""


class StaticFunction:
    """Callable wrapper produced by ``to_static``
    (reference: dy2static/program_translator.py StaticFunction).

    ``bucket_batch=True`` enables batch-dim bucketing for INFERENCE
    paths: inputs whose leading dim varies are padded up to the next
    power-of-two bucket so XLA compiles one program per bucket instead
    of one per concrete batch — the TPU-native answer to the reference's
    symbolic-shape engine (static shapes, bounded recompiles). Outputs
    carrying the padded batch are sliced back.

    Contract: outputs must be row-wise in the batch — cross-batch
    reductions (batch-mean losses, BatchNorm training stats) would see
    the zero pad rows. When gradient recording is live the padding is
    skipped automatically (training uses exact shapes)."""

    def __init__(self, fn: Callable, layer: Optional[Layer] = None,
                 input_spec=None, build_strategy=None, full_graph=True,
                 bucket_batch=False, bucket_sizes=None,
                 bucket_seq=False, seq_axis=1, seq_bucket_sizes=None,
                 seq_pad_value=0, constraints=None):
        self._fn = fn
        self._layer = layer
        self._input_spec = input_spec
        # DimExpr-lite: named dims in input_spec + relational constraints
        from .constraints import DimConstraints
        self._constraints = DimConstraints(constraints) \
            if (constraints or self._spec_dim_names(input_spec)) else None
        if constraints and not self._spec_dim_names(input_spec):
            missing = self._constraints.names
            if missing:
                # constraints can only bind through named spec dims
                raise E.InvalidArgumentError(
                    f"to_static(constraints=...) names dims {sorted(missing)} "
                    "but input_spec declares no named dims",
                    hint="use InputSpec([None, 'S'], ...) style names")
        self._programs: Dict[tuple, _Program] = {}
        self._bucket_batch = bool(bucket_batch)
        self._bucket_sizes = sorted(bucket_sizes) if bucket_sizes else None
        self._bucket_seq = bool(bucket_seq)
        self._seq_axis = int(seq_axis)
        self._seq_bucket_sizes = sorted(seq_bucket_sizes)             if seq_bucket_sizes else None
        self._seq_pad_value = seq_pad_value
        # full_graph=False: a capture failure (data-dependent Python
        # branch) becomes a graph break — that signature runs eagerly
        # with a one-time warning, like the reference's SOT fallback.
        self._full_graph = bool(full_graph)
        self._eager_keys: set = set()
        self._segmented_keys: set = set()
        self._segmented = None
        # introspection-registry identity, assigned on first use: the
        # registry's records outlive this object, so they are keyed by
        # a process-unique uid, never id(self) (address reuse would
        # alias a successor function onto stale records)
        self._registry_uid = None
        functools.update_wrapper(self, fn)

    @staticmethod
    def _spec_dim_names(input_spec):
        """All symbolic dim names declared across the input specs."""
        names = set()
        for s in (input_spec or []):
            if isinstance(s, InputSpec):
                names.update(d for d in s.shape if isinstance(d, str))
        return names

    def _axis_name(self, axis: int):
        """The symbolic name bound to ``axis`` (first spec declaring
        one), or None — used to aim constraint pruning at the bucketed
        axis."""
        for s in (self._input_spec or []):
            if isinstance(s, InputSpec) and len(s.shape) > axis \
                    and isinstance(s.shape[axis], str):
                return s.shape[axis]
        return None

    def _check_dims(self, args):
        """Bind named spec dims against the call's concrete shapes;
        raise typed errors on name conflicts (the dim_a == dim_b
        relation) and on violated constraints."""
        if self._constraints is None:
            return
        bindings: dict = {}
        for spec, a in zip(self._input_spec or [], args):
            if not (isinstance(spec, InputSpec) and isinstance(a, Tensor)):
                continue
            shape = a._data.shape
            if len(spec.shape) != len(shape):
                raise E.InvalidArgumentError(
                    f"input rank {len(shape)} does not match "
                    f"InputSpec {spec.shape}")
            for axis, d in enumerate(spec.shape):
                if isinstance(d, int) and d >= 0 and d != shape[axis]:
                    raise E.InvalidArgumentError(
                        f"input dim {axis} is {shape[axis]}, InputSpec "
                        f"fixes it to {d}")
                if isinstance(d, str):
                    seen = bindings.setdefault(d, int(shape[axis]))
                    if seen != int(shape[axis]):
                        raise E.InvalidArgumentError(
                            f"symbolic dim {d!r} bound to both {seen} "
                            f"and {shape[axis]} in one call",
                            hint="the same name on two axes asserts "
                                 "they are equal (DimExpr relation)")
        self._constraints.check(bindings)

    def _admit_fn(self, axis: int):
        """Bucket-size predicate from the unary constraints on the
        name bound to ``axis``, or None when unconstrained."""
        if self._constraints is None:
            return None
        name = self._axis_name(axis)
        if name is None or name not in self._constraints.names:
            return None
        return lambda b: self._constraints.admits(name, b)

    @staticmethod
    def _pick_bucket(n: int, sizes, admit=None) -> int:
        if sizes:
            for b in sizes:
                if n <= b and (admit is None or admit(b)):
                    return b
            return n          # beyond the largest bucket: run unbucketed
        b = 1
        while b < n:
            b <<= 1
        if admit is not None and not admit(b):
            # the power-of-two ladder violates a unary constraint on
            # this dim (e.g. "S % 96 == 0"): take the smallest admitted
            # size >= n within a bounded scan, else run unbucketed (the
            # real size already passed _check_dims)
            for c in range(n, 4 * b + 1):
                if admit(c):
                    return c
            return n
        return b

    def _bucket_of(self, n: int) -> int:
        return self._pick_bucket(n, self._bucket_sizes,
                                 admit=self._admit_fn(0))

    def _apply_bucketing(self, args):
        """Pad every Tensor arg's leading dim from the common batch size
        to its bucket; returns (padded_args, real_batch or None,
        padded_batch).

        Bucketing is an INFERENCE-path feature (serving variable batch):
        the padded rows flow through the function, so outputs must be
        row-wise in the batch; and because padding rebuilds inputs, it
        only engages while grad recording is off (paddle.no_grad() /
        eval serving) — training always uses exact shapes (correct beats
        fewer compiles). Closure-captured parameters are invisible here,
        so grad state is the only safe gate."""
        if state.grad_enabled():
            return args, None, None
        batches = {a._data.shape[0] for a in args
                   if isinstance(a, Tensor) and a._data.ndim > 0}
        if len(batches) != 1:
            return args, None, None
        (n,) = batches
        b = self._bucket_of(int(n))
        if b == n:
            return args, None, None
        import jax.numpy as _jnp

        def pad(a):
            if isinstance(a, Tensor) and a._data.ndim > 0 \
                    and a._data.shape[0] == n:
                widths = [(0, b - n)] + [(0, 0)] * (a._data.ndim - 1)
                return Tensor(_jnp.pad(a._data, widths))
            return a
        return tuple(pad(a) for a in args), int(n), int(b)

    def _seq_bucket_of(self, n: int) -> int:
        return self._pick_bucket(n, self._seq_bucket_sizes,
                                 admit=self._admit_fn(self._seq_axis))

    def _apply_seq_bucketing(self, args):
        """Pad the sequence axis to its bucket (the reference's dynamic
        seq-len bucketing policy for serving). SOUND for causal /
        right-context-free computations only: right-padding cannot
        change the outputs at real positions of a causal model (position
        i attends to <= i), so slicing the pad tail back off is EXACT —
        no mask plumbing needed. Non-causal models must consume an
        explicit mask themselves or keep bucket_seq off. Inference-only
        like batch bucketing (skipped while grads record).

        Coincidence hazard (like batch bucketing's): any output whose
        ``seq_axis`` dim equals the padded bucket is sliced — a feature
        dim that lands exactly on a bucket (both are often powers of
        two) would be truncated. Choose ``seq_bucket_sizes`` that avoid
        the model's feature dims when outputs mix axes."""
        if state.grad_enabled():
            return args, None, None
        axis = self._seq_axis
        lens = {a._data.shape[axis] for a in args
                if isinstance(a, Tensor) and a._data.ndim > axis}
        if len(lens) != 1:
            return args, None, None
        (n,) = lens
        b = self._seq_bucket_of(int(n))
        if b == n:
            return args, None, None
        import jax.numpy as _jnp

        def pad(a):
            if isinstance(a, Tensor) and a._data.ndim > axis                     and a._data.shape[axis] == n:
                widths = [(0, 0)] * a._data.ndim
                widths[axis] = (0, b - n)
                return Tensor(_jnp.pad(a._data, widths,
                                       constant_values=self._seq_pad_value))
            return a
        return tuple(pad(a) for a in args), int(n), int(b)

    # -- helpers -------------------------------------------------------------
    def _named_params(self):
        if self._layer is None:
            return []
        return [(n, p) for n, p in self._layer.named_parameters()
                if p is not None]

    def _named_buffers(self):
        if self._layer is None:
            return []
        return [(n, b) for n, b in self._layer.named_buffers()
                if b is not None]

    def _cache_key(self, args, kwargs):
        training = self._layer.training if self._layer is not None else False
        return (_sig_of(args), _sig_of(kwargs), training,
                tuple(str(p._data.dtype) for _, p in self._named_params()))

    def _build_program(self, args, kwargs) -> _Program:
        named_params = self._named_params()
        named_buffers = self._named_buffers()
        fn = self._fn
        out_store: dict = {}

        def pure(param_arrays, buffer_arrays, arg_arrays, kwarg_arrays):
            # Rebind handles to tracers for the duration of the trace,
            # restore after (the handles belong to live eager objects).
            saved_p = [(p, p._data) for _, p in named_params]
            saved_b = [(b, b._data) for _, b in named_buffers]
            try:
                for (n, p) in named_params:
                    p._data = param_arrays[n]
                for (n, b) in named_buffers:
                    b._data = buffer_arrays[n]
                with state.functional_mode():
                    try:
                        out = fn(*arg_arrays, **kwarg_arrays)
                    except (jax.errors.TracerBoolConversionError,
                            jax.errors.ConcretizationTypeError) as e:
                        raise _GraphBreak(
                            "to_static: the function branches on a tensor "
                            "VALUE, which trace-based capture cannot "
                            "record (the reference's SOT guards exist for "
                            "this — jit/sot/translate.py). Rewrite the "
                            "branch with paddle_tpu.where / lax.cond, or "
                            "keep it out of the to_static region. Python "
                            "branches on non-tensor values are baked at "
                            "trace time per input signature. "
                            "(full_graph=False falls back to eager "
                            "execution instead of raising — the "
                            "reference's SOT graph-break behavior.)") from e
                new_buffers = {n: b._data for n, b in named_buffers}
                flat, tree = jax.tree_util.tree_flatten(
                    out, is_leaf=lambda x: isinstance(x, Tensor))
                flat = [o._data if isinstance(o, Tensor) else o for o in flat]
                out_store["tree"] = tree
                out_store["n_out"] = len(flat)
                return tuple(flat), new_buffers
            finally:
                for p, d in saved_p:
                    p._data = d
                for b, d in saved_b:
                    b._data = d

        return _Program(jax.jit(pure), out_store)

    def __call__(self, *args, **kwargs):
        if not _to_static_enabled:
            return self._fn(*args, **kwargs)
        self._check_dims(args)
        real_batch = None
        seq_pad = None
        if self._bucket_batch and not kwargs:
            args, real_batch, padded_batch = self._apply_bucketing(args)
        if self._bucket_seq and not kwargs:
            args, real_seq, padded_seq = self._apply_seq_bucketing(args)
            if real_seq is not None:
                seq_pad = (self._seq_axis, real_seq, padded_seq)
        if seq_pad is not None and real_batch is None:
            out = self.__wrapped_call(args, kwargs)
            return self._unpad_seq(out, *seq_pad)
        if real_batch is not None:
            out = self.__wrapped_call(args, kwargs)
            # Ranks of the padded inputs: an output that is batch-major
            # normally keeps one of these ranks. Slicing an output whose
            # leading dim merely COINCIDES with the bucket size (e.g. a
            # [num_classes, ...] table where num_classes == bucket) would
            # silently truncate it — warn when the rank heuristic says the
            # sliced output doesn't look like any padded input.
            in_ranks = {a._data.ndim for a in args
                        if isinstance(a, Tensor) and a._data.ndim > 0}
            odd_ranks = []

            def unpad(o):
                if isinstance(o, Tensor) and o._data.ndim > 0 \
                        and o._data.shape[0] == padded_batch:
                    # Reduced-rank outputs ([B] predictions from [B, F]
                    # inputs) are normal batch-major shapes; only an
                    # output of HIGHER rank than every padded input looks
                    # like a non-batch table caught by coincidence.
                    if o._data.ndim > max(in_ranks):
                        odd_ranks.append(o._data.ndim)
                    return Tensor(o._data[:real_batch])
                return o
            out = jax.tree_util.tree_map(
                unpad, out, is_leaf=lambda x: isinstance(x, Tensor))
            if odd_ranks:   # warn AFTER tree_map so file:line is the caller
                import warnings

                warnings.warn(
                    "to_static bucketing: sliced output(s) of rank(s) "
                    f"{sorted(set(odd_ranks))} whose leading dim == bucket "
                    f"size {padded_batch} but whose rank matches no padded "
                    "input — if such an output is not batch-major, disable "
                    "bucket_batch for this function", stacklevel=2)
            if seq_pad is not None:
                out = self._unpad_seq(out, *seq_pad)
            return out
        return self.__wrapped_call(args, kwargs)

    def _unpad_seq(self, out, axis, real, padded):
        def unpad(o):
            if isinstance(o, Tensor) and o._data.ndim > axis                     and o._data.shape[axis] == padded:
                idx = [slice(None)] * o._data.ndim
                idx[axis] = slice(0, real)
                return Tensor(o._data[tuple(idx)])
            return o
        return jax.tree_util.tree_map(
            unpad, out, is_leaf=lambda x: isinstance(x, Tensor))

    def __wrapped_call(self, args, kwargs):
        key = self._cache_key(args, kwargs)
        if key in self._eager_keys:
            return self._fn(*args, **kwargs)
        if key in self._segmented_keys:
            return self.__segmented_call(key, args, kwargs)
        try:
            return self.__compiled_call(key, args, kwargs)
        except _GraphBreak as e:
            if self._full_graph:
                raise E.PreconditionNotMetError(str(e)) from e
            import warnings

            # mixed capture (reference SOT, jit/sot/translate.py:30):
            # this signature now runs as compiled segments around the
            # eager island — in BOTH eval and training mode (taped
            # slices carry cached vjps, segment.py call_taped).
            self._segmented_keys.add(key)
            self._programs.pop(key, None)
            warnings.warn(
                "to_static: graph break in "
                f"{getattr(self._fn, '__name__', self._fn)} "
                "(data-dependent Python branch); this input "
                "signature runs as compiled segments around the "
                "branch (full_graph=False)", stacklevel=3)
            return self.__segmented_call(key, args, kwargs)

    def __segmented_call(self, key, args, kwargs):
        if self._segmented is None:
            from .segment import SegmentedFunction
            self._segmented = SegmentedFunction(self._fn, self._cache_key)
        from .segment import SegmentCaptureError
        try:
            return self._segmented(args, kwargs)
        except SegmentCaptureError as e:
            # recorder/replay-internal failure degrades to eager; the
            # user's own exceptions propagate (re-running fn here would
            # double-execute its side effects)
            import warnings

            warnings.warn(
                "to_static: segmented capture failed for "
                f"{getattr(self._fn, '__name__', self._fn)} ({e}); this "
                "input signature now runs eagerly", stacklevel=2)
            self._segmented_keys.discard(key)
            self._eager_keys.add(key)
            return self._fn(*args, **kwargs)

    def __compiled_call(self, key, args, kwargs):
        prog = self._programs.get(key)
        t_compile = None
        exec_rec = None
        if prog is None:
            if _monitor.enabled():
                # program-cache miss == a fresh trace+compile; a miss on
                # a StaticFunction that ALREADY holds programs is a
                # recompile (new input signature / training flip) — the
                # reference's _ExecutorCache growth events.
                _monitor.inc("jit.cache.miss",
                             doc="to_static program-cache misses")
                if self._programs:
                    _monitor.inc("jit.recompile",
                                 doc="cache misses after the first "
                                     "program (signature churn)")
                t_compile = time.perf_counter()
            prog = self._build_program(args, kwargs)
            self._programs[key] = prog
        elif _monitor.enabled():
            _monitor.inc("jit.cache.hit",
                         doc="to_static program-cache hits")
            from ..monitor import exectime as _exectime
            from ..monitor import programs as _programs
            _programs.note_hit(self._registry_key(key))
            # measured execution plane: 1-in-N sampled wall time of
            # HIT dispatches only (a miss's wall time is compile —
            # jit.compile_ms already owns it). The recorder blocks on
            # the sampled call's outputs below; unsampled calls and
            # the off path add zero synchronizations.
            exec_rec = _exectime.maybe_sample(self._registry_key(key))

        named_params = self._named_params()
        named_buffers = self._named_buffers()
        param_arrays = {n: p._data for n, p in named_params}
        buffer_arrays = {n: b._data for n, b in named_buffers}
        arg_arrays = jax.tree_util.tree_map(
            lambda x: x._data if isinstance(x, Tensor) else x, args,
            is_leaf=lambda x: isinstance(x, Tensor))
        kwarg_arrays = jax.tree_util.tree_map(
            lambda x: x._data if isinstance(x, Tensor) else x, kwargs,
            is_leaf=lambda x: isinstance(x, Tensor))

        trainable = [(n, p) for n, p in named_params if not p.stop_gradient]
        diff_args: List[Tuple[int, Tensor]] = [
            (i, a) for i, a in enumerate(args)
            if isinstance(a, Tensor) and not a.stop_gradient
            and jnp.issubdtype(a._data.dtype, jnp.inexact)]
        need_grad = state.grad_enabled() and (trainable or diff_args)

        if not need_grad:
            flat_out, new_buffers = prog.jitted(
                param_arrays, buffer_arrays, arg_arrays, kwarg_arrays)
            if exec_rec is not None:
                exec_rec((flat_out, new_buffers))
            compile_ms = self._note_compile(t_compile)
            if t_compile is not None:
                from ..monitor import mfu as _mfu
                cost = _mfu.lowered_cost(
                    prog.jitted, param_arrays, buffer_arrays,
                    arg_arrays, kwarg_arrays)
                _mfu.record_program_flops(cost["flops"],
                                          source="to_static")
                self._register_program(
                    key, prog, compile_ms, cost, param_arrays,
                    buffer_arrays, arg_arrays, kwarg_arrays)
        else:
            train_names = [n for n, _ in trainable]
            diff_idx = [i for i, _ in diff_args]

            def closed(train_arrays, diff_arg_arrays):
                pa = dict(param_arrays)
                pa.update(train_arrays)
                aa = list(arg_arrays)
                for i, arr in zip(diff_idx, diff_arg_arrays):
                    aa[i] = arr
                return prog.jitted(pa, buffer_arrays, tuple(aa),
                                   kwarg_arrays)

            train_arrays = {n: p._data for n, p in trainable}
            diff_arg_arrays = tuple(a._data for _, a in diff_args)
            (flat_out, new_buffers), vjp_fn = jax.vjp(
                closed, train_arrays, diff_arg_arrays)
            if exec_rec is not None:
                # the grad path re-traces the vjp composition per call,
                # so a sample here measures the TRAINING dispatch's
                # wall time (trace + forward execution) — the number a
                # drift detector actually wants for this seam
                exec_rec((flat_out, new_buffers))
            compile_ms = self._note_compile(t_compile)
            if t_compile is not None:
                # MFU accounting must count what a TRAINING call
                # executes — forward AND backward — so lower the same
                # vjp composition run above, not just prog.jitted
                # (forward alone under-counts ~3x). Falls back to the
                # forward program if the composed lowering can't be
                # analyzed.
                from ..monitor import mfu as _mfu

                def _full_step(ta, da):
                    out, inner_vjp = jax.vjp(closed, ta, da)
                    cts = jax.tree_util.tree_map(
                        _mfu.ones_cotangent, out)
                    # return out too: the real call materializes the
                    # forward results, so the analyzed program must
                    # keep them live (grads alone let XLA DCE any
                    # forward op the backward doesn't reuse)
                    return out, inner_vjp(cts)

                cost = _mfu.lowered_cost(
                    jax.jit(_full_step), train_arrays, diff_arg_arrays)
                if not cost["flops"]:
                    cost = _mfu.lowered_cost(
                        prog.jitted, param_arrays, buffer_arrays,
                        arg_arrays, kwarg_arrays)
                _mfu.record_program_flops(cost["flops"],
                                          source="to_static")
                self._register_program(
                    key, prog, compile_ms, cost, param_arrays,
                    buffer_arrays, arg_arrays, kwarg_arrays)

            input_tensors = [p for _, p in trainable] + \
                [a for _, a in diff_args]
            zero_bufs = {n: jnp.zeros_like(v)
                         for n, v in new_buffers.items()}

            def tape_vjp(cotangents):
                cts = cotangents if isinstance(cotangents, tuple) else \
                    (cotangents,)
                g_train, g_args = vjp_fn((tuple(cts), zero_bufs))
                return [g_train[n] for n in train_names] + list(g_args)

            from ..autograd import tape
            out_tensors = [Tensor(o) for o in flat_out]
            tape.record_node(f"to_static[{self._fn.__name__}]", tape_vjp,
                             input_tensors, out_tensors)
            for n, b in named_buffers:
                b._data = new_buffers[n]
            tree = prog.out_tree_store["tree"]
            wrapped = jax.tree_util.tree_unflatten(tree, out_tensors)
            return wrapped

        for n, b in named_buffers:
            b._data = new_buffers[n]
        tree = prog.out_tree_store["tree"]
        return jax.tree_util.tree_unflatten(
            tree, [Tensor(o) for o in flat_out])

    @staticmethod
    def _note_compile(t_compile):
        """Observe trace+compile latency for a cache-miss call (timed
        through the first execution, where jax.jit actually compiles);
        returns the ms (None on cache hits). The caller follows up with
        the MFU capture — the new program's XLA-cost-analysis FLOPs
        into ``jit.program.flops`` (one extra re-trace + HLO lowering
        per compile; no second XLA compile — see monitor/mfu.py) —
        lowering the grad-path vjp composition where one exists so
        training programs count fwd+bwd FLOPs — and the introspection-
        registry record (``_register_program``)."""
        if t_compile is None:
            return None
        ms = (time.perf_counter() - t_compile) * 1e3
        _monitor.observe(
            "jit.compile_ms", ms,
            doc="to_static trace+compile wall time per cache miss",
            buckets=tuple(float(10 ** i) / 10 for i in range(9)))
        return ms

    def _registry_key(self, key):
        if self._registry_uid is None:
            from ..monitor import programs as _programs
            self._registry_uid = _programs.next_uid()
        return ("to_static", self._registry_uid, key)

    def _register_program(self, key, prog, compile_ms, cost,
                          param_arrays, buffer_arrays, arg_arrays,
                          kwarg_arrays):
        """Feed the compiled-program introspection registry
        (monitor/programs.py) at the cache-miss seam: name, input
        signature, compile wall-ms, analyzed FLOPs + bytes-accessed
        (``cost`` = monitor.mfu.lowered_cost result), the per-leaf
        sharding summary of the concrete params/args (the ``/sharding``
        endpoint's per-program feed), and a LAZY memory+collective
        analyzer over the forward program's avals (the ``/programs`` /
        ``/roofline`` endpoints pay the one AOT compile, not this
        call). Grad-path programs record the forward program's memory
        breakdown — the executable this cache actually holds."""
        from ..monitor import programs as _programs
        args = (param_arrays, buffer_arrays, arg_arrays, kwarg_arrays)
        try:
            from ..distributed import introspect as _introspect
            sharding = _introspect.describe_tree(
                {"params": param_arrays, "args": arg_arrays,
                 "kwargs": kwarg_arrays})
        except Exception:
            sharding = None
        _programs.record_program(
            self._registry_key(key),
            getattr(self._fn, "__name__", "to_static"),
            source="to_static",
            signature=_programs.signature_of((arg_arrays, kwarg_arrays)),
            donated=(),
            compile_ms=round(compile_ms, 3)
            if compile_ms is not None else None,
            flops=cost["flops"],
            bytes_accessed=cost["bytes_accessed"],
            sharding=sharding,
            analyzer=_programs.analyzer_for(prog.jitted, args))

    @property
    def concrete_programs(self):
        return self._programs

    def rollback(self):
        return self._fn


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, full_graph=True, bucket_batch=False,
              bucket_sizes=None, bucket_seq=False, seq_axis=1,
              seq_bucket_sizes=None, seq_pad_value=0, constraints=None,
              **kwargs):
    """paddle.jit.to_static parity (reference: jit/api.py:136).
    ``bucket_batch``/``bucket_sizes``: see StaticFunction — pad variable
    leading dims to buckets so XLA recompiles O(log max_batch) times.
    ``bucket_seq``/``seq_axis``/``seq_bucket_sizes``/``seq_pad_value``:
    the same policy for the SEQUENCE axis (serving variable-length
    prompts with O(log max_len) compiles). Exact for causal models
    (right-padding cannot influence real positions); non-causal
    functions must consume a mask themselves. ``full_graph=False``:
    data-dependent Python branches run as compiled segments around the
    break (jit/segment.py) instead of erroring."""
    extra = dict(bucket_batch=bucket_batch, bucket_sizes=bucket_sizes,
                 bucket_seq=bucket_seq, seq_axis=seq_axis,
                 seq_bucket_sizes=seq_bucket_sizes,
                 seq_pad_value=seq_pad_value,
                 full_graph=full_graph, constraints=constraints)

    def decorate(obj):
        if isinstance(obj, Layer):
            sf = StaticFunction(obj.forward, layer=obj,
                                input_spec=input_spec, **extra)
            obj.forward = sf
            return obj
        layer = getattr(obj, "__self__", None)
        if isinstance(layer, Layer):
            return StaticFunction(obj, layer=layer, input_spec=input_spec,
                                  **extra)
        return StaticFunction(obj, layer=None, input_spec=input_spec,
                              **extra)

    if function is not None:
        return decorate(function)
    return decorate


def not_to_static(fn):
    fn.__not_to_static__ = True
    return fn


# ---------------------------------------------------------------------------
# save / load: StableHLO export (reference: jit.save -> .pdmodel/.pdiparams)
# ---------------------------------------------------------------------------

def _resolve_specs(layer, input_spec):
    """InputSpec dims of None export as *symbolic* dims (jax.export shape
    polymorphism) so the artifact serves any size on those axes — the
    dynamic-dim behavior of the reference's exported programs."""
    specs = []
    scope = jax.export.SymbolicScope()
    syms = {}

    def _dim(d, axis):
        if isinstance(d, str):
            # named symbolic dim (DimExpr-lite): shared across inputs
            # by NAME, so ids/mask pairs declared with the same name
            # export as one program-level symbol
            if d not in syms:
                syms[d] = jax.export.symbolic_shape(d, scope=scope)[0]
            return syms[d]
        if d is None or (isinstance(d, int) and d < 0):
            # One shared symbol per axis position: None batch dims of
            # different inputs must unify (ids/mask pairs broadcast
            # together), matching the reference where a dynamic dim is a
            # program-level symbol, not per-input.
            if axis not in syms:
                syms[axis] = jax.export.symbolic_shape(
                    f"dyn_d{axis}", scope=scope)[0]
            return syms[axis]
        return int(d)

    for s in input_spec:
        if isinstance(s, InputSpec):
            shape = tuple(_dim(d, i) for i, d in enumerate(s.shape))
            specs.append(jax.ShapeDtypeStruct(shape, s.dtype))
        elif isinstance(s, Tensor):
            specs.append(jax.ShapeDtypeStruct(tuple(s._data.shape),
                                              s._data.dtype))
        else:
            arr = jnp.asarray(s)
            specs.append(jax.ShapeDtypeStruct(arr.shape, arr.dtype))
    return specs


def save(layer, path, input_spec=None, **configs):
    """paddle.jit.save parity: writes ``path.pdmodel`` (serialized StableHLO
    program via jax.export), ``path.pdiparams`` (weights), ``path.pdmeta``
    (treedefs). The artifact is hermetic: load() does not need the model
    class."""
    if isinstance(layer, StaticFunction):
        fn, owner = layer._fn, layer._layer
        input_spec = input_spec or layer._input_spec
    elif isinstance(layer, Layer):
        fwd = layer.forward
        if isinstance(fwd, StaticFunction):
            fn, owner = fwd._fn, layer
            input_spec = input_spec or fwd._input_spec
        else:
            fn, owner = fwd, layer
    else:
        fn, owner = layer, None

    if input_spec is None:
        raise E.InvalidArgumentError(
            "jit.save requires input_spec (pass it here or to to_static)")
    specs = _resolve_specs(owner, input_spec)

    named_params = [] if owner is None else \
        [(n, p) for n, p in owner.named_parameters()]
    named_buffers = [] if owner is None else \
        [(n, b) for n, b in owner.named_buffers()]
    if owner is not None:
        was_training = owner.training
        owner.eval()

    out_store = {}

    def pure(param_arrays, buffer_arrays, *arg_arrays):
        saved_p = [(p, p._data) for _, p in named_params]
        saved_b = [(b, b._data) for _, b in named_buffers]
        try:
            for (n, p) in named_params:
                p._data = param_arrays[n]
            for (n, b) in named_buffers:
                b._data = buffer_arrays[n]
            with state.functional_mode():
                out = fn(*arg_arrays)
            flat, tree = jax.tree_util.tree_flatten(
                out, is_leaf=lambda x: isinstance(x, Tensor))
            out_store["tree_pickle"] = pickle.dumps(tree)
            return tuple(o._data if isinstance(o, Tensor) else o
                         for o in flat)
        finally:
            for p, d in saved_p:
                p._data = d
            for b, d in saved_b:
                b._data = d

    param_specs = {n: jax.ShapeDtypeStruct(tuple(p._data.shape),
                                           p._data.dtype)
                   for n, p in named_params}
    buffer_specs = {n: jax.ShapeDtypeStruct(tuple(b._data.shape),
                                            b._data.dtype)
                    for n, b in named_buffers}
    exported = jax.export.export(jax.jit(pure))(
        param_specs, buffer_specs, *specs)

    os.makedirs(os.path.dirname(os.path.abspath(path)) or ".",
                exist_ok=True)
    with open(path + ".pdmodel", "wb") as f:
        f.write(exported.serialize())
    from ..framework.io import save as fsave
    fsave({"params": {n: p for n, p in named_params},
           "buffers": {n: b for n, b in named_buffers}},
          path + ".pdiparams")
    with open(path + ".pdmeta", "wb") as f:
        pickle.dump({"out_tree": out_store["tree_pickle"],
                     "n_inputs": len(specs)}, f)
    if owner is not None and was_training:
        owner.train()


class TranslatedLayer(Layer):
    """Deserialized inference program (reference:
    jit/translated_layer.py TranslatedLayer)."""

    def __init__(self, exported, params, buffers, out_tree):
        super().__init__()
        self._exported = exported
        self._param_arrays = {n: (p._data if isinstance(p, Tensor)
                                  else jnp.asarray(np.asarray(p)))
                              for n, p in params.items()}
        self._buffer_arrays = {n: (b._data if isinstance(b, Tensor)
                                   else jnp.asarray(np.asarray(b)))
                               for n, b in buffers.items()}
        for n, arr in self._param_arrays.items():
            self.add_parameter(n.replace(".", "__"), Parameter(arr))
        self._out_tree = out_tree

    def forward(self, *args):
        arg_arrays = [a._data if isinstance(a, Tensor) else jnp.asarray(a)
                      for a in args]
        flat = self._exported.call(self._param_arrays, self._buffer_arrays,
                                   *arg_arrays)
        return jax.tree_util.tree_unflatten(
            self._out_tree, [Tensor(o) for o in flat])


def load(path, **configs) -> TranslatedLayer:
    """paddle.jit.load parity."""
    with open(path + ".pdmodel", "rb") as f:
        exported = jax.export.deserialize(f.read())
    from ..framework.io import load as fload
    blob = fload(path + ".pdiparams")
    with open(path + ".pdmeta", "rb") as f:
        meta = pickle.load(f)
    out_tree = pickle.loads(meta["out_tree"])
    return TranslatedLayer(exported, blob["params"], blob["buffers"],
                           out_tree)
