"""DimExpr-lite: symbolic dimension names + relational constraints for
``to_static`` (VERDICT-r4 item 7).

Reference capability: `paddle/pir/include/dialect/shape/` — the DimExpr
dialect lets programs carry symbolic dims with RELATIONS between them
(equalities, divisibility) that the compiler checks and exploits; CINN's
symbolic buckets compile one program per constraint-satisfying shape
class. TPU-native scope: XLA wants static shapes, so the constraint
system here does the two jobs that survive that design point:

1. **Capture-time checking.** ``InputSpec`` dims may be NAMES
   (``InputSpec([None, "S"])``); using one name in two places asserts
   equality across inputs (the `dim_a == dim_b` relation), and
   ``to_static(constraints=["S % 8 == 0", "B <= 64"])`` adds arbitrary
   arithmetic relations. Violations raise typed
   ``InvalidArgumentError``s naming the constraint and the observed
   values — at the call boundary, not as a shape error three layers
   into a traced function.
2. **Bucket pruning.** The batch/seq bucketing policies pad dims up to
   bucket sizes; a bucket that violates a unary constraint on the
   bucketed dim would compile a program whose shape the user declared
   impossible. Constraint-aware bucket choice skips those sizes (e.g.
   ``S % 128 == 0`` turns the power-of-two ladder into multiples of
   128), so every compiled specialization satisfies the declared
   relations.

The expression language is Python's own arithmetic/comparison subset
over dim names — parsed with ``ast`` and restricted to a whitelist, so
a constraint string cannot execute anything.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Sequence

from ..core import enforce as E

__all__ = ["DimConstraints"]

_ALLOWED = (
    ast.Expression, ast.Compare, ast.BoolOp, ast.BinOp, ast.UnaryOp,
    ast.Name, ast.Constant, ast.Load,
    ast.And, ast.Or, ast.Not,
    ast.Eq, ast.NotEq, ast.Lt, ast.LtE, ast.Gt, ast.GtE,
    ast.Add, ast.Sub, ast.Mult, ast.FloorDiv, ast.Mod, ast.Pow,
    ast.USub, ast.UAdd,
)


class DimConstraints:
    """A set of relations over named symbolic dims."""

    def __init__(self, exprs: Optional[Iterable[str]] = None):
        self.exprs: List[str] = [str(e) for e in (exprs or [])]
        self._compiled = []
        for expr in self.exprs:
            self._compiled.append(self._compile(expr))

    @staticmethod
    def _compile(expr: str):
        try:
            tree = ast.parse(expr, mode="eval")
        except SyntaxError as e:
            raise E.InvalidArgumentError(
                f"invalid dim constraint {expr!r}: {e.msg}",
                hint="constraints are boolean expressions over dim "
                     "names, e.g. 'S % 8 == 0' or 'B <= 64'") from e
        for node in ast.walk(tree):
            if not isinstance(node, _ALLOWED):
                raise E.InvalidArgumentError(
                    f"dim constraint {expr!r} uses disallowed syntax "
                    f"({type(node).__name__})",
                    hint="only names, integers, + - * // % **, "
                         "comparisons, and and/or/not are allowed")
            if isinstance(node, ast.Constant) and not isinstance(
                    node.value, (int, bool)):
                raise E.InvalidArgumentError(
                    f"dim constraint {expr!r}: constant {node.value!r} "
                    "is not an integer (dims are integers)")
        names = frozenset(n.id for n in ast.walk(tree)
                          if isinstance(n, ast.Name))
        if not names:
            raise E.InvalidArgumentError(
                f"dim constraint {expr!r} names no dimension",
                hint="a constraint must mention at least one InputSpec "
                     "dim name")
        code = compile(tree, "<dim-constraint>", "eval")
        return code, names

    @property
    def names(self) -> frozenset:
        out = frozenset()
        for _, ns in self._compiled:
            out |= ns
        return out

    # -- capture-time checking ----------------------------------------------
    def check(self, bindings: Dict[str, int]):
        """Evaluate every constraint whose names are all bound; raise a
        typed error naming the violated relation and the observed
        values. Partially-bound constraints are skipped (the caller may
        bind more dims later)."""
        for expr, (code, names) in zip(self.exprs, self._compiled):
            if not names <= bindings.keys():
                continue
            env = {n: int(bindings[n]) for n in names}
            if not eval(code, {"__builtins__": {}}, env):   # noqa: S307
                seen = ", ".join(f"{n}={env[n]}" for n in sorted(names))
                raise E.InvalidArgumentError(
                    f"dim constraint violated: {expr!r} with {seen}",
                    hint="declared via to_static(constraints=...) / "
                         "InputSpec dim names")

    def admits(self, name: str, value: int) -> bool:
        """Would binding ``name=value`` satisfy every UNARY constraint
        on ``name``? (Multi-dim relations can't veto a single bucket
        choice — they are checked against real bindings instead.)"""
        for _, (code, names) in zip(self.exprs, self._compiled):
            if names == {name} and not eval(
                    code, {"__builtins__": {}}, {name: int(value)}):
                return False
        return True

    def prune(self, name: str, sizes: Sequence[int]) -> List[int]:
        """Filter candidate bucket sizes to those the unary constraints
        on ``name`` admit."""
        return [s for s in sizes if self.admits(name, s)]
