"""paddle.jit parity surface (reference: python/paddle/jit/__init__.py)."""
from .api import (InputSpec, StaticFunction, TranslatedLayer,  # noqa
                  enable_to_static, load, not_to_static, save, to_static)
