"""paddle.jit parity surface (reference: python/paddle/jit/__init__.py)."""
from .api import (InputSpec, StaticFunction, TranslatedLayer,  # noqa
                  enable_to_static, load, not_to_static, save, to_static)


# -- verbosity/logging controls (reference: jit/dy2static/logging_utils.py) -
_code_level = 0
_verbosity = 0


def set_code_level(level=100, also_to_stdout=False):
    """Set how much transformed code is logged (parity surface; trace-based
    capture has one level of 'transformed code' — the jaxpr)."""
    global _code_level
    _code_level = level


def set_verbosity(level=0, also_to_stdout=False):
    global _verbosity
    _verbosity = level


_ignored_modules = set()


def ignore_module(modules):
    """Mark modules whose functions are never treated as user code during
    capture (reference: jit/api.py ignore_module)."""
    if not isinstance(modules, (list, tuple)):
        modules = [modules]
    _ignored_modules.update(modules)
