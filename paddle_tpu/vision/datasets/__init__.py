"""paddle.vision.datasets parity (reference: vision/datasets/).

The reference downloads MNIST/Cifar/Flowers at first use; this environment
has no egress, so these classes load from a local `data_file`/`image_path`
and raise a clear error when absent. `FakeData` provides synthetic images
for smoke tests (analogue of the reference test fixtures)."""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from ...io import Dataset
from ...core import enforce as E

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "FakeData"]


class FakeData(Dataset):
    """Synthetic image classification dataset."""

    def __init__(self, size=256, image_shape=(3, 32, 32), num_classes=10,
                 transform=None, seed=0):
        rng = np.random.default_rng(seed)
        self.images = rng.normal(size=(size, *image_shape)).astype("float32")
        self.labels = rng.integers(0, num_classes, size=size).astype("int64")
        self.transform = transform

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)


class MNIST(Dataset):
    """reference vision/datasets/mnist.py — loads idx-format files from
    ``image_path``/``label_path`` (no auto-download here)."""

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend=None):
        if image_path is None or label_path is None:
            raise FileNotFoundError(
                "MNIST auto-download is unavailable (no network); pass "
                "image_path= and label_path= to local idx(.gz) files")
        self.transform = transform
        self.images = self._read_images(image_path)
        self.labels = self._read_labels(label_path)

    @staticmethod
    def _open(path):
        return gzip.open(path, "rb") if path.endswith(".gz") \
            else open(path, "rb")

    def _read_images(self, path):
        with self._open(path) as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            data = np.frombuffer(f.read(), dtype=np.uint8)
        return data.reshape(n, rows, cols)

    def _read_labels(self, path):
        with self._open(path) as f:
            magic, n = struct.unpack(">II", f.read(8))
            data = np.frombuffer(f.read(), dtype=np.uint8)
        return data.astype("int64")

    def __getitem__(self, idx):
        img = self.images[idx].astype("float32")[..., None]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    pass


class _CifarBase(Dataset):
    _n_classes = 10

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        if data_file is None or not os.path.exists(data_file):
            raise FileNotFoundError(
                "Cifar auto-download is unavailable (no network); pass "
                "data_file= pointing at the local python-version archive")
        import pickle
        import tarfile
        self.transform = transform
        images, labels = [], []
        key = b"labels" if self._n_classes == 10 else b"fine_labels"
        with tarfile.open(data_file) as tf:
            names = [m for m in tf.getmembers()
                     if ("test" in m.name if mode == "test"
                         else "data_batch" in m.name or "train" in m.name)]
            for m in names:
                d = pickle.load(tf.extractfile(m), encoding="bytes")
                images.append(d[b"data"])
                labels.extend(d[key])
        self.images = np.concatenate(images).reshape(-1, 3, 32, 32)
        self.labels = np.asarray(labels, dtype="int64")

    def __getitem__(self, idx):
        img = self.images[idx].astype("float32")
        if self.transform is not None:
            img = self.transform(img.transpose(1, 2, 0))
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)


class Cifar10(_CifarBase):
    _n_classes = 10


class Cifar100(_CifarBase):
    _n_classes = 100


# -- filesystem folder datasets (reference: vision/datasets/folder.py) ------

IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".pgm", ".tif",
                  ".tiff", ".webp", ".npy")


def _default_loader(path):
    if path.endswith(".npy"):
        return np.load(path)
    from .. import image_load
    return image_load(path)


class DatasetFolder(Dataset):
    """Generic ``root/class_x/xxx.ext`` folder dataset (reference:
    vision/datasets/folder.py DatasetFolder): samples are (image, class
    index), classes are subdirectory names in sorted order."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        import os
        self.root = root
        self.transform = transform
        self.loader = loader or _default_loader
        exts = tuple(e.lower() for e in (extensions or IMG_EXTENSIONS))
        self.classes = sorted(
            d for d in os.listdir(root)
            if os.path.isdir(os.path.join(root, d)))
        self.class_to_idx = {c: i for i, c in enumerate(self.classes)}
        if is_valid_file is None:
            def is_valid_file(p):
                return p.lower().endswith(exts)
        self.samples = []
        for c in self.classes:
            cdir = os.path.join(root, c)
            for dirpath, _, files in sorted(os.walk(cdir)):
                for f in sorted(files):
                    p = os.path.join(dirpath, f)
                    if is_valid_file(p):
                        self.samples.append((p, self.class_to_idx[c]))
        if not self.samples:
            raise E.PreconditionNotMetError(
                f"Found 0 files in subfolders of {root} "
                f"(looked for extensions {exts})")

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, target

    def __len__(self):
        return len(self.samples)


class ImageFolder(Dataset):
    """Flat/recursive image folder WITHOUT labels (reference:
    vision/datasets/folder.py ImageFolder): samples are [image]."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        import os
        self.root = root
        self.transform = transform
        self.loader = loader or _default_loader
        exts = tuple(e.lower() for e in (extensions or IMG_EXTENSIONS))
        if is_valid_file is None:
            def is_valid_file(p):
                return p.lower().endswith(exts)
        self.samples = []
        for dirpath, _, files in sorted(os.walk(root)):
            for f in sorted(files):
                p = os.path.join(dirpath, f)
                if is_valid_file(p):
                    self.samples.append(p)
        if not self.samples:
            raise E.PreconditionNotMetError(f"Found 0 files in {root}")

    def __getitem__(self, idx):
        img = self.loader(self.samples[idx])
        if self.transform is not None:
            img = self.transform(img)
        return [img]

    def __len__(self):
        return len(self.samples)


class Flowers(Dataset):
    """Flowers-102 (reference: vision/datasets/flowers.py). Zero-egress
    environment: requires pre-downloaded archives via ``data_file``/
    ``label_file``/``setid_file`` — download=True raises with
    instructions, the same gating every other download-backed dataset
    here uses."""

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=False,
                 backend=None):
        if data_file is None or label_file is None or setid_file is None:
            raise E.PreconditionNotMetError(
                "Flowers requires local data_file/label_file/setid_file "
                "(102flowers.tgz, imagelabels.mat, setid.mat) — automatic "
                "download is unavailable in this build")
        import scipy.io as sio        # gated import
        self.transform = transform
        labels = sio.loadmat(label_file)["labels"][0]
        setid = sio.loadmat(setid_file)
        key = {"train": "trnid", "valid": "valid", "test": "tstid"}[mode]
        self.indexes = setid[key][0]
        self._archive = data_file
        self._labels = labels

    def __getitem__(self, idx):
        import io
        import tarfile
        i = int(self.indexes[idx])
        with tarfile.open(self._archive) as tf:
            data = tf.extractfile(f"jpg/image_{i:05d}.jpg").read()
        from .. import image_load
        img = image_load(io.BytesIO(data))
        if self.transform is not None:
            img = self.transform(img)
        return img, int(self._labels[i - 1]) - 1

    def __len__(self):
        return len(self.indexes)


class VOC2012(Dataset):
    """Pascal VOC2012 segmentation pairs (reference:
    vision/datasets/voc2012.py); local archive only (zero egress)."""

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend=None):
        if data_file is None:
            raise E.PreconditionNotMetError(
                "VOC2012 requires a local data_file (VOCtrainval tar) — "
                "automatic download is unavailable in this build")
        import tarfile
        self.transform = transform
        self._archive = data_file
        with tarfile.open(data_file) as tf:
            names = tf.getnames()
        seg = [n for n in names if "/ImageSets/Segmentation/" in n
               and n.endswith(f"{'train' if mode == 'train' else 'val'}.txt")]
        if not seg:
            raise E.PreconditionNotMetError("segmentation index not found in archive")
        with tarfile.open(data_file) as tf:
            ids = tf.extractfile(seg[0]).read().decode().split()
        self.ids = ids

    def __getitem__(self, idx):
        import io
        import tarfile
        vid = self.ids[idx]
        from .. import image_load
        with tarfile.open(self._archive) as tf:
            img = image_load(io.BytesIO(tf.extractfile(
                f"VOCdevkit/VOC2012/JPEGImages/{vid}.jpg").read()))
            lbl = image_load(io.BytesIO(tf.extractfile(
                f"VOCdevkit/VOC2012/SegmentationClass/{vid}.png").read()))
        if self.transform is not None:
            img = self.transform(img)
        return img, lbl

    def __len__(self):
        return len(self.ids)
