"""paddle.vision.datasets parity (reference: vision/datasets/).

The reference downloads MNIST/Cifar/Flowers at first use; this environment
has no egress, so these classes load from a local `data_file`/`image_path`
and raise a clear error when absent. `FakeData` provides synthetic images
for smoke tests (analogue of the reference test fixtures)."""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from ...io import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "FakeData"]


class FakeData(Dataset):
    """Synthetic image classification dataset."""

    def __init__(self, size=256, image_shape=(3, 32, 32), num_classes=10,
                 transform=None, seed=0):
        rng = np.random.default_rng(seed)
        self.images = rng.normal(size=(size, *image_shape)).astype("float32")
        self.labels = rng.integers(0, num_classes, size=size).astype("int64")
        self.transform = transform

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)


class MNIST(Dataset):
    """reference vision/datasets/mnist.py — loads idx-format files from
    ``image_path``/``label_path`` (no auto-download here)."""

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend=None):
        if image_path is None or label_path is None:
            raise FileNotFoundError(
                "MNIST auto-download is unavailable (no network); pass "
                "image_path= and label_path= to local idx(.gz) files")
        self.transform = transform
        self.images = self._read_images(image_path)
        self.labels = self._read_labels(label_path)

    @staticmethod
    def _open(path):
        return gzip.open(path, "rb") if path.endswith(".gz") \
            else open(path, "rb")

    def _read_images(self, path):
        with self._open(path) as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            data = np.frombuffer(f.read(), dtype=np.uint8)
        return data.reshape(n, rows, cols)

    def _read_labels(self, path):
        with self._open(path) as f:
            magic, n = struct.unpack(">II", f.read(8))
            data = np.frombuffer(f.read(), dtype=np.uint8)
        return data.astype("int64")

    def __getitem__(self, idx):
        img = self.images[idx].astype("float32")[..., None]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    pass


class _CifarBase(Dataset):
    _n_classes = 10

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        if data_file is None or not os.path.exists(data_file):
            raise FileNotFoundError(
                "Cifar auto-download is unavailable (no network); pass "
                "data_file= pointing at the local python-version archive")
        import pickle
        import tarfile
        self.transform = transform
        images, labels = [], []
        key = b"labels" if self._n_classes == 10 else b"fine_labels"
        with tarfile.open(data_file) as tf:
            names = [m for m in tf.getmembers()
                     if ("test" in m.name if mode == "test"
                         else "data_batch" in m.name or "train" in m.name)]
            for m in names:
                d = pickle.load(tf.extractfile(m), encoding="bytes")
                images.append(d[b"data"])
                labels.extend(d[key])
        self.images = np.concatenate(images).reshape(-1, 3, 32, 32)
        self.labels = np.asarray(labels, dtype="int64")

    def __getitem__(self, idx):
        img = self.images[idx].astype("float32")
        if self.transform is not None:
            img = self.transform(img.transpose(1, 2, 0))
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)


class Cifar10(_CifarBase):
    _n_classes = 10


class Cifar100(_CifarBase):
    _n_classes = 100
