"""paddle.vision.models parity (reference: python/paddle/vision/models/).
Weights-from-url loading is unavailable (no egress); pretrained=True raises
with that explanation."""
from .lenet import LeNet  # noqa
from .resnet import (ResNet, resnet18, resnet34, resnet50, resnet101,  # noqa
                     resnet152)
from .vgg import VGG, vgg11, vgg13, vgg16, vgg19  # noqa
from .mobilenetv2 import MobileNetV2, mobilenet_v2  # noqa
