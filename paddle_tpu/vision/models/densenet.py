"""DenseNet (reference: vision/models/densenet.py)."""
from __future__ import annotations

from ... import nn, ops

__all__ = ["DenseNet", "densenet121", "densenet161", "densenet169",
           "densenet201", "densenet264"]

_CFG = {
    121: (64, 32, [6, 12, 24, 16]),
    161: (96, 48, [6, 12, 36, 24]),
    169: (64, 32, [6, 12, 32, 32]),
    201: (64, 32, [6, 12, 48, 32]),
    264: (64, 32, [6, 12, 64, 48]),
}


class _DenseLayer(nn.Layer):
    def __init__(self, in_c, growth_rate, bn_size, dropout):
        super().__init__()
        self.norm1 = nn.BatchNorm2D(in_c)
        self.relu = nn.ReLU()
        self.conv1 = nn.Conv2D(in_c, bn_size * growth_rate, 1,
                               bias_attr=False)
        self.norm2 = nn.BatchNorm2D(bn_size * growth_rate)
        self.conv2 = nn.Conv2D(bn_size * growth_rate, growth_rate, 3,
                               padding=1, bias_attr=False)
        self.dropout = nn.Dropout(dropout) if dropout else None

    def forward(self, x):
        out = self.conv1(self.relu(self.norm1(x)))
        out = self.conv2(self.relu(self.norm2(out)))
        if self.dropout is not None:
            out = self.dropout(out)
        return ops.concat([x, out], axis=1)


class _DenseBlock(nn.Layer):
    def __init__(self, num_layers, in_c, growth_rate, bn_size, dropout):
        super().__init__()
        self.layers = nn.LayerList([
            _DenseLayer(in_c + i * growth_rate, growth_rate, bn_size,
                        dropout) for i in range(num_layers)])

    def forward(self, x):
        for layer in self.layers:
            x = layer(x)
        return x


class _Transition(nn.Sequential):
    def __init__(self, in_c, out_c):
        super().__init__(
            nn.BatchNorm2D(in_c),
            nn.ReLU(),
            nn.Conv2D(in_c, out_c, 1, bias_attr=False),
            nn.AvgPool2D(2, stride=2))


class DenseNet(nn.Layer):
    def __init__(self, layers=121, bn_size=4, dropout=0.0,
                 num_classes=1000, with_pool=True):
        super().__init__()
        num_init, growth_rate, block_cfg = _CFG[layers]
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            nn.Conv2D(3, num_init, 7, stride=2, padding=3, bias_attr=False),
            nn.BatchNorm2D(num_init),
            nn.ReLU(),
            nn.MaxPool2D(3, stride=2, padding=1))
        blocks = []
        c = num_init
        for i, n in enumerate(block_cfg):
            blocks.append(_DenseBlock(n, c, growth_rate, bn_size, dropout))
            c = c + n * growth_rate
            if i != len(block_cfg) - 1:
                blocks.append(_Transition(c, c // 2))
                c = c // 2
        self.blocks = nn.Sequential(*blocks)
        self.norm_final = nn.BatchNorm2D(c)
        self.relu = nn.ReLU()
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = nn.Linear(c, num_classes)

    def forward(self, x):
        x = self.relu(self.norm_final(self.blocks(self.stem(x))))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = ops.flatten(x, start_axis=1)
            x = self.fc(x)
        return x


def _densenet(layers, pretrained, **kwargs):
    if pretrained:
        raise NotImplementedError(
            "pretrained weights require network access, unavailable here")
    return DenseNet(layers, **kwargs)


def densenet121(pretrained=False, **kwargs):
    return _densenet(121, pretrained, **kwargs)


def densenet161(pretrained=False, **kwargs):
    return _densenet(161, pretrained, **kwargs)


def densenet169(pretrained=False, **kwargs):
    return _densenet(169, pretrained, **kwargs)


def densenet201(pretrained=False, **kwargs):
    return _densenet(201, pretrained, **kwargs)


def densenet264(pretrained=False, **kwargs):
    return _densenet(264, pretrained, **kwargs)
