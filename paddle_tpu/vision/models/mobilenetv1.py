"""MobileNetV1 (reference: vision/models/mobilenetv1.py) — depthwise
separable convs; the depthwise step is a grouped conv XLA maps directly."""
from __future__ import annotations

from ... import nn, ops

__all__ = ["MobileNetV1", "mobilenet_v1"]


class ConvBNRelu(nn.Sequential):
    def __init__(self, in_c, out_c, kernel=3, stride=1, padding=1, groups=1):
        super().__init__(
            nn.Conv2D(in_c, out_c, kernel, stride=stride, padding=padding,
                      groups=groups, bias_attr=False),
            nn.BatchNorm2D(out_c),
            nn.ReLU())


class DepthwiseSeparable(nn.Layer):
    def __init__(self, in_c, out_c1, out_c2, scale, stride):
        super().__init__()
        c1 = int(out_c1 * scale)
        c2 = int(out_c2 * scale)
        self.dw = ConvBNRelu(in_c, c1, stride=stride, groups=in_c)
        self.pw = ConvBNRelu(c1, c2, kernel=1, padding=0)

    def forward(self, x):
        return self.pw(self.dw(x))


class MobileNetV1(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.scale = scale
        self.num_classes = num_classes
        self.with_pool = with_pool
        s = lambda c: int(c * scale)  # noqa: E731
        self.conv1 = ConvBNRelu(3, s(32), stride=2)
        cfg = [
            (s(32), 32, 64, 1), (s(64), 64, 128, 2),
            (s(128), 128, 128, 1), (s(128), 128, 256, 2),
            (s(256), 256, 256, 1), (s(256), 256, 512, 2),
            (s(512), 512, 512, 1), (s(512), 512, 512, 1),
            (s(512), 512, 512, 1), (s(512), 512, 512, 1),
            (s(512), 512, 512, 1), (s(512), 512, 1024, 2),
            (s(1024), 1024, 1024, 1),
        ]
        self.blocks = nn.Sequential(*[
            DepthwiseSeparable(in_c, c1, c2, scale, st)
            for in_c, c1, c2, st in cfg])
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = nn.Linear(int(1024 * scale), num_classes)

    def forward(self, x):
        x = self.blocks(self.conv1(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = ops.flatten(x, start_axis=1)
            x = self.fc(x)
        return x


def mobilenet_v1(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise NotImplementedError(
            "pretrained weights require network access, unavailable here")
    return MobileNetV1(scale=scale, **kwargs)
