"""ShuffleNetV2 (reference: vision/models/shufflenetv2.py)."""
from __future__ import annotations

from ... import nn, ops

__all__ = ["ShuffleNetV2", "shufflenet_v2_x0_25", "shufflenet_v2_x0_33",
           "shufflenet_v2_x0_5", "shufflenet_v2_x1_0", "shufflenet_v2_x1_5",
           "shufflenet_v2_x2_0", "shufflenet_v2_swish"]


def channel_shuffle(x, groups):
    n, c, h, w = x.shape
    x = x.reshape([n, groups, c // groups, h, w])
    x = x.transpose([0, 2, 1, 3, 4])
    return x.reshape([n, c, h, w])


def _act(name):
    return nn.Swish() if name == "swish" else nn.ReLU()


class ConvBNAct(nn.Sequential):
    def __init__(self, in_c, out_c, kernel, stride, groups=1, act="relu"):
        pad = kernel // 2
        layers = [nn.Conv2D(in_c, out_c, kernel, stride=stride, padding=pad,
                            groups=groups, bias_attr=False),
                  nn.BatchNorm2D(out_c)]
        if act:
            layers.append(_act(act))
        super().__init__(*layers)


class ConvBN(nn.Sequential):
    def __init__(self, in_c, out_c, kernel, stride, groups=1):
        super().__init__(
            nn.Conv2D(in_c, out_c, kernel, stride=stride, padding=kernel // 2,
                      groups=groups, bias_attr=False),
            nn.BatchNorm2D(out_c))


class InvertedResidual(nn.Layer):
    def __init__(self, in_c, out_c, stride, act="relu"):
        super().__init__()
        self.stride = stride
        branch = out_c // 2
        if stride == 1:
            self.branch2 = nn.Sequential(
                ConvBNAct(branch, branch, 1, 1, act=act),
                ConvBN(branch, branch, 3, 1, groups=branch),
                ConvBNAct(branch, branch, 1, 1, act=act))
        else:
            self.branch1 = nn.Sequential(
                ConvBN(in_c, in_c, 3, stride, groups=in_c),
                ConvBNAct(in_c, branch, 1, 1, act=act))
            self.branch2 = nn.Sequential(
                ConvBNAct(in_c, branch, 1, 1, act=act),
                ConvBN(branch, branch, 3, stride, groups=branch),
                ConvBNAct(branch, branch, 1, 1, act=act))

    def forward(self, x):
        if self.stride == 1:
            half = x.shape[1] // 2
            x1 = x[:, :half]
            x2 = x[:, half:]
            out = ops.concat([x1, self.branch2(x2)], axis=1)
        else:
            out = ops.concat([self.branch1(x), self.branch2(x)], axis=1)
        return channel_shuffle(out, 2)


_STAGE_OUT = {
    0.25: [24, 24, 48, 96, 512],
    0.33: [24, 32, 64, 128, 512],
    0.5: [24, 48, 96, 192, 1024],
    1.0: [24, 116, 232, 464, 1024],
    1.5: [24, 176, 352, 704, 1024],
    2.0: [24, 244, 488, 976, 2048],
}


class ShuffleNetV2(nn.Layer):
    def __init__(self, scale=1.0, act="relu", num_classes=1000,
                 with_pool=True):
        super().__init__()
        cfg = _STAGE_OUT[scale]
        repeats = [4, 8, 4]
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.conv1 = ConvBNAct(3, cfg[0], 3, 2, act=act)
        self.maxpool = nn.MaxPool2D(3, stride=2, padding=1)
        stages = []
        in_c = cfg[0]
        for i, rep in enumerate(repeats):
            out_c = cfg[i + 1]
            stage = [InvertedResidual(in_c, out_c, 2, act)]
            for _ in range(rep - 1):
                stage.append(InvertedResidual(out_c, out_c, 1, act))
            stages.append(nn.Sequential(*stage))
            in_c = out_c
        self.stages = nn.Sequential(*stages)
        self.conv_last = ConvBNAct(in_c, cfg[-1], 1, 1, act=act)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(cfg[-1], num_classes)

    def forward(self, x):
        x = self.maxpool(self.conv1(x))
        x = self.conv_last(self.stages(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = ops.flatten(x, start_axis=1)
            x = self.fc(x)
        return x


def _shufflenet(scale, act, pretrained, **kwargs):
    if pretrained:
        raise NotImplementedError(
            "pretrained weights require network access, unavailable here")
    return ShuffleNetV2(scale=scale, act=act, **kwargs)


def shufflenet_v2_x0_25(pretrained=False, **kwargs):
    return _shufflenet(0.25, "relu", pretrained, **kwargs)


def shufflenet_v2_x0_33(pretrained=False, **kwargs):
    return _shufflenet(0.33, "relu", pretrained, **kwargs)


def shufflenet_v2_x0_5(pretrained=False, **kwargs):
    return _shufflenet(0.5, "relu", pretrained, **kwargs)


def shufflenet_v2_x1_0(pretrained=False, **kwargs):
    return _shufflenet(1.0, "relu", pretrained, **kwargs)


def shufflenet_v2_x1_5(pretrained=False, **kwargs):
    return _shufflenet(1.5, "relu", pretrained, **kwargs)


def shufflenet_v2_x2_0(pretrained=False, **kwargs):
    return _shufflenet(2.0, "relu", pretrained, **kwargs)


def shufflenet_v2_swish(pretrained=False, **kwargs):
    return _shufflenet(1.0, "swish", pretrained, **kwargs)
