"""paddle.vision.ops parity: detection/vision operators.

Reference capability: python/paddle/vision/ops.py (nms, roi_align,
roi_pool, psroi_pool, box_coder, prior_box, yolo_box,
distribute_fpn_proposals, deform_conv2d — phi detection kernels).
TPU-native notes: pooled/aligned ops are bilinear gathers (differentiable,
jit-able, MXU-adjacent); NMS and FPN distribution have data-dependent
output sizes, so they run eagerly on host numpy — the same
host-side role they play in the reference's CPU kernels (suppression is
input-pipeline work, not device work).
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..nn.layer.base import Layer
from ..ops._op import op_fn, unwrap, wrap
from ..nn import Sequential as _nn_Sequential
from ..core import enforce as E

__all__ = [
    "nms", "roi_align", "roi_pool", "psroi_pool", "box_coder", "prior_box",
    "yolo_box", "distribute_fpn_proposals", "deform_conv2d",
    "RoIAlign", "RoIPool", "PSRoIPool", "DeformConv2D",
]


# ---------------------------------------------------------------------------
# NMS (eager host op — variable-size output, reference: ops.py nms)
# ---------------------------------------------------------------------------

def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None, name=None):
    """Hard NMS; with scores, keeps by descending score; with categories,
    suppression is per-category (batched NMS). Returns kept indices."""
    b = np.asarray(unwrap(boxes))
    n = len(b)
    if scores is not None:
        order = np.argsort(-np.asarray(unwrap(scores)))
    else:
        order = np.arange(n)
    cats = None if category_idxs is None else np.asarray(
        unwrap(category_idxs))

    x1, y1, x2, y2 = b[:, 0], b[:, 1], b[:, 2], b[:, 3]
    areas = np.maximum(0.0, x2 - x1) * np.maximum(0.0, y2 - y1)
    keep = []
    suppressed = np.zeros(n, bool)
    for i in order:
        if suppressed[i]:
            continue
        keep.append(i)
        xx1 = np.maximum(x1[i], x1)
        yy1 = np.maximum(y1[i], y1)
        xx2 = np.minimum(x2[i], x2)
        yy2 = np.minimum(y2[i], y2)
        inter = np.maximum(0.0, xx2 - xx1) * np.maximum(0.0, yy2 - yy1)
        iou = inter / np.maximum(areas[i] + areas - inter, 1e-10)
        over = iou > iou_threshold
        if cats is not None:
            over &= cats == cats[i]
        over[i] = False
        suppressed |= over
    keep = np.asarray(keep, np.int64)
    if top_k is not None:
        keep = keep[:top_k]
    return wrap(jnp.asarray(keep))


# ---------------------------------------------------------------------------
# RoI pooling family (differentiable bilinear gathers)
# ---------------------------------------------------------------------------

@op_fn(name="roi_align_op", nondiff_args=(1, 2))
def _roi_align(x, boxes, boxes_num, *, output_size, spatial_scale,
               sampling_ratio, aligned):
    """x [N, C, H, W], boxes [R, 4] (x1,y1,x2,y2), boxes_num [N] ->
    [R, C, ph, pw] (reference: roi_align phi kernel semantics)."""
    n, c, h, w = x.shape
    ph, pw = output_size
    r = boxes.shape[0]
    # map each roi to its batch image via the boxes_num prefix sum
    roi_batch = jnp.searchsorted(jnp.cumsum(boxes_num),
                                 jnp.arange(r), side="right")
    offset = 0.5 if aligned else 0.0
    bx = boxes * spatial_scale
    x1 = bx[:, 0] - offset
    y1 = bx[:, 1] - offset
    x2 = bx[:, 2] - offset
    y2 = bx[:, 3] - offset
    rw = x2 - x1
    rh = y2 - y1
    if not aligned:
        rw = jnp.maximum(rw, 1.0)
        rh = jnp.maximum(rh, 1.0)
    bin_w = rw / pw
    bin_h = rh / ph
    s = sampling_ratio if sampling_ratio > 0 else 2
    # sample grid: [R, ph, pw, s, s]
    iy = jnp.arange(ph)
    ix = jnp.arange(pw)
    sy = (jnp.arange(s) + 0.5) / s
    sx = (jnp.arange(s) + 0.5) / s
    ys = (y1[:, None, None] + (iy[None, :, None] + sy[None, None, :])
          * bin_h[:, None, None])                      # [R, ph, s]
    xs = (x1[:, None, None] + (ix[None, :, None] + sx[None, None, :])
          * bin_w[:, None, None])                      # [R, pw, s]

    def bilinear(img, yy, xx):
        """img [C, H, W]; yy [ph, s]; xx [pw, s] -> [C, ph, pw] (mean over
        the s*s samples per bin)."""
        y0 = jnp.floor(yy)
        x0 = jnp.floor(xx)
        wy1 = yy - y0
        wx1 = xx - x0

        def g(iyv, ixv):
            oky = (iyv >= 0) & (iyv < h)
            okx = (ixv >= 0) & (ixv < w)
            iyc = jnp.clip(iyv.astype(jnp.int32), 0, h - 1)
            ixc = jnp.clip(ixv.astype(jnp.int32), 0, w - 1)
            # [C, ph, s, pw, s]
            v = img[:, iyc[:, :, None, None], ixc[None, None, :, :]]
            m = (oky[:, :, None, None] & okx[None, None, :, :])
            return v * m[None]

        w00 = ((1 - wy1)[:, :, None, None] * (1 - wx1)[None, None, :, :])
        w01 = ((1 - wy1)[:, :, None, None] * wx1[None, None, :, :])
        w10 = (wy1[:, :, None, None] * (1 - wx1)[None, None, :, :])
        w11 = (wy1[:, :, None, None] * wx1[None, None, :, :])
        acc = (g(y0, x0) * w00[None] + g(y0, x0 + 1) * w01[None]
               + g(y0 + 1, x0) * w10[None] + g(y0 + 1, x0 + 1) * w11[None])
        return acc.mean(axis=(2, 4))                   # mean over s, s

    imgs = x[roi_batch]                                # [R, C, H, W]
    out = jax.vmap(bilinear)(imgs, ys, xs)             # [R, C, ph, pw]
    return out


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    sr = int(sampling_ratio)
    if sr <= 0:
        # reference uses an adaptive ceil(roi_extent / output_size) per
        # RoI; static shapes need ONE count, so take the ceil over the
        # largest concrete RoI (bounded), falling back to 4 under tracing
        from ..core import is_tracer
        ba = unwrap(boxes)
        if is_tracer(ba):
            sr = 4
        else:
            b = np.asarray(ba)
            if len(b) == 0:
                sr = 1
            else:
                ext_h = (b[:, 3] - b[:, 1]) * spatial_scale / output_size[0]
                ext_w = (b[:, 2] - b[:, 0]) * spatial_scale / output_size[1]
                sr = int(np.clip(np.ceil(max(ext_h.max(), ext_w.max(),
                                             1.0)), 1, 8))
    return _roi_align(x, boxes, boxes_num, output_size=tuple(output_size),
                      spatial_scale=float(spatial_scale),
                      sampling_ratio=sr, aligned=bool(aligned))


@op_fn(name="roi_pool_op", nondiff_args=(1, 2))
def _roi_pool(x, boxes, boxes_num, *, output_size, spatial_scale):
    """Max pooling per RoI bin (reference: roi_pool kernel)."""
    n, c, h, w = x.shape
    ph, pw = output_size
    r = boxes.shape[0]
    roi_batch = jnp.searchsorted(jnp.cumsum(boxes_num),
                                 jnp.arange(r), side="right")
    bx = jnp.round(boxes * spatial_scale)
    x1 = bx[:, 0]
    y1 = bx[:, 1]
    rw = jnp.maximum(bx[:, 2] - x1 + 1, 1.0)
    rh = jnp.maximum(bx[:, 3] - y1 + 1, 1.0)

    ys = jnp.arange(h, dtype=jnp.float32)
    xs = jnp.arange(w, dtype=jnp.float32)

    def pool_one(img, x1i, y1i, rwi, rhi):
        # bin id of each pixel row/col (or -1 outside the roi)
        bin_y = jnp.floor((ys - y1i) / (rhi / ph))
        bin_x = jnp.floor((xs - x1i) / (rwi / pw))
        ybin = jnp.clip(bin_y, 0, ph - 1).astype(jnp.int32)
        xbin = jnp.clip(bin_x, 0, pw - 1).astype(jnp.int32)
        oky = (ys >= y1i) & (bin_y >= 0) & (bin_y < ph)
        okx = (xs >= x1i) & (bin_x >= 0) & (bin_x < pw)
        mask = oky[:, None] & okx[None, :]
        vals = jnp.where(mask[None], img, -jnp.inf)    # [C, H, W]
        # scatter-max into bins
        flat_bins = (ybin[:, None] * pw + xbin[None, :]).reshape(-1)
        flat = vals.reshape(c, -1)
        out = jax.vmap(lambda row: jax.ops.segment_max(
            row, flat_bins, ph * pw))(flat)
        out = jnp.where(jnp.isfinite(out), out, 0.0)
        return out.reshape(c, ph, pw)

    imgs = x[roi_batch]
    return jax.vmap(pool_one)(imgs, x1, y1, rw, rh)


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
             name=None):
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    return _roi_pool(x, boxes, boxes_num, output_size=tuple(output_size),
                     spatial_scale=float(spatial_scale))


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
               name=None):
    """Position-sensitive RoI pooling (reference: psroi_pool): input
    channels C = out_c * ph * pw; bin (i, j) reads channel group
    (i*pw + j)."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    xa = unwrap(x)
    c = xa.shape[1]
    if c % (ph * pw) != 0:
        raise E.InvalidArgumentError(
            f"psroi_pool needs channels divisible by {ph * pw}, got {c}")
    out_c = c // (ph * pw)
    # average-align each position-sensitive group then pick its own bin
    aligned = roi_align(x, boxes, boxes_num, output_size,
                        spatial_scale=spatial_scale, sampling_ratio=2,
                        aligned=False)
    al = unwrap(aligned)                       # [R, C, ph, pw]
    r = al.shape[0]
    al = al.reshape(r, ph * pw, out_c, ph, pw)
    # out[r, c, i, j] = al[r, i*pw + j, c, i, j] — full advanced indexing
    # (all axes indexed together) keeps the broadcast shape [R,out_c,ph,pw]
    ri = jnp.arange(r)[:, None, None, None]
    ci = jnp.arange(out_c)[None, :, None, None]
    ii = jnp.arange(ph)[None, None, :, None]
    jj = jnp.arange(pw)[None, None, None, :]
    out = al[ri, ii * pw + jj, ci, ii, jj]     # [R, out_c, ph, pw]
    return wrap(out)


# ---------------------------------------------------------------------------
# box utilities
# ---------------------------------------------------------------------------

@op_fn(name="box_coder_op")
def _box_coder(prior_box, target_box, prior_box_var, *, code_type,
               box_normalized, axis):
    """encode_center_size / decode_center_size (reference: ops.py
    box_coder)."""
    norm = 0.0 if box_normalized else 1.0
    pw = prior_box[:, 2] - prior_box[:, 0] + norm
    ph_ = prior_box[:, 3] - prior_box[:, 1] + norm
    pcx = prior_box[:, 0] + pw * 0.5
    pcy = prior_box[:, 1] + ph_ * 0.5
    if code_type == "encode_center_size":
        tw = target_box[:, 2] - target_box[:, 0] + norm
        th = target_box[:, 3] - target_box[:, 1] + norm
        tcx = target_box[:, 0] + tw * 0.5
        tcy = target_box[:, 1] + th * 0.5
        dx = (tcx[:, None] - pcx[None, :]) / pw[None, :]
        dy = (tcy[:, None] - pcy[None, :]) / ph_[None, :]
        dw = jnp.log(tw[:, None] / pw[None, :])
        dh = jnp.log(th[:, None] / ph_[None, :])
        out = jnp.stack([dx, dy, dw, dh], axis=-1)     # [T, P, 4]
        if prior_box_var is not None:
            out = out / prior_box_var[None, :, :]
        return out
    # decode: deltas [P, 4] or [N, P, 4]; ``axis`` selects which dim of a
    # 3-D target the priors broadcast along (reference box_coder axis)
    d = target_box
    if d.ndim == 3:
        # axis=0: priors broadcast to [1, M, 4] (align with target dim 1);
        # axis=1: priors broadcast to [N, 1, 4] (align with target dim 0).
        expand = (None, slice(None)) if axis == 0 else (slice(None), None)
        pw = pw[expand]
        ph_ = ph_[expand]
        pcx = pcx[expand]
        pcy = pcy[expand]
        if prior_box_var is not None:
            d = d * prior_box_var[expand]
    elif prior_box_var is not None:
        d = d * prior_box_var
    cx = d[..., 0] * pw + pcx
    cy = d[..., 1] * ph_ + pcy
    bw = jnp.exp(d[..., 2]) * pw
    bh = jnp.exp(d[..., 3]) * ph_
    return jnp.stack([cx - bw * 0.5, cy - bh * 0.5,
                      cx + bw * 0.5 - norm, cy + bh * 0.5 - norm], axis=-1)


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True, axis=0,
              name=None):
    pv = None
    if prior_box_var is not None:
        if isinstance(prior_box_var, (list, tuple)):
            pv = jnp.broadcast_to(jnp.asarray(prior_box_var, jnp.float32),
                                  unwrap(prior_box).shape)
        else:
            pv = unwrap(prior_box_var)
    return _box_coder(prior_box, target_box, wrap(pv) if pv is not None
                      else None, code_type=code_type,
                      box_normalized=box_normalized, axis=axis)


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, min_max_aspect_ratios_order=False,
              name=None):
    """SSD prior boxes (reference: ops.py prior_box)."""
    fh, fw = unwrap(input).shape[2:]
    ih, iw = unwrap(image).shape[2:]
    step_w = steps[0] or iw / fw
    step_h = steps[1] or ih / fh
    ars = [1.0]
    for ar in aspect_ratios:
        if not any(abs(ar - a) < 1e-6 for a in ars):
            ars.append(float(ar))
            if flip:
                ars.append(1.0 / float(ar))
    boxes = []
    for s in min_sizes:
        boxes.append((s, s))
        ar_boxes = [(s * np.sqrt(ar), s / np.sqrt(ar))
                    for ar in ars if abs(ar - 1.0) >= 1e-6]
        max_boxes = []
        if max_sizes:
            for ms in max_sizes:
                d = np.sqrt(s * ms)
                max_boxes.append((d, d))
        # paddle default (flag False): [min, aspect_ratios..., max];
        # flag True is the Caffe [min, max, aspect_ratios...] ordering
        if min_max_aspect_ratios_order:
            boxes.extend(max_boxes)
            boxes.extend(ar_boxes)
        else:
            boxes.extend(ar_boxes)
            boxes.extend(max_boxes)
    nb = len(boxes)
    cy = (np.arange(fh) + offset) * step_h
    cx = (np.arange(fw) + offset) * step_w
    out = np.zeros((fh, fw, nb, 4), np.float32)
    for k, (bw, bh) in enumerate(boxes):
        out[:, :, k, 0] = (cx[None, :] - bw / 2) / iw
        out[:, :, k, 1] = (cy[:, None] - bh / 2) / ih
        out[:, :, k, 2] = (cx[None, :] + bw / 2) / iw
        out[:, :, k, 3] = (cy[:, None] + bh / 2) / ih
    if clip:
        out = np.clip(out, 0.0, 1.0)
    var = np.broadcast_to(np.asarray(variance, np.float32),
                          out.shape).copy()
    return wrap(jnp.asarray(out)), wrap(jnp.asarray(var))


@op_fn(name="yolo_box_op", nondiff_args=(1,))
def _yolo_box(x, img_size, *, anchors, class_num, conf_thresh,
              downsample_ratio, clip_bbox, scale_x_y):
    """Decode YOLO head output [N, na*(5+nc), H, W] -> (boxes, scores)
    (reference: ops.py yolo_box)."""
    n, _, h, w = x.shape
    na = len(anchors) // 2
    nc = class_num
    x = x.reshape(n, na, 5 + nc, h, w)
    grid_x = jnp.arange(w, dtype=jnp.float32)
    grid_y = jnp.arange(h, dtype=jnp.float32)
    sx = jax.nn.sigmoid(x[:, :, 0]) * scale_x_y - (scale_x_y - 1) / 2
    sy = jax.nn.sigmoid(x[:, :, 1]) * scale_x_y - (scale_x_y - 1) / 2
    bx = (sx + grid_x[None, None, None, :]) / w
    by = (sy + grid_y[None, None, :, None]) / h
    aw = jnp.asarray(anchors[0::2], jnp.float32)
    ah = jnp.asarray(anchors[1::2], jnp.float32)
    in_w = w * downsample_ratio
    in_h = h * downsample_ratio
    bw = jnp.exp(x[:, :, 2]) * aw[None, :, None, None] / in_w
    bh = jnp.exp(x[:, :, 3]) * ah[None, :, None, None] / in_h
    conf = jax.nn.sigmoid(x[:, :, 4])
    probs = jax.nn.sigmoid(x[:, :, 5:]) * conf[:, :, None]
    mask = conf > conf_thresh
    img_h = img_size[:, 0].astype(jnp.float32)
    img_w = img_size[:, 1].astype(jnp.float32)
    x1 = (bx - bw / 2) * img_w[:, None, None, None]
    y1 = (by - bh / 2) * img_h[:, None, None, None]
    x2 = (bx + bw / 2) * img_w[:, None, None, None]
    y2 = (by + bh / 2) * img_h[:, None, None, None]
    if clip_bbox:
        x1 = jnp.clip(x1, 0, img_w[:, None, None, None] - 1)
        y1 = jnp.clip(y1, 0, img_h[:, None, None, None] - 1)
        x2 = jnp.clip(x2, 0, img_w[:, None, None, None] - 1)
        y2 = jnp.clip(y2, 0, img_h[:, None, None, None] - 1)
    boxes = jnp.stack([x1, y1, x2, y2], axis=-1)       # [N, na, H, W, 4]
    boxes = boxes * mask[..., None]
    boxes = boxes.reshape(n, na * h * w, 4)
    scores = (probs * mask[:, :, None]).transpose(0, 1, 3, 4, 2)
    scores = scores.reshape(n, na * h * w, nc)
    return boxes, scores


def yolo_box(x, img_size, anchors, class_num, conf_thresh=0.01,
             downsample_ratio=32, clip_bbox=True, scale_x_y=1.0, name=None,
             iou_aware=False, iou_aware_factor=0.5):
    if iou_aware:
        raise NotImplementedError(
            "yolo_box: iou_aware=True (the [N, na*(6+nc)] channel layout "
            "with conf^(1-f)*iou^f scoring) is not implemented")
    return _yolo_box(x, img_size, anchors=tuple(anchors),
                     class_num=int(class_num), conf_thresh=conf_thresh,
                     downsample_ratio=downsample_ratio,
                     clip_bbox=bool(clip_bbox), scale_x_y=float(scale_x_y))


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False,
                             rois_num=None, name=None):
    """Assign RoIs to FPN levels by scale (reference: ops.py
    distribute_fpn_proposals). Eager: level membership is data-dependent."""
    rois = np.asarray(unwrap(fpn_rois))
    off = 1.0 if pixel_offset else 0.0
    ws = np.maximum(rois[:, 2] - rois[:, 0] + off, 0)
    hs = np.maximum(rois[:, 3] - rois[:, 1] + off, 0)
    scale = np.sqrt(ws * hs)
    lvl = np.floor(np.log2(scale / refer_scale + 1e-8)) + refer_level
    lvl = np.clip(lvl, min_level, max_level).astype(np.int64)
    outs, restore = [], []
    idx_order = []
    for L in range(min_level, max_level + 1):
        sel = np.flatnonzero(lvl == L)
        outs.append(wrap(jnp.asarray(rois[sel])))
        idx_order.append(sel)
    order = np.concatenate(idx_order) if idx_order else np.array([], np.int64)
    restore = np.empty_like(order)
    restore[order] = np.arange(len(order))
    if rois_num is not None:
        # batched input: per-level outputs carry per-image counts [B]
        counts = np.asarray(unwrap(rois_num)).reshape(-1).astype(np.int64)
        img_id = np.repeat(np.arange(len(counts)), counts)
        rois_num_per = [wrap(jnp.asarray(np.bincount(
            img_id[i], minlength=len(counts)).astype(np.int32)))
            for i in idx_order]
    else:
        rois_num_per = [wrap(jnp.asarray(np.asarray([len(i)], np.int32)))
                        for i in idx_order]
    return outs, wrap(jnp.asarray(restore.reshape(-1, 1))), rois_num_per


# ---------------------------------------------------------------------------
# deformable conv (grid_sample composition)
# ---------------------------------------------------------------------------

def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Deformable conv v1/v2 (reference: ops.py deform_conv2d) composed
    from bilinear sampling at offset positions + a dense matmul —
    the TPU-friendly im2col formulation."""
    from ..nn.functional.vision import grid_sample
    xa = unwrap(x)
    off = unwrap(offset)
    wt = unwrap(weight)
    n, cin, h, w = xa.shape
    cout, cin_g, kh, kw = wt.shape
    sh = sw = stride if isinstance(stride, int) else None
    if sh is None:
        sh, sw = stride
    ph_ = pw_ = padding if isinstance(padding, int) else None
    if ph_ is None:
        ph_, pw_ = padding
    dh = dw_ = dilation if isinstance(dilation, int) else None
    if dh is None:
        dh, dw_ = dilation
    oh = (h + 2 * ph_ - dh * (kh - 1) - 1) // sh + 1
    ow = (w + 2 * pw_ - dw_ * (kw - 1) - 1) // sw + 1

    base_y = jnp.arange(oh) * sh - ph_
    base_x = jnp.arange(ow) * sw - pw_
    ky = jnp.arange(kh) * dh
    kx = jnp.arange(kw) * dw_
    # absolute sample positions [oh, ow, kh, kw]
    pos_y = base_y[:, None, None, None] + ky[None, None, :, None]
    pos_x = base_x[None, :, None, None] + kx[None, None, None, :]
    off = off.reshape(n, deformable_groups, kh * kw, 2, oh, ow)
    # paddle offset layout: [dg, kh*kw, (dy, dx), oh, ow]
    dy = off[:, :, :, 0].reshape(n, deformable_groups, kh, kw, oh, ow)
    dx = off[:, :, :, 1].reshape(n, deformable_groups, kh, kw, oh, ow)
    sy = pos_y[None, None].transpose(0, 1, 4, 5, 2, 3) + dy  # broadcast
    sx = pos_x[None, None].transpose(0, 1, 4, 5, 2, 3) + dx
    # normalize to [-1, 1] for grid_sample (align_corners=True)
    gy = 2.0 * sy / jnp.maximum(h - 1, 1) - 1.0
    gx = 2.0 * sx / jnp.maximum(w - 1, 1) - 1.0
    # [n, dg, kh, kw, oh, ow] -> sample each (kh, kw) tap: grid
    # [n, kh*kw*oh, ow, 2] per deformable group
    cg = cin // deformable_groups
    cols = []
    for g in range(deformable_groups):
        grid = jnp.stack([gx[:, g], gy[:, g]], axis=-1)   # [n,kh,kw,oh,ow,2]
        grid = grid.transpose(0, 1, 3, 2, 4, 5).reshape(
            n, kh * oh, kw * ow, 2)
        xg = xa[:, g * cg:(g + 1) * cg]
        samp = grid_sample(wrap(xg), wrap(grid), align_corners=True)
        samp = unwrap(samp).reshape(n, cg, kh, oh, kw, ow)
        cols.append(samp.transpose(0, 1, 2, 4, 3, 5))     # [n,cg,kh,kw,oh,ow]
    col = jnp.concatenate(cols, axis=1)                   # [n,cin,kh,kw,oh,ow]
    if mask is not None:
        m = unwrap(mask).reshape(n, deformable_groups, kh, kw, oh, ow)
        m = jnp.repeat(m, cg, axis=1)
        col = col * m
    col = col.reshape(n, cin * kh * kw, oh * ow)
    wmat = wt.reshape(cout, cin_g * kh * kw)
    if groups == 1:
        out = jnp.einsum("ok,nkp->nop", wmat, col)
    else:
        col = col.reshape(n, groups, (cin // groups) * kh * kw, oh * ow)
        wmat = wmat.reshape(groups, cout // groups, -1)
        out = jnp.einsum("gok,ngkp->ngop", wmat, col).reshape(
            n, cout, oh * ow)
    out = out.reshape(n, cout, oh, ow)
    if bias is not None:
        out = out + unwrap(bias)[None, :, None, None]
    return wrap(out)


# ---------------------------------------------------------------------------
# layer wrappers (reference: ops.py RoIAlign/RoIPool/PSRoIPool/DeformConv2D)
# ---------------------------------------------------------------------------

class RoIAlign(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return roi_align(x, boxes, boxes_num, self.output_size,
                         self.spatial_scale)


class RoIPool(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self.output_size,
                        self.spatial_scale)


class PSRoIPool(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return psroi_pool(x, boxes, boxes_num, self.output_size,
                          self.spatial_scale)


class DeformConv2D(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        if isinstance(kernel_size, int):
            kernel_size = (kernel_size, kernel_size)
        self.stride = stride
        self.padding = padding
        self.dilation = dilation
        self.deformable_groups = deformable_groups
        self.groups = groups
        kh, kw = kernel_size
        self.weight = self.create_parameter(
            [out_channels, in_channels // groups, kh, kw],
            attr=weight_attr)
        self.bias = self.create_parameter([out_channels], attr=bias_attr,
                                          is_bias=True) \
            if bias_attr is not False else None

    def forward(self, x, offset, mask=None):
        return deform_conv2d(x, offset, self.weight, self.bias,
                             self.stride, self.padding, self.dilation,
                             self.deformable_groups, self.groups, mask)


# ---------------------------------------------------------------------------
# image IO (reference: ops.py read_file/decode_jpeg) + detection long tail
# ---------------------------------------------------------------------------

def read_file(filename, name=None):
    """Read raw file bytes as a uint8 tensor (reference: ops.py
    read_file)."""
    with open(filename, "rb") as f:
        data = f.read()
    return wrap(jnp.asarray(np.frombuffer(data, np.uint8)))


def decode_jpeg(x, mode="unchanged", name=None):
    """Decode a JPEG byte tensor to CHW uint8 (reference: ops.py
    decode_jpeg, nvjpeg kernel). Host-side decode via Pillow/matplotlib —
    image IO is input-pipeline work, not device work."""
    raw = bytes(np.asarray(unwrap(x)).astype(np.uint8).tobytes())
    import io as _io
    arr = None
    try:
        from PIL import Image

        img = Image.open(_io.BytesIO(raw))
        if mode == "gray":
            img = img.convert("L")
        elif mode == "rgb":
            img = img.convert("RGB")
        arr = np.asarray(img)
    except ImportError:
        try:
            import matplotlib.image as mpimg

            arr = mpimg.imread(_io.BytesIO(raw), format="jpeg")
            if arr.dtype != np.uint8:
                arr = (arr * 255).astype(np.uint8)
        except ImportError as e:
            raise E.PreconditionNotMetError(
                "decode_jpeg needs Pillow or matplotlib for host-side "
                "decode; neither is importable") from e
    if arr.ndim == 2:
        arr = arr[None]                    # [1, H, W]
    else:
        arr = arr.transpose(2, 0, 1)       # [C, H, W]
    return wrap(jnp.asarray(arr))


def matrix_nms(bboxes, scores, score_threshold, post_threshold,
               nms_top_k, keep_top_k, use_gaussian=False, gaussian_sigma=2.0,
               background_label=0, normalized=True, return_index=False,
               return_rois_num=True, name=None):
    """Matrix NMS (reference: ops.py matrix_nms, SOLOv2): soft decay of
    scores by pairwise IoU — fully vectorized (no sequential suppression),
    which is exactly the TPU-friendly formulation."""
    bx = np.asarray(unwrap(bboxes)).astype(np.float64)   # [N, M, 4]
    sc = np.asarray(unwrap(scores)).astype(np.float64)   # [N, C, M]
    n, c, m = sc.shape
    out_rois, out_idx, out_num = [], [], []
    norm = 0.0 if normalized else 1.0
    for b in range(n):
        dets, idxs = [], []
        for cls in range(c):
            if cls == background_label:
                continue
            s = sc[b, cls]
            keep = np.flatnonzero(s > score_threshold)
            if keep.size == 0:
                continue
            order = keep[np.argsort(-s[keep])][:nms_top_k]
            boxes = bx[b, order]
            ss = s[order]
            # pairwise IoU of the sorted candidates
            x1 = np.maximum(boxes[:, None, 0], boxes[None, :, 0])
            y1 = np.maximum(boxes[:, None, 1], boxes[None, :, 1])
            x2 = np.minimum(boxes[:, None, 2], boxes[None, :, 2])
            y2 = np.minimum(boxes[:, None, 3], boxes[None, :, 3])
            inter = (np.clip(x2 - x1 + norm, 0, None)
                     * np.clip(y2 - y1 + norm, 0, None))
            area = ((boxes[:, 2] - boxes[:, 0] + norm)
                    * (boxes[:, 3] - boxes[:, 1] + norm))
            iou = inter / np.maximum(area[:, None] + area[None, :] - inter,
                                     1e-10)
            iou = np.triu(iou, k=1)
            iou_cmax = iou.max(axis=0)                    # [k] per candidate
            # decay_j = min over suppressors i of f(iou[i,j]) / f(cmax[i])
            # (cmax indexed by the suppressor ROW — SOLOv2 eq. 5)
            if use_gaussian:
                decay = np.exp((iou_cmax[:, None] ** 2 - iou ** 2)
                               / gaussian_sigma).min(axis=0)
            else:
                decay = ((1.0 - iou) / np.maximum(1.0 - iou_cmax[:, None],
                                                  1e-10)).min(axis=0)
            decayed = ss * decay
            ok = decayed >= post_threshold
            for j in np.flatnonzero(ok):
                dets.append([cls, decayed[j], *boxes[j]])
                idxs.append(order[j] + b * m)
        if dets:
            dets = np.asarray(dets)
            srt = np.argsort(-dets[:, 1])[:keep_top_k]
            dets = dets[srt]
            idxs = np.asarray(idxs)[srt]
        else:
            dets = np.zeros((0, 6))
            idxs = np.zeros((0,), np.int64)
        out_rois.append(dets)
        out_idx.append(idxs)
        out_num.append(len(dets))
    rois = wrap(jnp.asarray(np.concatenate(out_rois)
                            if out_rois else np.zeros((0, 6)),
                            jnp.float32))
    res = (rois,)
    if return_index:
        res = res + (wrap(jnp.asarray(np.concatenate(out_idx).astype(
            np.int64) if out_idx else np.zeros(0, np.int64))),)
    if return_rois_num:
        res = res + (wrap(jnp.asarray(np.asarray(out_num, np.int32))),)
    return res if len(res) > 1 else res[0]


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=False, return_rois_num=False,
                       name=None):
    """RPN proposal generation (reference: ops.py generate_proposals):
    decode anchors by deltas, clip, filter small, NMS per image."""
    sc = np.asarray(unwrap(scores))          # [N, A, H, W]
    bd = np.asarray(unwrap(bbox_deltas))     # [N, 4A, H, W]
    ims = np.asarray(unwrap(img_size))       # [N, 2] (h, w)
    anc = np.asarray(unwrap(anchors)).reshape(-1, 4)      # [AHW?, 4]
    var = np.asarray(unwrap(variances)).reshape(-1, 4)
    n, a, h, w = sc.shape
    offset = 1.0 if pixel_offset else 0.0
    rois_out, num_out, score_out = [], [], []
    for b in range(n):
        s = sc[b].transpose(1, 2, 0).reshape(-1)              # [HWA]
        d = bd[b].reshape(a, 4, h, w).transpose(2, 3, 0, 1).reshape(-1, 4)
        # anchors arrive [H, W, A, 4] flattened
        order = np.argsort(-s)[:pre_nms_top_n]
        s_top, d_top = s[order], d[order]
        anc_top, var_top = anc[order], var[order]
        aw = anc_top[:, 2] - anc_top[:, 0] + offset
        ah = anc_top[:, 3] - anc_top[:, 1] + offset
        acx = anc_top[:, 0] + aw * 0.5
        acy = anc_top[:, 1] + ah * 0.5
        cx = var_top[:, 0] * d_top[:, 0] * aw + acx
        cy = var_top[:, 1] * d_top[:, 1] * ah + acy
        bw = aw * np.exp(np.minimum(var_top[:, 2] * d_top[:, 2], 10.0))
        bh = ah * np.exp(np.minimum(var_top[:, 3] * d_top[:, 3], 10.0))
        px1 = cx - bw * 0.5
        py1 = cy - bh * 0.5
        px2 = cx + bw * 0.5 - offset
        py2 = cy + bh * 0.5 - offset
        ih, iw = ims[b]
        px1 = np.clip(px1, 0, iw - offset)
        py1 = np.clip(py1, 0, ih - offset)
        px2 = np.clip(px2, 0, iw - offset)
        py2 = np.clip(py2, 0, ih - offset)
        keep = np.flatnonzero(((px2 - px1 + offset) >= min_size)
                              & ((py2 - py1 + offset) >= min_size))
        props = np.stack([px1, py1, px2, py2], axis=1)[keep]
        ps = s_top[keep]
        # greedy hard NMS
        order2 = np.argsort(-ps)
        sel = []
        while order2.size:
            i = order2[0]
            sel.append(i)
            if len(sel) >= post_nms_top_n:
                break
            rest = order2[1:]
            xx1 = np.maximum(props[i, 0], props[rest, 0])
            yy1 = np.maximum(props[i, 1], props[rest, 1])
            xx2 = np.minimum(props[i, 2], props[rest, 2])
            yy2 = np.minimum(props[i, 3], props[rest, 3])
            inter = (np.clip(xx2 - xx1 + offset, 0, None)
                     * np.clip(yy2 - yy1 + offset, 0, None))
            a1 = ((props[i, 2] - props[i, 0] + offset)
                  * (props[i, 3] - props[i, 1] + offset))
            a2 = ((props[rest, 2] - props[rest, 0] + offset)
                  * (props[rest, 3] - props[rest, 1] + offset))
            iou = inter / np.maximum(a1 + a2 - inter, 1e-10)
            order2 = rest[iou <= nms_thresh]
        rois_out.append(props[sel])
        score_out.append(ps[sel])
        num_out.append(len(sel))
    rois = wrap(jnp.asarray(np.concatenate(rois_out) if rois_out
                            else np.zeros((0, 4)), jnp.float32))
    rscores = wrap(jnp.asarray(np.concatenate(score_out) if score_out
                               else np.zeros((0,)), jnp.float32))
    if return_rois_num:
        return rois, rscores, wrap(jnp.asarray(
            np.asarray(num_out, np.int32)))
    return rois, rscores


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, name=None, scale_x_y=1.0):
    """YOLOv3 training loss (reference: ops.py yolo_loss; phi
    yolov3_loss kernel): decode predictions, match ground truth to the
    best anchor, sum coordinate + objectness + class losses. Pure jnp —
    differentiable end to end (taped through the op dispatcher)."""
    if gt_score is None:
        gs = jnp.ones(unwrap(gt_label).shape, jnp.float32)
        gt_score = wrap(gs)
    return _yolo_loss_op(x, gt_box, gt_label, gt_score,
                         anchors=tuple(anchors),
                         anchor_mask=tuple(anchor_mask),
                         class_num=int(class_num),
                         ignore_thresh=float(ignore_thresh),
                         downsample_ratio=int(downsample_ratio),
                         use_label_smooth=bool(use_label_smooth),
                         scale_x_y=float(scale_x_y))


@op_fn(name="yolo_loss_op", nondiff_args=(1, 2, 3))
def _yolo_loss_op(xa, gt_box, gt_label, gt_score, *, anchors, anchor_mask,
                  class_num, ignore_thresh, downsample_ratio,
                  use_label_smooth, scale_x_y):
    gb = gt_box.astype(jnp.float32)              # [N, B, 4] (cx cy w h)
    gl = gt_label                                # [N, B]
    gsc = gt_score.astype(jnp.float32)           # [N, B] (mixup weights)
    n, _, h, w = xa.shape
    na = len(anchor_mask)
    an_all = jnp.asarray(anchors, jnp.float32).reshape(-1, 2)
    an = an_all[jnp.asarray(anchor_mask)]
    pred = xa.reshape(n, na, 5 + class_num, h, w)
    # scale_x_y (YOLOv4 grid sensitivity): x*s - 0.5*(s-1)
    px = jax.nn.sigmoid(pred[:, :, 0]) * scale_x_y - 0.5 * (scale_x_y - 1.0)
    py = jax.nn.sigmoid(pred[:, :, 1]) * scale_x_y - 0.5 * (scale_x_y - 1.0)
    pw = pred[:, :, 2]
    ph_ = pred[:, :, 3]
    pobj = pred[:, :, 4]
    pcls = pred[:, :, 5:]
    input_size = downsample_ratio * h

    gx = gb[..., 0]                              # normalized cx
    gy = gb[..., 1]
    gw = gb[..., 2]
    gh = gb[..., 3]
    valid = (gw > 0) & (gl >= 0)
    gi = jnp.clip((gx * w).astype(jnp.int32), 0, w - 1)
    gj = jnp.clip((gy * h).astype(jnp.int32), 0, h - 1)
    # best anchor per gt by wh IoU against ALL anchors (reference rule),
    # then only gts whose best anchor is in this level's mask contribute
    gwh = jnp.stack([gw * input_size, gh * input_size], -1)  # [N,B,2]
    inter = (jnp.minimum(gwh[..., None, 0], an_all[None, None, :, 0])
             * jnp.minimum(gwh[..., None, 1], an_all[None, None, :, 1]))
    union = (gwh[..., 0] * gwh[..., 1])[..., None] \
        + (an_all[:, 0] * an_all[:, 1])[None, None] - inter
    best = jnp.argmax(inter / jnp.maximum(union, 1e-10), axis=-1)  # [N,B]
    mask_arr = jnp.asarray(anchor_mask)
    in_level = (best[..., None] == mask_arr[None, None]).any(-1) & valid
    level_idx = jnp.argmax(
        best[..., None] == mask_arr[None, None], axis=-1)   # anchor slot

    bidx = jnp.arange(n)[:, None].repeat(gb.shape[1], 1)
    tx = gx * w - gi
    ty = gy * h - gj
    tw = jnp.log(jnp.maximum(gwh[..., 0], 1e-9)
                 / an[level_idx][..., 0])
    th = jnp.log(jnp.maximum(gwh[..., 1], 1e-9)
                 / an[level_idx][..., 1])
    scale = 2.0 - gw * gh                         # box size weighting

    sel = (bidx, level_idx, gj, gi)
    wsel = jnp.where(in_level, scale, 0.0)
    loss_xy = (wsel * ((px[sel] - tx) ** 2 + (py[sel] - ty) ** 2)).sum(1)
    loss_wh = (wsel * ((pw[sel] - jnp.where(in_level, tw, 0.0)) ** 2
                       + (ph_[sel] - jnp.where(in_level, th, 0.0)) ** 2)
               ).sum(1)
    # objectness: positives at assigned cells (weighted by gt_score for
    # mixup); negatives elsewhere EXCEPT cells whose predicted box
    # overlaps any gt above ignore_thresh (reference noobj_mask rule)
    obj_t = jnp.zeros((n, na, h, w))
    obj_t = obj_t.at[sel].max(jnp.where(in_level, gsc, 0.0))
    # decode predicted boxes (normalized) for the ignore-mask IoU
    cell_x = (jnp.arange(w)[None, None, None, :] + px) / w
    cell_y = (jnp.arange(h)[None, None, :, None] + py) / h
    bw_p = jnp.exp(jnp.clip(pw, -10, 10)) * an[None, :, 0, None, None] \
        / input_size
    bh_p = jnp.exp(jnp.clip(ph_, -10, 10)) * an[None, :, 1, None, None] \
        / input_size
    # IoU of every cell box against every gt: [N, na, h, w, B]
    px1 = cell_x - bw_p / 2
    px2 = cell_x + bw_p / 2
    py1 = cell_y - bh_p / 2
    py2 = cell_y + bh_p / 2
    qx1 = (gx - gw / 2)[:, None, None, None, :]
    qx2 = (gx + gw / 2)[:, None, None, None, :]
    qy1 = (gy - gh / 2)[:, None, None, None, :]
    qy2 = (gy + gh / 2)[:, None, None, None, :]
    iw = jnp.maximum(jnp.minimum(px2[..., None], qx2)
                     - jnp.maximum(px1[..., None], qx1), 0.0)
    ih = jnp.maximum(jnp.minimum(py2[..., None], qy2)
                     - jnp.maximum(py1[..., None], qy1), 0.0)
    inter_c = iw * ih
    area_p = (bw_p * bh_p)[..., None]
    area_g = (gw * gh)[:, None, None, None, :]
    iou_c = inter_c / jnp.maximum(area_p + area_g - inter_c, 1e-10)
    iou_c = jnp.where(valid[:, None, None, None, :], iou_c, 0.0)
    ignore = (jnp.max(iou_c, axis=-1) > ignore_thresh) & (obj_t <= 0)
    obj_logits = pobj
    obj_loss_map = jnp.maximum(obj_logits, 0) - obj_logits * obj_t \
        + jnp.log1p(jnp.exp(-jnp.abs(obj_logits)))
    obj_loss_map = jnp.where(ignore, 0.0, obj_loss_map)
    loss_obj = obj_loss_map.sum((1, 2, 3))
    # classification at positive cells
    smooth = 1.0 / max(class_num, 1) if use_label_smooth else 0.0
    onehot = jax.nn.one_hot(jnp.clip(gl, 0, class_num - 1), class_num)
    cls_t = onehot * (1.0 - smooth) + smooth / class_num \
        if use_label_smooth else onehot
    cl = pcls.transpose(0, 1, 3, 4, 2)[sel]       # [N, B, class_num]
    cls_map = jnp.maximum(cl, 0) - cl * cls_t \
        + jnp.log1p(jnp.exp(-jnp.abs(cl)))
    loss_cls = (jnp.where(in_level[..., None], cls_map, 0.0)).sum((1, 2))
    return loss_xy + loss_wh + loss_obj + loss_cls


__all__ += ["read_file", "decode_jpeg", "matrix_nms", "generate_proposals",
            "yolo_loss"]


class ConvNormActivation(_nn_Sequential):
    """Conv2D + norm + activation block (reference: vision/ops.py
    ConvNormActivation — the building block of the mobilenet family)."""

    _DEFAULT = object()   # distinguishes "use BatchNorm2D/ReLU default"
                          # from an explicit None = "no norm/activation"

    def __init__(self, in_channels, out_channels, kernel_size=3, stride=1,
                 padding=None, groups=1, norm_layer=_DEFAULT,
                 activation_layer=_DEFAULT, dilation=1, bias=None):
        from ..nn import BatchNorm2D, Conv2D, ReLU
        if padding is None:
            padding = (kernel_size - 1) // 2 * dilation
        if norm_layer is ConvNormActivation._DEFAULT:
            norm_layer = BatchNorm2D
        if activation_layer is ConvNormActivation._DEFAULT:
            activation_layer = ReLU
        if bias is None:
            bias = norm_layer is None   # after resolution: no norm -> bias
        layers = [Conv2D(in_channels, out_channels, kernel_size, stride,
                         padding, dilation=dilation, groups=groups,
                         bias_attr=None if bias else False)]
        if norm_layer is not None:
            layers.append(norm_layer(out_channels))
        if activation_layer is not None:
            layers.append(activation_layer())
        super().__init__(*layers)
