"""paddle.vision parity surface (reference: python/paddle/vision/).

Like the reference __init__, the model zoo, transforms, and datasets are
also re-exported at the top level (paddle.vision.ResNet, ... — the
reference binds them via relative imports)."""
from . import models  # noqa: F401
from . import ops  # noqa: F401
from . import transforms  # noqa: F401
from . import datasets  # noqa: F401
from .models import *  # noqa: F401,F403
from .transforms import *  # noqa: F401,F403
from .datasets import (Cifar10, Cifar100, DatasetFolder, FashionMNIST,  # noqa
                       Flowers, ImageFolder, MNIST, VOC2012)
# the star import of the transforms PACKAGE also pulls in its
# same-named transforms.py submodule attribute, shadowing the package —
# re-bind the subpackages from sys.modules (a plain re-import would just
# read back the shadowed attribute) so paddle.vision.transforms stays
# the package
import sys as _sys
from ..core import enforce as E

transforms = _sys.modules[__name__ + ".transforms"]
models = _sys.modules[__name__ + ".models"]
datasets = _sys.modules[__name__ + ".datasets"]


# -- image backend selection (reference: vision/image.py) -------------------
_image_backend = "pil"


def set_image_backend(backend):
    """reference: vision/image.py set_image_backend — 'pil', 'cv2', or
    'tensor' selects what image_load / dataset loaders return."""
    global _image_backend
    if backend not in ("pil", "cv2", "tensor"):
        raise E.InvalidArgumentError(
            f"image backend must be pil/cv2/tensor, got {backend!r}")
    _image_backend = backend


def get_image_backend():
    return _image_backend


def image_load(path, backend=None):
    """Load an image file via the selected backend (reference:
    vision/image.py image_load)."""
    backend = backend or _image_backend
    if backend == "cv2":
        try:
            import cv2

            return cv2.imread(path)
        except ImportError as e:
            raise E.PreconditionNotMetError("cv2 backend requested but OpenCV is not "
                               "installed") from e
    try:
        from PIL import Image

        img = Image.open(path)
        if backend == "pil":
            return img
        import numpy as _np

        from ..core.tensor import Tensor
        arr = _np.asarray(img)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return Tensor(arr.transpose(2, 0, 1))
    except ImportError as e:
        raise E.PreconditionNotMetError(
            "image_load needs Pillow for the pil/tensor backends") from e


__all__ = ["models", "ops", "transforms", "datasets", "set_image_backend",
           "get_image_backend", "image_load"]
