"""Transform classes (reference: vision/transforms/transforms.py)."""
from __future__ import annotations

import numbers
import random
from typing import Sequence

import numpy as np

from . import functional as F

__all__ = ["BaseTransform", "Compose", "ToTensor", "Normalize", "Resize",
           "RandomCrop", "CenterCrop", "RandomHorizontalFlip",
           "RandomVerticalFlip", "RandomResizedCrop", "RandomRotation",
           "Pad", "Transpose", "BrightnessTransform", "ContrastTransform",
           "SaturationTransform", "HueTransform", "ColorJitter"]


class BaseTransform:
    """reference transforms.py BaseTransform (keys handling elided: one
    image in, one image out — the dominant use)."""

    def __init__(self, keys=None):
        self.keys = keys

    def __call__(self, img):
        return self._apply_image(img)

    def _apply_image(self, img):
        raise NotImplementedError


class Compose:
    def __init__(self, transforms: Sequence):
        self.transforms = list(transforms)

    def __call__(self, img):
        for t in self.transforms:
            img = t(img)
        return img


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        super().__init__(keys)
        self.data_format = data_format

    def _apply_image(self, img):
        return F.to_tensor(img, self.data_format)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False,
                 keys=None):
        super().__init__(keys)
        if isinstance(mean, numbers.Number):
            mean = [mean] * 3
        if isinstance(std, numbers.Number):
            std = [std] * 3
        self.mean, self.std, self.data_format = mean, std, data_format

    def _apply_image(self, img):
        return F.normalize(img, self.mean, self.std, self.data_format)


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = size
        self.interpolation = interpolation

    def _apply_image(self, img):
        return F.resize(img, self.size, self.interpolation)


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode="constant", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding
        self.pad_if_needed = pad_if_needed
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        img = np.asarray(img)
        if self.padding is not None:
            img = F.pad(img, self.padding, self.fill, self.padding_mode)
        h, w = img.shape[:2]
        th, tw = self.size
        if self.pad_if_needed and (h < th or w < tw):
            img = F.pad(img, (max(tw - w, 0), max(th - h, 0)),
                        self.fill, self.padding_mode)
            h, w = img.shape[:2]
        top = random.randint(0, h - th)
        left = random.randint(0, w - tw)
        return F.crop(img, top, left, th, tw)


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        super().__init__(keys)
        self.size = size

    def _apply_image(self, img):
        return F.center_crop(img, self.size)


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return F.hflip(img)
        return np.asarray(img)


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return F.vflip(img)
        return np.asarray(img)


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def _apply_image(self, img):
        img = np.asarray(img)
        h, w = img.shape[:2]
        area = h * w
        for _ in range(10):
            target = random.uniform(*self.scale) * area
            ar = np.exp(random.uniform(np.log(self.ratio[0]),
                                       np.log(self.ratio[1])))
            cw = int(round(np.sqrt(target * ar)))
            ch = int(round(np.sqrt(target / ar)))
            if 0 < cw <= w and 0 < ch <= h:
                top = random.randint(0, h - ch)
                left = random.randint(0, w - cw)
                patch = F.crop(img, top, left, ch, cw)
                return F.resize(patch, self.size, self.interpolation)
        return F.resize(F.center_crop(img, min(h, w)), self.size,
                        self.interpolation)


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0, keys=None):
        super().__init__(keys)
        if isinstance(degrees, numbers.Number):
            degrees = (-degrees, degrees)
        self.degrees = degrees
        self.kw = dict(interpolation=interpolation, expand=expand,
                       center=center, fill=fill)

    def _apply_image(self, img):
        angle = random.uniform(*self.degrees)
        return F.rotate(img, angle, **self.kw)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        super().__init__(keys)
        self.padding, self.fill, self.mode = padding, fill, padding_mode

    def _apply_image(self, img):
        return F.pad(img, self.padding, self.fill, self.mode)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        super().__init__(keys)
        self.order = order

    def _apply_image(self, img):
        arr = np.asarray(img)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return arr.transpose(self.order)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return np.asarray(img)
        factor = random.uniform(max(0, 1 - self.value), 1 + self.value)
        return F.adjust_brightness(img, factor)


class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        if value < 0:
            raise ValueError("contrast value must be non-negative")
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return np.asarray(img)
        factor = random.uniform(max(0, 1 - self.value), 1 + self.value)
        return F.adjust_contrast(img, factor)


class SaturationTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return np.asarray(img)
        factor = random.uniform(max(0, 1 - self.value), 1 + self.value)
        return F.adjust_saturation(img, factor)


class HueTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        if not 0 <= value <= 0.5:
            raise ValueError("hue value must be in [0, 0.5]")
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return np.asarray(img)
        return F.adjust_hue(img, random.uniform(-self.value, self.value))


class ColorJitter(BaseTransform):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0,
                 keys=None):
        super().__init__(keys)
        self.transforms = []
        if brightness:
            self.transforms.append(BrightnessTransform(brightness))
        if contrast:
            self.transforms.append(ContrastTransform(contrast))
        if saturation:
            self.transforms.append(SaturationTransform(saturation))
        if hue:
            self.transforms.append(HueTransform(hue))

    def _apply_image(self, img):
        ts = list(self.transforms)
        random.shuffle(ts)
        for t in ts:
            img = t(img)
        return img
