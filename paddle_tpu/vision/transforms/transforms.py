"""Transform classes (reference: vision/transforms/transforms.py)."""
from __future__ import annotations

import math
import numbers
import random
from typing import Sequence

import numpy as np

from . import functional as F
from ...core import enforce as E

__all__ = ["BaseTransform", "Compose", "ToTensor", "Normalize", "Resize",
           "RandomCrop", "CenterCrop", "RandomHorizontalFlip",
           "RandomVerticalFlip", "RandomResizedCrop", "RandomRotation",
           "Pad", "Transpose", "BrightnessTransform", "ContrastTransform",
           "SaturationTransform", "HueTransform", "ColorJitter"]


class BaseTransform:
    """reference transforms.py BaseTransform (keys handling elided: one
    image in, one image out — the dominant use)."""

    def __init__(self, keys=None):
        self.keys = keys

    def __call__(self, img):
        return self._apply_image(img)

    def _apply_image(self, img):
        raise NotImplementedError


class Compose:
    def __init__(self, transforms: Sequence):
        self.transforms = list(transforms)

    def __call__(self, img):
        for t in self.transforms:
            img = t(img)
        return img


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        super().__init__(keys)
        self.data_format = data_format

    def _apply_image(self, img):
        return F.to_tensor(img, self.data_format)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False,
                 keys=None):
        super().__init__(keys)
        if isinstance(mean, numbers.Number):
            mean = [mean] * 3
        if isinstance(std, numbers.Number):
            std = [std] * 3
        self.mean, self.std, self.data_format = mean, std, data_format

    def _apply_image(self, img):
        return F.normalize(img, self.mean, self.std, self.data_format)


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = size
        self.interpolation = interpolation

    def _apply_image(self, img):
        return F.resize(img, self.size, self.interpolation)


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode="constant", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding
        self.pad_if_needed = pad_if_needed
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        img = np.asarray(img)
        if self.padding is not None:
            img = F.pad(img, self.padding, self.fill, self.padding_mode)
        h, w = img.shape[:2]
        th, tw = self.size
        if self.pad_if_needed and (h < th or w < tw):
            img = F.pad(img, (max(tw - w, 0), max(th - h, 0)),
                        self.fill, self.padding_mode)
            h, w = img.shape[:2]
        top = random.randint(0, h - th)
        left = random.randint(0, w - tw)
        return F.crop(img, top, left, th, tw)


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        super().__init__(keys)
        self.size = size

    def _apply_image(self, img):
        return F.center_crop(img, self.size)


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return F.hflip(img)
        return np.asarray(img)


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return F.vflip(img)
        return np.asarray(img)


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def _apply_image(self, img):
        img = np.asarray(img)
        h, w = img.shape[:2]
        area = h * w
        for _ in range(10):
            target = random.uniform(*self.scale) * area
            ar = np.exp(random.uniform(np.log(self.ratio[0]),
                                       np.log(self.ratio[1])))
            cw = int(round(np.sqrt(target * ar)))
            ch = int(round(np.sqrt(target / ar)))
            if 0 < cw <= w and 0 < ch <= h:
                top = random.randint(0, h - ch)
                left = random.randint(0, w - cw)
                patch = F.crop(img, top, left, ch, cw)
                return F.resize(patch, self.size, self.interpolation)
        return F.resize(F.center_crop(img, min(h, w)), self.size,
                        self.interpolation)


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0, keys=None):
        super().__init__(keys)
        if isinstance(degrees, numbers.Number):
            degrees = (-degrees, degrees)
        self.degrees = degrees
        self.kw = dict(interpolation=interpolation, expand=expand,
                       center=center, fill=fill)

    def _apply_image(self, img):
        angle = random.uniform(*self.degrees)
        return F.rotate(img, angle, **self.kw)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        super().__init__(keys)
        self.padding, self.fill, self.mode = padding, fill, padding_mode

    def _apply_image(self, img):
        return F.pad(img, self.padding, self.fill, self.mode)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        super().__init__(keys)
        self.order = order

    def _apply_image(self, img):
        arr = np.asarray(img)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return arr.transpose(self.order)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return np.asarray(img)
        factor = random.uniform(max(0, 1 - self.value), 1 + self.value)
        return F.adjust_brightness(img, factor)


class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        if value < 0:
            raise E.InvalidArgumentError("contrast value must be non-negative")
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return np.asarray(img)
        factor = random.uniform(max(0, 1 - self.value), 1 + self.value)
        return F.adjust_contrast(img, factor)


class SaturationTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return np.asarray(img)
        factor = random.uniform(max(0, 1 - self.value), 1 + self.value)
        return F.adjust_saturation(img, factor)


class HueTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        if not 0 <= value <= 0.5:
            raise E.InvalidArgumentError("hue value must be in [0, 0.5]")
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return np.asarray(img)
        return F.adjust_hue(img, random.uniform(-self.value, self.value))


class ColorJitter(BaseTransform):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0,
                 keys=None):
        super().__init__(keys)
        self.transforms = []
        if brightness:
            self.transforms.append(BrightnessTransform(brightness))
        if contrast:
            self.transforms.append(ContrastTransform(contrast))
        if saturation:
            self.transforms.append(SaturationTransform(saturation))
        if hue:
            self.transforms.append(HueTransform(hue))

    def _apply_image(self, img):
        ts = list(self.transforms)
        random.shuffle(ts)
        for t in ts:
            img = t(img)
        return img


class Grayscale(BaseTransform):
    """reference: transforms.py Grayscale."""

    def __init__(self, num_output_channels=1, keys=None):
        super().__init__(keys)
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        return F.to_grayscale(img, self.num_output_channels)


class RandomAffine(BaseTransform):
    """reference: transforms.py RandomAffine — random rotation,
    translation, scale and shear in the given ranges."""

    def __init__(self, degrees, translate=None, scale=None, shear=None,
                 interpolation="nearest", fill=0, center=None, keys=None):
        super().__init__(keys)
        self.degrees = (-degrees, degrees) if np.isscalar(degrees) \
            else tuple(degrees)
        self.translate = translate
        self.scale = scale
        self.shear = shear
        self.interpolation = interpolation
        self.fill = fill
        self.center = center

    def _apply_image(self, img):
        arr = np.asarray(img)
        h, w = arr.shape[:2]
        angle = random.uniform(*self.degrees)
        if self.translate is not None:
            tx = random.uniform(-self.translate[0], self.translate[0]) * w
            ty = random.uniform(-self.translate[1], self.translate[1]) * h
            translate = (tx, ty)
        else:
            translate = (0.0, 0.0)
        scale = random.uniform(*self.scale) if self.scale is not None else 1.0
        if self.shear is not None:
            sh = self.shear
            if np.isscalar(sh):
                shear = (random.uniform(-sh, sh), 0.0)
            elif len(sh) == 2:
                shear = (random.uniform(sh[0], sh[1]), 0.0)
            else:
                shear = (random.uniform(sh[0], sh[1]),
                         random.uniform(sh[2], sh[3]))
        else:
            shear = (0.0, 0.0)
        return F.affine(img, angle, translate, scale, shear,
                        self.interpolation, self.fill, self.center)


class RandomPerspective(BaseTransform):
    """reference: transforms.py RandomPerspective."""

    def __init__(self, prob=0.5, distortion_scale=0.5,
                 interpolation="nearest", fill=0, keys=None):
        super().__init__(keys)
        self.prob = prob
        self.distortion_scale = distortion_scale
        self.interpolation = interpolation
        self.fill = fill

    def _apply_image(self, img):
        if random.random() >= self.prob:
            return np.asarray(img)
        arr = np.asarray(img)
        h, w = arr.shape[:2]
        d = self.distortion_scale
        hw, hh = int(d * w / 2), int(d * h / 2)
        tl = (random.randint(0, hw), random.randint(0, hh))
        tr = (w - 1 - random.randint(0, hw), random.randint(0, hh))
        br = (w - 1 - random.randint(0, hw), h - 1 - random.randint(0, hh))
        bl = (random.randint(0, hw), h - 1 - random.randint(0, hh))
        start = [(0, 0), (w - 1, 0), (w - 1, h - 1), (0, h - 1)]
        end = [tl, tr, br, bl]
        return F.perspective(img, start, end, self.interpolation, self.fill)


class RandomErasing(BaseTransform):
    """reference: transforms.py RandomErasing — erase a random rectangle
    with value or noise."""

    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False, keys=None):
        super().__init__(keys)
        self.prob = prob
        self.scale = scale
        self.ratio = ratio
        self.value = value
        self.inplace = inplace

    def _apply_image(self, img):
        arr = np.asarray(img) if not hasattr(img, "_data") else img
        if random.random() >= self.prob:
            return arr
        if hasattr(arr, "_data"):
            h, w = arr.shape[-2], arr.shape[-1]        # CHW tensor
            ch = arr.shape[-3]
        else:
            h, w = arr.shape[:2]
            ch = arr.shape[2] if arr.ndim == 3 else 1
        area = h * w
        for _ in range(10):
            target = random.uniform(*self.scale) * area
            aspect = math.exp(random.uniform(math.log(self.ratio[0]),
                                             math.log(self.ratio[1])))
            eh = int(round(math.sqrt(target * aspect)))
            ew = int(round(math.sqrt(target / aspect)))
            if eh < h and ew < w:
                i = random.randint(0, h - eh)
                j = random.randint(0, w - ew)
                if self.value == "random":
                    v = np.random.default_rng().normal(
                        size=(eh, ew, ch)).astype(np.float32)
                    if hasattr(arr, "_data"):
                        v = v.transpose(2, 0, 1)
                else:
                    v = self.value
                return F.erase(arr, i, j, eh, ew, v, self.inplace)
        return arr
